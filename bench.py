"""Headline benchmark: batched model fitting throughput (series fitted/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md), so the baseline is measured
in-process: the reference's per-series fit path — a scalar optimizer loop per
series (Breeze + Commons-Math CGD, ref
``/root/reference/src/main/scala/com/cloudera/sparkts/models/EWMA.scala:45-69``)
— is emulated with an equivalent per-series scipy/numpy CGD loop on CPU, timed
on a subsample, and extrapolated.  ``vs_baseline`` = batched-TPU rate divided
by that per-series CPU rate.

Current flagship config: EWMA fit on a synthetic AR(1) panel (BASELINE.json
config #1).  Switches to ARIMA(2,1,2) when the ARIMA tier lands.
"""

import json
import os
import time

import numpy as np


def _synthetic_ar1_panel(n_series: int, n_obs: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    phi = rng.uniform(0.5, 0.95, size=(n_series, 1))
    eps = rng.normal(size=(n_series, n_obs))
    out = np.empty((n_series, n_obs))
    out[:, 0] = eps[:, 0]
    for t in range(1, n_obs):
        out[:, t] = phi[:, 0] * out[:, t - 1] + eps[:, t]
    return out + 100.0


def _ewma_sse_and_grad(alpha: float, x: np.ndarray):
    """Scalar-loop SSE + analytic gradient — the per-series objective shape
    of the reference (ref ``EWMA.scala:81-123``), with the correct gradient
    sign (dJ/da = -2 Σ err_i · dS_i/da; verified against finite differences)."""
    n = x.shape[0]
    s = x[0]        # S_i, starting at S_0 = x_0
    dsda = 0.0      # dS_i/da, dS_0/da = 0
    sse = 0.0
    djda = 0.0
    for i in range(n - 1):
        err = x[i + 1] - s
        sse += err * err
        djda += -2.0 * err * dsda
        dsda = x[i + 1] - s + (1.0 - alpha) * dsda
        s = alpha * x[i + 1] + (1.0 - alpha) * s
    return sse, djda


def _baseline_rate(panel: np.ndarray, sample: int = 32) -> float:
    """Per-series scalar CPU fit rate (series/sec), reference-style."""
    try:
        from scipy.optimize import minimize as sp_minimize

        def fit_one(x):
            sp_minimize(lambda a: _ewma_sse_and_grad(a[0], x)[0],
                        np.array([0.94]), method="CG",
                        jac=lambda a: np.array([_ewma_sse_and_grad(a[0], x)[1]]),
                        tol=1e-6)
    except ImportError:
        def fit_one(x):
            a = 0.94
            for _ in range(60):
                _, g = _ewma_sse_and_grad(a, x)
                a -= 1e-6 * g
    sub = panel[:sample]
    t0 = time.perf_counter()
    for row in sub:
        fit_one(row)
    dt = time.perf_counter() - t0
    return sample / dt


def main():
    import jax
    import jax.numpy as jnp
    from spark_timeseries_tpu.models import ewma

    n_series = int(os.environ.get("BENCH_N_SERIES", "65536"))
    n_obs = int(os.environ.get("BENCH_N_OBS", "128"))
    panel = _synthetic_ar1_panel(n_series, n_obs)

    if jax.devices()[0].platform == "tpu":
        dtype = jnp.float32
    else:
        jax.config.update("jax_enable_x64", True)
        dtype = jnp.float64
    values = jnp.asarray(panel, dtype=dtype)

    fit = jax.jit(lambda v: ewma.fit(v).smoothing)
    fit(values).block_until_ready()  # compile
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        fit(values).block_until_ready()
    batched_rate = n_series * reps / (time.perf_counter() - t0)

    cpu_rate = _baseline_rate(panel)

    print(json.dumps({
        "metric": "EWMA series fitted/sec/chip (synthetic AR(1) panel, "
                  f"{n_series}x{n_obs})",
        "value": round(batched_rate, 1),
        "unit": "series/sec",
        "vs_baseline": round(batched_rate / cpu_rate, 2),
    }))


if __name__ == "__main__":
    main()
