"""Headline benchmark: ARIMA(2,1,2) batched fitting throughput
(series fitted/sec/chip) at the BASELINE.md north-star scale: a 1M-series
synthetic panel, chunked through HBM.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} where
``value`` is the 1M-series rate and the extra fields carry the scaling curve
(8k -> 64k -> 512k -> 1M), device peak memory, and the CPU-baseline
emulation's parameters.

The reference publishes no numbers (BASELINE.md), so the baseline is measured
in-process: the reference's per-series fit path — Hannan-Rissanen init + a
scalar optimizer loop per series (Commons-Math CGD/BOBYQA, ref
``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMA.scala:79-200``)
— is emulated with a per-series scipy fit of the same CSS objective on CPU,
timed on a pinned subsample and extrapolated.  ``vs_baseline`` = batched rate
divided by that per-series CPU rate; the emulation's subsample size and
per-series timing spread are reported alongside so the ratio's quality is
inspectable.
"""

import json
import os
import time

import numpy as np

BASELINE_SAMPLE = 8          # pinned subsample for the CPU emulation
CHUNK = 131072               # series per device chunk at the 1M scale


def _synthetic_arima_panel(n_series: int, n_obs: int,
                           seed: int = 0) -> np.ndarray:
    """ARIMA(2,1,2) draws: ARMA(2,2) innovations then one integration."""
    rng = np.random.default_rng(seed)
    phi = np.stack([rng.uniform(0.1, 0.3, n_series),
                    rng.uniform(0.2, 0.5, n_series)], axis=1)
    theta = np.stack([rng.uniform(0.1, 0.4, n_series),
                      rng.uniform(0.0, 0.2, n_series)], axis=1)
    eps = rng.normal(size=(n_series, n_obs + 2)).astype(np.float32)
    y = np.zeros((n_series, n_obs), dtype=np.float32)
    for t in range(n_obs):
        ar = 0.0
        if t >= 1:
            ar = phi[:, 0] * y[:, t - 1]
        if t >= 2:
            ar = ar + phi[:, 1] * y[:, t - 2]
        ma = theta[:, 0] * eps[:, t + 1] + theta[:, 1] * eps[:, t]
        y[:, t] = 1.0 + ar + ma + eps[:, t + 2]
    return np.cumsum(y, axis=1)


def _css_neg_ll(params: np.ndarray, diffed: np.ndarray,
                p: int = 2, q: int = 2) -> float:
    """Scalar-loop CSS negative log likelihood — the reference's per-series
    objective shape (ref ``ARIMA.scala:430-445,581-618``)."""
    c = params[0]
    phi = params[1:1 + p]
    theta = params[1 + p:1 + p + q]
    n = diffed.shape[0]
    max_lag = max(p, q)
    errs = np.zeros(q)
    css = 0.0
    for i in range(max_lag, n):
        yhat = c
        for j in range(p):
            yhat += phi[j] * diffed[i - j - 1]
        for j in range(q):
            yhat += theta[j] * errs[j]
        e = diffed[i] - yhat
        css += e * e
        if q:
            errs[1:] = errs[:-1]
            errs[0] = e
    sigma2 = css / n
    return 0.5 * n * np.log(2 * np.pi * sigma2) + css / (2 * sigma2)


def _baseline_rate(panel: np.ndarray, sample: int = BASELINE_SAMPLE):
    """Per-series reference-style CPU rate (series/sec): a derivative-free
    scipy solve of the same CSS objective per series (the css-bobyqa path's
    cost shape).  Returns (rate, per-series timing list)."""
    from scipy.optimize import minimize as sp_minimize

    sub = panel[:sample]
    times = []
    for row in sub:
        t0 = time.perf_counter()
        diffed = np.diff(row.astype(np.float64))
        x0 = np.array([np.mean(diffed), 0.1, 0.1, 0.1, 0.1])
        sp_minimize(_css_neg_ll, x0, args=(diffed,), method="Powell",
                    options={"maxiter": 2000})
        times.append(time.perf_counter() - t0)
    return sample / sum(times), times


def _peak_memory_bytes():
    """Device peak memory, or None when the platform doesn't expose
    ``memory_stats`` (the tunneled axon runtime reports nothing — emitting
    0.0 would read as a measurement)."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
        peak = (stats or {}).get("peak_bytes_in_use")
        return int(peak) if peak else None
    except Exception:
        return None


def main():
    import jax
    import jax.numpy as jnp
    from spark_timeseries_tpu.models import arima

    n_target = int(os.environ.get("BENCH_N_SERIES", "1000000"))
    n_obs = int(os.environ.get("BENCH_N_OBS", "128"))
    chunk = min(int(os.environ.get("BENCH_CHUNK", str(CHUNK))), n_target)

    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        dtype = jnp.float32
    else:
        jax.config.update("jax_enable_x64", True)
        dtype = jnp.float64

    panel = _synthetic_arima_panel(n_target, n_obs)

    def _fit(v, n_real):
        m = arima.fit(2, 1, 2, v, warn=False)
        # converged-lane count rides along so the throughput number is
        # auditable (speed not bought by silent non-convergence); one extra
        # scalar per chunk, no extra passes.  ``n_real`` masks the ragged
        # tail's zero-padded lanes out of the count (traced, so the tail
        # reuses the same executable).
        lane = jnp.arange(v.shape[0]) < n_real
        return (m.coefficients,
                jnp.sum(jnp.where(lane, m.diagnostics.converged, False)))

    fit = jax.jit(_fit)

    def run(values: np.ndarray, chunk_n: int) -> float:
        """Fit a panel chunked through HBM; returns
        ``(wall_seconds, converged_lane_count)``.  Timing is
        to host materialization of every chunk's coefficients (on the
        tunneled TPU platform block_until_ready alone does not synchronize),
        and includes the H2D transfer of each chunk — the real pipeline
        cost shape for a panel larger than device memory.

        Double-buffered: chunk ``i+1``'s transfer + fit are dispatched
        (JAX dispatch is async) before chunk ``i``'s coefficients are pulled
        to host, so H2D/compute/D2H overlap; at most two chunks are live in
        HBM at once."""
        t0 = time.perf_counter()
        pending = None
        converged = 0

        def pull(out):
            nonlocal converged
            np.asarray(out[0])
            converged += int(out[1])

        for start in range(0, values.shape[0], chunk_n):
            part = values[start:start + chunk_n]
            n_real = part.shape[0]
            if n_real != chunk_n:           # ragged tail: pad to one shape
                pad = np.zeros((chunk_n - n_real, n_obs), part.dtype)
                part = np.concatenate([part, pad])
            out = fit(jnp.asarray(part, dtype), jnp.asarray(n_real))
            if pending is not None:
                pull(pending)
            pending = out
        pull(pending)
        return time.perf_counter() - t0, converged

    # scaling curve: does the small-panel rate hold at 1M?  Each point uses
    # chunk = min(CHUNK, n) so small panels aren't padded up to the big
    # chunk shape (jit caches one executable per chunk shape)
    curve = {}
    converged_target = 0
    for n in (8192, 65536, 524288, n_target):
        if n > n_target:
            continue
        c = min(chunk, n)
        np.asarray(fit(jnp.asarray(panel[:c], dtype),
                       jnp.asarray(c))[0])                  # warm this shape
        reps = 2 if n <= 65536 else 1
        dt, conv = min(run(panel[:n], c) for _ in range(reps))
        curve[str(n)] = round(n / dt, 1)
        if n == n_target:
            converged_target = conv
    rate_1m = curve[str(n_target)]

    cpu_rate, cpu_times = _baseline_rate(panel)

    # refit demonstration on one chunk: gather the non-converged tail,
    # re-fit it with a 4x budget, report the convergence lift and its cost
    # (cost scales with the tail, not the chunk; first call includes the
    # bucket shape's compile)
    refit_demo = None
    if os.environ.get("BENCH_REFIT", "1") == "1":
        from spark_timeseries_tpu.models import refit_unconverged
        from spark_timeseries_tpu.models.arima import LM_MAX_ITER

        demo_n = min(chunk, n_target)
        fit_model = jax.jit(lambda v: arima.fit(2, 1, 2, v, warn=False))
        model = fit_model(jnp.asarray(panel[:demo_n], dtype))
        before = float(np.asarray(model.diagnostics.converged).mean())
        t0 = time.perf_counter()
        model2 = refit_unconverged(
            panel[:demo_n].astype(np.float32 if dtype == jnp.float32
                                  else np.float64),
            model,
            lambda v, m: arima.fit(2, 1, 2, v, warn=False,
                                   max_iter=4 * LM_MAX_ITER,
                                   user_init_params=m.coefficients))
        after = float(np.asarray(model2.diagnostics.converged).mean())
        refit_demo = {
            "chunk": demo_n,
            "converged_pct_before": round(100 * before, 2),
            "converged_pct_after": round(100 * after, 2),
            "seconds_incl_compile": round(time.perf_counter() - t0, 2),
        }

    peak = _peak_memory_bytes()
    peak_mb = round(peak / 2**20, 1) if peak is not None else None

    print(json.dumps({
        "metric": "ARIMA(2,1,2) series fitted/sec/chip "
                  f"({n_target}x{n_obs} panel, chunk={chunk})",
        "value": rate_1m,
        "unit": "series/sec",
        "vs_baseline": round(rate_1m / cpu_rate, 2),
        "converged_pct": round(100.0 * converged_target / n_target, 2),
        "scaling_curve": curve,
        "peak_device_memory_mb": peak_mb,
        "refit_demo": refit_demo,
        "baseline_emulation": {
            "kind": "per-series scipy Powell on the same CSS objective",
            "sample": BASELINE_SAMPLE,
            "rate": round(cpu_rate, 3),
            "per_series_sec_min": round(min(cpu_times), 3),
            "per_series_sec_max": round(max(cpu_times), 3),
        },
    }))


if __name__ == "__main__":
    main()
