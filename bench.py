"""Headline benchmark: ARIMA(2,1,2) batched fitting throughput
(series fitted/sec/chip) at the BASELINE.md north-star scale: a 1M-series
synthetic panel, chunked through HBM.

Streams one JSON line per scaling-curve point as it lands (8k -> 64k ->
512k -> 1M; ``"partial": true`` on all but the last), then a final headline
line: {"metric", "value", "unit", "vs_baseline", ...} where ``value`` is the
largest completed panel's rate and the extra fields carry the full scaling
curve, device peak memory, and the CPU-baseline emulation's parameters.
Consumers should parse the LAST JSON line; earlier lines exist so a crash
mid-run still leaves a labeled partial record.  When the TPU is unreachable
the run degrades to a reduced-scale CPU measurement labeled ``"degraded"``
instead of exiting nonzero.

The reference publishes no numbers (BASELINE.md), so the baseline is measured
in-process: the reference's per-series fit path — Hannan-Rissanen init + a
scalar optimizer loop per series (Commons-Math CGD/BOBYQA, ref
``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMA.scala:79-200``)
— is emulated with a per-series scipy fit of the same CSS objective on CPU,
timed on a pinned subsample and extrapolated.  ``vs_baseline`` = batched rate
divided by that per-series CPU rate; the emulation's subsample size and
per-series timing spread are reported alongside so the ratio's quality is
inspectable.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_SAMPLE = 8          # pinned subsample for the CPU emulation
CHUNK = 131072               # series per device chunk at the 1M scale
CPU_FALLBACK_N = 16384       # panel size for the degraded CPU run


def _emit(obj: dict) -> None:
    """One JSON line to stdout, flushed immediately — partial evidence
    survives any later crash (round 2's record was empty because the old
    all-or-nothing design printed nothing until the full run finished)."""
    print(json.dumps(obj), flush=True)


def _probe_backend():
    """Probe accelerator availability in a disposable subprocess.

    A wedged TPU tunnel can make backend init either raise UNAVAILABLE
    (round 2's failure) or hang indefinitely (unkillable from inside the
    process) — probing in a child with a hard timeout protects the parent
    from both.  Returns the platform string ("axon"/"tpu"/...) on
    success or None when the accelerator is unreachable, in which case
    the caller runs a labeled degraded CPU bench instead of dying with
    rc=1; a timeout additionally sets ``_PROBE_STATE["timed_out"]`` so
    every record of the fallback run carries a ``probe_timed_out``
    marker (the hang is then data, not folklore).

    Each probe attempt gets a hard ``BENCH_PROBE_TIMEOUT_S``-second cap
    (default 30; the legacy ``BENCH_PROBE_TIMEOUT`` spelling is honored
    when the new one is unset).  The default is a SINGLE pass — in this
    container TPU probes hang rather than fail fast (ROADMAP), and the
    previous window-budgeted default (30 min of 240 s probes, kept for
    r4-era tunnel wedges that eventually cleared) wedged entire rounds.
    The patient behavior is still available, opt-in:
    ``BENCH_PROBE_WINDOW`` minutes of probing every
    ``BENCH_PROBE_BACKOFF`` seconds (default 120), or with the window
    at 0, ``BENCH_PROBE_TRIES`` attempts (default 1).  Every failed
    probe emits a JSON line to stdout — the driver's record then
    contains the proof of how long the chip was actually down, not just
    the fallback's ``degraded`` marker.
    """
    tries = int(os.environ.get("BENCH_PROBE_TRIES", "1"))
    probe_timeout = float(
        os.environ.get("BENCH_PROBE_TIMEOUT_S",
                       os.environ.get("BENCH_PROBE_TIMEOUT", "30")))
    backoff = float(os.environ.get("BENCH_PROBE_BACKOFF", "120"))
    window_s = 60.0 * float(os.environ.get("BENCH_PROBE_WINDOW", "0"))
    code = ("import jax, jax.numpy as jnp\n"
            "d = jax.devices()[0]\n"
            "x = jnp.ones((8, 8))\n"
            "(x @ x).block_until_ready()\n"
            "print('PLATFORM=' + d.platform, flush=True)\n")
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            out = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=probe_timeout)
            for line in out.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    return line.split("=", 1)[1]
            reason = (out.stderr.strip().splitlines() or ["no output"])[-1]
        except subprocess.TimeoutExpired:
            reason = f"probe hung > {probe_timeout:.0f}s"
            _PROBE_STATE["timed_out"] = True
        elapsed = time.monotonic() - start
        _emit({"probe_attempt": attempt, "elapsed_s": round(elapsed, 1),
               "window_s": window_s, "reason": reason[-200:]})
        print(f"# backend probe {attempt} failed at {elapsed:.0f}s: "
              f"{reason}", file=sys.stderr, flush=True)
        out_of_window = window_s > 0 and elapsed + backoff > window_s
        out_of_tries = window_s == 0 and attempt >= tries
        if out_of_window or out_of_tries:
            return None
        time.sleep(backoff if window_s else backoff * attempt)


DEGRADED_NOTE = "TPU unreachable after backend probes; CPU fallback"

# set by _probe_backend when any probe attempt hit its hard timeout —
# module-level (not a third return value) so benchmarks/* callers of
# _resolve_platform keep their 2-tuple contract
_PROBE_STATE = {"timed_out": False}


def _mark_degraded(obj: dict, degraded) -> None:
    """Stamp a record of a CPU-fallback run: the degraded note, plus
    ``probe_timed_out`` when the fallback was forced by a hung probe
    rather than a clean probe failure — the bench history must show
    WHY the platform changed."""
    if degraded:
        obj.setdefault("degraded", DEGRADED_NOTE)
        if _PROBE_STATE["timed_out"]:
            obj.setdefault("probe_timed_out", True)


def _resolve_platform():
    """Probe the accelerator and fall back to CPU when unreachable.

    Returns ``(platform, degraded)``: ``degraded`` is True only when the
    probe FAILED (wedged tunnel) — a deliberate CPU run is not degraded.
    Every benchmark entry point (bench.py, benchmarks/bench_suite.py,
    benchmarks/roofline.py) shares this so a wedged-TPU record can never
    masquerade as an intentional CPU capture.  ``BENCH_FORCE_CPU=1``
    skips the probe for an *intentional* CPU capture (no degraded
    marker) — without it a CPU baseline taken while the tunnel is down
    would be indistinguishable from a fallback."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
        return "cpu", False

    platform = _probe_backend()
    degraded = platform is None

    import jax

    if degraded or platform == "cpu":
        # env-var JAX_PLATFORMS is overridden by the axon sitecustomize;
        # the config update below is the one switch that actually works
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    return platform, degraded


def timed_min(fn, *args, reps: int = 3, want_out: bool = False):
    """Thin wrapper over the shared min-estimator harness
    (``spark_timeseries_tpu.utils.observability.timed_min`` — the one
    place the protocol is documented and implemented).  Kept here because
    the benchmark entry points (``roofline.py``, ``pallas_ab.py``,
    ``bench_suite.py``, ``docs/experiments/hw_pallas.py``) import it as
    ``from bench import timed_min``; the import is deferred so merely
    importing bench.py never initializes a JAX backend."""
    from spark_timeseries_tpu.utils.observability import timed_min as _tm
    return _tm(fn, *args, reps=reps, want_out=want_out)


def chained(pass_fn, reps: int):
    """Jit a fori_loop chaining ``reps`` calls of a scalar-returning
    ``pass_fn(params, *args)`` with a tiny feedback term into params, so
    the calls serialize, CSE cannot collapse them, D2H stays one float,
    and the tunnel's fixed round trip amortizes ``1/reps`` — divide the
    measured wall time by ``reps``."""
    import jax
    import jax.numpy as jnp

    def run(prm, *args):
        def body(_, carry):
            x, acc = carry
            s = pass_fn(x, *args)
            return (x + 1e-30 * s, acc + s)
        return jax.lax.fori_loop(
            0, reps, body, (prm, jnp.zeros((), prm.dtype)))[1]
    return jax.jit(run)


def _synthetic_arima_panel(n_series: int, n_obs: int,
                           seed: int = 0) -> np.ndarray:
    """ARIMA(2,1,2) draws: ARMA(2,2) innovations then one integration."""
    rng = np.random.default_rng(seed)
    phi = np.stack([rng.uniform(0.1, 0.3, n_series),
                    rng.uniform(0.2, 0.5, n_series)], axis=1)
    theta = np.stack([rng.uniform(0.1, 0.4, n_series),
                      rng.uniform(0.0, 0.2, n_series)], axis=1)
    eps = rng.normal(size=(n_series, n_obs + 2)).astype(np.float32)
    y = np.zeros((n_series, n_obs), dtype=np.float32)
    for t in range(n_obs):
        ar = 0.0
        if t >= 1:
            ar = phi[:, 0] * y[:, t - 1]
        if t >= 2:
            ar = ar + phi[:, 1] * y[:, t - 2]
        ma = theta[:, 0] * eps[:, t + 1] + theta[:, 1] * eps[:, t]
        y[:, t] = 1.0 + ar + ma + eps[:, t + 2]
    return np.cumsum(y, axis=1)


def _css_neg_ll(params: np.ndarray, diffed: np.ndarray,
                p: int = 2, q: int = 2) -> float:
    """Scalar-loop CSS negative log likelihood — the reference's per-series
    objective shape (ref ``ARIMA.scala:430-445,581-618``)."""
    c = params[0]
    phi = params[1:1 + p]
    theta = params[1 + p:1 + p + q]
    n = diffed.shape[0]
    max_lag = max(p, q)
    errs = np.zeros(q)
    css = 0.0
    for i in range(max_lag, n):
        yhat = c
        for j in range(p):
            yhat += phi[j] * diffed[i - j - 1]
        for j in range(q):
            yhat += theta[j] * errs[j]
        e = diffed[i] - yhat
        css += e * e
        if q:
            errs[1:] = errs[:-1]
            errs[0] = e
    sigma2 = css / n
    return 0.5 * n * np.log(2 * np.pi * sigma2) + css / (2 * sigma2)


def _baseline_rate(panel: np.ndarray, sample: int = BASELINE_SAMPLE):
    """Per-series reference-style CPU rate (series/sec): a derivative-free
    scipy solve of the same CSS objective per series (the css-bobyqa path's
    cost shape).  Returns (rate, per-series timing list)."""
    from scipy.optimize import minimize as sp_minimize

    sub = panel[:sample]
    times = []
    for row in sub:
        t0 = time.perf_counter()
        diffed = np.diff(row.astype(np.float64))
        x0 = np.array([np.mean(diffed), 0.1, 0.1, 0.1, 0.1])
        sp_minimize(_css_neg_ll, x0, args=(diffed,), method="Powell",
                    options={"maxiter": 2000})
        times.append(time.perf_counter() - t0)
    return sample / sum(times), times


def _min_root_moduli(coefs: np.ndarray, p: int, q: int, icpt: int = 1):
    """Per-lane minimum root modulus of the AR and MA characteristic
    polynomials — the common-factor-ridge diagnostic: non-converged lanes
    whose min AR and MA roots sit together near/inside the unit circle are
    on an ill-identified cancellation plateau, not a solver-budget cliff
    (see ``models/arima.py`` fit docstring).  Root finding delegates to
    ``arima.find_roots`` so the sign/layout conventions live in one place."""
    from spark_timeseries_tpu.models.arima import find_roots

    def minmod(tail):
        out = np.full(tail.shape[0], np.inf)
        for i, c in enumerate(tail):
            cc = np.trim_zeros(np.r_[1.0, c], "b")
            if cc.size > 1 and np.isfinite(cc).all():
                roots = find_roots(cc)
                if roots.size:
                    out[i] = np.abs(roots).min()
        return out

    phi = coefs[:, icpt:icpt + p]
    theta = coefs[:, icpt + p:icpt + p + q]
    return minmod(-phi), minmod(theta)


def _measure_h2d(part: np.ndarray, np_dtype) -> float:
    """Host-to-device bandwidth for one chunk (MB/s, best of 3): a bare
    ``device_put`` timed to readiness.  The host buffer is prepared with
    zero device traffic (the tunnel is the thing being measured)."""
    import jax
    host = np.ascontiguousarray(np.asarray(part, np_dtype))
    best = np.inf
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_put(host).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return host.nbytes / best / 2**20


def _peak_memory_bytes():
    """Device peak memory, or None when the platform doesn't expose
    ``memory_stats`` (the tunneled axon runtime reports nothing — emitting
    0.0 would read as a measurement)."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
        peak = (stats or {}).get("peak_bytes_in_use")
        return int(peak) if peak else None
    except Exception:
        return None


def main():
    platform, degraded = _resolve_platform()

    import jax
    import jax.numpy as jnp
    from spark_timeseries_tpu import engine as sts_engine
    from spark_timeseries_tpu.models import arima
    from spark_timeseries_tpu.utils import contracts, costs, metrics, \
        tracing

    # recompile/compile-seconds tracking rides jax.monitoring; when the
    # installed JAX lacks the hooks the stats stay 0 and hooks_installed
    # says so in the artifact (graceful no-op fallback)
    metrics.install_jax_hooks()
    # device-memory watermark at span boundaries (device.mem.* gauges);
    # self-disarms after one probe on platforms with no memory stats
    costs.install_device_memory_sampler()

    # live telemetry plane (ISSUE 10): every bench runs with the scrape
    # exporter armed on a free port (BENCH_TELEMETRY_PORT pins one), so
    # an operator can `python -m tools.sts_top <url>` a long bench and
    # every record's metrics block carries measured scrape latencies +
    # heartbeat-gauge presence.  The flight recorder arms off
    # STS_INCIDENT_DIR as usual; its incidents.written counter lands in
    # the telemetry block, where tools/bench_gate.py zero-baselines it
    # (a bench round must not organically crash).
    telem_server = None
    try:
        from spark_timeseries_tpu.utils import telemetry as sts_telemetry
        telem_server = sts_telemetry.start(
            port=int(os.environ.get("BENCH_TELEMETRY_PORT", "0")))
        print(f"# telemetry exporter at {telem_server.url}",
              file=sys.stderr, flush=True)
    except Exception as e:        # noqa: BLE001 — optional accounting;
        # a bench must measure even when the port is unavailable
        print(f"# telemetry exporter failed to start: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)

    def _telemetry_block(snap: dict) -> dict:
        """Exporter self-measurement for the metrics block: scrape
        latency of the two hot routes plus whether the job-heartbeat
        gauges materialized this round (tolerated-absent in rounds that
        predate the telemetry plane, like serving_update_p50)."""
        import urllib.request

        tb: dict = {
            "heartbeat_gauges": any(k.startswith("engine.job.")
                                    for k in snap["gauges"]),
            "incidents_written": int(
                snap["counters"].get("incidents.written", 0)),
        }
        if telem_server is not None:
            tb["port"] = telem_server.port
            for route, key in (("/metrics", "metrics_scrape_ms"),
                               ("/snapshot.json", "snapshot_scrape_ms")):
                try:
                    t0 = time.perf_counter()
                    with urllib.request.urlopen(telem_server.url + route,
                                                timeout=10) as resp:
                        resp.read()
                    tb[key] = round(1e3 * (time.perf_counter() - t0), 2)
                except Exception as e:  # noqa: BLE001 — a failed scrape
                    # is itself a finding the artifact should carry
                    tb[key + "_error"] = f"{type(e).__name__}: {e}"
        return tb

    # static-analysis summary (ISSUE 4): every BENCH record also says
    # whether the tree it measured was invariant-clean — sts-lint
    # finding counts plus the jaxpr/HLO contract results.  Lint is a
    # fast pure-AST pass over the package; contracts trace+lower one
    # family by default (BENCH_CONTRACT_FAMILIES widens it; "all" =
    # every family, "" skips).  Computed once, embedded in every record.
    _static_cache: dict = {}

    def _static_analysis_block() -> dict:
        if _static_cache:
            return _static_cache
        repo = os.path.dirname(os.path.abspath(__file__))
        block: dict = {}
        try:
            if repo not in sys.path:
                sys.path.insert(0, repo)
            from tools.sts_lint import (DEFAULT_BASELINE, lint_paths,
                                        load_baseline)
            res, _ = lint_paths(
                [os.path.join(repo, "spark_timeseries_tpu")], root=repo,
                baseline=load_baseline(DEFAULT_BASELINE))
            s = res.summary()
            block["findings"] = s["findings"]
            block["suppressed"] = s["suppressed"]
            block["baselined"] = s["baselined"]
            if s["by_code"]:
                block["by_code"] = s["by_code"]
        except Exception as e:      # noqa: BLE001 — optional accounting
            block["lint_error"] = f"{type(e).__name__}: {e}"
        fams_env = os.environ.get("BENCH_CONTRACT_FAMILIES", "arima")
        fams = list(contracts.CONTRACT_FAMILIES) if fams_env == "all" \
            else [f for f in fams_env.split(",") if f]
        if fams:
            try:
                with metrics.span("bench.contracts"):
                    rep = contracts.check_all(fams)
                block["contracts_checked"] = rep["contracts_checked"]
                block["contracts_failed"] = rep["contracts_failed"]
                block["contract_families"] = rep["families"]
                if rep["contracts_failed"]:
                    block["contract_failures"] = rep["failures"]
            except Exception as e:  # noqa: BLE001 — optional accounting
                block["contracts_error"] = f"{type(e).__name__}: {e}"
        else:
            block["contracts_checked"] = 0
            block["contracts_failed"] = 0
        if fams:
            # host-boundary tier (ISSUE 19): warmed-chunk program count
            # + device→host bytes, gated by bench_gate from this block
            try:
                with metrics.span("bench.boundary_contracts"):
                    pc = contracts.pipeline_contracts()
                block["boundary"] = {
                    "pipeline_programs": pc["pipeline_programs"],
                    "programs_budget": pc["programs_budget"],
                    "host_transfer_bytes_per_chunk":
                        pc["host_transfer_bytes_per_chunk"],
                    "unexpected_transfer_bytes":
                        pc["unexpected_transfer_bytes"],
                    "boundary_failed": pc["boundary_failed"],
                }
            except Exception as e:  # noqa: BLE001 — optional accounting
                block["boundary_error"] = f"{type(e).__name__}: {e}"
        _static_cache.update(block)
        return _static_cache

    def _metrics_block() -> dict:
        """Why-block for every record: recompiles + compile seconds from
        the jax.monitoring hooks, per-span wall-time stats for every
        instrumented stage (the model fits' spans fire at trace time under
        the jitted fit, so each model family fitted shows up), the
        accumulated fit counter bundles, the top-N slowest individual
        span scopes from the trace ring (the aggregate histograms can't
        say WHICH round/chunk was slow — these can), the device
        memory gauges when the platform reports them, and the
        static-analysis (lint + contract) summary."""
        snap = metrics.snapshot()
        block = dict(metrics.jax_stats(snap=snap))
        block["spans"] = snap["spans"]
        block["slowest_spans"] = tracing.slowest_spans(8)
        # exclusive self-time attribution (docs/design.md §6g): which
        # scope ITSELF ate the time, rolled up per subsystem — the block
        # tools/bench_diff.py diffs across rounds
        block["self_times"] = tracing.self_time_report(10)
        fit_counters = {k: v for k, v in snap["counters"].items()
                        if k.startswith(("fit.", "optimize.",
                                         "resilience."))}
        if fit_counters:
            block["fit_counters"] = fit_counters
        resil_gauges = {k: v for k, v in snap["gauges"].items()
                        if k.startswith("resilience.")}
        if resil_gauges:
            block["resilience_gauges"] = resil_gauges
        mem_gauges = {k: v for k, v in snap["gauges"].items()
                      if k.startswith("device.mem.")}
        if mem_gauges:
            block["device_memory"] = mem_gauges
        # the streaming engine's accounting: executable cache hits/misses,
        # chunks, bytes donated/transferred, pad lanes (tools/bench_gate.py
        # gates engine.cache_misses against the trailing median)
        eng_counters = {k: v for k, v in snap["counters"].items()
                        if k.startswith("engine.")}
        # the attribution gauges ride along (engine.host_overhead_frac /
        # engine.bubble_ms_total — last stream wins, like engine.job.*)
        eng_counters.update(
            {k: v for k, v in snap["gauges"].items()
             if k.startswith("engine.") and not k.startswith("engine.job.")})
        if eng_counters:
            block["engine"] = eng_counters
        # the serving tier's accounting: sessions opened, ticks ingested,
        # update/forecast calls, state bytes (tools/bench_gate.py gates the
        # serving.update span's p50/p95 against the trailing median)
        serv = {k: v for k, v in snap["counters"].items()
                if k.startswith("serving.")}
        serv.update({k: v for k, v in snap["gauges"].items()
                     if k.startswith("serving.")})
        if serv:
            block["serving"] = serv
        # the backtest tier's accounting: sweeps run, candidates/series/
        # origins evaluated, journal resume hits, dead lanes (the
        # headline accuracy numbers live in backtest_demo — these are
        # the volume counters behind them)
        bt = {k: v for k, v in snap["counters"].items()
              if k.startswith("backtest.")}
        bt.update({k: v for k, v in snap["gauges"].items()
                   if k.startswith("backtest.")})
        if bt:
            block["backtest"] = bt
        block["telemetry"] = _telemetry_block(snap)
        block["static_analysis"] = _static_analysis_block()
        return block

    def emit(obj: dict) -> None:
        # EVERY line of a probe-failure fallback carries the marker — a
        # partial record surviving a mid-curve crash must be as clearly
        # labeled as the headline (sites that set a more specific
        # degraded message keep theirs).  Every record also carries the
        # metrics block current at emit time, so a partial record still
        # explains its own recompiles/spans.
        _mark_degraded(obj, degraded)
        obj.setdefault("metrics", _metrics_block())
        _emit(obj)

    n_series_env = os.environ.get("BENCH_N_SERIES")
    n_target = int(n_series_env) if n_series_env else 1000000
    n_obs = int(os.environ.get("BENCH_N_OBS", "128"))
    on_tpu = platform != "cpu"
    if on_tpu:
        dtype = jnp.float32
    else:
        # degraded run: still measure something real, at a scale CPU can
        # finish in minutes — but never silently override an explicitly
        # requested panel size
        if n_series_env is None:
            n_target = min(n_target, CPU_FALLBACK_N)
        jax.config.update("jax_enable_x64", True)
        dtype = jnp.float64
    np_dtype = np.float32 if dtype == jnp.float32 else np.float64
    chunk = min(int(os.environ.get("BENCH_CHUNK", str(CHUNK))), n_target)

    panel = _synthetic_arima_panel(n_target, n_obs)

    # record which css-lm solver the fits will use, so the artifact is
    # self-describing.  Probe the gate through eval_shape so it takes
    # exactly the branch the jitted fits take (a tracer — the
    # device-count fallback, not a concrete array's sharding, which can
    # disagree on single-process multi-device hosts), at the REAL
    # post-differencing chunk shape (chunk, n_obs - 1) — the gate is
    # obs-dependent (VMEM bound), so a placeholder obs count would
    # mislabel the artifact (advisor r4) — and no device allocation
    gate = {}

    def _gate_probe(v):
        gate["pallas"] = arima._use_pallas_lm(v, None)
        return v

    jax.eval_shape(_gate_probe,
                   jax.ShapeDtypeStruct((chunk, n_obs - 1), dtype))
    css_lm_path = "pallas" if gate["pallas"] else "xla"

    # CPU-baseline emulation first: it is cheap, accelerator-independent,
    # and lets every streamed curve point carry vs_baseline
    with metrics.span("bench.baseline_emulation"):
        cpu_rate, cpu_times = _baseline_rate(panel)

    # the streaming fit engine (ISSUE 5) replaces this file's former
    # inline double-buffer loop: shape-bucketed AOT executables (one
    # compile per chunk bucket, shared across curve points and reps),
    # prefetch-depth H2D/compute/D2H overlap, donated chunk buffers on
    # accelerators, ragged-tail bucketing, and per-chunk failure
    # isolation — with `engine.*` counters landing in every record's
    # metrics block.  STS_COMPILE_CACHE additionally persists the
    # executables across processes.
    eng = sts_engine.FitEngine()

    # BENCH_JOURNAL=dir arms the durable-streaming chunk journal
    # (ISSUE 6): each curve point journals under its own subdirectory
    # (the journal spec is content-hashed per job — panel size included —
    # so points cannot share one), and a re-run of a killed bench resumes
    # committed chunks instead of refitting them.  The per-point engine
    # stats then carry journal_hits/journal_commits alongside the
    # quarantine/deadline/degradation counters, which land in the
    # metrics block as engine.* counters either way.
    journal_base = os.environ.get("BENCH_JOURNAL") or None

    def run(values: np.ndarray, chunk_n: int, n: int):
        """One streamed pass; returns the engine's
        ``(wall_seconds, converged_lane_count, chunk_failures, stats)``.
        Timing covers dispatch through host materialization of every
        chunk's outputs (on the tunneled TPU platform block_until_ready
        alone does not synchronize) and includes each chunk's H2D — the
        real pipeline cost shape for a panel larger than device memory."""
        jr = os.path.join(journal_base, f"n{n}") if journal_base else None
        res = eng.stream_fit(np.asarray(values, np_dtype), "arima",
                             chunk_size=chunk_n, p=2, d=1, q=2, journal=jr)
        return res.wall_s, res.n_converged, res.chunk_failures, res.stats

    # scaling curve: does the small-panel rate hold at 1M?  Each point uses
    # chunk = min(CHUNK, n) so small panels aren't padded up to the big
    # chunk shape (jit caches one executable per chunk shape).  Every point
    # is streamed as its own labeled JSON line the moment it lands, so a
    # crash mid-curve still leaves a parseable partial record.
    curve = {}
    curve_h2d = {}
    h2d_by_chunk = {}
    eng_by_n = {}
    converged_target = 0
    error = None
    try:
        for n in dict.fromkeys((8192, 65536, 524288, n_target)):
            if n > n_target:
                continue
            c = min(chunk, n)
            with metrics.span("bench.warmup"):
                # precompile this point's exact chunk shape (and the
                # tail's series bucket, when the point has a ragged
                # tail) ahead of the timed pass — bucket=False keys the
                # executables exactly as stream_fit will look them up,
                # donation flag included; with a warm in-process or
                # persistent cache this is a cache hit, not a compile
                shapes = [(c, n_obs)]
                tail = n % c
                if tail:
                    shapes.append((min(sts_engine.series_bucket(tail), c),
                                   n_obs))
                eng.warmup(("arima",), shapes, dtype=dtype,
                           variants=("dense",), bucket=False,
                           p=2, d=1, q=2)
            # per-point H2D bandwidth at this point's chunk shape (cached
            # by shape — re-shipping an identical chunk measures nothing
            # new): the curve's shape is transfer-dominated over the dev
            # tunnel, and a single-chunk point (n == c) cannot overlap
            # transfer with compute at all — the artifact carries both
            # facts per point so a non-monotone curve explains itself.
            # CPU runs skip it: device_put is a host memcpy there and the
            # number would be fiction.
            h2d_mbps = None
            if on_tpu:
                if c not in h2d_by_chunk:
                    with metrics.span("bench.h2d_probe"):
                        h2d_by_chunk[c] = round(
                            _measure_h2d(panel[:c], np_dtype), 2)
                h2d_mbps = h2d_by_chunk[c]
                curve_h2d[str(n)] = h2d_mbps
            # with a journal armed a second rep would resume from the
            # first rep's commits and time a (near-empty) resume pass,
            # not a fit — one rep keeps the point honest
            reps = 1 if journal_base else (2 if n <= 65536 else 1)
            with metrics.span("bench.fit_panel"):
                # prefer the rep with the most coverage, then the fastest —
                # a rep that dropped a chunk skips that chunk's work, so
                # min-by-time alone would bias toward degraded runs
                dt, conv, chunk_failures, eng_stats = min(
                    (run(panel[:n], c, n) for _ in range(reps)),
                    key=lambda r: (sum(f["n_series"] for f in r[2]), r[0]))
            # the rate covers only the series that actually fitted: a
            # failed chunk's lanes must not inflate the numerator
            n_failed = sum(f["n_series"] for f in chunk_failures)
            curve[str(n)] = round(max(n - n_failed, 0) / dt, 1)
            eng_by_n[n] = eng_stats
            converged_target = conv
            point = {
                "metric": "ARIMA(2,1,2) series fitted/sec/chip "
                          f"({n}x{n_obs} curve point, chunk={c})",
                "value": curve[str(n)],
                "unit": "series/sec",
                "vs_baseline": round(curve[str(n)] / cpu_rate, 2),
                "partial": n != n_target,
                "n_chunks": -(-n // c),
                "platform": platform,
                "css_lm_path": css_lm_path,
                # per-pass engine accounting: a non-zero cache_misses here
                # means this point paid a compile the warmup didn't cover
                "engine": eng_stats,
            }
            if chunk_failures:
                point["fit_failures"] = chunk_failures[:8]
                point["n_failed_chunks"] = len(chunk_failures)
                point["n_failed_series"] = n_failed
            if h2d_mbps is not None:
                point["h2d_mbps"] = h2d_mbps
            emit(point)
    except Exception as e:          # noqa: BLE001 — any mid-curve death
        # (backend loss, OOM) must degrade to the best completed point,
        # never to an empty record
        error = f"{type(e).__name__}: {e}"
        print(f"# curve aborted: {error}", file=sys.stderr, flush=True)

    # remediation in the headline path (round-4 verdict item 4): gather the
    # non-converged tail, re-fit it with a 4x budget, then (a) fit the
    # still-stuck lanes at a lower order — the batched analogue of the
    # reference's per-series Try-fallback re-fit (ARIMA.scala:315-319) —
    # and (b) measure the common-factor-ridge diagnostic on whatever
    # remains, so the artifact itself documents why the residual tail is
    # irreducible at this series length rather than asserting it in prose.
    # Runs in degraded CPU fallbacks too (reduced scale makes it cheap).
    refit_demo = None
    if error is None and os.environ.get("BENCH_REFIT", "1") == "1":
        try:
            from spark_timeseries_tpu.models import refit_unconverged
            from spark_timeseries_tpu.models.arima import LM_MAX_ITER

            demo_n = min(chunk, n_target)
            with metrics.span("bench.refit_demo"):
                model = eng.fit(np.asarray(panel[:demo_n], np_dtype),
                                "arima", p=2, d=1, q=2)
                before = float(
                    np.asarray(model.diagnostics.converged).mean())
                t0 = time.perf_counter()
                model2 = refit_unconverged(
                    panel[:demo_n].astype(np_dtype),
                    model,
                    lambda v, m: arima.fit(2, 1, 2, v, warn=False,
                                           max_iter=4 * LM_MAX_ITER,
                                           user_init_params=m.coefficients))
                after = float(
                    np.asarray(model2.diagnostics.converged).mean())
            refit_demo = {
                "chunk": demo_n,
                "converged_pct_before": round(100 * before, 2),
                "converged_pct_after": round(100 * after, 2),
                "seconds_incl_compile": round(time.perf_counter() - t0, 2),
            }

            still = ~np.asarray(model2.diagnostics.converged)
            if still.any():
                # lower-order fallback for the stuck lanes (the ridge is a
                # (2,1,2) cancellation artifact; (1,1,1) is identified)
                m_lo = arima.fit(1, 1, 1,
                                 jnp.asarray(panel[:demo_n][still],
                                             dtype),
                                 warn=False, max_iter=4 * LM_MAX_ITER)
                lo_conv = np.asarray(m_lo.diagnostics.converged)
                covered = float(np.asarray(
                    model2.diagnostics.converged).sum() + lo_conv.sum())
                min_ar, min_ma = _min_root_moduli(
                    np.asarray(model2.coefficients,
                               np.float64)[still], 2, 2)
                near = np.isfinite(min_ar) & np.isfinite(min_ma)
                ridge = near & (min_ar < 1.1) & (min_ma < 1.1) \
                    & (np.abs(min_ar - min_ma) < 0.2)
                refit_demo["still_unconverged"] = {
                    "count": int(still.sum()),
                    "diagnosable": int(near.sum()),
                    "ridge_pct": round(
                        100 * float(ridge.sum()) / float(still.sum()), 1),
                    "median_min_ar_root": round(float(np.median(
                        min_ar[near])), 3) if near.any() else None,
                    "median_min_ma_root": round(float(np.median(
                        min_ma[near])), 3) if near.any() else None,
                    "note": "AR/MA min roots together near/inside the "
                            "unit circle = common-factor cancellation "
                            "plateau (ill-identified at this n, not a "
                            "budget cliff)",
                }
                refit_demo["lower_order_fallback"] = {
                    "order": [1, 1, 1],
                    "converged_pct_of_stuck": round(
                        100 * float(lo_conv.mean()), 2),
                    "combined_converged_pct": round(
                        100 * covered / demo_n, 2),
                }
        except Exception as e:      # noqa: BLE001 — optional extra; its
            # failure must not void the already-measured curve
            refit_demo = {"error": f"{type(e).__name__}: {e}"}

    # resilience demo (ISSUE 2): corrupt a small slice of the panel the way
    # production ingestion fails (all-NaN, constant, divergent lanes), run
    # fit_resilient, and record the per-series disposition — the bench
    # artifact then carries resilience.* counters/gauges in its metrics
    # block plus an explicit outcome summary, proving the fail-soft path
    # works at the benched scale.
    resilience_demo = None
    if error is None and os.environ.get("BENCH_RESILIENCE", "1") == "1":
        try:
            from spark_timeseries_tpu.utils import resilience
            from spark_timeseries_tpu.models.arima import fit_resilient

            demo_n = min(4096, n_target)
            corrupted = np.array(panel[:demo_n], dtype=np_dtype)
            corrupted[0] = np.nan                        # all-NaN
            corrupted[1] = 1.0                           # constant
            corrupted[2] = np.cumsum(np.cumsum(          # divergence bait
                np.exp(np.linspace(0.0, 12.0, n_obs)))).astype(np_dtype)
            with metrics.span("bench.resilience_demo"):
                t0 = time.perf_counter()
                _, outcome = fit_resilient(
                    jnp.asarray(corrupted), 2, 1, 2,
                    retry=resilience.RetryPolicy(max_restarts=1))
                demo_s = time.perf_counter() - t0
            resilience_demo = {
                "panel": demo_n,
                "corrupted_lanes": 3,
                "outcome": outcome.counts(),
                "seconds_incl_compile": round(demo_s, 2),
            }
        except Exception as e:      # noqa: BLE001 — optional extra; its
            # failure must not void the already-measured curve
            resilience_demo = {"error": f"{type(e).__name__}: {e}"}

    # serving demo (ISSUE 7): warm a ServingSession on a slice of the
    # panel, stream ticks through the O(1) Kalman update (a single cached
    # executable — zero compiles after warmup), and report the per-tick
    # latency distribution plus forecast throughput.  The serving.update
    # span's p50/p95 land in the metrics block, where
    # tools/bench_gate.py enforces the per-tick latency SLO.
    serving_demo = None
    if error is None and os.environ.get("BENCH_SERVING", "1") == "1":
        try:
            from spark_timeseries_tpu.statespace import serving as sstate

            demo_n = min(int(os.environ.get("BENCH_SERVING_SERIES",
                                            "1024")), n_target)
            ticks = max(1, min(int(os.environ.get("BENCH_SERVING_TICKS",
                                                  "64")), n_obs - 32))
            hist = np.array(panel[:demo_n, :n_obs - ticks], dtype=np_dtype)
            live = np.array(panel[:demo_n, n_obs - ticks:], dtype=np_dtype)
            with metrics.span("bench.serving_demo"):
                model = arima.fit(2, 1, 2, jnp.asarray(hist), warn=False)
                sess = sstate.ServingSession.start(model, hist)
                sess.warmup()              # compile outside the timed ticks
                t0 = time.perf_counter()
                for t in range(ticks):
                    sess.update(live[:, t])
                update_s = time.perf_counter() - t0
                horizon = 24
                sess.forecast(horizon)     # compile the horizon's program
                fc_reps = 3
                t0 = time.perf_counter()
                for _ in range(fc_reps):
                    sess.forecast(horizon)
                fc_s = time.perf_counter() - t0
                # self-heal demo (ISSUE 9), after the timed ticks so it
                # cannot contaminate the latency SLO — and on a PRIVATE
                # registry session, so the deliberately injected
                # divergences never feed the global serving.diverged
                # counter the gate zero-baselines (that counter must
                # stay a measurement of ORGANIC lane divergence; an
                # always-poisoned baseline would mask real regressions).
                # The serving.heal span is global: heal_p50 is a real
                # latency however the divergence was provoked.
                from spark_timeseries_tpu.utils import (
                    resilience as _resil)
                heal_sess = sstate.ServingSession.start(
                    model, hist, registry=metrics.MetricsRegistry())
                stride = max(1, demo_n // 8)
                with _resil.fault_injection("state_poison",
                                            lane_stride=stride):
                    heal_sess.update(live[:, 0])
                heal_sess.update(live[:, 1])
                heal_report = heal_sess.heal()
            # quality demo (ISSUE 15): a SEPARATE quality-armed session
            # on a private registry streams a stationary slice of the
            # same panel — separate so the fused quality step's extra
            # per-tick work never contaminates the gated
            # serving_update_p50/p95 (this scope's serving.update spans
            # land under bench.quality_demo with a smaller count, so
            # the gate's busiest-leaf matcher keeps reading the main
            # demo's numbers).  live_smape is gated lower-is-better and
            # drift_alarms zero-baselined: the stream is stationary by
            # construction, so any alarm is a false positive.
            from spark_timeseries_tpu.statespace import QualityPolicy
            q_sess = sstate.ServingSession.start(
                model, hist, registry=metrics.MetricsRegistry(),
                quality=QualityPolicy())
            q_sess.warmup()
            q_ticks = max(1, min(ticks - 1, 48))
            with metrics.span("bench.quality_demo"):
                for t in range(q_ticks):
                    q_sess.update(live[:, t])
            qsum = q_sess.quality_summary() or {}
            # the update span nests under this demo's scope
            # ("bench.serving_demo/serving.update") — resolve it with the
            # same leaf matcher the gate uses, so the reported and gated
            # numbers can never diverge
            from tools.bench_gate import _leaf_span
            sp = _leaf_span(metrics.snapshot()["spans"],
                            "serving.update") or {}
            serving_demo = {
                "panel": demo_n,
                "ticks": ticks,
                "update_p50_ms": round(1e3 * sp.get("p50_s", 0.0), 3),
                "update_p95_ms": round(1e3 * sp.get("p95_s", 0.0), 3),
                "updates_per_s": round(ticks / update_s, 1),
                "tick_throughput_series_per_s": round(
                    ticks * demo_n / update_s, 1),
                "forecast_horizon": horizon,
                "forecast_series_per_s": round(
                    fc_reps * demo_n / fc_s, 1),
                "state_bytes": sess.state_bytes,
                "heal": {"quarantined": heal_report.get("quarantined"),
                         "healed": heal_report.get("healed"),
                         "dead": heal_report.get("dead"),
                         "heal_p50_ms": round(1e3 * (_leaf_span(
                             metrics.snapshot()["spans"],
                             "serving.heal") or {}).get("p50_s", 0.0),
                             3)},
                "quality": {
                    "ticks": q_ticks,
                    "horizon": qsum.get("horizon"),
                    "live_smape": qsum.get("live_smape"),
                    "live_mase": qsum.get("live_mase"),
                    "live_coverage": qsum.get("live_coverage"),
                    "anomaly_p95": qsum.get("anomaly_p95"),
                    "drifted_lanes": qsum.get("drifted_lanes", 0),
                    "drift_alarms": qsum.get("drift_alarms", 0),
                },
            }
        except Exception as e:  # noqa: BLE001 — optional extra; its
            # failure must not void the already-measured curve
            serving_demo = {"error": f"{type(e).__name__}: {e}"}

    # fleet demo (ISSUE 12): multiplex ≥64 tenant sessions onto one
    # coalesced update executable through the FleetScheduler and measure
    # the aggregate lane-tick throughput plus the pooled per-tick p99
    # across every session's latency window.  Runs on a PRIVATE registry
    # (like the heal demo) so the injected-load numbers never pollute
    # the global serving counters; the block's own shed_lanes field is
    # what tools/bench_gate.py zero-baselines (a bench fleet must not
    # shed under its own nominal load), and fleet_ticks_per_s is gated
    # higher-is-better once two rounds carry it.  Since ISSUE 17 the
    # timed loop runs through FleetRuntime's supervised background pump
    # (blocking producer-side admission), so fleet_ticks_per_s also
    # guards the async runtime's overhead and pump_restarts /
    # checkpoint_failures become zero-baselined supervision gates.
    fleet_demo = None
    if error is None and os.environ.get("BENCH_FLEET", "1") == "1":
        try:
            from spark_timeseries_tpu.statespace import (AdmissionPolicy,
                                                         FleetRuntime,
                                                         FleetScheduler,
                                                         RuntimePolicy)
            from spark_timeseries_tpu.statespace import serving as sstate
            from spark_timeseries_tpu.utils import lineage as _lineage

            n_sessions = max(2, int(os.environ.get("BENCH_FLEET_SESSIONS",
                                                   "64")))
            per = max(8, int(os.environ.get("BENCH_FLEET_SERIES", "16")))
            rounds = max(1, int(os.environ.get("BENCH_FLEET_TICKS", "32")))
            need = n_sessions * per
            fl_panel = _synthetic_arima_panel(need, 65 + rounds, seed=5)
            # differenced slices are stationary AR(2)-ish; one shared
            # order keeps every tenant in ONE coalescing group
            fl_hist = np.diff(fl_panel, axis=1).astype(np_dtype)
            fleet_reg = metrics.MetricsRegistry()
            # fresh lineage window: the e2e percentiles below must
            # describe THIS demo's pumped ticks, not leftovers from
            # earlier blocks (the plane is process-global)
            _lineage.reset()
            with metrics.span("bench.fleet_demo"):
                fl_model = arima.fit(2, 0, 0,
                                     jnp.asarray(fl_hist[:per, :64]),
                                     warn=False)
                sched = FleetScheduler(AdmissionPolicy(queue_depth=4),
                                       registry=fleet_reg,
                                       auto_pump=False)
                for i in range(n_sessions):
                    sess = sstate.ServingSession.start(
                        fl_model, fl_hist[i * per:(i + 1) * per, :64],
                        label=f"bench-t{i}", registry=fleet_reg)
                    sched.attach(sess)
                sched.warmup()             # compile outside the timing
                live = fl_hist[:, 64:64 + rounds]
                rt = FleetRuntime(sched, registry=fleet_reg,
                                  label="bench-fleet",
                                  policy=RuntimePolicy(
                                      pump_interval_s=0.0005))
                with rt:
                    t0 = time.perf_counter()
                    for t in range(rounds):
                        for i in range(n_sessions):
                            rt.submit(f"bench-t{i}",
                                      live[i * per:(i + 1) * per, t],
                                      block=True, timeout=60.0)
                    rt.quiesce(timeout=60.0)
                    fleet_s = time.perf_counter() - t0
                pooled = np.concatenate([
                    np.fromiter(sched.session(la)._tick_lat,
                                dtype=np.float64)
                    for la in sched.tenants]) * 1e3
                # lineage roll-up taken HERE, before the quality
                # sub-demo adds its own pumped ticks to the plane
                lin_sum = _lineage.lineage_summary()
            # fleet quality sub-block (ISSUE 15): a small SEPARATE
            # quality-armed tenant group pumped through its own
            # scheduler (private registry, after the timing) proves the
            # coalesced dispatch path with the fused quality step armed
            # and reports the aggregate online accuracy — without
            # perturbing the gated fleet_ticks_per_s numbers above.
            from spark_timeseries_tpu.statespace import QualityPolicy
            q_n, q_rounds = min(4, n_sessions), min(12, rounds)
            q_reg = metrics.MetricsRegistry()
            q_sched = FleetScheduler(AdmissionPolicy(queue_depth=4),
                                     registry=q_reg, auto_pump=False)
            for i in range(q_n):
                q_sched.attach(sstate.ServingSession.start(
                    fl_model, fl_hist[i * per:(i + 1) * per, :64],
                    label=f"bench-q{i}", registry=q_reg,
                    quality=QualityPolicy()))
            q_sched.warmup()
            q_live = fl_hist[:, 64:64 + q_rounds]
            for t in range(q_rounds):
                for i in range(q_n):
                    q_sched.submit(f"bench-q{i}",
                                   q_live[i * per:(i + 1) * per, t])
                q_sched.pump()
            q_sums = [q_sched.session(la).quality_summary() or {}
                      for la in q_sched.tenants]
            q_smapes = [s.get("live_smape") for s in q_sums
                        if isinstance(s.get("live_smape"), (int, float))]
            fl_quality = {
                "tenants": q_n, "ticks": q_rounds,
                "live_smape": round(float(np.mean(q_smapes)), 4)
                if q_smapes else None,
                "drift_alarms": int(sum(s.get("drift_alarms", 0)
                                        for s in q_sums)),
            }
            fl_counters = fleet_reg.snapshot()["counters"]
            stage_tot = lin_sum.get("stage_totals_ms") or {}
            stage_denom = sum(stage_tot.values()) or 1.0
            fleet_demo = {
                "sessions": n_sessions,
                "series_per_session": per,
                "ticks": rounds,
                "coalesced_dispatches": int(
                    fl_counters.get("fleet.coalesced_dispatches", 0)),
                "fleet_ticks_per_s": round(
                    n_sessions * per * rounds / fleet_s, 1),
                "tick_p99_ms": round(float(np.percentile(pooled, 99)), 3),
                "tick_p50_ms": round(float(np.percentile(pooled, 50)), 3),
                "shed_lanes": int(fl_counters.get("fleet.shed_lanes", 0)),
                "slo_burns": int(fl_counters.get("fleet.slo_burns", 0)),
                "rejected": int(fl_counters.get("fleet.rejected", 0)),
                "pump_restarts": int(
                    fl_counters.get("fleet.pump_restarts", 0)),
                "checkpoint_failures": int(
                    fl_counters.get("fleet.checkpoint_failures", 0)),
                "backpressure_waits": int(
                    fl_counters.get("fleet.backpressure_waits", 0)),
                # end-to-end submit→delivery latency from the lineage
                # plane (docs/design.md §6h): what a CALLER experienced,
                # vs tick_p50_ms which times only the jitted dispatch.
                # bench_gate gates fleet_e2e_p95_ms lower-is-better;
                # None (disarmed plane) degrades to tolerated-absent.
                "fleet_e2e_p50_ms": (lin_sum.get("e2e") or {}).get(
                    "p50_ms"),
                "fleet_e2e_p95_ms": (lin_sum.get("e2e") or {}).get(
                    "p95_ms"),
                "e2e_stage_share": {
                    k: round(v / stage_denom, 4)
                    for k, v in sorted(stage_tot.items())},
                "seconds": round(fleet_s, 3),
                "quality": fl_quality,
            }
        except Exception as e:  # noqa: BLE001 — optional extra; its
            # failure must not void the already-measured curve
            fleet_demo = {"error": f"{type(e).__name__}: {e}"}

    # ultra-long demo (ISSUE 8): one 10⁶-observation synthetic ARMA
    # series fitted end-to-end through the DARIMA split-and-combine tier
    # — global differencing, obs-axis segmentation, segments streamed as
    # a batch through engine.stream_fit (bucketed executables, chunk
    # isolation), in-graph WLS combination, and one exact forecast off
    # the affine-recurrence origin recovery.  `obs_per_s` is the tier's
    # headline throughput; tools/bench_gate.py guards it (long_obs_per_s,
    # 25% lower-is-regression) once two rounds carry it.
    long_demo = None
    if error is None and os.environ.get("BENCH_LONG", "1") == "1":
        try:
            from spark_timeseries_tpu import longseries
            from spark_timeseries_tpu.ops.scan_parallel import ar1_filter

            long_n = int(os.environ.get("BENCH_LONG_OBS", "1000000"))
            rng = np.random.default_rng(11)
            e = rng.standard_normal(long_n + 1).astype(np_dtype)
            # ARMA(1,1): MA part vectorized, AR(1) via the associative
            # scan (the subsystem's own O(log n) primitive)
            x = e[1:] + np_dtype(0.4) * e[:-1]
            series = np.asarray(ar1_filter(jnp.asarray(x), 0.1, 0.6),
                                np_dtype)
            with metrics.span("bench.long_demo"):
                t0 = time.perf_counter()
                lf = longseries.fit_long(series, order=(1, 0, 1),
                                         warn=False)
                fit_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                fc = lf.forecast(24)
                forecast_s = time.perf_counter() - t0
            long_demo = {
                "n_obs": long_n,
                "n_segments": lf.plan.n_segments,
                "seg_len": lf.plan.seg_len,
                "segments_weighted": lf.combined.n_weighted,
                "used_wls": lf.combined.used_wls,
                "coefficients_head": [round(float(v), 4) for v in
                                      np.asarray(lf.coefficients)[:4]],
                "sigma2": round(float(lf.sigma2), 4),
                "fit_s": round(fit_s, 3),
                "obs_per_s": round(lf.plan.n_used / fit_s, 1),
                "forecast_s_incl_origin": round(forecast_s, 3),
                "forecast_finite": bool(np.all(np.isfinite(fc))),
            }
        except Exception as e:  # noqa: BLE001 — optional extra; its
            # failure must not void the already-measured curve
            long_demo = {"error": f"{type(e).__name__}: {e}"}

    # backtest demo (ISSUE 13): the repo's FIRST ACCURACY HEADLINE — a
    # pinned synthetic panel of three known generating processes (AR(1),
    # ARMA(1,1), SES local level) swept through backtest_panel's
    # 4-candidate grid: per-candidate streamed fits, pinned-gain origin
    # replay, in-graph NaN-masked metrics, champion selection.
    # champion_smape / champion_mase are the panel-mean out-of-sample
    # errors of each series' champion; tools/bench_gate.py gates BOTH as
    # higher-is-regression once two rounds carry them — a modeling-path
    # change that silently degrades forecast quality now fails the gate
    # even if throughput is unchanged.  The panel is seeded and the
    # whole sweep deterministic on CPU, so the gated numbers move only
    # when the math does.
    backtest_demo = None
    if error is None and os.environ.get("BENCH_BACKTEST", "1") == "1":
        try:
            from spark_timeseries_tpu.backtest import (CandidateGrid,
                                                       backtest_panel)

            bt_S = max(6, int(os.environ.get("BENCH_BACKTEST_SERIES",
                                             "16")))
            bt_n = max(256, int(os.environ.get("BENCH_BACKTEST_OBS",
                                               "768")))
            bt_burn = 256

            def _bt_arma(S, phi, theta, seed):
                r = np.random.default_rng(seed)
                e = r.standard_normal((S, bt_n + bt_burn))
                y = np.zeros((S, bt_n + bt_burn))
                for t in range(1, bt_n + bt_burn):
                    ar = sum(p * y[:, t - 1 - i]
                             for i, p in enumerate(phi))
                    ma = sum(q * e[:, t - 1 - i]
                             for i, q in enumerate(theta))
                    y[:, t] = 2.0 + ar + e[:, t] + ma
                return y[:, bt_burn:]

            def _bt_ses(S, alpha, seed):
                r = np.random.default_rng(seed)
                e = r.standard_normal((S, bt_n))
                y = np.zeros((S, bt_n))
                lvl = np.full(S, 10.0)
                for t in range(bt_n):
                    y[:, t] = lvl + e[:, t]
                    lvl = lvl + alpha * e[:, t]
                return y

            bt_panel = np.concatenate([
                _bt_arma(bt_S, (0.8,), (), 101),
                _bt_arma(bt_S, (0.4,), (0.9,), 102),
                _bt_ses(bt_S, 0.4, 103),
            ]).astype(np_dtype)
            bt_truth = np.repeat([0, 2, 3], bt_S)
            bt_grid = CandidateGrid({"ar": [1, 2], "arima": [(1, 0, 1)],
                                     "ewma": True}, horizons=(1, 2, 4))
            with metrics.span("bench.backtest_demo"):
                t0 = time.perf_counter()
                bt_rep = backtest_panel(bt_panel, bt_grid,
                                        n_origins=128, stride=2,
                                        min_train=bt_n - 256)
                bt_s = time.perf_counter() - t0
            bt_sm = bt_rep.champion_score("smape")
            bt_ms = bt_rep.champion_score("mase")
            backtest_demo = {
                "n_series": int(bt_panel.shape[0]),
                "n_obs": bt_n,
                "n_candidates": len(bt_rep.candidates),
                "n_origins": bt_rep.schedule.n_origins,
                "horizons": list(bt_rep.horizons),
                "champion_smape": round(float(np.nanmean(bt_sm)), 4),
                "champion_mase": round(float(np.nanmean(bt_ms)), 4),
                "true_model_recovery": round(float(
                    np.mean(bt_rep.champion == bt_truth)), 4),
                "champion_counts": bt_rep.champion_counts(),
                "coverage_mean": round(float(np.nanmean(
                    bt_rep.horizon_table("coverage"))), 4),
                "series_per_s": round(
                    bt_panel.shape[0] * len(bt_rep.candidates) / bt_s, 1),
                "seconds": round(bt_s, 3),
            }
        except Exception as e:  # noqa: BLE001 — optional extra; its
            # failure must not void the already-measured curve
            backtest_demo = {"error": f"{type(e).__name__}: {e}"}

    # compiled-program cost accounting (ISSUE 3): ask XLA what one
    # compiled fit of the benched chunk shape costs — FLOPs, bytes, peak
    # memory, HLO op mix — per family in BENCH_COST_FAMILIES (default:
    # the headline's own family).  Shape-only lowering: each block costs
    # one compile, no fitting; the blocks let the perf trajectory
    # correlate measured regressions with what the compiler emitted.
    cost_reports = {}
    cost_fams = [f for f in os.environ.get("BENCH_COST_FAMILIES",
                                           "arima").split(",") if f]
    for fam in cost_fams:
        try:
            with metrics.span("bench.cost_report"):
                cost_reports[fam] = costs.fit_cost_report(
                    fam, min(chunk, n_target), n_obs, dtype=dtype)
        except Exception as e:  # noqa: BLE001 — optional accounting; its
            # failure must not void the measured curve
            cost_reports[fam] = {"error": f"{type(e).__name__}: {e}"}

    if not curve:
        # nothing measured at all (first fit died): the run is still not
        # empty — the CPU-baseline emulation above always completes
        record = {
            "metric": f"ARIMA(2,1,2) fit FAILED before first curve point "
                      f"({n_target}x{n_obs})",
            "value": None,
            "unit": "series/sec",
            "platform": platform,
            "error": error,
            "baseline_emulation": {
                "kind": "per-series scipy Powell on the same CSS objective",
                "sample": BASELINE_SAMPLE,
                "rate": round(cpu_rate, 3),
            },
        }
        if degraded:
            record["degraded"] = DEGRADED_NOTE + " also failed"
        emit(record)
        return

    peak = _peak_memory_bytes()
    peak_mb = round(peak / 2**20, 1) if peak is not None else None

    best_n = max(int(k) for k in curve)

    # device-resident compute rate on one chunk — the same fit with the
    # panel already in HBM, so the H2D transfer drops out of the timing.
    # The gap between this and the pipeline rate is the transfer overhead
    # the double buffering couldn't hide (the roofline's numerator).
    device_resident = None
    try:
        with metrics.span("bench.device_resident"):
            c = min(chunk, best_n)
            dev = jax.device_put(jnp.asarray(panel[:c], dtype))
            # same engine executable as the streamed chunks, panel
            # already in HBM, results pulled to host each rep
            np.asarray(eng.fit(dev, "arima", p=2, d=1, q=2)
                       .coefficients)                        # warm
            reps_dr = 3
            t0 = time.perf_counter()
            for _ in range(reps_dr):
                np.asarray(eng.fit(dev, "arima", p=2, d=1, q=2)
                           .coefficients)
            device_resident = round(c * reps_dr
                                    / (time.perf_counter() - t0), 1)
        emit({
            "metric": "ARIMA(2,1,2) series fitted/sec/chip "
                      f"(device-resident chunk {c}x{n_obs}, no H2D)",
            "value": device_resident,
            "unit": "series/sec",
            "vs_baseline": round(device_resident / cpu_rate, 2),
            "platform": platform,
            "css_lm_path": css_lm_path,
        })
    except Exception as e:          # noqa: BLE001 — optional extra
        print(f"# device-resident timing failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)

    # H2D auditability (round-4 verdict item 3): how much of the measured
    # transfer time did the double-buffered pipeline hide under compute?
    # t_serial = t_device_resident + t_h2d; overlap = (t_serial - t_pipe)
    # / t_h2d.  A single-chunk point has nothing to pipeline (its transfer
    # strictly precedes its compute), which is why small curve points can
    # undercut larger ones over a slow tunnel — n_chunks on each curve
    # line makes that readable from the artifact alone.
    h2d_mbps = curve_h2d.get(str(best_n))
    overlap_pct = None
    if on_tpu and h2d_mbps and device_resident:
        t_h2d = best_n * n_obs * np.dtype(np_dtype).itemsize \
            / (h2d_mbps * 2**20)
        t_pipe = best_n / curve[str(best_n)]
        t_dr = best_n / device_resident
        if t_h2d > 0:
            overlap_pct = round(
                100.0 * max(0.0, min(1.0, (t_dr + t_h2d - t_pipe) / t_h2d)),
                1)

    # fused vs staged A/B (ISSUE 20, docs/design.md §6e): the warm chunk
    # path through the SAME cached executable, publishing through the
    # per-bucket plan (fused — the headline default) vs the per-chunk
    # skeleton walk (staged — the bitwise oracle).  Program counts come
    # from the engine's own counters: a warm A/B pass that compiles
    # anything is itself a finding.
    # The A/B runs in float32 — the production dtype (§6's contract) —
    # even when the degraded CPU curve above measured f64 for scipy
    # parity, so the fused/staged rates baseline apples-to-apples with
    # what an accelerator round would measure.
    fused_vs_staged = None
    try:
        ab_n = min(8192, n_target)
        ab_c = min(chunk, ab_n)
        ab_panel = np.asarray(panel[:ab_n], np.float32)
        fused_vs_staged = {"n_series": ab_n, "chunk": ab_c,
                           "dtype": "float32"}
        for label, fu in (("staged", False), ("fused", True)):
            best = None
            misses0 = eng.cache_stats()["cache_misses"]
            for _ in range(2):
                t0 = time.perf_counter()
                r = eng.stream_fit(ab_panel, "arima", chunk_size=ab_c,
                                   p=2, d=1, q=2, fused=fu)
                dt = time.perf_counter() - t0
                if best is None or dt < best[0]:
                    best = (dt, r)
            fused_vs_staged[label] = {
                "rate": round(ab_n / best[0], 1),
                "programs_compiled":
                    eng.cache_stats()["cache_misses"] - misses0,
                "programs_dispatched": best[1].n_chunks,
                "publish_plans": int(best[1].stats.get(
                    "publish_plans", 0)),
            }
    except Exception as e:          # noqa: BLE001 — optional extra
        print(f"# fused/staged A/B failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)

    headline = {
        "metric": "ARIMA(2,1,2) series fitted/sec/chip "
                  f"({best_n}x{n_obs} panel, chunk={min(chunk, best_n)})",
        "value": curve[str(best_n)],
        "unit": "series/sec",
        "vs_baseline": round(curve[str(best_n)] / cpu_rate, 2),
        "converged_pct": round(100.0 * converged_target / best_n, 2),
        "scaling_curve": curve,
        "curve_h2d_mbps": curve_h2d,
        "h2d_mbps": h2d_mbps,
        "h2d_overlap_pct": overlap_pct,
        "device_resident_rate": device_resident,
        "fused_vs_staged": fused_vs_staged,
        "platform": platform,
        "css_lm_path": css_lm_path,
        "peak_device_memory_mb": peak_mb,
        "refit_demo": refit_demo,
        "resilience_demo": resilience_demo,
        "serving_demo": serving_demo,
        "fleet_demo": fleet_demo,
        "long_demo": long_demo,
        "backtest_demo": backtest_demo,
        "cost_reports": cost_reports,
        "baseline_emulation": {
            "kind": "per-series scipy Powell on the same CSS objective",
            "sample": BASELINE_SAMPLE,
            "rate": round(cpu_rate, 3),
            "per_series_sec_min": round(min(cpu_times), 3),
            "per_series_sec_max": round(max(cpu_times), 3),
        },
    }
    # headline attribution (docs/design.md §6g): the headline point's own
    # stream phase accounting — per-chunk host/device phase records, the
    # device-idle bubble, and the host-overhead fraction that
    # tools/bench_gate.py gates (lower-better, tolerated-absent in
    # pre-attribution rounds)
    att = (eng_by_n.get(best_n) or {}).get("phases")
    if isinstance(att, dict):
        headline["engine_attribution"] = att
    if degraded:
        headline["degraded"] = DEGRADED_NOTE + " at reduced scale"
    if error is not None:
        headline["partial"] = True
        headline["error"] = error
    emit(headline)


if __name__ == "__main__":
    main()
