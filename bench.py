"""Headline benchmark: ARIMA(2,1,2) batched fitting throughput
(series fitted/sec/chip) — the BASELINE.md north-star metric.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md), so the baseline is measured
in-process: the reference's per-series fit path — Hannan-Rissanen init + a
scalar optimizer loop per series (Commons-Math CGD/BOBYQA, ref
``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMA.scala:79-200``)
— is emulated with a per-series scipy fit of the same CSS objective on CPU,
timed on a subsample and extrapolated.  ``vs_baseline`` = batched rate
divided by that per-series CPU rate.
"""

import json
import os
import time

import numpy as np


def _synthetic_arima_panel(n_series: int, n_obs: int,
                           seed: int = 0) -> np.ndarray:
    """ARIMA(2,1,2) draws: ARMA(2,2) innovations then one integration."""
    rng = np.random.default_rng(seed)
    phi = np.stack([rng.uniform(0.1, 0.3, n_series),
                    rng.uniform(0.2, 0.5, n_series)], axis=1)
    theta = np.stack([rng.uniform(0.1, 0.4, n_series),
                      rng.uniform(0.0, 0.2, n_series)], axis=1)
    eps = rng.normal(size=(n_series, n_obs + 2))
    y = np.zeros((n_series, n_obs))
    for t in range(n_obs):
        ar = 0.0
        if t >= 1:
            ar = phi[:, 0] * y[:, t - 1]
        if t >= 2:
            ar = ar + phi[:, 1] * y[:, t - 2]
        ma = theta[:, 0] * eps[:, t + 1] + theta[:, 1] * eps[:, t]
        y[:, t] = 1.0 + ar + ma + eps[:, t + 2]
    return np.cumsum(y, axis=1)


def _css_neg_ll(params: np.ndarray, diffed: np.ndarray,
                p: int = 2, q: int = 2) -> float:
    """Scalar-loop CSS negative log likelihood — the reference's per-series
    objective shape (ref ``ARIMA.scala:430-445,581-618``)."""
    c = params[0]
    phi = params[1:1 + p]
    theta = params[1 + p:1 + p + q]
    n = diffed.shape[0]
    max_lag = max(p, q)
    errs = np.zeros(q)
    css = 0.0
    for i in range(max_lag, n):
        yhat = c
        for j in range(p):
            yhat += phi[j] * diffed[i - j - 1]
        for j in range(q):
            yhat += theta[j] * errs[j]
        e = diffed[i] - yhat
        css += e * e
        if q:
            errs[1:] = errs[:-1]
            errs[0] = e
    sigma2 = css / n
    return 0.5 * n * np.log(2 * np.pi * sigma2) + css / (2 * sigma2)


def _baseline_rate(panel: np.ndarray, sample: int = 6) -> float:
    """Per-series reference-style CPU rate (series/sec): HR-free init plus a
    derivative-free scipy solve of the same CSS objective (the css-bobyqa
    path's cost shape)."""
    from scipy.optimize import minimize as sp_minimize

    sub = panel[:sample]
    t0 = time.perf_counter()
    for row in sub:
        diffed = np.diff(row)
        x0 = np.array([np.mean(diffed), 0.1, 0.1, 0.1, 0.1])
        sp_minimize(_css_neg_ll, x0, args=(diffed,), method="Powell",
                    options={"maxiter": 2000})
    dt = time.perf_counter() - t0
    return sample / dt


def main():
    import jax
    import jax.numpy as jnp
    from spark_timeseries_tpu.models import arima

    n_series = int(os.environ.get("BENCH_N_SERIES", "8192"))
    n_obs = int(os.environ.get("BENCH_N_OBS", "128"))
    panel = _synthetic_arima_panel(n_series, n_obs)

    if jax.devices()[0].platform == "tpu":
        dtype = jnp.float32
    else:
        jax.config.update("jax_enable_x64", True)
        dtype = jnp.float64
    values = jnp.asarray(panel, dtype=dtype)

    fit = jax.jit(lambda v: arima.fit(2, 1, 2, v, warn=False).coefficients)
    # time to host materialization: on the tunneled TPU platform,
    # block_until_ready alone does not synchronize with device execution
    np.asarray(fit(values))  # compile + warm
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(fit(values))
    batched_rate = n_series * reps / (time.perf_counter() - t0)

    cpu_rate = _baseline_rate(panel)

    print(json.dumps({
        "metric": "ARIMA(2,1,2) series fitted/sec/chip (synthetic panel, "
                  f"{n_series}x{n_obs})",
        "value": round(batched_rate, 1),
        "unit": "series/sec",
        "vs_baseline": round(batched_rate / cpu_rate, 2),
    }))


if __name__ == "__main__":
    main()
