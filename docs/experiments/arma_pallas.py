"""Pallas TPU kernel for the ARMA CSS inner loop — the framework's hot op.

Every Levenberg-Marquardt iteration of an ARIMA/ARIMAX fit needs, per
series: the one-step residuals ``e_t``, the Gauss-Newton normal equations
``J^T J`` / ``J^T e``, and the cost.  The XLA path builds them by
``jacfwd`` through a ``lax.scan`` (p+q+1 tangent streams through HBM); this
kernel instead runs the error recurrence AND the reference's analytic
derivative recurrence (ref
``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMA.scala:465-534``):

    e_t       = y_t - c - Σ_j φ_j y_{t-j-1} - Σ_k θ_k e_{t-k}
    ∂e_t/∂x   = -u_t - Σ_k θ_k ∂e_{t-k}/∂x,   u = (1, y_{t-j-1}, e_{t-k})

entirely in VMEM, accumulating the packed upper triangle of ``J^T J``,
``J^T e`` and the cost in one pass over time.  Series are blocked
``(8, 128)`` lanes per grid step (the float32 VPU tile), parameters ride
as per-lane vectors, and every op is elementwise — pure VPU work with no
HBM traffic beyond one read of the series block.

On non-TPU backends the same kernel runs under ``interpret=True`` (used by
the CPU test tier); callers gate on platform via :func:`use_pallas`.

Measured on a v5e chip (8192 series x 128 obs, ARIMA(2,1,2)): this kernel
reaches ~5.5k fits/sec while the XLA ``jacfwd``-through-``scan`` path in
:func:`spark_timeseries_tpu.ops.optimize.minimize_least_squares` reaches
~12.8k — XLA's fusion of the tangent streams already saturates the VPU for
this recurrence, and Mosaic's per-step dynamic VMEM reads cost more than
XLA's pipelined scan.  The kernel is therefore kept as an alternative
backend (and the template for a future cross-chip RDMA variant), not the
default fit path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
MAX_ROWS = 64          # sublane rows per block: 64x128 lanes = 8 VPU tiles


def use_pallas() -> bool:
    return jax.default_backend() == "tpu"


def _block_rows(n_series: int) -> int:
    """Sublane rows per grid block: enough to cover the panel (amortizing
    per-op issue overhead across VPU tiles) but capped so VMEM holds the
    series block."""
    rows = -(-n_series // LANES)
    return max(8, min(MAX_ROWS, ((rows + 7) // 8) * 8))


def _pack_triu_index(n: int):
    pairs = []
    for a in range(n):
        for b in range(a, n):
            pairs.append((a, b))
    return pairs


def _css_kernel(p: int, q: int, icpt: int, n_obs: int, with_grad: bool,
                params_ref, y_ref, out_ref):
    """One series block: params (nparams, 8, 128), y (n_obs, 8, 128),
    out (n_out, 8, 128) where n_out = 1 (cost) [+ triu + nparams]."""
    nparams = icpt + p + q
    max_lag = max(p, q)
    pairs = _pack_triu_index(nparams) if with_grad else []

    # derive the zero from real data so Mosaic gives every carry entry the
    # same (non-replicated) layout as computed values
    zero = y_ref[0, 0] * 0.0
    c = params_ref[0, 0] if icpt else zero
    phi = [params_ref[icpt + j, 0] for j in range(p)]
    theta = [params_ref[icpt + p + k, 0] for k in range(q)]

    # carry: error ring (q), derivative rings (q per param), accumulators
    n_acc = 1 + (len(pairs) + nparams if with_grad else 0)
    carry0 = ([zero] * q                                   # e ring, newest first
              + [zero] * (q * nparams if with_grad else 0)  # de rings
              + [zero] * n_acc)                             # cost, jtj, jtr

    def body(t, carry):
        e_ring = list(carry[:q])
        off = q
        if with_grad:
            de_ring = [list(carry[off + k * nparams: off + (k + 1) * nparams])
                       for k in range(q)]
            off += q * nparams
        acc = list(carry[off:])

        y_t = y_ref[t, 0]
        yhat = c
        for j in range(p):
            yhat = yhat + phi[j] * y_ref[t - (j + 1), 0]
        for k in range(q):
            yhat = yhat + theta[k] * e_ring[k]
        e_t = y_t - yhat

        if with_grad:
            # de_t[x] = -(u_x + Σ_k θ_k de_{t-k}[x])
            de_t = []
            for x in range(nparams):
                if x < icpt:
                    u = zero + 1.0
                elif x < icpt + p:
                    u = y_ref[t - (x - icpt + 1), 0]
                else:
                    u = e_ring[x - icpt - p]
                s = u
                for k in range(q):
                    s = s + theta[k] * de_ring[k][x]
                de_t.append(-s)

        # accumulate
        acc[0] = acc[0] + e_t * e_t
        if with_grad:
            for idx, (a, b) in enumerate(pairs):
                acc[1 + idx] = acc[1 + idx] + de_t[a] * de_t[b]
            for x in range(nparams):
                acc[1 + len(pairs) + x] = \
                    acc[1 + len(pairs) + x] + de_t[x] * e_t

        new_e_ring = ([e_t] + e_ring[:-1]) if q else []
        out = list(new_e_ring)
        if with_grad:
            new_de = [de_t] + de_ring[:-1] if q else []
            for ring in new_de:
                out.extend(ring)
        out.extend(acc)
        return tuple(out)

    final = jax.lax.fori_loop(max_lag, n_obs, body, tuple(carry0))
    off = q + (q * nparams if with_grad else 0)
    for i in range(n_acc):
        out_ref[i, 0] = final[off + i]


@functools.lru_cache(maxsize=None)
def _build_call(p: int, q: int, icpt: int, n_obs: int, n_blocks: int,
                rows: int, with_grad: bool, interpret: bool):
    nparams = icpt + p + q
    n_out = 1 + (len(_pack_triu_index(nparams)) + nparams if with_grad else 0)
    kernel = functools.partial(_css_kernel, p, q, icpt, n_obs, with_grad)
    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((nparams, 1, rows, LANES),
                         lambda i: (0, i, 0, 0)),
            pl.BlockSpec((n_obs, 1, rows, LANES),
                         lambda i: (0, i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((n_out, 1, rows, LANES),
                               lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (n_out, n_blocks, rows, LANES), jnp.float32),
        interpret=interpret,
    )


def _blocked(x: jnp.ndarray, n_series: int,
             rows: int) -> Tuple[jnp.ndarray, int, int]:
    """(n_series, k) -> (k, n_blocks, rows, 128) with zero padding."""
    block = rows * LANES
    pad = (-n_series) % block
    n_blocks = (n_series + pad) // block
    x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
    x = jnp.moveaxis(x, 0, -1)                      # (k, S)
    return x.reshape(*x.shape[:-1], n_blocks, rows, LANES), n_blocks, pad


def css_normal_equations(params: jnp.ndarray, y: jnp.ndarray,
                         p: int, q: int, icpt: int,
                         interpret: bool | None = None):
    """Batched (J^T J, J^T e, cost) for the ARMA CSS residuals.

    ``params (S, nparams)`` float32, ``y (S, n)`` float32 (the differenced
    series).  Returns ``(jtj (S, nparams, nparams), jtr (S, nparams),
    cost (S,))``.
    """
    if interpret is None:
        interpret = not use_pallas()
    nparams = icpt + p + q
    S, n_obs = y.shape
    rows = _block_rows(S)
    params_b, n_blocks, _ = _blocked(params.astype(jnp.float32), S, rows)
    y_b, _, _ = _blocked(y.astype(jnp.float32), S, rows)

    call = _build_call(p, q, icpt, n_obs, n_blocks, rows, True, interpret)
    out = call(params_b, y_b)                       # (n_out, nb, 8, 128)
    out = out.reshape(out.shape[0], -1)[:, :S].T    # (S, n_out)

    cost = out[:, 0]
    pairs = _pack_triu_index(nparams)
    jtj = jnp.zeros((S, nparams, nparams), jnp.float32)
    for idx, (a, b) in enumerate(pairs):
        v = out[:, 1 + idx]
        jtj = jtj.at[:, a, b].set(v)
        if a != b:
            jtj = jtj.at[:, b, a].set(v)
    jtr = out[:, 1 + len(pairs):1 + len(pairs) + nparams]
    return jtj, jtr, cost


def css_cost(params: jnp.ndarray, y: jnp.ndarray,
             p: int, q: int, icpt: int,
             interpret: bool | None = None) -> jnp.ndarray:
    """Batched CSS (sum of squared one-step errors) only — the cheap trial
    evaluation inside the LM loop.  Shapes as in
    :func:`css_normal_equations`; returns ``(S,)``."""
    if interpret is None:
        interpret = not use_pallas()
    S, n_obs = y.shape
    rows = _block_rows(S)
    params_b, n_blocks, _ = _blocked(params.astype(jnp.float32), S, rows)
    y_b, _, _ = _blocked(y.astype(jnp.float32), S, rows)
    call = _build_call(p, q, icpt, n_obs, n_blocks, rows, False, interpret)
    out = call(params_b, y_b)
    return out.reshape(out.shape[0], -1)[0, :S]


def fit_css_lm(params0: jnp.ndarray, y: jnp.ndarray, p: int, q: int,
               icpt: int, max_iter: int = 50, tol: float = 1e-6,
               interpret: bool | None = None):
    """Levenberg-Marquardt on the CSS residuals driven by the fused kernel.

    Same algorithm as :func:`spark_timeseries_tpu.ops.optimize.
    minimize_least_squares` (Marquardt-scaled damping, accept-if-improved,
    per-lane convergence) but with the normal equations built by one Pallas
    pass instead of ``jacfwd`` streams.  All lanes iterate together; state
    is ``(x, cost, lam, done)`` batched over series.

    Returns ``(x (S, k), cost (S,), converged (S,), n_iter ())``.
    """
    if interpret is None:
        interpret = not use_pallas()
    params0 = params0.astype(jnp.float32)
    y = y.astype(jnp.float32)
    S, k = params0.shape
    eye = jnp.eye(k, dtype=jnp.float32)

    def body(state):
        x, f, lam, done, it = state
        jtj, jtr, _ = css_normal_equations(x, y, p, q, icpt, interpret)
        damp = lam[:, None] * jnp.diagonal(jtj, axis1=-2, axis2=-1) + 1e-12
        delta = jnp.linalg.solve(jtj + damp[:, :, None] * eye,
                                 jtr[..., None])[..., 0]
        x_new = x - delta
        f_new = css_cost(x_new, y, p, q, icpt, interpret)
        improved = (f_new < f) & jnp.isfinite(f_new) & ~done
        x = jnp.where(improved[:, None], x_new, x)
        lam = jnp.where(done, lam,
                        jnp.where(improved, lam * 0.1, lam * 10.0))
        rel_drop = (f - f_new) <= tol * (jnp.abs(f) + tol)
        step_small = jnp.max(jnp.abs(delta), axis=-1) <= tol * (
            jnp.max(jnp.abs(x), axis=-1) + tol)
        newly_done = improved & (rel_drop | step_small)
        newly_done = newly_done | (~improved & (lam > 1e8))
        f = jnp.where(improved, f_new, f)
        return x, f, lam, done | newly_done, it + 1

    def cond(state):
        _, _, _, done, it = state
        return (~jnp.all(done)) & (it < max_iter)

    f0 = css_cost(params0, y, p, q, icpt, interpret)
    lam0 = jnp.full((S,), 1e-3, jnp.float32)
    done0 = jnp.zeros((S,), bool)
    x, f, lam, done, it = jax.lax.while_loop(
        cond, body, (params0, f0, lam0, done0, jnp.asarray(0)))
    return x, f, done, it
