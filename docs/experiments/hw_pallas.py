"""Pallas TPU kernel for the Holt-Winters fused SSE value-and-grad.

The slowest model family in the suite is Holt-Winters: every projected-
gradient trial evaluates ``models.holt_winters._hw_sse_value_and_grad``
(ref recurrence ``/root/reference/src/main/scala/com/cloudera/sparkts/models/HoltWinters.scala:180-226``;
SSE objective ``:66-83``), a ``lax.scan`` whose per-lane carry — level,
trend, the period-``m`` season ring, their three tangents each, and the
(sse, grad) accumulators, ``4m + 12`` floats — streams through HBM every
step group exactly like the pre-Pallas ARMA pass did.  This kernel keeps
that carry in VMEM for the whole time axis, the architecture proven by
``ops/pallas_arma.py`` (1.57-2.23x measured on the ARMA fit):

- lanes block as ``(rows, 128)`` tiles with the full time axis resident;
- time advances in 16-step static-unrolled chunks (every series read a
  static VMEM index);
- the season rings are Python lists of VMEM values, rotated statically.

:func:`fit_box` is the panel-batched projected-gradient driver mirroring
``ops.optimize._minimize_box_one``'s state machine (Armijo backtracking
on the projected-gradient arc, per-lane convergence) in plain array ops
— one kernel dispatch per line-search trial for the whole panel, where
the vmapped driver pays XLA's batched while-in-while carry masking.

ARCHIVED (round 5, unmeasured): this driver shipped opt-in behind
``STS_PALLAS_HW=1`` in round 4 explicitly "until its A/B line is
captured on chip" — and the chip never admitted the capture: the one
healthy tunnel window of round 5 (08:32-08:51 UTC) wedged mid-
``pallas_ab.py`` before the HW line ran, and the wedge outlasted the
round (probe log: ``benchmarks/probe_log_r05.txt``).  The
build-measure-then-ship discipline cuts both ways: a perf path that was
never measured does not ship, even gated — so the driver moved here and
``holt_winters.fit`` keeps the measured XLA box fit
(``ops.optimize.minimize_box`` over the fused value-and-grad pass) as
its only path.

To measure and revive: run ``python docs/experiments/hw_pallas.py`` on
a healthy chip — it prints the A/B JSON line (this driver vs the
vmapped ``minimize_box``, the capture shape r4's ``pallas_ab.py`` used).
If it clears ~1.2x, restore the file to ``ops/pallas_hw.py``, re-wire
the ``route_panel`` gate in ``holt_winters.fit`` (git history:
``models/holt_winters.py`` @ r4-r5, gate at the ``minimize_box`` call),
and resurrect ``tests/test_pallas_hw.py`` from git history (it pinned
this kernel to ``_hw_sse_value_and_grad`` at interpret mode).  The
numerics were green when archived: the kernel matched the XLA pass and
the driver's fits matched ``minimize_box`` per lane.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spark_timeseries_tpu.ops.pallas_arma import (LANES, TIME_CHUNK,
                                                  _block_rows, _blocked,
                                                  use_pallas)


def _hw_kernel(m: int, additive: bool, n_steps: int,
               params_ref, init_ref, y_ref, out_ref):
    """One lane block.  ``params (3, rows, 128)`` = (α, β, γ);
    ``init (2+m, rows, 128)`` = (level0, trend0, season0[m]);
    ``y (n_steps, rows, 128)`` = series[period:];
    ``out (4, rows, 128)`` = (sse, dsse/dα, dsse/dβ, dsse/dγ).

    Step recurrence and tangents exactly as
    ``models.holt_winters._hw_sse_value_and_grad`` (dense path).
    """
    a, b, g = params_ref[0], params_ref[1], params_ref[2]
    zero = a * 0.0
    one_m_a = 1.0 - a
    one_m_b = 1.0 - b
    one_m_g = 1.0 - g
    n_chunks = n_steps // TIME_CHUNK
    tail = n_steps - n_chunks * TIME_CHUNK

    def steps(y_chunk, carry, count):
        (level, trend, seasons, dl, db_, dseasons, sse, grad) = carry
        for i in range(count):
            x = y_chunk[i]
            s_i = seasons[0]
            ds_i = dseasons[0]
            base = level + trend
            dbase = [dl[j] + db_[j] for j in range(3)]
            if additive:
                e = x - (base + s_i)
                de = [-(dbase[j] + ds_i[j]) for j in range(3)]
                lw = x - s_i
                dlw = [-ds_i[j] for j in range(3)]
            else:
                e = x - base * s_i
                de = [-(dbase[j] * s_i + base * ds_i[j]) for j in range(3)]
                lw = x / s_i
                x_s2 = x / (s_i * s_i)
                dlw = [-x_s2 * ds_i[j] for j in range(3)]
            new_level = a * lw + one_m_a * base
            dnl = [a * dlw[j] + one_m_a * dbase[j] for j in range(3)]
            dnl[0] = dnl[0] + (lw - base)              # e_α term
            new_trend = b * (new_level - level) + one_m_b * trend
            dnt = [b * (dnl[j] - dl[j]) + one_m_b * db_[j]
                   for j in range(3)]
            dnt[1] = dnt[1] + (new_level - level - trend)   # e_β term
            if additive:
                sw = x - new_level
                dsw = [-dnl[j] for j in range(3)]
            else:
                sw = x / new_level
                x_l2 = x / (new_level * new_level)
                dsw = [-x_l2 * dnl[j] for j in range(3)]
            new_season = g * sw + one_m_g * s_i
            dns = [g * dsw[j] + one_m_g * ds_i[j] for j in range(3)]
            dns[2] = dns[2] + (sw - s_i)               # e_γ term
            seasons = seasons[1:] + [new_season]
            dseasons = dseasons[1:] + [dns]
            level, trend, dl, db_ = new_level, new_trend, dnl, dnt
            sse = sse + e * e
            grad = [grad[j] + 2.0 * e * de[j] for j in range(3)]
        return (level, trend, seasons, dl, db_, dseasons, sse, grad)

    def flatten(carry):
        level, trend, seasons, dl, db_, dseasons, sse, grad = carry
        return (level, trend) + tuple(seasons) + tuple(dl) + tuple(db_) \
            + tuple(x for row in dseasons for x in row) + (sse,) \
            + tuple(grad)

    def unflatten(flat):
        level, trend = flat[0], flat[1]
        seasons = list(flat[2:2 + m])
        off = 2 + m
        dl = list(flat[off:off + 3])
        db_ = list(flat[off + 3:off + 6])
        off += 6
        dseasons = [list(flat[off + 3 * j: off + 3 * (j + 1)])
                    for j in range(m)]
        off += 3 * m
        return (level, trend, seasons, dl, db_, dseasons, flat[off],
                list(flat[off + 1:off + 4]))

    def chunk_body(ci, flat):
        base_t = pl.multiple_of(ci * TIME_CHUNK, 1)
        y_c = y_ref[pl.ds(base_t, TIME_CHUNK)]
        carry = steps([y_c[i] for i in range(TIME_CHUNK)],
                      unflatten(flat), TIME_CHUNK)
        return flatten(carry)

    carry0 = (init_ref[0], init_ref[1],
              [init_ref[2 + j] for j in range(m)],
              [zero] * 3, [zero] * 3,
              [[zero] * 3 for _ in range(m)], zero, [zero] * 3)
    flat = jax.lax.fori_loop(0, n_chunks, chunk_body, flatten(carry0)) \
        if n_chunks else flatten(carry0)
    if tail:
        base_t = n_chunks * TIME_CHUNK
        carry = steps([y_ref[base_t + i] for i in range(tail)],
                      unflatten(flat), tail)
    else:
        carry = unflatten(flat)
    _, _, _, _, _, _, sse, grad = carry
    out_ref[0] = sse
    for j in range(3):
        out_ref[1 + j] = grad[j]


@functools.lru_cache(maxsize=None)
def _build_call(m: int, additive: bool, n_steps: int, n_blocks: int,
                rows: int, interpret: bool):
    kernel = functools.partial(_hw_kernel, m, additive, n_steps)
    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((3, 1, rows, LANES), lambda i: (0, i, 0, 0)),
            pl.BlockSpec((2 + m, 1, rows, LANES), lambda i: (0, i, 0, 0)),
            pl.BlockSpec((n_steps, 1, rows, LANES), lambda i: (0, i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((4, 1, rows, LANES), lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((4, n_blocks, rows, LANES),
                                       jnp.float32),
        interpret=interpret,
    )


def sse_value_and_grad(params: jnp.ndarray, y_steps_b, init_b,
                       S: int, rows: int, n_blocks: int,
                       m: int, additive: bool, n_steps: int,
                       interpret: bool):
    """Blocked-input form: one kernel dispatch for the whole panel.
    ``params (S, 3)`` raw; ``y_steps_b``/``init_b`` pre-blocked."""
    params_b, _ = _blocked(params.astype(jnp.float32), S, rows)
    call = _build_call(m, additive, n_steps, n_blocks, rows, interpret)
    out = call(params_b, init_b, y_steps_b)       # (4, nb, rows, 128)
    out = out.reshape(4, -1)[:, :S]
    return out[0], out[1:].T                      # f (S,), g (S, 3)


def _prep(series: jnp.ndarray, period: int, model_type: str):
    """Shared data prep for the pass and the driver: validate the
    window, compute the data-only init components, and block the series
    and init planes once.  Returns
    ``(y_b, init_b, S, rows, n_blocks, n_steps, additive)``."""
    from ..models.holt_winters import HoltWintersModel
    additive = model_type.lower().startswith("additive")
    S, n = series.shape
    n_steps = n - period
    if n_steps < 1:
        raise ValueError(
            f"series too short for Holt-Winters: need more than "
            f"period = {period} observations, got {n}")
    probe = HoltWintersModel(model_type, period, 0.0, 0.0, 0.0)
    level0, trend0, season0 = probe._init_components(series)
    rows = _block_rows(S)
    y_b, n_blocks = _blocked(series[:, period:].astype(jnp.float32), S,
                             rows)
    init = jnp.concatenate([level0[:, None], trend0[:, None], season0],
                           axis=-1).astype(jnp.float32)
    init_b, _ = _blocked(init, S, rows)
    return y_b, init_b, S, rows, n_blocks, n_steps, additive


def value_and_grad(params: jnp.ndarray, series: jnp.ndarray, period: int,
                   model_type: str, interpret: bool | None = None):
    """Standalone batched ``(sse (S,), grad (S, 3))`` — drop-in numerics
    for ``models.holt_winters._hw_sse_value_and_grad`` (dense panels)."""
    if interpret is None:
        interpret = not use_pallas()
    y_b, init_b, S, rows, n_blocks, n_steps, additive = _prep(
        series, period, model_type)
    return sse_value_and_grad(params, y_b, init_b, S, rows, n_blocks,
                              period, additive, n_steps, interpret)


def _project(x):
    return jnp.clip(x, 0.0, 1.0)


def fit_box(x0: jnp.ndarray, series: jnp.ndarray, period: int,
            model_type: str, tol: float = 1e-10, max_iter: int = 1000,
            max_backtracks: int = 40, interpret: bool | None = None):
    """Panel-batched projected gradient on [0, 1]³ with the kernel pass.

    Mirrors ``ops.optimize._minimize_box_one`` (Armijo backtracking on
    the projected-gradient arc, identical accept/convergence tests) in
    plain array ops.  Returns ``(x, fun, converged, n_iter)``.
    """
    if interpret is None:
        interpret = not use_pallas()
    x0 = _project(x0.astype(jnp.float32))
    # init components are data-only: computed once, outside the loop
    y_b, init_b, S, rows, n_blocks, n_steps, additive = _prep(
        series, period, model_type)

    def vag(x):
        return sse_value_and_grad(x, y_b, init_b, S, rows, n_blocks,
                                  period, additive, n_steps, interpret)

    f0, g0 = vag(x0)

    def bt_cond(c):
        accepted, k, done = c[2], c[6], c[7]
        return jnp.logical_and(jnp.any(~accepted & ~done),
                               k < max_backtracks)

    def bt_body(c):
        t, x, accepted, xb, fb, gb, k, done, f, g = c
        x_trial = _project(x - t[:, None] * g)
        f_t, g_t = vag(x_trial)
        decrease = jnp.sum(g * (x - x_trial), axis=-1)
        ok = (f_t <= f - 1e-4 * decrease) & jnp.isfinite(f_t)
        newly = ok & ~accepted & ~done
        xb = jnp.where(newly[:, None], x_trial, xb)
        fb = jnp.where(newly, f_t, fb)
        gb = jnp.where(newly[:, None], g_t, gb)
        return (jnp.where(accepted | newly, t, t * 0.5), x,
                accepted | newly, xb, fb, gb, k + 1, done, f, g)

    def body(state):
        x, f, g, it_lanes, it, done = state
        t0 = jnp.ones((S,), jnp.float32)
        bt0 = (t0, x, jnp.zeros((S,), bool), x, f, g,
               jnp.asarray(0), done, f, g)
        _, _, accepted, x_new, f_new, g_new, _, _, _, _ = \
            jax.lax.while_loop(bt_cond, bt_body, bt0)
        step_norm = jnp.max(jnp.abs(x_new - x), axis=-1)
        f_stall = jnp.abs(f_new - f) <= tol * (jnp.abs(f) + tol)
        newly_done = (step_norm <= tol) | f_stall | ~accepted
        active = ~done
        take = accepted & active
        x = jnp.where(take[:, None], x_new, x)
        f = jnp.where(take, f_new, f)
        g = jnp.where(take[:, None], g_new, g)
        return (x, f, g, it_lanes + active.astype(jnp.int32), it + 1,
                done | (newly_done & active))

    def cond(state):
        done, it = state[5], state[4]
        return jnp.logical_and(~jnp.all(done), it < max_iter)

    x, f, _, it_lanes, _, done = jax.lax.while_loop(
        cond, body, (x0, f0, g0, jnp.zeros((S,), jnp.int32),
                     jnp.asarray(0), jnp.zeros((S,), bool)))
    return x, f, done, it_lanes


if __name__ == "__main__":
    # The A/B that decides revival (see the module docstring): this
    # driver vs the shipped vmapped minimize_box, at the shape round
    # 4's pallas_ab.py used.  Run on a healthy chip; off-TPU the kernel
    # interprets (hours — smoke only at tiny HW_AB_* overrides).
    import json
    import os
    import sys

    import numpy as np

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", ".."))
    from bench import timed_min
    from spark_timeseries_tpu.models.holt_winters import (
        _hw_sse_value_and_grad)
    from spark_timeseries_tpu.ops.optimize import minimize_box

    on_tpu = use_pallas()
    S = int(os.environ.get("HW_AB_SERIES", "4096" if on_tpu else "64"))
    n = int(os.environ.get("HW_AB_OBS", "120" if on_tpu else "32"))
    period = 12 if on_tpu else 8
    t_ax = np.arange(n)
    y = (10.0 + 0.05 * t_ax + 2.0 * np.sin(2 * np.pi * t_ax / period)
         )[None, :] + 0.3 * np.random.default_rng(0).normal(size=(S, n))
    y = jnp.asarray(y, jnp.float32)
    x0 = jnp.broadcast_to(jnp.asarray([0.3, 0.1, 0.1], jnp.float32),
                          (S, 3))
    iters = 200

    def xla():
        def run(x0_, y_):
            return minimize_box(
                lambda p, s: _hw_sse_value_and_grad(p, s, period,
                                                    "additive")[0],
                x0_, 0.0, 1.0, y_, tol=1e-6, max_iter=iters,
                value_and_grad_fn=lambda p, s: _hw_sse_value_and_grad(
                    p, s, period, "additive")).x
        return timed_min(jax.jit(run), x0, y)

    def pl_():
        def run(x0_, y_):
            return fit_box(x0_, y_, period, "additive", tol=1e-6,
                           max_iter=iters, interpret=not on_tpu)[0]
        return timed_min(jax.jit(run), x0, y)

    t_x, t_p = xla(), pl_()
    print(json.dumps({
        "metric": f"HoltWinters additive box fit ({S}x{n} f32, "
                  f"period={period}, max_iter={iters})",
        "xla_s": round(t_x, 3), "pallas_s": round(t_p, 3),
        "speedup": round(t_x / t_p, 2), "unit": "s/fit",
        "revive_if": ">= ~1.2x on a healthy chip"}))
