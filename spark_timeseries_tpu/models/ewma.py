"""EWMA (simple exponential smoothing), batched.

Capability parity with the reference's ``EWMA``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/EWMA.scala:32-144``):
model ``S_t = a * X_t + (1 - a) * S_{t-1}``, ``S_0 = X_0``; fitting minimizes
the one-step-ahead sum of squared errors over the smoothing parameter ``a``
starting from 0.94.

TPU-native design: the recurrence is a ``lax.scan``, and the scalar
Commons-Math CGD loop becomes one batched solve over the whole panel (one
compiled program fits every series at once).  The default ``method="lm"``
runs Levenberg-Marquardt on hand-fused normal equations accumulated in the
scan carry (``_ewma_normal_eqs``; the reference also hand-derives its
gradient, ``EWMA.scala:102-123``); ``method="bfgs"``/``"box"`` use autodiff
through the same scan.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
from jax import lax

from ..ops.optimize import (minimize_bfgs, minimize_box,
                            minimize_least_squares)
from ..ops.ragged import (apply_short_quarantine, ragged_view, short_lanes,
                          step_weights)
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from .base import (FitDiagnostics, diagnostics_from, normal_quantile,
                   scan_unroll)

# floor for the smoothing parameter when *inverting* the recurrence: the
# box method's lower bound (EWMA.scala's unbounded CGD shares the hazard —
# a lane at a≈0 would emit inf when dividing by it)
SMOOTHING_FLOOR = 1e-4


class EWMAModel(NamedTuple):
    """Smoothing parameter ``a``: scalar for one series, ``(n_series,)`` for
    a batched panel fit (ref ``EWMA.scala:75``)."""
    smoothing: jnp.ndarray
    diagnostics: Optional[FitDiagnostics] = None

    @property
    def n_params(self) -> int:
        """Estimated-parameter count (the smoothing scalar) — the
        parsimony key the backtest tier's champion tie-break orders
        near-equal out-of-sample scores by."""
        return 1

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Smooth i.i.d. observations: ``S_t = a X_t + (1-a) S_{t-1}``
        (ref ``EWMA.scala:135-143``).  ``ts (..., n)``; scan over time with
        the batch riding along elementwise."""
        a = jnp.asarray(self.smoothing)
        xs = jnp.moveaxis(ts, -1, 0)            # (n, ...)

        def step(s_prev, x_t):
            s = a * x_t + (1.0 - a) * s_prev
            return s, s

        _, out = lax.scan(step, xs[0], xs[1:], unroll=scan_unroll())
        return jnp.moveaxis(jnp.concatenate([xs[:1], out]), 0, -1)

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Invert the smoothing recurrence — elementwise, no scan needed
        (ref ``EWMA.scala:125-133``).  The divisor is floored at
        ``SMOOTHING_FLOOR`` so an unconstrained-fit lane at ``a≈0`` yields a
        large-but-finite inversion instead of inf poisoning the batch."""
        a = jnp.asarray(self.smoothing)
        if a.ndim and ts.ndim > 1:
            a = a[..., None]
        a = jnp.where(a >= 0, jnp.maximum(a, SMOOTHING_FLOOR),
                      jnp.minimum(a, -SMOOTHING_FLOOR))
        prev = ts[..., :-1]
        rest = (ts[..., 1:] - (1.0 - a) * prev) / a
        return jnp.concatenate([ts[..., :1], rest], axis=-1)

    def sse(self, ts: jnp.ndarray) -> jnp.ndarray:
        """One-step-ahead SSE: forecast for t+1 is the smoothed value at t
        (ref ``EWMA.scala:81-96``)."""
        smoothed = self.add_time_dependent_effects(ts)
        err = ts[..., 1:] - smoothed[..., :-1]
        return jnp.sum(err * err, axis=-1)

    def forecast(self, ts: jnp.ndarray, n_future: int) -> jnp.ndarray:
        """Flat forecast at the final smoothed level — simple exponential
        smoothing has no trend or season, so every horizon repeats S_n
        (beyond reference: ``EWMA.scala`` exposes no forecast surface).
        ``ts (..., n)`` → ``(..., n_future)``."""
        if n_future < 1:
            raise ValueError("forecast needs n_future >= 1")
        ts = jnp.asarray(ts)
        level = self.add_time_dependent_effects(ts)[..., -1]
        return jnp.broadcast_to(level[..., None],
                                (*level.shape, n_future))

    def forecast_interval(self, ts: jnp.ndarray, n_future: int,
                          conf: float = 0.95):
        """Prediction bands for the flat forecast: the SES forecast-error
        variance is ``var_h = σ²(1 + (h-1)α²)`` (the class-1 state-space
        result with β = γ = 0), σ² from the one-step residuals.  Returns
        ``(point, lower, upper)``, each ``(..., n_future)``."""
        if n_future < 1:
            raise ValueError("forecast_interval needs n_future >= 1")
        ts = jnp.asarray(ts)
        a = jnp.asarray(self.smoothing, ts.dtype)
        smoothed = self.add_time_dependent_effects(ts)
        point = jnp.broadcast_to(
            smoothed[..., -1:], (*smoothed.shape[:-1], n_future))
        err = ts[..., 1:] - smoothed[..., :-1]
        sigma2 = jnp.mean(err * err, axis=-1)
        h = jnp.arange(n_future, dtype=ts.dtype)         # h-1 for h = 1..
        var_h = sigma2[..., None] * (1.0 + h * a[..., None] ** 2)
        half = normal_quantile(conf, ts.dtype) * jnp.sqrt(var_h)
        return point, point - half, point + half


def _ewma_normal_eqs(params: jnp.ndarray, series: jnp.ndarray,
                     n_valid=None):
    """Fused-carry Gauss-Newton pass for the one-step SSE residuals (same
    trick as ``arima._arma_normal_eqs``, docs/design.md §9b): with
    ``s_t = a x_t + (1-a) s_{t-1}`` and ``e_t = x_{t+1} - s_t``, the
    tangent obeys ``ds_t = x_t - s_{t-1} + (1-a) ds_{t-1}``, so JᵀJ, Jᵀr,
    and sse accumulate in the scan carry and no ``(1, m)`` Jacobian is
    materialized.  The ``t = 0`` residual ``x_1 - s_0 = x_1 - x_0`` has
    zero tangent (``s_0 = x_0`` is data).

    ``n_valid`` (scalar): valid-window length of a left-aligned ragged
    lane (``ops.ragged``) — residuals whose target index falls past it
    get weight 0, matching the trimmed series exactly."""
    a = params[0]

    if n_valid is None:
        def step(carry, inp):
            s, ds, jtj, jtr, sse = carry
            x_t, x_next = inp
            ds = x_t - s + (1.0 - a) * ds
            s = a * x_t + (1.0 - a) * s
            e = x_next - s
            return (s, ds, jtj + ds * ds, jtr - ds * e, sse + e * e), None

        xs = (series[1:-1], series[2:])
    else:
        def step(carry, inp):
            s, ds, jtj, jtr, sse = carry
            x_t, x_next, w = inp
            ds = x_t - s + (1.0 - a) * ds
            s = a * x_t + (1.0 - a) * s
            e = w * (x_next - s)
            dsw = w * ds
            return (s, ds, jtj + dsw * dsw, jtr - dsw * e,
                    sse + e * e), None

        # residual e_t targets x_{t+1} at absolute index i+2 for step i
        ws = step_weights(series.shape[-1] - 2, n_valid, offset=2,
                          dtype=series.dtype)
        xs = (series[1:-1], series[2:], ws)

    zero = jnp.zeros((), series.dtype)
    (_, _, jtj, jtr, sse), _ = lax.scan(
        step, (series[0], zero, zero, zero, zero), xs,
        unroll=scan_unroll())
    e0 = series[1] - series[0]
    if n_valid is not None:
        e0 = jnp.where(n_valid >= 2, e0, jnp.zeros((), series.dtype))
    return (jtj.reshape(1, 1), jtr.reshape(1), sse + e0 * e0)


@_metrics.instrument_fit("ewma")
def fit(ts: jnp.ndarray, init: float = 0.94, tol: float = 1e-9,
        max_iter: Optional[int] = None, method: str = "lm",
        retry: Optional[_resilience.RetryPolicy] = None) -> EWMAModel:
    """Fit EWMA by minimizing one-step SSE over the smoothing parameter
    (ref ``EWMA.scala:45-69``; same 0.94 initial guess).

    ``method="lm"`` (default) runs batched Levenberg-Marquardt on the
    one-step residuals — float32-robust on TPU — with the result projected
    into the model domain [``SMOOTHING_FLOOR``, 1] (out-of-domain lanes are
    flagged non-converged); ``method="bfgs"``
    reproduces the reference's unbounded optimization whose result "should
    always be sanity checked", while ``method="box"`` constrains ``a`` to
    [1e-4, 1] — the formally correct domain.

    ``ts`` may be ``(n,)`` or ``(n_series, n)``; the returned model's
    ``smoothing`` is correspondingly scalar or ``(n_series,)``.  ``init``
    may be a per-lane ``(n_series,)`` array (e.g. a ``refit_unconverged``
    warm start from a previous fit's ``smoothing``).

    NaN-padded panels (leading/trailing padding per lane) fit directly:
    valid windows are left-aligned and the SSE weighted to them, matching
    independent fits of the trimmed series (``ops.ragged``).  Lanes with
    fewer than 3 valid observations get NaN smoothing and
    ``diagnostics.converged == False``; interior gaps raise.
    """
    ts = jnp.asarray(ts)
    ts, obs_len = ragged_view(ts)
    extra = () if obs_len is None else (obs_len,)
    rk = _resilience.retry_kwargs(retry)
    # explicit max_iter wins over the policy's per-attempt budget (the
    # arima/garch precedence); 200 is the historical default
    if max_iter is None:
        max_iter = retry.max_iter if retry is not None \
            and retry.max_iter is not None else 200

    def objective(params, series, *v):
        model = EWMAModel(params[0])
        if not v:
            return model.sse(series)
        # weighted SSE: residual e_t targets index t+1; live iff < n_valid
        smoothed = model.add_time_dependent_effects(series)
        err = series[1:] - smoothed[:-1]
        w = step_weights(err.shape[-1], v[0], offset=1, dtype=series.dtype)
        return jnp.sum(w * err * err)

    x0 = jnp.broadcast_to(jnp.asarray(init, ts.dtype)[..., None],
                          (*ts.shape[:-1], 1))
    if method == "lm":
        res = minimize_least_squares(
            None, x0, ts, *extra, tol=tol, max_iter=max_iter,
            normal_eqs_fn=lambda prm, y, *v: _ewma_normal_eqs(
                prm, y, n_valid=v[0] if v else None), **rk)
        # LM is unconstrained but the model domain is (0, 1]: a lane that
        # converges outside it (possible on near-random-walk data, where
        # the SSE is flat past a=1) would silently yield an oscillating,
        # divergent smoother from add_time_dependent_effects.  Project such
        # lanes back into the box and flag them non-converged so
        # refit_unconverged can retry them (e.g. with method="box").
        in_domain = jnp.all((res.x >= SMOOTHING_FLOOR) & (res.x <= 1.0),
                            axis=-1)
        res = res._replace(x=jnp.clip(res.x, SMOOTHING_FLOOR, 1.0),
                           converged=res.converged & in_domain)
    elif method == "box":
        res = minimize_box(objective, x0, 1e-4, 1.0, ts, *extra,
                           tol=tol, max_iter=max_iter, **rk)
    elif method == "bfgs":
        res = minimize_bfgs(objective, x0, ts, *extra, tol=tol,
                            max_iter=max_iter, **rk)
    else:
        raise ValueError(f"unknown method {method!r}")
    # per-lane quarantine: a diverged lane falls back to the initial guess
    # instead of emitting NaN smoothing (same policy as the ARIMA/GARCH fits)
    lane_ok = jnp.all(jnp.isfinite(res.x), axis=-1, keepdims=True)
    params = jnp.where(lane_ok, res.x, x0)
    conv = diagnostics_from(res, lane_ok)
    if obs_len is not None:
        short = short_lanes(obs_len, 3, "EWMA one-step SSE")
        params, conv_mask = apply_short_quarantine(params, conv.converged,
                                                   short)
        conv = conv._replace(converged=conv_mask)
    return EWMAModel(params[..., 0], diagnostics=conv)


@_metrics.instrument_fit("ewma", record=False)
def fit_panel(panel) -> EWMAModel:
    """Batched fit over a :class:`~spark_timeseries_tpu.panel.Panel` — the
    TPU equivalent of ``rdd.mapValues(EWMA.fitModel)``."""
    return fit(panel.values)


def _naive_model(v: jnp.ndarray) -> EWMAModel:
    """Terminal fallback: ``a = 1`` (the naive last-value smoother) —
    defined for any series with finite observations, including constants
    (a ragged lane's NaN-padding steps drop out of the nansum)."""
    sse = jnp.nansum((v[..., 1:] - v[..., :-1]) ** 2, axis=-1)
    m = EWMAModel(jnp.ones(v.shape[:-1], v.dtype))
    return m._replace(diagnostics=FitDiagnostics(
        jnp.isfinite(sse), jnp.zeros(sse.shape, jnp.int32), sse))


@_metrics.instrument_fit("ewma", record=False, name="ewma.fit_resilient")
def fit_resilient(ts: jnp.ndarray,
                  retry: Optional[_resilience.RetryPolicy] = None,
                  **kwargs):
    """Fail-soft batched EWMA: LM (with multi-start retry) → box-constrained
    solve → naive ``a = 1`` smoother.  ``ts (n_series, n)``; returns
    ``(model, FitOutcome)`` — see ``utils.resilience.resilient_fit``."""
    if retry is None:
        retry = _resilience.RetryPolicy()
    chain = [
        ("lm", lambda v: fit.__wrapped__(v, retry=retry, **kwargs)),
        ("box", lambda v: fit.__wrapped__(
            v, **_resilience.override_kwargs(kwargs, method="box"))),
        ("naive", _naive_model),
    ]
    return _resilience.resilient_fit(ts, chain, min_len=3, family="ewma")
