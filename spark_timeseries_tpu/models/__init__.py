"""Model tier (L5): batched classical time-series models.

Parity targets the reference's ``models/`` package
(``/root/reference/src/main/scala/com/cloudera/sparkts/models/``): ARIMA,
ARIMAX, AR, ARX, EWMA, GARCH/ARGARCH, Holt-Winters, RegressionARIMA — but
every fit is a batched XLA program over the panel instead of a per-series
Commons-Math loop.
"""

from ..utils.resilience import FitOutcome, RetryPolicy
from . import (arima, arimax, autoregression, autoregression_x, ewma, garch,
               holt_winters, regression_arima)
from .arima import ARIMAModel
from .arimax import ARIMAXModel
from .autoregression import ARModel
from .autoregression_x import ARXModel
from .base import FitDiagnostics, TimeSeriesModel, refit_unconverged
from .ewma import EWMAModel
from .garch import ARGARCHModel, EGARCHModel, GARCHModel
from .holt_winters import HoltWintersModel
from .regression_arima import RegressionARIMAModel

__all__ = ["TimeSeriesModel", "FitDiagnostics", "refit_unconverged",
           "FitOutcome", "RetryPolicy",
           "ewma", "EWMAModel",
           "autoregression", "ARModel",
           "autoregression_x", "ARXModel",
           "arima", "ARIMAModel", "arimax", "ARIMAXModel",
           "garch", "GARCHModel", "ARGARCHModel", "EGARCHModel",
           "holt_winters", "HoltWintersModel",
           "regression_arima", "RegressionARIMAModel"]
