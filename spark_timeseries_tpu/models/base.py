"""Model-tier base contract.

Mirrors the reference's ``TimeSeriesModel`` trait (ref
``/root/reference/src/main/scala/com/cloudera/sparkts/models/TimeSeriesModel.scala:23-45``)
— every model can add/remove its time-dependent effects — with two TPU-native
changes:

- models are **pytrees** (NamedTuples of jax arrays), so a fitted model flows
  through ``jit``/``vmap``/``pjit`` and serializes trivially;
- every model is **batched**: parameter fields may carry a leading
  ``(n_series,)`` dim, in which case the model IS the whole panel's fit and
  its methods operate on ``(n_series, n_obs)`` arrays in one XLA call.
  The reference's "one model object per series inside a mapValues closure"
  becomes "one pytree of stacked parameters".
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax.numpy as jnp


def scan_unroll() -> int:
    """Unroll factor for the model tier's time-axis ``lax.scan``s.

    The recurrences carry tiny per-step state (ring buffers, level/trend/
    season scalars), so on TPU the scans are latency-bound on the loop, not
    FLOPs; unrolling 8 steps per XLA while-iteration halves the ARIMA
    fit's fused residual+Jacobian pass at bench scale (4.1ms -> 2.0ms,
    32768x128 float32, v5e) and nearly triples the EWMA fit (298k -> 842k
    series/sec at 65536x128; 16 was measured *worse* there — 389k — the
    wider body spills).  On CPU (the test mesh) runtime is FLOP-bound and
    larger scan bodies only inflate compile time, so the factor stays 1.
    Evaluated lazily at trace time — importing the package must not
    initialize a JAX backend."""
    import jax
    return 8 if jax.default_backend() != "cpu" else 1


class FitDiagnostics(NamedTuple):
    """Per-lane optimizer outcome attached to every fitted model — the
    batched replacement for the reference's per-series ``println`` warnings
    and swallowed optimizer state (ref ``ARIMA.scala:246-256``).

    ``converged`` is False both for lanes whose optimizer hit its iteration
    cap and for lanes that were quarantined back to their initial guess
    (non-finite result); ``fun`` is the objective at the returned parameters.
    """
    converged: jnp.ndarray   # bool (...,)
    n_iter: jnp.ndarray      # (...,)
    fun: jnp.ndarray         # (...,)


def diagnostics_from(res, lane_ok=None) -> FitDiagnostics:
    """Build :class:`FitDiagnostics` from a ``MinimizeResult``; ``lane_ok``
    (the quarantine mask, True = kept the optimizer's result) demotes
    quarantined lanes to non-converged."""
    converged = jnp.asarray(res.converged)
    if lane_ok is not None:
        converged = converged & jnp.reshape(jnp.asarray(lane_ok),
                                            converged.shape)
    fun = jnp.asarray(res.fun)
    # a lane whose objective is non-finite (e.g. an all-NaN series) may
    # still trip the optimizer's "pinned" exit; it has not converged
    return FitDiagnostics(converged & jnp.isfinite(fun),
                          jnp.asarray(res.n_iter), fun)


class TimeSeriesModel:
    """Informal interface; concrete models are NamedTuple pytrees."""

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """i.i.d.-ify: strip this model's time-dependent structure.

        Inverse of :meth:`add_time_dependent_effects`
        (ref ``TimeSeriesModel.scala:24-33``)."""
        raise NotImplementedError

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Overlay this model's time-dependent structure on i.i.d. draws
        (ref ``TimeSeriesModel.scala:35-44``)."""
        raise NotImplementedError


def scalar_or_batch(x: Any) -> jnp.ndarray:
    """Canonicalize a parameter to a jax array (scalar or ``(batch,)``)."""
    return jnp.asarray(x)
