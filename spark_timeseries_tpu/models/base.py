"""Model-tier base contract.

Mirrors the reference's ``TimeSeriesModel`` trait (ref
``/root/reference/src/main/scala/com/cloudera/sparkts/models/TimeSeriesModel.scala:23-45``)
— every model can add/remove its time-dependent effects — with two TPU-native
changes:

- models are **pytrees** (NamedTuples of jax arrays), so a fitted model flows
  through ``jit``/``vmap``/``pjit`` and serializes trivially;
- every model is **batched**: parameter fields may carry a leading
  ``(n_series,)`` dim, in which case the model IS the whole panel's fit and
  its methods operate on ``(n_series, n_obs)`` arrays in one XLA call.
  The reference's "one model object per series inside a mapValues closure"
  becomes "one pytree of stacked parameters".
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, NamedTuple, Optional

import jax.numpy as jnp


def normal_quantile(conf, dtype) -> jnp.ndarray:
    """Two-sided standard-normal quantile: ``z`` with ``P(|Z| < z) = conf``
    (1.95996 at 0.95) — shared by every model's ``forecast_interval``."""
    from jax.scipy.special import erfinv
    return jnp.sqrt(jnp.asarray(2.0, dtype)) \
        * erfinv(jnp.asarray(conf, dtype))


def on_accelerator() -> bool:
    """True when the default backend is an accelerator (not CPU).  The one
    backend gate for passes that only win where scans are memory-bound —
    evaluated lazily so importing the package never initializes a
    backend."""
    import jax
    return jax.default_backend() != "cpu"


# CPU unroll only pays off when the traced bucket is wide enough to
# amortize the 4x-larger scan body's compile time: the engine's bench
# buckets (8192+ lanes) are loop-overhead-bound at runtime, while the
# test/interactive tier (8-256 lanes) is compile-bound — unrolling it
# would multiply suite compile time for nothing
UNROLL_LANES_MIN = 1024

_unroll_hint = threading.local()


@contextmanager
def unroll_hint(n_lanes: Optional[int]):
    """Trace-scoped lane-width hint for :func:`scan_unroll` — set by the
    ENGINE around lowering, because inside the ``vmap`` the batch width
    is not visible to the model code.  The hint is a pure function of
    the engine's padding bucket (which is part of both the engine's
    executable key and jax's aval-keyed jit cache), so a given shape
    always traces with the same unroll — no cache inconsistency."""
    prev = getattr(_unroll_hint, "n", None)
    _unroll_hint.n = None if n_lanes is None else int(n_lanes)
    try:
        yield
    finally:
        _unroll_hint.n = prev


def scan_unroll() -> int:
    """Unroll factor for the model tier's time-axis ``lax.scan``s.

    The recurrences carry tiny per-step state (ring buffers, level/trend/
    season scalars), so on TPU the scans are latency-bound on the loop, not
    FLOPs; unrolling 8 steps per XLA while-iteration halves the ARIMA
    fit's fused residual+Jacobian pass at bench scale (4.1ms -> 2.0ms,
    32768x128 float32, v5e) and nearly triples the EWMA fit (298k -> 842k
    series/sec at 65536x128; 16 was measured *worse* there — 389k — the
    wider body spills).  On CPU the scan body is a swarm of small
    vector ops over the lane axis, so runtime is loop-overhead-bound at
    bench width: unroll=4 lifts the 8192x128 ARIMA(2,1,2) css-lm chunk
    program 2332 -> 2904 series/s on the 1-core bench box (unroll=2:
    2640; unroll=8 *regresses* to 2290 — the wider body blows the
    cache).  But compile time scales with the unrolled body too, and
    the test/interactive tier is compile-bound — a global CPU unroll=4
    blew the tier-1 suite past its wall budget — so CPU unrolls ONLY
    when the enclosing trace carries a wide-bucket :func:`unroll_hint`
    (≥ ``UNROLL_LANES_MIN`` lanes; the engine sets it from its padding
    bucket).  Unrolling reorders XLA's fusion choices, so results are
    NOT bitwise against unroll=1 — both engine paths (staged and fused)
    trace through this one policy, which is what keeps the
    fused-vs-staged bitwise oracle intact.  Evaluated lazily at trace
    time — importing the package must not initialize a JAX backend.
    ``STS_SCAN_UNROLL`` overrides everything (tuning knob; re-jit after
    changing it — traces cache the value)."""
    import os
    env = os.environ.get("STS_SCAN_UNROLL")
    if env:
        try:
            val = int(env)
        except ValueError as e:
            raise ValueError(
                f"STS_SCAN_UNROLL must be a positive integer, got {env!r}"
            ) from e
        if val < 1:
            raise ValueError(
                f"STS_SCAN_UNROLL must be >= 1, got {env!r}")
        return val
    if on_accelerator():
        return 8
    hint = getattr(_unroll_hint, "n", None)
    return 4 if hint is not None and hint >= UNROLL_LANES_MIN else 1


class FitDiagnostics(NamedTuple):
    """Per-lane optimizer outcome attached to every fitted model — the
    batched replacement for the reference's per-series ``println`` warnings
    and swallowed optimizer state (ref ``ARIMA.scala:246-256``).

    ``converged`` is False both for lanes whose optimizer hit its iteration
    cap and for lanes that were quarantined back to their initial guess
    (non-finite result); ``fun`` is the objective at the returned parameters.
    ``attempts`` is the per-lane multi-start solve count when the fit ran
    with a retry policy (``utils.resilience.RetryPolicy``), else None.
    """
    converged: jnp.ndarray   # bool (...,)
    n_iter: jnp.ndarray      # (...,)
    fun: jnp.ndarray         # (...,)
    attempts: Optional[jnp.ndarray] = None   # (...,) multi-start solves


def diagnostics_from(res, lane_ok=None) -> FitDiagnostics:
    """Build :class:`FitDiagnostics` from a ``MinimizeResult``; ``lane_ok``
    (the quarantine mask, True = kept the optimizer's result) demotes
    quarantined lanes to non-converged."""
    converged = jnp.asarray(res.converged)
    if lane_ok is not None:
        converged = converged & jnp.reshape(jnp.asarray(lane_ok),
                                            converged.shape)
    fun = jnp.asarray(res.fun)
    # a lane whose objective is non-finite (e.g. an all-NaN series) may
    # still trip the optimizer's "pinned" exit; it has not converged
    return FitDiagnostics(converged & jnp.isfinite(fun),
                          jnp.asarray(res.n_iter), fun,
                          getattr(res, "attempts", None))


def refit_unconverged(values, model, fit_fn, min_bucket: int = 256):
    """Compact-and-refit the lanes of a batched fit that did not converge.

    The batched answer to heterogeneous convergence (SURVEY.md §7 hard part
    #3): under ``vmap`` every lane pays the slowest lane's iterations, so
    production fits cap the iteration budget (e.g. ``arima.fit``'s LM cap)
    and a tail of hard lanes — near-unit-root series, poor inits — reports
    ``diagnostics.converged == False``.  Instead of re-running the whole
    panel with a larger budget (reference analogue: the per-series ``Try``
    fallback re-fits, ``ARIMA.scala:315-319``), this gathers just those
    lanes into a small padded batch, re-fits them there, and scatters the
    results back.  Cost scales with the unconverged fraction, not the panel.

    ``values (n_series, n)`` is the data the model was fitted on; ``model``
    is any fitted model pytree whose ``diagnostics.converged`` has one entry
    per series.  ``fit_fn(sub_values, sub_model) -> sub_fitted`` re-fits the
    compacted subset — it receives the per-lane slice of the original model
    so it can warm-start, e.g.::

        model = arima.fit(2, 1, 2, values)                  # capped budget
        model = refit_unconverged(
            values, model,
            lambda v, m: arima.fit(2, 1, 2, v, max_iter=500,
                                   user_init_params=m.coefficients))

    The compacted batch is padded (repeating the first hard lane) up to a
    power-of-two size ``>= min_bucket`` so repeated refits compile a bounded
    set of shapes.  Lanes already converged are returned bit-identical.
    """
    import numpy as np

    if getattr(model, "diagnostics", None) is None:
        raise ValueError("model carries no diagnostics; fit it first")
    conv = np.asarray(model.diagnostics.converged)
    if conv.ndim == 0:
        # unbatched model: its leaves are scalars, so a scatter-merge has
        # nothing to index — re-run the fit directly instead
        raise ValueError(
            "model is unbatched (scalar diagnostics); refit_unconverged "
            "needs a batched fit — re-fit the single series directly")
    conv = conv.reshape(-1)
    n_series = conv.shape[0]
    values = jnp.asarray(values)
    if values.ndim < 2 or values.shape[0] != n_series:
        raise ValueError(
            f"values {values.shape} does not match the model's "
            f"{n_series} diagnosed lanes")
    idx = np.flatnonzero(~conv)
    if idx.size == 0:
        return model

    # never refit a batch larger than the panel itself (a tiny panel would
    # otherwise be padded up to min_bucket and cost MORE than a full re-fit)
    bucket = max(min_bucket, 1 << (int(idx.size) - 1).bit_length())
    if bucket > n_series:
        bucket = n_series
    pad_idx = idx if bucket == idx.size else np.concatenate(
        [idx, np.full(bucket - idx.size, idx[0], idx.dtype)])

    import jax

    def _is_array(leaf):
        # static leaves (ints like ARIMA's p/d/q, strings like Holt-Winters'
        # model_type) pass through untouched
        return isinstance(leaf, (jnp.ndarray, np.ndarray))

    def _slice(leaf):
        if not _is_array(leaf):
            return leaf
        arr = jnp.asarray(leaf)
        if arr.ndim >= 1 and arr.shape[0] == n_series:
            return arr[pad_idx]
        return leaf

    sub_fitted = fit_fn(values[pad_idx],
                        jax.tree_util.tree_map(_slice, model))

    k = idx.size

    def _merge(orig, new):
        if not _is_array(orig):
            return orig
        arr = jnp.asarray(orig)
        if arr.ndim >= 1 and arr.shape[0] == n_series:
            return arr.at[idx].set(
                jnp.asarray(new)[:k].astype(arr.dtype))
        return orig

    return jax.tree_util.tree_map(_merge, model, sub_fitted)


class TimeSeriesModel:
    """Informal interface; concrete models are NamedTuple pytrees."""

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """i.i.d.-ify: strip this model's time-dependent structure.

        Inverse of :meth:`add_time_dependent_effects`
        (ref ``TimeSeriesModel.scala:24-33``)."""
        raise NotImplementedError

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Overlay this model's time-dependent structure on i.i.d. draws
        (ref ``TimeSeriesModel.scala:35-44``)."""
        raise NotImplementedError


def scalar_or_batch(x: Any) -> jnp.ndarray:
    """Canonicalize a parameter to a jax array (scalar or ``(batch,)``)."""
    return jnp.asarray(x)
