"""GARCH(1,1) and AR(1)+GARCH(1,1) volatility models, batched.

Capability parity with the reference's ``GARCH`` / ``ARGARCH`` / ``EGARCH``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/GARCH.scala:26-283``):
Bollerslev GARCH(1,1) conditional-variance recurrence
``h_i = omega + alpha·eta_{i-1}² + beta·h_{i-1}`` with
``h_0 = omega / (1 - alpha - beta)``, maximum-likelihood fitting from the
reference's (.2, .2, .2) initial guess, standardize/filter transforms,
sampling, and the two-stage AR(1)+GARCH fit.

TPU-native design: every recurrence is a ``lax.scan`` whose carry broadcasts
over the batch, so one compiled program evaluates the whole panel; the
gradient comes from autodiff through the scan (the reference hand-derives it
— and returns it permuted relative to its parameter vector,
``GARCH.scala:96-115`` returns (alpha, beta, omega) order for (omega, alpha,
beta) params; autodiff is both simpler and actually consistent).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.optimize import minimize_bfgs, minimize_box, minimize_newton
from ..ops.ragged import ragged_view, step_weights
from . import autoregression
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from .base import FitDiagnostics, diagnostics_from, scan_unroll


def _move(ts):
    return jnp.moveaxis(jnp.asarray(ts), -1, 0)


def _packed_gradient(ctor, params, ts):
    """Log-likelihood gradient w.r.t. a packed parameter vector, vmapped
    over the broadcast of the parameter batch dims and ``ts``'s leading dims
    (scalar params with a batched ts must still vmap over the series).
    ``ctor(packed (..., k)) -> model``; returns ``(..., k)``."""
    ts = jnp.asarray(ts)
    packed = jnp.stack(jnp.broadcast_arrays(*params), axis=-1)
    batch = jnp.broadcast_shapes(packed.shape[:-1], ts.shape[:-1])
    packed = jnp.broadcast_to(packed, (*batch, packed.shape[-1]))
    ts = jnp.broadcast_to(ts, (*batch, ts.shape[-1]))

    def ll(prm, series):
        return ctor(prm).log_likelihood(series)

    g = jax.grad(ll)
    for _ in range(len(batch)):
        g = jax.vmap(g)
    return g(packed, ts)


class GARCHModel(NamedTuple):
    """GARCH(1,1) parameters; each scalar or ``(n_series,)``
    (ref ``GARCH.scala:73-76``)."""
    omega: jnp.ndarray
    alpha: jnp.ndarray
    beta: jnp.ndarray
    diagnostics: Optional[FitDiagnostics] = None

    @property
    def _params(self):
        return (jnp.asarray(self.omega), jnp.asarray(self.alpha),
                jnp.asarray(self.beta))

    def _h0(self):
        w, a, b = self._params
        return w / (1.0 - a - b)

    def log_likelihood(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Gaussian log likelihood under the variance recurrence
        (ref ``GARCH.scala:82-88``; Bollerslev 1986).  ``ts (..., n)`` →
        ``(...)``.

        The variance path is affine in ``h`` with *known* driving terms
        (the observed squared residuals), so it is evaluated by an
        associative scan in O(log n) depth rather than a sequential scan —
        the whole likelihood (and its autodiff gradient) parallelizes over
        time, which is what makes batched fitting fast on long series.
        """
        ts = jnp.asarray(ts)
        n = ts.shape[-1]
        from ..ops.scan_parallel import garch_variance
        h = garch_variance(ts, *self._params)           # (..., n); h[0] = h0
        x = ts[..., 1:]
        hh = h[..., 1:]
        lls = -0.5 * jnp.log(hh) - 0.5 * x * x / hh
        return jnp.sum(lls, axis=-1) - 0.5 * jnp.log(2.0 * jnp.pi) * (n - 1)

    def forecast_variance(self, ts: jnp.ndarray,
                          n_future: int) -> jnp.ndarray:
        """k-step-ahead conditional variance forecasts for k = 1..n_future
        — beyond reference (``GARCH.scala`` has no forecast surface).

        Textbook GARCH(1,1) term structure: with persistence ``κ = α+β``
        and unconditional variance ``σ² = ω/(1-κ)``,
        ``E[h_{t+k} | t] = σ² + κ^{k-1}(h_{t+1} - σ²)`` where ``h_{t+1} =
        ω + α x_t² + β h_t`` comes from the filtered variance path (the
        same associative scan as the likelihood).  Forecasts revert
        geometrically to σ²; an IGARCH lane (κ = 1, RiskMetrics-style)
        takes its limit form ``h_{t+1} + k·ω`` (linear growth), and an
        explosive lane (κ > 1) diverges at its own rate rather than being
        clipped.  ``ts (..., n)`` → ``(..., n_future)``.
        """
        if n_future < 1:
            raise ValueError("forecast_variance needs n_future >= 1")
        ts = jnp.asarray(ts)
        from ..ops.scan_parallel import garch_variance
        w, a, b = self._params
        kappa = a + b
        # the stationary fixed point does not exist at κ = 1 (IGARCH /
        # RiskMetrics): seed the filtered path with the sample variance
        # there, and replace the geometric-reversion form (inf - inf =
        # NaN) with its κ→1 limit, linear growth h_{t+1} + k·ω
        unit = jnp.isclose(kappa, 1.0)
        seed = jnp.where(unit, jnp.mean(ts * ts, axis=-1),
                         w / jnp.where(unit, jnp.ones_like(kappa),
                                       1.0 - kappa))
        h = garch_variance(ts, w, a, b, h0=seed)
        h_next = w + a * ts[..., -1] ** 2 + b * h[..., -1]
        k = jnp.arange(n_future)
        sigma2 = w / jnp.where(unit, jnp.ones_like(kappa), 1.0 - kappa)
        geo = sigma2[..., None] \
            + kappa[..., None] ** k * (h_next - sigma2)[..., None]
        lin = h_next[..., None] + w[..., None] * k
        return jnp.where(unit[..., None], lin, geo)

    def gradient(self, ts: jnp.ndarray) -> jnp.ndarray:
        """d log-likelihood / d(omega, alpha, beta) via autodiff through the
        scan — replaces the reference's hand recursion (``GARCH.scala:96-115``)
        and fixes its permuted output ordering.  Returns ``(..., 3)``."""
        return _packed_gradient(
            lambda prm: GARCHModel(prm[..., 0], prm[..., 1], prm[..., 2]),
            self._params, ts)

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Standardize: divide each observation by its conditional volatility
        (ref ``GARCH.scala:131-146``)."""
        w, a, b = self._params
        xs = _move(ts)

        def step(carry, eta):
            prev_eta, prev_var = carry
            var = w + a * prev_eta * prev_eta + b * prev_var
            return (eta, var), eta / jnp.sqrt(var)

        var0 = jnp.broadcast_to(self._h0(), xs.shape[1:])
        out0 = xs[0] / jnp.sqrt(var0)
        _, rest = lax.scan(step, (xs[0], var0), xs[1:], unroll=scan_unroll())
        return jnp.moveaxis(jnp.concatenate([out0[None], rest]), 0, -1)

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Filter: scale standardized draws by the conditional volatility
        (ref ``GARCH.scala:148-163``)."""
        w, a, b = self._params
        xs = _move(ts)

        def step(carry, z):
            prev_eta, prev_var = carry
            var = w + a * prev_eta * prev_eta + b * prev_var
            eta = z * jnp.sqrt(var)
            return (eta, var), eta

        var0 = jnp.broadcast_to(self._h0(), xs.shape[1:])
        eta0 = xs[0] * jnp.sqrt(var0)
        _, rest = lax.scan(step, (eta0, var0), xs[1:], unroll=scan_unroll())
        return jnp.moveaxis(jnp.concatenate([eta0[None], rest]), 0, -1)

    def sample_with_variances(self, n: int, key,
                              shape=()) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(ref ``GARCH.scala:165-177``; like the reference, index 0 of the
        sample stays 0 — only its variance seeds the recurrence)."""
        w, a, b = self._params
        # draws in the parameters' dtype: float32 params under jax_enable_x64
        # would otherwise mix f32/f64 in the scan carry and fail to trace
        z = jax.random.normal(key, (n, *shape), dtype=jnp.asarray(w).dtype)
        var0 = jnp.broadcast_to(self._h0(), z.shape[1:])

        def step(carry, z_i):
            prev_eta, prev_var = carry
            var = w + b * prev_var + a * prev_eta * prev_eta
            eta = jnp.sqrt(var) * z_i
            return (eta, var), (eta, var)

        eta0 = jnp.sqrt(var0) * z[0]
        _, (etas, variances) = lax.scan(step, (eta0, var0), z[1:],
                                        unroll=scan_unroll())
        ts = jnp.concatenate([jnp.zeros_like(var0)[None], etas])
        variances = jnp.concatenate([var0[None], variances])
        return jnp.moveaxis(ts, 0, -1), jnp.moveaxis(variances, 0, -1)

    def sample(self, n: int, key, shape=()) -> jnp.ndarray:
        return self.sample_with_variances(n, key, shape)[0]


def _unconstrain(omega, alpha, beta):
    """(omega, alpha, beta) -> unconstrained (u, s, r): omega = exp(u),
    alpha + beta = sigmoid(s), alpha/(alpha+beta) = sigmoid(r)."""
    total = alpha + beta
    return (jnp.log(omega), jax.scipy.special.logit(total),
            jax.scipy.special.logit(alpha / total))


def _constrain(params):
    u, s, r = params[..., 0], params[..., 1], params[..., 2]
    omega = jnp.exp(u)
    total = jax.nn.sigmoid(s)
    frac = jax.nn.sigmoid(r)
    return omega, total * frac, total * (1.0 - frac)


@_metrics.instrument_fit("garch")
def fit(ts: jnp.ndarray, init=(0.2, 0.2, 0.2), tol: float = 1e-6,
        max_iter: Optional[int] = None,
        method: str = "newton",
        retry: Optional[_resilience.RetryPolicy] = None) -> GARCHModel:
    """Fit GARCH(1,1) by maximum likelihood (ref ``GARCH.scala:33-53``; same
    (.2, .2, .2) initial guess).

    The reference runs unconstrained CGD directly on (omega, alpha, beta) and
    relies on the iterates staying inside the stationarity region
    ``omega > 0, alpha + beta < 1`` (outside it ``h_0`` goes negative and the
    likelihood is NaN).  Batched solves can't afford per-lane luck, so the
    solve here runs in an unconstrained reparameterization of that region —
    ``omega = exp(u)``, ``alpha + beta = sigmoid(s)``,
    ``alpha = sigmoid(r)·(alpha+beta)`` — where the likelihood is smooth
    everywhere; results are mapped back.

    ``method="newton"`` (default): batched damped Newton on the 3x3
    autodiff Hessian — quadratic convergence, ~10-30 iterations, and it
    reaches optima the vmapped-BFGS line search sometimes gives up short of.
    ``method="bfgs"`` keeps the previous solver.

    ``max_iter`` defaults per method (100 for Newton, 500 for BFGS — the
    previous solver keeps its previous budget).

    ``ts (..., n)``; leading dims fit in one batched solve.
    """
    ts = jnp.asarray(ts)

    def neg_ll(params, series):
        omega, alpha, beta = _constrain(params)
        return -GARCHModel(omega, alpha, beta).log_likelihood(series)

    o0, a0, b0 = (jnp.asarray(v, ts.dtype) for v in init)
    x0 = jnp.broadcast_to(jnp.stack(_unconstrain(o0, a0, b0), axis=-1),
                          (*ts.shape[:-1], 3))
    rk = _resilience.retry_kwargs(retry)
    if max_iter is None and retry is not None:
        max_iter = retry.max_iter
    if method == "newton":
        res = minimize_newton(neg_ll, x0, ts, tol=tol,
                              max_iter=100 if max_iter is None else max_iter,
                              **rk)
    elif method == "bfgs":
        res = minimize_bfgs(neg_ll, x0, ts, tol=tol,
                            max_iter=500 if max_iter is None else max_iter,
                            **rk)
    else:
        raise ValueError(f"unknown method {method!r}")
    ok = jnp.all(jnp.isfinite(res.x), axis=-1, keepdims=True)
    params = jnp.where(ok, res.x, x0)
    return GARCHModel(*_constrain(params),
                      diagnostics=diagnostics_from(res, ok))


@_metrics.instrument_fit("garch", record=False)
def fit_panel(panel) -> GARCHModel:
    """Batched fit over a Panel — ``rdd.mapValues(GARCH.fitModel)``."""
    return fit(panel.values)


def _const_gaussian_neg_ll(v: jnp.ndarray, var: jnp.ndarray) -> jnp.ndarray:
    """Constant-variance Gaussian negative log likelihood over the observed
    (non-NaN) entries, in closed form — ragged lanes' padding drops out of
    the nansum instead of poisoning the diagnostics."""
    n_valid = jnp.sum(~jnp.isnan(v), axis=-1).astype(v.dtype)
    return 0.5 * (jnp.nansum(v * v, axis=-1) / var
                  + n_valid * (jnp.log(var) + jnp.log(2.0 * jnp.pi)))


def _const_variance_model(v: jnp.ndarray) -> GARCHModel:
    """Terminal fallback: constant conditional variance (α = β = 0,
    ω = sample variance) — the volatility-model analogue of a mean fit;
    NaN padding on ragged lanes is ignored."""
    var = jnp.clip(jnp.nanvar(v, axis=-1), 1e-12, None)
    zeros = jnp.zeros_like(var)
    m = GARCHModel(var, zeros, zeros)
    neg_ll = _const_gaussian_neg_ll(v, var)
    return m._replace(diagnostics=FitDiagnostics(
        jnp.isfinite(neg_ll), jnp.zeros(neg_ll.shape, jnp.int32), neg_ll))


@_metrics.instrument_fit("garch", record=False, name="garch.fit_resilient")
def fit_resilient(ts: jnp.ndarray,
                  retry: Optional[_resilience.RetryPolicy] = None,
                  **kwargs):
    """Fail-soft batched GARCH(1,1): Newton (with multi-start retry) →
    BFGS → constant-variance model.  ``ts (n_series, n)``; returns
    ``(model, FitOutcome)`` — see ``utils.resilience.resilient_fit``."""
    if retry is None:
        retry = _resilience.RetryPolicy()
    chain = [
        ("newton", lambda v: fit.__wrapped__(v, retry=retry, **kwargs)),
        ("bfgs", lambda v: fit.__wrapped__(
            v, **_resilience.override_kwargs(kwargs, method="bfgs"))),
        ("const", _const_variance_model),
    ]
    return _resilience.resilient_fit(ts, chain, min_len=3, family="garch")


class ARGARCHModel(NamedTuple):
    """AR(1) + GARCH(1,1): ``y_i = c + phi·y_{i-1} + eta_i`` with GARCH
    variance on ``eta`` (ref ``GARCH.scala:188-198``)."""
    c: jnp.ndarray
    phi: jnp.ndarray
    omega: jnp.ndarray
    alpha: jnp.ndarray
    beta: jnp.ndarray
    diagnostics: Optional[FitDiagnostics] = None

    def _h0(self):
        return jnp.asarray(self.omega) / \
            (1.0 - jnp.asarray(self.alpha) - jnp.asarray(self.beta))

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """(ref ``GARCH.scala:200-215``)."""
        c, phi = jnp.asarray(self.c), jnp.asarray(self.phi)
        w, a, b = (jnp.asarray(self.omega), jnp.asarray(self.alpha),
                   jnp.asarray(self.beta))
        xs = _move(ts)

        def step(carry, inp):
            prev_eta, prev_var = carry
            y_prev, y_cur = inp
            var = w + a * prev_eta * prev_eta + b * prev_var
            eta = y_cur - c - phi * y_prev
            return (eta, var), eta / jnp.sqrt(var)

        var0 = jnp.broadcast_to(self._h0(), xs.shape[1:])
        eta0 = xs[0] - c
        out0 = eta0 / jnp.sqrt(var0)
        _, rest = lax.scan(step, (eta0, var0), (xs[:-1], xs[1:]),
                           unroll=scan_unroll())
        return jnp.moveaxis(jnp.concatenate([out0[None], rest]), 0, -1)

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """(ref ``GARCH.scala:217-233``) — the AR feedback reads the
        *output* series, so it rides in the scan carry."""
        c, phi = jnp.asarray(self.c), jnp.asarray(self.phi)
        w, a, b = (jnp.asarray(self.omega), jnp.asarray(self.alpha),
                   jnp.asarray(self.beta))
        xs = _move(ts)

        def step(carry, z):
            prev_eta, prev_var, prev_out = carry
            var = w + a * prev_eta * prev_eta + b * prev_var
            eta = z * jnp.sqrt(var)
            out = c + phi * prev_out + eta
            return (eta, var, out), out

        var0 = jnp.broadcast_to(self._h0(), xs.shape[1:])
        eta0 = xs[0] * jnp.sqrt(var0)
        out0 = c + eta0
        _, rest = lax.scan(step, (eta0, var0, out0), xs[1:],
                           unroll=scan_unroll())
        return jnp.moveaxis(jnp.concatenate([out0[None], rest]), 0, -1)

    def sample_with_variances(self, n: int, key,
                              shape=()) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(ref ``GARCH.scala:235-247``; index 0 stays 0 as in the
        reference)."""
        c, phi = jnp.asarray(self.c), jnp.asarray(self.phi)
        w, a, b = (jnp.asarray(self.omega), jnp.asarray(self.alpha),
                   jnp.asarray(self.beta))
        z = jax.random.normal(key, (n, *shape), dtype=w.dtype)
        var0 = jnp.broadcast_to(self._h0(), z.shape[1:])

        def step(carry, z_i):
            prev_eta, prev_var, prev_y = carry
            var = w + b * prev_var + a * prev_eta * prev_eta
            eta = jnp.sqrt(var) * z_i
            y = c + phi * prev_y + eta
            return (eta, var, y), (y, var)

        eta0 = jnp.sqrt(var0) * z[0]
        y0 = jnp.zeros_like(var0)
        _, (ys, variances) = lax.scan(step, (eta0, var0, y0), z[1:],
                                      unroll=scan_unroll())
        ts = jnp.concatenate([y0[None], ys])
        variances = jnp.concatenate([var0[None], variances])
        return jnp.moveaxis(ts, 0, -1), jnp.moveaxis(variances, 0, -1)

    def sample(self, n: int, key, shape=()) -> jnp.ndarray:
        return self.sample_with_variances(n, key, shape)[0]


@_metrics.instrument_fit("argarch")
def fit_ar_garch(ts: jnp.ndarray,
                 retry: Optional[_resilience.RetryPolicy] = None
                 ) -> ARGARCHModel:
    """Two-stage AR(1)+GARCH(1,1) fit (ref ``GARCH.scala:63-69``): AR(1) by
    OLS, then GARCH(1,1) on the residuals.  Batched over leading dims."""
    ts = jnp.asarray(ts)
    # stage fits are machinery of THIS fit: record only the argarch bundle
    ar = autoregression.fit.__wrapped__(ts, 1)
    residuals = ar.remove_time_dependent_effects(ts)
    g = fit.__wrapped__(residuals, retry=retry)
    return ARGARCHModel(ar.c, jnp.asarray(ar.coefficients)[..., 0],
                        g.omega, g.alpha, g.beta,
                        diagnostics=g.diagnostics)


@_metrics.instrument_fit("argarch", record=False)
def fit_ar_garch_panel(panel) -> ARGARCHModel:
    return fit_ar_garch(panel.values)


def _const_variance_ar_model(v: jnp.ndarray) -> ARGARCHModel:
    """Terminal AR(1)+GARCH fallback: AR(1) by OLS with constant residual
    variance (α = β = 0).  Ragged lanes fit on their valid window like the
    primary fits (``ops.ragged`` left-alignment + weighted moments), and a
    lane whose AR solve is degenerate (e.g. a constant series, whose lag
    regressor is collinear with the intercept) demotes per-lane to the
    mean model (φ = 0) instead of failing the stage."""
    aligned, nv = ragged_view(v)
    if nv is None:
        w = jnp.ones(aligned.shape, v.dtype)
        n_val = jnp.full(aligned.shape[:-1], aligned.shape[-1], v.dtype)
    else:
        w = step_weights(aligned.shape[-1], jnp.asarray(nv)[..., None],
                         dtype=v.dtype)
        n_val = jnp.maximum(jnp.asarray(nv).astype(v.dtype), 1.0)
    ar = autoregression.fit.__wrapped__(aligned, 1, n_valid=nv)
    c = jnp.asarray(ar.c)
    phi = jnp.asarray(ar.coefficients)[..., 0]
    mean_v = jnp.sum(w * aligned, axis=-1) / n_val
    ar_ok = jnp.isfinite(c) & jnp.isfinite(phi)
    c = jnp.where(ar_ok, c, mean_v)
    phi = jnp.where(ar_ok, phi, 0.0)
    resid = autoregression.ARModel(c, phi[..., None]) \
        .remove_time_dependent_effects(aligned)
    mean_r = jnp.sum(w * resid, axis=-1) / n_val
    var = jnp.sum(w * (resid - mean_r[..., None]) ** 2, axis=-1) / n_val
    var = jnp.clip(var, 1e-12, None)
    zeros = jnp.zeros_like(var)
    ok = jnp.isfinite(var) & jnp.isfinite(phi) & jnp.isfinite(c)
    return ARGARCHModel(c, phi, var, zeros, zeros,
                        diagnostics=FitDiagnostics(
                            ok, jnp.zeros(ok.shape, jnp.int32),
                            jnp.where(ok, var, jnp.nan)))


@_metrics.instrument_fit("argarch", record=False,
                         name="argarch.fit_resilient")
def fit_ar_garch_resilient(ts: jnp.ndarray,
                           retry: Optional[_resilience.RetryPolicy] = None):
    """Fail-soft batched AR(1)+GARCH(1,1): two-stage fit (with multi-start
    retry on the GARCH stage) → AR(1) with constant residual variance.
    ``ts (n_series, n)``; returns ``(model, FitOutcome)``."""
    if retry is None:
        retry = _resilience.RetryPolicy()
    chain = [
        ("argarch", lambda v: fit_ar_garch.__wrapped__(v, retry=retry)),
        ("ar_const", _const_variance_ar_model),
    ]
    return _resilience.resilient_fit(ts, chain, min_len=3, family="argarch")


_EGARCH_KAPPA = 0.7978845608028654     # E|z| = sqrt(2/pi) for Gaussian z


class EGARCHModel(NamedTuple):
    """Nelson (1991) EGARCH(1,1).  The reference *declares* this model but
    leaves every method ``UnsupportedOperationException``
    (ref ``GARCH.scala:262-283``, citing an EGARCH working paper); here it
    is implemented in full as a beyond-reference capability.

    Log-variance recurrence (z are standardized residuals)::

        log h_t = omega + beta * log h_{t-1}
                  + alpha * (|z_{t-1}| - sqrt(2/pi)) + gamma * z_{t-1}
        z_t     = eta_t / sqrt(h_t),    log h_0 = omega / (1 - beta)

    ``gamma`` is the leverage/asymmetry term; the reference's stub carries
    only (omega, alpha, beta), so ``gamma`` defaults to 0 and the stub's
    constructor surface is a strict subset.  Parameters are scalars or
    ``(n_series,)`` for a batched panel fit.
    """
    omega: jnp.ndarray
    alpha: jnp.ndarray
    beta: jnp.ndarray
    gamma: jnp.ndarray = 0.0
    diagnostics: Optional[FitDiagnostics] = None

    @property
    def _params(self):
        return (jnp.asarray(self.omega), jnp.asarray(self.alpha),
                jnp.asarray(self.beta), jnp.asarray(self.gamma))

    def _log_h0(self):
        w, _, b, _ = self._params
        return w / (1.0 - b)

    def variances(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Conditional-variance path ``h`` aligned with ``ts`` (``h[0]`` is
        the stationary seed).  ``z_{t-1}`` reads the *observed* residuals
        scaled by the evolving variance, so the recurrence is inherently
        sequential — a ``lax.scan`` over time with the batch riding
        elementwise (unlike GARCH's variance, which is affine in ``h`` and
        evaluates by associative scan)."""
        w, a, b, g = self._params
        xs = _move(ts)
        logh0 = jnp.broadcast_to(self._log_h0(), xs.shape[1:])

        def step(logh_prev, eta_prev):
            z = eta_prev * jnp.exp(-0.5 * logh_prev)
            logh = w + b * logh_prev \
                + a * (jnp.abs(z) - _EGARCH_KAPPA) + g * z
            return logh, logh

        _, rest = lax.scan(step, logh0, xs[:-1], unroll=scan_unroll())
        logh = jnp.concatenate([logh0[None], rest])
        return jnp.moveaxis(jnp.exp(logh), 0, -1)

    def log_likelihood(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Gaussian log likelihood under the log-variance recurrence
        (same ``t >= 1`` window as :meth:`GARCHModel.log_likelihood`)."""
        ts = jnp.asarray(ts)
        n = ts.shape[-1]
        h = self.variances(ts)
        x, hh = ts[..., 1:], h[..., 1:]
        lls = -0.5 * jnp.log(hh) - 0.5 * x * x / hh
        return jnp.sum(lls, axis=-1) - 0.5 * jnp.log(2.0 * jnp.pi) * (n - 1)

    def gradient(self, ts: jnp.ndarray) -> jnp.ndarray:
        """d log-likelihood / d(omega, alpha, beta, gamma) via autodiff
        through the scan.  Returns ``(..., 4)``."""
        return _packed_gradient(
            lambda prm: EGARCHModel(prm[..., 0], prm[..., 1], prm[..., 2],
                                    prm[..., 3]),
            self._params, ts)

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Standardize: ``z_t = eta_t / sqrt(h_t)``."""
        return jnp.asarray(ts) / jnp.sqrt(self.variances(ts))

    def _filter_with_log_variances(self, z: jnp.ndarray):
        """Filter standardized draws; returns ``(eta, log h)``.  The driving
        terms are the *input* z's (known up front), so ``log h`` is affine
        in itself and evaluates by associative scan
        (:func:`~spark_timeseries_tpu.ops.scan_parallel.linear_recurrence`)
        — O(log n) depth, time-shardable."""
        from ..ops.scan_parallel import linear_recurrence
        z = jnp.asarray(z)
        w, a, b, g = (p[..., None] if p.ndim and z.ndim > 1 else p
                      for p in self._params)
        drive = w + a * (jnp.abs(z[..., :-1]) - _EGARCH_KAPPA) \
            + g * z[..., :-1]
        logh0 = jnp.broadcast_to(w / (1.0 - b), z[..., :1].shape)
        coef = jnp.concatenate(
            [jnp.zeros_like(logh0),
             jnp.broadcast_to(b, drive.shape)], axis=-1)
        off = jnp.concatenate([logh0, drive], axis=-1)
        logh = linear_recurrence(coef, off, axis=-1)
        return z * jnp.exp(0.5 * logh), logh

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Filter: scale standardized draws by the conditional volatility
        (associative scan — see :meth:`_filter_with_log_variances`)."""
        return self._filter_with_log_variances(ts)[0]

    def sample_with_variances(self, n: int, key,
                              shape=()) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Gaussian draws pushed through the filter; returns (ts, h) from
        the single associative-scan pass."""
        z = jax.random.normal(key, (*shape, n),
                              dtype=jnp.asarray(self.omega).dtype)
        ts, logh = self._filter_with_log_variances(z)
        return ts, jnp.exp(logh)

    def sample(self, n: int, key, shape=()) -> jnp.ndarray:
        return self.sample_with_variances(n, key, shape)[0]


def _eg_constrain(params):
    """Unconstrained (w, a, s, g) -> (omega, alpha, beta, gamma) with the
    stationarity constraint |beta| < 1 enforced by tanh."""
    return (params[..., 0], params[..., 1], jnp.tanh(params[..., 2]),
            params[..., 3])


@_metrics.instrument_fit("egarch")
def fit_egarch(ts: jnp.ndarray, init=(0.2, 0.9, 0.0),
               tol: Optional[float] = None, max_iter: Optional[int] = None,
               method: str = "newton",
               retry: Optional[_resilience.RetryPolicy] = None
               ) -> EGARCHModel:
    """Fit EGARCH(1,1) by maximum likelihood, batched over leading dims.

    ``init = (alpha0, beta0, gamma0)``; ``omega0`` is implied by matching
    the stationary log variance to the sample ``log var(ts)``.  ``beta`` is
    optimized through ``tanh`` so every iterate keeps ``|beta| < 1`` (the
    log-variance form needs no positivity constraints — that is EGARCH's
    selling point, and what makes the batched solve well-behaved).

    ``method="newton"`` (default): batched damped Newton on the 4x4
    autodiff Hessian (~10-30 iterations).  ``method="descent"``: batched
    Armijo-backtracking descent — the robust first-order fallback, needing
    on the order of hundreds of iterations.  Raw BFGS is not offered: the
    likelihood's gradient is badly scaled at the variance-matched start
    (∂/∂gamma is ~10x ∂/∂beta) and its first line search fails outright.
    Both solvers reach the same optimum as a derivative-free scalar oracle
    (see ``tests/test_garch.py::test_egarch_fit_matches_independent_scalar_mle``
    and ``test_egarch_descent_matches_newton``).

    ``tol`` and ``max_iter`` default per method and dtype (Newton: the
    solver's dtype-aware tolerance — 1e-6 in float32, where a 1e-12
    relative-drop test would be unreachable — and 200 iterations; descent:
    1e-12 and 1000 iterations); explicit values are honored as given.
    """
    ts = jnp.asarray(ts)

    def neg_ll(params, series):
        w, a, b, g = _eg_constrain(params)
        return -EGARCHModel(w, a, b, g).log_likelihood(series)

    a0, b0, g0 = (jnp.asarray(v, ts.dtype) for v in init)
    logvar = jnp.log(jnp.clip(jnp.var(ts, axis=-1), 1e-12, None))
    w0 = (1.0 - b0) * logvar
    x0 = jnp.stack(jnp.broadcast_arrays(
        w0, a0, jnp.arctanh(b0), g0), axis=-1).astype(ts.dtype)
    rk = _resilience.retry_kwargs(retry)
    if max_iter is None and retry is not None:
        max_iter = retry.max_iter
    if method == "newton":
        res = minimize_newton(neg_ll, x0, ts, tol=tol,
                              max_iter=200 if max_iter is None else max_iter,
                              **rk)
    elif method == "descent":
        res = minimize_box(neg_ll, x0, -jnp.inf, jnp.inf, ts,
                           tol=1e-12 if tol is None else tol,
                           max_iter=1000 if max_iter is None else max_iter,
                           **rk)
    else:
        raise ValueError(f"unknown method {method!r}")
    ok = jnp.all(jnp.isfinite(res.x), axis=-1, keepdims=True)
    params = jnp.where(ok, res.x, x0)
    return EGARCHModel(*_eg_constrain(params),
                       diagnostics=diagnostics_from(res, ok))


@_metrics.instrument_fit("egarch", record=False)
def fit_egarch_panel(panel) -> EGARCHModel:
    """Batched EGARCH fit over a Panel."""
    return fit_egarch(panel.values)


def _const_log_variance_model(v: jnp.ndarray) -> EGARCHModel:
    """Terminal EGARCH fallback: constant log variance matched to the
    sample variance (α = β = γ = 0); NaN padding on ragged lanes is
    ignored."""
    var = jnp.clip(jnp.nanvar(v, axis=-1), 1e-12, None)
    w = jnp.log(var)
    zeros = jnp.zeros_like(w)
    m = EGARCHModel(w, zeros, zeros, zeros)
    neg_ll = _const_gaussian_neg_ll(v, var)
    return m._replace(diagnostics=FitDiagnostics(
        jnp.isfinite(neg_ll), jnp.zeros(neg_ll.shape, jnp.int32), neg_ll))


@_metrics.instrument_fit("egarch", record=False, name="egarch.fit_resilient")
def fit_egarch_resilient(ts: jnp.ndarray,
                         retry: Optional[_resilience.RetryPolicy] = None,
                         **kwargs):
    """Fail-soft batched EGARCH(1,1): Newton (with multi-start retry) →
    Armijo descent → constant-log-variance model.  ``ts (n_series, n)``;
    returns ``(model, FitOutcome)``."""
    if retry is None:
        retry = _resilience.RetryPolicy()
    chain = [
        ("newton", lambda v: fit_egarch.__wrapped__(v, retry=retry,
                                                    **kwargs)),
        ("descent", lambda v: fit_egarch.__wrapped__(
            v, **_resilience.override_kwargs(kwargs, method="descent"))),
        ("const", _const_log_variance_model),
    ]
    return _resilience.resilient_fit(ts, chain, min_len=3, family="egarch")
