"""Holt-Winters triple exponential smoothing, batched.

Capability parity with the reference's ``HoltWinters``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/HoltWinters.scala:41-325``):
additive and multiplicative seasonality, R ``stats::HoltWinters``-style
components recurrence, initialization by 2-period convolution decomposition
plus linear regression, SSE objective over t >= period, and level+trend+season
forecasting (with R's extra trend weight).

TPU-native design: the level/trend/season recurrence is one ``lax.scan``
whose carry is ``(level, trend, season ring buffer)`` broadcasting over the
panel; the derivative-free bounded BOBYQA fit (ref ``HoltWinters.scala:66-83``)
becomes a batched projected-gradient solve on [0, 1]³ with autodiff through
the scan.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.lag import lag_matrix
from ..ops.optimize import MinimizeResult, minimize_box
from ..ops.ragged import (apply_short_quarantine, ragged_view, short_lanes,
                          step_weights)
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from .base import (FitDiagnostics, diagnostics_from, normal_quantile,
                   on_accelerator,
                   scan_unroll)


def _kernel(period: int) -> np.ndarray:
    """Centered moving-average weights (ref ``HoltWinters.scala:228-237``)."""
    if period % 2 == 0:
        # host-built constant; the only caller converts with
        # jnp.asarray(_kernel(period), ts.dtype), so f64 never leaks
        k = np.full(period + 1, 1.0 / period)    # sts: noqa[STS004]
        k[0] = k[-1] = 0.5 / period
        return k
    return np.full(period, 1.0 / period)         # sts: noqa[STS004]


class HoltWintersModel(NamedTuple):
    """``model_type`` in {"additive", "multiplicative"}; smoothing parameters
    scalar or ``(n_series,)`` (ref ``HoltWinters.scala:88-99``)."""
    model_type: str
    period: int
    alpha: jnp.ndarray
    beta: jnp.ndarray
    gamma: jnp.ndarray
    diagnostics: Optional[FitDiagnostics] = None

    @property
    def additive(self) -> bool:
        t = self.model_type.lower()
        if t not in ("additive", "multiplicative"):
            raise ValueError(f"Invalid model type: {self.model_type}")
        return t == "additive"

    # -- initialization (ref HoltWinters.scala:271-324) ---------------------

    def _init_components(self, ts: jnp.ndarray):
        """Initial (level, trend, season[period]) from the first two periods:
        convolution detrend, paired seasonal means, simple linear regression
        on the trend window (Hyndman's hw-initialization recipe)."""
        period = self.period
        additive = self.additive
        window = ts[..., :2 * period]
        kernel = jnp.asarray(_kernel(period), ts.dtype)
        ksize = kernel.shape[0]
        out_len = 2 * period - ksize + 1

        # lag_matrix row r = window[r+ksize-1 .. r] — reversed windows, which
        # the symmetric kernel makes equivalent to a forward convolution
        trend = lag_matrix(window, ksize - 1,
                           include_original=True) @ kernel   # (..., out_len)

        n_pad = (ksize - 1) // 2
        pad = [(0, 0)] * (trend.ndim - 1) + [(n_pad, n_pad)]
        padded = jnp.pad(trend, pad)
        if additive:
            removed = jnp.where(padded != 0, window - padded, 0.0)
        else:
            removed = jnp.where(padded != 0,
                                window / jnp.where(padded != 0, padded, 1.0),
                                0.0)

        first, second = removed[..., :period], removed[..., period:]
        either_zero = (first == 0) | (second == 0)
        seasonal_mean = jnp.where(either_zero, first + second,
                                  (first + second) / 2.0)
        mean_of = jnp.sum(seasonal_mean, axis=-1, keepdims=True) / period
        init_season = (seasonal_mean - mean_of) if additive \
            else seasonal_mean / mean_of

        idx = jnp.arange(1, out_len + 1, dtype=ts.dtype)
        xbar = jnp.mean(idx)
        ybar = jnp.mean(trend, axis=-1, keepdims=True)
        xxbar = jnp.sum((idx - xbar) ** 2)
        xybar = jnp.sum((idx - xbar) * (trend - ybar), axis=-1)
        init_trend = xybar / xxbar
        init_level = ybar[..., 0] - init_trend * xbar
        return init_level, init_trend, init_season

    # -- components recurrence (ref HoltWinters.scala:180-226) --------------

    def _run(self, ts: jnp.ndarray):
        """One scan over t; returns (fitted, (final_level, final_trend,
        final_season_ring)).  The ring's head is ``season[i]`` so the final
        carry is exactly what ``forecast`` needs."""
        period = self.period
        additive = self.additive
        a = jnp.asarray(self.alpha)
        b = jnp.asarray(self.beta)
        g = jnp.asarray(self.gamma)

        level0, trend0, season0 = self._init_components(ts)
        xs = jnp.moveaxis(ts[..., period:], -1, 0)           # ts[i+period]

        def step(carry, x):
            level, trend, seasons = carry
            s_i = seasons[..., 0]
            base = level + trend
            dest = base + s_i if additive else base * s_i
            lw = (x - s_i) if additive else (x / s_i)
            new_level = a * lw + (1.0 - a) * base
            new_trend = b * (new_level - level) + (1.0 - b) * trend
            sw = (x - new_level) if additive else (x / new_level)
            new_season = g * sw + (1.0 - g) * s_i
            seasons = jnp.concatenate(
                [seasons[..., 1:], new_season[..., None]], axis=-1)
            return (new_level, new_trend, seasons), dest

        final, dests = lax.scan(step, (level0, trend0, season0), xs,
                                unroll=scan_unroll())
        fitted = jnp.concatenate(
            [jnp.zeros((*ts.shape[:-1], period), ts.dtype),
             jnp.moveaxis(dests, 0, -1)], axis=-1)
        return fitted, final

    def get_holt_winters_components(self, ts: jnp.ndarray):
        """(fitted, final_level, final_trend, final_season[period]) — the
        final components rather than full trajectories (all any caller of
        the reference's version consumes, ``HoltWinters.scala:147-168``)."""
        fitted, (level, trend, seasons) = self._run(jnp.asarray(ts))
        return fitted, level, trend, seasons

    # -- objective / effects / forecast -------------------------------------

    def sse(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Σ_{t≥period} (ts_t - fitted_t)² (ref ``HoltWinters.scala:106-121``)."""
        ts = jnp.asarray(ts)
        fitted, _ = self._run(ts)
        err = ts[..., self.period:] - fitted[..., self.period:]
        return jnp.sum(err * err, axis=-1)

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Fitted values (ref ``HoltWinters.scala:133-141``)."""
        return self._run(jnp.asarray(ts))[0]

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError(
            "not implemented in the reference either "
            "(HoltWinters.scala:126-128)")

    def forecast(self, ts: jnp.ndarray, n_future: int) -> jnp.ndarray:
        """``(level + (h+1)·trend) ⊕ season`` per horizon step
        (ref ``HoltWinters.scala:147-168``, R's extra trend weight)."""
        ts = jnp.asarray(ts)
        _, (level, trend, seasons) = self._run(ts)
        h = jnp.arange(1, n_future + 1, dtype=ts.dtype)
        season_idx = jnp.arange(n_future) % self.period
        season = seasons[..., season_idx]
        base = level[..., None] + h * trend[..., None]
        return base + season if self.additive else base * season

    def forecast_interval(self, ts: jnp.ndarray, n_future: int,
                          conf: float = 0.95):
        """Prediction bands for both model types — beyond reference
        (``HoltWinters.scala:147-168`` forecasts points only).

        Linearized state-space variance for the R-style recurrence with
        additive one-step noise ``y = ŷ + ε``:
        ``var_h = σ²(1 + Σ_{j=1}^{h-1} c_{h,j}²)`` with σ² from the
        one-step fitted residuals and

            c_{h,j} = α(1 + (h-j)β)·(s_h/s_j)
                      + γ(1-α)·(F_h/F_j)·1{h ≡ j mod period}

        where ``s_j`` is the seasonal factor applied at lead j and
        ``F_j = level + j·trend``; for the additive model both ratios are
        1 and the formula reduces to the exact Class-1 result of Hyndman,
        Koehler, Ord & Snyder (2008, ch. 6) under the R↔ETS parameter map
        ``β_ets = αβ, γ_ets = γ(1-α)`` (the recurrence updates are
        ``level += αe``, ``trend += αβe``, ``season += γ(1-α)e``).  For
        the multiplicative model this is a first-order linearization; a
        400k-path Monte-Carlo of the recurrence matched it to <0.5%
        relative variance at every lead through three seasons (dev
        experiment; the coverage tests pin 3% at 200k paths, the sim
        noise floor CI can afford).  Returns ``(point, lower, upper)``,
        each ``(..., n_future)``.
        """
        if n_future < 1:
            raise ValueError("forecast_interval needs n_future >= 1")
        ts = jnp.asarray(ts)
        additive = self.additive
        # one scan serves both the residual variance (fitted values) and
        # the point forecast (final carry) — forecast() would re-run it
        fitted, (level, trend, seasons) = self._run(ts)
        h = jnp.arange(1, n_future + 1, dtype=ts.dtype)
        season_idx = jnp.arange(n_future) % self.period
        s_lead = seasons[..., season_idx]                # (..., H) s_h
        base = level[..., None] + h * trend[..., None]   # (..., H) F_h
        point = base + s_lead if additive else base * s_lead
        err = ts[..., self.period:] - fitted[..., self.period:]
        sigma2 = jnp.mean(err * err, axis=-1)

        a = jnp.asarray(self.alpha, ts.dtype)
        b = jnp.asarray(self.beta, ts.dtype)
        g = jnp.asarray(self.gamma, ts.dtype)
        # params and series may carry different batch shapes (scalar model
        # over a panel, or per-lane model on one series): plain broadcasting
        # between σ² (series batch) and Σc² (params ⊗ series batch) aligns
        if additive:
            # c depends on the lag h-j alone — O(H) cumsum form
            j = jnp.arange(1, n_future, dtype=ts.dtype)
            hit = (jnp.arange(1, n_future) % self.period == 0) \
                .astype(ts.dtype)
            cj = a[..., None] * (1.0 + j * b[..., None]) \
                + g[..., None] * (1.0 - a[..., None]) * hit
            csum = jnp.cumsum(cj * cj, axis=-1)
            csum = jnp.concatenate(
                [jnp.zeros((*csum.shape[:-1], 1), ts.dtype), csum], axis=-1)
        else:
            # the season and trend ratios break lag-stationarity: (H, H)
            lags = jnp.arange(1, n_future + 1)[:, None] \
                - jnp.arange(1, n_future + 1)[None, :]   # h - j
            future = (lags > 0).astype(ts.dtype)
            hit = ((lags % self.period == 0) & (lags > 0)).astype(ts.dtype)
            ratio_s = s_lead[..., :, None] / s_lead[..., None, :]
            ratio_f = base[..., :, None] / base[..., None, :]
            an = a[..., None, None]
            c = an * (1.0 + lags.astype(ts.dtype) * b[..., None, None]) \
                * ratio_s \
                + g[..., None, None] * (1.0 - an) * ratio_f * hit
            csum = jnp.sum((c * future) ** 2, axis=-1)
        var_h = sigma2[..., None] * (1.0 + csum)
        half = normal_quantile(conf, ts.dtype) * jnp.sqrt(var_h)
        return point, point - half, point + half


def _hw_sse_value_and_grad(params: jnp.ndarray, series: jnp.ndarray,
                           period: int, model_type: str,
                           n_valid=None):
    """Fused forward pass computing ``(sse, dsse/d(α,β,γ))`` in one scan.

    Reverse-mode autodiff through the components recurrence stores every
    step's (level, trend, season-ring) carry for the backward sweep; here
    the hand tangent recurrences ride the forward carry instead (the same
    fused-accumulator shape as ``arima._arma_normal_eqs``, docs/design.md
    §9b).  Differentiating the update equations of ``HoltWintersModel._run``
    w.r.t. θ = (α, β, γ), with ``e_α/e_β/e_γ`` the unit vectors:

        dlw  = -ds_i                (additive)  |  -(x/s_i²)·ds_i  (mult.)
        dl'  = e_α(lw - base) + α·dlw + (1-α)·dbase
        db'  = e_β(l' - l - b) + β(dl' - dl) + (1-β)·db
        dsw  = -dl'                 (additive)  |  -(x/l'²)·dl'    (mult.)
        ds'  = e_γ(sw - s_i) + γ·dsw + (1-γ)·ds_i
        de   = -(dbase + ds_i)      (additive)  |  -(dbase·s_i + base·ds_i)

    and ``g += 2·e·de``, ``sse += e²`` accumulate per step.  The initial
    components are data-only (``_init_components``), so tangents start at
    zero.  Single lane ``series (n,)``; vmapped by ``minimize_box``.

    ``n_valid`` (scalar): valid-window length of a left-aligned ragged
    lane (``ops.ragged``) — steps at absolute index ≥ ``n_valid`` get
    weight 0 in both accumulators, matching the trimmed series.
    """
    model = HoltWintersModel(model_type, period, params[0], params[1],
                             params[2])
    additive = model.additive
    a, b, g = params[0], params[1], params[2]
    dtype = series.dtype
    e_a = jnp.asarray([1.0, 0.0, 0.0], dtype)
    e_b = jnp.asarray([0.0, 1.0, 0.0], dtype)
    e_g = jnp.asarray([0.0, 0.0, 1.0], dtype)

    level0, trend0, season0 = model._init_components(series)
    if n_valid is None:
        xs = series[period:]
    else:
        ws = step_weights(series.shape[-1] - period, n_valid,
                          offset=period, dtype=dtype)
        xs = (series[period:], ws)

    def step(carry, inp):
        if n_valid is None:
            x = inp
        else:
            x, w = inp
        (level, trend, seasons, dl, db_, dseasons, sse, grad) = carry
        s_i = seasons[0]
        ds_i = dseasons[0]
        base = level + trend
        dbase = dl + db_
        if additive:
            dest = base + s_i
            e = x - dest
            de = -(dbase + ds_i)
            lw = x - s_i
            dlw = -ds_i
        else:
            dest = base * s_i
            e = x - dest
            de = -(dbase * s_i + base * ds_i)
            lw = x / s_i
            dlw = -(x / (s_i * s_i)) * ds_i
        new_level = a * lw + (1.0 - a) * base
        dnew_level = e_a * (lw - base) + a * dlw + (1.0 - a) * dbase
        new_trend = b * (new_level - level) + (1.0 - b) * trend
        dnew_trend = e_b * (new_level - level - trend) \
            + b * (dnew_level - dl) + (1.0 - b) * db_
        if additive:
            sw = x - new_level
            dsw = -dnew_level
        else:
            sw = x / new_level
            dsw = -(x / (new_level * new_level)) * dnew_level
        new_season = g * sw + (1.0 - g) * s_i
        dnew_season = e_g * (sw - s_i) + g * dsw + (1.0 - g) * ds_i
        seasons = jnp.concatenate([seasons[1:], new_season[None]])
        dseasons = jnp.concatenate([dseasons[1:], dnew_season[None]])
        if n_valid is not None:
            e = w * e
            de = w * de
        return (new_level, new_trend, seasons, dnew_level, dnew_trend,
                dseasons, sse + e * e, grad + 2.0 * e * de), None

    zero3 = jnp.zeros((3,), dtype)
    carry0 = (level0, trend0, season0, zero3, zero3,
              jnp.zeros((period, 3), dtype), jnp.zeros((), dtype), zero3)
    (out, _) = lax.scan(step, carry0, xs, unroll=scan_unroll())
    return out[6], out[7]


@_metrics.instrument_fit("holt_winters")
def fit(ts: jnp.ndarray, period: int, model_type: str = "additive",
        init=(0.3, 0.1, 0.1), tol: float = 1e-10,
        max_iter: Optional[int] = None,
        retry: Optional[_resilience.RetryPolicy] = None) -> HoltWintersModel:
    """Fit (alpha, beta, gamma) by minimizing SSE over [0, 1]³
    (ref ``HoltWinters.scala:58-83``; same R-style (0.3, 0.1, 0.1) start;
    bounded BOBYQA → batched projected gradient).

    ``ts (..., n)``; leading dims fit in one batched solve.

    NaN-padded panels (leading/trailing padding per lane) fit directly:
    valid windows are left-aligned and the SSE weighted to them, matching
    independent fits of the trimmed series (``ops.ragged``).  Lanes with
    fewer than ``2 * period + 1`` valid observations get NaN parameters
    and ``diagnostics.converged == False``; interior gaps raise.
    """
    ts = jnp.asarray(ts)
    ts, obs_len = ragged_view(ts)
    extra = () if obs_len is None else (obs_len,)

    def objective(params, series, *v):
        model = HoltWintersModel(model_type, period, params[0], params[1],
                                 params[2])
        if not v:
            return model.sse(series)
        fitted, _ = model._run(series)
        err = series[period:] - fitted[period:]
        w = step_weights(err.shape[-1], v[0], offset=period,
                         dtype=series.dtype)
        return jnp.sum(w * err * err)

    def value_and_grad(params, series, *v):
        return _hw_sse_value_and_grad(params, series, period, model_type,
                                      n_valid=v[0] if v else None)

    # the fused forward pass trades ~4x primal FLOPs for zero backward
    # storage: a win on TPU (memory-bound scans) and a measured 2.5x LOSS
    # on flop-bound CPU (46.9 -> 18.8 series/s at the suite config), so
    # CPU keeps reverse-mode autodiff — same backend gate as scan_unroll.
    # STS_HW_FUSED=1/0 overrides the gate either way so CPU CI can drive
    # fit() end-to-end through the fused pass (advisor r3).
    import os
    env = os.environ.get("STS_HW_FUSED")
    if env is not None and env not in ("0", "1"):
        raise ValueError(f"STS_HW_FUSED must be '0' or '1', got {env!r}")
    fused = on_accelerator() if env is None else env == "1"
    vag = value_and_grad if fused else None

    x0 = jnp.broadcast_to(jnp.asarray(init, ts.dtype), (*ts.shape[:-1], 3))
    # A Pallas VMEM-resident box-fit driver was built in round 4 but its
    # A/B was never admitted by the chip; build-measure-then-ship cuts
    # both ways, so it is archived with its revival recipe in
    # docs/experiments/hw_pallas.py and the measured XLA box fit is the
    # one shipped path.
    rk = _resilience.retry_kwargs(retry)
    # explicit max_iter wins over the policy's per-attempt budget (the
    # arima/garch precedence); 1000 is the historical default
    if max_iter is None:
        max_iter = retry.max_iter if retry is not None \
            and retry.max_iter is not None else 1000
    res = minimize_box(objective, x0, 0.0, 1.0, ts, *extra, tol=tol,
                       max_iter=max_iter, value_and_grad_fn=vag, **rk)
    ok = jnp.all(jnp.isfinite(res.x), axis=-1, keepdims=True)
    p = jnp.where(ok, res.x, x0)
    conv = diagnostics_from(res, ok)
    if obs_len is not None:
        short = short_lanes(obs_len, 2 * period + 1,
                            "Holt-Winters fit (two init periods + 1)")
        p, conv_mask = apply_short_quarantine(p, conv.converged, short)
        conv = conv._replace(converged=conv_mask)
    return HoltWintersModel(model_type, period, p[..., 0], p[..., 1],
                            p[..., 2], diagnostics=conv)


@_metrics.instrument_fit("holt_winters", record=False)
def fit_panel(panel, period: int, model_type: str = "additive",
              **kwargs) -> HoltWintersModel:
    """Batched fit over a Panel — ``rdd.mapValues(HoltWinters.fitModel)``."""
    return fit(panel.values, period, model_type, **kwargs)


def _naive_seasonal_model(v: jnp.ndarray, period: int,
                          model_type: str) -> HoltWintersModel:
    """Terminal fallback: α = 1, β = γ = 0 — level tracks the last
    observation, trend and the initial seasonal pattern stay frozen.
    Ragged lanes evaluate the SSE on their valid window (``ops.ragged``
    left-alignment + step weights), like the primary fit."""
    aligned, nv = ragged_view(v)
    ones = jnp.ones(v.shape[:-1], v.dtype)
    zeros = jnp.zeros_like(ones)
    m = HoltWintersModel(model_type, period, ones, zeros, zeros)
    fitted, _ = m._run(aligned)
    err = aligned[..., period:] - fitted[..., period:]
    if nv is None:
        sse = jnp.sum(err * err, axis=-1)
        ok = jnp.isfinite(sse)
    else:
        w = step_weights(err.shape[-1], jnp.asarray(nv)[..., None],
                         offset=period, dtype=v.dtype)
        # zero the tail BEFORE squaring: a multiplicative run over the
        # zero-padded tail can emit inf, and 0 * inf is NaN
        err = jnp.where(w > 0, err, 0.0)
        sse = jnp.sum(err * err, axis=-1)
        ok = jnp.isfinite(sse) & (jnp.asarray(nv) >= 2 * period + 1)
    return m._replace(diagnostics=FitDiagnostics(
        ok, jnp.zeros(sse.shape, jnp.int32), sse))


@_metrics.instrument_fit("holt_winters", record=False,
                         name="holt_winters.fit_resilient")
def fit_resilient(ts: jnp.ndarray, period: int,
                  model_type: str = "additive",
                  retry: Optional[_resilience.RetryPolicy] = None,
                  **kwargs):
    """Fail-soft batched Holt-Winters: projected-gradient fit (with
    multi-start retry) → a mid-box restart ``init=(0.5, 0.3, 0.3)`` →
    naive ``α = 1`` model.  ``ts (n_series, n)``; returns
    ``(model, FitOutcome)``."""
    if retry is None:
        retry = _resilience.RetryPolicy()
    chain = [
        ("box", lambda v: fit.__wrapped__(v, period, model_type,
                                          retry=retry, **kwargs)),
        ("box_midstart", lambda v: fit.__wrapped__(
            v, period, model_type,
            **_resilience.override_kwargs(kwargs, init=(0.5, 0.3, 0.3)))),
        ("naive", lambda v: _naive_seasonal_model(v, period, model_type)),
    ]
    return _resilience.resilient_fit(ts, chain, min_len=2 * period + 1,
                                     family="holt_winters")
