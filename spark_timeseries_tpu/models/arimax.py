"""ARIMAX(p, d, q) — ARIMA with exogenous regressors, batched.

Capability parity with the reference's ``ARIMAX``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMAX.scala:34-613``):
``Y_t = beta * X_t + ARIMA`` with per-column exogenous lags up to
``xreg_max_lag`` (optionally including the non-lagged values), initialization
from an ARX fit plus Hannan-Rissanen MA estimates, CSS-CGD refinement of the
ARMA part, and forecasting with d-order integration unwinding.

Coefficient layout (ref ``ARIMAX.scala:177-186``): slot 0 the intercept
(zero when fit without one — the reference keeps the slot too, cf. its
coefficient-count assertions in ``ARIMAXSuite.scala:118,127``), then AR terms,
MA terms, and for each exogenous column its lagged terms in increasing lag
order, then the non-lagged columns.

Like the reference, the CSS objective treats the series as a pure ARMA — the
exogenous coefficients stay frozen at their ARX estimates during refinement
(the reference's CSS gradient is identically zero in the xreg slots,
``ARIMAX.scala:304-371``, so its CGD never moves them either).

Deviations from the reference (intended semantics where its code is
inconsistent):

- the exogenous impact is the full dot product of the assembled lagged-xreg
  predictor row with the xreg coefficients — the reference's accumulation
  loop overwrites instead of summing and cycles its coefficient index
  (``ARIMAX.scala:512-527``);
- exogenous columns are differenced independently — the reference differences
  the column-major flattened matrix, bleeding values across column boundaries
  (``ARIMAX.scala:100-104``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.univariate import (differences_of_order_d,
                              inverse_differences_of_order_d)
from . import autoregression_x
from .arima import (_add_effects_one, _batched, _log_likelihood_css_arma,
                    _one_step_errors, _remove_effects_one,
                    hannan_rissanen_init)
from ..ops.optimize import minimize_bfgs, minimize_box


class ARIMAXModel(NamedTuple):
    """ARIMAX(p, d, q) with ``xreg_max_lag`` exogenous lags per column
    (ref ``ARIMAX.scala:190-198``)."""
    p: int
    d: int
    q: int
    xreg_max_lag: int
    coefficients: jnp.ndarray
    include_original_xreg: bool = True
    has_intercept: bool = True

    @property
    def _n_arma(self) -> int:
        return 1 + self.p + self.q

    @property
    def arma_coefficients(self) -> jnp.ndarray:
        """``[c, AR..., MA...]`` — the slice the CSS likelihood sees."""
        return jnp.asarray(self.coefficients)[..., :self._n_arma]

    @property
    def xreg_coefficients(self) -> jnp.ndarray:
        return jnp.asarray(self.coefficients)[..., self._n_arma:]

    # -- likelihood (pure ARMA, ref ARIMAX.scala:267-289) -------------------

    def log_likelihood_css_arma(self, diffed: jnp.ndarray) -> jnp.ndarray:
        return _batched(
            lambda prm, y: _log_likelihood_css_arma(prm, y, self.p, self.q, 1),
            self.arma_coefficients, jnp.asarray(diffed))

    def gradient_log_likelihood_css_arma(self, diffed: jnp.ndarray) -> jnp.ndarray:
        """Gradient w.r.t. the full coefficient vector; identically zero in
        the frozen xreg slots (matches ref ``ARIMAX.scala:304-371``)."""
        g = _batched(
            jax.grad(lambda prm, y: _log_likelihood_css_arma(
                prm, y, self.p, self.q, 1)),
            self.arma_coefficients, jnp.asarray(diffed))
        pad = jnp.zeros_like(self.xreg_coefficients)
        return jnp.concatenate([g, pad], axis=-1)

    # -- effects (pure ARMA, ref ARIMAX.scala:566-613) ----------------------

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        return _batched(
            lambda prm, y: _remove_effects_one(
                prm, y, self.p, self.d, self.q, 1),
            self.arma_coefficients, jnp.asarray(ts))

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        return _batched(
            lambda prm, y: _add_effects_one(
                prm, y, self.p, self.d, self.q, 1),
            self.arma_coefficients, jnp.asarray(ts))

    # -- forecasting --------------------------------------------------------

    def difference_xreg(self, xreg: jnp.ndarray) -> jnp.ndarray:
        """Order-d difference each exogenous column independently, drop the
        first ``d`` rows, and left-pad ``max(p, q)`` zero rows
        (ref ``ARIMAX.scala:543-557``; see module docstring for the
        column-independence deviation).  ``xreg (..., r, k)``."""
        cols = jnp.moveaxis(jnp.asarray(xreg), -1, -2)          # (..., k, r)
        diffed = differences_of_order_d(cols, self.d)[..., self.d:]
        max_lag = max(self.p, self.q)
        pad = [(0, 0)] * (diffed.ndim - 1) + [(max_lag, 0)]
        return jnp.moveaxis(jnp.pad(diffed, pad), -1, -2)

    def forecast(self, ts: jnp.ndarray, xreg: jnp.ndarray) -> jnp.ndarray:
        """Forecast one value per ``xreg`` row (ref ``ARIMAX.scala:200-257``,
        which returns ``results.drop(nFuture)``).

        ``ts (n,)`` is the observed history; ``xreg (n_future, k)`` holds the
        exogenous values for the forecast window.  The ARMA recurrence runs on
        the differenced history exactly as ARIMA's forecast does; each future
        step adds the exogenous impact of its (differenced, lagged) xreg row;
        the result is integrated back through the last ``d`` observations.
        """
        ts = jnp.asarray(ts)
        xreg = jnp.asarray(xreg)
        if ts.ndim > 1 or jnp.asarray(self.coefficients).ndim > 1:
            return _batched(
                lambda prm, y: self._forecast_one(prm, y, xreg),
                jnp.asarray(self.coefficients), ts)
        return self._forecast_one(jnp.asarray(self.coefficients), ts, xreg)

    def _forecast_one(self, params: jnp.ndarray, ts: jnp.ndarray,
                      xreg: jnp.ndarray) -> jnp.ndarray:
        p, d, q = self.p, self.d, self.q
        c = params[0]
        phi = params[1:1 + p]
        theta = params[1 + p:1 + p + q]
        bx = params[1 + p + q:]
        max_lag = max(p, q)
        n_future = xreg.shape[-2]

        diffed = differences_of_order_d(ts, d)[d:]
        ext = jnp.concatenate([jnp.full((max_lag,), c, ts.dtype), diffed])

        # history: one-step-ahead ARMA fits -> final MA error buffer
        yhat, err = _one_step_errors(params[:1 + p + q], ext, p, q, 1)
        hist = jnp.concatenate([jnp.zeros((max_lag,), ts.dtype), yhat])

        errs0 = (ext - hist)[::-1][:q] if q > 0 else jnp.zeros((0,), ts.dtype)
        recent0 = hist[::-1][:p] if p > 0 else jnp.zeros((0,), ts.dtype)

        # exogenous impact per future step: lags of the differenced window
        # (values before the window start are zero) ‖ current values
        dx = self.difference_xreg(xreg)                  # (max_lag+nf-d, k)
        k = xreg.shape[-1]
        lags = []
        for lag in range(1, self.xreg_max_lag + 1):
            shifted = jnp.roll(dx, lag, axis=-2).at[:lag, :].set(0.0) \
                if lag <= dx.shape[-2] else jnp.zeros_like(dx)
            lags.append(shifted)
        # reference column order: per column, its lags ascending; then the
        # non-lagged columns (ARIMAX.scala:183-186)
        parts = []
        for col in range(k):
            for lag_arr in lags:
                parts.append(lag_arr[..., col])
        if self.include_original_xreg:
            for col in range(k):
                parts.append(dx[..., col])
        predictors = (jnp.stack(parts, axis=-1) if parts
                      else jnp.zeros((dx.shape[-2], 0), ts.dtype))
        impact = (predictors @ bx)[-n_future + d:] if n_future > d \
            else jnp.zeros((0,), ts.dtype)
        impact = jnp.concatenate(
            [jnp.zeros((n_future - impact.shape[-1],), ts.dtype), impact]) \
            if impact.shape[-1] < n_future else impact

        def fwd_step(carry, imp):
            recent, errs = carry
            out = c + phi @ recent + theta @ errs + imp
            if p > 0:
                recent = jnp.concatenate([out[None], recent[:-1]])
            if q > 0:
                errs = jnp.concatenate([jnp.zeros((1,), ts.dtype), errs[:-1]])
            return (recent, errs), out

        (_, _), fwd = lax.scan(fwd_step, (recent0, errs0), impact)

        if d == 0:
            return fwd
        # seeds = diagonal of the incremental-differences matrix: the i-th
        # order difference at index n-d+i (ref ARIMA.scala:755-758)
        n = ts.shape[-1]
        rows = [ts]
        for i in range(1, d):
            prev = rows[i - 1]
            rows.append(jnp.concatenate(
                [jnp.zeros((i,), ts.dtype),
                 differences_of_order_d(prev[i:], 1)]))
        prev_terms = jnp.stack([rows[i][n - d + i] for i in range(d)])
        integrated = inverse_differences_of_order_d(
            jnp.concatenate([prev_terms, fwd]), d)
        return integrated[d:]


def fit(p: int, d: int, q: int, ts: jnp.ndarray, xreg: jnp.ndarray,
        xreg_max_lag: int, include_original_xreg: bool = True,
        include_intercept: bool = True,
        user_init_params: Optional[jnp.ndarray] = None,
        method: str = "css-cgd") -> ARIMAXModel:
    """Fit an ARIMAX(p, d, q) (ref ``ARIMAX.scala:61-90``): initialize the
    ARX part by OLS on [y lags ‖ xreg lags ‖ xreg] (with the xreg columns
    differenced to order d, ref ``ARIMAX.scala:92-112``), the MA part by
    Hannan-Rissanen, then refine the ARMA slice by batched CSS maximum
    likelihood with the xreg coefficients frozen.

    ``ts (..., n)``; ``xreg (n, k)`` (or batched ``(..., n, k)``).
    """
    ts = jnp.asarray(ts)
    xreg = jnp.asarray(xreg)
    diffed = differences_of_order_d(ts, d)[..., d:]
    icpt = 1 if include_intercept else 0

    if user_init_params is not None:
        init_full = jnp.asarray(user_init_params, ts.dtype)
        c0 = init_full[..., :1]
        ar0 = init_full[..., 1:1 + p]
        ma0 = init_full[..., 1 + p:1 + p + q]
        bx = init_full[..., 1 + p + q:]
    else:
        # ARX on the raw series with differenced xreg (ref ARIMAX.scala:92-112)
        cols = jnp.moveaxis(xreg, -1, -2)
        dx = jnp.moveaxis(differences_of_order_d(cols, d), -1, -2)
        arx = autoregression_x.fit(ts, dx, p, xreg_max_lag,
                                   include_original_xreg,
                                   no_intercept=not include_intercept)
        c0 = jnp.asarray(arx.c)[..., None] if include_intercept \
            else jnp.zeros((*ts.shape[:-1], 1), ts.dtype)
        ar0 = arx.coefficients[..., :p]
        bx = arx.coefficients[..., p:]
        if q > 0:
            ma0 = hannan_rissanen_init(p, q, diffed,
                                       include_intercept)[..., -q:]
        else:
            ma0 = jnp.zeros((*ts.shape[:-1], 0), ts.dtype)

    # refine [c?, AR, MA] by CSS; xreg slots stay frozen
    if include_intercept:
        init = jnp.concatenate([c0, ar0, ma0], axis=-1)
    else:
        init = jnp.concatenate([ar0, ma0], axis=-1)

    if init.shape[-1] > 0:
        def neg_ll(prm, y):
            return -_log_likelihood_css_arma(prm, y, p, q, icpt)

        if method == "css-cgd":
            res = minimize_bfgs(neg_ll, init, diffed, tol=1e-7, max_iter=500)
        elif method == "css-bobyqa":
            res = minimize_box(neg_ll, init, -jnp.inf, jnp.inf, diffed,
                               tol=1e-10, max_iter=500)
        else:
            raise ValueError(f"unknown method {method!r}")
        lane_ok = jnp.all(jnp.isfinite(res.x), axis=-1, keepdims=True)
        refined = jnp.where(lane_ok, res.x, init)
    else:
        refined = init

    if include_intercept:
        full = jnp.concatenate([refined, bx], axis=-1)
    else:
        zero_c = jnp.zeros((*ts.shape[:-1], 1), ts.dtype)
        full = jnp.concatenate([zero_c, refined, bx], axis=-1)
    return ARIMAXModel(p, d, q, xreg_max_lag, full, include_original_xreg,
                       include_intercept)
