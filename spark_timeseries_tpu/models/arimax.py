"""ARIMAX(p, d, q) — ARIMA with exogenous regressors, batched.

Capability parity with the reference's ``ARIMAX``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMAX.scala:34-613``):
``Y_t = beta * X_t + ARIMA`` with per-column exogenous lags up to
``xreg_max_lag`` (optionally including the non-lagged values), initialization
from an ARX fit plus Hannan-Rissanen MA estimates, CSS-CGD refinement of the
ARMA part, and forecasting with d-order integration unwinding.

Coefficient layout (ref ``ARIMAX.scala:177-186``): slot 0 the intercept
(zero when fit without one — the reference keeps the slot too, cf. its
coefficient-count assertions in ``ARIMAXSuite.scala:118,127``), then AR terms,
MA terms, and for each exogenous column its lagged terms in increasing lag
order, then the non-lagged columns.

Like the reference, the CSS objective treats the series as a pure ARMA — the
exogenous coefficients stay frozen at their ARX estimates during refinement
(the reference's CSS gradient is identically zero in the xreg slots,
``ARIMAX.scala:304-371``, so its CGD never moves them either).

Deviations from the reference (intended semantics where its code is
inconsistent):

- the exogenous impact is the full dot product of the assembled lagged-xreg
  predictor row with the xreg coefficients — the reference's accumulation
  loop overwrites instead of summing and cycles its coefficient index
  (``ARIMAX.scala:512-527``);
- exogenous columns are differenced independently — the reference differences
  the column-major flattened matrix, bleeding values across column boundaries
  (``ARIMAX.scala:100-104``);
- the ARMA refinement runs on the **xreg-adjusted** differenced series
  (``diff_d(y) - bx·X_terms``) rather than the raw one.  The reference's CSS
  objective ignores the exogenous part entirely, so its intercept drifts
  toward the series mean (absorbing the exogenous mean) and only its barely-
  moving CGD keeps forecasts from double-counting the xreg effect; adjusting
  first makes fit and forecast mutually consistent.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.univariate import differences_of_order_d
from . import autoregression_x
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from .base import FitDiagnostics, diagnostics_from, normal_quantile
from .arima import (LM_MAX_ITER, _add_effects_one, _arma_normal_eqs,
                    _batched, _difference_rows, _log_likelihood_css_arma,
                    _one_step_errors, _remove_effects_one,
                    hannan_rissanen_init)
from ..ops.optimize import (minimize_bfgs, minimize_box,
                            minimize_least_squares)


def _assemble_xreg_terms(dx: jnp.ndarray, xreg_max_lag: int,
                         include_original: bool) -> jnp.ndarray:
    """Assemble ``[per-column lags ascending ‖ current columns]`` rows over a
    differenced window, zero-filling lags that reach before the window start
    (reference column order, ``ARIMAX.scala:183-186``).
    ``dx (..., r, k)`` → ``(..., r, n_xreg_coefs)``."""
    k = dx.shape[-1]
    lags = []
    for lag in range(1, xreg_max_lag + 1):
        lags.append(jnp.roll(dx, lag, axis=-2).at[..., :lag, :].set(0.0))
    parts = []
    for col in range(k):
        for lag_arr in lags:
            parts.append(lag_arr[..., col])
    if include_original:
        for col in range(k):
            parts.append(dx[..., col])
    if not parts:
        return jnp.zeros((*dx.shape[:-1], 0), dx.dtype)
    return jnp.stack(parts, axis=-1)


class ARIMAXModel(NamedTuple):
    """ARIMAX(p, d, q) with ``xreg_max_lag`` exogenous lags per column
    (ref ``ARIMAX.scala:190-198``)."""
    p: int
    d: int
    q: int
    xreg_max_lag: int
    coefficients: jnp.ndarray
    include_original_xreg: bool = True
    has_intercept: bool = True
    diagnostics: Optional["FitDiagnostics"] = None

    @property
    def _n_arma(self) -> int:
        return 1 + self.p + self.q

    @property
    def arma_coefficients(self) -> jnp.ndarray:
        """``[c, AR..., MA...]`` — the slice the CSS likelihood sees."""
        return jnp.asarray(self.coefficients)[..., :self._n_arma]

    @property
    def xreg_coefficients(self) -> jnp.ndarray:
        return jnp.asarray(self.coefficients)[..., self._n_arma:]

    # -- likelihood (pure ARMA, ref ARIMAX.scala:267-289) -------------------

    def log_likelihood_css_arma(self, diffed: jnp.ndarray) -> jnp.ndarray:
        return _batched(
            lambda prm, y: _log_likelihood_css_arma(prm, y, self.p, self.q, 1),
            self.arma_coefficients, jnp.asarray(diffed))

    def gradient_log_likelihood_css_arma(self, diffed: jnp.ndarray) -> jnp.ndarray:
        """Gradient w.r.t. the full coefficient vector; identically zero in
        the frozen xreg slots (matches ref ``ARIMAX.scala:304-371``)."""
        g = _batched(
            jax.grad(lambda prm, y: _log_likelihood_css_arma(
                prm, y, self.p, self.q, 1)),
            self.arma_coefficients, jnp.asarray(diffed))
        pad = jnp.zeros_like(self.xreg_coefficients)
        return jnp.concatenate([g, pad], axis=-1)

    # -- effects (pure ARMA, ref ARIMAX.scala:566-613) ----------------------

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        return _batched(
            lambda prm, y: _remove_effects_one(
                prm, y, self.p, self.d, self.q, 1),
            self.arma_coefficients, jnp.asarray(ts))

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        return _batched(
            lambda prm, y: _add_effects_one(
                prm, y, self.p, self.d, self.q, 1),
            self.arma_coefficients, jnp.asarray(ts))

    # -- exogenous terms ----------------------------------------------------

    def difference_xreg(self, xreg: jnp.ndarray) -> jnp.ndarray:
        """Order-d difference each exogenous column independently and drop
        the first ``d`` rows (ref ``ARIMAX.scala:543-557``; see module
        docstring for the column-independence deviation).
        ``xreg (..., r, k)`` → ``(..., r - d, k)``."""
        cols = jnp.moveaxis(jnp.asarray(xreg), -1, -2)          # (..., k, r)
        diffed = differences_of_order_d(cols, self.d)[..., self.d:]
        return jnp.moveaxis(diffed, -1, -2)

    def _xreg_terms(self, dx: jnp.ndarray) -> jnp.ndarray:
        return _assemble_xreg_terms(dx, self.xreg_max_lag,
                                    self.include_original_xreg)

    def xreg_contribution(self, xreg: jnp.ndarray) -> jnp.ndarray:
        """Exogenous contribution ``bx·X_terms`` on the differenced scale,
        one value per row of ``diff_d(xreg)``."""
        dx = self.difference_xreg(jnp.asarray(xreg))
        return self._xreg_terms(dx) @ self.xreg_coefficients

    # -- forecasting --------------------------------------------------------

    def forecast(self, ts: jnp.ndarray, xreg: jnp.ndarray) -> jnp.ndarray:
        """One-step-ahead predictions over a window: ``ts (n,)`` and
        ``xreg (n, k)`` cover the SAME time span, and the result holds one
        prediction per observation (the reference's suite calls this with
        the hold-out series and its exogenous matrix and asserts
        ``results.length == ts.length``, ref ``ARIMAXSuite.scala:100-106``).

        On the differenced scale: ``ŷ_t = ARMA 1-step fit of the adjusted
        series + bx·X_terms_t``; for ``d > 0`` the prediction is re-levelled
        through the lower-order differences at ``t-1`` (the ARIMA
        integration unwinding, ref ``ARIMA.scala:747-753``).
        """
        ts = jnp.asarray(ts)
        xreg = jnp.asarray(xreg)
        coefs = jnp.asarray(self.coefficients)
        p_b, t_b, x_b = coefs.ndim > 1, ts.ndim > 1, xreg.ndim > 2
        if not (p_b or t_b or x_b):
            return self._forecast_one(coefs, ts, xreg)
        # a per-series xreg (..., n, k) — which fit() supports — must be
        # vmapped alongside params/ts, not closed over (it would otherwise
        # mis-broadcast inside the per-lane forecast)
        return jax.vmap(self._forecast_one,
                        in_axes=(0 if p_b else None, 0 if t_b else None,
                                 0 if x_b else None))(coefs, ts, xreg)

    def _forecast_one(self, params: jnp.ndarray, ts: jnp.ndarray,
                      xreg: jnp.ndarray) -> jnp.ndarray:
        p, d, q = self.p, self.d, self.q
        c = params[0]
        max_lag = max(p, q)
        n = ts.shape[-1]

        dy = differences_of_order_d(ts, d)[d:]
        dx = self.difference_xreg(xreg)
        g = self._xreg_terms(dx) @ params[1 + p + q:]
        adjusted = dy - g

        ext = jnp.concatenate([jnp.full((max_lag,), c, ts.dtype), adjusted])
        yhat, _ = _one_step_errors(params[:1 + p + q], ext, p, q, 1)
        hist = jnp.concatenate([jnp.zeros((max_lag,), ts.dtype), yhat])
        pred_diff = hist[max_lag:] + g          # 1-step preds of dy

        if d == 0:
            return pred_diff
        # re-level: ŷ_t = Σ_{i<d} diff_i(y)_{t-1} + pred of diff_d(y)_t
        level = jnp.sum(_difference_rows(ts, d), axis=0)    # Σ_{i<d} diff_i
        t_idx = jnp.arange(d, n)
        preds = level[t_idx - 1] + pred_diff[t_idx - d]
        return jnp.concatenate([ts[:d], preds])

    def _sigma2_one(self, params: jnp.ndarray, ts: jnp.ndarray,
                    xreg: jnp.ndarray) -> jnp.ndarray:
        """One-step error variance of the xreg-adjusted ARMA, CSS
        convention (burn-in dropped from the sum, full differenced length
        as divisor — same as the ARIMA bands)."""
        p, q = self.p, self.q
        dy = differences_of_order_d(ts, self.d)[self.d:]
        dx = self.difference_xreg(xreg)
        adjusted = dy - self._xreg_terms(dx) @ params[1 + p + q:]
        _, err = _one_step_errors(params[:1 + p + q], adjusted, p, q, 1)
        return jnp.sum(err * err) / adjusted.shape[-1]

    def forecast_interval(self, ts: jnp.ndarray, xreg: jnp.ndarray,
                          conf: float = 0.95):
        """Bands on the one-step-ahead window predictions — beyond
        reference (``ARIMAX.scala`` has no uncertainty output).

        Every position of :meth:`forecast`'s output is a 1-step forecast
        conditional on the observed history and exogenous row, so the
        error variance is the constant one-step σ² of the xreg-adjusted
        ARMA; bands are ``± z·σ`` around each prediction.  The first ``d``
        positions of :meth:`forecast`'s output are raw pass-through
        observations, not forecasts — their bands are NaN rather than a
        fabricated interval around the observation itself.  Returns
        ``(pred, lower, upper)``, each shaped like :meth:`forecast`'s
        output.
        """
        ts = jnp.asarray(ts)
        xreg = jnp.asarray(xreg)
        pred = self.forecast(ts, xreg)
        coefs = jnp.asarray(self.coefficients)
        p_b, t_b, x_b = coefs.ndim > 1, ts.ndim > 1, xreg.ndim > 2
        if not (p_b or t_b or x_b):
            sigma2 = self._sigma2_one(coefs, ts, xreg)
        else:
            sigma2 = jax.vmap(
                self._sigma2_one,
                in_axes=(0 if p_b else None, 0 if t_b else None,
                         0 if x_b else None))(coefs, ts, xreg)
        half = normal_quantile(conf, ts.dtype) \
            * jnp.sqrt(sigma2)[..., None]
        half = jnp.where(jnp.arange(pred.shape[-1]) < self.d,
                         jnp.nan, half)
        return pred, pred - half, pred + half


@_metrics.instrument_fit("arimax")
def fit(p: int, d: int, q: int, ts: jnp.ndarray, xreg: jnp.ndarray,
        xreg_max_lag: int, include_original_xreg: bool = True,
        include_intercept: bool = True,
        user_init_params: Optional[jnp.ndarray] = None,
        method: str = "css-lm",
        max_iter: Optional[int] = None,
        retry: Optional[_resilience.RetryPolicy] = None) -> ARIMAXModel:
    """Fit an ARIMAX(p, d, q) (ref ``ARIMAX.scala:61-90``): initialize the
    ARX part by OLS on [y lags ‖ xreg lags ‖ xreg] (with the xreg columns
    differenced to order d, ref ``ARIMAX.scala:92-112``), the MA part by
    Hannan-Rissanen, then refine the ARMA slice by batched CSS maximum
    likelihood with the xreg coefficients frozen.

    ``ts (..., n)``; ``xreg (n, k)`` (or batched ``(..., n, k)``).
    """
    ts = jnp.asarray(ts)
    xreg = jnp.asarray(xreg)
    if xreg.ndim < 2 or xreg.shape[-2] != ts.shape[-1]:
        # otherwise the mismatch surfaces later as an opaque concatenate
        # shape error from the terms assembly
        raise ValueError(
            f"xreg must be (n, k) or (..., n, k) with n = series length "
            f"{ts.shape[-1]}; got {xreg.shape}")
    diffed = differences_of_order_d(ts, d)[..., d:]
    # size-preserving per-column differencing once; the dropped-d view feeds
    # the terms assembly, the full-length view the ARX init
    dx_full = jnp.moveaxis(
        differences_of_order_d(jnp.moveaxis(xreg, -1, -2), d), -1, -2)
    dxreg = dx_full[..., d:, :]
    terms = _assemble_xreg_terms(dxreg, xreg_max_lag, include_original_xreg)
    icpt = 1 if include_intercept else 0

    if user_init_params is not None:
        init_full = jnp.asarray(user_init_params, ts.dtype)
        c0 = init_full[..., :1]
        ar0 = init_full[..., 1:1 + p]
        ma0 = init_full[..., 1 + p:1 + p + q]
        bx = init_full[..., 1 + p + q:]
    else:
        # ARX on the raw series with differenced xreg (ref ARIMAX.scala:92-112)
        arx = autoregression_x.fit.__wrapped__(
            ts, dx_full, p, xreg_max_lag, include_original_xreg,
            no_intercept=not include_intercept)
        c0 = jnp.asarray(arx.c)[..., None] if include_intercept \
            else jnp.zeros((*ts.shape[:-1], 1), ts.dtype)
        ar0 = arx.coefficients[..., :p]
        bx = arx.coefficients[..., p:]
        if q > 0:
            ma0 = hannan_rissanen_init(p, q, diffed,
                                       include_intercept)[..., -q:]
        else:
            ma0 = jnp.zeros((*ts.shape[:-1], 0), ts.dtype)

    # refine [c?, AR, MA] by CSS on the xreg-adjusted series (see module
    # docstring); xreg slots stay frozen at their ARX estimates
    adjusted = diffed - jnp.einsum("...nm,...m->...n", terms, bx)
    if include_intercept:
        init = jnp.concatenate([c0, ar0, ma0], axis=-1)
    else:
        init = jnp.concatenate([ar0, ma0], axis=-1)

    if init.shape[-1] > 0:
        def neg_ll(prm, y):
            return -_log_likelihood_css_arma(prm, y, p, q, icpt)

        rk = _resilience.retry_kwargs(retry)
        if max_iter is None and retry is not None \
                and retry.max_iter is not None:
            max_iter = retry.max_iter
        if method == "css-lm":
            # the refinement runs on the xreg-adjusted series with pure
            # [c?, AR, MA] parameters — exactly arima's CSS residual, so
            # the fused-carry normal equations apply unchanged
            res = minimize_least_squares(
                None, init, adjusted,
                max_iter=max_iter if max_iter is not None else LM_MAX_ITER,
                normal_eqs_fn=lambda prm, y: _arma_normal_eqs(
                    prm, y, p, q, icpt), **rk)
        elif method == "css-cgd":
            res = minimize_bfgs(neg_ll, init, adjusted, tol=1e-7,
                                max_iter=max_iter if max_iter is not None else 500,
                                **rk)
        elif method == "css-bobyqa":
            res = minimize_box(neg_ll, init, -jnp.inf, jnp.inf, adjusted,
                               tol=1e-10,
                               max_iter=max_iter if max_iter is not None else 500,
                               **rk)
        else:
            raise ValueError(f"unknown method {method!r}")
        lane_ok = jnp.all(jnp.isfinite(res.x), axis=-1, keepdims=True)
        refined = jnp.where(lane_ok, res.x, init)
        diag = diagnostics_from(res, lane_ok)
    else:
        # nothing to refine (p = q = 0, no intercept): the fit is the direct
        # ARX solve; report its residual CSS so fit_report still works
        refined = init
        fun = jnp.sum(adjusted * adjusted, axis=-1)
        diag = FitDiagnostics(
            jnp.all(jnp.isfinite(bx), axis=-1) & jnp.isfinite(fun),
            jnp.zeros(fun.shape, jnp.int32), fun)

    if include_intercept:
        full = jnp.concatenate([refined, bx], axis=-1)
    else:
        zero_c = jnp.zeros((*ts.shape[:-1], 1), ts.dtype)
        full = jnp.concatenate([zero_c, refined, bx], axis=-1)
    return ARIMAXModel(p, d, q, xreg_max_lag, full, include_original_xreg,
                       include_intercept, diagnostics=diag)


def _pad_to_order(model: ARIMAXModel, p: int, q: int) -> ARIMAXModel:
    """Re-express a lower-ARMA-order ARIMAX fit in the (p, q) layout by
    zero-filling the absent AR/MA slots (the intercept slot is always
    present in this family's layout, ref ``ARIMAX.scala:177-186``)."""
    coefs = jnp.asarray(model.coefficients)
    c = coefs[..., :1]
    ar = coefs[..., 1:1 + model.p]
    ma = coefs[..., 1 + model.p:1 + model.p + model.q]
    bx = coefs[..., 1 + model.p + model.q:]
    zero = lambda k: jnp.zeros((*coefs.shape[:-1], k), coefs.dtype)
    full = jnp.concatenate([c, ar, zero(p - model.p),
                            ma, zero(q - model.q), bx], axis=-1)
    return ARIMAXModel(p, model.d, q, model.xreg_max_lag, full,
                       model.include_original_xreg, model.has_intercept,
                       diagnostics=model.diagnostics)


@_metrics.instrument_fit("arimax", record=False, name="arimax.fit_resilient")
def fit_resilient(ts: jnp.ndarray, xreg: jnp.ndarray, p: int, d: int, q: int,
                  xreg_max_lag: int, include_original_xreg: bool = True,
                  include_intercept: bool = True,
                  retry=None, **kwargs):
    """Fail-soft batched ARIMAX: css-lm (with multi-start retry) →
    css-bobyqa → xreg-plus-intercept only (the ARMA slots zeroed, exogenous
    effects kept).  ``ts (n_series, n)``; ``xreg`` must be a shared
    unbatched ``(n, k)`` design (a per-series design cannot be compacted
    alongside the panel).  Returns ``(model, FitOutcome)`` — see
    ``utils.resilience.resilient_fit``."""
    if retry is None:
        retry = _resilience.RetryPolicy()
    xreg = jnp.asarray(xreg)
    if xreg.ndim != 2:
        raise ValueError(
            "fit_resilient needs a shared unbatched (n, k) design; got "
            f"xreg shape {xreg.shape}")

    def _fit(v, **kw):
        return fit.__wrapped__(p, d, q, v, xreg, xreg_max_lag,
                               include_original_xreg, include_intercept,
                               **kw, **kwargs)

    chain = [
        ("css-lm", lambda v: _fit(v, retry=retry)),
        ("css-bobyqa", lambda v: fit.__wrapped__(
            p, d, q, v, xreg, xreg_max_lag, include_original_xreg,
            include_intercept,
            **_resilience.override_kwargs(kwargs, method="css-bobyqa"))),
        ("xreg_only", lambda v: _pad_to_order(
            fit.__wrapped__(0, d, 0, v, xreg, xreg_max_lag,
                            include_original_xreg, include_intercept,
                            **kwargs), p, q)),
    ]
    min_len = d + max(2 * max(p, q) + 3 + p + q, xreg_max_lag + 2, 3)
    return _resilience.resilient_fit(ts, chain, min_len=min_len,
                                     family="arimax")
