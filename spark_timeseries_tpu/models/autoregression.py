"""Autoregressive AR(p) models, batched.

Capability parity with the reference's ``Autoregression``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/Autoregression.scala:24-96``):
OLS on the trimmed lag matrix, optional intercept, add/remove time-dependent
effects, model-based sampling.

TPU-native design: the OLS runs as one batched QR solve over the whole panel
(MXU matmuls) instead of per-series Commons-Math
``OLSMultipleLinearRegression``; the ``addTimeDependentEffects`` output
recurrence is a ``lax.scan`` with a length-``p`` ring carry.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.lag import lag_matvec, lag_stack
from ..ops.linalg import ols_gram
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from .base import FitDiagnostics, scan_unroll


class ARModel(NamedTuple):
    """AR(p) parameters; ``c`` scalar or ``(batch,)``, ``coefficients``
    ``(p,)`` or ``(batch, p)`` in increasing lag order
    (ref ``Autoregression.scala:58-60``).  ``diagnostics.converged`` marks
    lanes whose OLS solve came back finite (the direct solve has no
    iteration count — ``n_iter`` is 0 and ``fun`` a 0/NaN flag)."""
    c: jnp.ndarray
    coefficients: jnp.ndarray
    diagnostics: Optional[FitDiagnostics] = None

    @property
    def order(self) -> int:
        return self.coefficients.shape[-1]

    @property
    def n_params(self) -> int:
        """Estimated-parameter count (intercept slot + AR lags) — the
        parsimony key the backtest tier's champion tie-break orders
        near-equal out-of-sample scores by.  The intercept slot counts
        even for ``no_intercept`` fits (the model pytree does not record
        the constraint); tie-breaking only needs a consistent ordering
        across candidates, not an exact likelihood penalty."""
        return self.order + 1

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """``out[i] = ts[i] - c - Σ_j coef_j · ts[i-j-1]`` with out-of-range
        terms dropped (ref ``Autoregression.scala:62-77``) — fully
        vectorized via a zero-padded lag matrix."""
        c = jnp.asarray(self.c)
        coefs = jnp.asarray(self.coefficients)
        p = coefs.shape[-1]
        pad = [(0, 0)] * (ts.ndim - 1) + [(p, 0)]
        padded = jnp.pad(ts, pad)
        ar_part = lag_matvec(padded, coefs, p)          # (..., n)
        return ts - c[..., None] - ar_part if c.ndim else ts - c - ar_part

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """``out[i] = c + ts[i] + Σ_j coef_j · out[i-j-1]`` — an order-``p``
        linear recurrence on the *output*, so a ``lax.scan`` with a
        recent-first ring carry (ref ``Autoregression.scala:79-94``)."""
        c = jnp.asarray(self.c)
        coefs = jnp.asarray(self.coefficients)
        p = coefs.shape[-1]
        xs = jnp.moveaxis(ts, -1, 0)                    # (n, ...)
        carry0 = jnp.zeros((*xs.shape[1:], p), ts.dtype)

        def step(carry, x_t):
            d = c + x_t + jnp.sum(coefs * carry, axis=-1)
            return jnp.concatenate([d[..., None], carry[..., :-1]], axis=-1), d

        _, out = lax.scan(step, carry0, xs, unroll=scan_unroll())
        return jnp.moveaxis(out, 0, -1)

    def sample(self, n: int, key, shape=()) -> jnp.ndarray:
        """Gaussian innovations pushed through the model
        (ref ``Autoregression.scala:90-94``)."""
        noise = jax.random.normal(
            key, (*shape, n), dtype=jnp.asarray(self.coefficients).dtype)
        return self.add_time_dependent_effects(noise)


@_metrics.instrument_fit("ar")
def fit(ts: jnp.ndarray, max_lag: int = 1, no_intercept: bool = False,
        n_valid: jnp.ndarray | None = None) -> ARModel:
    """Fit AR(max_lag) by OLS on the lag matrix
    (ref ``Autoregression.scala:38-53``).  ``ts (..., n)``; all leading
    dims are batched through one QR solve.

    ``n_valid (...,)`` restricts each lane to its left-aligned valid window
    (see :func:`~spark_timeseries_tpu.ops.ragged.ragged_view`): OLS rows
    whose target index falls at or past ``n_valid`` get weight 0, which is
    exactly the OLS of the trimmed series."""
    ts = jnp.asarray(ts)
    y = ts[..., max_lag:]
    X = lag_stack(ts, max_lag)
    w = None
    if n_valid is not None:
        from ..ops.ragged import step_weights
        w = step_weights(y.shape[-1], jnp.asarray(n_valid)[..., None],
                         offset=max_lag, dtype=ts.dtype)
    res = ols_gram(X, y, add_intercept=not no_intercept, row_weights=w)
    if no_intercept:
        c = jnp.zeros(ts.shape[:-1], ts.dtype)
        coefs = res.beta
    else:
        c, coefs = res.beta[..., 0], res.beta[..., 1:]
    # direct solve: "converged" = finite solution, in 0 iterations (the
    # resilient fallback chains key off this mask like any optimizer's)
    ok = jnp.all(jnp.isfinite(res.beta), axis=-1)
    diag = FitDiagnostics(ok, jnp.zeros(ok.shape, jnp.int32),
                          jnp.where(ok, 0.0, jnp.nan).astype(ts.dtype))
    return ARModel(c, coefs, diagnostics=diag)


@_metrics.instrument_fit("ar", record=False)
def fit_panel(panel, max_lag: int = 1, no_intercept: bool = False) -> ARModel:
    """Batched fit over a Panel — the ``mapValues(Autoregression.fitModel)``
    equivalent."""
    return fit(panel.values, max_lag, no_intercept)


def _mean_model(v: jnp.ndarray, max_lag: int) -> ARModel:
    """Terminal fallback: intercept-only (all AR coefficients zero) — the
    drift/mean model, defined for any lane with finite observations
    (NaN padding on ragged lanes is ignored, like the primary fits)."""
    c = jnp.nanmean(v, axis=-1)
    ok = jnp.isfinite(c)
    return ARModel(c, jnp.zeros((*v.shape[:-1], max_lag), v.dtype),
                   diagnostics=FitDiagnostics(
                       ok, jnp.zeros(ok.shape, jnp.int32),
                       jnp.where(ok, 0.0, jnp.nan).astype(v.dtype)))


@_metrics.instrument_fit("ar", record=False, name="ar.fit_resilient")
def fit_resilient(ts: jnp.ndarray, max_lag: int = 1,
                  no_intercept: bool = False,
                  retry: Optional[_resilience.RetryPolicy] = None):
    """Fail-soft batched AR(p): OLS → intercept-only mean model.  The OLS
    solve is direct, so ``retry`` is accepted for interface uniformity but
    unused.  ``ts (n_series, n)``; returns ``(model, FitOutcome)``."""
    del retry
    chain = [
        ("ols", lambda v: fit.__wrapped__(v, max_lag, no_intercept)),
        ("mean", lambda v: _mean_model(v, max_lag)),
    ]
    return _resilience.resilient_fit(ts, chain, min_len=2 * max_lag + 2,
                                     family="ar")
