"""Regression with ARIMA (AR(1)) error structure — Cochrane-Orcutt, batched.

Capability parity with the reference's ``RegressionARIMA``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/RegressionARIMA.scala:34-201``):
``Y_t = B·X_t + e_t`` with ``e_t = rho·e_{t-1} + w_t``; iterative
Cochrane-Orcutt estimation driven by a Durbin-Watson autocorrelation check,
rho-convergence threshold 0.001, and the same stopping rules.

TPU-native design: the reference iterates per series with scalar OLS; here
the WHOLE iteration is one compiled ``lax.while_loop`` over the panel —
each step one batched OLS, per-lane ``finished`` masks freezing converged
series (SURVEY.md §7 hard part #3), and the loop exiting early the moment
every lane is done.  One device dispatch for the whole fit: the r4 host-
level loop paid one dispatch round trip per iteration and measured 11.5x
baseline where the rest of the suite runs 1,700x+ (r4 verdict weak #5).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.linalg import ols
from ..stats import dwtest
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from .base import FitDiagnostics, normal_quantile

DW_MARGIN = 0.05
RHO_DIFF_THRESHOLD = 0.001


def _broadcast_design(y: jnp.ndarray, X) -> jnp.ndarray:
    """A shared unbatched ``(n, k)`` design broadcasts over ``y``'s batch —
    one rule for the fit and the forecast surfaces."""
    X = jnp.asarray(X)
    if y.ndim > 1 and X.ndim == 2:
        X = jnp.broadcast_to(X, (*y.shape[:-1], *X.shape))
    return X


def _is_autocorrelated(residuals: jnp.ndarray) -> jnp.ndarray:
    """Durbin-Watson statistic outside 2 ± 0.05
    (ref ``RegressionARIMA.scala:163-176``)."""
    dw = dwtest(residuals)
    return (dw <= 2.0 - DW_MARGIN) | (dw >= 2.0 + DW_MARGIN)


class RegressionARIMAModel(NamedTuple):
    """(ref ``RegressionARIMA.scala:180-201``); ``regression_coeff`` holds
    the intercept then the K regressor coefficients; ``arima_orders`` is
    (p, d, q) = (1, 0, 0); ``arima_coeff`` the AR(1) rho."""
    regression_coeff: jnp.ndarray
    arima_orders: Tuple[int, int, int]
    arima_coeff: jnp.ndarray
    diagnostics: Optional[FitDiagnostics] = None

    def add_time_dependent_effects(self, ts):
        raise NotImplementedError(
            "unsupported in the reference too (RegressionARIMA.scala:186-191)")

    def remove_time_dependent_effects(self, ts):
        raise NotImplementedError(
            "unsupported in the reference too (RegressionARIMA.scala:193-198)")

    def _residuals(self, ts: jnp.ndarray, X: jnp.ndarray) -> jnp.ndarray:
        beta = jnp.asarray(self.regression_coeff)
        return ts - (jnp.einsum("...nk,...k->...n", X, beta[..., 1:])
                     + beta[..., :1])

    def _point_from_resid(self, resid: jnp.ndarray,
                          Xf: jnp.ndarray) -> jnp.ndarray:
        """``x_{n+h}'β + ρ^h e_n`` with the ρ powers as a cumulative
        product — float ``**`` lowers to exp/log on TPU and NaNs for the
        negative ρ a Cochrane-Orcutt fit can legitimately produce."""
        beta = jnp.asarray(self.regression_coeff)
        rho = jnp.asarray(self.arima_coeff)
        H = Xf.shape[-2]
        decay = jnp.cumprod(
            jnp.broadcast_to(rho[..., None], (*rho.shape, H)), axis=-1)
        reg_part = jnp.einsum("...hk,...k->...h", Xf, beta[..., 1:]) \
            + beta[..., :1]
        return reg_part + decay * resid[..., -1][..., None]

    def forecast(self, ts: jnp.ndarray, regressors,
                 future_regressors) -> jnp.ndarray:
        """GLS point forecasts under the fitted AR(1) error — beyond
        reference (``RegressionARIMA.scala`` has no forecast surface).

        ``y_{n+h} = x_{n+h}'β + ρ^h e_n``: the regression part is
        deterministic given the supplied future design rows, and the error
        forecast decays from the last in-sample residual at the fitted ρ.
        ``future_regressors (..., H, k)`` → ``(..., H)``; a shared
        unbatched design broadcasts over the batch like in the fit.
        """
        ts = jnp.asarray(ts)
        X = _broadcast_design(ts, regressors)
        Xf = _broadcast_design(ts, future_regressors)
        return self._point_from_resid(self._residuals(ts, X), Xf)

    def forecast_interval(self, ts: jnp.ndarray, regressors,
                          future_regressors, conf: float = 0.95):
        """Prediction bands for :meth:`forecast`: the AR(1)-error forecast
        variance is ``σ_u² Σ_{j<h} ρ^{2j}`` with the innovation variance
        ``σ_u²`` estimated from ``u_t = e_t - ρ e_{t-1}`` (regression
        coefficients treated as known, the standard Cochrane-Orcutt
        asymptotics).  Returns ``(point, lower, upper)``, each
        ``(..., H)``.
        """
        ts = jnp.asarray(ts)
        X = _broadcast_design(ts, regressors)
        Xf = _broadcast_design(ts, future_regressors)
        rho = jnp.asarray(self.arima_coeff)
        resid = self._residuals(ts, X)          # one residual pass serves
        point = self._point_from_resid(resid, Xf)      # point and bands
        u = resid[..., 1:] - rho[..., None] * resid[..., :-1]
        sigma_u2 = jnp.mean(u * u, axis=-1)
        j = jnp.arange(point.shape[-1], dtype=ts.dtype)
        # (ρ²)^j keeps the pow base non-negative (TPU-safe for ρ < 0)
        var_h = sigma_u2[..., None] \
            * jnp.cumsum((rho * rho)[..., None] ** j, axis=-1)
        half = normal_quantile(conf, ts.dtype) * jnp.sqrt(var_h)
        return point, point - half, point + half


@_metrics.instrument_fit("regression_arima", record=False)
def fit(ts: jnp.ndarray, regressors: jnp.ndarray, method: str,
        *optimization_args) -> RegressionARIMAModel:
    """Method dispatch (ref ``RegressionARIMA.scala:35-59``); currently
    ``"cochrane-orcutt"`` with an optional max-iteration argument."""
    if method != "cochrane-orcutt":
        raise NotImplementedError(
            f'Regression ARIMA method "{method}" not defined.')
    if not optimization_args:
        return fit_cochrane_orcutt(ts, regressors)
    if not isinstance(optimization_args[0], int):
        raise ValueError(
            "Maximum iteration parameter to Cochrane-Orcutt must be integer")
    if len(optimization_args) > 1:
        raise ValueError(
            "Cochrane-Orcutt accepts at most one optimization argument "
            "(max_iter)")
    return fit_cochrane_orcutt(ts, regressors, optimization_args[0])


@_metrics.instrument_fit("regression_arima")
def fit_cochrane_orcutt(ts: jnp.ndarray, regressors: jnp.ndarray,
                        max_iter: int = 10) -> RegressionARIMAModel:
    """Iterative Cochrane-Orcutt (ref ``RegressionARIMA.scala:83-160``).

    ``ts (..., n)``; ``regressors (..., n, k)`` (a shared unbatched ``(n, k)``
    design broadcasts over the batch).  Every iteration solves one batched
    OLS; stopping (no residual autocorrelation by Durbin-Watson, rho
    converged, or ``max_iter``) is tracked per lane.
    """
    y = jnp.asarray(ts)
    X = jnp.asarray(regressors)
    if X.shape[-2] != y.shape[-1]:
        raise ValueError(
            f"regressors have {X.shape[-2]} rows which is not equal to time "
            f"series length {y.shape[-1]}")
    X = _broadcast_design(y, X)
    beta, resid, rho, finished, n_done = _co_loop(y, X, max_iter)
    diag = FitDiagnostics(finished, n_done,
                          jnp.sum(resid * resid, axis=-1))
    return RegressionARIMAModel(beta, (1, 0, 0), rho, diagnostics=diag)


@functools.partial(jax.jit, static_argnames=("max_iter",))
def _co_loop(y: jnp.ndarray, X: jnp.ndarray, max_iter: int):
    """The whole Cochrane-Orcutt iteration as ONE compiled while_loop:
    initial OLS, then per-step [rho re-estimate → transformed OLS →
    original-regression residuals → stopping rules], with per-lane
    freezing and an early exit once every lane is finished.  Exactly the
    reference's per-series recursion (``RegressionARIMA.scala:83-160``),
    panel-batched."""

    # Step 1: OLS y = a + B·X + e
    res = ols(X, y, add_intercept=True)
    beta0 = res.beta
    resid0 = res.residuals
    finished0 = ~_is_autocorrelated(resid0)
    rho0 = jnp.zeros(y.shape[:-1], y.dtype)
    n_done0 = jnp.zeros(y.shape[:-1], jnp.int32)

    def body(state):
        it, beta, resid, rho, finished, n_done = state
        n_done = n_done + (~finished).astype(jnp.int32)
        # rho from e_t = rho·e_{t-1} (no-intercept simple regression)
        e_prev, e_cur = resid[..., :-1], resid[..., 1:]
        rho_new = jnp.sum(e_prev * e_cur, axis=-1) / \
            jnp.sum(e_prev * e_prev, axis=-1)

        # transformed regression Y'_t = Y_t - rho·Y_{t-1}, X'_t likewise
        r = rho_new[..., None]
        y_dash = y[..., 1:] - r * y[..., :-1]
        x_dash = X[..., 1:, :] - rho_new[..., None, None] * X[..., :-1, :]
        tres = ols(x_dash, y_dash, add_intercept=True)
        beta_new = tres.beta.at[..., 0].set(
            tres.beta[..., 0] / (1.0 - rho_new))

        # residuals of the *original* regression under the new coefficients
        yhat = jnp.einsum("...nk,...k->...n", X, beta_new[..., 1:]) \
            + beta_new[..., :1]
        resid_new = y - yhat

        # stopping rules evaluated on the executed iteration
        # (ref RegressionARIMA.scala:144-151)
        still_ar = _is_autocorrelated(tres.residuals)
        rhos_converged = (it >= 1) & \
            (jnp.abs(rho_new - rho) <= RHO_DIFF_THRESHOLD)
        now_finished = ~still_ar | rhos_converged

        # frozen lanes keep their values
        upd = ~finished
        beta = jnp.where(upd[..., None], beta_new, beta)
        resid = jnp.where(upd[..., None], resid_new, resid)
        rho = jnp.where(upd, rho_new, rho)
        return (it + 1, beta, resid, rho, finished | now_finished, n_done)

    def cond(state):
        it, finished = state[0], state[4]
        return jnp.logical_and(it < max_iter, ~jnp.all(finished))

    state = jax.lax.while_loop(
        cond, body,
        (jnp.asarray(0), beta0, resid0, rho0, finished0, n_done0))
    _, beta, resid, rho, finished, n_done = state
    return beta, resid, rho, finished, n_done


@_metrics.instrument_fit("regression_arima", record=False)
def fit_panel(panel, regressors, max_iter: int = 10) -> RegressionARIMAModel:
    """Batched Cochrane-Orcutt over a Panel against a shared regressor
    design."""
    return fit_cochrane_orcutt(panel.values, regressors, max_iter)


def _plain_ols_model(v: jnp.ndarray, X: jnp.ndarray) -> RegressionARIMAModel:
    """Terminal fallback: the plain OLS regression with ρ = 0 (no error
    autocorrelation modeled) — always defined where the design is."""
    Xb = _broadcast_design(v, X)
    res = ols(Xb, v, add_intercept=True)
    ok = jnp.all(jnp.isfinite(res.beta), axis=-1)
    diag = FitDiagnostics(ok, jnp.zeros(ok.shape, jnp.int32),
                          jnp.sum(res.residuals * res.residuals, axis=-1))
    return RegressionARIMAModel(res.beta, (1, 0, 0),
                                jnp.zeros(v.shape[:-1], v.dtype),
                                diagnostics=diag)


@_metrics.instrument_fit("regression_arima", record=False,
                         name="regression_arima.fit_resilient")
def fit_resilient(ts: jnp.ndarray, regressors: jnp.ndarray,
                  max_iter: int = 10, retry=None):
    """Fail-soft batched Cochrane-Orcutt: the iterative fit → plain OLS
    with ρ = 0 for lanes whose iteration never settled.  ``ts
    (n_series, n)``; ``regressors`` must be a shared unbatched ``(n, k)``
    design.  Returns ``(model, FitOutcome)``."""
    del retry       # the CO iteration has its own per-lane stopping rules
    X = jnp.asarray(regressors)
    if X.ndim != 2:
        raise ValueError(
            "fit_resilient needs a shared unbatched (n, k) design; got "
            f"regressors shape {X.shape}")
    chain = [
        ("cochrane_orcutt",
         lambda v: fit_cochrane_orcutt.__wrapped__(v, X, max_iter)),
        ("ols", lambda v: _plain_ols_model(v, X)),
    ]
    return _resilience.resilient_fit(ts, chain, min_len=X.shape[-1] + 3,
                                     family="regression_arima")
