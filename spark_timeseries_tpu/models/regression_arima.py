"""Regression with ARIMA (AR(1)) error structure — Cochrane-Orcutt, batched.

Capability parity with the reference's ``RegressionARIMA``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/RegressionARIMA.scala:34-201``):
``Y_t = B·X_t + e_t`` with ``e_t = rho·e_{t-1} + w_t``; iterative
Cochrane-Orcutt estimation driven by a Durbin-Watson autocorrelation check,
rho-convergence threshold 0.001, and the same stopping rules.

TPU-native design: the reference iterates per series with scalar OLS; here
every iteration is a batched OLS over the whole panel, with per-lane
``finished`` masks freezing converged series (SURVEY.md §7 hard part #3) —
the loop runs the fixed ``max_iter`` bound and masking reproduces the
data-dependent early exit.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

from ..ops.linalg import ols
from ..stats import dwtest
from .base import FitDiagnostics

DW_MARGIN = 0.05
RHO_DIFF_THRESHOLD = 0.001


def _is_autocorrelated(residuals: jnp.ndarray) -> jnp.ndarray:
    """Durbin-Watson statistic outside 2 ± 0.05
    (ref ``RegressionARIMA.scala:163-176``)."""
    dw = dwtest(residuals)
    return (dw <= 2.0 - DW_MARGIN) | (dw >= 2.0 + DW_MARGIN)


class RegressionARIMAModel(NamedTuple):
    """(ref ``RegressionARIMA.scala:180-201``); ``regression_coeff`` holds
    the intercept then the K regressor coefficients; ``arima_orders`` is
    (p, d, q) = (1, 0, 0); ``arima_coeff`` the AR(1) rho."""
    regression_coeff: jnp.ndarray
    arima_orders: Tuple[int, int, int]
    arima_coeff: jnp.ndarray
    diagnostics: Optional[FitDiagnostics] = None

    def add_time_dependent_effects(self, ts):
        raise NotImplementedError(
            "unsupported in the reference too (RegressionARIMA.scala:186-191)")

    def remove_time_dependent_effects(self, ts):
        raise NotImplementedError(
            "unsupported in the reference too (RegressionARIMA.scala:193-198)")


def fit(ts: jnp.ndarray, regressors: jnp.ndarray, method: str,
        *optimization_args) -> RegressionARIMAModel:
    """Method dispatch (ref ``RegressionARIMA.scala:35-59``); currently
    ``"cochrane-orcutt"`` with an optional max-iteration argument."""
    if method != "cochrane-orcutt":
        raise NotImplementedError(
            f'Regression ARIMA method "{method}" not defined.')
    if not optimization_args:
        return fit_cochrane_orcutt(ts, regressors)
    if not isinstance(optimization_args[0], int):
        raise ValueError(
            "Maximum iteration parameter to Cochrane-Orcutt must be integer")
    if len(optimization_args) > 1:
        raise ValueError(
            "Cochrane-Orcutt accepts at most one optimization argument "
            "(max_iter)")
    return fit_cochrane_orcutt(ts, regressors, optimization_args[0])


def fit_cochrane_orcutt(ts: jnp.ndarray, regressors: jnp.ndarray,
                        max_iter: int = 10) -> RegressionARIMAModel:
    """Iterative Cochrane-Orcutt (ref ``RegressionARIMA.scala:83-160``).

    ``ts (..., n)``; ``regressors (..., n, k)`` (a shared unbatched ``(n, k)``
    design broadcasts over the batch).  Every iteration solves one batched
    OLS; stopping (no residual autocorrelation by Durbin-Watson, rho
    converged, or ``max_iter``) is tracked per lane.
    """
    y = jnp.asarray(ts)
    X = jnp.asarray(regressors)
    if X.shape[-2] != y.shape[-1]:
        raise ValueError(
            f"regressors have {X.shape[-2]} rows which is not equal to time "
            f"series length {y.shape[-1]}")
    if y.ndim > 1 and X.ndim == 2:
        X = jnp.broadcast_to(X, (*y.shape[:-1], *X.shape))

    # Step 1: OLS y = a + B·X + e
    res = ols(X, y, add_intercept=True)
    beta = res.beta
    resid = res.residuals

    finished = ~_is_autocorrelated(resid)
    rho = jnp.zeros(y.shape[:-1], y.dtype)
    n_done = jnp.zeros(y.shape[:-1], jnp.int32)

    for it in range(max_iter):
        n_done = n_done + (~finished).astype(jnp.int32)
        # rho from e_t = rho·e_{t-1} (no-intercept simple regression)
        e_prev, e_cur = resid[..., :-1], resid[..., 1:]
        rho_new = jnp.sum(e_prev * e_cur, axis=-1) / \
            jnp.sum(e_prev * e_prev, axis=-1)

        # transformed regression Y'_t = Y_t - rho·Y_{t-1}, X'_t likewise
        r = rho_new[..., None]
        y_dash = y[..., 1:] - r * y[..., :-1]
        x_dash = X[..., 1:, :] - rho_new[..., None, None] * X[..., :-1, :]
        tres = ols(x_dash, y_dash, add_intercept=True)
        beta_new = tres.beta.at[..., 0].set(
            tres.beta[..., 0] / (1.0 - rho_new))

        # residuals of the *original* regression under the new coefficients
        yhat = jnp.einsum("...nk,...k->...n", X, beta_new[..., 1:]) \
            + beta_new[..., :1]
        resid_new = y - yhat

        # stopping rules evaluated on the executed iteration
        # (ref RegressionARIMA.scala:144-151)
        still_ar = _is_autocorrelated(tres.residuals)
        rhos_converged = jnp.asarray(it >= 1) & \
            (jnp.abs(rho_new - rho) <= RHO_DIFF_THRESHOLD)
        now_finished = ~still_ar | rhos_converged

        # frozen lanes keep their values
        upd = ~finished
        beta = jnp.where(upd[..., None], beta_new, beta)
        resid = jnp.where(upd[..., None], resid_new, resid)
        rho = jnp.where(upd, rho_new, rho)
        finished = finished | now_finished

    diag = FitDiagnostics(finished, n_done,
                          jnp.sum(resid * resid, axis=-1))
    return RegressionARIMAModel(beta, (1, 0, 0), rho, diagnostics=diag)


def fit_panel(panel, regressors, max_iter: int = 10) -> RegressionARIMAModel:
    """Batched Cochrane-Orcutt over a Panel against a shared regressor
    design."""
    return fit_cochrane_orcutt(panel.values, regressors, max_iter)
