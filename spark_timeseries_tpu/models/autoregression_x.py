"""ARX: autoregression with exogenous regressors, batched.

Capability parity with the reference's ``AutoregressionX``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/AutoregressionX.scala:27-131``):
OLS on ``[lagged y ‖ lagged X ‖ current X]`` with the reference's column
ordering and trimming conventions.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from ..ops.lag import lag_matrix, lag_matrix_multi
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from ..ops.linalg import ols
from .base import FitDiagnostics


def _empty_cols(x: jnp.ndarray, rows: int) -> jnp.ndarray:
    return jnp.zeros((*x.shape[:-1], rows, 0), x.dtype)


def assemble_predictors(y: jnp.ndarray, x: jnp.ndarray, y_max_lag: int,
                        x_max_lag: int,
                        include_original_x: bool = True) -> jnp.ndarray:
    """Design matrix ``(..., n - maxLag, cols)`` in the reference's column
    order: AR lags of y, per-column lags of x, then current x
    (ref ``AutoregressionX.scala:71-92``)."""
    n = y.shape[-1]
    max_lag = max(y_max_lag, x_max_lag)
    rows = n - max_lag

    # a shared unbatched design x (n, k) broadcasts over y's batch dims
    # (and vice versa) so the column concat below sees uniform ranks
    batch = jnp.broadcast_shapes(y.shape[:-1], x.shape[:-2])
    y = jnp.broadcast_to(y, (*batch, n))
    x = jnp.broadcast_to(x, (*batch, *x.shape[-2:]))

    if y_max_lag > 0:
        ar_y = lag_matrix(y, y_max_lag)[..., max_lag - y_max_lag:, :]
    else:
        ar_y = _empty_cols(y, rows)

    if x_max_lag > 0:
        lagged_x = lag_matrix_multi(x, x_max_lag)[..., max_lag - x_max_lag:, :]
    else:
        lagged_x = _empty_cols(y, rows)

    parts = [ar_y, lagged_x]
    if include_original_x:
        parts.append(x[..., max_lag:, :])
    return jnp.concatenate(parts, axis=-1)


class ARXModel(NamedTuple):
    """Coefficient order matches the reference (ref
    ``AutoregressionX.scala:100-111``): y lags ascending, then per-x-column
    lags ascending, then non-lagged x columns."""
    c: jnp.ndarray
    coefficients: jnp.ndarray
    y_max_lag: int
    x_max_lag: int
    includes_original_x: bool
    diagnostics: Optional[FitDiagnostics] = None

    def predict(self, y: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
        """(ref ``AutoregressionX.scala:117-130``) — one batched matvec."""
        predictors = assemble_predictors(y, x, self.y_max_lag, self.x_max_lag,
                                         self.includes_original_x)
        out = jnp.einsum("...nk,...k->...n", predictors,
                         jnp.asarray(self.coefficients))
        c = jnp.asarray(self.c)
        return out + (c[..., None] if c.ndim else c)


@_metrics.instrument_fit("arx")
def fit(y: jnp.ndarray, x: jnp.ndarray, y_max_lag: int, x_max_lag: int,
        include_original_x: bool = True, no_intercept: bool = False) -> ARXModel:
    """OLS fit (ref ``AutoregressionX.scala:48-68``).  ``y (..., n)``,
    ``x (..., n, k)``; leading dims batch through one QR solve."""
    y = jnp.asarray(y)
    x = jnp.asarray(x)
    max_lag = max(y_max_lag, x_max_lag)
    trim_y = y[..., max_lag:]
    predictors = assemble_predictors(y, x, y_max_lag, x_max_lag,
                                     include_original_x)
    res = ols(predictors, trim_y, add_intercept=not no_intercept)
    if no_intercept:
        c = jnp.zeros(y.shape[:-1], y.dtype)
        coeffs = res.beta
    else:
        c, coeffs = res.beta[..., 0], res.beta[..., 1:]
    ok = jnp.all(jnp.isfinite(res.beta), axis=-1)
    diag = FitDiagnostics(ok, jnp.zeros(ok.shape, jnp.int32),
                          jnp.where(ok, 0.0, jnp.nan).astype(y.dtype))
    return ARXModel(c, coeffs, y_max_lag, x_max_lag, include_original_x,
                    diagnostics=diag)


def _n_arx_coefs(k: int, y_max_lag: int, x_max_lag: int,
                 include_original_x: bool) -> int:
    return y_max_lag + k * x_max_lag + (k if include_original_x else 0)


def _mean_model(v: jnp.ndarray, k: int, y_max_lag: int, x_max_lag: int,
                include_original_x: bool) -> ARXModel:
    """Terminal fallback: intercept-only (every AR and exogenous
    coefficient zero); NaN padding on ragged lanes is ignored."""
    c = jnp.nanmean(v, axis=-1)
    ok = jnp.isfinite(c)
    width = _n_arx_coefs(k, y_max_lag, x_max_lag, include_original_x)
    return ARXModel(c, jnp.zeros((*v.shape[:-1], width), v.dtype),
                    y_max_lag, x_max_lag, include_original_x,
                    diagnostics=FitDiagnostics(
                        ok, jnp.zeros(ok.shape, jnp.int32),
                        jnp.where(ok, 0.0, jnp.nan).astype(v.dtype)))


@_metrics.instrument_fit("arx", record=False, name="arx.fit_resilient")
def fit_resilient(y: jnp.ndarray, x: jnp.ndarray, y_max_lag: int,
                  x_max_lag: int, include_original_x: bool = True,
                  no_intercept: bool = False,
                  retry: Optional[_resilience.RetryPolicy] = None):
    """Fail-soft batched ARX: OLS → intercept-only mean model.  ``y
    (n_series, n)``; ``x`` must be a shared unbatched ``(n, k)`` design
    (a per-series design cannot be compacted alongside the panel).
    Returns ``(model, FitOutcome)``."""
    del retry
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(
            "fit_resilient needs a shared unbatched (n, k) design; got "
            f"xreg shape {x.shape}")
    k = x.shape[-1]
    chain = [
        ("ols", lambda v: fit.__wrapped__(v, x, y_max_lag, x_max_lag,
                                          include_original_x, no_intercept)),
        ("mean", lambda v: _mean_model(v, k, y_max_lag, x_max_lag,
                                       include_original_x)),
    ]
    min_len = max(y_max_lag, x_max_lag) \
        + _n_arx_coefs(k, y_max_lag, x_max_lag, include_original_x) + 2
    return _resilience.resilient_fit(y, chain, min_len=min_len, family="arx")
