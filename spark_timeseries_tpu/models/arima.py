"""ARIMA(p, d, q) models, batched.

Capability parity with the reference's ``ARIMA``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMA.scala:54-831``):
Hannan-Rissanen initialization, conditional-sum-of-squares maximum likelihood,
add/remove time-dependent effects, sampling, forecasting with d-order
integration unwinding, stationarity/invertibility root checks, ``approxAIC``,
and Hyndman-Khandakar automatic order selection.

TPU-native design (SURVEY.md §7):

- The ``iterateARMA`` sequential recurrence (ref ``ARIMA.scala:581-618``)
  becomes a ``lax.scan`` carrying a length-``q`` MA-error ring buffer; the AR
  contribution is precomputed as one lag-matrix matvec (an MXU matmul over the
  batch) so the scan carry stays minimal.
- The hand-derived CSS gradient (ref ``ARIMA.scala:465-534``) is replaced by
  autodiff through the scan.
- The per-series Commons-Math optimizer loop becomes a batched BFGS solve
  (``css-cgd`` analog) with a projected-gradient fallback (``css-bobyqa``
  analog — the reference's BOBYQA call is *unbounded*, ref ``ARIMA.scala:156``,
  so its role here is robustness, not bounds).
- ``auto_fit_panel`` trades FLOPs for uniformity: instead of a data-dependent
  per-series stepwise search, the whole (p, q) candidate grid is fitted for
  every series in batched solves and the winner selected by AIC with masks.
"""

from __future__ import annotations

import os
import warnings
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.lag import lag_matvec, lag_stack
from ..ops.linalg import ols_gram, spd_solve
from ..ops.ragged import (apply_short_quarantine, ragged_view, short_lanes,
                          step_weights)
from ..ops.optimize import (MinimizeResult, minimize_bfgs, minimize_box,
                            minimize_least_squares)
from ..ops.univariate import (differences_of_order_d,
                              inverse_differences_of_order_d)
from ..stats import KPSS_CONSTANT_CRITICAL_VALUES, kpsstest
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from . import autoregression
from .base import (FitDiagnostics, diagnostics_from, normal_quantile,
                   scan_unroll)


# ---------------------------------------------------------------------------
# parameter layout helpers (coefficients = [intercept?, AR..., MA...],
# ref ARIMA.scala:406 "intercept, AR, MA, with increasing degrees")
# ---------------------------------------------------------------------------

def _split_params(params: jnp.ndarray, p: int, q: int, icpt: int):
    """Split a ``(..., icpt+p+q)`` coefficient vector into (c, phi, theta)."""
    if icpt:
        c = params[..., 0]
    else:
        c = jnp.zeros(params.shape[:-1], params.dtype)
    phi = params[..., icpt:icpt + p]
    theta = params[..., icpt + p:icpt + p + q]
    return c, phi, theta


def _lag_stack_or_empty(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """``lag_stack`` that tolerates ``k == 0`` (returns ``(..., 0, n)``)."""
    if k == 0:
        return jnp.zeros((*x.shape[:-1], 0, x.shape[-1]), x.dtype)
    return lag_stack(x, k)


# ---------------------------------------------------------------------------
# core recurrences (single series; public methods vmap over the batch)
# ---------------------------------------------------------------------------

def _one_step_errors(params: jnp.ndarray, y: jnp.ndarray,
                     p: int, q: int, icpt: int):
    """One-step-ahead fitted values and errors for t >= max(p, q).

    The gold-standard mode of the reference's ``iterateARMA``
    (ref ``ARIMA.scala:581-618`` with ``goldStandard = ts``): AR terms read the
    observed series (precomputed as a lag-matrix matvec); MA terms feed back
    one-step errors through a ``lax.scan`` ring carry.

    Returns ``(yhat, err)``, each of length ``n - max(p, q)``.
    """
    n = y.shape[-1]
    c, phi, theta = _split_params(params, p, q, icpt)
    max_lag = max(p, q)

    if p > 0:
        base = c + lag_matvec(y, phi, p)           # t = p .. n-1
        base = base[max_lag - p:]                  # t = max_lag .. n-1
    else:
        base = jnp.full((n - max_lag,), c, y.dtype)
    y_t = y[max_lag:]

    if q == 0:
        return base, y_t - base

    def step(errs, inp):
        b, yt = inp
        yhat = b + theta @ errs
        e = yt - yhat
        return jnp.concatenate([e[None], errs[:-1]]), (yhat, e)

    errs0 = jnp.zeros((q,), y.dtype)
    _, (yhat, err) = lax.scan(step, errs0, (base, y_t),
                              unroll=scan_unroll())
    return yhat, err


def _arma_normal_eqs(params: jnp.ndarray, y: jnp.ndarray,
                     p: int, q: int, icpt: int,
                     mask: Optional[jnp.ndarray] = None,
                     n_valid: Optional[jnp.ndarray] = None):
    """Hand-fused Gauss-Newton normal equations for the CSS residuals:
    one scan computes ``(JᵀJ, Jᵀr, sse)`` with the accumulators in the
    carry, never materializing the ``(k, m)`` Jacobian.

    Same residuals as :func:`_one_step_errors`; the Jacobian row follows
    from differentiating the recurrence — with
    ``ŷ_t = c + φ·y_lags + θ·e_ring`` and ``e_t = y_t - ŷ_t``,

        T_t ≡ ∂e_t/∂x = -u_t - Σ_j θ_j T_{t-j},
        u_t = (1 if icpt, y_{t-1..t-p}, e_{t-1..t-q}),

    so ``JᵀJ += T Tᵀ``, ``Jᵀr += T e``, ``sse += e²`` accumulate per step.
    Replacing the autodiff (linearize) pass with this cuts the pass's HBM
    traffic ~4x and measures 1.8x faster at the bench chunk shape
    (16.2 -> 9.2 ms at 131072x128 f32, v5e) — see docs/design.md §9b.

    ``mask`` (k,) reproduces the masked-residual objective
    ``r(x ∘ mask)``: the recurrence runs at the masked point and the
    chain-rule factor lands as an outer-product scale at the end.

    ``n_valid`` (scalar) restricts the lane to its left-aligned valid
    window (``ops.ragged``): steps at absolute index ≥ ``n_valid`` get
    weight 0 on the residual and its tangent, so the accumulators — and
    the weighted values the rings carry — equal those of the trimmed
    series exactly (the zero-padded tail never contributes).
    """
    dtype = y.dtype
    k = icpt + p + q
    if mask is not None:
        params = params * mask
    c, phi, theta = _split_params(params, p, q, icpt)
    max_lag = max(p, q)

    if p > 0:
        base = c + lag_matvec(y, phi, p)
        base = base[max_lag - p:]
    else:
        base = jnp.full((y.shape[-1] - max_lag,), c, dtype)
    y_t = y[max_lag:]
    # newest-first y lags at the first step: y[max_lag-1], ..., y[max_lag-p]
    y_ring0 = y[max_lag - p:max_lag][::-1]

    def step(carry, inp):
        e_ring, y_ring, T_ring, jtj, jtr, sse = carry
        if n_valid is None:
            b_t, yy = inp
        else:
            b_t, yy, w = inp
        e = yy - b_t - (theta @ e_ring if q else jnp.zeros((), dtype))
        u_parts = []
        if icpt:
            u_parts.append(jnp.ones((1,), dtype))
        u_parts += [y_ring, e_ring]
        u = jnp.concatenate(u_parts)
        T = -u - (theta @ T_ring if q else jnp.zeros((k,), dtype))
        if n_valid is not None:
            e = w * e
            T = w * T
        jtj = jtj + jnp.outer(T, T)
        jtr = jtr + T * e
        sse = sse + e * e
        if q:
            e_ring = jnp.concatenate([e[None], e_ring[:-1]])
            T_ring = jnp.concatenate([T[None], T_ring[:-1]])
        if p:
            y_ring = jnp.concatenate([yy[None], y_ring[:-1]])
        return (e_ring, y_ring, T_ring, jtj, jtr, sse), None

    if n_valid is None:
        xs = (base, y_t)
    else:
        ws = step_weights(y_t.shape[-1], n_valid, offset=max_lag,
                          dtype=dtype)
        xs = (base, y_t, ws)
    carry0 = (jnp.zeros((q,), dtype), y_ring0,
              jnp.zeros((q, k), dtype), jnp.zeros((k, k), dtype),
              jnp.zeros((k,), dtype), jnp.zeros((), dtype))
    (_, _, _, jtj, jtr, sse), _ = lax.scan(step, carry0, xs,
                                           unroll=scan_unroll())
    if mask is not None:
        jtj = jtj * jnp.outer(mask, mask)
        jtr = jtr * mask
    return jtj, jtr, sse


def _log_likelihood_css_arma(params: jnp.ndarray, diffed: jnp.ndarray,
                             p: int, q: int, icpt: int,
                             n_valid: Optional[jnp.ndarray] = None
                             ) -> jnp.ndarray:
    """CSS log likelihood of an ARMA(p, q) on an already-differenced series
    (ref ``ARIMA.scala:430-445``): residuals for t < max(p, q) are dropped,
    ``sigma² = css / n``.

    Deliberate deviation (like the other documented reference-bug fixes):
    the leading factor is the real ``-n / 2.0`` — the reference's
    ``-n / 2`` is Scala *integer* division (``ARIMA.scala:444``), so for
    odd-length series its likelihood (and ``approxAIC``) is off by
    ``0.5·log(2π·sigma²)``; model-selection thresholds tuned against
    reference AIC values can differ by that amount.

    ``n_valid`` (scalar): valid-window length of a left-aligned ragged
    lane (``ops.ragged``) — residuals past it get weight 0 and the
    divisor becomes ``n_valid``, matching the trimmed series."""
    _, err = _one_step_errors(params, diffed, p, q, icpt)
    if n_valid is None:
        n_eff = diffed.shape[-1]
        css = jnp.sum(err * err)
    else:
        w = step_weights(err.shape[-1], n_valid, offset=max(p, q),
                         dtype=diffed.dtype)
        n_eff = jnp.asarray(n_valid, diffed.dtype)
        css = jnp.sum(w * err * err)
    sigma2 = css / n_eff
    return (-n_eff / 2.0) * jnp.log(2.0 * jnp.pi * sigma2) \
        - css / (2.0 * sigma2)


def _remove_effects_one(params: jnp.ndarray, ts: jnp.ndarray,
                        p: int, d: int, q: int, icpt: int) -> jnp.ndarray:
    """Recover the underlying errors from an ARIMA(p, d, q) realization
    (ref ``ARIMA.scala:627-647``): difference, left-extend ``max(p, q)``
    entries equal to the intercept, then invert the ARMA recurrence — the
    recovered error at t feeds the MA terms of later steps."""
    c, phi, theta = _split_params(params, p, q, icpt)
    max_lag = max(p, q)
    diffed = differences_of_order_d(ts, d)
    ext = jnp.concatenate(
        [jnp.full((max_lag,), c, ts.dtype), diffed])

    # AR part reads the *input* series -> precomputable
    if p > 0:
        ar_part = lag_matvec(ext, phi, p)[max_lag - p:]
    else:
        ar_part = jnp.zeros(ext.shape[-1] - max_lag, ts.dtype)
    base = ext[max_lag:] - c - ar_part

    if q == 0:
        return base

    def step(errs, b):
        out = b - theta @ errs
        return jnp.concatenate([out[None], errs[:-1]]), out

    _, out = lax.scan(step, jnp.zeros((q,), ts.dtype), base,
                      unroll=scan_unroll())
    return out


def _add_effects_one(params: jnp.ndarray, ts: jnp.ndarray,
                     p: int, d: int, q: int, icpt: int) -> jnp.ndarray:
    """Overlay ARIMA(p, d, q) structure on i.i.d. draws
    (ref ``ARIMA.scala:655-668``): prior AR values equal the intercept, prior
    MA errors are zero; the MA terms consume the *input* errors (which are
    known up front, so only the AR output feedback needs a scan carry), and
    the result is inverse-differenced ``d`` times."""
    c, phi, theta = _split_params(params, p, q, icpt)
    max_lag = max(p, q)
    n = ts.shape[-1]

    # error at extended index k is 0 for k < max_lag (never pushed into the
    # ring before iteration starts), ts[k - max_lag] after
    if q > 0:
        e_pad = jnp.concatenate([jnp.zeros((max_lag,), ts.dtype), ts])
        ma_part = lag_matvec(e_pad, theta, q)[max_lag - q:]
    else:
        ma_part = jnp.zeros((n,), ts.dtype)
    drive = ts + c + ma_part

    if p == 0:
        out = drive
    else:
        def step(recent, d_t):
            out_t = d_t + phi @ recent
            return jnp.concatenate([out_t[None], recent[:-1]]), out_t

        recent0 = jnp.full((p,), c, ts.dtype)
        _, out = lax.scan(step, recent0, drive, unroll=scan_unroll())

    return inverse_differences_of_order_d(out, d)


def _difference_rows(ts: jnp.ndarray, d: int) -> jnp.ndarray:
    """Rows 0..d-1 of incremental differences; row ``i`` holds the proper
    i-th order difference from index ``i`` on (zeros before).  Unlike the
    size-preserving ``differences_of_order_d`` (whose copied first element
    would leak a raw value into row i at index i — the artifact the
    reference's ``diffMatrix`` carries into its first re-levelled step,
    ``ARIMA.scala:735-744``), every retained entry here is a true
    difference."""
    rows = [ts]
    for i in range(1, d):
        prev = rows[i - 1]
        rows.append(jnp.concatenate(
            [jnp.zeros((i,), ts.dtype), prev[i:] - prev[i - 1:-1]]))
    return jnp.stack(rows)


def _forecast_one(params: jnp.ndarray, ts: jnp.ndarray, n_future: int,
                  p: int, d: int, q: int, icpt: int) -> jnp.ndarray:
    """1-step-ahead fitted historicals + ``n_future`` forecast periods
    (ref ``ARIMA.scala:696-764``), including the d-order integration
    unwinding through the incremental-differences matrix.

    Deviation from the reference: the initial MA error buffer for the
    forward pass is ordered newest-first (``maTerms[j]`` = error at
    ``t-j-1``), matching ``iterateARMA``'s own convention — the reference
    fills it oldest-first (``ARIMA.scala:726-728``), which misorders the
    buffer whenever ``q > 1``.
    """
    c, phi, theta = _split_params(params, p, q, icpt)
    max_lag = max(p, q)
    n = ts.shape[-1]

    diffed = differences_of_order_d(ts, d)[d:]
    ext = jnp.concatenate([jnp.full((max_lag,), c, ts.dtype), diffed])
    hist_len = ext.shape[-1]

    yhat, err = _one_step_errors(params, ext, p, q, icpt)
    hist = jnp.concatenate([jnp.zeros((max_lag,), ts.dtype), yhat])

    # forward pass: future errors are zero, AR terms read prior predictions
    if q > 0:
        # newest-first: error at hist_len-1, hist_len-2, ...
        errs0 = (ext - hist)[::-1][:q]
    else:
        errs0 = jnp.zeros((0,), ts.dtype)
    recent0 = hist[::-1][:p] if p > 0 else jnp.zeros((0,), ts.dtype)

    def fwd_step(carry, _):
        recent, errs = carry
        out = c + phi @ recent + theta @ errs
        if p > 0:
            recent = jnp.concatenate([out[None], recent[:-1]])
        if q > 0:
            errs = jnp.concatenate([jnp.zeros((1,), ts.dtype), errs[:-1]])
        return (recent, errs), out

    (_, _), fwd = lax.scan(fwd_step, (recent0, errs0), None, length=n_future,
                           unroll=scan_unroll())

    results = jnp.zeros((n + n_future,), ts.dtype)
    results = results.at[:d].set(ts[:d])
    results = results.at[d:n].set(hist[max_lag:])
    results = results.at[n:].set(fwd)

    if d != 0:
        # incremental differences of order 0..d-1 (ref ARIMA.scala:735-744,
        # with proper differences at the boundary — see _difference_rows)
        diff_matrix = _difference_rows(ts, d)                # (d, n)

        # historical 1-step-ahead forecasts for the integrated series
        # (ref ARIMA.scala:747-753)
        i_idx = jnp.arange(d, hist_len - max_lag)
        level = jnp.sum(diff_matrix, axis=0)                 # col sums rows<d
        hist_fit = level[i_idx - 1] + hist[max_lag + i_idx]
        results = results.at[d:hist_len - max_lag].set(hist_fit)

        # unwind the forward curve through the last d incremental differences
        # (ref ARIMA.scala:755-763)
        prev_terms = jnp.diagonal(diff_matrix[:, n - d:])    # (d,)
        fwd_integrated = inverse_differences_of_order_d(
            jnp.concatenate([prev_terms, fwd]), d)
        results = results.at[n - d:].set(fwd_integrated)
    return results


def _psi_half_widths(params: jnp.ndarray, ts: jnp.ndarray, h: int,
                     p: int, d: int, q: int, icpt: int,
                     conf: float) -> jnp.ndarray:
    """Half-widths of symmetric ``conf`` forecast bands for horizons 1..h —
    beyond-reference capability (the reference's forecast returns point
    values only, ``ARIMA.scala:696-764``).

    Standard psi-weight construction: the ARIMA(p,d,q) process has MA(∞)
    weights ``ψ_j`` from the *nonstationary* AR polynomial
    ``φ*(B) = φ(B)(1-B)^d``; the h-step forecast error variance is
    ``σ² Σ_{j<h} ψ_j²`` with σ² estimated from the one-step CSS residuals
    (so a d>0 model's bands correctly widen without bound).  All static
    shapes; the ψ recursion is a ``lax.scan`` with a (p+d) ring carry.
    """
    import math

    c, phi, theta = _split_params(params, p, q, icpt)
    # σ² from the CSS residual convention: the t < max(p, q) burn-in is
    # dropped from the sum but the divisor is the FULL differenced length,
    # exactly σ² = css/n as _log_likelihood_css_arma (and the reference,
    # ARIMA.scala:430-445) computes it.  This is a second O(n) scan on top
    # of forecast()'s own; acceptable because forecasting is off the hot
    # fit path.
    diffed = differences_of_order_d(ts, d)[d:]
    _, err = _one_step_errors(params, diffed, p, q, icpt)
    sigma2 = jnp.sum(err * err) / diffed.shape[-1]

    # φ*(B) = φ(B)(1-B)^d as 1 - Σ a_j B^j, j = 1..p+d
    binom = jnp.asarray([math.comb(d, k) * (-1.0) ** k
                         for k in range(d + 1)], ts.dtype)
    ar_star = jnp.convolve(
        jnp.concatenate([jnp.ones((1,), ts.dtype), -phi]), binom)
    a = -ar_star[1:]                                   # (p+d,)

    th = jnp.zeros((h,), ts.dtype)
    k = min(q, h - 1)
    if k:
        th = th.at[1:1 + k].set(theta[:k])

    m = p + d
    if m:
        buf0 = jnp.zeros((m,), ts.dtype).at[0].set(1.0)

        def step(buf, th_j):
            # ψ_j = θ_j + Σ_i a_i ψ_{j-i}; buf is newest-first ψ_{j-1..j-m}
            psi_j = th_j + a @ buf
            return jnp.concatenate([psi_j[None], buf[:-1]]), psi_j

        _, rest = lax.scan(step, buf0, th[1:], unroll=scan_unroll())
    else:
        rest = th[1:]
    psis = jnp.concatenate([jnp.ones((1,), ts.dtype), rest])

    var_h = sigma2 * jnp.cumsum(psis * psis)
    return normal_quantile(conf, ts.dtype) * jnp.sqrt(var_h)


def ar_truncation(c: jnp.ndarray, phi: jnp.ndarray, theta: jnp.ndarray,
                  n_terms: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Truncated AR(∞) representation of a (batched) ARMA.

    With the fit's sign conventions (``y_t = c + Σφ_i y_{t-i} + e_t +
    Σθ_i e_{t-i}``), the AR polynomial ``Π(B) = φ(B)/θ(B) = 1 - Σπ_j Bʲ``
    satisfies ``φ(B) = Π(B)θ(B)``; matching coefficients of ``Bᵏ`` gives
    the recursion

        π_k = φ_k + θ_k - Σ_{i=1..min(k-1, q)} θ_i π_{k-i}

    (taps beyond the order are zero), and the AR-form intercept is
    ``c_pi = c / θ(1) = c / (1 + Σθ_i)`` (both forms share the process
    mean ``μ = c/φ(1) = c_pi/Π(1)``).  Truncation error decays at the MA
    root rate, so an invertible model's tail is geometric — the mapping
    every DARIMA segment estimate goes through before combination.

    ``phi (..., p)``, ``theta (..., q)``, ``c (...)``; returns
    ``(c_pi (...), pi (..., n_terms))``.  Fully traced (a ``lax.scan``
    with a length-``q`` ring carry), batched over leading dims.
    """
    phi = jnp.asarray(phi)
    theta = jnp.asarray(theta)
    dtype = phi.dtype
    c = jnp.asarray(c, dtype)
    batch = phi.shape[:-1]
    p, q = phi.shape[-1], theta.shape[-1]
    n_terms = int(n_terms)
    if n_terms < 1:
        raise ValueError(f"ar_truncation needs n_terms >= 1, got {n_terms}")

    def taps(x, k):
        if k >= n_terms:
            return x[..., :n_terms]
        return jnp.concatenate(
            [x, jnp.zeros((*batch, n_terms - k), dtype)], axis=-1)

    phi_ext = taps(phi, p)
    c_pi = c / (1.0 + jnp.sum(theta, axis=-1))
    if q == 0:
        return c_pi, phi_ext
    th_ext = taps(theta, q)

    def step(ring, inp):
        # ring is newest-first: π_{k-1} .. π_{k-q} (zeros for k-i < 1)
        ph_k, th_k = inp
        pi_k = ph_k + th_k - jnp.einsum("...q,...q->...", theta, ring)
        ring = jnp.concatenate([pi_k[..., None], ring[..., :-1]], axis=-1)
        return ring, pi_k

    ring0 = jnp.zeros((*batch, q), dtype)
    _, pis = lax.scan(step, ring0,
                      (jnp.moveaxis(phi_ext, -1, 0),
                       jnp.moveaxis(th_ext, -1, 0)),
                      unroll=scan_unroll())
    return c_pi, jnp.moveaxis(pis, 0, -1)


def _batched(fn_one, params: jnp.ndarray, ts: jnp.ndarray, *args):
    """vmap ``fn_one(params_1d, ts_1d, *args)`` over an optional shared
    leading batch dim of ``params`` / ``ts``."""
    p_b = params.ndim > 1
    t_b = ts.ndim > 1
    if not (p_b or t_b):
        return fn_one(params, ts, *args)
    in_axes = (0 if p_b else None, 0 if t_b else None) + (None,) * len(args)
    return jax.vmap(fn_one, in_axes=in_axes)(params, ts, *args)


# ---------------------------------------------------------------------------
# polynomial root checks (host-side; calendar-free but eig is not a TPU op)
# ---------------------------------------------------------------------------

def find_roots(coefficients: Sequence[float]) -> np.ndarray:
    """Roots of ``c[0] + c[1] x + ... + c[n] x^n`` via companion-matrix
    eigenvalues (ref ``ARIMA.scala:381-399``).  Host-side numpy — off the
    hot path, used only for stationarity/invertibility screening."""
    coefficients = np.asarray(coefficients, dtype=np.float64)
    n = coefficients.shape[-1] - 1
    if n < 1:
        return np.zeros((0,), dtype=np.complex128)
    # deliberate f64: companion-matrix eigenvalues want full precision
    # for the |root|<=1 screen; host-only, never enters traced code
    companion = np.zeros((n, n))                  # sts: noqa[STS004]
    companion[n - 1, :] = -coefficients[:n] / coefficients[n]
    if n > 1:
        companion[:n - 1, 1:] = np.eye(n - 1)     # sts: noqa[STS004]
    return np.linalg.eigvals(companion)


def _step_down_stationary(phi: jnp.ndarray, orders: jnp.ndarray
                          ) -> jnp.ndarray:
    """Batched stationarity via the Levinson step-down (Schur-Cohn) test —
    no eigendecompositions, so it scales to (candidates × series) batches,
    and traceable (static-shape unrolled recursion) so it can screen
    candidates on-device inside the fused auto-fit kernel.

    ``phi (..., max_p)`` padded AR coefficients, ``orders (...)`` the actual
    order per lane (coefficients beyond it are ignored).  The AR polynomial
    ``1 - φ₁z - ... - φ_p z^p`` has all roots outside the unit circle iff
    every reflection coefficient of the step-down recursion lies in (-1, 1)
    (same criterion the reference's eigenvalue check encodes,
    ref ``ARIMA.scala:798-815``).
    """
    phi = jnp.asarray(phi)
    orders = jnp.asarray(orders)
    max_p = phi.shape[-1]
    ok = jnp.ones(jnp.broadcast_shapes(phi.shape[:-1], orders.shape),
                  dtype=bool)
    if max_p == 0:
        return ok
    # zero-padded lanes: coefficients at index >= order are already zero for
    # fits produced here; mask anyway so stray values can't leak in
    idx = jnp.arange(max_p)
    phi = jnp.where(idx < orders[..., None], phi, 0.0)
    a = phi
    for m in range(max_p, 0, -1):
        k = a[..., m - 1]
        active = orders >= m
        ok &= ~active | (jnp.abs(k) < 1.0)
        # (1-k)(1+k) instead of 1-k²: near-unit-root lanes (|k|→1) keep
        # their leading digits in float32, where the squared form cancels
        # catastrophically (this screen runs in the panel dtype on TPU)
        denom = (1.0 - k) * (1.0 + k)
        safe = jnp.where(jnp.abs(denom) < 1e-12, 1.0, denom)
        lower = (a[..., :m - 1] + k[..., None] * a[..., m - 2::-1]) \
            / safe[..., None] if m > 1 else a[..., :0]
        a = jnp.concatenate([jnp.where(active[..., None], lower,
                                       a[..., :m - 1]),
                             jnp.zeros_like(a[..., m - 1:])], axis=-1)
    return ok


def _all_roots_outside_unit_circle(polys: np.ndarray) -> np.ndarray:
    """Batched check that every root of each ascending-coefficient polynomial
    lies outside the unit circle (ref ``ARIMA.scala:798-815``).

    ``polys (..., k+1)`` -> bool ``(...)``.  One batched ``eigvals`` over
    stacked companion matrices instead of a per-series loop.
    """
    polys = np.asarray(polys, dtype=np.float64)
    batch = polys.shape[:-1]
    k = polys.shape[-1] - 1
    if k < 1:
        return np.ones(batch, dtype=bool)
    flat = polys.reshape(-1, k + 1)
    ok = np.ones(flat.shape[0], dtype=bool)
    # a zero leading coefficient means the polynomial's effective degree is
    # lower (e.g. an exactly-zero trailing AR coefficient) — dividing by it
    # poisons the companion matrix and eigvals raises on non-finite input;
    # peel degrees down, batching the eigvals call per effective degree
    remaining = np.ones(flat.shape[0], dtype=bool)
    ok &= np.all(np.isfinite(flat), axis=-1)            # NaN lane: not ok
    remaining &= np.all(np.isfinite(flat), axis=-1)
    for deg in range(k, 0, -1):
        lead = np.abs(flat[:, deg]) > 1e-300
        process = remaining & lead
        if np.any(process):
            sub = flat[process]
            # deliberate f64 (see find_roots): host-side eig screen
            comp = np.zeros((sub.shape[0], deg, deg))  # sts: noqa[STS004]
            comp[:, deg - 1, :] = -sub[:, :deg] / sub[:, deg:deg + 1]
            if deg > 1:
                comp[:, :deg - 1, 1:] = np.eye(deg - 1)  # sts: noqa[STS004]
            roots = np.linalg.eigvals(comp)             # (b, deg)
            ok[process] &= ~np.any(np.abs(roots) <= 1.0, axis=-1)
        remaining &= ~lead
    # lanes still remaining are constant polynomials: no roots, trivially ok
    return ok.reshape(batch) if batch else bool(ok.reshape(()))


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------

class ARIMAModel(NamedTuple):
    """ARIMA(p, d, q) with coefficients ``[intercept?, AR..., MA...]``
    (ref ``ARIMA.scala:402-410``); ``coefficients`` may carry a leading
    batch dim, in which case the model is an entire panel's fit."""
    p: int
    d: int
    q: int
    coefficients: jnp.ndarray
    has_intercept: bool = True
    diagnostics: Optional["FitDiagnostics"] = None

    @property
    def _icpt(self) -> int:
        return 1 if self.has_intercept else 0

    @property
    def intercept(self) -> jnp.ndarray:
        c, _, _ = _split_params(jnp.asarray(self.coefficients),
                                self.p, self.q, self._icpt)
        return c

    @property
    def ar_coefficients(self) -> jnp.ndarray:
        return jnp.asarray(self.coefficients)[..., self._icpt:self._icpt + self.p]

    @property
    def ma_coefficients(self) -> jnp.ndarray:
        i = self._icpt + self.p
        return jnp.asarray(self.coefficients)[..., i:i + self.q]

    # -- likelihood ---------------------------------------------------------

    def log_likelihood_css(self, ts: jnp.ndarray) -> jnp.ndarray:
        """CSS log likelihood of the ARIMA on an *undifferenced* series
        (ref ``ARIMA.scala:414-420``)."""
        ts = jnp.asarray(ts)
        diffed = differences_of_order_d(ts, self.d)[..., self.d:]
        return self.log_likelihood_css_arma(diffed)

    def log_likelihood_css_arma(self, diffed: jnp.ndarray) -> jnp.ndarray:
        """CSS log likelihood of the ARMA on an already-differenced series
        (ref ``ARIMA.scala:430-445``)."""
        return _batched(
            lambda prm, y: _log_likelihood_css_arma(
                prm, y, self.p, self.q, self._icpt),
            jnp.asarray(self.coefficients), jnp.asarray(diffed))

    def log_likelihood_exact(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Exact (σ²-concentrated) Gaussian log likelihood on an
        *undifferenced* series, via the stationary-initialized Kalman
        filter (``statespace.convert.arma_concentrated_neg_ll``).

        Unlike :meth:`log_likelihood_css` this keeps the first
        ``max(p, q)`` observations and weights them by the stationary
        prior — the objective ``fit(..., objective="exact")`` maximizes,
        and the common scale for comparing CSS and exact fits."""
        from ..statespace.convert import arma_concentrated_neg_ll
        ts = jnp.asarray(ts)
        diffed = differences_of_order_d(ts, self.d)[..., self.d:]
        return _batched(
            lambda prm, y: -arma_concentrated_neg_ll(
                prm, y, self.p, self.q, self._icpt),
            jnp.asarray(self.coefficients), diffed)

    def gradient_log_likelihood_css_arma(self, diffed: jnp.ndarray) -> jnp.ndarray:
        """Gradient of the CSS log likelihood — autodiff through the scan
        replaces the reference's hand-derived recursion
        (ref ``ARIMA.scala:465-534``)."""
        return _batched(
            jax.grad(lambda prm, y: _log_likelihood_css_arma(
                prm, y, self.p, self.q, self._icpt)),
            jnp.asarray(self.coefficients), jnp.asarray(diffed))

    # -- effects / sampling / forecasting -----------------------------------

    def remove_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Recover underlying errors (ref ``ARIMA.scala:627-647``)."""
        return _batched(
            lambda prm, y: _remove_effects_one(
                prm, y, self.p, self.d, self.q, self._icpt),
            jnp.asarray(self.coefficients), jnp.asarray(ts))

    def add_time_dependent_effects(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Apply the ARIMA process to i.i.d. errors
        (ref ``ARIMA.scala:655-668``)."""
        return _batched(
            lambda prm, y: _add_effects_one(
                prm, y, self.p, self.d, self.q, self._icpt),
            jnp.asarray(self.coefficients), jnp.asarray(ts))

    def sample(self, n: int, key, shape=()) -> jnp.ndarray:
        """Gaussian innovations pushed through the process
        (ref ``ARIMA.scala:675-678``)."""
        noise = jax.random.normal(
            key, (*shape, n), dtype=jnp.asarray(self.coefficients).dtype)
        return self.add_time_dependent_effects(noise)

    def forecast(self, ts: jnp.ndarray, n_future: int) -> jnp.ndarray:
        """Fitted 1-step-ahead historicals followed by ``n_future`` forecast
        periods (ref ``ARIMA.scala:696-764``)."""
        ts = jnp.asarray(ts)
        need = self.d + max(self.p, self.q) + 1
        if ts.shape[-1] < need:
            # the lag gathers would silently clamp and return garbage
            raise ValueError(
                f"forecast needs at least d + max(p, q) + 1 = {need} trailing"
                f" observations for ARIMA({self.p},{self.d},{self.q}); "
                f"got {ts.shape[-1]}")
        return _batched(
            lambda prm, y: _forecast_one(
                prm, y, n_future, self.p, self.d, self.q, self._icpt),
            jnp.asarray(self.coefficients), ts)

    def forecast_interval(self, ts: jnp.ndarray, n_future: int,
                          conf: float = 0.95):
        """Point forecast plus symmetric ``conf`` prediction bands.

        Returns ``(forecast, lower, upper)``: ``forecast`` is exactly
        :meth:`forecast`'s output (historicals + future); ``lower``/
        ``upper`` cover only the ``n_future`` future steps, widening with
        horizon via the psi-weight error variance (beyond reference —
        ``ARIMA.scala``'s forecast has no uncertainty output).

        Bands are bounded only where the fitted AR part is stationary: a
        lane with explosive AR coefficients (typically one whose fit
        reports ``converged=False`` — check ``diagnostics`` /
        ``is_stationary()`` and re-fit via ``models.refit_unconverged``)
        has genuinely unbounded forecast variance, so its bands grow at
        the explosive rate and overflow to ``inf``/NaN at longer horizons
        rather than flattening to a fabricated width.
        """
        if n_future < 1:
            raise ValueError("forecast_interval needs n_future >= 1")
        ts = jnp.asarray(ts)
        point = self.forecast(ts, n_future)
        half = _batched(
            lambda prm, y: _psi_half_widths(
                prm, y, n_future, self.p, self.d, self.q, self._icpt,
                conf),
            jnp.asarray(self.coefficients), ts)
        future = point[..., ts.shape[-1]:]
        return point, future - half, future + half

    # -- diagnostics --------------------------------------------------------

    def is_stationary(self):
        """AR characteristic roots outside the unit circle
        (ref ``ARIMA.scala:777-786``)."""
        if self.p == 0:
            coefs = np.asarray(self.coefficients)
            shape = coefs.shape[:-1]
            return np.ones(shape, bool) if shape else True
        phi = np.asarray(self.ar_coefficients)
        # leading 1.0 of the f64 host-side characteristic polynomial
        ones = np.ones((*phi.shape[:-1], 1))      # sts: noqa[STS004]
        return _all_roots_outside_unit_circle(
            np.concatenate([ones, -phi], axis=-1))

    def is_invertible(self):
        """MA characteristic roots outside the unit circle
        (ref ``ARIMA.scala:788-796``)."""
        if self.q == 0:
            coefs = np.asarray(self.coefficients)
            shape = coefs.shape[:-1]
            return np.ones(shape, bool) if shape else True
        theta = np.asarray(self.ma_coefficients)
        # leading 1.0 of the f64 host-side characteristic polynomial
        ones = np.ones((*theta.shape[:-1], 1))    # sts: noqa[STS004]
        return _all_roots_outside_unit_circle(
            np.concatenate([ones, theta], axis=-1))

    def approx_aic(self, ts: jnp.ndarray) -> jnp.ndarray:
        """Conditional-likelihood AIC approximation
        (ref ``ARIMA.scala:826-830``)."""
        ll = self.log_likelihood_css(ts)
        return -2.0 * ll + 2.0 * (self.p + self.q + self._icpt)

    @property
    def n_params(self) -> int:
        """Estimated-parameter count (intercept + AR + MA) — the AIC
        penalty's k, and the parsimony key the backtest tier's champion
        tie-break orders near-equal out-of-sample scores by."""
        return self.p + self.q + self._icpt

    # -- distributed-combination exports (the longseries tier) --------------

    def ar_inf_coefficients(self, n_terms: int) -> Tuple[jnp.ndarray,
                                                         jnp.ndarray]:
        """The model's AR(∞) representation truncated at ``n_terms``:
        ``(c_pi, pi)`` with ``pi (..., n_terms)`` such that

            y_t ≈ c_pi + Σ_{j=1..n_terms} pi_j · y_{t-j} + e_t

        on the d-times-differenced scale (``d`` is not expanded here —
        the AR form lives where the ARMA does).  This is the common
        coefficient space the DARIMA combiner
        (``longseries.combine``) maps every segment estimate into; see
        :func:`ar_truncation`."""
        coefs = jnp.asarray(self.coefficients)
        phi = coefs[..., self._icpt:self._icpt + self.p]
        theta = coefs[..., self._icpt + self.p:self._icpt + self.p + self.q]
        c = self.intercept
        return ar_truncation(c, phi, theta, n_terms)

    def coefficient_precision(self, ts: jnp.ndarray,
                              assume_differenced: bool = False
                              ) -> jnp.ndarray:
        """Observed-information export: the (batched) Hessian of the
        negative CSS log-likelihood at the fitted coefficients — the
        asymptotic precision (inverse covariance) of the CSS estimator,
        which is what inverse-covariance combination schemes
        (``fit_long``, the DARIMA combiner) weight by.

        ``ts`` the series the model was fitted on (``(n,)`` or matching
        batch); ``assume_differenced=True`` skips the order-``d``
        differencing when ``ts`` is already on the ARMA scale.  Returns
        ``(..., k, k)`` with ``k = icpt + p + q``."""
        y = jnp.asarray(ts)
        if not assume_differenced:
            y = differences_of_order_d(y, self.d)[..., self.d:]
        p, q, icpt = self.p, self.q, self._icpt

        def neg_ll(prm, yy):
            return -_log_likelihood_css_arma(prm, yy, p, q, icpt)

        return _batched(jax.hessian(neg_ll),
                        jnp.asarray(self.coefficients), y)


# ---------------------------------------------------------------------------
# fitting
# ---------------------------------------------------------------------------

def hannan_rissanen_init(p: int, q: int, y: jnp.ndarray,
                         include_intercept: bool,
                         n_valid: Optional[jnp.ndarray] = None
                         ) -> jnp.ndarray:
    """Hannan-Rissanen initial ARMA estimates (ref ``ARIMA.scala:216-242``):
    fit AR(m) with ``m = max(p, q) + 1``, estimate errors, then OLS of the
    series on [AR lag terms ‖ MA error-lag terms].  Fully batched: ``y`` may
    be ``(..., n)``.

    ``n_valid (...,)`` restricts each lane to its left-aligned valid window
    (``ops.ragged``): both OLS stages weight out rows whose target index
    falls past it, matching the init of the trimmed series."""
    y = jnp.asarray(y)
    m = max(p, q) + 1
    mx = max(p, q)

    ar = autoregression.fit.__wrapped__(y, m, n_valid=n_valid)
    est = lag_matvec(y, jnp.atleast_1d(ar.coefficients), m) \
        + jnp.asarray(ar.c)[..., None]
    y_trunc = y[..., m:]
    errors = y_trunc - est

    n_rows = y_trunc.shape[-1] - mx
    Xs = jnp.concatenate([_lag_stack_or_empty(y_trunc, p)[..., -n_rows:],
                          _lag_stack_or_empty(errors, q)[..., -n_rows:]],
                         axis=-2)
    target = y_trunc[..., mx:]
    w = None
    if n_valid is not None:
        w = step_weights(n_rows, jnp.asarray(n_valid)[..., None],
                         offset=m + mx, dtype=y.dtype)
    res = ols_gram(Xs, target, add_intercept=include_intercept,
                   row_weights=w)
    return res.beta


def _pallas_lm_mode(diffed: jnp.ndarray, nv) -> str:
    """Route the css-lm solve through the Pallas fused-NE kernel?
    ``"pallas"`` / ``"pallas_shard_map"`` / ``"xla"``.

    Gate semantics live in :func:`ops.pallas_arma.route_mode` (shared
    with the Holt-Winters driver); the measured win here is 1.57x over
    the vmapped XLA fused-carry path
    (``benchmarks/pallas_ab_r04_tpu.jsonl``).  Series-sharded panels
    keep the kernel via a per-shard ``shard_map`` wrap rather than
    silently dropping to the XLA path (r4 verdict weak #4); ragged
    panels keep it too — the kernel computes per-lane step weights in
    VMEM (r5).
    """
    from ..ops.pallas_arma import route_mode
    return route_mode(diffed, nv, allow_1d=True, allow_ragged=True)


def _use_pallas_lm(diffed: jnp.ndarray, nv) -> bool:
    """Bool view for grid callers that have no shard_map wrap (the
    fused auto-fit); warns when a forced flag meets a sharded panel."""
    from ..ops.pallas_arma import route_panel
    return route_panel(diffed, nv, allow_1d=True, allow_ragged=True)


@_metrics.instrument_fit("arima")
def fit(p: int, d: int, q: int, ts: jnp.ndarray,
        include_intercept: bool = True, method: str = "css-lm",
        user_init_params: Optional[jnp.ndarray] = None,
        warn: bool = True, max_iter: Optional[int] = None,
        retry: Optional[_resilience.RetryPolicy] = None,
        n_valid: Optional[jnp.ndarray] = None,
        objective: str = "css") -> ARIMAModel:
    """Fit an ARIMA(p, d, q) by conditional-sum-of-squares maximum likelihood
    (ref ``ARIMA.scala:79-116``).

    ``ts`` may be ``(n,)`` or ``(n_series, n)`` — the whole panel fits in one
    batched solve.  ``method``:

    - ``"css-lm"`` (default): batched Levenberg-Marquardt on the one-step
      residuals.  Maximizing the CSS likelihood is exactly minimizing the
      residual sum of squares (the likelihood is monotone in it,
      ``ARIMA.scala:430-445``), and LM stays robust in float32 on TPU where
      a BFGS line search underflows.  On the TPU backend, float32
      panels of >= 1024 series — dense or NaN-padded ragged (the kernel
      computes per-lane step weights in VMEM) — route through the
      Pallas fused-NE kernel
      (``ops.pallas_arma.fit_css_lm``, measured 1.57x over the XLA
      path; smaller panels would mostly pad the kernel's 1024-lane
      blocks, and very long series would overflow a VMEM-resident
      block — both keep the XLA path, ``ops.pallas_arma.vmem_fits``).
      Series-sharded panels (``NamedSharding`` over the series axis,
      >= 1024 lanes per shard) keep the kernel too, one ``shard_map``
      shard per device (``ops.pallas_arma.fit_css_lm_sharded`` —
      distribution changes neither the math nor the routing).
      ``STS_PALLAS=0`` restores the XLA path, ``STS_PALLAS=1`` forces
      the kernel anywhere (interpreter mode off-TPU, for tests); the
      routing is decided at call time on the concrete panel, so a
      user-held ``jax.jit`` around ``fit`` bakes it in — re-jit after
      changing the flag.
    - ``"css-cgd"``: batched BFGS on the autodiff gradient (the reference's
      conjugate-gradient analog).
    - ``"css-bobyqa"``: projected gradient with backtracking (the
      derivative-free fallback's role).

    Matches the reference's AR-only fast path (pure OLS when ``q == 0``).

    ``max_iter`` caps the optimizer iterations (default: 50 for LM, 500
    otherwise); under vmap every lane pays the slowest lane's iterations,
    and on the bench panel 50 trades ~1 point of batch convergence for ~2x
    throughput — raise it for full-convergence parity runs.

    On short series expect a stubborn non-converged tail regardless of
    budget (bench panel, 128 obs: 88.6% at 50 iterations, only 91.3% at
    200, and damping-schedule variants measured within ±2 points): those
    lanes' CSS optima sit near AR/MA common-factor ridges — their fitted
    minimum AR and MA root moduli land together near/inside the unit
    circle (median 0.58 vs 1.9 for converged lanes) where the objective
    is an ill-identified plateau.  This is finite-sample statistics, not
    a solver knob: check ``is_stationary()``/``is_invertible()``, and
    prefer ``models.refit_unconverged`` or a lower-order ``auto_fit``
    for such lanes.

    NaN-padded panels (leading/trailing padding per lane, the
    ``from_observations`` + ``union`` ingestion shape) fit directly: each
    lane's contiguous valid window is detected, left-aligned, and the CSS
    objective weighted to it — per-lane results equal independent fits of
    the trimmed series (``ops.ragged``; pinned by ``tests/test_ragged.py``).
    Lanes too short for the order get NaN coefficients and
    ``diagnostics.converged == False``.  Interior gaps still raise —
    impute those with ``fill`` first.

    ``retry`` (a ``utils.resilience.RetryPolicy``) enables the optimizers'
    multi-start path: non-converged / non-finite lanes re-solve from
    jittered inits inside the batched computation, the per-lane attempt
    count lands in ``diagnostics.attempts``, and ``retry.max_iter`` (when
    set) becomes the per-attempt budget unless ``max_iter`` overrides it.
    The css-lm method then takes the XLA solver path (the Pallas kernel
    has no restart loop).

    ``n_valid`` (per-lane valid-window lengths) bypasses the
    value-dependent NaN detection entirely: ``ts`` must then already be
    left-aligned with zeroed tails (the ``ops.ragged._left_align``
    layout), and the whole fit — including the ragged weighting — traces
    with no host branches, which is what the engine's AOT bucketed
    executables (``spark_timeseries_tpu.engine``) need.  Short-lane
    quarantine still applies, but as a traced mask without the host
    warning.

    ``objective="exact"`` upgrades the estimate from CSS to the exact
    Gaussian maximum likelihood: the CSS solution above becomes the
    initial point for a batched BFGS on the σ²-concentrated Kalman-filter
    log-likelihood (``statespace.convert.arma_concentrated_neg_ll`` —
    stationary initial distribution, no dropped leading residuals).
    Per lane the better of {refined, CSS-init} under the exact objective
    is kept, so the exact fit's exact log-likelihood is never below the
    CSS solution's.  Fully traced — the same ragged/engine contracts
    apply; ``diagnostics.fun`` then holds the exact negative
    log-likelihood instead of the CSS one.
    """
    if objective not in ("css", "exact"):
        raise ValueError(f"unknown objective {objective!r}; expected "
                         f"'css' or 'exact'")
    if objective == "exact":
        base = fit.__wrapped__(p, d, q, ts, include_intercept, method,
                               user_init_params, warn=False,
                               max_iter=max_iter, retry=retry,
                               n_valid=n_valid)
        # the refine honors the retry policy's iteration cap the same way
        # the CSS solve below does
        if max_iter is None and retry is not None \
                and retry.max_iter is not None:
            max_iter = retry.max_iter
        model = _exact_refine(base, ts, n_valid=n_valid, max_iter=max_iter)
        _warn_stationarity_invertibility(model, warn)
        return model
    ts = jnp.asarray(ts)
    rk = _resilience.retry_kwargs(retry)
    if max_iter is None and retry is not None and retry.max_iter is not None:
        max_iter = retry.max_iter
    if n_valid is not None:
        obs_len = jnp.asarray(n_valid)
    else:
        ts, obs_len = ragged_view(ts)
    icpt = 1 if include_intercept else 0
    diffed = differences_of_order_d(ts, d)[..., d:]
    nv = None if obs_len is None else jnp.maximum(obs_len - d, 0)

    def _short_lanes(min_n):
        """Lanes whose valid window can't support the order (ragged only);
        min_n counts post-differencing observations."""
        if nv is None:
            return None
        return short_lanes(nv, min_n,
                           f"ARIMA({p},{d},{q}) fit (post-differencing)")

    if p > 0 and q == 0 and user_init_params is None:
        # AR fast path (ref ARIMA.scala:90-96); OLS is direct, so the
        # diagnostics mark every finite lane converged in 0 iterations
        short = _short_lanes(2 * p + icpt + 1)
        ar = autoregression.fit.__wrapped__(
            diffed, p, no_intercept=not include_intercept, n_valid=nv)
        parts = ([jnp.asarray(ar.c)[..., None]] if include_intercept else []) \
            + [jnp.atleast_1d(ar.coefficients)]
        coefs = jnp.concatenate(parts, axis=-1)
        lane_ok = jnp.all(jnp.isfinite(coefs), axis=-1)
        fun = -_ll_batched(coefs, diffed, nv, p, q, icpt)
        coefs, lane_ok = apply_short_quarantine(coefs, lane_ok, short)
        model = ARIMAModel(p, d, q, coefs, include_intercept)
        model = model._replace(diagnostics=FitDiagnostics(
            lane_ok, jnp.zeros(lane_ok.shape, jnp.int32), fun))
        _warn_stationarity_invertibility(model, warn)
        return model

    dim = p + q + icpt
    if dim == 0:
        model = ARIMAModel(p, d, q, jnp.zeros((*ts.shape[:-1], 0), ts.dtype),
                           include_intercept)
        fun = -_ll_batched(jnp.asarray(model.coefficients), diffed, nv,
                           p, q, icpt)
        return model._replace(diagnostics=FitDiagnostics(
            jnp.isfinite(fun), jnp.zeros(fun.shape, jnp.int32), fun))

    max_lag = max(p, q)
    if diffed.shape[-1] <= max_lag:
        raise ValueError(
            f"series too short to fit ARIMA({p},{d},{q}): the CSS window "
            f"needs more than max(p, q) = {max_lag} observations after "
            f"order-{d} differencing, got {diffed.shape[-1]}")
    if user_init_params is None:
        # Hannan-Rissanen: AR(max_lag+1) fit, two truncations, then an OLS
        # that needs at least as many rows as parameters
        min_n = 2 * max_lag + 2 + p + q + icpt
        if diffed.shape[-1] < min_n:
            raise ValueError(
                f"series too short to fit ARIMA({p},{d},{q}): the "
                f"Hannan-Rissanen initialization needs >= {min_n} "
                f"observations after order-{d} differencing, got "
                f"{diffed.shape[-1]}; pass user_init_params to skip it")
        short = _short_lanes(min_n)
        init = hannan_rissanen_init(p, q, diffed, include_intercept,
                                    n_valid=nv)
        if short is not None:
            # a too-short lane's HR gram may be singular-but-finite; pin
            # its init to a neutral zero vector so LM stays finite there
            init = jnp.where(short[..., None] if init.ndim > short.ndim
                             else short, jnp.zeros((), init.dtype), init)
    else:
        short = _short_lanes(max_lag + 1)
        init = jnp.broadcast_to(jnp.asarray(user_init_params, ts.dtype),
                                (*ts.shape[:-1], dim))

    extra = () if nv is None else (nv,)

    def neg_ll(prm, y, *v):
        return -_log_likelihood_css_arma(prm, y, p, q, icpt,
                                         n_valid=v[0] if v else None)

    if method == "css-lm":
        mi = max_iter if max_iter is not None else LM_MAX_ITER
        # retry and injected optimizer faults both live in the XLA solver
        # (the Pallas kernel has neither a restart loop nor the fault hook)
        lm_mode = "xla" if (rk or _resilience.forced_optimizer_failures()) \
            else _pallas_lm_mode(diffed, nv)
        if lm_mode != "xla":
            from ..ops.pallas_arma import fit_css_lm, fit_css_lm_sharded
            x2 = init if init.ndim == 2 else init[None]
            y2 = diffed if diffed.ndim == 2 else diffed[None]
            nv2 = None
            if nv is not None:
                nv2 = jnp.atleast_1d(jnp.asarray(nv))
            solver = fit_css_lm_sharded if lm_mode == "pallas_shard_map" \
                else fit_css_lm
            res = MinimizeResult(*solver(x2, y2, p, q, icpt, max_iter=mi,
                                         n_valid=nv2))
            if init.ndim != 2:
                res = MinimizeResult(res.x[0], res.fun[0],
                                     res.converged[0], res.n_iter[0])
        else:
            res = minimize_least_squares(
                None, init, diffed, *extra, max_iter=mi,
                normal_eqs_fn=lambda prm, y, *v: _arma_normal_eqs(
                    prm, y, p, q, icpt, n_valid=v[0] if v else None), **rk)
    elif method == "css-cgd":
        res = minimize_bfgs(neg_ll, init, diffed, *extra, tol=1e-7,
                            max_iter=max_iter if max_iter is not None else 500,
                            **rk)
    elif method == "css-bobyqa":
        res = minimize_box(neg_ll, init, -jnp.inf, jnp.inf, diffed, *extra,
                           tol=1e-10,
                           max_iter=max_iter if max_iter is not None else 500,
                           **rk)
    else:
        raise ValueError(f"unknown method {method!r}")

    # quarantine failed lanes back to their (finite) initial guess rather
    # than poisoning the batch (SURVEY.md §7 hard part #3); per-lane, so a
    # partially-NaN result never yields a mixed coefficient vector
    lane_ok = jnp.all(jnp.isfinite(res.x), axis=-1, keepdims=True)
    params = jnp.where(lane_ok, res.x, init)
    conv = diagnostics_from(res, lane_ok)
    params, conv_mask = apply_short_quarantine(params, conv.converged, short)
    model = ARIMAModel(p, d, q, params, include_intercept,
                       diagnostics=conv._replace(converged=conv_mask))
    _warn_stationarity_invertibility(model, warn)
    return model


# undecorated fit for internal search/segment loops (auto_fit candidates,
# fit_long segments): internal exploratory fits must not inflate the public
# fit.arima.* counter bundle — only the entry point the user called records
_fit_unrecorded = fit.__wrapped__


def segment_fit_outputs(p: int, q: int, segs, *,
                        include_intercept: bool = True,
                        method: str = "css-lm",
                        max_iter: Optional[int] = None,
                        objective: str = "css"):
    """Traced fit entry point for the fused longseries fit→combine
    program (docs/design.md §6e/§8): fit one chunk of already-
    differenced segment windows and return exactly the two pieces the
    WLS combiner consumes — ``(coefficients (K, icpt+p+q),
    converged (K,))`` — with no model pytree and no host crossing in
    between.  Meant to run under an enclosing ``jax.jit`` trace, hence
    the undecorated ``fit.__wrapped__`` underneath (spans/counters are
    host-side and must not leak into a compiled program)."""
    m = _fit_unrecorded(p, 0, q, segs,
                        include_intercept=include_intercept,
                        method=method, max_iter=max_iter, warn=False,
                        objective=objective)
    return m.coefficients, jnp.reshape(m.diagnostics.converged, (-1,))


def _exact_refine(base: ARIMAModel, ts: jnp.ndarray,
                  n_valid: Optional[jnp.ndarray] = None,
                  max_iter: Optional[int] = None) -> ARIMAModel:
    """Refine a CSS-fitted model under the exact Kalman likelihood.

    Batched BFGS on ``statespace.convert.arma_concentrated_neg_ll`` from
    the CSS coefficients; per lane the refined parameters are kept only
    when they do not worsen the exact objective (BFGS can wander on the
    common-factor plateaus the CSS fit already documents), so the result
    is exact-loglik-monotone versus its init by construction.  Lanes the
    CSS fit quarantined (NaN coefficients) stay quarantined — a NaN init
    propagates through the solve and the keep-the-better rule falls back
    to the init.
    """
    from ..statespace.convert import arma_concentrated_neg_ll

    p, q, icpt = base.p, base.q, base._icpt
    init = jnp.asarray(base.coefficients)
    if init.shape[-1] == 0:
        return base
    ts = jnp.asarray(ts)
    if n_valid is not None:
        obs_len = jnp.asarray(n_valid)
    else:
        ts, obs_len = ragged_view(ts)
    diffed = differences_of_order_d(ts, base.d)[..., base.d:]
    nv = None if obs_len is None else jnp.maximum(obs_len - base.d, 0)
    extra = () if nv is None else (nv,)

    def neg_ll(prm, y, *v):
        return arma_concentrated_neg_ll(prm, y, p, q, icpt,
                                        n_valid=v[0] if v else None)

    res = minimize_bfgs(neg_ll, init, diffed, *extra, tol=1e-9,
                        max_iter=max_iter if max_iter is not None else 200)
    if init.ndim == 1:
        f_init = neg_ll(init, diffed, *extra)
    else:
        f_init = jax.vmap(neg_ll)(init, diffed, *extra)
    # keep the refined point only when it is finite and no worse than the
    # init under the exact objective (NaN comparisons are False, so NaN
    # lanes fall back to the init automatically)
    improved = jnp.isfinite(res.fun) \
        & jnp.all(jnp.isfinite(res.x), axis=-1) & (res.fun <= f_init)
    params = jnp.where(improved[..., None] if init.ndim > 1 else improved,
                       res.x, init)
    fun = jnp.where(improved, res.fun, f_init)
    base_conv = base.diagnostics.converged if base.diagnostics is not None \
        else jnp.isfinite(f_init)
    converged = jnp.where(improved, jnp.asarray(res.converged),
                          jnp.reshape(jnp.asarray(base_conv), fun.shape))
    diag = FitDiagnostics(converged & jnp.isfinite(fun),
                          jnp.asarray(res.n_iter), fun)
    return ARIMAModel(base.p, base.d, base.q, params, base.has_intercept,
                      diagnostics=diag)


def _ll_batched(coefs: jnp.ndarray, diffed: jnp.ndarray,
                nv: Optional[jnp.ndarray], p: int, q: int,
                icpt: int) -> jnp.ndarray:
    """CSS log likelihood batched over lanes, valid-window aware."""
    if nv is None:
        return _batched(
            lambda prm, y: _log_likelihood_css_arma(prm, y, p, q, icpt),
            coefs, diffed)
    fn = lambda prm, y, v: _log_likelihood_css_arma(prm, y, p, q, icpt,
                                                    n_valid=v)
    if diffed.ndim > 1:
        return jax.vmap(fn)(jnp.broadcast_to(
            coefs, (*diffed.shape[:-1], coefs.shape[-1])), diffed, nv)
    return fn(coefs, diffed, nv)


def _warn_stationarity_invertibility(model: ARIMAModel, warn: bool) -> None:
    """ref ``ARIMA.scala:246-256`` (println there; ``warnings`` here)."""
    if not warn:
        return
    # stacklevel walks _warn(1) -> fit(2) -> instrument_fit wrapper(3) ->
    # the user's call site(4)
    if not np.all(model.is_stationary()):
        warnings.warn("AR parameters are not stationary", stacklevel=4)
    if not np.all(model.is_invertible()):
        warnings.warn("MA parameters are not invertible", stacklevel=4)


@_metrics.instrument_fit("arima", record=False)
def fit_panel(panel, p: int, d: int, q: int, engine=None,
              **kwargs) -> ARIMAModel:
    """Batched fit over a Panel — the ``rdd.mapValues(ARIMA.fitModel(...))``
    equivalent (ref ``src/site/markdown/docs/users.md:107-118``).

    Routes through the streaming fit engine's shape-bucketed executable
    cache (``spark_timeseries_tpu.engine``): the panel pads to its
    ``pad_bucket`` shape, so fitting many same-bucket panels costs one
    XLA compile, not one per shape.  ``engine=False`` restores the direct
    eager fit; an explicit :class:`~spark_timeseries_tpu.engine.FitEngine`
    uses that instance's cache.  Inputs the engine cannot bucket (sharded
    panels, ``user_init_params``) fall back to the direct fit
    automatically."""
    warn = kwargs.pop("warn", True)
    if engine is False:
        return fit(p, d, q, panel.values, warn=warn, **kwargs)
    from ..engine import default_engine
    eng = engine if engine is not None else default_engine()
    return eng.fit(panel.values, "arima", warn=warn, p=p, d=d, q=q,
                   **kwargs)


def _poly_roots_batched(coefs: np.ndarray) -> np.ndarray:
    """Roots of each ascending-coefficient polynomial row: ``(S, k+1)`` →
    complex ``(S, k)``.  Rows whose leading coefficient is ~0 (effective
    lower degree) or non-finite get NaN roots — the caller treats those
    lanes as not-detectable rather than guessing a deflation."""
    coefs = np.asarray(coefs, dtype=np.float64)
    S, k1 = coefs.shape
    k = k1 - 1
    # host-side eig screen, deliberate f64 (see find_roots)
    roots = np.full((S, k), np.nan, np.complex128)  # sts: noqa[STS004]
    ok = (np.abs(coefs[:, -1]) > 1e-8) \
        & np.all(np.isfinite(coefs), axis=-1)
    if k >= 1 and np.any(ok):
        sub = coefs[ok]
        comp = np.zeros((sub.shape[0], k, k))       # sts: noqa[STS004]
        comp[:, k - 1, :] = -sub[:, :k] / sub[:, k:k + 1]
        if k > 1:
            comp[:, :k - 1, 1:] = np.eye(k - 1)     # sts: noqa[STS004]
        roots[ok] = np.linalg.eigvals(comp)
    return roots


def _cancellation_suspects(model: ARIMAModel,
                           tol: float = 0.15) -> np.ndarray:
    """Per-lane common-factor cancellation detection, host-side: True
    where some AR root sits within ``tol`` (relative to the root's
    magnitude, floor 1) of some MA root.

    A near-common factor means the lane is effectively a *lower-order*
    ARMA wearing a (p, q) costume: the shared root direction is flat in
    the likelihood, the optimizer plateaus on a ridge (the BENCH
    ``refit_demo`` signature — 15.3% of series at the bench shape), and
    the honest remedy is refitting at a searched lower order, which is
    exactly what the ``auto_order`` fallback stage does.  Off the hot
    path: batched companion eigvals over tiny (p, p)/(q, q) matrices.
    """
    p, q = model.p, model.q
    coefs = np.asarray(model.coefficients, dtype=np.float64)
    if coefs.ndim == 1:
        coefs = coefs[None]
    S = coefs.shape[0]
    if p == 0 or q == 0:
        return np.zeros(S, bool)
    icpt = model._icpt
    phi = coefs[:, icpt:icpt + p]
    theta = coefs[:, icpt + p:icpt + p + q]
    one = np.ones((S, 1))                           # sts: noqa[STS004]
    # AR: 1 - φ₁z - ... ; MA: 1 + θ₁z + ...  (ascending coefficients)
    ar = _poly_roots_batched(np.concatenate([one, -phi], axis=1))
    ma = _poly_roots_batched(np.concatenate([one, theta], axis=1))
    dist = np.abs(ar[:, :, None] - ma[:, None, :])          # (S, p, q)
    scale = np.maximum(1.0, np.abs(ar))[:, :, None]
    rel = np.where(np.isfinite(dist), dist / scale, np.inf)
    return np.min(rel.reshape(S, -1), axis=-1) < tol


def _pad_to_order(model: ARIMAModel, p: int, q: int) -> ARIMAModel:
    """Re-express a lower-order fit as an ARIMA(p, d, q) model by
    zero-filling the absent AR/MA slots — an AR(p') fit with θ = 0 *is* an
    ARIMA(p, d, q) point, so fallback results merge into the primary
    parameter layout exactly."""
    icpt = model._icpt
    coefs = jnp.asarray(model.coefficients)
    parts = [coefs[..., :icpt + model.p],
             jnp.zeros((*coefs.shape[:-1], p - model.p), coefs.dtype),
             coefs[..., icpt + model.p:],
             jnp.zeros((*coefs.shape[:-1], q - model.q), coefs.dtype)]
    return ARIMAModel(p, model.d, q, jnp.concatenate(parts, axis=-1),
                      model.has_intercept, diagnostics=model.diagnostics)


def _make_auto_order_stage(p: int, d: int, q: int,
                           max_iter: Optional[int]):
    """The ``auto_order`` fallback stage: re-select (p', q') ≤ (p, q) for
    the gathered failing lanes via the batched order search
    (:func:`auto_fit_panel` over the d-differenced lanes, ``max_d=0``
    pinning the primary's d so every lane shares the merged model's
    static layout), and embed each winner's zero-padded coefficients in
    the primary [c, AR(p), MA(q)] slots.  A lane "converges" in this
    stage when the search found an admissible winner (finite AIC); its
    ``diagnostics.fun`` carries that AIC.  Returns a
    :class:`~spark_timeseries_tpu.utils.resilience.StageResult` so the
    selected per-lane (p', d, q') lands in ``FitOutcome.orders``."""

    def stage(v: jnp.ndarray):
        diffed = differences_of_order_d(v, d)[..., d:] if d else v
        with warnings.catch_warnings():
            # failing lanes routinely have no admissible candidate or a
            # capped screen — that is this stage's normal diet, and the
            # outcome is reported through status codes, not warnings
            warnings.simplefilter("ignore")
            sel = auto_fit_panel(diffed, max_p=p, max_d=0, max_q=q,
                                 max_iter=max_iter)
        dtype = v.dtype
        coefs = jnp.asarray(np.asarray(sel.coefficients), dtype)
        conv = np.isfinite(sel.aic) \
            & np.all(np.isfinite(sel.coefficients), axis=-1)
        n_sub = coefs.shape[0]
        diag = FitDiagnostics(jnp.asarray(conv),
                              jnp.zeros((n_sub,), jnp.int32),
                              jnp.asarray(np.asarray(sel.aic), dtype))
        model = ARIMAModel(p, d, q, coefs, True, diagnostics=diag)
        orders = np.asarray(sel.orders, np.int32).copy()
        orders[:, 1] = d                     # the search ran at the
        #                                      primary's (pinned) d
        return _resilience.StageResult(model, orders)

    return stage


@_metrics.instrument_fit("arima", record=False, name="arima.fit_resilient")
def fit_resilient(ts: jnp.ndarray, p: int, d: int, q: int,
                  include_intercept: bool = True,
                  fallbacks: Sequence[str] = ("ar", "mean"),
                  retry: Optional[_resilience.RetryPolicy] = None,
                  auto_order: bool = False,
                  cancel_tol: float = 0.15,
                  **kwargs):
    """Fail-soft batched ARIMA over a panel: health masking, multi-start
    retry, and a declarative fallback chain — ARIMA(p, d, q) →
    [``auto_order``] → ``"ar"`` (AR(p) via the direct OLS fast path,
    θ = 0) → ``"mean"`` (intercept-only drift model on the d-differenced
    series).

    ``ts (n_series, n)``.  Returns ``(model, outcome)``: an
    :class:`ARIMAModel` in the full (p, d, q) layout whose per-lane
    parameters come from the first stage that converged for that lane, and
    a :class:`~spark_timeseries_tpu.utils.resilience.FitOutcome` with
    per-series status / health / attempts / fallback indices, plus the
    effective per-lane ``orders`` (p, d, q).  Unfittable lanes (all-NaN,
    inf, interior gaps, too short) are skipped with an explicit status
    and NaN parameters instead of raising; healthy lanes match
    :func:`fit` bit-for-bit.  ``kwargs`` pass through to the primary
    :func:`fit` (``method``, ``max_iter``, ...).

    ``auto_order=True`` (ROADMAP item 1's resilience wiring) inserts the
    adaptive stage ahead of the hardcoded fallbacks: lanes whose primary
    fit failed — or *converged but plateaued* on common-factor
    cancellation (some AR root within ``cancel_tol`` of an MA root:
    the lane is a lower-order ARMA on a likelihood ridge) — are re-fitted
    through the batched order search (:func:`auto_fit_panel`) over the
    full (p', q') ≤ (p, q) grid at the primary's d, and the per-series
    AIC winner replaces the lane *only if admissible* (suspect lanes
    keep their converged primary result otherwise).  The selected order
    per series is recorded in ``outcome.orders``; lanes the auto stage
    saw but nothing rescued count into
    ``resilience.auto_fallback_dead`` (zero-baselined by the bench
    gate).  ``auto_order=False`` (the default) leaves the pre-existing
    chain — stages, routing, and results — bit-for-bit untouched.

    One routing caveat for the bit-for-bit claim: a restart budget forces
    css-lm onto the XLA solver, while a *plain* fit of a TPU panel large
    enough for the Pallas gate routes through the kernel, whose iteration
    trajectories differ in low-order bits.  Pass
    ``retry=RetryPolicy(max_restarts=0)`` to keep the plain routing (and
    exact equality) there; health masking and the fallback chain still
    apply.
    """
    if retry is None:
        retry = _resilience.RetryPolicy()
    icpt = 1 if include_intercept else 0
    max_lag = max(p, q)
    # the Hannan-Rissanen floor (the binding one when q > 0), plus d
    min_len = d + max(2 * max_lag + 2 + p + q + icpt, max_lag + 2, 3)

    chain = [("arima", lambda v: fit.__wrapped__(
        p, d, q, v, include_intercept=include_intercept, retry=retry,
        warn=False, **kwargs))]
    suspect_fn = None
    if auto_order:
        if not include_intercept:
            raise ValueError(
                "auto_order=True requires include_intercept=True: the "
                "batched order search always carries an intercept slot, "
                "and its winners must embed into the primary layout")
        if p == 0 and q == 0:
            raise ValueError(
                "auto_order=True needs p > 0 or q > 0: an ARIMA(0,d,0) "
                "primary has no lower order to search")
        chain.append(("auto_order", _make_auto_order_stage(
            p, d, q, kwargs.get("max_iter"))))
        if p > 0 and q > 0:
            suspect_fn = lambda m: _cancellation_suspects(m, cancel_tol)  # noqa: E731
    for fb in fallbacks:
        if fb == "ar" and p > 0 and q > 0:
            chain.append(("ar", lambda v: _pad_to_order(
                _fit_unrecorded(p, d, 0, v,
                                include_intercept=include_intercept,
                                warn=False), p, q)))
        elif fb == "mean":
            chain.append(("mean", lambda v: _pad_to_order(
                _fit_unrecorded(0, d, 0, v,
                                include_intercept=include_intercept,
                                warn=False), p, q)))
        elif fb != "ar":
            raise ValueError(f"unknown arima fallback {fb!r}; "
                             f"expected 'ar' or 'mean'")
    model, outcome = _resilience.resilient_fit(
        ts, chain, min_len=min_len, family="arima",
        suspect_fn=suspect_fn)

    # back-fill the static per-stage orders so outcome.orders is total:
    # auto_order lanes already carry their searched (p', d, q')
    status = np.asarray(outcome.status)
    n_series = status.shape[0]
    orders = outcome.orders
    if orders is None:
        orders = np.full((n_series, 3), -1, np.int32)
    static_order = {"arima": (p, d, q), "ar": (p, d, 0),
                    "mean": (0, d, 0)}
    unfilled = orders[:, 0] < 0
    primary = unfilled & np.isin(
        status, (_resilience.STATUS_OK, _resilience.STATUS_RETRIED,
                 _resilience.STATUS_ABANDONED))
    orders[primary] = (p, d, q)
    fb_used = np.asarray(outcome.fallback_used)
    for j, (name, _) in enumerate(chain):
        so = static_order.get(name)
        if so is None:
            continue
        mask = unfilled & (status == _resilience.STATUS_FALLBACK) \
            & (fb_used == j)
        orders[mask] = so
    return model, outcome._replace(orders=orders)


@_metrics.instrument_fit("arima")
def fit_long(p: int, d: int, q: int, ts: jnp.ndarray,
             segment_len: int = 65536, **kwargs) -> ARIMAModel:
    """ARIMA for ultra-long series: segment-parallel CSS fits combined by
    precision weighting.

    The CSS likelihood's MA recursion is inherently sequential in t, so a
    direct fit of a multi-million-observation series serializes the time
    axis (the EWMA/GARCH recurrences are associative scans; this one is
    not).  Beyond-reference capability in the spirit of distributed-ARIMA /
    divide-and-conquer estimation (DLSA; see PAPERS.md "Distributed ARIMA
    Models for Ultra-long Time Series"): after differencing, the series is
    split into ``n // segment_len`` contiguous segments, every segment is
    fitted as one lane of the existing batched ARMA solve (time blocks
    become the batch axis — embarrassingly parallel, mesh-shardable), and
    the per-segment estimates ``theta_k`` are combined by inverse-covariance
    weighting

        theta* = (sum_k H_k)^{-1} sum_k H_k theta_k,

    where ``H_k`` is the autodiff Hessian of the segment's negative CSS
    log-likelihood at its optimum (the asymptotic precision of the CSS
    estimator).  Segments with non-finite estimates or a non-PD Hessian get
    weight 0; if no segment is weightable the result falls back to the
    plain mean of finite segment estimates (and the quarantined HR inits
    those contain), mirroring ``fit``'s quarantine-to-init behavior.

    The head remainder (``n - d - n_segments*segment_len`` observations) is
    dropped from estimation — the most recent data always participates;
    per-segment CSS also drops its own ``max(p, q)`` burn-in, so cross-
    boundary MA carry is ignored (each segment conditions on zero initial
    errors, exactly like the reference's CSS on a whole series).

    ``ts (n,)`` or ``(batch, n)``; returns a standard :class:`ARIMAModel`
    (scalar or per-batch coefficients) whose diagnostics aggregate the
    per-segment fits (``converged`` = a majority of the weightable segments'
    own fits converged, ``n_iter`` = max over segments, ``fun`` = the masked
    sum of weightable segments' objectives).  ``kwargs`` pass through to
    :func:`fit` (``method``, ``max_iter``, ``include_intercept``, ...);
    ``warn`` keeps :func:`fit`'s default (warnings evaluated once, on the
    combined model).

    This is the *in-memory* combiner (everything fits in one batched
    solve, combination in the raw ARMA parameter space).  For series too
    long for one dispatch — or when the segments should stream through
    the engine's journaled/deadlined/OOM-degradable chunk pipeline and
    the result should carry an exact state-space forecast — use the
    ultra-long tier, :func:`spark_timeseries_tpu.longseries.fit_long`
    (DARIMA: combination in the common AR-truncation space with design-
    gram WLS weights, docs/design.md §8).
    """
    ts = jnp.asarray(ts)
    single = ts.ndim == 1
    if single:
        ts = ts[None]
    batch, n = ts.shape
    diffed = differences_of_order_d(ts, d)[..., d:]
    n_diff = diffed.shape[-1]
    n_segments = n_diff // segment_len
    if n_segments < 2:
        raise ValueError(
            f"series too short to segment: {n_diff} differenced obs at "
            f"segment_len={segment_len} gives {n_segments} segment(s); "
            "call fit() directly")
    # keep the most recent complete segments; drop the head remainder
    segs = diffed[..., n_diff - n_segments * segment_len:]
    segs = segs.reshape(batch * n_segments, segment_len)

    include_intercept = kwargs.get("include_intercept", True)
    warn = kwargs.pop("warn", True)
    m = _fit_unrecorded(p, 0, q, segs, warn=False, **kwargs)

    icpt = 1 if include_intercept else 0
    dim = icpt + p + q
    theta = m.coefficients.reshape(batch, n_segments, dim)

    # per-segment precision: Hessian of the segment's negative CSS
    # log-likelihood at the optimum (tiny dim x dim, batched — the same
    # observed-information export the longseries combiner weights by)
    H = m.coefficient_precision(segs, assume_differenced=True)
    H = H.reshape(batch, n_segments, dim, dim)

    # weightable = finite estimate + finite, PD-ish Hessian.  A segment
    # whose optimizer merely hit its iteration cap still carries its best
    # parameters and a valid curvature — it contributes to the combination;
    # convergence gates the reported flag below, not the weights.
    finite_t = jnp.all(jnp.isfinite(theta), axis=-1)
    ok = (finite_t
          & jnp.all(jnp.isfinite(H), axis=(-2, -1))
          & jnp.all(jnp.diagonal(H, axis1=-2, axis2=-1) > 0, axis=-1))
    # zero out unusable segments with where (NaN * 0 is NaN — a poisoned
    # segment must not leak through the weighted sums)
    H_ok = jnp.where(ok[..., None, None], H, 0.0)
    theta_ok = jnp.where(ok[..., None], theta, 0.0)
    H_sum = jnp.sum(H_ok, axis=1)                          # (batch, dim, dim)
    Ht_sum = jnp.sum(H_ok @ theta_ok[..., None], axis=1)   # (batch, dim, 1)
    eye = jnp.eye(dim, dtype=H.dtype)
    combined = spd_solve(H_sum + 1e-8 * eye, Ht_sum[..., 0])
    # fallback chain: no weightable segment (H_sum ~ 0 solves to an exact
    # zero vector, which would silently read as a "fit") or a non-finite
    # solve -> plain mean of the finite segment estimates, which includes
    # the quarantined HR inits; only if nothing is finite keep zeros
    n_finite = jnp.maximum(jnp.sum(finite_t, axis=-1), 1)
    mean_finite = (jnp.sum(jnp.where(finite_t[..., None], theta, 0.0), axis=1)
                   / n_finite[..., None].astype(theta.dtype))
    use_solve = (jnp.any(ok, axis=-1, keepdims=True)
                 & jnp.all(jnp.isfinite(combined), axis=-1, keepdims=True))
    combined = jnp.where(use_solve, combined, mean_finite)

    fun = jnp.sum(jnp.where(ok, m.diagnostics.fun.reshape(batch, n_segments),
                            0.0), axis=-1)
    # converged = a MAJORITY of weightable segments converged (any-segment
    # gating let a 1-of-16 series read as converged, so a downstream
    # refit_unconverged pass would skip it entirely)
    seg_conv = ok & m.diagnostics.converged.reshape(batch, n_segments)
    n_ok = jnp.sum(ok, axis=-1)
    diags = FitDiagnostics(
        (n_ok > 0) & (2 * jnp.sum(seg_conv, axis=-1) > n_ok),
        jnp.max(m.diagnostics.n_iter.reshape(batch, n_segments), axis=-1),
        fun)
    if single:
        combined = combined[0]
        diags = FitDiagnostics(diags.converged[0], diags.n_iter[0],
                               diags.fun[0])
    model = ARIMAModel(p, d, q, combined, include_intercept,
                       diagnostics=diags)
    _warn_stationarity_invertibility(model, warn)
    return model


# ---------------------------------------------------------------------------
# automatic order selection (Hyndman-Khandakar, ref ARIMA.scala:280-375)
# ---------------------------------------------------------------------------

KPSS_SIGNIFICANCE = 0.05

# default LM iteration cap: under vmap every lane pays the slowest lane's
# iterations; 50 trades ~1 point of batch convergence (95.6% vs 96.8% at 100
# on the bench panel) for ~2x throughput, and non-converged lanes keep their
# best-found parameters.  Override per call via fit(..., max_iter=...).
LM_MAX_ITER = 50

# screening budget for auto_fit_panel's candidate grid: selection only
# needs the AICs separated (lanes that matter converge in ~8-10
# iterations; bench panel medians), and each series' winner is then
# refined at the remaining budget on S lanes instead of C·S
SCREEN_MAX_ITER = 25


def _choose_d(ts: jnp.ndarray, max_d: int) -> int:
    """Lowest differencing order whose KPSS statistic indicates level
    stationarity (ref ``ARIMA.scala:287-297``; R forecast::ndiffs)."""
    for diff in range(max_d + 1):
        test_ts = differences_of_order_d(ts, diff)
        stat, critical_values = kpsstest(test_ts, "c")
        if float(stat) < critical_values[KPSS_SIGNIFICANCE]:
            return diff
    raise ValueError(
        f"stationarity not achieved with differencing order <= {max_d}")


@_metrics.instrument_fit("arima")
def auto_fit(ts: jnp.ndarray, max_p: int = 5, max_d: int = 2,
             max_q: int = 5) -> ARIMAModel:
    """Hyndman-Khandakar stepwise automatic ARIMA (ref ``ARIMA.scala:280-375``):
    choose ``d`` by KPSS, then a local (p, q, intercept) search scored by
    approximate AIC, keeping only stationary+invertible candidates.

    Deviation from the reference: the neighborhood step varies *both* p and q
    (the reference's surrounding-parameter generation drops the q offset,
    ``ARIMA.scala:362``, leaving q frozen at its incumbent value).
    """
    ts = jnp.asarray(ts)
    d = _choose_d(ts, max_d)
    # reference quirk kept: the stepwise search runs on the size-preserving
    # differenced series (first d entries are raw values, ARIMA.scala:299)
    diffed = differences_of_order_d(ts, d)
    add_intercept = d <= 1

    def try_fit(p, q, intercept):
        for method in ("css-lm", "css-bobyqa"):
            try:
                m = _fit_unrecorded(p, 0, q, diffed,
                                    include_intercept=intercept,
                                    method=method, warn=False)
                if np.all(np.isfinite(np.asarray(m.coefficients))):
                    return m
            except (ValueError, FloatingPointError,
                    np.linalg.LinAlgError):
                # numerical inadmissibility of THIS candidate (too-short CSS
                # window, singular normal equations, overflow); anything
                # else is a genuine bug and must propagate
                continue
        return None

    past = set()
    best_model, best_aic = None, np.inf
    next_params = [(p, q, add_intercept)
                   for p, q in [(0, 0), (2, 2), (1, 0), (0, 1)]]

    while next_params:
        past.update(next_params)
        candidates = [try_fit(p, q, i) for p, q, i in next_params]
        improving = []
        for m in candidates:
            if m is None or not (np.all(m.is_stationary())
                                 and np.all(m.is_invertible())):
                continue
            aic = float(m.approx_aic(diffed))
            if np.isfinite(aic) and aic < best_aic:
                improving.append((m, aic))
        if not improving:
            break
        best_model, best_aic = min(improving, key=lambda t: t[1])
        deltas = (-1, 0, 1)
        surrounding = []
        for dp in deltas:
            for dq in deltas:
                intercept = (not best_model.has_intercept) \
                    if (dp == 0 and dq == 0) else best_model.has_intercept
                surrounding.append(
                    (best_model.p + dp, best_model.q + dq, intercept))
        next_params = [c for c in surrounding
                       if c not in past and 0 <= c[0] <= max_p
                       and 0 <= c[1] <= max_q]

    if best_model is None:
        raise ValueError("auto_fit failed to fit any admissible ARMA model")
    # carry the winning candidate's diagnostics: fit_report / the
    # fit.arima.* counter bundle then work on auto_fit output too
    return ARIMAModel(best_model.p, d, best_model.q,
                      best_model.coefficients, best_model.has_intercept,
                      diagnostics=best_model.diagnostics)


class PanelARIMAFit(NamedTuple):
    """Per-series automatic order selection over a panel.

    ``orders (n_series, 3)`` holds (p, d, q); ``coefficients`` is zero-padded
    to ``(n_series, 1 + max_p + max_q)`` — slot 0 the intercept (zero when
    ``d > 1`` for that series), slots ``1..max_p`` the AR terms, slots
    ``1+max_p..`` the MA terms; ``aic (n_series,)``.
    """
    orders: np.ndarray
    coefficients: np.ndarray
    aic: np.ndarray
    max_p: int

    def model_for(self, i: int) -> ARIMAModel:
        """Materialize series ``i``'s fit as a standalone model."""
        p, d, q = (int(v) for v in self.orders[i])
        icpt = d <= 1
        coefs = []
        if icpt:
            coefs.append(self.coefficients[i, :1])
        coefs.append(self.coefficients[i, 1:1 + p])
        coefs.append(self.coefficients[i, 1 + self.max_p:1 + self.max_p + q])
        return ARIMAModel(p, d, q, jnp.concatenate(coefs), icpt)


def _auto_fit_panel_kernel(values: jnp.ndarray, masks_base: jnp.ndarray,
                           pq_arr: jnp.ndarray, crit: float,
                           max_p: int, max_q: int, max_d: int,
                           max_iter: int, screen_iter: int,
                           use_pallas_lm: bool = False,
                           n_valid: Optional[jnp.ndarray] = None) -> tuple:
    """Fully fused panel auto-fit — ONE dispatch for the whole search:
    batched KPSS d-selection, per-series differencing (a gather from the
    size-preserving diff stack), Hannan-Rissanen init, one batched LM solve
    over every ``(candidate, series)`` lane of the *padded* parameterization
    ``[c, AR(max_p), MA(max_q)]``, then on-device admissibility screening
    (step-down stationarity/invertibility) and per-series AIC argmin.

    Round-2 verdict weak #3: the previous per-d-group host loop (dispatch +
    numpy screening + numpy argmin per group) left auto-ARIMA
    dispatch-latency-bound at ~1-2k series/s; fusing the groups is possible
    exactly because ``differences_of_order_d`` is size-preserving, so every
    d shares one shape and the per-series d becomes a gather index.

    ``masks_base (C, k)`` has slot 0 (intercept) set for every candidate;
    it is zeroed per series here when that series' chosen d > 1 (the
    reference's intercept rule, ref ``ARIMA.scala:299-301``).  Frozen slots
    stay put inside LM because a masked parameter never enters the
    residuals: its Jacobian column is zero, so the normal-equation step for
    that slot is ``0 / 1e-12 = 0``.

    Returns ``(orders (S, 3), coefs (S, k), aic (S,), d_ok (S,),
    screen_capped (S,))`` — the last flags winners whose screen stage hit
    the reduced iteration cap (selection-risk telemetry).

    ``n_valid (S,)`` restricts each lane to its left-aligned valid window
    (``ops.ragged``; r4 verdict weak #7): the KPSS d-selection, the
    Hannan-Rissanen grams, the masked LM objective, and the per-lane AIC
    sample size all see the window length, so a NaN-padded ingestion
    panel auto-selects orders without a destructive ``fill`` — per-lane
    results equal independent auto-fits of the trimmed series (pinned by
    ``tests/test_ragged.py``).
    """
    dtype = values.dtype
    S, n = values.shape
    k = 1 + max_p + max_q
    C = masks_base.shape[0]

    # per-series d: lowest order whose KPSS statistic passes (batched over
    # the full stack of candidate differencing orders, ref ARIMA.scala:287-297)
    diffs = jnp.stack([differences_of_order_d(values, dd)
                       for dd in range(max_d + 1)])          # (D, S, n)
    # n_valid is d-invariant: the size-preserving diff keeps the first d
    # entries raw (the reference quirk), so every lane's window length
    # survives differencing unchanged
    stats = jnp.stack([kpsstest(diffs[dd], "c", n_valid=n_valid)[0]
                       for dd in range(max_d + 1)])          # (D, S)
    passes = stats < crit
    d_ok = jnp.any(passes, axis=0)
    d_per = jnp.argmax(passes, axis=0)                       # (S,)
    diffed = jnp.take_along_axis(
        diffs, d_per[None, :, None], axis=0)[0]              # (S, n)
    icpt = d_per <= 1

    masks = jnp.broadcast_to(masks_base[:, None, :], (C, S, k))
    masks = masks * jnp.where((jnp.arange(k) == 0)[None, None, :],
                              icpt.astype(dtype)[None, :, None],
                              jnp.ones((), dtype))

    # Hannan-Rissanen on the padded orders (ref ARIMA.scala:216-242, with
    # m = max(max_p, max_q) + 1 shared by every candidate): AR(m) errors,
    # then one *masked* OLS per candidate from shared normal equations
    m = max(max_p, max_q) + 1
    mx = max(max_p, max_q)
    ar = autoregression.fit.__wrapped__(diffed, m, n_valid=n_valid)
    est = lag_matvec(diffed, jnp.atleast_1d(ar.coefficients), m) \
        + jnp.asarray(ar.c)[..., None]
    y_trunc = diffed[..., m:]
    errors = y_trunc - est
    n_rows = y_trunc.shape[-1] - mx
    Xs = jnp.concatenate(
        [jnp.ones((S, 1, n_rows), dtype),
         _lag_stack_or_empty(y_trunc, max_p)[..., -n_rows:],
         _lag_stack_or_empty(errors, max_q)[..., -n_rows:]], axis=-2)
    target = y_trunc[..., mx:]
    if n_valid is not None:
        # rows whose target index falls past the valid window get weight
        # 0 in the grams (0/1 weights square to themselves, so weighting
        # one side is exact) — same rule as hannan_rissanen_init
        w_hr = step_weights(n_rows, jnp.asarray(n_valid)[..., None],
                            offset=m + mx, dtype=dtype)      # (S, n_rows)
        Xs_w = Xs * w_hr[:, None, :]
    else:
        Xs_w = Xs
    N = jnp.einsum("skn,sln->skl", Xs_w, Xs)         # XᵀX (S, k, k)
    b = jnp.einsum("skn,sn->sk", Xs_w, target)
    # candidate-masked normal equations: (M N M + (I - M)) β = M b — SPD
    # (masked gram + identity fill), so the unrolled Cholesky path applies
    Mn = masks[..., :, None] * N[None] * masks[..., None, :]
    ident = jnp.eye(k, dtype=dtype) * (1.0 - masks)[..., :, None]
    init = spd_solve(Mn + ident, masks * b[None])

    # two-stage search: SCREEN the whole (candidate, series) grid on a
    # reduced iteration budget (selection only needs AICs separated, and
    # the lanes that matter converge in ~8-10 iterations), then REFINE
    # just each series' winner at the full budget.  Per-iteration LM cost
    # is batch-linear, so screen(C·S·s) + refine(S·r) beats grid(C·S·r)
    # ~1.6x at the default grid while the final coefficients get a
    # longer, warm-started polish than the old single stage gave them.
    def _grid_lm(x0, y, mask, iters):
        """One masked-LM dispatch for the grid: Pallas driver when the
        (statically decided) gate allows — a (C, S, k) x0 flattens
        candidate-major over the one shared panel, and the kernel
        re-reads panel blocks per candidate rather than materializing C
        copies — XLA fused-carry otherwise."""
        if use_pallas_lm:
            from ..ops.pallas_arma import fit_css_lm
            lead = x0.shape[:-1]
            flat = fit_css_lm(x0.reshape(-1, k), y, max_p, max_q, 1,
                              max_iter=iters, mask=mask.reshape(-1, k),
                              n_valid=n_valid)
            return MinimizeResult(flat[0].reshape(*lead, k),
                                  flat[1].reshape(lead),
                                  flat[2].reshape(lead),
                                  flat[3].reshape(lead))
        y_bc = jnp.broadcast_to(y, (*x0.shape[:-1], y.shape[-1]))
        if n_valid is None:
            return minimize_least_squares(
                None, x0, y_bc, mask, max_iter=iters,
                normal_eqs_fn=lambda prm, yy, mm: _arma_normal_eqs(
                    prm, yy, max_p, max_q, 1, mask=mm))
        nv_bc = jnp.broadcast_to(jnp.asarray(n_valid), x0.shape[:-1])
        return minimize_least_squares(
            None, x0, y_bc, mask, nv_bc, max_iter=iters,
            normal_eqs_fn=lambda prm, yy, mm, vv: _arma_normal_eqs(
                prm, yy, max_p, max_q, 1, mask=mm, n_valid=vv))

    res = _grid_lm(init, diffed, masks, screen_iter)
    lane_ok = jnp.all(jnp.isfinite(res.x), axis=-1, keepdims=True)
    params = jnp.where(lane_ok, res.x, init) * masks

    # CSS likelihood in closed form from the LM's own objective
    # (sse = res.fun), skipping a whole extra primal pass: with
    # sigma² = sse/n', ll = -(n'/2)(log(2π·sse/n') + 1).  Quarantined
    # lanes (x reset to init) keep res.fun's value, but their aic is
    # non-finite or their params screen out below, same as before.
    n_eff = n if n_valid is None \
        else jnp.maximum(jnp.asarray(n_valid).astype(dtype), 1.0)  # (S,)
    neg_ll = 0.5 * n_eff * (jnp.log(2.0 * jnp.pi * res.fun / n_eff) + 1.0)

    # admissibility screen + AIC argmin, all on device (no host round-trip)
    n_params = (pq_arr[:, 0] + pq_arr[:, 1])[:, None] \
        + icpt[None, :].astype(pq_arr.dtype)                 # (C, S)
    aic = 2.0 * neg_ll + 2.0 * n_params.astype(dtype)
    ok = jnp.all(jnp.isfinite(params), axis=-1) & jnp.isfinite(aic)
    ok &= n_params > 0                           # empty candidate: no terms
    ok &= _step_down_stationary(params[..., 1:1 + max_p], pq_arr[:, :1])
    # MA invertibility: roots of 1 + θ₁z + ... outside the circle is the
    # same step-down criterion applied to -θ (ref ARIMA.scala:788-796)
    ok &= _step_down_stationary(-params[..., 1 + max_p:], pq_arr[:, 1:])
    aic = jnp.where(ok, aic, jnp.inf)

    best = jnp.argmin(aic, axis=0)                           # (S,)
    sel = jnp.arange(S)
    chosen_aic = aic[best, sel]
    failed = ~jnp.isfinite(chosen_aic)
    # selection-risk telemetry: winners whose screen stage hit the reduced
    # iteration cap — their AIC ordering could differ from a full-budget grid
    screen_capped = (~res.converged)[best, sel] & ~failed
    coefs = jnp.where(failed[:, None], 0.0, params[best, sel])
    orders = jnp.stack([jnp.where(failed, 0, pq_arr[best, 0]),
                        d_per.astype(pq_arr.dtype),
                        jnp.where(failed, 0, pq_arr[best, 1])], axis=-1)

    # refinement: polish each series' winner at the full budget (S lanes,
    # warm-started).  A refined lane is kept only if it stays finite and
    # admissible — otherwise the screened parameters stand.
    refine_iter = max_iter - screen_iter
    if refine_iter > 0:
        best_masks = masks[best, sel]                        # (S, k)
        res_r = _grid_lm(coefs, diffed, best_masks, refine_iter)
        refined = res_r.x * best_masks
        keep = jnp.all(jnp.isfinite(refined), axis=-1)
        keep &= _step_down_stationary(refined[:, 1:1 + max_p],
                                      orders[:, 0])
        keep &= _step_down_stationary(-refined[:, 1 + max_p:],
                                      orders[:, 2])
        keep &= ~failed
        neg_ll_r = 0.5 * n_eff * (
            jnp.log(2.0 * jnp.pi * res_r.fun / n_eff) + 1.0)
        aic_r = 2.0 * neg_ll_r + 2.0 * (
            orders[:, 0] + orders[:, 2] + icpt.astype(pq_arr.dtype)
        ).astype(dtype)
        keep &= jnp.isfinite(aic_r)
        coefs = jnp.where(keep[:, None], refined, coefs)
        chosen_aic = jnp.where(keep, aic_r, chosen_aic)
    return orders, coefs, chosen_aic, d_ok, screen_capped


@_metrics.instrument_fit("arima", record=False)
def auto_fit_panel(values: jnp.ndarray, max_p: int = 5, max_d: int = 2,
                   max_q: int = 5, max_iter: Optional[int] = None,
                   screen_max_iter: Optional[int] = None) -> PanelARIMAFit:
    """Batched automatic ARIMA over a whole panel — the TPU replacement for
    per-series stepwise search (SURVEY.md §7 hard part #4): the entire
    (p, q) candidate grid is fitted for *all* series in one compiled batched
    solve over padded ``[c, AR(max_p), MA(max_q)]`` parameters (inactive
    slots masked), non-stationary/non-invertible/non-finite fits are masked
    to +inf AIC, and each series takes its argmin.  ``values (n_series, n)``.

    d is chosen per series by batched KPSS *inside the same kernel*; the
    per-series differenced view is a gather from the stack of candidate
    differencing orders (size-preserving, so every d shares one shape).
    The whole search — d selection, grid screen, admissibility screen,
    AIC argmin, then a refinement of each series' winner at the remaining
    budget (kept only while finite and admissible) — is one trace and one
    device dispatch.  ``max_iter`` is the total per-lane budget
    (screen + refinement); ``screen_max_iter`` bounds the grid-screen
    stage (default ``SCREEN_MAX_ITER`` = 25 — pass
    ``screen_max_iter=max_iter`` to restore a full-budget grid when
    selection itself needs slow-converging candidates fully fitted,
    e.g. near-unit-root panels).

    Deliberate deviation: every candidate's CSS drops the common
    ``t < max(max_p, max_q)`` residual window instead of its own
    ``max(p, q)``, so AICs are compared on the *same* sample (the
    reference compares AICs computed on per-order sample sizes).

    NaN-padded panels (leading/trailing padding per lane, the
    ``from_observations`` + ``union`` ingestion shape) auto-fit
    directly, like ``fit``: each lane's valid window drives its KPSS
    d-selection, HR init, masked LM, and AIC sample size.  Lanes too
    short for the order grid get NaN coefficients, +inf aic, and orders
    (0, 0, 0) instead of failing the panel.
    """
    values = jnp.asarray(values)
    values, obs_len = ragged_view(values)
    if max_iter is None:
        max_iter = LM_MAX_ITER
    screen_iter = min(SCREEN_MAX_ITER if screen_max_iter is None
                      else screen_max_iter, max_iter)

    width = 1 + max_p + max_q
    pq = [(p, q) for p in range(max_p + 1) for q in range(max_q + 1)]
    masks = np.zeros((len(pq), width), dtype=np.dtype(values.dtype))
    masks[:, 0] = 1.0        # zeroed per series in-kernel when its d > 1
    for ci, (p, q) in enumerate(pq):
        masks[ci, 1:1 + p] = 1.0
        masks[ci, 1 + max_p:1 + max_p + q] = 1.0

    crit = KPSS_CONSTANT_CRITICAL_VALUES[KPSS_SIGNIFICANCE]
    # the Pallas-vs-XLA routing decision must be a STATIC jit argument:
    # decided inside the trace it would be baked into the cached
    # executable and STS_PALLAS toggles silently ignored on same-shape
    # calls (jit caches key on function + avals + statics, not env).
    # Deciding here also reads the CONCRETE panel's sharding, which the
    # in-trace gate cannot
    use_pl = _use_pallas_lm(values, obs_len)
    # lanes whose window can't support the padded-order HR init (the
    # grid's shared m = max(max_p, max_q) + 1 stages): quarantine rather
    # than poison/raise — the batched replacement for the reference's
    # per-series autoFit must degrade per lane (ARIMA.scala:280-304)
    short = None
    if obs_len is not None:
        mx = max(max_p, max_q)
        min_n = 2 * mx + 3 + max_p + max_q
        short = short_lanes(obs_len, min_n,
                            f"auto_fit_panel (max_p={max_p}, max_q={max_q}) "
                            f"Hannan-Rissanen initialization")
    kernel = jax.jit(_auto_fit_panel_kernel,
                     static_argnums=(4, 5, 6, 7, 8, 9))
    orders, coefs, aic, d_ok, screen_capped = kernel(
        values, jnp.asarray(masks), jnp.asarray(pq, dtype=np.int32),
        float(crit), max_p, max_q, max_d, max_iter, screen_iter, use_pl,
        obs_len)

    short_np = np.asarray(short) if short is not None else None

    # advisor r3: the reduced screen budget can change order selection on
    # slow-converging panels; surface it when it plausibly did
    if screen_iter < max_iter:
        capped = np.asarray(screen_capped)
        if short_np is not None:
            capped = capped[~short_np]
        capped_frac = float(np.mean(capped)) if capped.size else 0.0
        if capped_frac > 0.5:
            warnings.warn(
                f"auto_fit_panel: {capped_frac:.0%} of winning lanes hit the "
                f"screen-stage iteration cap ({screen_iter}); order selection "
                f"may differ from a full-budget grid — pass "
                f"screen_max_iter=max_iter to restore one",
                stacklevel=3)

    d_ok = np.asarray(d_ok)
    if short_np is not None:
        d_ok = d_ok | short_np      # short lanes quarantine, never raise
    if not d_ok.all() and max_d > 0:
        # max_d == 0 pins d: there is nothing to select, so a KPSS
        # rejection is a finite-sample false positive on an already-
        # differenced series (the longseries auto path differences
        # globally), not a failure — the grid fits stand either way
        bad = int(np.sum(~d_ok))
        raise ValueError(
            f"stationarity not achieved with differencing order <= {max_d} "
            f"for {bad} series")

    out_aic = np.asarray(aic)
    out_orders = np.asarray(orders, dtype=np.int64)
    out_coefs = np.asarray(coefs, dtype=np.float64)
    if short_np is not None and short_np.any():
        out_aic = np.where(short_np, np.inf, out_aic)
        out_coefs = np.where(short_np[:, None], np.nan, out_coefs)
        out_orders = np.where(short_np[:, None], 0, out_orders)
    # single-series auto_fit raises in this situation; for a panel, mark the
    # failed lanes (aic stays +inf, coefficients zero) and warn instead of
    # failing every other series
    n_failed = int(np.sum(~np.isfinite(out_aic))
                   - (short_np.sum() if short_np is not None else 0))
    if n_failed:
        warnings.warn(
            f"auto_fit_panel: no admissible ARMA candidate for {n_failed} "
            f"series; their aic is +inf and coefficients are zero",
            stacklevel=3)
    return PanelARIMAFit(out_orders, out_coefs, out_aic, max_p)
