"""Streaming fit engine: shape-bucketed AOT executables, buffer donation,
and library-grade chunk pipelining.

The bench trajectory (BENCH_r04/r05) shows batched-fit throughput limited
by two host-visible costs rather than by the chips: every distinct
``(n_series, n_obs)`` panel shape re-traces and re-compiles the whole fit
program (the tail-latency killer under multi-tenant traffic, where panels
arrive in arbitrary shapes), and the only H2D/compute/D2H overlap in the
tree was an inline double-buffer loop private to ``bench.py``.  This
module is the one path every batched fit takes — the distributed-ARIMA
lesson (PAPERS.md: "Distributed ARIMA Models for Ultra-long Time Series",
ARIMA_PLUS's precompiled in-database fit pipelines) applied to XLA:
amortize compilation across the workload, stream partitions through the
accelerator, and account for both in the metrics registry.

Three tiers, layered:

- **shape bucketing** (:func:`pad_bucket`, promoted here from a static
  check in ``utils.contracts`` — contracts now *imports* the policy it
  asserts): any raw panel shape maps to a canonical padded bucket (series
  to the next power of two, floor 8; observations to the next multiple of
  32, floor 32), so the executable cache sees one shape per bucket
  instead of one per panel.  Padding lanes are all-NaN — exactly the
  shape the existing ragged/resilience machinery masks: the ragged
  valid-window weighting for AOT fits, ``utils.resilience`` health
  classification for resilient fits.  The stable-jaxpr contract
  (``utils.contracts``) is what keeps "same bucket" implying "same
  program".
- **AOT executable cache** (:meth:`FitEngine.fit` /
  :meth:`FitEngine.warmup`): one ``jit(...).lower(...).compile()`` per
  ``(family, bucket, dtype, platform, statics, variant)``, held by the
  engine and counted as ``engine.cache_hits`` / ``engine.cache_misses``.
  ``warmup(families, shapes)`` precompiles ahead of traffic; setting
  ``STS_COMPILE_CACHE=/path`` (or :func:`configure_compile_cache`)
  additionally arms JAX's persistent on-disk compilation cache
  (``jax_compilation_cache_dir``), so a *fresh process* deserializes
  instead of compiling.
- **streaming executor** (:meth:`FitEngine.stream_fit`): the
  double-buffered chunk pipeline that used to live inline in ``bench.py``,
  generalized — prefetch-depth-controlled H2D/compute/D2H overlap (JAX
  dispatch is async; at most ``prefetch`` chunks live on device),
  ``donate_argnums`` on the panel buffer so successive chunks reuse the
  same HBM in place (auto-disabled on CPU, where XLA cannot alias the
  buffer), ragged-tail bucketing (a tail chunk pads to its own series
  bucket, not the full chunk shape), and per-chunk failure isolation —
  a poisoned chunk is *recorded* in the result and in
  ``engine.chunk_failures``, never raised, matching the bench-tier
  semantics it replaces.

Numerics contract: a panel already at its bucket shape (dense, no NaN)
runs the exact program ``jax.jit(models.<family>.fit)`` would run —
bit-for-bit identical results; a panel padded on the series axis keeps
every real lane bit-for-bit (all-NaN lanes are weighted out exactly);
padding on the observation axis routes through the ragged valid-window
weighting, whose results match trimmed per-series fits to float rounding
(the documented ``ops.ragged`` equivalence, pinned by
``tests/test_ragged.py``).  Eager callers note: eager-vs-jit float32
differences are pre-existing XLA fusion noise, not introduced here — the
"pre-engine path" for every batched workload (bench, production
pipelines) was already the jitted fit.

``Panel.fit_resilient`` and ``models.arima.fit_panel`` route through the
module-level :func:`default_engine`; ``bench.py`` consumes
:meth:`FitEngine.stream_fit` and embeds the ``engine.*`` counters in
every BENCH record.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple)

import numpy as np

from .utils import metrics as _metrics

__all__ = [
    "SERIES_BUCKET_FLOOR", "OBS_BUCKET_MULTIPLE",
    "pad_bucket", "series_bucket",
    "configure_compile_cache",
    "FitEngine", "StreamResult", "default_engine",
    "ENGINE_FAMILIES", "RAGGED_FAMILIES",
]

# ---------------------------------------------------------------------------
# bucket policy (the single source of truth; utils.contracts re-exports)
# ---------------------------------------------------------------------------

# series round up to a power of two (floor 8), observation counts to a
# multiple of 32 (floor 32).  Raw shapes in the same bucket share one
# compiled program; the stable-jaxpr contract keeps that true.
SERIES_BUCKET_FLOOR = 8
OBS_BUCKET_MULTIPLE = 32


def series_bucket(n_series: int) -> int:
    """Series-axis bucket: next power of two, floor 8."""
    s = SERIES_BUCKET_FLOOR
    while s < n_series:
        s *= 2
    return s


def pad_bucket(n_series: int, n_obs: int) -> Tuple[int, int]:
    """Canonical padded shape for a raw panel shape: series to the next
    power of two (floor 8), observations to the next multiple of 32
    (floor 32)."""
    t = max(OBS_BUCKET_MULTIPLE,
            -(-n_obs // OBS_BUCKET_MULTIPLE) * OBS_BUCKET_MULTIPLE)
    return series_bucket(n_series), t


# ---------------------------------------------------------------------------
# persistent compilation cache (STS_COMPILE_CACHE)
# ---------------------------------------------------------------------------

_cache_state = {"dir": None}


def configure_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Arm JAX's persistent on-disk compilation cache.

    ``path`` (or, when None, the ``STS_COMPILE_CACHE`` environment
    variable) becomes ``jax_compilation_cache_dir``; the
    minimum-compile-time threshold is dropped to 0 so even fast fit
    programs persist.  Returns the armed directory, or None when neither
    source names one (the cache stays off — JAX's default).  Idempotent;
    a fresh process pointed at a warm directory deserializes executables
    instead of compiling them (``jax.cache_hits`` in the metrics
    registry counts the proof).
    """
    if path is None:
        path = os.environ.get("STS_COMPILE_CACHE")
    if not path:
        return None
    if _cache_state["dir"] == path:
        return path
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except AttributeError:  # pragma: no cover — much older jax
        pass
    _cache_state["dir"] = path
    _metrics.set_gauge("engine.compile_cache_enabled", 1.0)
    return path


# ---------------------------------------------------------------------------
# family table: statics builders + traced fit dispatch
# ---------------------------------------------------------------------------

# statics builders turn an engine call's kwargs into the hashable tuple
# that keys the executable cache AND parameterizes the traced fit.  An
# unknown kwarg raises TypeError, which `fit` treats as "bypass to the
# direct eager path" (e.g. arima's user_init_params array cannot be a
# static).
_STATICS_BUILDERS: Dict[str, Callable[..., tuple]] = {
    "arima": lambda p=2, d=1, q=2, include_intercept=True,
    method="css-lm", max_iter=None, retry=None:
        (int(p), int(d), int(q), bool(include_intercept), str(method),
         max_iter, retry),
    "ar": lambda max_lag=2, no_intercept=False:
        (int(max_lag), bool(no_intercept)),
    "ewma": lambda: (),
    "garch": lambda: (),
    "argarch": lambda: (),
    "egarch": lambda: (),
    "holt_winters": lambda period=12, model_type="additive":
        (int(period), str(model_type)),
}

ENGINE_FAMILIES = tuple(_STATICS_BUILDERS)

# families whose fit accepts an explicit left-aligned valid-window length
# (`n_valid=`), enabling the fully-traced ragged variant that
# observation-axis padding needs.  The x-carrying families (arimax, arx,
# regression_arima) stay on the direct / resilient paths: their exogenous
# regressor matrices would need the same obs-axis padding treatment.
RAGGED_FAMILIES = ("arima", "ar")


def _family_fit(family: str, statics: tuple, values, n_valid):
    """One batched fit, dispatched by (family, statics) — runs under the
    engine's jit trace, so every entry point is the undecorated
    ``.__wrapped__`` (spans/counters are host-side; the engine records
    its own, off the reconstructed model)."""
    from . import models as m

    if family == "arima":
        p, d, q, icpt, method, max_iter, retry = statics
        return m.arima.fit.__wrapped__(
            p, d, q, values, include_intercept=icpt, method=method,
            max_iter=max_iter, retry=retry, warn=False, n_valid=n_valid)
    if family == "ar":
        max_lag, no_icpt = statics
        return m.autoregression.fit.__wrapped__(
            values, max_lag, no_intercept=no_icpt, n_valid=n_valid)
    if n_valid is not None:
        raise ValueError(
            f"family {family!r} has no traced ragged fit; only "
            f"{RAGGED_FAMILIES} accept observation-axis padding")
    if family == "ewma":
        return m.ewma.fit.__wrapped__(values)
    if family == "garch":
        return m.garch.fit.__wrapped__(values)
    if family == "argarch":
        return m.garch.fit_ar_garch.__wrapped__(values)
    if family == "egarch":
        return m.garch.fit_egarch.__wrapped__(values)
    if family == "holt_winters":
        period, model_type = statics
        return m.holt_winters.fit.__wrapped__(values, period,
                                              model_type=model_type)
    raise ValueError(f"unknown engine family {family!r}; expected one of "
                     f"{sorted(_STATICS_BUILDERS)}")


class _Skeleton(NamedTuple):
    """Trace-time structure of a fitted model pytree: how to rebuild the
    host model from the executable's array outputs.  ``static_leaves``
    holds the (position, value) pairs of non-array leaves (model orders,
    flags) captured during tracing; ``array_pos`` the positions the
    executable's outputs fill."""
    treedef: Any
    static_leaves: Tuple[Tuple[int, Any], ...]
    array_pos: Tuple[int, ...]
    n_leaves: int


_skeleton_capture = threading.local()


def _is_arrayish(leaf: Any) -> bool:
    return hasattr(leaf, "dtype") and hasattr(leaf, "shape")


def _split_model(model, values, n_real):
    """Shared tail of both traced variants: flatten the fitted model,
    capture its skeleton (trace-time only), and reduce a lane-masked
    converged count."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(model)
    pos = tuple(i for i, leaf in enumerate(leaves) if _is_arrayish(leaf))
    slot = getattr(_skeleton_capture, "slot", None)
    if slot is not None:
        slot["skeleton"] = _Skeleton(
            treedef,
            tuple((i, leaves[i]) for i in range(len(leaves))
                  if i not in pos),
            pos, len(leaves))
    lane = jnp.arange(values.shape[0], dtype=jnp.int32) < n_real
    diag = getattr(model, "diagnostics", None)
    conv = getattr(diag, "converged", None) if diag is not None else None
    if conv is not None:
        n_conv = jnp.sum(jnp.where(lane, jnp.reshape(conv, (-1,)), False))
    else:
        n_conv = jnp.sum(lane)
    return [leaves[i] for i in pos], n_conv


def _dense_fit(family: str, statics: tuple, values, n_real):
    """Traced dense fit: exactly the program ``jax.jit(fit)`` runs, plus
    a lane-masked converged count (padding lanes — zero rows on the
    stream tier — self-quarantine per lane and are sliced off host-side)."""
    return _split_model(_family_fit(family, statics, values, None),
                        values, n_real)


def _ragged_fit(family: str, statics: tuple, values, n_real):
    """Traced ragged fit: NaN-padded input (leading/trailing per lane —
    bucket padding is all-NaN lanes plus trailing observation columns) is
    left-aligned in-trace and fitted against its explicit per-lane valid
    window, so one executable serves every raw shape in the bucket."""
    from .ops.ragged import _left_align

    aligned, length, _ = _left_align(values)
    return _split_model(_family_fit(family, statics, aligned, length),
                        values, n_real)


# Module-level jit wrappers (one function object per variant x donation,
# so repeated lowers share jax's jit cache; see STS006).  values sits at
# argument 2; family and statics are static.
def _make_jits():
    import jax
    table = {}
    for variant, fn in (("dense", _dense_fit), ("ragged", _ragged_fit)):
        table[variant, False] = jax.jit(fn, static_argnums=(0, 1))
        table[variant, True] = jax.jit(fn, static_argnums=(0, 1),
                                       donate_argnums=(2,))
    return table


_jit_table: Dict[Tuple[str, bool], Any] = {}
_jit_lock = threading.Lock()


def _jit_for(variant: str, donate: bool):
    with _jit_lock:
        if not _jit_table:
            _jit_table.update(_make_jits())
        return _jit_table[variant, donate]


# ---------------------------------------------------------------------------
# host-side input classification
# ---------------------------------------------------------------------------

def _host_view(values) -> Optional[np.ndarray]:
    """Zero-copy numpy view when the input already lives on host."""
    if isinstance(values, np.ndarray):
        return values
    return None


def _has_nan(values) -> bool:
    if not np.issubdtype(np.asarray(values).dtype if isinstance(
            values, np.ndarray) else values.dtype, np.floating):
        return False
    host = _host_view(values)
    if host is not None:
        return bool(np.isnan(host).any())
    # device input: one tiny reduction instead of pulling the panel
    import jax.numpy as jnp
    return bool(jnp.any(jnp.isnan(values)))


def _interior_gap_count(host: np.ndarray) -> int:
    """Lanes with NaN strictly inside their observed window (the class
    the ragged machinery cannot mask — same policy as
    ``ops.ragged.ragged_view``, checked host-side because the engine's
    traced fits cannot raise on data)."""
    obs = ~np.isnan(host)
    n = host.shape[-1]
    any_obs = obs.any(axis=-1)
    start = obs.argmax(axis=-1)
    last = n - 1 - obs[:, ::-1].argmax(axis=-1)
    window = np.where(any_obs, last - start + 1, 0)
    return int(np.sum(obs.sum(axis=-1) != window))


def _multi_device(values) -> bool:
    sharding = getattr(values, "sharding", None)
    if sharding is None:
        return False
    try:
        return len(sharding.device_set) > 1
    except Exception:  # noqa: BLE001 — exotic sharding: be conservative
        return True


# ---------------------------------------------------------------------------
# results
# ---------------------------------------------------------------------------

class StreamResult(NamedTuple):
    """Outcome of one :meth:`FitEngine.stream_fit` pass.

    ``n_fitted`` counts the series whose chunks completed (``n_series``
    minus poisoned-chunk lanes); ``models`` is None unless
    ``collect=True`` (then a list of per-chunk host model pytrees, lanes
    sliced back to the chunk's real count).  ``stats`` carries the
    per-call engine accounting bench embeds: cache hits/misses, compile
    seconds, bytes donated/transferred, pad lanes, chunk count."""
    n_series: int
    n_fitted: int
    n_converged: int
    wall_s: float
    n_chunks: int
    chunk_failures: List[Dict[str, Any]]
    models: Optional[List[Any]]
    stats: Dict[str, Any]

    @property
    def rate(self) -> float:
        """Fitted series per second (0 when nothing completed)."""
        return self.n_fitted / self.wall_s if self.wall_s > 0 else 0.0


class _Entry(NamedTuple):
    compiled: Any
    skeleton: _Skeleton
    bucket: Tuple[int, int]
    variant: str
    donate: bool


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class FitEngine:
    """Shape-bucketed AOT executable cache + streaming chunk executor.

    One engine instance owns one executable cache; the module-level
    :func:`default_engine` is what ``Panel.fit_resilient`` and
    ``models.arima.fit_panel`` route through.  Thread-safe: the cache is
    lock-guarded, and executables themselves are immutable.

    ``donate``: ``None`` (auto) donates chunk buffers on accelerators and
    skips donation on CPU (XLA CPU cannot alias them and would warn);
    True/False force.  ``prefetch``: how many dispatched chunks may be
    pending ahead of the one being drained in :meth:`stream_fit`
    (1 = the classic double buffer — two chunks live during overlap;
    the default 2 keeps a third in flight to ride out pull jitter).
    """

    def __init__(self, *, registry: Optional[Any] = None,
                 prefetch: int = 2, donate: Optional[bool] = None,
                 compile_cache_dir: Optional[str] = None):
        self._reg = registry if registry is not None \
            else _metrics.get_registry()
        self.prefetch = max(1, int(prefetch))
        self._donate = donate
        self._lock = threading.RLock()
        self._entries: Dict[tuple, _Entry] = {}
        configure_compile_cache(compile_cache_dir)

    # -- donation policy ----------------------------------------------------

    def donate_default(self) -> bool:
        if self._donate is not None:
            return bool(self._donate)
        import jax
        return jax.default_backend() != "cpu"

    # -- executable cache ---------------------------------------------------

    def _entry(self, family: str, statics: tuple, bucket: Tuple[int, int],
               dtype, variant: str, donate: bool) -> _Entry:
        import jax

        # canonicalize the key dtype: under x64-off, f64 input lowers to
        # the byte-identical f32 program — two raw-dtype keys would
        # compile it twice and double-count cache misses
        dtype = jax.dtypes.canonicalize_dtype(np.dtype(dtype))
        key = (family, statics, bucket, str(dtype), variant,
               donate, jax.default_backend())
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._reg.inc("engine.cache_hits")
                return hit
        # compile outside the lock: one slow compile must not serialize
        # unrelated buckets (a duplicate race costs one redundant compile,
        # resolved by last-write-wins below)
        self._reg.inc("engine.cache_misses")
        jitted = _jit_for(variant, donate)
        spec_v = jax.ShapeDtypeStruct(bucket, dtype)
        spec_n = jax.ShapeDtypeStruct((), np.int32)
        slot: Dict[str, Any] = {}
        _skeleton_capture.slot = slot
        try:
            with _metrics.span("engine.compile"):
                compiled = jitted.lower(family, statics, spec_v,
                                        spec_n).compile()
        finally:
            _skeleton_capture.slot = None
        skeleton = slot.get("skeleton")
        if skeleton is None:
            # jit served the lowering from its cache without re-tracing;
            # one abstract re-trace recovers the skeleton
            _skeleton_capture.slot = slot
            try:
                jax.eval_shape(
                    lambda v, n: (_dense_fit if variant == "dense"
                                  else _ragged_fit)(family, statics, v, n),
                    spec_v, spec_n)
            finally:
                _skeleton_capture.slot = None
            skeleton = slot["skeleton"]
        entry = _Entry(compiled, skeleton, bucket, variant, donate)
        with self._lock:
            self._entries[key] = entry
            self._reg.set_gauge("engine.executables", len(self._entries))
        return entry

    def cache_stats(self) -> Dict[str, int]:
        snap = self._reg.snapshot()["counters"]
        with self._lock:
            n = len(self._entries)
        return {"executables": n,
                "cache_hits": int(snap.get("engine.cache_hits", 0)),
                "cache_misses": int(snap.get("engine.cache_misses", 0))}

    # -- model reconstruction ----------------------------------------------

    @staticmethod
    def _rebuild(skeleton: _Skeleton, arrays: Sequence[Any],
                 n_series: int, n_obs: int, bucket: Tuple[int, int]):
        """Executable outputs -> host model pytree, padding sliced off:
        leading dims equal to the series bucket shrink to ``n_series``;
        second dims equal to an *expanded* obs bucket shrink to
        ``n_obs``.  Slicing happens host-side — a device-side gather
        would compile one tiny program per raw shape, which is exactly
        the per-shape compile churn the bucketed cache exists to kill."""
        import jax
        import jax.numpy as jnp

        bs, bt = bucket
        leaves: List[Any] = [None] * skeleton.n_leaves
        for i, val in skeleton.static_leaves:
            leaves[i] = val
        for i, arr in zip(skeleton.array_pos, arrays):
            if hasattr(arr, "ndim") and arr.ndim >= 1:
                cut0 = arr.shape[0] == bs and bs != n_series
                cut1 = arr.ndim >= 2 and bt != n_obs and arr.shape[1] == bt
                if cut0 or cut1:
                    host = np.asarray(arr)
                    if cut0:
                        host = host[:n_series]
                    if cut1:
                        host = host[:, :n_obs]
                    arr = jnp.asarray(host)
            leaves[i] = arr
        return jax.tree_util.tree_unflatten(skeleton.treedef, leaves)

    # -- single-shot bucketed fit (the Panel / fit_panel tier) --------------

    def fit(self, values, family: str = "arima", *,
            bucket_obs: bool = True, warn: bool = False, **kwargs):
        """Fit one panel through the bucketed executable cache.

        ``values (n_series, n_obs)``; ``kwargs`` are the family's static
        fit parameters (arima: ``p``/``d``/``q``/``include_intercept``/
        ``method``/``max_iter``/``retry``).  Returns the fitted model
        with padding lanes/columns sliced off, so the result is shaped
        exactly as the direct fit's would be.

        Routing: a panel already at its bucket shape runs the dense
        program (bit-for-bit the jitted direct fit); series-only padding
        keeps the dense program with zero-padded lanes (real lanes
        bit-for-bit, pad lanes sliced off); NaN input or observation-axis
        padding takes the traced ragged program (:data:`RAGGED_FAMILIES`
        — valid-window weighted, trimmed-fit equivalent to float
        rounding).  Inputs the engine cannot bucket (non-2D, multi-device
        sharded, unknown families, non-static kwargs such as arima's
        ``user_init_params``) fall back to the direct eager fit and count
        ``engine.bypass``.

        Padding happens host-side (device-side slicing/padding would
        compile one tiny program per raw shape — the churn the bucket
        kills), so a *device-resident* panel that is not bucket-exact
        pays one D2H+H2D round trip per fit; keep hot device-resident
        loops at bucket-exact shapes (the bench's device-resident block
        does) or feed host arrays.
        """
        builder = _STATICS_BUILDERS.get(family)
        if builder is None or getattr(values, "ndim", None) != 2 \
                or _multi_device(values) \
                or not np.issubdtype(np.dtype(getattr(values, "dtype",
                                                      np.float64)),
                                     np.floating):
            return self._direct(values, family, warn, kwargs)
        try:
            statics = builder(**kwargs)
        except TypeError:
            return self._direct(values, family, warn, kwargs)

        with _metrics.span("engine.fit"):
            n_series, n_obs = values.shape
            bs, bt = pad_bucket(n_series, n_obs)
            if not bucket_obs:
                bt = n_obs
            has_nan = _has_nan(values)
            dtype = values.dtype

            if not has_nan and (n_series, n_obs) == (bs, bt):
                entry = self._entry(family, statics, (bs, bt), dtype,
                                    "dense", False)
                arrays, _ = entry.compiled(values, np.int32(n_series))
            elif not has_nan and n_obs == bt:
                # series-only padding: zero lanes quarantine themselves
                # per lane and are sliced off — real lanes bit-for-bit
                host = np.asarray(values)
                padded = np.zeros((bs, bt), host.dtype)
                padded[:n_series] = host
                self._reg.inc("engine.pad_lanes", bs - n_series)
                entry = self._entry(family, statics, (bs, bt), dtype,
                                    "dense", False)
                arrays, _ = entry.compiled(padded, np.int32(n_series))
            else:
                if family not in RAGGED_FAMILIES:
                    return self._direct(values, family, warn, kwargs)
                host = np.asarray(values)
                gaps = _interior_gap_count(host)
                if gaps:
                    raise ValueError(
                        f"{gaps} lane(s) have NaN strictly inside their "
                        f"observed window; valid-window fits need "
                        f"contiguous observations — impute interior gaps "
                        f"first (e.g. Panel.fill), leading/trailing "
                        f"padding needs no fill")
                padded = np.full((bs, bt), np.nan, host.dtype)
                padded[:n_series, :n_obs] = host
                self._reg.inc("engine.pad_lanes", bs - n_series)
                self._reg.inc("engine.pad_obs", bt - n_obs)
                entry = self._entry(family, statics, (bs, bt), dtype,
                                    "ragged", False)
                arrays, _ = entry.compiled(padded, np.int32(n_series))

            model = self._rebuild(entry.skeleton, arrays, n_series, n_obs,
                                  entry.bucket)
            self._reg.inc("engine.fits")
        _metrics.record_fit(family, model, self._reg)
        if warn and family == "arima":
            from .models.arima import _warn_stationarity_invertibility
            _warn_stationarity_invertibility(model, True)
        return model

    def _direct(self, values, family: str, warn: bool, kwargs):
        """Bypass: the family's public eager fit, untouched semantics."""
        self._reg.inc("engine.bypass")
        from . import models as m

        if family == "arima":
            kw = dict(kwargs)
            p, d, q = kw.pop("p", 2), kw.pop("d", 1), kw.pop("q", 2)
            return m.arima.fit(p, d, q, values, warn=warn, **kw)
        table = {
            "ar": m.autoregression.fit,
            "ewma": m.ewma.fit,
            "garch": m.garch.fit,
            "argarch": m.garch.fit_ar_garch,
            "egarch": m.garch.fit_egarch,
            "holt_winters": m.holt_winters.fit,
        }
        if family not in table:
            raise ValueError(
                f"unknown engine family {family!r}; expected one of "
                f"{sorted(_STATICS_BUILDERS)}")
        return table[family](values, **kwargs)

    # -- resilient tier (the Panel.fit_resilient front-end) -----------------

    @staticmethod
    def resilient_dispatch(family: str) -> Callable:
        """The family's ``fit_resilient`` entry point (the direct,
        unbucketed chain)."""
        from . import models
        dispatch = {
            "arima": models.arima.fit_resilient,
            "arimax": models.arimax.fit_resilient,
            "ar": models.autoregression.fit_resilient,
            "arx": models.autoregression_x.fit_resilient,
            "ewma": models.ewma.fit_resilient,
            "garch": models.garch.fit_resilient,
            "argarch": models.garch.fit_ar_garch_resilient,
            "egarch": models.garch.fit_egarch_resilient,
            "holt_winters": models.holt_winters.fit_resilient,
            "regression_arima": models.regression_arima.fit_resilient,
        }
        if family not in dispatch:
            raise ValueError(f"unknown model family {family!r}; expected "
                             f"one of {sorted(dispatch)}")
        return dispatch[family]

    def fit_resilient(self, values, family: str, *args, **kwargs):
        """Bucket the series axis, run the family's ``fit_resilient``
        chain, slice the padding back off.

        Padding lanes are all-NaN, so the existing resilience health
        machinery classifies them unfittable and masks them out of every
        stage — real lanes are bit-for-bit the unbucketed chain's result.
        The observation axis is deliberately NOT padded here: the
        resilient stages run eagerly (where ragged handling is
        value-dependent), several families carry ``(n_obs, k)`` exogenous
        regressors that would need matching pads, and series count is
        what actually varies under multi-tenant traffic.  Returns
        ``(model, FitOutcome)`` shaped for the REAL lanes.
        """
        fit_fn = self.resilient_dispatch(family)
        if getattr(values, "ndim", None) != 2 or _multi_device(values) \
                or not np.issubdtype(np.dtype(getattr(values, "dtype",
                                                      np.float64)),
                                     np.floating):
            return fit_fn(values, *args, **kwargs)

        n_series, n_obs = values.shape
        bs = series_bucket(n_series)
        if bs == n_series:
            return fit_fn(values, *args, **kwargs)

        import jax.numpy as jnp

        host = np.asarray(values)
        padded = np.full((bs, n_obs), np.nan, host.dtype)
        padded[:n_series] = host
        self._reg.inc("engine.pad_lanes", bs - n_series)
        model, outcome = fit_fn(jnp.asarray(padded), *args, **kwargs)
        model = self._slice_lanes(model, n_series, bs)
        outcome = type(outcome)(
            None if outcome.params is None else outcome.params[:n_series],
            outcome.status[:n_series], outcome.attempts[:n_series],
            outcome.fallback_used[:n_series], outcome.health[:n_series])
        return model, outcome

    @staticmethod
    def _slice_lanes(model, n_series: int, bucket_s: int):
        import jax

        def cut(leaf):
            if hasattr(leaf, "ndim") and getattr(leaf, "ndim", 0) >= 1 \
                    and leaf.shape[0] == bucket_s:
                return leaf[:n_series]
            return leaf

        return jax.tree_util.tree_map(cut, model)

    # -- warmup -------------------------------------------------------------

    def warmup(self, families: Sequence[str] = ("arima",),
               shapes: Sequence[Tuple[int, int]] = ((1024, 128),),
               *, dtype=None, variants: Optional[Sequence[str]] = None,
               bucket: bool = True, **kwargs) -> Dict[str, Any]:
        """Precompile executables ahead of traffic: one AOT compile per
        ``(family, bucket(shape), variant)``.  ``kwargs`` parameterize
        every family's statics (families that reject a kwarg use their
        defaults).  With ``STS_COMPILE_CACHE`` armed the compiles also
        persist to disk, so the *next* process warms from deserialization
        alone.  Returns a summary of what was built.

        ``bucket=False`` uses each shape verbatim as the executable
        shape instead of padding it through :func:`pad_bucket` — the
        streaming tier's keying, where full chunks run at their exact
        ``(chunk_size, n_obs)`` (obs-axis padding would change dense
        chunk numerics) — and compiles with the engine's stream-tier
        donation default so the cache key matches what
        :meth:`stream_fit` will look up.  Warm a stream with the exact
        chunk and tail shapes (see ``bench.py``); warm single-shot
        :meth:`fit` traffic with the default bucketing."""
        import jax

        if dtype is None:
            import jax.numpy as jnp
            dtype = jnp.float32
        built = []
        t0 = time.perf_counter()
        with _metrics.span("engine.warmup"):
            for family in families:
                builder = _STATICS_BUILDERS.get(family)
                if builder is None:
                    raise ValueError(
                        f"unknown engine family {family!r}; expected a "
                        f"subset of {sorted(_STATICS_BUILDERS)}")
                try:
                    statics = builder(**kwargs)
                except TypeError:
                    statics = builder()
                fam_variants = variants if variants is not None else (
                    ("dense", "ragged") if family in RAGGED_FAMILIES
                    else ("dense",))
                don = False if bucket else self.donate_default()
                for shape in shapes:
                    bkt = pad_bucket(*shape) if bucket else tuple(shape)
                    for variant in fam_variants:
                        self._entry(family, statics, bkt, dtype,
                                    variant, don)
                        built.append({"family": family,
                                      "bucket": list(bkt),
                                      "variant": variant})
        return {"built": built, "wall_s": round(time.perf_counter() - t0, 3),
                "platform": jax.default_backend(),
                **self.cache_stats()}

    # -- streaming executor (the bench tier) --------------------------------

    def stream_fit(self, values, family: str = "arima", *,
                   chunk_size: int = 131072,
                   prefetch: Optional[int] = None,
                   donate: Optional[bool] = None,
                   collect: bool = False, **kwargs) -> StreamResult:
        """Fit a panel larger than device memory by streaming chunks.

        Pipelining: each chunk's H2D transfer + fit is dispatched (JAX
        dispatch is async) while earlier chunks' results are still being
        pulled, so transfer, compute, and result D2H overlap; at most
        ``prefetch`` dispatched chunks wait ahead of the one being
        drained (``prefetch + 1`` briefly live on device).  Chunk
        buffers are
        engine-owned and (on accelerators) donated to the executable, so
        successive chunks reuse the same HBM in place.  The tail chunk
        pads to its own series bucket — not the full chunk shape — and
        both tail and full-chunk executables come from the bucketed
        cache, so re-streaming any same-shaped workload compiles nothing.

        Failure isolation: a chunk whose dispatch or host materialization
        raises is recorded in ``chunk_failures`` (and the
        ``engine.chunk_failures`` counter) and skipped; the stream never
        dies on one poisoned chunk.

        Timing covers dispatch through host materialization of every
        chunk's outputs — the real pipeline cost for out-of-core panels.
        """
        import jax

        builder = _STATICS_BUILDERS.get(family)
        if builder is None:
            raise ValueError(
                f"unknown engine family {family!r}; expected one of "
                f"{sorted(_STATICS_BUILDERS)}")
        statics = builder(**kwargs)
        host = values if isinstance(values, np.ndarray) \
            else np.asarray(values)
        if host.ndim != 2:
            raise ValueError(
                f"stream_fit needs a (n_series, n_obs) panel, "
                f"got {host.shape}")
        n_series, n_obs = host.shape
        chunk = max(1, min(int(chunk_size), n_series))
        depth = self.prefetch if prefetch is None else max(1, int(prefetch))
        don = self.donate_default() if donate is None else bool(donate)
        before = self.cache_stats()

        conv = 0
        failures: List[Dict[str, Any]] = []
        models: Optional[List[Any]] = [] if collect else None
        pending: deque = deque()

        def record_failure(start: int, n_real: int, e: Exception) -> None:
            failures.append({"chunk_start": int(start),
                             "n_series": int(n_real),
                             "error": f"{type(e).__name__}: {e}"})
            self._reg.inc("engine.chunk_failures")
            _metrics.trace_instant("engine.chunk_failure",
                                   {"chunk_start": int(start),
                                    "error": type(e).__name__})

        def pull(out, entry: _Entry, start: int, n_real: int) -> None:
            nonlocal conv
            with _metrics.span("engine.collect"):
                try:
                    arrays = [np.asarray(a) for a in out[0]]
                    conv += int(out[1])
                except Exception as e:  # noqa: BLE001 — deferred device
                    # errors surface at materialization; isolate the chunk
                    record_failure(start, n_real, e)
                    return
            self._reg.inc("engine.chunks")
            if models is not None:
                models.append(self._rebuild(entry.skeleton, arrays, n_real,
                                            n_obs, entry.bucket))

        t0 = time.perf_counter()
        with _metrics.span("engine.stream"):
            for start in range(0, n_series, chunk):
                part = host[start:start + chunk]
                n_real = part.shape[0]
                bs = chunk if n_real == chunk \
                    else min(series_bucket(n_real), chunk)
                variant = "dense"
                if np.issubdtype(part.dtype, np.floating) \
                        and np.isnan(part).any():
                    if family not in RAGGED_FAMILIES:
                        record_failure(start, n_real, ValueError(
                            f"NaN input needs a traced ragged fit; "
                            f"family {family!r} has none "
                            f"(only {RAGGED_FAMILIES})"))
                        continue
                    variant = "ragged"
                    gaps = _interior_gap_count(part)
                    if gaps:
                        # same contract as FitEngine.fit, stream-tier
                        # semantics: recorded, not raised
                        record_failure(start, n_real, ValueError(
                            f"{gaps} lane(s) have NaN strictly inside "
                            f"their observed window; impute interior "
                            f"gaps first"))
                        continue
                if n_real != bs:          # ragged tail: its own bucket
                    fill = np.nan if variant == "ragged" else 0.0
                    padded = np.full((bs, n_obs), fill, part.dtype)
                    padded[:n_real] = part
                    part = padded
                    self._reg.inc("engine.pad_lanes", bs - n_real)
                try:
                    entry = self._entry(family, statics, (bs, n_obs),
                                        part.dtype, variant, don)
                    with _metrics.span("engine.dispatch"):
                        dev = jax.device_put(part)
                        out = entry.compiled(dev, np.int32(n_real))
                    self._reg.inc("engine.bytes_h2d", int(part.nbytes))
                    if don:
                        self._reg.inc("engine.bytes_donated",
                                      int(part.nbytes))
                except Exception as e:  # noqa: BLE001 — same isolation
                    record_failure(start, n_real, e)
                    continue
                pending.append((out, entry, start, n_real))
                while len(pending) >= depth + 1:
                    pull(*pending.popleft())
            while pending:
                pull(*pending.popleft())
        wall = time.perf_counter() - t0

        after = self.cache_stats()
        n_failed = sum(f["n_series"] for f in failures)
        stats = {
            "cache_hits": after["cache_hits"] - before["cache_hits"],
            "cache_misses": after["cache_misses"] - before["cache_misses"],
            "executables": after["executables"],
            "donated": don,
            "prefetch": depth,
            "chunk_size": chunk,
        }
        return StreamResult(n_series, max(n_series - n_failed, 0), conv,
                            wall, -(-n_series // chunk), failures, models,
                            stats)


# ---------------------------------------------------------------------------
# default engine
# ---------------------------------------------------------------------------

_default_engine: Optional[FitEngine] = None
_default_lock = threading.Lock()


def default_engine() -> FitEngine:
    """The process-wide engine instance ``Panel`` and ``fit_panel`` route
    through (lazily created; ``STS_COMPILE_CACHE`` is honored at
    creation)."""
    global _default_engine
    with _default_lock:
        if _default_engine is None:
            _default_engine = FitEngine()
        return _default_engine


# ---------------------------------------------------------------------------
# CLI: `python -m spark_timeseries_tpu.engine` (the `make warmup` target)
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m spark_timeseries_tpu.engine",
        description="Precompile fit executables at the given shapes "
                    "(with STS_COMPILE_CACHE set, persists them to disk "
                    "so later processes skip compiles entirely).")
    ap.add_argument("--families", default="arima",
                    help=f"comma-separated subset of {ENGINE_FAMILIES} "
                         f"(default arima)")
    ap.add_argument("--shapes", default="16384x128",
                    help="comma-separated n_seriesXn_obs raw shapes; each "
                         "warms its padding bucket (default 16384x128, "
                         "the CPU bench chunk)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--cache-dir", default=None,
                    help="persistent compile cache directory (default: "
                         "$STS_COMPILE_CACHE when set)")
    args = ap.parse_args(argv)

    families = [f for f in args.families.split(",") if f]
    unknown = [f for f in families if f not in _STATICS_BUILDERS]
    if unknown:
        ap.error(f"unknown families {unknown}; expected subset of "
                 f"{sorted(_STATICS_BUILDERS)}")
    shapes = []
    try:
        for tok in args.shapes.split(","):
            if not tok:
                continue
            s, t = (int(x) for x in tok.lower().split("x"))
            if s < 1 or t < 1:
                raise ValueError
            shapes.append((s, t))
        if not shapes:
            raise ValueError
    except ValueError:
        ap.error(f"--shapes must be <n_series>x<n_obs>[,...] with positive "
                 f"ints, got {args.shapes!r}")

    _metrics.install_jax_hooks()
    eng = FitEngine(compile_cache_dir=args.cache_dir)
    report = eng.warmup(families, shapes, dtype=np.dtype(args.dtype))
    report["jax"] = _metrics.jax_stats()
    print(json.dumps(report, indent=1))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
