"""Streaming fit engine: shape-bucketed AOT executables, buffer donation,
and library-grade chunk pipelining.

The bench trajectory (BENCH_r04/r05) shows batched-fit throughput limited
by two host-visible costs rather than by the chips: every distinct
``(n_series, n_obs)`` panel shape re-traces and re-compiles the whole fit
program (the tail-latency killer under multi-tenant traffic, where panels
arrive in arbitrary shapes), and the only H2D/compute/D2H overlap in the
tree was an inline double-buffer loop private to ``bench.py``.  This
module is the one path every batched fit takes — the distributed-ARIMA
lesson (PAPERS.md: "Distributed ARIMA Models for Ultra-long Time Series",
ARIMA_PLUS's precompiled in-database fit pipelines) applied to XLA:
amortize compilation across the workload, stream partitions through the
accelerator, and account for both in the metrics registry.

Three tiers, layered:

- **shape bucketing** (:func:`pad_bucket`, promoted here from a static
  check in ``utils.contracts`` — contracts now *imports* the policy it
  asserts): any raw panel shape maps to a canonical padded bucket (series
  to the next power of two, floor 8; observations to the next multiple of
  32, floor 32), so the executable cache sees one shape per bucket
  instead of one per panel.  Padding lanes are all-NaN — exactly the
  shape the existing ragged/resilience machinery masks: the ragged
  valid-window weighting for AOT fits, ``utils.resilience`` health
  classification for resilient fits.  The stable-jaxpr contract
  (``utils.contracts``) is what keeps "same bucket" implying "same
  program".
- **AOT executable cache** (:meth:`FitEngine.fit` /
  :meth:`FitEngine.warmup`): one ``jit(...).lower(...).compile()`` per
  ``(family, bucket, dtype, platform, statics, variant)``, held by the
  engine and counted as ``engine.cache_hits`` / ``engine.cache_misses``.
  ``warmup(families, shapes)`` precompiles ahead of traffic; setting
  ``STS_COMPILE_CACHE=/path`` (or :func:`configure_compile_cache`)
  additionally arms JAX's persistent on-disk compilation cache
  (``jax_compilation_cache_dir``), so a *fresh process* deserializes
  instead of compiling.
- **streaming executor** (:meth:`FitEngine.stream_fit`): the
  double-buffered chunk pipeline that used to live inline in ``bench.py``,
  generalized — prefetch-depth-controlled H2D/compute/D2H overlap (JAX
  dispatch is async; at most ``prefetch`` chunks live on device),
  ``donate_argnums`` on the panel buffer so successive chunks reuse the
  same HBM in place (auto-disabled on CPU, where XLA cannot alias the
  buffer), ragged-tail bucketing (a tail chunk pads to its own series
  bucket, not the full chunk shape), and per-chunk failure isolation —
  a poisoned chunk is *recorded* in the result and in
  ``engine.chunk_failures``, never raised, matching the bench-tier
  semantics it replaces.  On top of it sits the opt-in **durability
  tier** (docs/design.md §3c; ``utils.durability``): a crash-consistent
  chunk journal with validated resume (``journal=``), a per-chunk
  deadline watchdog (``STS_CHUNK_DEADLINE_S``), end-of-stream
  quarantine retries with bounded backoff (``retry=``), and
  OOM-adaptive chunk halving (``engine.degraded_chunks``) — all
  strictly host-side.

Numerics contract: a panel already at its bucket shape (dense, no NaN)
runs the exact program ``jax.jit(models.<family>.fit)`` would run —
bit-for-bit identical results; a panel padded on the series axis keeps
every real lane bit-for-bit (all-NaN lanes are weighted out exactly);
padding on the observation axis routes through the ragged valid-window
weighting, whose results match trimmed per-series fits to float rounding
(the documented ``ops.ragged`` equivalence, pinned by
``tests/test_ragged.py``).  Eager callers note: eager-vs-jit float32
differences are pre-existing XLA fusion noise, not introduced here — the
"pre-engine path" for every batched workload (bench, production
pipelines) was already the jitted fit.

``Panel.fit_resilient`` and ``models.arima.fit_panel`` route through the
module-level :func:`default_engine`; ``bench.py`` consumes
:meth:`FitEngine.stream_fit` and embeds the ``engine.*`` counters in
every BENCH record.
"""

from __future__ import annotations

import os
import signal
import threading
import time
import traceback as _traceback
from collections import deque
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple)

import numpy as np

from .utils import durability as _durability
from .utils import flightrec as _flightrec
from .utils import metrics as _metrics
from .utils import telemetry as _telemetry
from .utils.durability import (BackoffPolicy, ChunkDeadlineExceeded,
                               JournalSpecMismatch)

__all__ = [
    "SERIES_BUCKET_FLOOR", "OBS_BUCKET_MULTIPLE",
    "pad_bucket", "series_bucket",
    "configure_compile_cache",
    "FitEngine", "StreamResult", "default_engine",
    "ENGINE_FAMILIES", "RAGGED_FAMILIES",
    "BackoffPolicy", "ChunkDeadlineExceeded", "JournalSpecMismatch",
]

# ---------------------------------------------------------------------------
# bucket policy (the single source of truth; utils.contracts re-exports)
# ---------------------------------------------------------------------------

# series round up to a power of two (floor 8), observation counts to a
# multiple of 32 (floor 32).  Raw shapes in the same bucket share one
# compiled program; the stable-jaxpr contract keeps that true.
SERIES_BUCKET_FLOOR = 8
OBS_BUCKET_MULTIPLE = 32

# per-chunk phase records kept in StreamResult.stats["phases"]; totals
# keep accumulating past the cap (records_dropped says how many rows
# were elided) — a 1M-series stream must not grow an unbounded stats list
_PHASE_RECORD_CAP = 64


def series_bucket(n_series: int) -> int:
    """Series-axis bucket: next power of two, floor 8."""
    s = SERIES_BUCKET_FLOOR
    while s < n_series:
        s *= 2
    return s


def pad_bucket(n_series: int, n_obs: int) -> Tuple[int, int]:
    """Canonical padded shape for a raw panel shape: series to the next
    power of two (floor 8), observations to the next multiple of 32
    (floor 32)."""
    t = max(OBS_BUCKET_MULTIPLE,
            -(-n_obs // OBS_BUCKET_MULTIPLE) * OBS_BUCKET_MULTIPLE)
    return series_bucket(n_series), t


# ---------------------------------------------------------------------------
# persistent compilation cache (STS_COMPILE_CACHE)
# ---------------------------------------------------------------------------

_cache_state = {"dir": None}


def configure_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Arm JAX's persistent on-disk compilation cache.

    ``path`` (or, when None, the ``STS_COMPILE_CACHE`` environment
    variable) becomes ``jax_compilation_cache_dir``; the
    minimum-compile-time threshold is dropped to 0 so even fast fit
    programs persist.  Returns the armed directory, or None when neither
    source names one (the cache stays off — JAX's default).  Idempotent;
    a fresh process pointed at a warm directory deserializes executables
    instead of compiling them (``jax.cache_hits`` in the metrics
    registry counts the proof).
    """
    if path is None:
        path = os.environ.get("STS_COMPILE_CACHE")
    if not path:
        return None
    if _cache_state["dir"] == path:
        return path
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except AttributeError:  # pragma: no cover — much older jax
        pass
    _cache_state["dir"] = path
    _metrics.set_gauge("engine.compile_cache_enabled", 1.0)
    return path


# ---------------------------------------------------------------------------
# family table: statics builders + traced fit dispatch
# ---------------------------------------------------------------------------

# statics builders turn an engine call's kwargs into the hashable tuple
# that keys the executable cache AND parameterizes the traced fit.  An
# unknown kwarg raises TypeError, which `fit` treats as "bypass to the
# direct eager path" (e.g. arima's user_init_params array cannot be a
# static).
_STATICS_BUILDERS: Dict[str, Callable[..., tuple]] = {
    "arima": lambda p=2, d=1, q=2, include_intercept=True,
    method="css-lm", max_iter=None, retry=None, objective="css":
        (int(p), int(d), int(q), bool(include_intercept), str(method),
         max_iter, retry, str(objective)),
    "ar": lambda max_lag=2, no_intercept=False:
        (int(max_lag), bool(no_intercept)),
    "ewma": lambda: (),
    "garch": lambda: (),
    "argarch": lambda: (),
    "egarch": lambda: (),
    "holt_winters": lambda period=12, model_type="additive":
        (int(period), str(model_type)),
}

ENGINE_FAMILIES = tuple(_STATICS_BUILDERS)

# families whose fit accepts an explicit left-aligned valid-window length
# (`n_valid=`), enabling the fully-traced ragged variant that
# observation-axis padding needs.  The x-carrying families (arimax, arx,
# regression_arima) stay on the direct / resilient paths: their exogenous
# regressor matrices would need the same obs-axis padding treatment.
RAGGED_FAMILIES = ("arima", "ar")


def _family_fit(family: str, statics: tuple, values, n_valid):
    """One batched fit, dispatched by (family, statics) — runs under the
    engine's jit trace, so every entry point is the undecorated
    ``.__wrapped__`` (spans/counters are host-side; the engine records
    its own, off the reconstructed model)."""
    from . import models as m

    if family == "arima":
        p, d, q, icpt, method, max_iter, retry, objective = statics
        return m.arima.fit.__wrapped__(
            p, d, q, values, include_intercept=icpt, method=method,
            max_iter=max_iter, retry=retry, warn=False, n_valid=n_valid,
            objective=objective)
    if family == "ar":
        max_lag, no_icpt = statics
        return m.autoregression.fit.__wrapped__(
            values, max_lag, no_intercept=no_icpt, n_valid=n_valid)
    if n_valid is not None:
        raise ValueError(
            f"family {family!r} has no traced ragged fit; only "
            f"{RAGGED_FAMILIES} accept observation-axis padding")
    if family == "ewma":
        return m.ewma.fit.__wrapped__(values)
    if family == "garch":
        return m.garch.fit.__wrapped__(values)
    if family == "argarch":
        return m.garch.fit_ar_garch.__wrapped__(values)
    if family == "egarch":
        return m.garch.fit_egarch.__wrapped__(values)
    if family == "holt_winters":
        period, model_type = statics
        return m.holt_winters.fit.__wrapped__(values, period,
                                              model_type=model_type)
    raise ValueError(f"unknown engine family {family!r}; expected one of "
                     f"{sorted(_STATICS_BUILDERS)}")


class _Skeleton(NamedTuple):
    """Trace-time structure of a fitted model pytree: how to rebuild the
    host model from the executable's array outputs.  ``static_leaves``
    holds the (position, value) pairs of non-array leaves (model orders,
    flags) captured during tracing; ``array_pos`` the positions the
    executable's outputs fill."""
    treedef: Any
    static_leaves: Tuple[Tuple[int, Any], ...]
    array_pos: Tuple[int, ...]
    n_leaves: int


_skeleton_capture = threading.local()


def _is_arrayish(leaf: Any) -> bool:
    return hasattr(leaf, "dtype") and hasattr(leaf, "shape")


def _split_model(model, values, n_real):
    """Shared tail of both traced variants: flatten the fitted model,
    capture its skeleton (trace-time only), and reduce a lane-masked
    converged count."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(model)
    pos = tuple(i for i, leaf in enumerate(leaves) if _is_arrayish(leaf))
    slot = getattr(_skeleton_capture, "slot", None)
    if slot is not None:
        slot["skeleton"] = _Skeleton(
            treedef,
            tuple((i, leaves[i]) for i in range(len(leaves))
                  if i not in pos),
            pos, len(leaves))
    lane = jnp.arange(values.shape[0], dtype=jnp.int32) < n_real
    diag = getattr(model, "diagnostics", None)
    conv = getattr(diag, "converged", None) if diag is not None else None
    if conv is not None:
        n_conv = jnp.sum(jnp.where(lane, jnp.reshape(conv, (-1,)), False))
    else:
        n_conv = jnp.sum(lane)
    return [leaves[i] for i in pos], n_conv


def _dense_fit(family: str, statics: tuple, values, n_real):
    """Traced dense fit: exactly the program ``jax.jit(fit)`` runs, plus
    a lane-masked converged count (padding lanes — zero rows on the
    stream tier — self-quarantine per lane and are sliced off host-side)."""
    return _split_model(_family_fit(family, statics, values, None),
                        values, n_real)


def _ragged_fit(family: str, statics: tuple, values, n_real):
    """Traced ragged fit: NaN-padded input (leading/trailing per lane —
    bucket padding is all-NaN lanes plus trailing observation columns) is
    left-aligned in-trace and fitted against its explicit per-lane valid
    window, so one executable serves every raw shape in the bucket."""
    from .ops.ragged import _left_align

    aligned, length, _ = _left_align(values)
    return _split_model(_family_fit(family, statics, aligned, length),
                        values, n_real)


# Module-level jit wrappers (one function object per variant x donation,
# so repeated lowers share jax's jit cache; see STS006).  values sits at
# argument 2; family and statics are static.
def _make_jits():
    import jax
    table = {}
    for variant, fn in (("dense", _dense_fit), ("ragged", _ragged_fit)):
        # once-per-process table build behind _jit_lock's memoization
        # (_jit_for), not a per-dispatch loop
        table[variant, False] = jax.jit(fn, static_argnums=(0, 1))  # sts: noqa[STS202]
        table[variant, True] = jax.jit(fn, static_argnums=(0, 1),  # sts: noqa[STS202]
                                       donate_argnums=(2,))
    return table


_jit_table: Dict[Tuple[str, bool], Any] = {}
_jit_lock = threading.Lock()


def _jit_for(variant: str, donate: bool):
    with _jit_lock:
        if not _jit_table:
            _jit_table.update(_make_jits())
        return _jit_table[variant, donate]


def expected_chunk_result_bytes(family: str, bucket: Tuple[int, int],
                                dtype: Any = "float32",
                                variant: str = "dense",
                                **kwargs) -> int:
    """Device→host bytes one warmed chunk's *sanctioned*
    materialization moves: the chunk program's output leaves plus the
    convergence scalar, from ``jax.eval_shape`` (shape-level only — no
    compile, no execution).  ``pipeline_contracts()`` pins the
    engine-counted ``engine.bytes_d2h`` per chunk against exactly this
    number; any surplus is an unsanctioned crossing."""
    import jax

    statics = _STATICS_BUILDERS[family](**kwargs)
    fn = _dense_fit if variant == "dense" else _ragged_fit
    values = jax.ShapeDtypeStruct(tuple(bucket), np.dtype(dtype))
    n_real = jax.ShapeDtypeStruct((), np.dtype(np.int32))
    arrays, conv = jax.eval_shape(
        lambda v, n: fn(family, statics, v, n), values, n_real)
    total = sum(int(np.prod(l.shape, dtype=np.int64))
                * np.dtype(l.dtype).itemsize
                for l in jax.tree_util.tree_leaves(arrays))
    return total + int(np.prod(conv.shape, dtype=np.int64)) \
        * np.dtype(conv.dtype).itemsize


# ---------------------------------------------------------------------------
# host-side input classification
# ---------------------------------------------------------------------------

def _host_view(values) -> Optional[np.ndarray]:
    """Zero-copy numpy view when the input already lives on host."""
    if isinstance(values, np.ndarray):
        return values
    return None


def _has_nan(values) -> bool:
    if not np.issubdtype(np.asarray(values).dtype if isinstance(
            values, np.ndarray) else values.dtype, np.floating):
        return False
    host = _host_view(values)
    if host is not None:
        return bool(np.isnan(host).any())
    # device input: one tiny reduction instead of pulling the panel
    import jax.numpy as jnp
    return bool(jnp.any(jnp.isnan(values)))


def _interior_gap_count(host: np.ndarray) -> int:
    """Lanes with NaN strictly inside their observed window (the class
    the ragged machinery cannot mask — same policy as
    ``ops.ragged.ragged_view``, checked host-side because the engine's
    traced fits cannot raise on data)."""
    obs = ~np.isnan(host)
    n = host.shape[-1]
    any_obs = obs.any(axis=-1)
    start = obs.argmax(axis=-1)
    last = n - 1 - obs[:, ::-1].argmax(axis=-1)
    window = np.where(any_obs, last - start + 1, 0)
    return int(np.sum(obs.sum(axis=-1) != window))


def _multi_device(values) -> bool:
    sharding = getattr(values, "sharding", None)
    if sharding is None:
        return False
    try:
        return len(sharding.device_set) > 1
    except Exception:  # noqa: BLE001 — exotic sharding: be conservative
        return True


# ---------------------------------------------------------------------------
# results
# ---------------------------------------------------------------------------

class _ChunkDataError(ValueError):
    """A chunk's input violates the engine's data contract (NaN for a
    family without a ragged fit, interior gaps).  Deterministic — the
    same data fails the same way forever — so these failures are
    terminal: recorded immediately, never quarantined for retry."""


class StreamResult(NamedTuple):
    """Outcome of one :meth:`FitEngine.stream_fit` pass.

    ``n_fitted`` counts the series whose chunks completed (``n_series``
    minus dead-chunk lanes); ``models`` is None unless ``collect=True``
    (then a list of per-chunk host model pytrees in series order, lanes
    sliced back to the chunk's real count — a chunk degraded under
    memory pressure contributes one model per sub-chunk).  ``stats``
    carries the per-call engine accounting bench embeds: cache
    hits/misses, bytes donated/transferred, chunk/journal/durability
    counters."""
    n_series: int
    n_fitted: int
    n_converged: int
    wall_s: float
    n_chunks: int
    chunk_failures: List[Dict[str, Any]]
    models: Optional[List[Any]]
    stats: Dict[str, Any]

    @property
    def rate(self) -> float:
        """Fitted series per second (0 when nothing completed)."""
        return self.n_fitted / self.wall_s if self.wall_s > 0 else 0.0


class _Entry(NamedTuple):
    compiled: Any
    skeleton: _Skeleton
    bucket: Tuple[int, int]
    variant: str
    donate: bool


class _PublishPlan(NamedTuple):
    """Fused-path publish recipe (docs/design.md §6e): the skeleton walk
    — static-leaf placement plus the padding-cut decision per output —
    resolved ONCE per (bucket, variant, n_real) key and replayed for
    every chunk in the bucket.  The staged path re-walks the skeleton
    per chunk and re-uploads every cut leaf through ``jnp.asarray``
    (one D2H+H2D round trip per padded output); the plan instead takes
    zero-copy numpy views of the already-materialized arrays, so a warm
    fused chunk's host traffic is exactly the sanctioned result
    materialization."""
    template: Tuple[Any, ...]     # n_leaves slots, static leaves filled
    array_pos: Tuple[int, ...]
    cuts: Tuple[Optional[Tuple[int, int]], ...]  # per output: (c0, c1)
    treedef: Any

    def rebuild(self, arrays: Sequence[Any]):
        import jax

        leaves = list(self.template)
        for i, arr, cut in zip(self.array_pos, arrays, self.cuts):
            if cut is not None:
                c0, c1 = cut
                if c0:
                    arr = arr[:c0]
                if c1:
                    arr = arr[:, :c1]
            leaves[i] = arr
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def _build_publish_plan(skeleton: _Skeleton, arrays: Sequence[Any],
                        n_series: int, n_obs: int,
                        bucket: Tuple[int, int]) -> _PublishPlan:
    """Resolve the per-bucket publish plan from the first materialized
    chunk's output shapes — the same cut policy as :meth:`FitEngine.
    _rebuild` (leading dims at the series bucket shrink to ``n_series``,
    second dims at an expanded obs bucket shrink to ``n_obs``), decided
    once instead of per chunk."""
    bs, bt = bucket
    template: List[Any] = [None] * skeleton.n_leaves
    for i, val in skeleton.static_leaves:
        template[i] = val
    cuts: List[Optional[Tuple[int, int]]] = []
    for arr in arrays:
        cut = None
        if hasattr(arr, "ndim") and arr.ndim >= 1:
            cut0 = arr.shape[0] == bs and bs != n_series
            cut1 = arr.ndim >= 2 and bt != n_obs and arr.shape[1] == bt
            if cut0 or cut1:
                cut = (n_series if cut0 else 0, n_obs if cut1 else 0)
        cuts.append(cut)
    return _PublishPlan(tuple(template), skeleton.array_pos,
                        tuple(cuts), skeleton.treedef)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class FitEngine:
    """Shape-bucketed AOT executable cache + streaming chunk executor.

    One engine instance owns one executable cache; the module-level
    :func:`default_engine` is what ``Panel.fit_resilient`` and
    ``models.arima.fit_panel`` route through.  Thread-safe: the cache is
    lock-guarded, and executables themselves are immutable.

    ``donate``: ``None`` (auto) donates chunk buffers on accelerators and
    skips donation on CPU (XLA CPU cannot alias them and would warn);
    True/False force.  ``prefetch``: how many dispatched chunks may be
    pending ahead of the one being drained in :meth:`stream_fit`
    (1 = the classic double buffer — two chunks live during overlap;
    the default 2 keeps a third in flight to ride out pull jitter).
    """

    def __init__(self, *, registry: Optional[Any] = None,
                 prefetch: int = 2, donate: Optional[bool] = None,
                 compile_cache_dir: Optional[str] = None):
        self._reg = registry if registry is not None \
            else _metrics.get_registry()
        self.prefetch = max(1, int(prefetch))
        self._donate = donate
        self._lock = threading.RLock()
        self._entries: Dict[tuple, _Entry] = {}
        configure_compile_cache(compile_cache_dir)

    # -- donation policy ----------------------------------------------------

    def donate_default(self) -> bool:
        if self._donate is not None:
            return bool(self._donate)
        import jax
        return jax.default_backend() != "cpu"

    # -- executable cache ---------------------------------------------------

    def _entry(self, family: str, statics: tuple, bucket: Tuple[int, int],
               dtype, variant: str, donate: bool) -> _Entry:
        import jax

        # canonicalize the key dtype: under x64-off, f64 input lowers to
        # the byte-identical f32 program — two raw-dtype keys would
        # compile it twice and double-count cache misses
        dtype = jax.dtypes.canonicalize_dtype(np.dtype(dtype))
        key = (family, statics, bucket, str(dtype), variant,
               donate, jax.default_backend())
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._reg.inc("engine.cache_hits")
                return hit
        # compile outside the lock: one slow compile must not serialize
        # unrelated buckets (a duplicate race costs one redundant compile,
        # resolved by last-write-wins below)
        self._reg.inc("engine.cache_misses")
        jitted = _jit_for(variant, donate)
        spec_v = jax.ShapeDtypeStruct(bucket, dtype)
        spec_n = jax.ShapeDtypeStruct((), np.int32)
        from .models.base import unroll_hint

        slot: Dict[str, Any] = {}
        _skeleton_capture.slot = slot
        try:
            # the bucket width is the scan-unroll policy's amortization
            # signal (models.base.scan_unroll): wide bench buckets trace
            # unrolled, narrow test/interactive buckets stay compile-lean
            with _metrics.span("engine.compile"), unroll_hint(bucket[0]):
                compiled = jitted.lower(family, statics, spec_v,
                                        spec_n).compile()
        finally:
            _skeleton_capture.slot = None
        skeleton = slot.get("skeleton")
        if skeleton is None:
            # jit served the lowering from its cache without re-tracing;
            # one abstract re-trace recovers the skeleton
            _skeleton_capture.slot = slot
            try:
                with unroll_hint(bucket[0]):
                    jax.eval_shape(
                        lambda v, n: (_dense_fit if variant == "dense"
                                      else _ragged_fit)(family, statics,
                                                        v, n),
                        spec_v, spec_n)
            finally:
                _skeleton_capture.slot = None
            skeleton = slot["skeleton"]
        entry = _Entry(compiled, skeleton, bucket, variant, donate)
        with self._lock:
            self._entries[key] = entry
            self._reg.set_gauge("engine.executables", len(self._entries))
        return entry

    def cache_stats(self) -> Dict[str, int]:
        snap = self._reg.snapshot()["counters"]
        with self._lock:
            n = len(self._entries)
        return {"executables": n,
                "cache_hits": int(snap.get("engine.cache_hits", 0)),
                "cache_misses": int(snap.get("engine.cache_misses", 0))}

    # -- model reconstruction ----------------------------------------------

    @staticmethod
    def _rebuild(skeleton: _Skeleton, arrays: Sequence[Any],
                 n_series: int, n_obs: int, bucket: Tuple[int, int]):
        """Executable outputs -> host model pytree, padding sliced off:
        leading dims equal to the series bucket shrink to ``n_series``;
        second dims equal to an *expanded* obs bucket shrink to
        ``n_obs``.  Slicing happens host-side — a device-side gather
        would compile one tiny program per raw shape, which is exactly
        the per-shape compile churn the bucketed cache exists to kill."""
        import jax
        import jax.numpy as jnp

        bs, bt = bucket
        leaves: List[Any] = [None] * skeleton.n_leaves
        for i, val in skeleton.static_leaves:
            leaves[i] = val
        for i, arr in zip(skeleton.array_pos, arrays):
            if hasattr(arr, "ndim") and arr.ndim >= 1:
                cut0 = arr.shape[0] == bs and bs != n_series
                cut1 = arr.ndim >= 2 and bt != n_obs and arr.shape[1] == bt
                if cut0 or cut1:
                    host = np.asarray(arr)
                    if cut0:
                        host = host[:n_series]
                    if cut1:
                        host = host[:, :n_obs]
                    arr = jnp.asarray(host)
            leaves[i] = arr
        return jax.tree_util.tree_unflatten(skeleton.treedef, leaves)

    # -- single-shot bucketed fit (the Panel / fit_panel tier) --------------

    def fit(self, values, family: str = "arima", *,
            bucket_obs: bool = True, warn: bool = False, **kwargs):
        """Fit one panel through the bucketed executable cache.

        ``values (n_series, n_obs)``; ``kwargs`` are the family's static
        fit parameters (arima: ``p``/``d``/``q``/``include_intercept``/
        ``method``/``max_iter``/``retry``).  Returns the fitted model
        with padding lanes/columns sliced off, so the result is shaped
        exactly as the direct fit's would be.

        Routing: a panel already at its bucket shape runs the dense
        program (bit-for-bit the jitted direct fit); series-only padding
        keeps the dense program with zero-padded lanes (real lanes
        bit-for-bit, pad lanes sliced off); NaN input or observation-axis
        padding takes the traced ragged program (:data:`RAGGED_FAMILIES`
        — valid-window weighted, trimmed-fit equivalent to float
        rounding).  Inputs the engine cannot bucket (non-2D, multi-device
        sharded, unknown families, non-static kwargs such as arima's
        ``user_init_params``) fall back to the direct eager fit and count
        ``engine.bypass``.

        Padding happens host-side (device-side slicing/padding would
        compile one tiny program per raw shape — the churn the bucket
        kills), so a *device-resident* panel that is not bucket-exact
        pays one D2H+H2D round trip per fit; keep hot device-resident
        loops at bucket-exact shapes (the bench's device-resident block
        does) or feed host arrays.
        """
        builder = _STATICS_BUILDERS.get(family)
        if builder is None or getattr(values, "ndim", None) != 2 \
                or _multi_device(values) \
                or not np.issubdtype(np.dtype(getattr(values, "dtype",
                                                      np.float64)),
                                     np.floating):
            return self._direct(values, family, warn, kwargs)
        try:
            statics = builder(**kwargs)
        except TypeError:
            return self._direct(values, family, warn, kwargs)

        with _metrics.span("engine.fit"):
            n_series, n_obs = values.shape
            bs, bt = pad_bucket(n_series, n_obs)
            if not bucket_obs:
                bt = n_obs
            has_nan = _has_nan(values)
            dtype = values.dtype

            if not has_nan and (n_series, n_obs) == (bs, bt):
                entry = self._entry(family, statics, (bs, bt), dtype,
                                    "dense", False)
                arrays, _ = entry.compiled(values, np.int32(n_series))
            elif not has_nan and n_obs == bt:
                # series-only padding: zero lanes quarantine themselves
                # per lane and are sliced off — real lanes bit-for-bit
                host = np.asarray(values)
                padded = np.zeros((bs, bt), host.dtype)
                padded[:n_series] = host
                self._reg.inc("engine.pad_lanes", bs - n_series)
                entry = self._entry(family, statics, (bs, bt), dtype,
                                    "dense", False)
                arrays, _ = entry.compiled(padded, np.int32(n_series))
            else:
                if family not in RAGGED_FAMILIES:
                    return self._direct(values, family, warn, kwargs)
                host = np.asarray(values)
                gaps = _interior_gap_count(host)
                if gaps:
                    raise ValueError(
                        f"{gaps} lane(s) have NaN strictly inside their "
                        f"observed window; valid-window fits need "
                        f"contiguous observations — impute interior gaps "
                        f"first (e.g. Panel.fill), leading/trailing "
                        f"padding needs no fill")
                padded = np.full((bs, bt), np.nan, host.dtype)
                padded[:n_series, :n_obs] = host
                self._reg.inc("engine.pad_lanes", bs - n_series)
                self._reg.inc("engine.pad_obs", bt - n_obs)
                entry = self._entry(family, statics, (bs, bt), dtype,
                                    "ragged", False)
                arrays, _ = entry.compiled(padded, np.int32(n_series))

            model = self._rebuild(entry.skeleton, arrays, n_series, n_obs,
                                  entry.bucket)
            self._reg.inc("engine.fits")
        _metrics.record_fit(family, model, self._reg)
        if warn and family == "arima":
            from .models.arima import _warn_stationarity_invertibility
            _warn_stationarity_invertibility(model, True)
        return model

    def _direct(self, values, family: str, warn: bool, kwargs):
        """Bypass: the family's public eager fit, untouched semantics."""
        self._reg.inc("engine.bypass")
        from . import models as m

        if family == "arima":
            kw = dict(kwargs)
            p, d, q = kw.pop("p", 2), kw.pop("d", 1), kw.pop("q", 2)
            return m.arima.fit(p, d, q, values, warn=warn, **kw)
        table = {
            "ar": m.autoregression.fit,
            "ewma": m.ewma.fit,
            "garch": m.garch.fit,
            "argarch": m.garch.fit_ar_garch,
            "egarch": m.garch.fit_egarch,
            "holt_winters": m.holt_winters.fit,
        }
        if family not in table:
            raise ValueError(
                f"unknown engine family {family!r}; expected one of "
                f"{sorted(_STATICS_BUILDERS)}")
        return table[family](values, **kwargs)

    # -- resilient tier (the Panel.fit_resilient front-end) -----------------

    @staticmethod
    def resilient_dispatch(family: str) -> Callable:
        """The family's ``fit_resilient`` entry point (the direct,
        unbucketed chain)."""
        from . import models
        dispatch = {
            "arima": models.arima.fit_resilient,
            "arimax": models.arimax.fit_resilient,
            "ar": models.autoregression.fit_resilient,
            "arx": models.autoregression_x.fit_resilient,
            "ewma": models.ewma.fit_resilient,
            "garch": models.garch.fit_resilient,
            "argarch": models.garch.fit_ar_garch_resilient,
            "egarch": models.garch.fit_egarch_resilient,
            "holt_winters": models.holt_winters.fit_resilient,
            "regression_arima": models.regression_arima.fit_resilient,
        }
        if family not in dispatch:
            raise ValueError(f"unknown model family {family!r}; expected "
                             f"one of {sorted(dispatch)}")
        return dispatch[family]

    def fit_resilient(self, values, family: str, *args, **kwargs):
        """Bucket the series axis, run the family's ``fit_resilient``
        chain, slice the padding back off.

        Padding lanes are all-NaN, so the existing resilience health
        machinery classifies them unfittable and masks them out of every
        stage — real lanes are bit-for-bit the unbucketed chain's result.
        The observation axis is deliberately NOT padded here: the
        resilient stages run eagerly (where ragged handling is
        value-dependent), several families carry ``(n_obs, k)`` exogenous
        regressors that would need matching pads, and series count is
        what actually varies under multi-tenant traffic.  Returns
        ``(model, FitOutcome)`` shaped for the REAL lanes.
        """
        fit_fn = self.resilient_dispatch(family)
        if getattr(values, "ndim", None) != 2 or _multi_device(values) \
                or not np.issubdtype(np.dtype(getattr(values, "dtype",
                                                      np.float64)),
                                     np.floating):
            return fit_fn(values, *args, **kwargs)

        n_series, n_obs = values.shape
        bs = series_bucket(n_series)
        if bs == n_series:
            return fit_fn(values, *args, **kwargs)

        import jax.numpy as jnp

        host = np.asarray(values)
        padded = np.full((bs, n_obs), np.nan, host.dtype)
        padded[:n_series] = host
        self._reg.inc("engine.pad_lanes", bs - n_series)
        model, outcome = fit_fn(jnp.asarray(padded), *args, **kwargs)
        model = self._slice_lanes(model, n_series, bs)
        outcome = type(outcome)(
            None if outcome.params is None else outcome.params[:n_series],
            outcome.status[:n_series], outcome.attempts[:n_series],
            outcome.fallback_used[:n_series], outcome.health[:n_series],
            None if outcome.orders is None
            else outcome.orders[:n_series])
        return model, outcome

    @staticmethod
    def _slice_lanes(model, n_series: int, bucket_s: int):
        import jax

        def cut(leaf):
            if hasattr(leaf, "ndim") and getattr(leaf, "ndim", 0) >= 1 \
                    and leaf.shape[0] == bucket_s:
                return leaf[:n_series]
            return leaf

        return jax.tree_util.tree_map(cut, model)

    # -- warmup -------------------------------------------------------------

    def warmup(self, families: Sequence[str] = ("arima",),
               shapes: Sequence[Tuple[int, int]] = ((1024, 128),),
               *, dtype=None, variants: Optional[Sequence[str]] = None,
               bucket: bool = True, **kwargs) -> Dict[str, Any]:
        """Precompile executables ahead of traffic: one AOT compile per
        ``(family, bucket(shape), variant)``.  ``kwargs`` parameterize
        every family's statics (families that reject a kwarg use their
        defaults).  With ``STS_COMPILE_CACHE`` armed the compiles also
        persist to disk, so the *next* process warms from deserialization
        alone.  Returns a summary of what was built.

        ``bucket=False`` uses each shape verbatim as the executable
        shape instead of padding it through :func:`pad_bucket` — the
        streaming tier's keying, where full chunks run at their exact
        ``(chunk_size, n_obs)`` (obs-axis padding would change dense
        chunk numerics) — and compiles with the engine's stream-tier
        donation default so the cache key matches what
        :meth:`stream_fit` will look up.  Warm a stream with the exact
        chunk and tail shapes (see ``bench.py``); warm single-shot
        :meth:`fit` traffic with the default bucketing."""
        import jax

        if dtype is None:
            import jax.numpy as jnp
            dtype = jnp.float32
        built = []
        t0 = time.perf_counter()
        with _metrics.span("engine.warmup"):
            for family in families:
                builder = _STATICS_BUILDERS.get(family)
                if builder is None:
                    raise ValueError(
                        f"unknown engine family {family!r}; expected a "
                        f"subset of {sorted(_STATICS_BUILDERS)}")
                try:
                    statics = builder(**kwargs)
                except TypeError:
                    statics = builder()
                fam_variants = variants if variants is not None else (
                    ("dense", "ragged") if family in RAGGED_FAMILIES
                    else ("dense",))
                don = False if bucket else self.donate_default()
                for shape in shapes:
                    bkt = pad_bucket(*shape) if bucket else tuple(shape)
                    for variant in fam_variants:
                        self._entry(family, statics, bkt, dtype,
                                    variant, don)
                        built.append({"family": family,
                                      "bucket": list(bkt),
                                      "variant": variant})
        return {"built": built, "wall_s": round(time.perf_counter() - t0, 3),
                "platform": jax.default_backend(),
                **self.cache_stats()}

    # -- streaming executor (the bench tier) --------------------------------

    def stream_fit(self, values, family: str = "arima", *,
                   chunk_size: int = 131072,
                   prefetch: Optional[int] = None,
                   donate: Optional[bool] = None,
                   collect: bool = False,
                   journal: Optional[str] = None,
                   job_meta: Optional[Dict[str, Any]] = None,
                   deadline_s: Optional[float] = None,
                   retry=None,
                   degrade: bool = True,
                   degrade_floor: Optional[int] = None,
                   resilient: bool = False,
                   fused: Optional[bool] = None,
                   on_progress: Optional[Callable[[Any], None]] = None,
                   job_label: Optional[str] = None,
                   **kwargs) -> StreamResult:
        """Fit a panel larger than device memory by streaming chunks.

        ``fused`` (default: on, except under ``resilient=True`` which is
        host-orchestrated by design) publishes each chunk through the
        per-bucket :class:`_PublishPlan` — the whole-pipeline-fusion
        contract (docs/design.md §6e): a warm chunk dispatches exactly
        ONE donated executable, and its only host crossing is the
        sanctioned result materialization
        (:func:`expected_chunk_result_bytes`); skeleton reattach work
        is resolved once per (bucket, variant, n_real) instead of per
        chunk, and padded outputs are cut as zero-copy numpy views
        instead of the staged path's slice + device re-upload.
        ``fused=False`` keeps the staged per-chunk :meth:`_rebuild`
        path — the oracle the fused-vs-staged equivalence tests pin
        against (bitwise for the dense variant: both paths run the SAME
        cached executable, they differ only in host-side publish).
        Journals are fused-agnostic: the job spec does not include the
        flag, so a journal written by either path resumes under the
        other with the same spec hash.

        Pipelining: each chunk's H2D transfer + fit is dispatched (JAX
        dispatch is async) while earlier chunks' results are still being
        pulled, so transfer, compute, and result D2H overlap; at most
        ``prefetch`` dispatched chunks wait ahead of the one being
        drained (``prefetch + 1`` briefly live on device).  Chunk
        buffers are
        engine-owned and (on accelerators) donated to the executable, so
        successive chunks reuse the same HBM in place.  The tail chunk
        pads to its own series bucket — not the full chunk shape — and
        both tail and full-chunk executables come from the bucketed
        cache, so re-streaming any same-shaped workload compiles nothing.

        Failure isolation: a chunk whose dispatch or host materialization
        raises is recorded in ``chunk_failures`` (and the
        ``engine.chunk_failures`` counter) and skipped; the stream never
        dies on one poisoned chunk.  Records carry the chunk's
        ``(chunk_start, chunk_stop, bucket)``, the exception type, and a
        truncated traceback, so quarantine triage is actionable.

        Durability tier (docs/design.md §3c), all host-side:

        - ``journal=path``: a crash-consistent chunk journal
          (:class:`~spark_timeseries_tpu.utils.durability.ChunkJournal`).
          Every completed chunk's model commits atomically
          (tmp+rename payload, ``.ok`` marker rename as the commit
          point, content-hashed against the job spec); re-running with
          the same path skips committed chunks via a validated restore
          (``engine.journal_hits``), so a killed job resumes where it
          died with bitwise-identical results.  A journal written by a
          different job spec refuses to resume
          (:class:`JournalSpecMismatch`); a corrupt entry is detected,
          moved to ``quarantine/``, and its chunk refit.  ``job_meta``
          (any JSON-serializable dict) is folded into the hashed spec —
          callers that derive the panel from something richer (the
          longseries tier's segmentation geometry: seg_len, overlap, d,
          AR-truncation order) record it here so a geometry change
          refuses resume instead of silently combining stale segments.
        - ``deadline_s`` (default: ``STS_CHUNK_DEADLINE_S``, unset =
          off): a watchdog thread arms a timer around each chunk's
          dispatch and result materialization; a chunk that outlives it
          raises :class:`ChunkDeadlineExceeded` on the caller's side
          (the hung worker thread is abandoned) and the stream
          continues.
        - ``retry`` (int or
          :class:`~spark_timeseries_tpu.utils.durability.BackoffPolicy`,
          default ``STS_CHUNK_RETRIES`` → 0): failed/timed-out chunks
          queue in quarantine and are retried at end-of-stream with
          deterministic exponential backoff before being declared dead
          (``engine.dead_chunks``).
        - ``degrade`` (default True): a chunk whose dispatch dies with
          ``RESOURCE_EXHAUSTED`` is halved and re-dispatched as two
          sub-chunks, recursing down to ``degrade_floor`` (default
          :data:`SERIES_BUCKET_FLOOR`) — ``engine.degraded_chunks``
          counts the splits; at the floor the OOM quarantines like any
          other failure.

        ``resilient=True`` routes every chunk through the family's
        fail-soft fallback chain (:meth:`fit_resilient` — health
        masking, multi-start retry, fallback stages, and for arima the
        ``auto_order=`` searched-order stage, all passed through
        ``kwargs``) instead of the AOT dense/ragged executables.  Chunks
        run synchronously (the chain is host-orchestrated gather/scatter,
        so there is no async dispatch to pipeline) but keep the full
        durability scaffolding: deadline watchdog, journal commits and
        validated resume, quarantine/backoff retries, and OOM halving.
        Per-chunk ``FitOutcome`` statuses aggregate into
        ``stats["resilient_statuses"]``; ``converged`` counts lanes whose
        status is ok/retried/fallback.

        Telemetry (docs/design.md §6f), all host-side: every run
        registers a live :class:`~spark_timeseries_tpu.utils.telemetry.
        JobProgress` (job id, chunks done/total/failed/quarantined/
        degraded, journal commits, EW-smoothed chunk cadence → ETA),
        heartbeat-stamped at every chunk dispatch and materialization —
        a hung chunk shows a growing heartbeat age on ``/healthz``
        *before* its deadline fires.  Progress also lands in the
        ``engine.job.*`` gauges (last-write-wins across concurrent
        jobs; per-job fidelity lives in ``/snapshot.json``).
        ``on_progress`` (optional callable) receives the ``JobProgress``
        after every chunk completion; a callback that raises is dropped
        after counting ``engine.progress_cb_errors``.  ``job_label``
        overrides the family string shown on the job's telemetry row
        (``/snapshot.json`` jobs panel, ``sts_top``) — multi-stream
        sweeps (the backtest tier's per-candidate fits, the longseries
        tier's segment streams) label each stream so an operator can
        read per-stage ETAs instead of a wall of identical
        ``arima-<pid>-<n>`` ids; purely observational, never part of
        the journal spec.  With
        ``STS_INCIDENT_DIR`` set, chunk deaths, deadline expiries,
        OOM-at-floor, the ``kill_after_chunk`` fault, and any exception
        escaping this call each leave a forensic incident bundle
        (``utils.flightrec``); bundle writing never touches the journal
        or the resume path.

        Timing covers dispatch through host materialization of every
        chunk's outputs — the real pipeline cost for out-of-core panels.
        """
        import jax

        from .utils import resilience as _resilience

        if resilient:
            # validates the family; the resilient tier has its own
            # (wider) family table and takes kwargs, not statics
            self.resilient_dispatch(family)
            statics = ("resilient",
                       tuple(sorted((k, repr(v))
                                    for k, v in kwargs.items())))
        else:
            builder = _STATICS_BUILDERS.get(family)
            if builder is None:
                raise ValueError(
                    f"unknown engine family {family!r}; expected one of "
                    f"{sorted(_STATICS_BUILDERS)}")
            statics = builder(**kwargs)
        host = values if isinstance(values, np.ndarray) \
            else np.asarray(values)
        if host.ndim != 2:
            raise ValueError(
                f"stream_fit needs a (n_series, n_obs) panel, "
                f"got {host.shape}")
        n_series, n_obs = host.shape
        chunk = max(1, min(int(chunk_size), n_series))
        depth = self.prefetch if prefetch is None else max(1, int(prefetch))
        don = self.donate_default() if donate is None else bool(donate)
        use_fused = (not resilient) if fused is None else bool(fused)
        # per-stream publish-plan cache: one skeleton walk per
        # (bucket, variant, n_real) key, replayed for every chunk in
        # that bucket (every full chunk shares one plan; the tail and
        # any OOM-degraded sub-ranges get their own)
        publish_plans: Dict[tuple, _PublishPlan] = {}
        before = self.cache_stats()
        partition = [(s, min(s + chunk, n_series))
                     for s in range(0, n_series, chunk)]

        if deadline_s is None:
            env = os.environ.get("STS_CHUNK_DEADLINE_S")
            try:
                deadline = float(env) if env else None
            except ValueError:
                raise ValueError(
                    f"STS_CHUNK_DEADLINE_S must be a number of seconds, "
                    f"got {env!r}") from None
        else:
            deadline = float(deadline_s)
        if deadline is not None and deadline <= 0:
            deadline = None
        policy = _durability.as_backoff(retry)
        floor = SERIES_BUCKET_FLOOR if degrade_floor is None \
            else max(1, int(degrade_floor))

        # membership test for progress accounting: OOM-degraded
        # sub-ranges must not count as whole chunks (chunks_done would
        # pass n_chunks and the ETA would collapse)
        partition_set = set(partition)

        if job_meta is not None:
            import json as _json
            try:
                _json.dumps(job_meta)
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"job_meta must be JSON-serializable (it is content-"
                    f"hashed into the journal spec): {e}") from None
        jr = None
        if journal:
            # the job spec the journal is content-hashed against: any
            # change to what a committed chunk MEANS (family, statics,
            # dtype, bucket policy, chunk partition, the panel's bytes,
            # and the caller's job_meta — e.g. the longseries tier's
            # segmentation geometry) must refuse resume — same-shape
            # different data would otherwise silently restore a previous
            # job's results
            spec = {
                "format": 1,
                "family": family,
                "statics": repr(statics),
                "dtype": str(np.dtype(host.dtype)),
                "n_series": int(n_series),
                "n_obs": int(n_obs),
                "chunk_size": int(chunk),
                "bucket_policy": [SERIES_BUCKET_FLOOR, OBS_BUCKET_MULTIPLE],
                "data_sha256": _durability.array_digest(host),
            }
            if job_meta is not None:
                spec["job"] = job_meta
            jr = _durability.ChunkJournal.open(journal, spec)
        keep_models = collect or jr is not None

        # live telemetry (docs/design.md §6f): the job's structured
        # heartbeat, registered before the first dispatch so an operator
        # can watch the run from chunk 0; the STS_TELEMETRY_PORT opt-in
        # is honored here (no exporter thread exists without it)
        _telemetry.ensure_started_from_env()
        label = str(job_label) if job_label else family
        progress = _telemetry.JobProgress(
            _telemetry.new_job_id(label), label, n_series,
            len(partition), chunk, journal_path=journal or None,
            resilient=resilient)
        _telemetry.register_job(progress, self._reg)
        cb_state = {"cb": on_progress}

        def _publish_progress() -> None:
            """engine.job.* gauges (last-write-wins across concurrent
            jobs) + the caller's on_progress callback, which is dropped
            after its first raise — observability must never kill the
            stream it observes."""
            eta = progress.eta_s
            self._reg.set_gauge("engine.job.chunks_done",
                                progress.chunks_done)
            self._reg.set_gauge("engine.job.chunks_total",
                                progress.n_chunks)
            self._reg.set_gauge("engine.job.chunks_failed",
                                progress.chunks_failed)
            self._reg.set_gauge("engine.job.eta_s",
                                eta if eta is not None else -1.0)
            if progress.ew_chunk_s is not None:
                self._reg.set_gauge("engine.job.chunk_s_ew",
                                    progress.ew_chunk_s)
            cb = cb_state["cb"]
            if cb is not None:
                try:
                    cb(progress)
                except Exception:  # noqa: BLE001 — see docstring
                    cb_state["cb"] = None
                    self._reg.inc("engine.progress_cb_errors")

        conv = 0
        dead_series = 0
        failures: List[Dict[str, Any]] = []
        collected: Dict[int, Any] = {}
        pending: deque = deque()
        quarantine: List[Dict[str, Any]] = []
        durex = {"journal_hits": 0, "journal_commits": 0,
                 "journal_corrupt": 0, "degraded_chunks": 0,
                 "quarantined": 0, "retry_attempts": 0, "recovered": 0,
                 "dead_chunks": 0, "abandoned_workers": 0}

        # performance attribution (docs/design.md §6g): per-chunk phase
        # timers around every host crossing of the pipeline — slice/scan
        # (prep), padding copy (pad), device_put + async enqueue
        # (dispatch), blocking on device outputs (device_wait), skeleton
        # reattach (reattach), journal commit (commit) — plus the
        # device-idle "bubble": the host-side gap between consecutive
        # device-wait windows net of the dispatch time that kept the
        # device fed in between.  Strictly host-side perf_counter reads;
        # nothing here is traced, so the instrumentation can never leak
        # a recompile into the warmed path.
        phase_totals = {"prep_s": 0.0, "pad_s": 0.0, "dispatch_s": 0.0,
                        "device_wait_s": 0.0, "reattach_s": 0.0,
                        "commit_s": 0.0}
        chunk_phases: List[Dict[str, Any]] = []
        phase_state = {"stage_wall_s": 0.0, "dropped": 0,
                       "last_wait_end": None, "feed_s": 0.0,
                       "bubble_s": 0.0}

        def _finish_rec(rec: Optional[Dict[str, Any]]) -> None:
            """Fold one chunk's phase record into the stream totals and
            the bounded per-chunk list (the bench `engine` block embeds
            it; _PHASE_RECORD_CAP keeps a 1M-series stream's stats from
            ballooning)."""
            if rec is None:
                return
            for key in phase_totals:
                phase_totals[key] += rec.get(key, 0.0)
            wall = rec.get("dispatch_call_s", 0.0) \
                + rec.get("materialize_call_s", 0.0)
            phase_state["stage_wall_s"] += wall
            if len(chunk_phases) < _PHASE_RECORD_CAP:
                row = {"chunk": rec["chunk"], "start": rec["start"],
                       "stop": rec["stop"],
                       "wall_ms": round(wall * 1e3, 3)}
                for key in ("prep_s", "pad_s", "dispatch_s",
                            "device_wait_s", "reattach_s", "commit_s",
                            "bubble_s"):
                    row[key[:-2] + "_ms"] = round(
                        rec.get(key, 0.0) * 1e3, 3)
                chunk_phases.append(row)
            else:
                phase_state["dropped"] += 1

        def _with_deadline(fn: Callable[[], Any], stage: str,
                           start: int, stop: int):
            """Run one blocking chunk stage under the watchdog: the work
            happens in a daemon thread, the caller waits at most
            ``deadline`` seconds.  On expiry the worker is abandoned
            (its eventual result is discarded) and the chunk fails like
            any other — strictly host-side, nothing here is traced."""
            if deadline is None:
                return fn()
            box: Dict[str, Any] = {}
            done = threading.Event()

            def _run():
                try:
                    box["value"] = fn()
                except BaseException as e:  # noqa: BLE001 — relayed below
                    box["error"] = e
                finally:
                    done.set()

            worker = threading.Thread(
                target=_run, daemon=True,
                name=f"sts-chunk-{start}-{stage}")
            worker.start()
            if not done.wait(deadline):
                durex["abandoned_workers"] += 1
                self._reg.inc("engine.deadline_expired")
                self._reg.inc("engine.abandoned_workers")
                _metrics.trace_instant(
                    "engine.deadline_expired",
                    {"chunk_start": int(start), "chunk_stop": int(stop),
                     "stage": stage, "deadline_s": deadline})
                err = ChunkDeadlineExceeded(
                    f"chunk [{start}, {stop}) exceeded the {deadline:g}s "
                    f"per-chunk deadline during {stage} "
                    f"(deadline_s= / STS_CHUNK_DEADLINE_S); the worker "
                    f"thread is abandoned and the stream continues")
                _flightrec.record_incident(
                    "deadline_expired", exc=err, job=progress,
                    journal_path=jr.path if jr is not None else None,
                    extra={"chunk": [int(start), int(stop)],
                           "stage": stage, "deadline_s": deadline},
                    registry=self._reg)
                # the retry loop gates on this: while the abandoned
                # worker lives, it may still own the range's device
                # buffers and eventually execute its fit
                err.worker = worker
                raise err
            if "error" in box:
                raise box["error"]
            return box["value"]

        def _prep(start: int, stop: int,
                  rec: Optional[Dict[str, Any]] = None):
            """Slice + pad one row range to its series bucket.  Raises
            :class:`_ChunkDataError` on deterministic data-contract
            violations (terminal — a retry cannot change the data)."""
            t0 = time.perf_counter()
            part = host[start:stop]
            n_real = stop - start
            bs = chunk if n_real == chunk \
                else min(series_bucket(n_real), chunk)
            variant = "dense"
            if np.issubdtype(part.dtype, np.floating) \
                    and np.isnan(part).any():
                if family not in RAGGED_FAMILIES:
                    raise _ChunkDataError(
                        f"NaN input needs a traced ragged fit; family "
                        f"{family!r} has none (only {RAGGED_FAMILIES})")
                variant = "ragged"
                gaps = _interior_gap_count(part)
                if gaps:
                    raise _ChunkDataError(
                        f"{gaps} lane(s) have NaN strictly inside their "
                        f"observed window; impute interior gaps first")
            if rec is not None:
                rec["prep_s"] = time.perf_counter() - t0
            if n_real != bs:          # ragged tail: its own bucket
                t0 = time.perf_counter()
                fill = np.nan if variant == "ragged" else 0.0
                padded = np.full((bs, n_obs), fill, part.dtype)
                padded[:n_real] = part
                part = padded
                if rec is not None:
                    rec["pad_s"] = time.perf_counter() - t0
                self._reg.inc("engine.pad_lanes", bs - n_real)
            return part, bs, variant, n_real

        def _dispatch(idx: int, start: int, stop: int):
            """Prep + executable lookup + async dispatch under the
            deadline (compiles can hang too).  Returns
            ``(out, entry, n_real, rec)`` where ``rec`` is the chunk's
            phase record (threaded through materialize/publish)."""
            rec: Dict[str, Any] = {"chunk": int(idx), "start": int(start),
                                   "stop": int(stop)}
            t_call = time.perf_counter()
            progress.heartbeat("dispatch", chunk=(start, stop))
            part, bs, variant, n_real = _prep(start, stop, rec)
            oom = _resilience.chunk_fault("oom_chunk", idx)
            if oom is not None and (start, stop) == partition[idx]:
                # fires at the full chunk size only, so the degraded
                # sub-chunks it provokes run clean
                raise _resilience.InjectedOOM(
                    "RESOURCE_EXHAUSTED: injected oom_chunk fault")

            def work():
                hang = _resilience.chunk_fault("hang_chunk", idx)
                if hang is not None:
                    time.sleep(hang.hang_s)
                entry = self._entry(family, statics, (bs, n_obs),
                                    part.dtype, variant, don)
                t0 = time.perf_counter()
                with _metrics.span("engine.dispatch"):
                    dev = jax.device_put(part)
                    out = entry.compiled(dev, np.int32(n_real))
                d = time.perf_counter() - t0
                rec["dispatch_s"] = rec.get("dispatch_s", 0.0) + d
                # dispatch enqueues device work: credit it against the
                # next inter-wait gap so a host that keeps the device
                # fed doesn't book a phantom bubble
                phase_state["feed_s"] += d
                return entry, out

            entry, out = _with_deadline(work, "dispatch", start, stop)
            self._reg.inc("engine.bytes_h2d", int(part.nbytes))
            if don:
                self._reg.inc("engine.bytes_donated", int(part.nbytes))
            rec["dispatch_call_s"] = time.perf_counter() - t_call
            return out, entry, n_real, rec

        def _materialize(out, entry: _Entry, idx: int, start: int,
                         stop: int, n_real: int,
                         rec: Optional[Dict[str, Any]] = None) -> None:
            """Block on the chunk's outputs under the deadline, then
            publish (and journal-commit) the result."""
            progress.heartbeat("materialize", chunk=(start, stop))
            t_call = time.perf_counter()
            last_end = phase_state["last_wait_end"]
            if last_end is not None:
                # device-idle bubble: the stretch between consecutive
                # device-wait windows the host spent NOT feeding the
                # device (gap net of dispatch time in the gap)
                gap = max(0.0, t_call - last_end - phase_state["feed_s"])
                phase_state["bubble_s"] += gap
                if rec is not None:
                    rec["bubble_s"] = gap
            phase_state["feed_s"] = 0.0

            def work():
                with _metrics.span("engine.collect"):
                    arrays = [np.asarray(a) for a in out[0]]
                    # the sanctioned chunk-result crossing: account every
                    # device→host byte here so pipeline_contracts() can
                    # pin "no transfers beyond result materialization"
                    self._reg.inc("engine.bytes_d2h",
                                  sum(int(a.nbytes) for a in arrays)
                                  + int(getattr(out[1], "nbytes", 0)))
                    return arrays, int(out[1])

            t0 = time.perf_counter()
            arrays, c = _with_deadline(work, "materialize", start, stop)
            now = time.perf_counter()
            phase_state["last_wait_end"] = now
            if rec is not None:
                rec["device_wait_s"] = now - t0
                rec["materialize_t_call"] = t_call
            _publish(entry, arrays, c, idx, start, stop, n_real, rec)

        def _publish(entry: _Entry, arrays, c: int, idx: int, start: int,
                     stop: int, n_real: int,
                     rec: Optional[Dict[str, Any]] = None) -> None:
            nonlocal conv
            conv += c
            self._reg.inc("engine.chunks")
            model = None
            if keep_models:
                t0 = time.perf_counter()
                if use_fused:
                    pkey = (entry.bucket, entry.variant, n_real)
                    plan = publish_plans.get(pkey)
                    if plan is None:
                        plan = _build_publish_plan(
                            entry.skeleton, arrays, n_real, n_obs,
                            entry.bucket)
                        publish_plans[pkey] = plan
                    model = plan.rebuild(arrays)
                else:
                    model = self._rebuild(entry.skeleton, arrays, n_real,
                                          n_obs, entry.bucket)
                if rec is not None:
                    rec["reattach_s"] = time.perf_counter() - t0
            if jr is not None:
                t0 = time.perf_counter()
                jr.commit(start, stop, model,
                          {"n_real": int(n_real), "n_conv": int(c),
                           "bucket": list(entry.bucket),
                           "variant": entry.variant})
                if rec is not None:
                    rec["commit_s"] = time.perf_counter() - t0
                durex["journal_commits"] += 1
                self._reg.inc("engine.journal_commits")
                progress.note(journal_commits=1)
                full = (start, stop) == partition[idx]
                if full and _resilience.chunk_fault(
                        "kill_after_chunk", idx) is not None:
                    _pre_kill_incident(idx, start, stop)
                    os.kill(os.getpid(), signal.SIGKILL)
                if full and _resilience.chunk_fault(
                        "corrupt_journal", idx) is not None:
                    jr.corrupt_entry(start, stop)
            if collect:
                collected[start] = (stop, model)
            if (start, stop) in partition_set:
                progress.note_chunk_done()
            else:
                progress.note(subchunks_done=1)
            if rec is not None:
                t_call = rec.pop("materialize_t_call", None)
                if t_call is not None:
                    rec["materialize_call_s"] = time.perf_counter() \
                        - t_call
                _finish_rec(rec)
            _publish_progress()

        def _pre_kill_incident(idx: int, start: int, stop: int) -> None:
            """The kill_after_chunk fault sends SIGKILL (which by
            definition runs no handlers), so the crash-forensics bundle
            is written immediately BEFORE the kill — the deterministic,
            testable stand-in for "the process died mid-job".  The
            bundle lands in STS_INCIDENT_DIR via tmp+fsync+rename; the
            journal directory is never touched."""
            _flightrec.record_incident(
                "kill_after_chunk", job=progress,
                journal_path=jr.path if jr is not None else None,
                extra={"chunk": [int(start), int(stop)],
                       "chunk_index": int(idx),
                       "note": "injected SIGKILL after journal commit"},
                registry=self._reg)

        res_statuses: Dict[str, int] = {}

        def _run_chunk_resilient(idx: int, start: int, stop: int) -> None:
            """One synchronous resilient chunk: the family's fallback
            chain under the deadline watchdog, then publish/journal.
            Honors the streaming fault hooks (hang/oom at the full chunk
            size) so the durability suite drives this path too."""
            import jax.numpy as jnp

            progress.heartbeat("resilient_fit", chunk=(start, stop))
            part = host[start:stop]
            oom = _resilience.chunk_fault("oom_chunk", idx)
            if oom is not None and (start, stop) == partition[idx]:
                raise _resilience.InjectedOOM(
                    "RESOURCE_EXHAUSTED: injected oom_chunk fault")

            def work():
                hang = _resilience.chunk_fault("hang_chunk", idx)
                if hang is not None:
                    time.sleep(hang.hang_s)
                with _metrics.span("engine.dispatch"):
                    return self.fit_resilient(jnp.asarray(part), family,
                                              **kwargs)

            model, outcome = _with_deadline(work, "resilient_fit",
                                            start, stop)
            nonlocal conv
            ok = np.isin(outcome.status,
                         (_resilience.STATUS_OK,
                          _resilience.STATUS_RETRIED,
                          _resilience.STATUS_FALLBACK))
            conv += int(ok.sum())
            for name, count in outcome.counts().items():
                res_statuses[name] = res_statuses.get(name, 0) + count
            self._reg.inc("engine.chunks")
            if jr is not None:
                jr.commit(start, stop, model if keep_models else None,
                          {"n_real": int(stop - start),
                           "n_conv": int(ok.sum()),
                           "resilient": True,
                           "statuses": outcome.counts()})
                durex["journal_commits"] += 1
                self._reg.inc("engine.journal_commits")
                progress.note(journal_commits=1)
                full = (start, stop) == partition[idx]
                if full and _resilience.chunk_fault(
                        "kill_after_chunk", idx) is not None:
                    _pre_kill_incident(idx, start, stop)
                    os.kill(os.getpid(), signal.SIGKILL)
                if full and _resilience.chunk_fault(
                        "corrupt_journal", idx) is not None:
                    jr.corrupt_entry(start, stop)
            if collect:
                collected[start] = (stop, model)
            if (start, stop) in partition_set:
                progress.note_chunk_done()
            else:
                progress.note(subchunks_done=1)
            _publish_progress()

        def _failure_kind(e: Exception) -> str:
            if isinstance(e, ChunkDeadlineExceeded):
                return "deadline"
            if _durability.is_oom(e):
                return "oom"
            return "error"

        def _record_terminal(start: int, stop: int, e: Exception,
                             kind: str, attempts: int) -> None:
            """Declare one row range dead: the actionable failure record
            (exception type, truncated traceback, chunk geometry) plus
            counters.  ``engine.dead_chunks`` counts quarantine
            exhaustion, not deterministic data rejections."""
            nonlocal dead_series
            n_real = stop - start
            dead_series += n_real
            bs = chunk if n_real == chunk \
                else min(series_bucket(n_real), chunk)
            tb = "".join(_traceback.format_exception(
                type(e), e, e.__traceback__))
            record = {
                "chunk_start": int(start),
                "chunk_stop": int(stop),
                "n_series": int(n_real),
                "bucket": int(bs),
                "kind": kind,
                "error_type": type(e).__name__,
                "error": f"{type(e).__name__}: {e}",
                "traceback": tb[-2000:],
                "attempts": int(attempts),
            }
            failures.append(record)
            self._reg.inc("engine.chunk_failures")
            if (start, stop) in partition_set:
                progress.note(failed=1)
            else:
                progress.note(subchunks_failed=1)
            if kind != "data":
                durex["dead_chunks"] += 1
                self._reg.inc("engine.dead_chunks")
                # chunk death is an operator incident (a deterministic
                # data rejection is a caller bug, not a crash story)
                _flightrec.record_incident(
                    "chunk_dead", exc=e, job=progress,
                    journal_path=jr.path if jr is not None else None,
                    extra={"failure": record}, registry=self._reg)
            _metrics.trace_instant(
                "engine.chunk_failure",
                {"chunk_start": int(start), "chunk_stop": int(stop),
                 "kind": kind, "error": type(e).__name__})
            _publish_progress()

        def _quarantine(idx: int, start: int, stop: int, e: Exception,
                        kind: str) -> None:
            durex["quarantined"] += 1
            self._reg.inc("engine.quarantined")
            progress.note(quarantined=1)
            _metrics.trace_instant(
                "engine.quarantine",
                {"chunk_start": int(start), "chunk_stop": int(stop),
                 "kind": kind, "error": type(e).__name__})
            if kind == "oom":
                # an OOM only reaches quarantine when it can no longer
                # split (at the degrade floor, or degrade=False) — the
                # "memory pressure won" forensic moment
                _flightrec.record_incident(
                    "oom_at_floor", exc=e, job=progress,
                    journal_path=jr.path if jr is not None else None,
                    extra={"chunk": [int(start), int(stop)],
                           "degrade_floor": int(floor),
                           "degrade": bool(degrade)},
                    registry=self._reg)
            quarantine.append({"idx": idx, "start": start, "stop": stop,
                               "error": e, "kind": kind})

        def _split(idx: int, start: int, stop: int) -> None:
            """OOM degradation: halve the range and run each half
            synchronously; halves route their own failures (an OOM in a
            half that can still halve recurses toward the floor)."""
            durex["degraded_chunks"] += 1
            self._reg.inc("engine.degraded_chunks")
            progress.note(degraded=1)
            mid = start + (stop - start) // 2
            _metrics.trace_instant(
                "engine.degrade_split",
                {"chunk_start": int(start), "chunk_stop": int(stop),
                 "mid": int(mid)})
            for a, b in ((start, mid), (mid, stop)):
                try:
                    _run_sync(idx, a, b)
                except _ChunkDataError as e:
                    _record_terminal(a, b, e, "data", 1)
                except Exception as e:  # noqa: BLE001 — chunk isolation
                    _quarantine(idx, a, b, e, _failure_kind(e))

        def _run_sync(idx: int, start: int, stop: int) -> None:
            """One synchronous attempt at exactly ``[start, stop)``;
            raises on failure.  An OOM that can still split degrades
            instead (each half then succeeds or routes itself), which
            counts as this attempt succeeding.  Both stages sit inside
            the OOM check: execution-time RESOURCE_EXHAUSTED surfaces
            when *blocking* on async outputs, so a half whose
            materialization OOMs must recurse toward the floor exactly
            like a dispatch OOM."""
            try:
                if resilient:
                    _run_chunk_resilient(idx, start, stop)
                else:
                    out, entry, n_real, rec = _dispatch(idx, start, stop)
                    _materialize(out, entry, idx, start, stop, n_real,
                                 rec)
            except Exception as e:  # noqa: BLE001 — classified below
                if _durability.is_oom(e) and degrade \
                        and (stop - start) > floor:
                    _split(idx, start, stop)
                    return
                raise

        def _route_failure(idx: int, start: int, stop: int,
                           e: Exception) -> None:
            if isinstance(e, _ChunkDataError):
                _record_terminal(start, stop, e, "data", 1)
            elif _durability.is_oom(e) and degrade \
                    and (stop - start) > floor:
                _split(idx, start, stop)
            else:
                _quarantine(idx, start, stop, e, _failure_kind(e))

        def _resume_from_journal(start: int, stop: int) -> bool:
            """True when ``[start, stop)`` was fully committed by a prior
            run and every entry restores cleanly; a corrupt entry is
            quarantined (journal-side) and the chunk refits."""
            cover = jr.covering(start, stop)
            if cover is None:
                return False
            loaded = []
            for meta in cover:
                try:
                    model, pmeta = jr.load(meta)
                except Exception as e:  # noqa: BLE001 — any corruption
                    # (CRC, mismatched sidecar, garbled JSON) means the
                    # entry cannot be trusted: move it aside and refit
                    jr.quarantine(meta)
                    durex["journal_corrupt"] += 1
                    self._reg.inc("engine.journal_corrupt")
                    _metrics.trace_instant(
                        "engine.journal_corrupt",
                        {"chunk_start": int(meta.get("start", -1)),
                         "chunk_stop": int(meta.get("stop", -1)),
                         "error": type(e).__name__})
                    return False
                loaded.append((pmeta, model))
            nonlocal conv
            for pmeta, model in loaded:
                conv += int(pmeta.get("n_conv", 0))
                for name, count in (pmeta.get("statuses") or {}).items():
                    res_statuses[name] = res_statuses.get(name, 0) \
                        + int(count)
                if collect:
                    collected[int(pmeta["start"])] = (int(pmeta["stop"]),
                                                      model)
            # one hit per restored CHUNK (a degraded chunk's sub-entry
            # tiling is still one chunk skipped), so journal_hits +
            # journal_commits + dead data/quarantine chunks reconcile
            # against n_chunks
            durex["journal_hits"] += 1
            self._reg.inc("engine.journal_hits")
            progress.note_chunk_done(restored=True)
            _publish_progress()
            return True

        def _pull(out, entry: _Entry, idx: int, start: int, stop: int,
                  n_real: int, rec: Optional[Dict[str, Any]] = None
                  ) -> None:
            try:
                _materialize(out, entry, idx, start, stop, n_real, rec)
            except Exception as e:  # noqa: BLE001 — deferred device
                # errors surface at materialization; isolate the chunk
                _route_failure(idx, start, stop, e)

        t0 = time.perf_counter()
        try:
            with _metrics.span("engine.stream"):
                for idx, (start, stop) in enumerate(partition):
                    if jr is not None and _resume_from_journal(start, stop):
                        continue
                    if resilient:
                        try:
                            _run_sync(idx, start, stop)
                        except Exception as e:  # noqa: BLE001 — isolation
                            _route_failure(idx, start, stop, e)
                        continue
                    try:
                        out, entry, n_real, rec = _dispatch(idx, start,
                                                            stop)
                    except Exception as e:  # noqa: BLE001 — isolation
                        _route_failure(idx, start, stop, e)
                        continue
                    pending.append((out, entry, idx, start, stop, n_real,
                                    rec))
                    while len(pending) >= depth + 1:
                        _pull(*pending.popleft())
                while pending:
                    _pull(*pending.popleft())

                # end-of-stream quarantine: bounded deterministic backoff
                # retries, then declare the chunk dead.  Index-based walk —
                # a retry that degrades under OOM can quarantine fresh
                # sub-ranges, which get their own retries.
                qi = 0
                while qi < len(quarantine):
                    q = quarantine[qi]
                    qi += 1
                    recovered = False
                    last_err = q["error"]
                    attempts = 1
                    for attempt in range(1, policy.max_retries + 1):
                        delay = policy.delay(attempt)
                        durex["retry_attempts"] += 1
                        self._reg.inc("engine.retry_attempts")
                        progress.heartbeat("retry",
                                           chunk=(q["start"], q["stop"]))
                        _metrics.trace_instant(
                            "engine.retry_attempt",
                            {"chunk_start": int(q["start"]),
                             "chunk_stop": int(q["stop"]),
                             "attempt": attempt, "delay_s": delay})
                        attempts += 1
                        hung = getattr(last_err, "worker", None)
                        if hung is not None and hung.is_alive():
                            # a deadline-abandoned worker may still own
                            # this range's device buffers and eventually
                            # run its fit; the backoff doubles as a grace
                            # join, and while it lives we never race a
                            # duplicate dispatch against it
                            hung.join(delay)
                            if hung.is_alive():
                                continue
                        elif delay > 0:
                            time.sleep(delay)
                        try:
                            _run_sync(q["idx"], q["start"], q["stop"])
                            recovered = True
                            break
                        except Exception as e:  # noqa: BLE001 — retried
                            last_err = e
                    if recovered:
                        durex["recovered"] += 1
                        self._reg.inc("engine.quarantine_recovered")
                    else:
                        _record_terminal(q["start"], q["stop"], last_err,
                                         _failure_kind(last_err), attempts)
        except BaseException as e:
            # chunk failures are isolated above, so anything escaping the
            # stream is an un-modeled failure — the flight recorder's
            # "unhandled exception" trigger; the bundle lands before the
            # exception reaches the caller, and the job is marked failed
            # so /snapshot.json tells the story even post-mortem
            _flightrec.record_incident(
                "stream_exception", exc=e, job=progress,
                journal_path=jr.path if jr is not None else None,
                registry=self._reg)
            _telemetry.finish_job(progress, "failed",
                                  error=f"{type(e).__name__}: {e}",
                                  registry=self._reg)
            raise
        wall = time.perf_counter() - t0
        _telemetry.finish_job(progress, "done", registry=self._reg)

        after = self.cache_stats()
        # attribution rollup (docs/design.md §6g): host-side phase
        # seconds (everything but the device wait) over the stream's
        # wall, plus the accumulated device-idle bubble.  Last-write-wins
        # gauges, like the engine.job.* family — the per-stream truth
        # rides StreamResult.stats["phases"].
        host_s = (phase_totals["prep_s"] + phase_totals["pad_s"]
                  + phase_totals["dispatch_s"]
                  + phase_totals["reattach_s"]
                  + phase_totals["commit_s"])
        host_frac = min(1.0, host_s / wall) if wall > 0 else 0.0
        bubble_ms = phase_state["bubble_s"] * 1e3
        self._reg.set_gauge("engine.host_overhead_frac",
                            round(host_frac, 6))
        self._reg.set_gauge("engine.bubble_ms_total", round(bubble_ms, 3))
        phases_block = {
            "per_chunk": chunk_phases,
            "records_dropped": phase_state["dropped"],
            "totals_ms": {k[:-2] + "_ms": round(v * 1e3, 3)
                          for k, v in phase_totals.items()},
            "host_ms": round(host_s * 1e3, 3),
            "bubble_ms_total": round(bubble_ms, 3),
            "stage_wall_ms": round(phase_state["stage_wall_s"] * 1e3, 3),
            "wall_ms": round(wall * 1e3, 3),
            "host_overhead_frac": round(host_frac, 4),
        }
        stats = {
            "cache_hits": after["cache_hits"] - before["cache_hits"],
            "cache_misses": after["cache_misses"] - before["cache_misses"],
            "executables": after["executables"],
            "donated": don,
            "fused": use_fused,
            "publish_plans": len(publish_plans),
            "prefetch": depth,
            "chunk_size": chunk,
            "deadline_s": deadline,
            "retries": policy.max_retries,
            "job_id": progress.job_id,
            "phases": phases_block,
            **durex,
        }
        if resilient:
            stats["resilient"] = True
            stats["resilient_statuses"] = dict(res_statuses)
        if jr is not None:
            stats["journal_path"] = jr.path
        models = None
        if collect:
            # models come back with their row ranges (stats
            # "collected_ranges", aligned index-for-index with the models
            # list), so a consumer can place each pytree against the
            # source rows even when failed chunks leave gaps or a
            # degraded chunk contributes several sub-range models — the
            # longseries tier aligns per-segment coefficients this way
            keys = sorted(collected)
            models = [collected[k][1] for k in keys]
            stats["collected_ranges"] = [[int(k), int(collected[k][0])]
                                         for k in keys]
        return StreamResult(n_series, max(n_series - dead_series, 0), conv,
                            wall, len(partition), failures, models,
                            stats)


# ---------------------------------------------------------------------------
# default engine
# ---------------------------------------------------------------------------

_default_engine: Optional[FitEngine] = None
_default_lock = threading.Lock()


def default_engine() -> FitEngine:
    """The process-wide engine instance ``Panel`` and ``fit_panel`` route
    through (lazily created; ``STS_COMPILE_CACHE`` is honored at
    creation)."""
    global _default_engine
    with _default_lock:
        if _default_engine is None:
            _default_engine = FitEngine()
        return _default_engine


# ---------------------------------------------------------------------------
# CLI: `python -m spark_timeseries_tpu.engine` (the `make warmup` target)
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m spark_timeseries_tpu.engine",
        description="Precompile fit executables at the given shapes "
                    "(with STS_COMPILE_CACHE set, persists them to disk "
                    "so later processes skip compiles entirely).")
    ap.add_argument("--families", default="arima",
                    help=f"comma-separated subset of {ENGINE_FAMILIES} "
                         f"(default arima)")
    ap.add_argument("--shapes", default="16384x128",
                    help="comma-separated n_seriesXn_obs raw shapes; each "
                         "warms its padding bucket (default 16384x128, "
                         "the CPU bench chunk)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--cache-dir", default=None,
                    help="persistent compile cache directory (default: "
                         "$STS_COMPILE_CACHE when set)")
    ap.add_argument("--serving", action="store_true",
                    help="also warm the serving tier's per-tick update "
                         "executables at the same series counts "
                         "(statespace.serving.warmup_update; families "
                         "with a state-space form only)")
    args = ap.parse_args(argv)

    families = [f for f in args.families.split(",") if f]
    unknown = [f for f in families if f not in _STATICS_BUILDERS]
    if unknown:
        ap.error(f"unknown families {unknown}; expected subset of "
                 f"{sorted(_STATICS_BUILDERS)}")
    shapes = []
    try:
        for tok in args.shapes.split(","):
            if not tok:
                continue
            s, t = (int(x) for x in tok.lower().split("x"))
            if s < 1 or t < 1:
                raise ValueError
            shapes.append((s, t))
        if not shapes:
            raise ValueError
    except ValueError:
        ap.error(f"--shapes must be <n_series>x<n_obs>[,...] with positive "
                 f"ints, got {args.shapes!r}")

    _metrics.install_jax_hooks()
    eng = FitEngine(compile_cache_dir=args.cache_dir)
    report = eng.warmup(families, shapes, dtype=np.dtype(args.dtype))
    if args.serving:
        from .statespace import serving as _serving
        served = []
        for fam in families:
            if fam not in _serving.WARMUP_FAMILIES:
                continue
            for s, _t in shapes:
                served.append(_serving.warmup_update(
                    fam, s, dtype=np.dtype(args.dtype)))
        report["serving"] = served or (
            f"no serving-capable families in {families}; expected a "
            f"subset of {list(_serving.WARMUP_FAMILIES)}")
    report["jax"] = _metrics.jax_stats()
    print(json.dumps(report, indent=1))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
