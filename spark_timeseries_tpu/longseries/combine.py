"""The DARIMA combiner: segment estimates → one global model, by WLS.

Per-segment ARMA estimates live in incompatible parameter spaces the
moment segments choose different orders (the ``auto`` path) — and even
at a common order, averaging raw ``(φ, θ)`` ignores how unequally
segments determine them.  DARIMA's answer (PAPERS.md, arXiv 2007.09577;
the DLSA scheme) is adopted here in two moves, both **in-graph**:

1. **Common space** — every segment's ``(c, φ, θ)`` maps to its
   truncated AR(∞) representation ``(c_π, π₁..π_{n_ar})``
   (:func:`~spark_timeseries_tpu.models.arima.ar_truncation`; the
   mapping is exact for pure AR and geometric-tail-accurate for
   invertible MA parts), so heterogeneous segment orders become
   comparable coordinates of one linear model
   ``y_t = c_π + Σ π_j y_{t-j} + e_t``.
2. **Inverse-covariance weights** — in that linear model the segment
   estimator's asymptotic precision is its design information
   ``X_kᵀX_k / σ̂²_k`` (``X_k`` the segment's lag design, ``σ̂²_k`` its
   AR-residual variance), so the weighted-least-squares combination

       θ* = (Σ_k X_kᵀX_k/σ̂²_k)⁻¹ Σ_k (X_kᵀX_k/σ̂²_k) θ_k

   is one tiny SPD solve after a sum of per-segment gram products.

Everything per-segment is one jitted program over a *chunk* of segments
(:func:`_combine_chunk_impl` — the ``long_combine`` cost/contract
family); the ``(D,D)`` information sum and ``(D,)`` weighted-estimate
sum ride across chunks **device-resident** (:func:`_combine_chunk_acc`),
so the host crosses exactly once — the final accumulator
materialization before the ridge-guarded solve.  The fused path
(:func:`fused_fit_combine`, docs/design.md §6e) goes one step further
and traces the segment *fit* into the same per-chunk program, so
``fit_long``'s whole fit→combine round trip is one executable per
chunk.  Segments with non-finite estimates, grams,
or variances get weight zero; if nothing is weightable the result falls
back to the plain mean of finite segment estimates, mirroring
``arima.fit_long``'s quarantine-to-init behavior.

Overlapping windows (``split.segment_panel`` with ``overlap > 0``)
double-cover ``overlap`` observations per boundary; the ``burn`` static
(``max(n_ar, overlap)``) zero-weights each window's leading rows so
every observation contributes to exactly one segment's gram.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from ..utils import metrics as _metrics

__all__ = ["combine_segments", "fused_fit_combine",
           "expected_combine_acc_bytes", "CombinedResult"]


def expected_combine_acc_bytes(n_ar: int, include_intercept: bool = True,
                               dtype=np.float32) -> int:
    """Bytes of the ONE sanctioned device→host crossing of a fused
    combination — the final accumulator pull (``A (D,D)``, ``b (D,)``,
    ``theta_sum (D,)`` and ``sig_sum`` in the panel dtype; three int32
    counters).  The ``fit_long`` analogue of
    ``engine.expected_chunk_result_bytes``: what
    ``longseries.fused_bytes_d2h`` must count per combination, exactly."""
    D = (1 if include_intercept else 0) + int(n_ar)
    it = np.dtype(dtype).itemsize
    return (D * D + 2 * D + 1) * it + 3 * 4


class CombinedResult(NamedTuple):
    """Outcome of one WLS combination.

    ``coefficients (D,)`` in the fit layout ``[c_π?, π₁..π_{n_ar}]``;
    ``sigma2`` the ok-segment mean AR-residual variance (the combined
    model's innovation-variance estimate); ``used_wls`` False when no
    segment was weightable and the mean-of-finite fallback produced the
    coefficients."""
    coefficients: np.ndarray
    sigma2: float
    n_segments: int
    n_finite: int
    n_weighted: int
    n_converged: int
    used_wls: bool


def _combine_chunk_impl(segs, coefs, conv, p: int, q: int, icpt: int,
                        n_ar: int, burn: int):
    """One chunk of segments → its summed combination pieces.

    ``segs (K, L)`` segment windows, ``coefs (K, icpt+p+q)`` per-segment
    ARMA estimates (NaN rows = failed segments), ``conv (K,)`` their
    converged flags.  Statics: the common order layout, the AR-truncation
    length, and the burn-in row count (``max(n_ar, overlap)`` — also
    de-duplicates overlapped observations).  Returns per-chunk sums:
    ``(A (D,D), b (D,), n_ok, theta_sum (D,), n_finite, sigma2_sum,
    n_conv)``.  Fully traced — no host callbacks, no value-dependent
    branching — so the whole combination is ``n_chunks`` dispatches.
    """
    import jax.numpy as jnp

    from ..models.arima import _split_params, ar_truncation
    from ..ops.lag import lag_stack

    dtype = segs.dtype
    K, L = segs.shape
    D = icpt + n_ar
    c, phi, theta = _split_params(coefs, p, q, icpt)
    c_pi, pi = ar_truncation(c, phi, theta, n_ar)            # (K,), (K,n_ar)
    if icpt:
        th = jnp.concatenate([c_pi[:, None], pi], axis=-1)   # (K, D)
    else:
        th = pi

    X = lag_stack(segs, n_ar)                                # (K, n_ar, R)
    rows = L - n_ar
    if icpt:
        X = jnp.concatenate([jnp.ones((K, 1, rows), dtype), X], axis=-2)
    y_t = segs[..., n_ar:]
    # row r targets window index n_ar + r; burn rows carry weight 0 (the
    # 0/1 weights square to themselves, so weighting one gram side is
    # exact — the ols_gram rule)
    w = ((n_ar + jnp.arange(rows)) >= burn).astype(dtype)    # (R,)
    Xw = X * w[None, None, :]
    G = jnp.einsum("kpn,kqn->kpq", Xw, X)                    # (K, D, D)
    resid = (y_t - jnp.einsum("kpn,kp->kn", X, th)) * w[None, :]
    n_live = jnp.sum(w)
    dof = jnp.maximum(n_live - D, 1.0)
    sigma2 = jnp.sum(resid * resid, axis=-1) / dof           # (K,)

    finite = jnp.all(jnp.isfinite(th), axis=-1)
    ok = (finite & jnp.isfinite(sigma2) & (sigma2 > 0)
          & jnp.all(jnp.isfinite(G), axis=(-2, -1)))
    # zero unusable segments with where (NaN·0 is NaN — a poisoned
    # segment must not leak through the sums)
    Wk = jnp.where(ok[:, None, None],
                   G / jnp.where(ok, sigma2, 1.0)[:, None, None], 0.0)
    th_ok = jnp.where(ok[:, None], th, 0.0)
    A = jnp.sum(Wk, axis=0)
    b = jnp.sum(jnp.einsum("kpq,kq->kp", Wk, th_ok), axis=0)
    theta_sum = jnp.sum(jnp.where(finite[:, None], th, 0.0), axis=0)
    sig_sum = jnp.sum(jnp.where(ok, sigma2, 0.0))
    n_conv = jnp.sum(ok & jnp.asarray(conv))
    return (A, b, jnp.sum(ok), theta_sum, jnp.sum(finite), sig_sum,
            n_conv)


def _combine_chunk_acc(segs, coefs, conv, acc, p: int, q: int, icpt: int,
                       n_ar: int, burn: int):
    """One chunk's combination pieces folded into the device-resident
    accumulators — the whole-pipeline-fusion form (docs/design.md §6e):
    the cross-chunk reduction happens in-graph, so the host crosses
    ONCE per combination (the final accumulator materialization)
    instead of seven times per chunk."""
    out = _combine_chunk_impl(segs, coefs, conv, p, q, icpt, n_ar, burn)
    # pin each lane to the accumulator's dtype: under x64 the impl's
    # counter reductions come back int64 and would promote the int32
    # counters, shifting the pinned accumulator byte budget
    return tuple((a + o).astype(a.dtype) for a, o in zip(acc, out))


def _fused_chunk_impl(segs, n_real, acc, p: int, q: int, icpt: int,
                      n_ar: int, burn: int, method: str,
                      max_iter, objective: str):
    """ONE program per segment chunk: fit the chunk's segments AND fold
    their combination pieces into the device-resident accumulators —
    the fused fit→combine path (docs/design.md §6e).  The per-segment
    coefficients never cross the host; ``n_real`` masks zero-padded tail
    lanes in-graph (their fits run but combine at weight zero, exactly
    like the stream tier's pad lanes)."""
    import jax.numpy as jnp

    from ..models.arima import segment_fit_outputs

    coefs, conv = segment_fit_outputs(
        p, q, segs, include_intercept=icpt != 0, method=method,
        max_iter=max_iter, objective=objective)
    lane = jnp.arange(segs.shape[0], dtype=jnp.int32) < n_real
    coefs = jnp.where(lane[:, None], coefs,
                      jnp.asarray(jnp.nan, coefs.dtype))
    conv = jnp.logical_and(conv, lane)
    out = _combine_chunk_impl(segs, coefs, conv, p, q, icpt, n_ar, burn)
    # pin each lane to the accumulator's dtype: under x64 the impl's
    # counter reductions come back int64 and would promote the int32
    # counters, shifting the pinned accumulator byte budget
    return tuple((a + o).astype(a.dtype) for a, o in zip(acc, out))


# module-level jits (STS006): every chunk of every combination shares one
# function object, so same-shape chunks hit the jit cache.  The
# accumulator argument is donated on accelerators (successive chunks
# update the same buffers in place); XLA CPU cannot alias donated
# buffers, so the CPU jits skip donation instead of warning per call.
def _jitted_chunk():
    import jax

    fn = _jitted_chunk.__dict__.get("fn")
    if fn is None:
        fn = jax.jit(_combine_chunk_impl, static_argnums=(3, 4, 5, 6, 7))
        _jitted_chunk.fn = fn
    return fn


def _jitted_chunk_acc():
    import jax

    fn = _jitted_chunk_acc.__dict__.get("fn")
    if fn is None:
        donate = (3,) if jax.default_backend() != "cpu" else ()
        fn = jax.jit(_combine_chunk_acc,
                     static_argnums=(4, 5, 6, 7, 8),
                     donate_argnums=donate)
        _jitted_chunk_acc.fn = fn
    return fn


def _jitted_fused():
    import jax

    fn = _jitted_fused.__dict__.get("fn")
    if fn is None:
        donate = (2,) if jax.default_backend() != "cpu" else ()
        fn = jax.jit(_fused_chunk_impl,
                     static_argnums=(3, 4, 5, 6, 7, 8, 9, 10),
                     donate_argnums=donate)
        _jitted_fused.fn = fn
    return fn


def _zero_acc(D: int, dtype):
    """Fresh device-resident accumulators in the combine layout:
    ``(A, b, n_ok, theta_sum, n_finite, sig_sum, n_conv)``.  Float
    pieces accumulate in the compute dtype in-graph; the staged host
    path's f64 cross-chunk order is gone on both paths (documented —
    docs/design.md §6e; the final solve still runs in f64 on host)."""
    import jax.numpy as jnp

    i32 = jnp.int32
    return (jnp.zeros((D, D), dtype), jnp.zeros((D,), dtype),
            jnp.zeros((), i32), jnp.zeros((D,), dtype),
            jnp.zeros((), i32), jnp.zeros((), dtype),
            jnp.zeros((), i32))


def _finalize(acc_host, *, D: int, K: int, dtype,
              ridge: float) -> CombinedResult:
    """Shared tail of both combine paths: the one sanctioned
    materialization already happened — ``acc_host`` is the 7-tuple of
    numpy accumulators — so this is pure host arithmetic: the
    ridge-guarded f64 WLS solve, the mean-of-finite fallback, and the
    counter bookkeeping."""
    A = np.asarray(acc_host[0], np.float64)
    b = np.asarray(acc_host[1], np.float64)
    n_ok = int(acc_host[2])
    theta_sum = np.asarray(acc_host[3], np.float64)
    n_finite = int(acc_host[4])
    sig_sum = float(acc_host[5])
    n_conv = int(acc_host[6])

    used_wls = False
    combined = np.zeros((D,), np.float64)
    if n_ok:
        scale = max(float(np.max(np.abs(np.diag(A)))), 1.0)
        solved = np.linalg.solve(A + ridge * scale * np.eye(D), b)
        if np.all(np.isfinite(solved)):
            combined = solved
            used_wls = True
    if not used_wls and n_finite:
        combined = theta_sum / n_finite
    sigma2 = sig_sum / n_ok if n_ok else float("nan")
    reg = _metrics.get_registry()
    reg.inc("longseries.segments_combined", n_ok)
    reg.inc("longseries.segments_dropped", K - n_ok)
    return CombinedResult(
        coefficients=combined.astype(dtype),
        sigma2=sigma2, n_segments=K, n_finite=n_finite,
        n_weighted=n_ok, n_converged=n_conv, used_wls=used_wls)


def combine_segments(segs: np.ndarray, coefs: np.ndarray,
                     converged: Optional[np.ndarray] = None, *,
                     p: int, q: int, include_intercept: bool = True,
                     n_ar: int, overlap: int = 0,
                     chunk_segments: int = 256,
                     ridge: float = 1e-8) -> CombinedResult:
    """Combine per-segment ARMA estimates into one global AR(``n_ar``)
    model by design-gram WLS (module docstring has the algebra).

    ``segs (K, L)`` the segment panel (``split.segment_panel``), ``coefs
    (K, icpt+p+q)`` per-segment estimates in the fit layout (NaN rows =
    dead segments — weight 0), ``converged (K,)`` optional per-segment
    convergence flags (reporting only).  ``chunk_segments`` bounds how
    many segments one jitted accumulation dispatch sees — the ONLY host
    crossing is the final accumulator materialization after the last
    chunk (docs/design.md §6e): the cross-chunk reduction stays
    device-resident in the panel dtype, folded in-graph by
    :func:`_combine_chunk_acc`.
    """
    segs = np.asarray(segs)
    coefs = np.asarray(coefs, segs.dtype)
    K, L = segs.shape
    if coefs.shape[0] != K:
        raise ValueError(
            f"{coefs.shape[0]} coefficient rows for {K} segments")
    icpt = 1 if include_intercept else 0
    n_ar = int(n_ar)
    if L <= max(n_ar, overlap) + n_ar + icpt:
        raise ValueError(
            f"segment window {L} too short for an AR({n_ar}) design "
            f"with burn-in {max(n_ar, overlap)}")
    conv = np.ones((K,), bool) if converged is None \
        else np.asarray(converged, bool).reshape(K)
    burn = max(n_ar, int(overlap))
    D = icpt + n_ar
    fn = _jitted_chunk_acc()

    step = max(1, int(chunk_segments))
    acc = _zero_acc(D, segs.dtype)
    with _metrics.span("longseries.combine"):
        for s in range(0, K, step):
            part = segs[s:s + step]
            acc = fn(part, coefs[s:s + step], conv[s:s + step], acc,
                     int(p), int(q), icpt, n_ar, burn)
        acc_host = tuple(np.asarray(a) for a in acc)
    return _finalize(acc_host, D=D, K=K, dtype=segs.dtype, ridge=ridge)


def fused_fit_combine(panel: np.ndarray, *, p: int, q: int,
                      include_intercept: bool = True, n_ar: int,
                      overlap: int = 0, chunk_segments: int = 256,
                      ridge: float = 1e-8, method: str = "css-lm",
                      max_iter: Optional[int] = None,
                      objective: str = "css") -> CombinedResult:
    """The fused ``fit_long`` hot path: segment fit AND WLS combination
    in ONE donated XLA program per segment chunk (docs/design.md §6e).

    ``panel (K, L)`` is the segment panel from ``split.segment_panel``.
    Where the staged path runs ``stream_fit`` over the segments (one
    fit program per chunk, per-segment coefficients materialized to the
    host) and then :func:`combine_segments` (one combine program per
    chunk), this traces :func:`~spark_timeseries_tpu.models.arima.\
segment_fit_outputs` straight into :func:`_combine_chunk_impl`: the
    per-segment coefficients never leave the device, the accumulators
    ride across chunks device-resident, and the host sees exactly one
    materialization — the final 7-tuple of sums.

    Every chunk is padded with zero lanes to the ``chunk_segments``
    width so the whole combination compiles exactly one executable;
    ``n_real`` masks the pad lanes in-graph (NaN-poisoned coefficients
    + convergence False → combination weight zero).  Accumulation order
    matches :func:`combine_segments`'s device path chunk-for-chunk, so
    fused-vs-staged differences come only from the fit fusing with the
    combine in one program (≤1e-6 at f32 bench scale — the equivalence
    tests pin this).

    Counters: ``longseries.fused_programs`` (dispatches) and
    ``longseries.fused_bytes_d2h`` (bytes of the one materialization) —
    the boundary contract for the ``fit_long`` budget row.
    """
    panel = np.asarray(panel)
    K, L = panel.shape
    icpt = 1 if include_intercept else 0
    n_ar = int(n_ar)
    if L <= max(n_ar, overlap) + n_ar + icpt:
        raise ValueError(
            f"segment window {L} too short for an AR({n_ar}) design "
            f"with burn-in {max(n_ar, overlap)}")
    burn = max(n_ar, int(overlap))
    D = icpt + n_ar
    step = max(1, min(int(chunk_segments), K))
    mi = None if max_iter is None else int(max_iter)
    fn = _jitted_fused()

    from ..models.base import unroll_hint

    acc = _zero_acc(D, panel.dtype)
    programs = 0
    # the chunk width is the scan-unroll amortization signal, exactly as
    # in engine._entry (models.base.scan_unroll)
    with _metrics.span("longseries.fused_fit_combine"), \
            unroll_hint(step):
        for s in range(0, K, step):
            part = panel[s:s + step]
            n_real = part.shape[0]
            if n_real < step:
                part = np.concatenate(
                    [part, np.zeros((step - n_real, L), panel.dtype)])
            acc = fn(part, np.int32(n_real), acc, int(p), int(q), icpt,
                     n_ar, burn, str(method), mi, str(objective))
            programs += 1
        acc_host = tuple(np.asarray(a) for a in acc)
    reg = _metrics.get_registry()
    reg.inc("longseries.fused_programs", programs)
    reg.inc("longseries.fused_bytes_d2h",
            sum(int(a.nbytes) for a in acc_host))
    return _finalize(acc_host, D=D, K=K, dtype=panel.dtype, ridge=ridge)
