"""The DARIMA combiner: segment estimates → one global model, by WLS.

Per-segment ARMA estimates live in incompatible parameter spaces the
moment segments choose different orders (the ``auto`` path) — and even
at a common order, averaging raw ``(φ, θ)`` ignores how unequally
segments determine them.  DARIMA's answer (PAPERS.md, arXiv 2007.09577;
the DLSA scheme) is adopted here in two moves, both **in-graph**:

1. **Common space** — every segment's ``(c, φ, θ)`` maps to its
   truncated AR(∞) representation ``(c_π, π₁..π_{n_ar})``
   (:func:`~spark_timeseries_tpu.models.arima.ar_truncation`; the
   mapping is exact for pure AR and geometric-tail-accurate for
   invertible MA parts), so heterogeneous segment orders become
   comparable coordinates of one linear model
   ``y_t = c_π + Σ π_j y_{t-j} + e_t``.
2. **Inverse-covariance weights** — in that linear model the segment
   estimator's asymptotic precision is its design information
   ``X_kᵀX_k / σ̂²_k`` (``X_k`` the segment's lag design, ``σ̂²_k`` its
   AR-residual variance), so the weighted-least-squares combination

       θ* = (Σ_k X_kᵀX_k/σ̂²_k)⁻¹ Σ_k (X_kᵀX_k/σ̂²_k) θ_k

   is one tiny SPD solve after a sum of per-segment gram products.

Everything per-segment is one jitted program over a *chunk* of segments
(:func:`_combine_chunk_impl` — the ``long_combine`` cost/contract
family): the host only crosses between chunks, accumulating the ``(D,D)``
information sum and ``(D,)`` weighted-estimate sum, then performs one
final ridge-guarded solve.  Segments with non-finite estimates, grams,
or variances get weight zero; if nothing is weightable the result falls
back to the plain mean of finite segment estimates, mirroring
``arima.fit_long``'s quarantine-to-init behavior.

Overlapping windows (``split.segment_panel`` with ``overlap > 0``)
double-cover ``overlap`` observations per boundary; the ``burn`` static
(``max(n_ar, overlap)``) zero-weights each window's leading rows so
every observation contributes to exactly one segment's gram.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from ..utils import metrics as _metrics

__all__ = ["combine_segments", "CombinedResult"]


class CombinedResult(NamedTuple):
    """Outcome of one WLS combination.

    ``coefficients (D,)`` in the fit layout ``[c_π?, π₁..π_{n_ar}]``;
    ``sigma2`` the ok-segment mean AR-residual variance (the combined
    model's innovation-variance estimate); ``used_wls`` False when no
    segment was weightable and the mean-of-finite fallback produced the
    coefficients."""
    coefficients: np.ndarray
    sigma2: float
    n_segments: int
    n_finite: int
    n_weighted: int
    n_converged: int
    used_wls: bool


def _combine_chunk_impl(segs, coefs, conv, p: int, q: int, icpt: int,
                        n_ar: int, burn: int):
    """One chunk of segments → its summed combination pieces.

    ``segs (K, L)`` segment windows, ``coefs (K, icpt+p+q)`` per-segment
    ARMA estimates (NaN rows = failed segments), ``conv (K,)`` their
    converged flags.  Statics: the common order layout, the AR-truncation
    length, and the burn-in row count (``max(n_ar, overlap)`` — also
    de-duplicates overlapped observations).  Returns per-chunk sums:
    ``(A (D,D), b (D,), n_ok, theta_sum (D,), n_finite, sigma2_sum,
    n_conv)``.  Fully traced — no host callbacks, no value-dependent
    branching — so the whole combination is ``n_chunks`` dispatches.
    """
    import jax.numpy as jnp

    from ..models.arima import _split_params, ar_truncation
    from ..ops.lag import lag_stack

    dtype = segs.dtype
    K, L = segs.shape
    D = icpt + n_ar
    c, phi, theta = _split_params(coefs, p, q, icpt)
    c_pi, pi = ar_truncation(c, phi, theta, n_ar)            # (K,), (K,n_ar)
    if icpt:
        th = jnp.concatenate([c_pi[:, None], pi], axis=-1)   # (K, D)
    else:
        th = pi

    X = lag_stack(segs, n_ar)                                # (K, n_ar, R)
    rows = L - n_ar
    if icpt:
        X = jnp.concatenate([jnp.ones((K, 1, rows), dtype), X], axis=-2)
    y_t = segs[..., n_ar:]
    # row r targets window index n_ar + r; burn rows carry weight 0 (the
    # 0/1 weights square to themselves, so weighting one gram side is
    # exact — the ols_gram rule)
    w = ((n_ar + jnp.arange(rows)) >= burn).astype(dtype)    # (R,)
    Xw = X * w[None, None, :]
    G = jnp.einsum("kpn,kqn->kpq", Xw, X)                    # (K, D, D)
    resid = (y_t - jnp.einsum("kpn,kp->kn", X, th)) * w[None, :]
    n_live = jnp.sum(w)
    dof = jnp.maximum(n_live - D, 1.0)
    sigma2 = jnp.sum(resid * resid, axis=-1) / dof           # (K,)

    finite = jnp.all(jnp.isfinite(th), axis=-1)
    ok = (finite & jnp.isfinite(sigma2) & (sigma2 > 0)
          & jnp.all(jnp.isfinite(G), axis=(-2, -1)))
    # zero unusable segments with where (NaN·0 is NaN — a poisoned
    # segment must not leak through the sums)
    Wk = jnp.where(ok[:, None, None],
                   G / jnp.where(ok, sigma2, 1.0)[:, None, None], 0.0)
    th_ok = jnp.where(ok[:, None], th, 0.0)
    A = jnp.sum(Wk, axis=0)
    b = jnp.sum(jnp.einsum("kpq,kq->kp", Wk, th_ok), axis=0)
    theta_sum = jnp.sum(jnp.where(finite[:, None], th, 0.0), axis=0)
    sig_sum = jnp.sum(jnp.where(ok, sigma2, 0.0))
    n_conv = jnp.sum(ok & jnp.asarray(conv))
    return (A, b, jnp.sum(ok), theta_sum, jnp.sum(finite), sig_sum,
            n_conv)


# module-level jit (STS006): every chunk of every combination shares one
# function object, so same-shape chunks hit the jit cache
def _jitted_chunk():
    import jax

    fn = _jitted_chunk.__dict__.get("fn")
    if fn is None:
        fn = jax.jit(_combine_chunk_impl, static_argnums=(3, 4, 5, 6, 7))
        _jitted_chunk.fn = fn
    return fn


def combine_segments(segs: np.ndarray, coefs: np.ndarray,
                     converged: Optional[np.ndarray] = None, *,
                     p: int, q: int, include_intercept: bool = True,
                     n_ar: int, overlap: int = 0,
                     chunk_segments: int = 256,
                     ridge: float = 1e-8) -> CombinedResult:
    """Combine per-segment ARMA estimates into one global AR(``n_ar``)
    model by design-gram WLS (module docstring has the algebra).

    ``segs (K, L)`` the segment panel (``split.segment_panel``), ``coefs
    (K, icpt+p+q)`` per-segment estimates in the fit layout (NaN rows =
    dead segments — weight 0), ``converged (K,)`` optional per-segment
    convergence flags (reporting only).  ``chunk_segments`` bounds how
    many segments one jitted accumulation dispatch sees — the only
    host crossings are between chunks.
    """
    segs = np.asarray(segs)
    coefs = np.asarray(coefs, segs.dtype)
    K, L = segs.shape
    if coefs.shape[0] != K:
        raise ValueError(
            f"{coefs.shape[0]} coefficient rows for {K} segments")
    icpt = 1 if include_intercept else 0
    n_ar = int(n_ar)
    if L <= max(n_ar, overlap) + n_ar + icpt:
        raise ValueError(
            f"segment window {L} too short for an AR({n_ar}) design "
            f"with burn-in {max(n_ar, overlap)}")
    conv = np.ones((K,), bool) if converged is None \
        else np.asarray(converged, bool).reshape(K)
    burn = max(n_ar, int(overlap))
    D = icpt + n_ar
    fn = _jitted_chunk()

    # host-side accumulators in f64: chunk sums arrive in the panel
    # dtype, but the cross-chunk reduction is host arithmetic
    A = np.zeros((D, D), np.float64)
    b = np.zeros((D,), np.float64)
    theta_sum = np.zeros((D,), np.float64)
    n_ok = n_finite = n_conv = 0
    sig_sum = 0.0
    step = max(1, int(chunk_segments))
    with _metrics.span("longseries.combine"):
        for s in range(0, K, step):
            part = segs[s:s + step]
            out = fn(part, coefs[s:s + step], conv[s:s + step],
                     int(p), int(q), icpt, n_ar, burn)
            A += np.asarray(out[0], np.float64)
            b += np.asarray(out[1], np.float64)
            n_ok += int(out[2])
            theta_sum += np.asarray(out[3], np.float64)
            n_finite += int(out[4])
            sig_sum += float(out[5])
            n_conv += int(out[6])

    used_wls = False
    combined = np.zeros((D,), np.float64)
    if n_ok:
        scale = max(float(np.max(np.abs(np.diag(A)))), 1.0)
        solved = np.linalg.solve(A + ridge * scale * np.eye(D), b)
        if np.all(np.isfinite(solved)):
            combined = solved
            used_wls = True
    if not used_wls and n_finite:
        combined = theta_sum / n_finite
    sigma2 = sig_sum / n_ok if n_ok else float("nan")
    reg = _metrics.get_registry()
    reg.inc("longseries.segments_combined", n_ok)
    reg.inc("longseries.segments_dropped", K - n_ok)
    return CombinedResult(
        coefficients=combined.astype(segs.dtype),
        sigma2=sigma2, n_segments=K, n_finite=n_finite,
        n_weighted=n_ok, n_converged=n_conv, used_wls=used_wls)
