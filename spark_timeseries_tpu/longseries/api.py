"""`fit_long`: the ultra-long-series front door.

One call turns a single 10⁶–10⁸-observation series into work the
existing machinery already knows how to do, end to end:

1. **difference globally** (``split.difference`` — one common ``d``, so
   every segment estimates a pure ARMA in one parameter space);
2. **split the obs axis** (``split.segment_panel`` via
   ``stats.segment_plan``) into an ``(n_segments, window)`` panel;
3. **fit segments as a batch** — either through
   ``engine.stream_fit`` (chunked, shape-bucketed executables, buffer
   donation, crash-consistent journal + resume, per-chunk deadlines,
   quarantine/backoff retries, OOM-adaptive halving: the whole
   durability tier applies to the obs axis for free) or, with
   ``auto=True``, through ``models.arima.auto_fit_panel`` (per-segment
   (p, q) order selection in one fused dispatch — DARIMA's
   heterogeneous-order mode);
4. **combine by WLS** in the common AR-truncation space
   (``longseries.combine`` — in-graph per chunk of segments);
5. **forecast exactly** — the combined AR model converts through
   ``statespace.to_statespace`` and the forecast-origin filter state
   over the FULL series is recovered in O(log chunk) depth by
   ``statespace.kalman.filter_forecast_origin``
   (``ops.scan_parallel.affine_recurrence``), so
   :meth:`LongSeriesFit.forecast` agrees with the sequential Kalman
   filter run over every observation — not a segment-local
   approximation.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np

from ..stats import SegmentPlan, segment_plan
from ..utils import metrics as _metrics
from . import combine as _combine
from . import split as _split

__all__ = ["fit_long", "LongSeriesFit", "FusedDurabilityError"]


class FusedDurabilityError(ValueError):
    """``fused=True`` was combined with a durability/streaming knob the
    fused fit→combine program cannot honor (``journal``, ``deadline_s``,
    ``chunk_retry``, ``engine``, ``degrade=False`` — or ``auto=True``,
    which is its own fused dispatch).  The fused path never touches
    ``stream_fit``, so a journal would never commit and a deadline would
    never arm; refusing loudly here beats a resume that silently refits
    (docs/design.md §6e)."""

# default AR-truncation length when the order carries an MA part: the
# tail decays at the MA root rate, so 12 terms put the truncation error
# below f32 resolution for |θ| ≲ 0.4 and below statistical noise for
# anything invertible; pure-AR orders map exactly at n_ar = p
DEFAULT_MA_TRUNCATION = 12

# segments per streamed chunk: big enough to amortize dispatch, small
# enough that chunk × window stays a few hundred MB at 10⁶-obs scale
DEFAULT_CHUNK_SEGMENTS = 512


class LongSeriesFit:
    """A combined ultra-long fit: the global AR model, the split
    geometry, per-segment accounting, and exact forecasting.

    ``model`` is a standard
    :class:`~spark_timeseries_tpu.models.arima.ARIMAModel` —
    AR(``n_ar``) with the original ``d`` — so everything a fitted model
    can do (likelihoods, statespace conversion, serving sessions) works
    on the combined estimate unchanged.
    """

    def __init__(self, model, plan: SegmentPlan,
                 combined: _combine.CombinedResult,
                 diffed: np.ndarray, ring: np.ndarray,
                 stream_stats: Optional[Dict[str, Any]] = None,
                 segment_orders: Optional[np.ndarray] = None,
                 warm: int = 512, origin_chunk: int = 65536):
        self.model = model
        self.plan = plan
        self.combined = combined
        self.sigma2 = combined.sigma2
        self.stream_stats = stream_stats
        self.segment_orders = segment_orders
        self._diffed = diffed
        self._dtype = diffed.dtype
        self._ring = ring
        self._warm = int(warm)
        self._origin_chunk = int(origin_chunk)
        self._origin_cache = None

    # -- introspection ------------------------------------------------------

    @property
    def coefficients(self):
        return self.model.coefficients

    @property
    def diagnostics(self):
        return self.model.diagnostics

    def describe(self) -> Dict[str, Any]:
        return {
            "order": (self.model.p, self.model.d, self.model.q),
            "n_obs": int(self.plan.head_drop + self.plan.n_used
                         + self.model.d),
            "n_segments": self.plan.n_segments,
            "seg_len": self.plan.seg_len,
            "overlap": self.plan.overlap,
            "head_drop": self.plan.head_drop,
            "segments_weighted": self.combined.n_weighted,
            "segments_finite": self.combined.n_finite,
            "segments_converged": self.combined.n_converged,
            "used_wls": self.combined.used_wls,
            "sigma2": self.sigma2,
        }

    # -- exact forecasting --------------------------------------------------

    def forecast_origin(self):
        """The exact forecast-origin
        :class:`~spark_timeseries_tpu.statespace.ssm.FilterState` of the
        combined model over the **full** differenced series — recovered
        once (cached) via
        :func:`~spark_timeseries_tpu.statespace.kalman.filter_forecast_origin`:
        a short sequential covariance burn-in, then pinned-gain
        ``affine_recurrence`` chunks in O(log chunk) depth.  Its ``a`` is
        the one-step-predicted state the next tick would filter against;
        its ``ring`` already holds the raw-difference seeds, so the state
        is forecast-ready on the raw scale."""
        if self._origin_cache is not None:
            return self._origin_cache
        import jax.numpy as jnp

        from ..statespace import to_statespace
        from ..statespace.kalman import filter_forecast_origin
        from ..statespace.ssm import SSMeta, initial_state

        ssm, meta = to_statespace(self.model)
        meta0 = SSMeta(meta.family, meta.mode, 0, meta.m)
        state0 = initial_state(ssm, meta0)
        with _metrics.span("longseries.forecast_origin"):
            origin = filter_forecast_origin(
                ssm, state0, self._diffed[None, :], meta0,
                warm=self._warm, chunk=self._origin_chunk)
        origin = origin._replace(ring=jnp.asarray(self._ring[None, :]))
        self._origin_cache = (ssm, meta, origin)
        # the differenced series is only needed to recover the origin;
        # at this tier's own scale (10⁶–10⁸ obs) keeping it alive would
        # double the fit handle's resident memory for nothing
        self._diffed = None
        return self._origin_cache

    def forecast(self, horizon: int) -> np.ndarray:
        """``(horizon,)`` point forecasts from the exact forecast-origin
        state — mean propagation with zero future innovations, integrated
        through the raw-difference ring (the same compiled program
        serving sessions use).  Exact, not segment-local: the origin
        state conditions on every observation in the series."""
        horizon = int(horizon)
        if horizon < 1:
            raise ValueError("forecast needs horizon >= 1")
        import jax.numpy as jnp

        from ..statespace.health import HealthPolicy, initial_health
        from ..statespace.serving import _jitted

        ssm, meta, origin = self.forecast_origin()
        offs = jnp.zeros((1, horizon), self._dtype)
        # the shared serving forecast program is health-aware (PR 9);
        # a freshly recovered origin is by construction an all-OK lane,
        # so the default policy + initial health reproduce the plain
        # mean propagation (quarantine masks nothing)
        policy = HealthPolicy().validate()
        health = initial_health(origin)
        with _metrics.span("longseries.forecast"):
            out = np.asarray(_jitted("forecast")(meta, horizon, policy,
                                                 ssm, origin, health,
                                                 offs))
        return out[0]

    @property
    def loglik(self) -> float:
        """Exact σ²-concentrated Gaussian log-likelihood of the combined
        model over the differenced series (a by-product of the origin
        recovery).  The filter runs at unit noise scale — `to_statespace`
        builds the SSM uncalibrated — so the raw accumulated loglik is
        NOT the model likelihood; σ² is profiled out in closed form from
        the carried (ssq, sumlogf, n_obs) instead
        (``kalman.concentrated_loglik``), the same convention as
        ``ARIMAModel.log_likelihood_exact`` (pinned by test)."""
        from ..statespace.kalman import concentrated_loglik

        _, _, origin = self.forecast_origin()
        return float(concentrated_loglik(origin)[0])

    def __repr__(self) -> str:
        return (f"LongSeriesFit(AR({self.model.p}), d={self.model.d}, "
                f"segments={self.plan.n_segments}x{self.plan.window}, "
                f"weighted={self.combined.n_weighted})")


def _collect_segment_coefs(result, n_segments: int, dim: int,
                           dtype) -> Tuple[np.ndarray, np.ndarray]:
    """Per-segment coefficient rows + converged flags from a
    ``StreamResult``, aligned through ``stats["collected_ranges"]`` —
    failed chunks leave NaN rows (weight 0 in the combiner), degraded
    chunks contribute per-sub-range models."""
    coefs = np.full((n_segments, dim), np.nan, dtype)
    conv = np.zeros((n_segments,), bool)
    ranges = result.stats.get("collected_ranges") or []
    for (start, stop), model in zip(ranges, result.models):
        rows = np.asarray(model.coefficients, dtype).reshape(-1, dim)
        coefs[start:stop] = rows
        diag = model.diagnostics
        if diag is not None:
            conv[start:stop] = np.asarray(diag.converged).reshape(-1)
    return coefs, conv


def fit_long(ts, order: Tuple[int, int, int] = (2, 1, 2),
             auto: bool = False, *,
             seg_len: Optional[int] = None, overlap: int = 0,
             n_ar: Optional[int] = None,
             max_p: int = 5, max_q: int = 5,
             engine=None, chunk_segments: int = DEFAULT_CHUNK_SEGMENTS,
             journal: Optional[str] = None,
             deadline_s: Optional[float] = None,
             chunk_retry=None, degrade: bool = True,
             fused: Optional[bool] = None,
             combine_chunk: int = 256,
             warm: int = 512, origin_chunk: int = 65536,
             **fit_kwargs) -> LongSeriesFit:
    """Fit one ultra-long series by DARIMA split-and-combine.

    ``ts (n,)`` — a single fully-observed series (NaNs raise: impute
    first; the series axis is what this tier refuses to be bound by, not
    data quality).  ``order = (p, d, q)``: ``d`` is applied globally
    before splitting; segments fit ARMA(p, q).  With ``auto=True`` each
    segment instead selects its own (p, q) ≤ (``max_p``, ``max_q``) via
    the fused ``auto_fit_panel`` grid — heterogeneous orders combine
    fine because combination happens in the common AR-truncation space.

    Split geometry: ``seg_len``/``overlap`` feed
    :func:`~spark_timeseries_tpu.stats.segment_plan` (default: power of
    two near ``8·sqrt(n)``).  ``n_ar`` is the AR-truncation length of
    the combined model (default: ``p`` for pure-AR orders — exact — else
    ``max(p + q, 12)``).

    Streaming knobs (rejected under ``auto=True``, which is one fused
    dispatch that never touches ``stream_fit`` — a journal that will
    never commit must fail loudly, not at the post-crash resume):
    ``engine`` (a
    :class:`~spark_timeseries_tpu.engine.FitEngine`; default the process
    engine), ``chunk_segments`` segments per streamed chunk,
    ``journal=path`` for crash-consistent per-chunk commits + validated
    resume (the journal spec content-hashes the segmentation geometry
    via ``job_meta``, so a changed split refuses resume),
    ``deadline_s``/``chunk_retry``/``degrade`` the engine's per-chunk
    watchdog / quarantine-retry / OOM-halving controls.  ``fit_kwargs``
    (``method``, ``max_iter``, ``include_intercept``) pass through to
    the per-segment ``arima.fit``; the *optimizer* multi-start
    ``retry=`` is not routable here (``stream_fit`` reserves the name
    for chunk-level retries — ``chunk_retry`` is this tier's failure
    recovery, and a failed segment already combines at weight zero).

    ``fused`` selects the whole-pipeline-fusion path (docs/design.md
    §6e): segment fit AND WLS combination as ONE donated XLA program
    per segment chunk (``combine.fused_fit_combine``) — the per-segment
    coefficients never cross the host.  Default (``None``): fused
    whenever no durability/streaming knob is in play and ``auto`` is
    off; any such knob (``journal``, ``deadline_s``, ``chunk_retry``,
    ``engine``, ``degrade=False``) keeps the staged ``stream_fit`` →
    ``combine_segments`` path, which remains the bitwise oracle and the
    only journaling path.  ``fused=True`` plus such a knob raises
    :class:`FusedDurabilityError` — loudly, because a journal the fused
    path will never commit must not fail at the post-crash resume.
    ``fused=False`` forces the staged path.  A journal written by the
    staged path resumes fine under the default-fused engine: the
    journal spec never hashes the fusion flag, and passing ``journal=``
    itself selects the staged path.

    Returns a :class:`LongSeriesFit` whose ``model`` is the combined
    AR(``n_ar``) :class:`~spark_timeseries_tpu.models.arima.ARIMAModel`
    (original ``d`` reattached) and whose :meth:`~LongSeriesFit.forecast`
    is exact over the full series.
    """
    host = np.asarray(ts)
    if host.ndim != 1:
        raise ValueError(
            f"fit_long fits ONE ultra-long series, got shape "
            f"{host.shape}; for panels of normal-length series use "
            f"engine.stream_fit / fit_panel")
    if not np.issubdtype(host.dtype, np.floating):
        host = host.astype(np.float32)
    if np.isnan(host).any():
        raise ValueError(
            "fit_long needs a fully-observed series; impute missing "
            "ticks first (Panel.fill / ops.fill_ts) — the segment "
            "combiner and the exact forecast-origin recovery both "
            "assume dense observations")
    p, d, q = (int(v) for v in order)
    if "retry" in fit_kwargs:
        raise ValueError(
            "fit_long does not take retry=: stream_fit reserves the "
            "name for chunk-level quarantine retries (pass chunk_retry=)"
            "; per-segment optimizer restarts are not routable through "
            "the streamed path — a failed segment combines at weight "
            "zero instead")
    warn = bool(fit_kwargs.pop("warn", True))
    include_intercept = bool(fit_kwargs.get("include_intercept", True))
    icpt = 1 if include_intercept else 0

    # fused-path resolution: any durability/streaming knob forces the
    # staged path (it is the only journaling/deadline/retry path);
    # asking for BOTH is a contradiction that must fail loudly now
    forcing = [name for name, on in (
        ("journal", journal is not None),
        ("deadline_s", deadline_s is not None),
        ("chunk_retry", chunk_retry is not None),
        ("engine", engine is not None),
        ("degrade", degrade is not True)) if on]
    if fused is None:
        use_fused = not auto and not forcing
    elif fused:
        if auto:
            raise FusedDurabilityError(
                "fused=True with auto=True: the auto path is already "
                "one fused auto_fit_panel dispatch — drop fused= or "
                "use auto=False")
        if forcing:
            raise FusedDurabilityError(
                f"fused=True cannot honor the durability/streaming "
                f"knobs {forcing}: the fused fit→combine program never "
                f"touches stream_fit, so a journal would never commit "
                f"and a deadline would never arm — drop them or pass "
                f"fused=False for the staged (durable) path")
        use_fused = True
    else:
        use_fused = False

    reg = _metrics.get_registry()
    with _metrics.span("longseries.fit_long"):
        diffed = _split.difference(host, d)
        plan = segment_plan(diffed.size, p if not auto else max_p,
                            q if not auto else max_q,
                            seg_len=seg_len, overlap=overlap)
        panel = _split.segment_panel(diffed, plan)
        K = plan.n_segments

        if n_ar is None:
            if auto:
                n_ar = max(max_p + max_q, DEFAULT_MA_TRUNCATION)
            else:
                n_ar = p if q == 0 else max(p + q, DEFAULT_MA_TRUNCATION)
        n_ar = int(n_ar)

        segment_orders = None
        stream_stats = None
        combined = None
        if auto:
            import jax.numpy as jnp

            from ..models.arima import auto_fit_panel
            bad_kw = set(fit_kwargs) - {"max_iter", "screen_max_iter"}
            if bad_kw:
                raise ValueError(
                    f"auto=True routes segments through auto_fit_panel, "
                    f"which takes only max_iter/screen_max_iter; got "
                    f"{sorted(bad_kw)} (the grid always fits with an "
                    f"intercept and its own optimizer config)")
            # the auto path is one fused dispatch that never touches
            # stream_fit: a streaming knob would be silently dead — in
            # particular a journal that never commits must fail loudly
            # now, not at the post-crash resume that finds nothing
            dead = [name for name, on in (
                ("journal", journal is not None),
                ("deadline_s", deadline_s is not None),
                ("chunk_retry", chunk_retry is not None),
                ("engine", engine is not None),
                ("degrade", degrade is not True),
                ("chunk_segments",
                 chunk_segments != DEFAULT_CHUNK_SEGMENTS)) if on]
            if dead:
                raise ValueError(
                    f"auto=True fits every segment in one fused "
                    f"auto_fit_panel dispatch; the streaming knobs "
                    f"{dead} have no effect there — drop them or use "
                    f"auto=False")
            # one fused dispatch: per-segment (p, q) selection on the
            # already-differenced panel (max_d=0 — d is global here)
            pf = auto_fit_panel(jnp.asarray(panel), max_p=max_p, max_d=0,
                                max_q=max_q, **fit_kwargs)
            cp, cq, c_icpt = max_p, max_q, True
            coefs = np.array(pf.coefficients, panel.dtype)
            conv = np.isfinite(np.asarray(pf.aic))
            # no-admissible-candidate lanes come back with aic=+inf but
            # ZERO coefficients — finite, so the gram weighting would
            # count them at full weight and drag the combination toward
            # zero; NaN them so the combiner's ok-mask drops them like
            # the stream path's failed chunks
            coefs[~conv] = np.nan
            segment_orders = pf.orders
        elif use_fused:
            bad_kw = set(fit_kwargs) - {"method", "max_iter",
                                        "include_intercept", "objective"}
            if bad_kw:
                raise ValueError(
                    f"the fused fit→combine program takes only "
                    f"method/max_iter/include_intercept/objective; got "
                    f"{sorted(bad_kw)} (pass fused=False to route "
                    f"other fit kwargs through the staged path)")
            cp, cq, c_icpt = p, q, include_intercept
            step = max(1, min(int(chunk_segments), K))
            combined = _combine.fused_fit_combine(
                panel, p=p, q=q, include_intercept=include_intercept,
                n_ar=n_ar, overlap=plan.overlap, chunk_segments=step,
                method=str(fit_kwargs.get("method", "css-lm")),
                max_iter=fit_kwargs.get("max_iter"),
                objective=str(fit_kwargs.get("objective", "css")))
            stream_stats = {"fused": True, "n_segments": K,
                            "chunk_segments": step,
                            "n_chunks": -(-K // step)}
        else:
            from ..engine import default_engine
            eng = engine if engine is not None else default_engine()
            cp, cq, c_icpt = p, q, include_intercept
            meta = {"tier": "longseries",
                    "order": [p, d, q],
                    "seg_len": plan.seg_len,
                    "overlap": plan.overlap,
                    "head_drop": plan.head_drop}
            result = eng.stream_fit(
                panel, "arima", chunk_size=int(chunk_segments),
                collect=True, journal=journal, job_meta=meta,
                deadline_s=deadline_s, retry=chunk_retry,
                degrade=degrade, p=p, d=0, q=q,
                job_label=f"longseries:arima({p},{d},{q})", **fit_kwargs)
            stream_stats = dict(result.stats)
            stream_stats["n_chunks"] = result.n_chunks
            stream_stats["chunk_failures"] = len(result.chunk_failures)
            coefs, conv = _collect_segment_coefs(
                result, K, icpt + p + q, panel.dtype)

        if combined is None:
            combined = _combine.combine_segments(
                panel, coefs, conv, p=cp, q=cq,
                include_intercept=bool(c_icpt), n_ar=n_ar,
                overlap=plan.overlap, chunk_segments=int(combine_chunk))

        import jax.numpy as jnp

        from ..models.arima import ARIMAModel
        from ..models.base import FitDiagnostics
        n_w = combined.n_weighted
        diags = FitDiagnostics(
            converged=jnp.asarray(n_w > 0
                                  and 2 * combined.n_converged > n_w),
            n_iter=jnp.asarray(0, jnp.int32),
            fun=jnp.asarray(combined.sigma2, panel.dtype))
        model = ARIMAModel(n_ar, d, 0,
                           jnp.asarray(combined.coefficients),
                           bool(c_icpt), diagnostics=diags)
        reg.inc("longseries.fits")
        reg.inc("longseries.segments", K)
        reg.set_gauge("longseries.last_n_obs", float(host.size))
    _warn(model, warn)
    return LongSeriesFit(model, plan, combined, diffed,
                         _split.tail_ring(host, d),
                         stream_stats=stream_stats,
                         segment_orders=segment_orders,
                         warm=warm, origin_chunk=origin_chunk)


def _warn(model, warn: bool) -> None:
    from ..models.arima import _warn_stationarity_invertibility
    _warn_stationarity_invertibility(model, bool(warn))
