"""Ultra-long series tier: DARIMA split-and-combine (ROADMAP item 2).

A single series with 10⁶–10⁸ observations (telemetry, tick data) cannot
be fitted by any batch path — the CSS MA recursion is sequential in t
and every engine tier scales the *series* axis only.  This subsystem
opens that workload class by changing the axis (PAPERS.md "Distributed
ARIMA Models for Ultra-long Time Series", arXiv 2007.09577):

- :mod:`split` — partition the obs axis into contiguous (optionally
  overlapping) windows and reshape them into an ``(n_segments, window)``
  panel, so segments stream through ``engine.stream_fit`` unchanged —
  bucketed executables, donation, journal/resume, deadlines, and
  OOM-adaptive halving all apply to the obs axis for free;
- :mod:`combine` — the DARIMA combiner: map each segment's ARMA estimate
  into the common truncated-AR(∞) space
  (``models.arima.ar_truncation``), then combine with inverse-covariance
  (design-gram WLS) weights, in-graph per chunk of segments;
- :mod:`api` — :func:`fit_long` plus exact forecasting: the combined
  model converts via ``statespace.to_statespace`` and the forecast-
  origin filter state over the FULL series is recovered through
  ``ops.scan_parallel.affine_recurrence`` in O(log chunk) depth
  (``statespace.kalman.filter_forecast_origin``), so ``forecast(h)`` is
  exact, not segment-local.

See docs/design.md §8.
"""

from . import api, combine, split  # noqa: F401
from .api import LongSeriesFit, fit_long  # noqa: F401
from .combine import CombinedResult, combine_segments  # noqa: F401
from .split import segment_panel, segment_plan, tail_ring  # noqa: F401

__all__ = ["api", "combine", "split", "fit_long", "LongSeriesFit",
           "combine_segments", "CombinedResult", "segment_panel",
           "segment_plan", "tail_ring"]
