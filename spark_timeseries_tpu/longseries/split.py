"""Obs-axis segmentation: one ultra-long series → a segment panel.

The whole trick of the DARIMA tier (PAPERS.md "Distributed ARIMA Models
for Ultra-long Time Series") is a change of axis: a series too long to
fit — the CSS MA recursion is sequential in t, and 10⁶–10⁸ observations
will not sit in one optimizer dispatch — is reshaped so that **time
blocks become the batch axis**.  The resulting ``(n_segments, window)``
panel is exactly the shape every existing engine path eats:
``engine.stream_fit`` chunks it, buckets it, journals it, deadlines it,
and OOM-degrades it with zero new machinery, which is why this module is
host-side numpy and ~nothing else.

Geometry (:func:`spark_timeseries_tpu.stats.segment_plan` chooses it):
windows tile the *tail* of the (already differenced) series — the most
recent data always participates, the ``head_drop`` leading observations
are excluded, mirroring ``arima.fit_long``.  With ``overlap = o > 0``
every window extends ``o`` observations left of its own ``seg_len``
stride, giving each segment fit real left context instead of a zero
burn-in; the combiner then weights each observation **once** by skipping
the first ``max(n_ar, o)`` design rows per window
(``longseries.combine``).
"""

from __future__ import annotations

import numpy as np

from ..stats import SegmentPlan, segment_plan

__all__ = ["segment_panel", "difference", "tail_ring", "SegmentPlan",
           "segment_plan"]


def difference(ts: np.ndarray, d: int) -> np.ndarray:
    """Order-``d`` differencing on host (``np.diff`` — the global
    differencing pass the split runs once, so segments fit a pure ARMA
    with a **common** d instead of per-segment differencing that would
    put segment estimates in incompatible spaces)."""
    ts = np.asarray(ts)
    return np.diff(ts, n=int(d)) if d else ts


def tail_ring(ts: np.ndarray, d: int) -> np.ndarray:
    """The last raw differences ``ring[j] = (Δʲ ts)[-1]`` for
    ``j < d`` — the ``FilterState.ring`` seed that lets the state-space
    forecast integrate back from the differenced filter scale to raw
    observations (``statespace.kalman.forecast_mean``)."""
    ts = np.asarray(ts)
    ring = np.zeros((int(d),), ts.dtype)
    cur = ts
    for j in range(int(d)):
        ring[j] = cur[-1]
        cur = np.diff(cur)
    return ring


def segment_panel(diffed: np.ndarray, plan: SegmentPlan) -> np.ndarray:
    """Reshape a 1-D (differenced) series into the ``(n_segments,
    window)`` panel its :class:`~spark_timeseries_tpu.stats.SegmentPlan`
    describes.

    Window ``k`` holds ``diffed[head_drop + k·seg_len : head_drop +
    k·seg_len + window]``; consecutive windows share their trailing/
    leading ``overlap`` observations.  Returns a contiguous host array
    (the copy is ``n_used + (n_segments-1)·overlap`` floats — a few MB
    at 10⁶ obs — and what ``stream_fit`` slices chunks from)."""
    diffed = np.asarray(diffed)
    if diffed.ndim != 1:
        raise ValueError(
            f"segment_panel splits one series; got shape {diffed.shape} "
            f"(fit ultra-long panels one series at a time)")
    if diffed.size < plan.head_drop + plan.n_used:
        raise ValueError(
            f"plan covers {plan.head_drop + plan.n_used} obs but the "
            f"series has {diffed.size}")
    starts = plan.head_drop + np.arange(plan.n_segments) * plan.seg_len
    idx = starts[:, None] + np.arange(plan.window)[None, :]
    return np.ascontiguousarray(diffed[idx])
