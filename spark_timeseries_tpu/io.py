"""Persistence and parsers: CSV / Parquet panels with index sidecars.

Capability parity with the reference's persistence tier
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/TimeSeriesRDD.scala:498-551,747-780``)
and ``YahooParser``
(ref ``/root/reference/src/main/scala/com/cloudera/sparkts/parsers/YahooParser.scala:24-49``).

File contracts match the reference so datasets interchange:

- **CSV**: a directory holding ``data.csv`` with one ``key,v0,v1,...`` line
  per series (the reference's ``saveAsCsv`` text-file rows) and a
  ``timeIndex`` sidecar holding ``DateTimeIndex`` string form
  (ref ``TimeSeriesRDD.scala:498-509``; sidecar name ``:504``).
- **Parquet**: a long-format observations table (timestamp, key, value —
  the reference's ``toObservationsDataFrame`` layout,
  ``TimeSeriesRDD.scala:419-443``) at ``<path>``, with the index string in a
  ``<path>.idx`` sidecar (ref ``TimeSeriesRDD.scala:526-551``).

There is no Kryo tier: sharded ``jax.Array``s are already bytes
(SURVEY.md §5 "distributed communication backend").
"""

from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from .panel import Panel
from .time import index as dtindex
from .utils import metrics as _metrics

CSV_DATA_FILE = "data.csv"
CSV_INDEX_FILE = "timeIndex"   # same sidecar name as the reference


# ---------------------------------------------------------------------------
# CSV (ref TimeSeriesRDD.scala:498-509 save, :750-764 load)
# ---------------------------------------------------------------------------

def _escape_key(key: str) -> str:
    """RFC-4180-style quoting for keys containing delimiters.  Plain keys
    are written bare, preserving the reference's file contract
    (``TimeSeriesRDD.scala:498-509`` writes keys raw and silently corrupts
    comma keys on reload — "match the contract" doesn't extend to
    preserving a data-loss bug).  Newlines are rejected outright: the file
    format is line-per-series, so a quoted key spanning physical lines
    could never be read back."""
    if "\n" in key or "\r" in key:
        raise ValueError(
            f"series key {key!r} contains a newline, which the "
            "line-per-series CSV contract cannot represent")
    if "," in key or '"' in key:
        return '"' + key.replace('"', '""') + '"'
    return key


def _split_key(line: str) -> tuple:
    """Split ``key,rest`` honoring the quoting from :func:`_escape_key`.

    Lines whose leading quote does not parse as well-formed quoting (e.g. a
    reference-written file whose raw key just happens to start with ``\"``)
    fall back to the bare ``key,rest`` split the reference's loader uses."""
    if not line.startswith('"'):
        key, _, rest = line.partition(",")
        return key, rest
    i = 1
    out = []
    while i < len(line):
        if line[i] == '"':
            if i + 1 < len(line) and line[i + 1] == '"':
                out.append('"')
                i += 2
                continue
            if i + 1 == len(line) or line[i + 1] == ",":
                return "".join(out), line[i + 2:]
            break                      # quote not closing the field: bare key
        out.append(line[i])
        i += 1
    key, _, rest = line.partition(",")
    return key, rest


@_metrics.instrumented("io.save_csv")
def save_csv(panel: Panel, path: str) -> None:
    """Write ``path/data.csv`` (one ``key,v0,v1,...`` row per series) and the
    ``path/timeIndex`` sidecar.

    The numeric block is formatted by the native codec when available
    (``native.fastcsv``: ``std::to_chars`` shortest round-trip decimals,
    the whole file assembled in one C pass — the same C-speed tier the
    reference gets from Scala's ``Double.toString``), falling back to
    ``np.savetxt`` (``%.17g`` also round-trips float64 exactly, including
    nan/inf) with the pre-escaped key column prepended per line.  Both
    paths parse back bit-identically through either loader."""
    import io as _io

    from .native import fastcsv

    os.makedirs(path, exist_ok=True)
    values = np.ascontiguousarray(np.atleast_2d(np.asarray(panel.values)),
                                  dtype=np.float64)
    esc = [_escape_key(str(key)) for key in panel.keys]
    lib = fastcsv()
    if lib is not None and values.shape[0] == len(esc):
        import ctypes
        keys_blob = "\n".join(esc).encode()
        rows, cols = values.shape
        out = ctypes.create_string_buffer(
            len(keys_blob) + rows * (cols * 33 + 2) + 1)
        n = lib.sts_format_csv(keys_blob, len(keys_blob),
                               values.ctypes.data_as(ctypes.c_void_p),
                               rows, cols, out)
        if n >= 0:
            with open(os.path.join(path, CSV_DATA_FILE), "wb") as f:
                f.write(out.raw[:n])
            with open(os.path.join(path, CSV_INDEX_FILE), "w") as f:
                f.write(panel.index.to_string())
            return
    buf = _io.StringIO()
    np.savetxt(buf, values, delimiter=",", fmt="%.17g")
    with open(os.path.join(path, CSV_DATA_FILE), "w") as f:
        f.writelines(
            key + "," + row + "\n"
            for key, row in zip(esc, buf.getvalue().splitlines()))
    with open(os.path.join(path, CSV_INDEX_FILE), "w") as f:
        f.write(panel.index.to_string())


def _unquote_key(token: str) -> str:
    """Decode one raw key token from the file (the span the native
    scanner reports): quoted tokens un-escape through :func:`_split_key`'s
    exact logic (including its malformed-quoting fallback)."""
    if not token.startswith('"'):
        return token
    return _split_key(token + ",")[0]


@_metrics.instrumented("io.load_csv")
def load_csv(path: str) -> Panel:
    """Inverse of :func:`save_csv` (ref ``timeSeriesRDDFromCsv``).

    The native codec parses the whole file in one C pass when available
    (``std::from_chars`` is correctly rounded, so shortest-repr and
    ``%.17g`` decimals both round-trip bit-exactly); the fallback splits
    keys per line (they may be RFC-4180 quoted) and parses the numeric
    payload in one pandas ``round_trip`` pass.  Corruption fails loudly
    on both paths — a truncated row or an empty field raises instead of
    NaN-filling (real NaNs travel as the literal token ``nan``).
    """
    import io as _io

    with open(os.path.join(path, CSV_INDEX_FILE)) as f:
        index = dtindex.from_string(f.read().strip())

    from .native import fastcsv
    lib = fastcsv()
    if lib is not None:
        import ctypes
        with open(os.path.join(path, CSV_DATA_FILE), "rb") as f:
            raw = f.read()
        if not raw.strip():
            return Panel(index, jnp.zeros((0, len(index))), [])
        # width comes from the first NON-blank line, mirroring the C
        # parser's and the Python fallback's blank-line skip — a leading
        # blank/CR-only line (hand-edited or concatenated files) must not
        # make the codecs disagree on the same file (ADVICE.md round 5)
        first = next(line for line in
                     (b.decode().rstrip("\r") for b in raw.split(b"\n"))
                     if line)
        _, first_rest = _split_key(first)
        width = first_rest.count(",") + 1
        rows_cap = raw.count(b"\n") + 1
        values = np.empty((rows_cap, width), np.float64)
        spans = np.empty((rows_cap, 2), np.int64)
        err_row = ctypes.c_longlong(-1)
        n = lib.sts_parse_csv(raw, len(raw), rows_cap, width,
                              values.ctypes.data_as(ctypes.c_void_p),
                              spans.ctypes.data_as(ctypes.c_void_p),
                              ctypes.byref(err_row))
        if n < 0:
            bad = int(err_row.value)
            what = ("has a malformed or empty numeric field" if n == -1
                    else f"does not have {width} values" if n == -2
                    else "overflowed the parser's row estimate")
            raise ValueError(
                f"corrupt data.csv: series row {bad} {what}")
        # spans are BYTE offsets — slice the bytes, then decode, so
        # non-ASCII keys stay correct
        keys = [_unquote_key(raw[a:b].decode()) for a, b in spans[:n]]
        _metrics.inc("io.csv_series_loaded", int(n))
        _metrics.inc("io.csv_bytes_read", len(raw))
        return Panel(index, jnp.asarray(values[:n]), keys)

    import pandas as pd
    keys, rests = [], []
    width = None
    with open(os.path.join(path, CSV_DATA_FILE)) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            key, rest = _split_key(line)
            # corruption must fail loudly, not NaN-fill: pandas would
            # silently pad a truncated row and read an empty field as NaN
            # (real NaNs are written as the literal token "nan")
            w = rest.count(",") + 1
            if width is None:
                width = w
            elif w != width:
                raise ValueError(
                    f"corrupt data.csv: series {key!r} has {w} values, "
                    f"first series has {width}")
            if rest.startswith(",") or rest.endswith(",") or ",," in rest:
                raise ValueError(
                    f"corrupt data.csv: series {key!r} has an empty field")
            keys.append(key)
            rests.append(rest)
    if not keys:
        return Panel(index, jnp.zeros((0, len(index))), keys)
    try:
        data = pd.read_csv(_io.StringIO("\n".join(rests)), header=None,
                           dtype=np.float64,
                           float_precision="round_trip").to_numpy()
    except (ValueError, TypeError):
        # tokens beyond double range: pandas round_trip maps "-1e400" to
        # -inf and "1e-400" to 0, but leaves POSITIVE overflow ("1e400")
        # as a string in an object column, which the pinned-dtype parse
        # rejects.  Re-parse unpinned and let numpy's str->f64 cast
        # finish the job — overflow to +/-inf, underflow to (+/-)0 —
        # matching java.lang.Double.parseDouble in the reference and the
        # native codec's strtod fallback (ADVICE r5).  Genuinely
        # malformed tokens still raise here and fail loudly.
        try:
            data = np.asarray(
                pd.read_csv(_io.StringIO("\n".join(rests)), header=None,
                            float_precision="round_trip").to_numpy(),
                dtype=np.float64)
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"corrupt data.csv: a numeric field failed to parse ({e})"
            ) from e
    _metrics.inc("io.csv_series_loaded", len(keys))
    _metrics.inc("io.csv_bytes_read",
                 os.path.getsize(os.path.join(path, CSV_DATA_FILE)))
    return Panel(index, jnp.asarray(data), keys)


# ---------------------------------------------------------------------------
# Parquet (ref TimeSeriesRDD.scala:526-551 save, :769-780 load)
# ---------------------------------------------------------------------------

@_metrics.instrumented("io.save_parquet")
def save_parquet(panel: Panel, path: str,
                 ts_col: str = "timestamp", key_col: str = "key",
                 value_col: str = "value") -> None:
    """Write the observations DataFrame to parquet plus the ``<path>.idx``
    index sidecar."""
    df = panel.to_observations_dataframe(ts_col, key_col, value_col)
    df.to_parquet(path, index=False)
    with open(path + ".idx", "w") as f:
        f.write(panel.index.to_string())


@_metrics.instrumented("io.load_parquet")
def load_parquet(path: str, ts_col: str = "timestamp", key_col: str = "key",
                 value_col: str = "value") -> Panel:
    """Inverse of :func:`save_parquet`
    (ref ``timeSeriesRDDFromParquet``)."""
    import pandas as pd
    with open(path + ".idx") as f:
        index = dtindex.from_string(f.read().strip())
    df = pd.read_parquet(path)
    return Panel.from_observations(df, index, ts_col, key_col, value_col)


# ---------------------------------------------------------------------------
# Yahoo finance CSV (ref parsers/YahooParser.scala:24-49)
# ---------------------------------------------------------------------------

def yahoo_string_to_panel(text: str, key_prefix: str = "",
                          zone: Optional[str] = None) -> Panel:
    """Parse Yahoo-finance CSV text (``Date,Open,High,...`` header, rows
    newest-first) into a panel keyed ``<prefix><column>``
    (ref ``YahooParser.scala:25-38``: labels from the header tail, rows
    reversed into chronological order, dates at start of day)."""
    import pandas as pd
    lines = [ln for ln in text.strip().split("\n") if ln]
    labels = [key_prefix + c for c in lines[0].split(",")[1:]]
    dates, rows = [], []
    for line in lines[1:]:
        tokens = line.split(",")
        dates.append(tokens[0])
        rows.append([float(t) for t in tokens[1:]])
    order = np.argsort(np.asarray(dates))        # chronological
    nanos = pd.DatetimeIndex(np.asarray(dates)[order]).as_unit("ns") \
        .asi8.astype(np.int64)
    data = np.asarray(rows, dtype=np.float64)[order].T   # (n_cols, n_obs)
    index = dtindex.irregular(nanos, zone)
    return Panel(index, jnp.asarray(data), labels)


@_metrics.instrumented("io.yahoo_file")
def yahoo_file_to_panel(path: str, key_prefix: Optional[str] = None,
                        zone: Optional[str] = None) -> Panel:
    """Parse one Yahoo CSV file; the default key prefix is the file name
    (ref ``YahooParser.scala:40-48``)."""
    if key_prefix is None:
        key_prefix = os.path.basename(path)
    with open(path) as f:
        return yahoo_string_to_panel(f.read(), key_prefix, zone)


@_metrics.instrumented("io.yahoo_files")
def yahoo_files_to_panel(path: str, zone: Optional[str] = None) -> Panel:
    """Load a directory of Yahoo CSV files into one panel — the counterpart
    of the reference's whole-directory ``yahooFiles``
    (ref ``YahooParser.scala:40-48``, which keys each file's series by its
    file name via ``wholeTextFiles``).

    The reference yields an RDD of per-file series each on its own index;
    one panel needs a shared time axis, so the per-file (irregular) indices
    are unioned and every file's series are rebased onto the union with NaN
    at instants the file doesn't cover.
    """
    from .time.union import union as index_union

    names = sorted(n for n in os.listdir(path)
                   if n.lower().endswith(".csv"))
    if not names:
        raise ValueError(f"no .csv files under {path!r}")
    panels = [yahoo_file_to_panel(os.path.join(path, n), zone=zone)
              for n in names]
    if len(panels) == 1:
        return panels[0]
    target = index_union([p.index for p in panels], zone)
    rebased = [p.with_index(target) for p in panels]
    return Panel(target,
                 jnp.concatenate([p.values for p in rebased]),
                 [k for p in rebased for k in p.keys])
