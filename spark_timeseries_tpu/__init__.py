"""spark_timeseries_tpu: a TPU-native time-series framework.

A from-scratch JAX/XLA re-design of the capabilities of Cloudera's
spark-timeseries (reference at /root/reference): date-time indices, panels of
keyed univariate series, vectorized series transforms, batched classical model
fitting (AR/ARX/ARIMA/ARIMAX/EWMA/GARCH/Holt-Winters/RegressionARIMA), and
batched statistical tests — with the panel stored as a sharded
(n_series, n_obs) array on a `jax.sharding.Mesh` and all per-series scalar
loops replaced by vmapped, XLA-compiled kernels.
"""

__version__ = "0.1.0"

import logging as _logging

# Library-logging hygiene: the package logs (e.g. observability.fit_report)
# through logging.getLogger("spark_timeseries_tpu") but never configures
# the root logger or prints by default; applications opt in via
# utils.observability.configure_logging(level).
_logging.getLogger(__name__).addHandler(_logging.NullHandler())

from . import backtest, engine, io, longseries, models, ops  # noqa: F401,E402
from . import parallel, stats, statespace, time, utils  # noqa: F401,E402
from .backtest import BacktestReport, backtest_panel  # noqa: F401,E402
from .panel import Panel, lagged_pair_key, lagged_string_key  # noqa: F401

__all__ = ["backtest", "engine", "io", "longseries", "models", "ops",
           "parallel", "stats", "statespace", "time", "utils", "Panel",
           "backtest_panel", "BacktestReport",
           "lagged_pair_key", "lagged_string_key", "__version__"]
