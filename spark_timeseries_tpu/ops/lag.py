"""Lag-matrix construction — the design-matrix builder for every OLS-based fit.

Capability parity with the reference's ``Lag.scala``
(``/root/reference/src/main/scala/com/cloudera/sparkts/Lag.scala:20-130``), but
tensorized: operates on ``(..., n)`` batches and returns ``(..., rows, cols)``
stacks, so one XLA gather builds the design matrices for an entire panel at
once instead of per-series scalar loops.
"""

from __future__ import annotations

import jax.numpy as jnp


def lag_matrix(x: jnp.ndarray, max_lag: int,
               include_original: bool = False) -> jnp.ndarray:
    """Trimmed lag matrix (ref ``Lag.scala:25-48``).

    For input ``(..., n)`` returns ``(..., n - max_lag, cols)`` where
    ``cols = max_lag (+1 if include_original)``.  Row ``r`` holds
    ``[x[r+max_lag] (optional), x[r+max_lag-1], ..., x[r]]`` — column ``c``
    is the series lagged ``c + (0 if include_original else 1)`` steps.
    """
    n = x.shape[-1]
    if max_lag >= n:
        raise ValueError(f"max_lag {max_lag} must be < series length {n}")
    initial = 0 if include_original else 1
    cols = [x[..., max_lag - lag:n - lag] for lag in range(initial, max_lag + 1)]
    return jnp.stack(cols, axis=-1)


def lag_stack(x: jnp.ndarray, max_lag: int,
              include_original: bool = False) -> jnp.ndarray:
    """``lag_matrix`` transposed: ``(..., cols, n - max_lag)`` with the lag
    index on the *second-minor* axis.

    Same contents as ``lag_matrix(x, max_lag).swapaxes(-1, -2)`` but built in
    this orientation on purpose: TPU tiling pads the two minor axes to
    (8, 128), so a ``(..., rows, cols)`` design with small ``cols`` (every
    AR/MA order in practice) inflates ~``128/cols``× in HBM, while this
    layout pads only ``8/cols``× — the difference between fitting a
    100k-series chunk and OOMing on it.  Use with :func:`ols_gram`.
    """
    n = x.shape[-1]
    if max_lag >= n:
        raise ValueError(f"max_lag {max_lag} must be < series length {n}")
    initial = 0 if include_original else 1
    rows = [x[..., max_lag - lag:n - lag] for lag in range(initial, max_lag + 1)]
    return jnp.stack(rows, axis=-2)


def lag_matvec(x: jnp.ndarray, coef: jnp.ndarray, max_lag: int) -> jnp.ndarray:
    """``lag_matrix(x, max_lag) @ coef`` without materializing the matrix —
    a sum of ``max_lag`` shifted slices, so the largest intermediate is one
    ``(..., n - max_lag)`` array (the lag matrix itself pads ~128/cols× on
    TPU; see :func:`lag_stack`).

    ``x (..., n)``, ``coef (..., max_lag)`` in increasing lag order →
    ``(..., n - max_lag)``.
    """
    n = x.shape[-1]
    out = None
    for c in range(max_lag):
        term = coef[..., c:c + 1] * x[..., max_lag - c - 1:n - c - 1]
        out = term if out is None else out + term
    if out is None:
        return jnp.zeros((*x.shape[:-1], n), x.dtype)[..., :n - max_lag]
    return out


def lag_matrix_multi(x: jnp.ndarray, max_lag: int,
                     include_original: bool = False) -> jnp.ndarray:
    """Lag each column of a multi-column input and concatenate
    (ref ``Lag.scala:107-129``).

    For ``(..., n, k)`` input returns ``(..., n - max_lag, k * cols)`` in the
    reference's ordering ``[a_-1 a_-2 b_-1 b_-2 ...]``.
    """
    per_col = lag_matrix(jnp.moveaxis(x, -1, -2), max_lag, include_original)
    # per_col: (..., k, rows, cols) -> (..., rows, k, cols) -> flatten last two
    per_col = jnp.moveaxis(per_col, -3, -2)
    return per_col.reshape(*per_col.shape[:-2], -1)
