"""Lag-matrix construction — the design-matrix builder for every OLS-based fit.

Capability parity with the reference's ``Lag.scala``
(``/root/reference/src/main/scala/com/cloudera/sparkts/Lag.scala:20-130``), but
tensorized: operates on ``(..., n)`` batches and returns ``(..., rows, cols)``
stacks, so one XLA gather builds the design matrices for an entire panel at
once instead of per-series scalar loops.
"""

from __future__ import annotations

import jax.numpy as jnp


def lag_matrix(x: jnp.ndarray, max_lag: int,
               include_original: bool = False) -> jnp.ndarray:
    """Trimmed lag matrix (ref ``Lag.scala:25-48``).

    For input ``(..., n)`` returns ``(..., n - max_lag, cols)`` where
    ``cols = max_lag (+1 if include_original)``.  Row ``r`` holds
    ``[x[r+max_lag] (optional), x[r+max_lag-1], ..., x[r]]`` — column ``c``
    is the series lagged ``c + (0 if include_original else 1)`` steps.
    """
    n = x.shape[-1]
    if max_lag >= n:
        raise ValueError(f"max_lag {max_lag} must be < series length {n}")
    initial = 0 if include_original else 1
    cols = [x[..., max_lag - lag:n - lag] for lag in range(initial, max_lag + 1)]
    return jnp.stack(cols, axis=-1)


def lag_matrix_multi(x: jnp.ndarray, max_lag: int,
                     include_original: bool = False) -> jnp.ndarray:
    """Lag each column of a multi-column input and concatenate
    (ref ``Lag.scala:107-129``).

    For ``(..., n, k)`` input returns ``(..., n - max_lag, k * cols)`` in the
    reference's ordering ``[a_-1 a_-2 b_-1 b_-2 ...]``.
    """
    per_col = lag_matrix(jnp.moveaxis(x, -1, -2), max_lag, include_original)
    # per_col: (..., k, rows, cols) -> (..., rows, k, cols) -> flatten last two
    per_col = jnp.moveaxis(per_col, -3, -2)
    return per_col.reshape(*per_col.shape[:-2], -1)
