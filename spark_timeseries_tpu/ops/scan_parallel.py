"""Sequence-parallel linear recurrences via associative scans.

The reference's capability envelope keeps every series on one machine and
walks it with O(n) scalar loops (``src/site/markdown/index.md:35-40``); its
sequential recurrences (EWMA smoothing, AR filters, GARCH variance) are the
reason.  Here those recurrences are first-order *affine* maps

    y_t = a_t * y_{t-1} + b_t

whose composition is associative, so ``jax.lax.associative_scan`` evaluates
them in O(log n) depth — and, when the time axis is sharded over a mesh
(``parallel.make_mesh(n, m)`` with ``m > 1``), XLA splits the scan across
the time shards with collectives riding ICI.  This is the framework's
sequence-parallelism story: series longer than one chip's HBM shard the
time axis and still filter/smooth in logarithmic depth — the classical-TS
analogue of ring-attention-style context parallelism.

Used by the EWMA and GARCH paths for long series; the general helper is
public for user-defined filters.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def linear_recurrence(a: jnp.ndarray, b: jnp.ndarray,
                      axis: int = -1) -> jnp.ndarray:
    """Solve ``y_t = a_t * y_{t-1} + b_t`` with ``y_{-1} = 0`` for all t,
    in O(log n) depth.

    ``a`` and ``b`` broadcast against each other; the recurrence runs along
    ``axis``.  The affine maps ``(a_t, b_t)`` compose as
    ``(a2, b2) ∘ (a1, b1) = (a1*a2, a2*b1 + b2)``, which is associative.
    """
    a, b = jnp.broadcast_arrays(a, b)

    def combine(left, right):
        a1, b1 = left
        a2, b2 = right
        return a1 * a2, a2 * b1 + b2

    _, y = lax.associative_scan(combine, (a, b), axis=axis)
    return y


def affine_recurrence(A: jnp.ndarray, b: jnp.ndarray,
                      x0: jnp.ndarray = None) -> jnp.ndarray:
    """Solve the *vector* affine recurrence ``x_t = A_t @ x_{t-1} + b_t``
    for t = 1..n in O(log n) depth — the matrix generalization of
    :func:`linear_recurrence`.

    ``A (n, ..., m, m)``, ``b (n, ..., m)`` with the time axis leading;
    ``x0 (..., m)`` seeds ``x_0`` (zeros when None).  The affine maps
    compose as ``(A2, b2) ∘ (A1, b1) = (A2 A1, A2 b1 + b2)`` — associative,
    so ``lax.associative_scan`` evaluates every prefix composition in
    logarithmic depth.  Returns ``x (n, ..., m)`` = the states x_1..x_n.

    This is the parallel-prefix engine behind the state-space tier's
    fixed-gain Kalman filter (``statespace.kalman.filter_panel_parallel``):
    with a pinned gain the filtered-state recursion is exactly this affine
    map, so a whole series filters in O(log n) depth instead of an O(n)
    scan — the same trade the EWMA/GARCH paths already make.
    """
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    if x0 is not None:
        # fold the seed into the first step: x_1 = A_1 x_0 + b_1
        b = b.at[0].add(jnp.einsum("...ij,...j->...i", A[0], x0))

    def combine(left, right):
        a1, b1 = left
        a2, b2 = right
        return (jnp.einsum("...ij,...jk->...ik", a2, a1),
                jnp.einsum("...ij,...j->...i", a2, b1) + b2)

    _, x = lax.associative_scan(combine, (A, b), axis=0)
    return x


def ewma_smooth(x: jnp.ndarray, alpha: jnp.ndarray,
                axis: int = -1) -> jnp.ndarray:
    """EWMA smoothing ``S_t = alpha*x_t + (1-alpha)*S_{t-1}``, ``S_0 = x_0``
    (the recurrence of ``models.ewma.EWMAModel.add_time_dependent_effects``),
    evaluated by associative scan — identical output, O(log n) depth,
    time-shardable."""
    x = jnp.asarray(x)
    alpha = jnp.asarray(alpha)
    if alpha.ndim and axis in (-1, x.ndim - 1):
        alpha = alpha[..., None]
    a = jnp.broadcast_to(1.0 - alpha, x.shape)
    b = alpha * x
    # S_0 = x_0 exactly: make the first step the identity-carrying seed
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(0, 1)
    a = a.at[tuple(idx)].set(0.0)
    b = b.at[tuple(idx)].set(x[tuple(idx)])
    return linear_recurrence(a, b, axis=axis)


def ar1_filter(x: jnp.ndarray, c, phi, axis: int = -1) -> jnp.ndarray:
    """AR(1) filtering ``y_t = c + phi*y_{t-1} + x_t`` with ``y_{-1} = 0``
    — the ``ARModel.add_time_dependent_effects`` recurrence for p=1 — by
    associative scan."""
    x = jnp.asarray(x)
    c = jnp.asarray(c)
    phi = jnp.asarray(phi)
    if axis in (-1, x.ndim - 1):
        if phi.ndim:
            phi = phi[..., None]
        if c.ndim:
            c = c[..., None]
    a = jnp.broadcast_to(phi, x.shape)
    b = x + c
    return linear_recurrence(a, b, axis=axis)


def garch_variance(errors: jnp.ndarray, omega, alpha, beta,
                   axis: int = -1, h0=None) -> jnp.ndarray:
    """Conditional-variance path ``h_t = omega + alpha*e²_{t-1} + beta*h_{t-1}``
    with ``h_0 = omega / (1 - alpha - beta)`` (the GARCH recurrence,
    ``models.garch.GARCHModel``), by associative scan.  Returns ``h`` aligned
    with ``errors`` (``h[0]`` is the seed).  Pass ``h0`` to override the
    stationary seed — e.g. the sample variance for an IGARCH lane
    (α+β = 1), where the stationary value does not exist."""
    e = jnp.asarray(errors)
    omega = jnp.asarray(omega)
    alpha = jnp.asarray(alpha)
    beta = jnp.asarray(beta)
    if axis in (-1, e.ndim - 1):
        if omega.ndim:
            omega = omega[..., None]
        if alpha.ndim:
            alpha = alpha[..., None]
        if beta.ndim:
            beta = beta[..., None]
    e2_prev = jnp.concatenate(
        [jnp.zeros_like(jnp.take(e, jnp.asarray([0]), axis=axis)),
         jnp.take(e, jnp.arange(e.shape[axis] - 1), axis=axis) ** 2],
        axis=axis)
    a = jnp.broadcast_to(beta, e.shape)
    b = omega + alpha * e2_prev
    if h0 is None:
        h0 = omega / (1.0 - alpha - beta)
    else:
        h0 = jnp.asarray(h0, e.dtype)
        if h0.ndim and axis in (-1, e.ndim - 1):
            h0 = h0[..., None]
    idx = [slice(None)] * e.ndim
    idx[axis] = slice(0, 1)
    a = a.at[tuple(idx)].set(0.0)
    b = b.at[tuple(idx)].set(jnp.broadcast_to(h0, b[tuple(idx)].shape))
    return linear_recurrence(a, b, axis=axis)
