"""Classical seasonal decomposition, batched — beyond-reference capability.

The reference has no decomposition op (its seasonal tier is only the
Holt-Winters smoother, ``HoltWinters.scala``); R users routinely pair
``decompose()`` with the models this framework ports, so the panel-scale
equivalent lives here.  Semantics follow R ``stats::decompose``: a centered
moving-average trend (half-weight endpoints for even periods), seasonal
figures as phase means of the detrended series re-centered to sum to zero
(additive) or rescaled to mean one (multiplicative), and NaN trend/remainder
edges where the centered window does not fit.

TPU-native design: the centered filter reuses :func:`roll_mean`'s shifted-
add accumulation (the even-period half-weight-ends filter is exactly
``roll_mean(roll_mean(x, period), 2)``), phase means are a one-hot
contraction over the phase index, everything is batched over leading dims
and jit-safe (static shapes only).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from .univariate import roll_mean


class Decomposition(NamedTuple):
    """``trend``/``seasonal``/``remainder`` each shaped like the input;
    ``figure (..., period)`` is the per-phase seasonal figure."""
    trend: jnp.ndarray
    seasonal: jnp.ndarray
    remainder: jnp.ndarray
    figure: jnp.ndarray


def _centered_ma(x: jnp.ndarray, period: int) -> jnp.ndarray:
    """Centered moving average with NaN edges, matching R ``filter(...,
    sides=2)``: odd periods use ``period`` equal taps, even periods the
    ``period + 1``-tap half-weight-ends filter, which factors exactly as a
    period-mean followed by a 2-mean (one shifted-add accumulator each,
    no window stack)."""
    if period % 2:
        core = roll_mean(x, period)
    else:
        core = roll_mean(roll_mean(x, period), 2)
    pad = jnp.full((*x.shape[:-1], period // 2), jnp.nan, x.dtype)
    return jnp.concatenate([pad, core, pad], axis=-1)


def decompose(values: jnp.ndarray, period: int,
              model: str = "additive") -> Decomposition:
    """Decompose ``values (..., n)`` into trend + seasonal + remainder
    (additive) or trend * seasonal * remainder (multiplicative), batched
    over every leading dim.

    Requires ``n >= 2 * period`` (same constraint as R's ``decompose``).
    """
    if model not in ("additive", "multiplicative"):
        raise ValueError("model must be 'additive' or 'multiplicative'")
    values = jnp.asarray(values)
    # integer input would truncate the filter taps and cast the NaN edge
    # pad into garbage; promote like the rest of the ops tier
    values = values.astype(jnp.result_type(values.dtype, jnp.float32))
    n = values.shape[-1]
    if n < 2 * period:
        raise ValueError(
            f"series of length {n} has fewer than two periods ({period})")

    trend = _centered_ma(values, period)
    detrended = values - trend if model == "additive" else values / trend

    # per-phase means over the valid (non-NaN-trend) window
    phase = jnp.arange(n) % period                       # (n,)
    valid = jnp.isfinite(detrended)
    contrib = jnp.where(valid, detrended, 0.0)
    one_hot = (phase[:, None] == jnp.arange(period)[None, :]) \
        .astype(values.dtype)                            # (n, period)
    sums = contrib @ one_hot                             # (..., period)
    counts = valid.astype(values.dtype) @ one_hot
    # a phase with no valid observations is NaN (as R's na.rm mean of an
    # empty set), and the re-centering ignores it rather than absorbing a
    # fabricated zero
    figure = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0),
                       jnp.nan)
    if model == "additive":
        figure = figure - jnp.nanmean(figure, axis=-1, keepdims=True)
    else:
        figure = figure / jnp.nanmean(figure, axis=-1, keepdims=True)

    seasonal = jnp.take(figure, phase, axis=-1)
    if model == "additive":
        remainder = values - trend - seasonal
    else:
        remainder = values / (trend * seasonal)
    return Decomposition(trend, seasonal, remainder, figure)
