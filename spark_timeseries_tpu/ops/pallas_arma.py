"""Pallas TPU kernel for the fused ARMA normal equations — the hot op.

Every Levenberg-Marquardt iteration of the headline ARIMA fit needs, per
lane: one-step CSS residuals, the Gauss-Newton accumulators ``JᵀJ``/``Jᵀr``
and the cost (ref hot loop being replaced:
``/root/reference/src/main/scala/com/cloudera/sparkts/models/ARIMA.scala:581-618``
+ the analytic derivative recurrence ``:465-534``).  The XLA path
(``arima._arma_normal_eqs``) carries those accumulators through a
``lax.scan`` whose carry (~37 floats/lane at ARIMA(2,1,2)) streams through
HBM every unrolled step group; this kernel instead keeps the ENTIRE carry
in VMEM for the whole time axis:

- lanes are blocked ``(ROWS, 128)`` (sublane x lane tiles; series on the
  128-lane minor axis), the full time axis of a block resident in VMEM —
  at the bench shape (131072 x 128 f32) a 64-row block is 4 MB of series
  data + ~1.2 MB of carry, far under the ~16 MB VMEM budget;
- time advances in a ``fori_loop`` over static-size chunks whose inner
  steps are Python-unrolled, so every ``y`` read inside a chunk is a
  STATIC index into a VMEM values array (the round-1 kernel's per-step
  dynamic sublane reads were its loss mode, ``docs/experiments/
  arma_pallas.py``);
- the 5x5 ``JᵀJ`` packs as its 15-element upper triangle, accumulated —
  like ``Jᵀr`` and the cost — as plain VPU registers/VMEM values.

HBM traffic per pass drops to one read of the series block plus 21 output
tiles per block: the XLA fused-carry pass is latency-bound on its carry
round trips, this one is VPU-compute-bound.

Numerics: float32 (the production TPU dtype).  The kernel is pinned to
``arima._arma_normal_eqs`` (itself pinned to autodiff at f64) by
``tests/test_pallas_arma.py`` in interpreter mode on CPU and compiled on
TPU.  Use :func:`use_pallas` to gate call sites by backend.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .linalg import spd_solve

LANES = 128
MAX_ROWS = 64          # sublane rows per block: 64x128 lanes = 8 VPU tiles
TIME_CHUNK = 16        # static-unrolled steps per fori_loop iteration


def use_pallas() -> bool:
    return jax.default_backend() == "tpu"


def _vmem_budget() -> float:
    return float(os.environ.get("STS_PALLAS_VMEM_MB", "12")) * 2 ** 20


def _rows_fit(rows: int, n_obs: int) -> bool:
    """Does an ``(n_obs, rows, 128)`` f32 block fit the VMEM budget?
    The y block dominates and Pallas double-buffers inputs across grid
    steps (the ``2 *``); params/out/live-carry add a further ~80
    ``(rows, 128)`` values.  The budget defaults to 12 MB (comfortably
    under a v5e core's ~16 MB VMEM at the bench shape, which needs
    ~11 MB); ``STS_PALLAS_VMEM_MB`` overrides it for parts with more or
    less VMEM."""
    return (2 * n_obs + 80) * rows * LANES * 4 <= _vmem_budget()


def vmem_fits(n_series: int, n_obs: int) -> bool:
    """Can SOME admissible lane-block row count hold this time axis in
    VMEM?  :func:`_block_rows` shrinks blocks down to 8 sublane rows
    (still full 8x128 VPU tiles) for long time axes before the router
    gives up, so the bound is rows=8's: ~1,500 obs at the default
    budget, any series count.  Beyond it the default route keeps the
    XLA fused-carry path, which streams the time axis and has no such
    limit (advisor r4: a >=1024-lane panel with n_obs in the thousands
    would otherwise default-route into a certain compile-time VMEM
    overflow)."""
    del n_series  # the shrink makes the bound series-count-independent
    return _rows_fit(8, n_obs)


def _series_sharding(y):
    """``(mesh, axis_name, per_shard_lanes)`` when ``y`` is a concrete
    array sharded over >1 device along axis 0 only (series-sharded,
    time replicated, single mesh axis name) — the shape
    :func:`fit_css_lm_sharded` can wrap; ``None`` otherwise (tracers,
    replicated/single-device arrays, exotic shardings)."""
    from jax.sharding import NamedSharding
    try:
        sh = y.sharding
        n_dev = len(sh.device_set)
    except Exception:       # noqa: BLE001 — tracers have no sharding
        return None
    if n_dev <= 1 or not isinstance(sh, NamedSharding) or y.ndim != 2:
        return None
    spec = sh.spec
    axis = spec[0] if len(spec) > 0 else None
    time_rep = len(spec) < 2 or spec[1] is None
    if not isinstance(axis, str) or not time_rep:
        return None
    return sh.mesh, axis, sh.shard_shape(y.shape)[0]


def route_mode(y: jnp.ndarray, n_valid=None, allow_1d: bool = False,
               min_lanes: int = 1024, default_on: bool = True,
               flag_env: str = "STS_PALLAS",
               allow_ragged: bool = False) -> str:
    """Shared default-routing gate for the Pallas fit drivers; returns
    ``"pallas"`` (direct kernel call), ``"pallas_shard_map"`` (kernel
    per shard under :func:`fit_css_lm_sharded`), or ``"xla"``.

    The kernels are (lanes, obs)-shaped and f32: deeper batch nests and
    f64 parity fits always keep the XLA path — under force too (forcing
    must never silently degrade an f64 fit).  Ragged panels
    (``n_valid``) are eligible only when the CALLER's driver threads the
    per-lane window through (``allow_ragged=True`` — the ARMA NE kernel
    does, r5; the Holt-Winters driver does not).  The default route
    additionally needs a real panel
    (>= ``min_lanes`` series — smaller ones would mostly pad the
    1024-lane blocks), the TPU backend, and a block that fits VMEM
    (:func:`vmem_fits`; long-obs panels keep the streaming XLA path).
    Series-sharded concrete panels (``NamedSharding`` over axis 0, >1
    device, >= ``min_lanes`` lanes per shard) route ``pallas_shard_map``
    — the SPMD partitioner cannot split a ``pallas_call`` over a
    sharded axis, but per-shard blocks are exactly the kernel's shape,
    so distribution must not cost the kernel win (nor change the math,
    ref ``TimeSeriesRDD.scala:52-59``).  A tracer falls back to the
    single-device-process proxy: routing is decided OUTSIDE jit on the
    concrete panel precisely so sharding is visible.

    ``STS_PALLAS=0`` disables, ``=1`` forces any eligible shape
    (interpreter mode off-TPU, for tests; the VMEM bound is NOT
    enforced under force, so a forced overflow fails loudly at compile
    time rather than silently rerouting).  ``default_on=False`` keeps a
    driver opt-in (force-only) until its win is measured on the real
    chip; such a driver names its OWN ``flag_env`` so forcing it is a
    separate decision from forcing the measured ones (a user setting
    ``STS_PALLAS=1`` for the mesh workflow must not silently opt into
    unmeasured drivers).
    """
    nd_ok = y.ndim == 2 or (allow_1d and y.ndim == 1)
    ragged_ok = n_valid is None or allow_ragged
    eligible = ragged_ok and nd_ok and y.dtype == jnp.float32
    flag = os.environ.get(flag_env)
    if flag is not None and flag not in ("0", "1"):
        raise ValueError(f"{flag_env} must be '0' or '1', got {flag!r}")
    if flag == "0" or not eligible:
        return "xla"
    sharded = _series_sharding(y)
    if flag == "1":
        return "pallas_shard_map" if sharded else "pallas"
    if not default_on or not use_pallas():
        return "xla"
    if sharded is not None:
        _, _, per_shard = sharded
        if per_shard >= min_lanes and vmem_fits(per_shard, y.shape[-1]):
            return "pallas_shard_map"
        return "xla"
    big_enough = y.ndim == 2 and y.shape[0] >= min_lanes
    try:
        on_one_device = len(y.sharding.device_set) == 1
    except Exception:       # noqa: BLE001 — tracers have no sharding
        on_one_device = jax.device_count() == 1
    if eligible and big_enough and on_one_device \
            and vmem_fits(y.shape[0], y.shape[-1]):
        return "pallas"
    return "xla"


def route_panel(y: jnp.ndarray, n_valid=None, allow_1d: bool = False,
                min_lanes: int = 1024, default_on: bool = True,
                flag_env: str = "STS_PALLAS",
                allow_ragged: bool = False) -> bool:
    """Bool view of :func:`route_mode` for callers without a shard_map
    wrapper (the Holt-Winters driver, the auto-fit grid): True only for
    the direct path.  A FORCED flag meeting the sharded shape falls back
    to XLA *loudly* — forcing must never silently degrade."""
    mode = route_mode(y, n_valid, allow_1d=allow_1d, min_lanes=min_lanes,
                      default_on=default_on, flag_env=flag_env,
                      allow_ragged=allow_ragged)
    if mode == "pallas_shard_map" and os.environ.get(flag_env) == "1":
        import warnings
        warnings.warn(
            f"{flag_env}=1 forced a Pallas driver on a series-sharded "
            f"panel, but this caller has no shard_map wrapper; keeping "
            f"the XLA path (arima.fit wraps the kernel per shard "
            f"automatically; elsewhere, place the panel on one device or "
            f"force inside your own shard_map region)", stacklevel=3)
    return mode == "pallas"


def _block_rows(n_series: int, n_obs: int | None = None) -> int:
    """Sublane rows per lane block; shrinks (in multiples of the 8-row
    VPU tile) until the block's time axis fits VMEM, so long-obs panels
    trade grid steps for residency instead of losing the kernel."""
    rows = -(-n_series // LANES)
    rows = max(8, min(MAX_ROWS, ((rows + 7) // 8) * 8))
    if n_obs is not None:
        while rows > 8 and not _rows_fit(rows, n_obs):
            rows -= 8
    return rows


def _grid_rows(s_y: int, n_obs: int | None = None) -> int:
    """Block rows for the shared-panel grid: every candidate's lane run
    pads to the block boundary, so pick the row count that minimizes
    that padding (largest rows on ties — fewer grid steps), among row
    counts whose block fits VMEM.  With the maximal block an unaligned
    panel just over a block multiple would waste up to ~2x kernel
    compute per candidate, more than the measured Pallas win."""
    best_rows, best_pad = 8, None
    for r in range(8, MAX_ROWS + 1, 8):
        if n_obs is not None and r > 8 and not _rows_fit(r, n_obs):
            continue
        pad = (-s_y) % (r * LANES)
        if best_pad is None or pad < best_pad or \
                (pad == best_pad and r > best_rows):
            best_rows, best_pad = r, pad
    return best_rows


def _triu_pairs(k: int):
    return [(a, b) for a in range(k) for b in range(a, k)]


def _ne_kernel(p: int, q: int, icpt: int, n_obs: int, ragged: bool,
               params_ref, *refs):
    """One lane block.  ``params (k, ROWS, 128)``, ``y (n_obs, ROWS, 128)``
    VMEM-resident; ``out (n_out, ROWS, 128)`` with
    ``n_out = 1 + len(triu) + k`` laid out ``[sse, jtj_triu..., jtr...]``.
    ``ragged`` adds an ``nv (1, ROWS, 128)`` input after params: the
    per-lane valid-window length.

    The recurrence per step (matching ``arima._arma_normal_eqs``):

        e_t = y_t - c - Σ_j φ_j y_{t-j-1} - Σ_m θ_m e_ring[m]
        T_t = -u_t - Σ_m θ_m T_ring[m],  u = (1?, y lags newest-first,
                                              e_ring)
        sse += e², jtj += T Tᵀ (triu), jtr += T e

    starting at t = max(p, q) with zero rings — identical conditioning.
    Ragged lanes weight ``e`` and ``T`` by ``(t < nv)`` BEFORE the
    accumulators and the ring pushes, exactly the XLA kernel's order, so
    results equal the trimmed series' (the zero tail never contributes).
    """
    if ragged:
        nv_ref, y_ref, out_ref = refs
    else:
        y_ref, out_ref = refs
    k = icpt + p + q
    max_lag = max(p, q)
    pairs = _triu_pairs(k)
    n_steps = n_obs - max_lag
    n_chunks = n_steps // TIME_CHUNK
    tail = n_steps - n_chunks * TIME_CHUNK

    zero = y_ref[0] * 0.0
    c = params_ref[0] if icpt else zero
    phi = [params_ref[icpt + j] for j in range(p)]
    theta = [params_ref[icpt + p + m] for m in range(q)]
    nv = nv_ref[0] if ragged else None

    def steps(y_chunk, y_lag_chunks, carry, count, base_abs):
        """``count`` static steps; every VMEM index below is static.
        ``y_chunk[i]`` is y_t for step i; ``y_lag_chunks[j][i]`` is
        y_{t-j-1}; ``base_abs`` is step 0's absolute time index (traced
        under the fori_loop) for the ragged step weight."""
        e_ring, T_ring, sse, jtj, jtr = carry
        for i in range(count):
            y_t = y_chunk[i]
            yhat = c
            for j in range(p):
                yhat = yhat + phi[j] * y_lag_chunks[j][i]
            for m in range(q):
                yhat = yhat + theta[m] * e_ring[m]
            e = y_t - yhat
            T = []
            for x in range(k):
                if x < icpt:
                    u = zero + 1.0
                elif x < icpt + p:
                    u = y_lag_chunks[x - icpt][i]
                else:
                    u = e_ring[x - icpt - p]
                s = u
                for m in range(q):
                    s = s + theta[m] * T_ring[m][x]
                T.append(-s)
            if ragged:
                w = jnp.where((base_abs + i) < nv, zero + 1.0, zero)
                e = e * w
                T = [t_x * w for t_x in T]
            sse = sse + e * e
            jtj = [jtj[idx] + T[a] * T[b]
                   for idx, (a, b) in enumerate(pairs)]
            jtr = [jtr[x] + T[x] * e for x in range(k)]
            if q:
                e_ring = [e] + e_ring[:-1]
                T_ring = [T] + T_ring[:-1]
        return e_ring, T_ring, sse, jtj, jtr

    def flatten(carry):
        e_ring, T_ring, sse, jtj, jtr = carry
        return tuple(e_ring) + tuple(x for row in T_ring for x in row) \
            + (sse,) + tuple(jtj) + tuple(jtr)

    def unflatten(flat):
        e_ring = list(flat[:q])
        off = q
        T_ring = [list(flat[off + m * k: off + (m + 1) * k])
                  for m in range(q)]
        off += q * k
        sse = flat[off]
        jtj = list(flat[off + 1: off + 1 + len(pairs)])
        jtr = list(flat[off + 1 + len(pairs):])
        return e_ring, T_ring, sse, jtj, jtr

    def chunk_body(ci, flat):
        base = pl.multiple_of(max_lag + ci * TIME_CHUNK, 1)
        y_c = y_ref[pl.ds(base, TIME_CHUNK)]
        lag_c = [y_ref[pl.ds(base - (j + 1), TIME_CHUNK)] for j in range(p)]
        carry = steps([y_c[i] for i in range(TIME_CHUNK)],
                      [[lc[i] for i in range(TIME_CHUNK)] for lc in lag_c],
                      unflatten(flat), TIME_CHUNK, base)
        return flatten(carry)

    carry0 = ([zero] * q, [[zero] * k for _ in range(q)], zero,
              [zero] * len(pairs), [zero] * k)
    flat = jax.lax.fori_loop(0, n_chunks, chunk_body, flatten(carry0)) \
        if n_chunks else flatten(carry0)
    if tail:
        base = max_lag + n_chunks * TIME_CHUNK
        y_c = [y_ref[base + i] for i in range(tail)]
        lag_c = [[y_ref[base + i - (j + 1)] for i in range(tail)]
                 for j in range(p)]
        carry = steps(y_c, lag_c, unflatten(flat), tail, base)
    else:
        carry = unflatten(flat)
    _, _, sse, jtj, jtr = carry
    out_ref[0] = sse
    for idx in range(len(pairs)):
        out_ref[1 + idx] = jtj[idx]
    for x in range(k):
        out_ref[1 + len(pairs) + x] = jtr[x]


@functools.lru_cache(maxsize=None)
def _build_call(p: int, q: int, icpt: int, n_obs: int, n_blocks: int,
                rows: int, interpret: bool, y_blocks: int | None = None,
                ragged: bool = False):
    """``y_blocks`` < ``n_blocks`` re-reads the same panel blocks for
    several parameter blocks (candidate-major grid lanes over one shared
    panel): param/out block ``i`` pairs with y block ``i % y_blocks``.
    ``ragged`` adds the per-lane ``nv`` input, block-mapped like ``y``
    (it is a property of the PANEL lane, so the grid's modulo map
    applies to it too)."""
    k = icpt + p + q
    n_out = 1 + len(_triu_pairs(k)) + k
    kernel = functools.partial(_ne_kernel, p, q, icpt, n_obs, ragged)
    y_map = (lambda i: (0, i % y_blocks, 0, 0)) if y_blocks \
        else (lambda i: (0, i, 0, 0))
    in_specs = [pl.BlockSpec((k, 1, rows, LANES), lambda i: (0, i, 0, 0))]
    if ragged:
        in_specs.append(pl.BlockSpec((1, 1, rows, LANES), y_map))
    in_specs.append(pl.BlockSpec((n_obs, 1, rows, LANES), y_map))
    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((n_out, 1, rows, LANES),
                               lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (n_out, n_blocks, rows, LANES), jnp.float32),
        interpret=interpret,
    )


def _blocked(x: jnp.ndarray, n_series: int, rows: int):
    """(n_series, m) -> (m, n_blocks, rows, 128) with zero padding; series
    land on the minor lane axis (one transpose, amortized across the LM
    iterations by transposing once up front in the driver)."""
    block = rows * LANES
    pad = (-n_series) % block
    n_blocks = (n_series + pad) // block
    x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
    x = jnp.moveaxis(x, 0, -1)
    return x.reshape(*x.shape[:-1], n_blocks, rows, LANES), n_blocks


def normal_equations(params: jnp.ndarray, y: jnp.ndarray,
                     p: int, q: int, icpt: int,
                     mask: jnp.ndarray | None = None,
                     n_valid: jnp.ndarray | None = None,
                     interpret: bool | None = None):
    """Batched fused ``(JᵀJ (S, k, k), Jᵀr (S, k), sse (S,))`` for the ARMA
    CSS residuals — drop-in numerics for ``arima._arma_normal_eqs`` over a
    whole panel.  ``params (S, k)``, ``y (S, n)``, float32.

    ``mask`` (S, k) reproduces the masked-residual objective
    ``r(x ∘ mask)`` exactly as the XLA kernel does
    (``arima._arma_normal_eqs``): the recurrence runs at the masked
    point and the chain-rule factor is an outer-product scale on the
    outputs — nothing inside the Pallas kernel changes.

    ``n_valid`` (S,) restricts each lane to its left-aligned valid
    window (``ops.ragged``): step weights are computed in-kernel from
    the per-lane length, so ragged panels keep the VMEM-resident path."""
    if interpret is None:
        interpret = not use_pallas()
    k = icpt + p + q
    S, n_obs = y.shape
    if n_obs <= max(p, q):
        # the XLA path fails loudly at trace time for this; negative step
        # counts here would otherwise wrap to garbage static indices
        raise ValueError(
            f"series too short for the CSS window: need more than "
            f"max(p, q) = {max(p, q)} observations, got {n_obs}")
    rows = _block_rows(S, n_obs)
    y_b, n_blocks = _blocked(y.astype(jnp.float32), S, rows)
    nv_b = None
    if n_valid is not None:
        nv_b, _ = _blocked(
            jnp.asarray(n_valid, jnp.float32)[:, None], S, rows)
    if mask is not None:
        mask = mask.astype(jnp.float32)
        params = params * mask
    out = _ne_from_blocked(params, y_b, S, rows, n_blocks, p, q,
                           icpt, n_obs, interpret, nv_b=nv_b)
    return _masked_ne(*out, mask) if mask is not None else out


def _masked_ne(jtj, jtr, sse, mask):
    """Chain-rule factor of the masked objective ``r(x ∘ mask)`` — the
    single source of truth matching ``arima._arma_normal_eqs``'s
    post-scale (the recurrence itself runs at the masked point)."""
    return (jtj * mask[:, :, None] * mask[:, None, :], jtr * mask, sse)


def _ne_from_blocked(params, y_b, S, rows, n_blocks, p, q, icpt, n_obs,
                     interpret, y_blocks=None, nv_b=None):
    k = icpt + p + q
    params_b, _ = _blocked(params.astype(jnp.float32), S, rows)
    call = _build_call(p, q, icpt, n_obs, n_blocks, rows, interpret,
                       y_blocks, nv_b is not None)
    out = call(params_b, *(() if nv_b is None else (nv_b,)),
               y_b)                               # (n_out, nb, rows, 128)
    out = out.reshape(out.shape[0], -1)[:, :S].T  # (S, n_out)
    pairs = _triu_pairs(k)
    sse = out[:, 0]
    tri = out[:, 1:1 + len(pairs)]
    rows_idx = [a for a, _ in pairs]
    cols_idx = [b for _, b in pairs]
    jtj = jnp.zeros((S, k, k), jnp.float32)
    jtj = jtj.at[:, jnp.asarray(rows_idx), jnp.asarray(cols_idx)].set(tri)
    jtj = jtj.at[:, jnp.asarray(cols_idx), jnp.asarray(rows_idx)].set(tri)
    jtr = out[:, 1 + len(pairs):]
    return jtj, jtr, sse


def fit_css_lm(x0: jnp.ndarray, y: jnp.ndarray, p: int, q: int, icpt: int,
               tol: float = 1e-6, max_iter: int = 50,
               mask: jnp.ndarray | None = None,
               n_valid: jnp.ndarray | None = None,
               interpret: bool | None = None):
    """Panel-batched Levenberg-Marquardt on the CSS residuals with the
    normal equations built by the Pallas kernel.

    The state machine mirrors ``ops.optimize._minimize_lm_one`` exactly
    (Marquardt-scaled damping, trial-point normal equations reused on
    accept, per-lane convergence/pinned exits) but batches lanes in plain
    array ops instead of ``vmap`` — one kernel dispatch per iteration for
    the whole panel, with the small SPD solves on the unrolled Cholesky
    path.  Returns ``(x, fun, converged, n_iter)`` with per-lane shapes.

    ``mask`` (S, k) freezes parameter slots per lane (the fused
    auto-ARIMA grid's candidate masks): a frozen slot's Jacobian column
    is zeroed, so its normal-equation step is ``0 / 1e-12 = 0`` and the
    slot never moves — identical to the XLA grid solver's behavior.

    ``x0`` may carry MORE lanes than ``y`` — ``x0 (C·S, k)``
    candidate-major over ``y (S, n)`` (the fused grid's shape): the
    kernel re-reads the one blocked panel for every candidate
    (param/out block ``i`` pairs with y block ``i % y_blocks``) instead
    of materializing ``C`` panel copies.  When the lane block size does
    not divide ``S``, every candidate's lane run is padded up to the
    block boundary (padded lanes start ``done`` and are sliced off the
    results) — the panel is never tiled.

    ``n_valid (S,)`` restricts each PANEL lane to its left-aligned
    valid window (``ops.ragged``): the kernel computes step weights
    from the per-lane length in VMEM, so ragged panels keep the
    Pallas path (r5; previously they always fell back to XLA).
    """
    if interpret is None:
        interpret = not use_pallas()
    x0 = x0.astype(jnp.float32)
    if mask is not None:
        mask = mask.astype(jnp.float32)
        x0 = x0 * mask
    S, k = x0.shape
    S_y, n_obs = y.shape
    if n_obs <= max(p, q):
        raise ValueError(
            f"series too short for the CSS window: need more than "
            f"max(p, q) = {max(p, q)} observations, got {n_obs}")
    y_blocks = None
    n_real, pad = S, 0
    if n_valid is not None:
        n_valid = jnp.asarray(n_valid, jnp.float32)
    if S != S_y:
        if S % S_y:
            raise ValueError(
                f"x0 lane count {S} is not a multiple of the panel's "
                f"{S_y} series")
        C = S // S_y
        # block by the PANEL's alignment, not the grid's size: candidate
        # runs pad to the block boundary, so choose the row count that
        # minimizes that padding
        rows = _grid_rows(S_y, n_obs)
        block = rows * LANES
        pad = (-S_y) % block
        if pad:
            # align each candidate's lane run to the block boundary so
            # one blocked panel serves all candidates via the modulo map
            y = jnp.pad(y, ((0, pad), (0, 0)))
            x0 = jnp.pad(x0.reshape(C, S_y, k),
                         ((0, 0), (0, pad), (0, 0))).reshape(-1, k)
            if mask is not None:
                mask = jnp.pad(mask.reshape(C, S_y, k),
                               ((0, 0), (0, pad), (0, 0))).reshape(-1, k)
            if n_valid is not None:
                n_valid = jnp.pad(n_valid, (0, pad))
            S = C * (S_y + pad)
        y_b, y_blocks = _blocked(y.astype(jnp.float32), S_y + pad, rows)
        n_blocks = S // block
    else:
        rows = _block_rows(S, n_obs)
        y_b, n_blocks = _blocked(y.astype(jnp.float32), S, rows)
    nv_b = None
    if n_valid is not None:
        nv_b, _ = _blocked(n_valid[:, None],
                           (S_y + pad) if y_blocks else S, rows)
    eye = jnp.eye(k, dtype=jnp.float32)

    def ne(x):
        if mask is not None:
            x = x * mask
        out = _ne_from_blocked(x, y_b, S, rows, n_blocks, p, q,
                               icpt, n_obs, interpret, y_blocks, nv_b)
        return _masked_ne(*out, mask) if mask is not None else out

    def body(state):
        x, f, jtj, jtr, lam, it_lanes, it, done = state
        # freeze finished lanes exactly like the vmapped reference: jax's
        # while_loop batching rule masks the carry once a lane's cond is
        # false, so done lanes there stop moving — gate every update here
        active = ~done
        damp = lam[:, None] * jnp.diagonal(jtj, axis1=-2, axis2=-1) + 1e-12
        delta = spd_solve(jtj + damp[..., None] * eye, jtr)
        x_new = x - delta
        jtj_new, jtr_new, f_new = ne(x_new)
        ok = jnp.all(jnp.isfinite(jtj_new), axis=(-2, -1)) \
            & jnp.all(jnp.isfinite(jtr_new), axis=-1)
        improved = (f_new < f) & jnp.isfinite(f_new) & ok
        take = improved & active
        x = jnp.where(take[:, None], x_new, x)
        f_keep = jnp.where(take, f_new, f)
        jtj = jnp.where(take[:, None, None], jtj_new, jtj)
        jtr = jnp.where(take[:, None], jtr_new, jtr)
        # pinned-at-minimum exit tests the PRE-update lambda (the
        # reference's s.lam), so a rejection at lam = 1e8 still updates
        # lam and only the NEXT rejection marks the lane done
        rel_drop = (f - f_new) <= tol * (jnp.abs(f) + tol)
        step_small = jnp.max(jnp.abs(delta), axis=-1) <= tol * (
            jnp.max(jnp.abs(x), axis=-1) + tol)
        newly = (improved & (rel_drop | step_small)) \
            | (~improved & (lam > 1e8))
        lam = jnp.where(active,
                        jnp.where(improved, lam * 0.1, lam * 10.0), lam)
        return (x, f_keep, jtj, jtr, lam,
                it_lanes + active.astype(jnp.int32), it + 1,
                done | (newly & active))

    def cond(state):
        done, it = state[7], state[6]
        return jnp.logical_and(~jnp.all(done), it < max_iter)

    jtj0, jtr0, f0 = ne(x0)
    lam0 = jnp.full((S,), 1e-3, jnp.float32)
    # block-alignment padding lanes start done: they must neither hold
    # the loop open nor count iterations
    done0 = (jnp.arange(S) % (S_y + pad) >= S_y) if pad \
        else jnp.zeros((S,), bool)
    state = jax.lax.while_loop(
        cond, body,
        (x0, f0, jtj0, jtr0, lam0, jnp.zeros((S,), jnp.int32),
         jnp.asarray(0), done0))
    x, f, _, _, _, it_lanes, _, done = state
    if pad:
        C = S // (S_y + pad)

        def unpad(a):
            return a.reshape(C, S_y + pad, *a.shape[1:])[:, :S_y] \
                .reshape(n_real, *a.shape[1:])
        x, f, done, it_lanes = (unpad(a) for a in (x, f, done, it_lanes))
    return x, f, done, it_lanes


def fit_css_lm_sharded(x0: jnp.ndarray, y: jnp.ndarray, p: int, q: int,
                       icpt: int, tol: float = 1e-6, max_iter: int = 50,
                       n_valid: jnp.ndarray | None = None,
                       interpret: bool | None = None):
    """:func:`fit_css_lm` on a series-sharded panel, kernel-per-shard.

    ``y`` must be concrete with a ``NamedSharding`` over axis 0 only
    (the shape :func:`_series_sharding` accepts — :func:`route_mode`
    guarantees it on the ``"pallas_shard_map"`` branch).  Each shard's
    lane block is device-local inside ``shard_map``, so the
    ``pallas_call`` never sees a sharded array; the LM ``while_loop``
    carries no collectives, so shards converge independently exactly as
    independent processes would (distribution must not change the math,
    ref ``TimeSeriesRDD.scala:52-59``; per-lane equality vs the
    unsharded kernel is pinned by
    ``tests/test_pallas_arma.py::test_default_route_shard_map_equivalence``).
    ``check_vma=False`` because ``pallas_call``'s out_shape carries no
    varying-mesh annotation (same caveat as the documented manual
    workflow in ``docs/users.md``)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh, axis, _ = _series_sharding(y)
    lane_sharding = NamedSharding(mesh, P(axis, None))
    x0 = jax.device_put(x0.astype(jnp.float32), lane_sharding)
    args = (x0, y)
    in_specs = (P(axis, None), P(axis, None))
    if n_valid is not None:
        args += (jax.device_put(jnp.asarray(n_valid, jnp.float32),
                                NamedSharding(mesh, P(axis))),)
        in_specs += (P(axis),)

    def per_shard(x0_l, y_l, *nv_l):
        return fit_css_lm(x0_l, y_l, p, q, icpt, tol=tol,
                          max_iter=max_iter,
                          n_valid=nv_l[0] if nv_l else None,
                          interpret=interpret)

    return jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(axis, None), P(axis), P(axis), P(axis)),
        check_vma=False)(*args)
