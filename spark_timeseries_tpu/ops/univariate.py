"""Univariate series kernels: imputation, differencing, autocorrelation, sampling.

Capability parity with the reference's ``UnivariateTimeSeries.scala``
(``/root/reference/src/main/scala/com/cloudera/sparkts/UnivariateTimeSeries.scala:26-501``),
re-designed for TPU: every function operates on ``(..., n)`` arrays so the same
compiled kernel handles one series or a million-series panel.  Scalar
while-loops become gather/cumulative-op formulations (no sequential scans on
the hot paths), NaN propagation is made explicit, and everything composes
under ``jit``/``vmap``/``pjit``.

``fill_spline`` is the one host-side exception (per-series variable knot sets
resist static shapes); it mirrors the reference's use of a host interpolator
(ref ``UnivariateTimeSeries.scala:301-321``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# neighbor-index primitives
# ---------------------------------------------------------------------------

def _prev_valid_idx(valid: jnp.ndarray) -> jnp.ndarray:
    """For each position, index of the nearest valid position at or before it;
    -1 when none exists."""
    n = valid.shape[-1]
    iota = jnp.arange(n)
    marked = jnp.where(valid, iota, -1)
    return jax.lax.cummax(marked, axis=valid.ndim - 1)


def _next_valid_idx(valid: jnp.ndarray) -> jnp.ndarray:
    """Index of the nearest valid position at or after each position; n when none."""
    n = valid.shape[-1]
    iota = jnp.arange(n)
    marked = jnp.where(valid, iota, n)
    rev = jnp.flip(marked, axis=-1)
    return jnp.flip(jax.lax.cummin(rev, axis=valid.ndim - 1), axis=-1)


def _gather_last_axis(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    return jnp.take_along_axis(x, idx, axis=-1)


# ---------------------------------------------------------------------------
# imputation (ref UnivariateTimeSeries.scala:144-321)
# ---------------------------------------------------------------------------

def fill_value(x: jnp.ndarray, filler: float) -> jnp.ndarray:
    """Replace NaNs with a constant (ref ``:159-174``)."""
    return jnp.where(jnp.isnan(x), filler, x)


fill_with_default = fill_value


def fill_previous(x: jnp.ndarray) -> jnp.ndarray:
    """Carry the last valid value forward; leading NaNs stay NaN (ref ``:214-229``)."""
    valid = ~jnp.isnan(x)
    pidx = _prev_valid_idx(valid)
    out = _gather_last_axis(x, jnp.clip(pidx, 0, None))
    return jnp.where(pidx < 0, jnp.nan, out)


def fill_next(x: jnp.ndarray) -> jnp.ndarray:
    """Carry the next valid value backward; trailing NaNs stay NaN (ref ``:231-248``)."""
    n = x.shape[-1]
    valid = ~jnp.isnan(x)
    nidx = _next_valid_idx(valid)
    out = _gather_last_axis(x, jnp.clip(nidx, None, n - 1))
    return jnp.where(nidx >= n, jnp.nan, out)


def fill_nearest(x: jnp.ndarray) -> jnp.ndarray:
    """Fill each NaN with the closest valid value; ties prefer the next value
    (ref ``:180-208``; all-NaN series stay NaN rather than raising)."""
    n = x.shape[-1]
    valid = ~jnp.isnan(x)
    iota = jnp.arange(n)
    pidx = _prev_valid_idx(valid)
    nidx = _next_valid_idx(valid)
    prev_val = jnp.where(pidx < 0, jnp.nan,
                         _gather_last_axis(x, jnp.clip(pidx, 0, None)))
    next_val = jnp.where(nidx >= n, jnp.nan,
                         _gather_last_axis(x, jnp.clip(nidx, None, n - 1)))
    dist_prev = iota - pidx
    dist_next = nidx - iota
    use_prev = (pidx >= 0) & ((nidx >= n) | (dist_prev < dist_next))
    filled = jnp.where(use_prev, prev_val, next_val)
    return jnp.where(valid, x, filled)


def fill_linear(x: jnp.ndarray) -> jnp.ndarray:
    """Linear interpolation across interior NaN runs; leading/trailing NaNs stay
    (ref ``:267-290``)."""
    n = x.shape[-1]
    valid = ~jnp.isnan(x)
    iota = jnp.arange(n)
    pidx = _prev_valid_idx(valid)
    nidx = _next_valid_idx(valid)
    interior = (pidx >= 0) & (nidx < n) & ~valid
    p = jnp.clip(pidx, 0, None)
    q = jnp.clip(nidx, None, n - 1)
    vp = _gather_last_axis(x, p)
    vq = _gather_last_axis(x, q)
    span = jnp.maximum(q - p, 1)
    interp = vp + (vq - vp) * (iota - p) / span
    return jnp.where(interior, interp, x)


def fill_zero(x: jnp.ndarray) -> jnp.ndarray:
    return fill_value(x, 0.0)


def fill_spline(x) -> np.ndarray:
    """Natural-cubic-spline fill between the first and last valid knots.

    Host-side (scipy), matching the reference's Commons-Math
    ``SplineInterpolator`` behavior (ref ``:301-321``): positions outside
    [first knot, last knot] are left untouched.  Accepts ``(n,)`` or
    ``(batch, n)`` numpy arrays.

    Panel-scale behavior: fully-observed rows are skipped outright, and rows
    sharing a missingness pattern are solved in ONE vectorized
    ``CubicSpline`` call (scipy splines batch along an axis), so the cost
    scales with the number of *distinct* NaN patterns — the per-row Python
    loop survives only in the worst case where every row's pattern is
    unique.
    """
    from scipy.interpolate import CubicSpline

    arr = np.array(x, dtype=np.float64, copy=True)
    batched = arr.ndim > 1
    rows = arr.reshape(-1, arr.shape[-1]) if batched else arr[None, :]
    nan_mask = np.isnan(rows)
    todo = np.flatnonzero(nan_mask.any(axis=1))

    patterns: dict = {}
    for i in todo:
        patterns.setdefault(nan_mask[i].tobytes(), []).append(int(i))
    for mask_bytes, idxs in patterns.items():
        knots = np.flatnonzero(~nan_mask[idxs[0]])
        if knots.size < 2:
            continue
        grid = np.arange(knots[0], knots[-1] + 1)
        sub = rows[idxs]
        if knots.size < 3:
            # two knots: natural spline degenerates to linear (vectorized)
            v0 = sub[:, knots[0]:knots[0] + 1]
            v1 = sub[:, knots[-1]:knots[-1] + 1]
            interp = v0 + (v1 - v0) * (grid - knots[0]) / (knots[-1] - knots[0])
        else:
            cs = CubicSpline(knots, sub[:, knots], axis=1, bc_type="natural")
            interp = cs(grid)
        rows[np.ix_(idxs, grid)] = interp
    return rows.reshape(arr.shape) if batched else rows[0]


_FILL_METHODS = {
    "linear": fill_linear,
    "nearest": fill_nearest,
    "next": fill_next,
    "previous": fill_previous,
    "spline": fill_spline,
    "zero": fill_zero,
}


def fillts(x, fill_method: str):
    """String-dispatched fill (ref ``:144-154``)."""
    try:
        return _FILL_METHODS[fill_method](x)
    except KeyError:
        raise ValueError(f"unknown fill method {fill_method!r}") from None


# ---------------------------------------------------------------------------
# NaN trimming (ref UnivariateTimeSeries.scala:101-142)
# ---------------------------------------------------------------------------

def first_not_nan(x: jnp.ndarray) -> jnp.ndarray:
    """Index of the first non-NaN along the last axis; n when all NaN."""
    valid = ~jnp.isnan(x)
    return jnp.where(jnp.any(valid, axis=-1),
                     jnp.argmax(valid, axis=-1), x.shape[-1])


def last_not_nan(x: jnp.ndarray) -> jnp.ndarray:
    """Index one past the last non-NaN along the last axis; 0 when all NaN.

    Deliberate off-by-one fix vs the reference: ``lastNotNaN``
    (ref ``:113-142``) returns the *inclusive* index but ``trimTrailing``
    uses it as an exclusive end, silently dropping the last valid
    observation; here the exclusive end is returned directly.
    """
    n = x.shape[-1]
    valid = ~jnp.isnan(x)
    rev_first = jnp.argmax(jnp.flip(valid, axis=-1), axis=-1)
    return jnp.where(jnp.any(valid, axis=-1), n - rev_first, 0)


def trim_leading(x: np.ndarray) -> np.ndarray:
    """Drop leading NaNs (host-side: dynamic output shape; 1-D only)."""
    start = int(first_not_nan(jnp.asarray(x)))
    return np.asarray(x)[start:]


def trim_trailing(x: np.ndarray) -> np.ndarray:
    """Drop trailing NaNs (host-side: dynamic output shape; 1-D only)."""
    end = int(last_not_nan(jnp.asarray(x)))
    return np.asarray(x)[:end]


# ---------------------------------------------------------------------------
# differencing (ref UnivariateTimeSeries.scala:384-495)
# ---------------------------------------------------------------------------

def differences_at_lag(x: jnp.ndarray, lag: int,
                       start_index: int | None = None) -> jnp.ndarray:
    """Size-preserving difference: ``out[i] = x[i] - x[i-lag]`` for
    ``i >= start_index``; earlier elements are copied (ref ``:384-405``)."""
    if lag == 0:
        return x
    start = lag if start_index is None else start_index
    if start < lag:
        raise ValueError("starting index cannot be less than lag")
    n = x.shape[-1]
    shifted = jnp.concatenate([x[..., :lag], x[..., :n - lag]], axis=-1)
    return jnp.where(jnp.arange(n) >= start, x - shifted, x)


def inverse_differences_at_lag(x: jnp.ndarray, lag: int,
                               start_index: int | None = None) -> jnp.ndarray:
    """Inverse of ``differences_at_lag``: ``out[i] = x[i] + out[i-lag]`` for
    ``i >= start_index`` (ref ``:426-447``).

    Closed form instead of a sequential loop: per residue class mod ``lag``,
    the recurrence telescopes to a strided cumulative sum plus the last copied
    element of the chain.
    """
    if lag == 0:
        return x
    start = lag if start_index is None else start_index
    if start < lag:
        raise ValueError("starting index cannot be less than lag")
    n = x.shape[-1]
    iota = jnp.arange(n)

    k = math.ceil(n / lag)
    pad = k * lag - n
    contrib = jnp.where(iota >= start, x, 0.0)
    contrib = jnp.pad(contrib, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    csum = jnp.cumsum(contrib.reshape(*x.shape[:-1], k, lag), axis=-2)
    csum = csum.reshape(*x.shape[:-1], k * lag)[..., :n]

    # chain base for position i: out at the largest chain index < start,
    # which lives in the copied region and therefore equals x there
    r = iota % lag
    base_idx = r + lag * ((start - 1 - r) // lag)
    base = _gather_last_axis(x, jnp.broadcast_to(base_idx, x.shape))
    return jnp.where(iota >= start, csum + base, x)


def differences_of_order_d(x: jnp.ndarray, d: int) -> jnp.ndarray:
    """Recursive order-d differencing; level i starts at index i (ref ``:468-483``)."""
    out = x
    for i in range(1, d + 1):
        out = differences_at_lag(out, 1, i)
    return out


def inverse_differences_of_order_d(x: jnp.ndarray, d: int) -> jnp.ndarray:
    """Inverse of ``differences_of_order_d`` (ref ``:485-495``)."""
    out = x
    for i in range(d, 0, -1):
        out = inverse_differences_at_lag(out, 1, i)
    return out


# ---------------------------------------------------------------------------
# ratios / autocorr / sampling / rolling (ref UnivariateTimeSeries.scala:43-96,332-373,497-499)
# ---------------------------------------------------------------------------

def quotients(x: jnp.ndarray, lag: int) -> jnp.ndarray:
    """``x[i+lag] / x[i]``; output is ``lag`` shorter (ref ``:47-55``)."""
    return x[..., lag:] / x[..., :-lag]


def price2ret(x: jnp.ndarray, lag: int) -> jnp.ndarray:
    """Simple returns ``x[i+lag]/x[i] - 1`` (ref ``:57-65``)."""
    return quotients(x, lag) - 1.0


def autocorr(x: jnp.ndarray, num_lags: int) -> jnp.ndarray:
    """Sample autocorrelation for lags 1..num_lags (ref ``:70-96``).

    Matches the reference's estimator exactly: per lag, the leading and
    trailing slices are separately demeaned and normalized.  Returns
    ``(..., num_lags)``.
    """
    n = x.shape[-1]
    corrs = []
    for lag in range(1, num_lags + 1):
        s1 = x[..., lag:]
        s2 = x[..., :n - lag]
        m1 = jnp.mean(s1, axis=-1, keepdims=True)
        m2 = jnp.mean(s2, axis=-1, keepdims=True)
        d1 = s1 - m1
        d2 = s2 - m2
        cov = jnp.sum(d1 * d2, axis=-1)
        v1 = jnp.sum(d1 * d1, axis=-1)
        v2 = jnp.sum(d2 * d2, axis=-1)
        corrs.append(cov / (jnp.sqrt(v1) * jnp.sqrt(v2)))
    return jnp.stack(corrs, axis=-1)


def downsample(x: jnp.ndarray, n: int, phase: int = 0) -> jnp.ndarray:
    """Every n-th element starting at ``phase`` (ref ``:327-345``)."""
    return x[..., phase::n]


def upsample(x: jnp.ndarray, n: int, phase: int = 0,
             use_zero: bool = False) -> jnp.ndarray:
    """Insert ``n-1`` fillers between elements, starting at ``phase``
    (ref ``:347-373``)."""
    filler = 0.0 if use_zero else jnp.nan
    orig = x.shape[-1]
    out = jnp.full((*x.shape[:-1], orig * n), filler, dtype=x.dtype)
    return out.at[..., phase::n].set(x)


def roll_sum(x: jnp.ndarray, window: int) -> jnp.ndarray:
    """Sliding-window sum; output length ``n - window + 1`` (ref ``:497-499``).

    Stacked-slice sum rather than a cumsum difference so a NaN only poisons
    the windows that actually contain it, matching the reference's per-window
    loop; XLA fuses the ``window`` adds into one pass.
    """
    n = x.shape[-1]
    out = x[..., :n - window + 1]
    for i in range(1, window):
        out = out + x[..., i:n - window + 1 + i]
    return out


def roll_mean(x: jnp.ndarray, window: int) -> jnp.ndarray:
    """Sliding-window mean (ref ``TimeSeriesRDD.scala:629-647`` rollMean)."""
    return roll_sum(x, window) / window
