"""Window resampling with pandas-style closed/stamp semantics.

Capability parity with the reference's ``Resample.scala``
(``/root/reference/src/main/scala/com/cloudera/sparkts/Resample.scala:47-121``).
The reference walks source/target instant streams with a merge iterator; here
bucket assignment is one vectorized ``searchsorted`` on the host (int64 nanos)
and aggregation is a batched segment reduction on device, so one call
resamples an entire ``(..., n)`` panel.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..time.index import DateTimeIndex


def bucket_assignments(source_nanos: np.ndarray, target_nanos: np.ndarray,
                       closed_right: bool, stamp_right: bool) -> np.ndarray:
    """Bucket index for each source instant; -1 where the observation falls in
    no window.  Vectorized equivalent of the reference's end-predicate walk
    (ref ``Resample.scala:78-119``).

    Window semantics (m = len(target)):
      - ``stamp_right``: stamp i labels the window *ending* at target[i];
        bucket 0 is unbounded below, observations after the last stamp drop.
      - ``not stamp_right``: stamp i labels the window *starting* at target[i];
        observations before the first stamp drop, the last window is unbounded
        above.
      - ``closed_right``: windows are (lo, hi] instead of [lo, hi).
    """
    side = "left" if closed_right else "right"
    pos = np.searchsorted(target_nanos, source_nanos, side=side)
    if stamp_right:
        bucket = pos
    else:
        bucket = pos - 1
    m = target_nanos.size
    return np.where((bucket >= 0) & (bucket < m), bucket, -1).astype(np.int64)


def _seg_reduce(values: jnp.ndarray, bucket: jnp.ndarray, m: int,
                how: str) -> jnp.ndarray:
    """Batched segment reduction along the last axis.  Empty buckets -> NaN."""
    seg = jnp.where(bucket < 0, m, bucket)  # park dropped obs in a spill bucket

    def one(v):
        count = jax.ops.segment_sum(jnp.ones_like(v), seg, num_segments=m + 1)
        if how in ("mean", "sum"):
            s = jax.ops.segment_sum(v, seg, num_segments=m + 1)
            out = s / count if how == "mean" else s
        elif how == "min":
            out = jax.ops.segment_min(v, seg, num_segments=m + 1)
        elif how == "max":
            out = jax.ops.segment_max(v, seg, num_segments=m + 1)
        elif how == "first":
            n = v.shape[-1]
            first_pos = jax.ops.segment_min(jnp.arange(n), seg, num_segments=m + 1)
            out = v[jnp.clip(first_pos, 0, n - 1)]
        elif how == "last":
            n = v.shape[-1]
            last_pos = jax.ops.segment_max(jnp.arange(n), seg, num_segments=m + 1)
            out = v[jnp.clip(last_pos, 0, n - 1)]
        elif how == "count":
            out = count
        else:
            raise ValueError(f"unknown aggregator {how!r}")
        return jnp.where(count > 0, out, jnp.nan)[:m]

    flat = values.reshape(-1, values.shape[-1])
    out = jax.vmap(one)(flat)
    return out.reshape(*values.shape[:-1], m)


def resample(values, source_index: DateTimeIndex, target_index: DateTimeIndex,
             aggr: Union[str, Callable] = "mean",
             closed_right: bool = False, stamp_right: bool = False):
    """Resample ``(..., n)`` values from ``source_index`` onto ``target_index``
    (ref ``Resample.scala:47-121``).

    ``aggr`` is one of ``mean|sum|min|max|first|last|count`` (device segment
    reduction), or a Python callable ``(np.ndarray, start, end) -> float``
    applied per bucket on the host for parity with the reference's arbitrary
    aggregator signature.
    """
    src = source_index.to_nanos_array()
    tgt = target_index.to_nanos_array()
    bucket = bucket_assignments(src, tgt, closed_right, stamp_right)

    if callable(aggr):
        # host fallback: contiguous bucket ranges, arbitrary aggregator
        arr = np.asarray(values)
        m = tgt.size
        # preserve a float input's width: the device path (_seg_reduce)
        # keeps f32 panels f32, and the host fallback must agree rather
        # than silently widening to numpy's f64 default (sts-lint STS004)
        out_dtype = arr.dtype if np.issubdtype(arr.dtype, np.floating) \
            else np.float64
        out = np.full((*arr.shape[:-1], m), np.nan, dtype=out_dtype)
        flat = arr.reshape(-1, arr.shape[-1])
        out_flat = out.reshape(-1, m)
        valid = bucket >= 0
        for b in range(m):
            locs = np.flatnonzero(valid & (bucket == b))
            if locs.size:
                start, end = int(locs[0]), int(locs[-1]) + 1
                out_flat[:, b] = [aggr(row, start, end) for row in flat]
        return out

    return _seg_reduce(jnp.asarray(values), jnp.asarray(bucket), tgt.size, aggr)
