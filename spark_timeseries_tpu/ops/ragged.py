"""Ragged / NaN-padded panel support: valid-window views for batched fits.

The reference's ingestion shape — ``timeSeriesRDDFromObservations`` followed
by index ``union`` (ref ``/root/reference/src/main/scala/com/cloudera/sparkts/TimeSeriesRDD.scala:694-745``)
— produces rectangular panels whose lanes are NaN-padded where a series
starts later or ends earlier than the union calendar.  The reference fills
(imputes) before fitting; here the CSS/SSE fits accept such panels directly
(SURVEY.md §7 hard part #5: mask semantics everywhere).

TPU-native design: instead of threading a per-observation boolean mask
through every recurrence (a second operand in every scan step), each lane's
contiguous valid window is **left-aligned by one gather** and reduced to a
single per-lane length.  Kernels then derive step weights from an
``iota < length`` comparison — one broadcast compare, no mask arrays in HBM
— and a fit on the padded panel is arithmetically identical to fitting each
trimmed series alone (pinned by ``tests/test_ragged.py``).

Interior gaps (NaNs strictly inside a lane's first..last finite window) are
*not* maskable this way — a lag recurrence reading a missing observation has
no exact conditional-CSS answer short of a Kalman filter — so they raise,
directing the caller to ``fill`` (the reference's own requirement for any
NaN, ``TimeSeriesRDD.scala:172-189``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _windows(values: jnp.ndarray):
    """Per-lane ``(start, length, n_observed)`` of the observed (non-NaN)
    window.  NaN alone marks padding: an ``inf`` is bad *data* and must
    flow into the objective to quarantine its lane loudly, not be trimmed
    silently."""
    n = values.shape[-1]
    obs = ~jnp.isnan(values)
    any_valid = jnp.any(obs, axis=-1)
    start = jnp.argmax(obs, axis=-1)
    last = n - 1 - jnp.argmax(obs[..., ::-1], axis=-1)
    length = jnp.where(any_valid, last - start + 1, 0)
    return start, length, jnp.sum(obs, axis=-1)


@jax.jit
def _left_align(values: jnp.ndarray):
    start, length, n_obs = _windows(values)
    n = values.shape[-1]
    idx = jnp.minimum(start[..., None] + jnp.arange(n), n - 1)
    rolled = jnp.take_along_axis(values, idx, axis=-1)
    tail = jnp.arange(n) >= length[..., None]
    rolled = jnp.where(tail, jnp.zeros((), values.dtype), rolled)
    return rolled, length, n_obs


def ragged_view(values: jnp.ndarray
                ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """``(aligned, lengths)`` view of a possibly NaN-padded panel.

    Fully-observed input returns ``(values, None)`` untouched (one scalar
    device reduction decides; no transfer, no relayout).  Otherwise every
    lane's contiguous observed window is shifted to position 0, the
    garbage tail is zeroed (so downstream recurrences stay NaN-free), and
    ``lengths (...,)`` gives each lane's valid-observation count — an
    all-NaN lane gets length 0.  Raises if any lane has NaN strictly inside
    its observed window (impute those with ``fill`` first; only *interior*
    gaps need it now).

    ``values (..., n)``: a single series or any batch of lanes.  Under an
    enclosing ``jit`` trace the padding check is impossible (it is a
    data-dependent branch), so tracers pass through as fully observed —
    ragged panels must enter ``fit`` outside ``jit`` (the fits jit their
    own kernels; benchmark wrappers that jit whole fits feed dense
    panels).
    """
    values = jnp.asarray(values)
    if values.dtype.kind != "f" or isinstance(values, jax.core.Tracer):
        return values, None
    if not bool(jnp.any(jnp.isnan(values))):
        return values, None
    aligned, length, n_obs = _left_align(values)
    holes = jnp.sum(n_obs != length)
    if int(holes):
        raise ValueError(
            f"{int(holes)} lane(s) have NaN strictly inside their observed "
            f"window; valid-window fits need contiguous observations — "
            f"impute interior gaps first (e.g. Panel.fill / ops.fill_ts), "
            f"leading/trailing padding needs no fill")
    return aligned, length


def step_weights(n_steps: int, n_valid: jnp.ndarray, offset: int = 0,
                 dtype=None) -> jnp.ndarray:
    """``(n_steps,)`` 0/1 weights: step ``i`` (absolute index
    ``offset + i`` in the lane) is live iff ``offset + i < n_valid``.
    The one primitive masked kernels need — computed from iota at trace
    time, never stored.  Batched ``n_valid`` must arrive pre-expanded
    (``n_valid[..., None]``) so the compare broadcasts to ``(..., n_steps)``."""
    w = (offset + jnp.arange(n_steps)) < n_valid
    return w if dtype is None else w.astype(dtype)


def short_lanes(obs_len: jnp.ndarray, min_n: int,
                what: str) -> Optional[jnp.ndarray]:
    """Flag lanes whose valid window is under ``min_n`` observations.

    The shared short-lane policy for every ragged fit: warn and return
    the boolean mask — callers then NaN those lanes' parameters via
    :func:`apply_short_quarantine` instead of poisoning the batch.
    Deliberately never raises, even when EVERY lane is short: batched
    fits degrade per lane on data content (the framework's failure
    philosophy — e.g. ``fit_long`` relies on an all-NaN panel coming
    back quarantined, not thrown), and the warning plus all-NaN
    parameters with ``converged == False`` carry the same information.
    Returns ``None`` when nothing is short.  ``what`` names the
    requirement in the message (e.g. ``"ARIMA(2,0,2) Hannan-Rissanen
    initialization"``).

    Traced ``obs_len`` (a fit running under the engine's AOT executables,
    where the lengths are runtime data) returns the traced boolean mask
    instead: quarantine applies identically via ``jnp.where``, the host
    warning is simply unavailable at trace time, and the mask keeps the
    jaxpr independent of the lengths' values (the stable-jaxpr contract).
    """
    import warnings

    import numpy as np
    if isinstance(obs_len, jax.core.Tracer):
        return obs_len < min_n
    short = np.asarray(obs_len) < min_n
    if not short.any():
        return None
    n = int(short.sum())
    count = f"all {n} lanes" if short.all() else f"{n} lane(s)"
    warnings.warn(
        f"{count} have valid windows shorter than the "
        f"{min_n} observations the {what} needs; their parameters are NaN "
        f"and diagnostics.converged is False", stacklevel=4)
    return jnp.asarray(short)


def apply_short_quarantine(params: jnp.ndarray, converged: jnp.ndarray,
                           short: Optional[jnp.ndarray]):
    """NaN out short lanes' parameters and demote them to non-converged
    (``short`` from :func:`short_lanes`; ``None`` passes through)."""
    if short is None:
        return params, converged
    s = short[..., None] if params.ndim > short.ndim else short
    return (jnp.where(s, jnp.nan, params),
            converged & ~jnp.reshape(short, converged.shape))
