"""Batched smooth optimizers — the TPU replacement for Commons-Math.

The reference drives every model fit through a scalar Commons-Math optimizer,
one series at a time:

- ``NonLinearConjugateGradientOptimizer`` with hand-derived gradients
  (ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/EWMA.scala:45-69``,
  ``ARIMA.scala:174-200``, ``GARCH.scala:33-53``)
- ``BOBYQAOptimizer`` for bounded / derivative-free problems
  (ref ``ARIMA.scala:130-160``, ``HoltWinters.scala:66-83``)

On TPU the whole panel optimizes in lockstep: objectives are written once in
JAX, gradients come from autodiff (through ``lax.scan`` recurrences), and a
``vmap`` over the series axis advances every series' parameters inside one
compiled XLA program.  Heterogeneous convergence across the batch is handled
by per-series convergence masks — converged lanes simply stop moving while
the rest iterate (SURVEY.md §7 "hard parts" #2, #3).

Two solvers cover the reference's needs:

- :func:`minimize_bfgs` — smooth unconstrained problems (CGD replacement).
- :func:`minimize_box` — box-constrained projected gradient with Armijo
  backtracking (BOBYQA replacement for the bounded fits; the reference's
  bounded problems — Holt-Winters [0,1]^3, ARIMA css-bobyqa — are smooth, so
  a projected-gradient method converges to the same optima).
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import metrics as _metrics
from .linalg import spd_solve


class MinimizeResult(NamedTuple):
    """Batched optimization artifacts (leading dims ``...`` = batch).

    ``attempts`` is populated only by the multi-start retry path
    (``restarts > 0`` or an active fault injection): the number of solve
    attempts each lane actually ran.  None on the plain single-start path.
    """
    x: jnp.ndarray          # (..., p) optimal parameters
    fun: jnp.ndarray        # (...,)   objective at optimum
    converged: jnp.ndarray  # (...,)   bool per-lane convergence mask
    n_iter: jnp.ndarray     # (...,)   iterations taken
    attempts: Optional[jnp.ndarray] = None  # (...,) multi-start solves run


# ---------------------------------------------------------------------------
# multi-start retry: re-solve non-converged / non-finite lanes from jittered
# inits INSIDE the batched computation (a lax.while over restarts — no host
# round-trips), instead of silently handing back NaN or cap-hit parameters
# ---------------------------------------------------------------------------

def _forced_failures() -> int:
    """Attempt count an active ``force_nonconverge`` fault injection makes
    the solvers report as non-converged (0 normally).  Read at call/trace
    time; ``utils.resilience.fault_injection`` clears jit caches around its
    scope so cached kernels never leak across regimes."""
    from ..utils import resilience as _resilience
    return _resilience.forced_optimizer_failures()


class _RestartState(NamedTuple):
    x: jnp.ndarray
    fun: jnp.ndarray
    converged: jnp.ndarray
    n_iter: jnp.ndarray
    attempt: jnp.ndarray


def _with_restarts(solve_one: Callable, restarts: int, scale: float,
                   fail_first: int) -> Callable:
    """Wrap a single-lane solver in a multi-start loop (designed, like the
    solvers themselves, to be vmapped).

    Attempt 0 runs from the caller's init; each further attempt re-solves
    from ``x0 + scale * (1 + |x0|) * N(0, 1)`` drawn from the lane's PRNG
    key folded with the attempt index.  The loop exits the moment an
    attempt converges with finite objective and parameters; otherwise the
    best finite-objective attempt is kept (falling back to ``x0`` when
    every attempt went non-finite — the quarantine-to-init policy the
    model fits already apply per lane).  Under ``vmap`` converged lanes
    hold position while the rest retry — every lane pays the slowest
    lane's attempts, the same trade as the convergence-masked iteration
    loops (SURVEY.md §7).

    ``fail_first`` (static, from fault injection) forces attempts
    ``< fail_first`` to report non-convergence — deterministic synthetic
    divergence for testing the retry and fallback machinery.
    """
    total = restarts + 1

    def wrapped(x0_i, key_i, *args_i):
        def one_attempt(att):
            jitter = jax.random.normal(jax.random.fold_in(key_i, att),
                                       x0_i.shape, x0_i.dtype) \
                * (scale * (1.0 + jnp.abs(x0_i)))
            x_start = jnp.where(att == 0, x0_i, x0_i + jitter)
            r = solve_one(x_start, *args_i)
            conv = r.converged
            if fail_first:
                conv = jnp.logical_and(conv, att >= fail_first)
            return r, conv

        r0, conv0 = one_attempt(jnp.asarray(0))
        fin0 = jnp.isfinite(r0.fun) & jnp.all(jnp.isfinite(r0.x))
        ok0 = conv0 & fin0
        state0 = _RestartState(
            jnp.where(fin0, r0.x, x0_i),
            jnp.where(fin0, r0.fun, jnp.asarray(jnp.inf, r0.fun.dtype)),
            ok0, r0.n_iter, jnp.asarray(1))

        def cond(s):
            return jnp.logical_and(~s.converged, s.attempt < total)

        def body(s):
            r, conv = one_attempt(s.attempt)
            fin = jnp.isfinite(r.fun) & jnp.all(jnp.isfinite(r.x))
            ok = conv & fin
            # frozen once converged (vmap runs every lane to the slowest
            # lane's exit); otherwise keep the best finite attempt so far
            better = (ok | (fin & (r.fun < s.fun))) & ~s.converged
            return _RestartState(
                jnp.where(better, r.x, s.x),
                jnp.where(better, r.fun, s.fun),
                s.converged | ok,
                jnp.where(better, r.n_iter, s.n_iter),
                s.attempt + (~s.converged).astype(s.attempt.dtype))

        final = lax.while_loop(cond, body, state0)
        return MinimizeResult(final.x, final.fun, final.converged,
                              final.n_iter, final.attempt)

    return wrapped


def _lane_keys(restart_key, batch_shape):
    """One PRNG key per lane (threaded through the vmap alongside x0)."""
    key = restart_key if restart_key is not None else jax.random.PRNGKey(0)
    if not batch_shape:
        return key
    keys = jax.random.split(key, math.prod(batch_shape))
    return keys.reshape(*batch_shape, *keys.shape[1:])


def _solve_with_policy(solve_one: Callable, x0: jnp.ndarray, args,
                       restarts: int, restart_scale: float, restart_key):
    """Shared driver: vmap ``solve_one`` over the batch dims, inserting the
    multi-start wrapper when a retry budget or an injected fault is active.
    ``restarts == 0`` with no fault takes the original path bit-for-bit."""
    batch_dims = x0.ndim - 1
    fail_first = _forced_failures()
    if restarts or fail_first:
        solve = _with_restarts(solve_one, restarts, restart_scale,
                               fail_first)
        keys = _lane_keys(restart_key, x0.shape[:-1])
        for _ in range(batch_dims):
            solve = jax.vmap(solve)
        return solve(x0, keys, *args)
    solve = solve_one
    for _ in range(batch_dims):
        solve = jax.vmap(solve)
    return solve(x0, *args)


def minimize_bfgs(fn: Callable, x0: jnp.ndarray, *args,
                  tol: float = 1e-8, max_iter: int = 200,
                  restarts: int = 0, restart_scale: float = 0.25,
                  restart_key=None) -> MinimizeResult:
    """Batched BFGS for smooth unconstrained objectives.

    ``fn(params, *args) -> scalar`` where ``params`` is ``(p,)``; ``x0`` may
    carry leading batch dims, in which case ``args`` entries must carry the
    same leading dims and the solve is vmapped over them.

    ``restarts > 0`` enables the multi-start retry path (see
    :func:`_with_restarts`): non-converged / non-finite lanes re-solve up
    to ``restarts`` more times from inits jittered by ``restart_scale``
    under per-lane keys split from ``restart_key``.
    """
    from jax.scipy.optimize import minimize as _jsp_minimize

    def solve_one(x0_i, *args_i):
        res = _jsp_minimize(lambda p: fn(p, *args_i), x0_i, method="BFGS",
                            tol=tol, options={"maxiter": max_iter})
        return MinimizeResult(res.x, res.fun, res.success, res.nit)

    with _metrics.span("optimize.bfgs"):
        # the recorder's host reads block on the device work; keeping
        # them inside the span attributes that wall-time to the solver
        res = _solve_with_policy(solve_one, x0, args, restarts,
                                 restart_scale, restart_key)
        return _metrics.observe_minimize("bfgs", res)


class _LMState(NamedTuple):
    x: jnp.ndarray
    f: jnp.ndarray
    jtj: jnp.ndarray
    jtr: jnp.ndarray
    lam: jnp.ndarray
    it: jnp.ndarray
    done: jnp.ndarray


def _minimize_lm_one(residual_fn, x0, tol, max_iter, lam0=1e-3,
                     lam_up=10.0, lam_down=0.1, normal_eqs_fn=None):
    """Single-lane Levenberg-Marquardt on a residual vector; designed to be
    vmapped (fixed-shape while_loop, per-lane damping and convergence).

    One fused residual+Jacobian pass per iteration: the normal equations are
    evaluated at the *trial* point, so an accepted step's next solve reuses
    them and a rejected step re-solves from the carried ones with higher
    damping — halving the recurrence work versus a separate cost evaluation.

    ``normal_eqs_fn(x) -> (JᵀJ, Jᵀr, sse)`` overrides the autodiff pass for
    residuals whose Jacobian has a cheap hand-fused form (e.g. the ARMA
    tangent recurrence accumulated in a scan carry, which never materializes
    the (p, m) Jacobian the linearize pass streams through HBM).
    """
    p = x0.shape[-1]
    eye = jnp.eye(p, dtype=x0.dtype)

    def autodiff_normal_eqs(x):
        # row-major Jacobian (p, m) via linearize: one primal pass, p tangent
        # passes.  Orientation matters on TPU — under vmap a (batch, m, p)
        # Jacobian pads its minor p axis to 128 lanes (~25x HBM at p=5),
        # while (batch, p, m) pads p only to 8 sublanes.
        r, fwd = jax.linearize(residual_fn, x)
        Jr = jax.vmap(fwd)(eye)                             # (p, m)
        return Jr @ Jr.T, Jr @ r, jnp.sum(r * r)

    normal_eqs = normal_eqs_fn if normal_eqs_fn is not None \
        else autodiff_normal_eqs

    def body(s: _LMState):
        # Marquardt scaling: damp by lam * diag(JTJ) for scale invariance.
        # JTJ + positive diagonal is SPD -> unrolled Cholesky (spd_solve);
        # the LU this replaces was ~90% of the LM iteration cost on TPU.
        damp = s.lam * jnp.diagonal(s.jtj) + 1e-12
        delta = spd_solve(s.jtj + damp * eye, s.jtr)
        x_new = s.x - delta
        jtj_new, jtr_new, f_new = normal_eqs(x_new)
        ok = jnp.all(jnp.isfinite(jtj_new)) & jnp.all(jnp.isfinite(jtr_new))
        improved = (f_new < s.f) & jnp.isfinite(f_new) & ok
        x = jnp.where(improved, x_new, s.x)
        f = jnp.where(improved, f_new, s.f)
        jtj = jnp.where(improved, jtj_new, s.jtj)
        jtr = jnp.where(improved, jtr_new, s.jtr)
        lam = jnp.where(improved, s.lam * lam_down, s.lam * lam_up)
        rel_drop = (s.f - f_new) <= tol * (jnp.abs(s.f) + tol)
        step_small = jnp.max(jnp.abs(delta)) <= tol * (
            jnp.max(jnp.abs(s.x)) + tol)
        done = jnp.logical_and(improved,
                               jnp.logical_or(rel_drop, step_small))
        # a rejected step with huge damping means we're pinned at a minimum
        done = jnp.logical_or(done, jnp.logical_and(~improved, s.lam > 1e8))
        return _LMState(x, f, jtj, jtr, lam, s.it + 1, done)

    def cond(s: _LMState):
        return jnp.logical_and(~s.done, s.it < max_iter)

    jtj0, jtr0, f0 = normal_eqs(x0)
    lam0 = jnp.asarray(lam0, x0.dtype)
    state = lax.while_loop(
        cond, body,
        _LMState(x0, f0, jtj0, jtr0, lam0, jnp.asarray(0),
                 jnp.asarray(False)))
    return MinimizeResult(state.x, state.f, state.done, state.it)


def minimize_least_squares(residual_fn: Callable | None, x0: jnp.ndarray,
                           *args, tol: float | None = None,
                           max_iter: int = 100,
                           normal_eqs_fn: Callable | None = None,
                           restarts: int = 0, restart_scale: float = 0.25,
                           restart_key=None) -> MinimizeResult:
    """Batched Levenberg-Marquardt for residual objectives (minimizes
    ``sum(residual_fn(x)**2)``).

    The TPU-native workhorse for every CSS/SSE fit: the normal-equation
    solves are tiny batched MXU matmuls, convergence is per-lane masked, and
    — unlike a BFGS line search — the updates stay well-behaved in float32
    (the production TPU dtype; SURVEY.md §7 hard part #7).

    ``residual_fn(params, *args) -> (m,)`` with ``params (p,)``; ``x0`` may
    carry leading batch dims, vmapped with matching ``args`` dims.  ``tol``
    defaults to a dtype-aware value (1e-10 for f64, 1e-6 for f32).

    ``normal_eqs_fn(params, *args) -> (JᵀJ, Jᵀr, sse)``, when given,
    replaces the autodiff Jacobian pass with a hand-fused one (see
    ``_minimize_lm_one``); ``residual_fn`` is then unused and may be None.

    ``restarts`` / ``restart_scale`` / ``restart_key`` enable the
    multi-start retry path for non-converged or non-finite lanes (see
    :func:`_with_restarts`); ``restarts=0`` (default) is the plain
    single-start solve, bit-for-bit.
    """
    if tol is None:
        tol = 1e-10 if x0.dtype == jnp.float64 else 1e-6

    def solve_one(x0_i, *args_i):
        ne = (lambda x: normal_eqs_fn(x, *args_i)) \
            if normal_eqs_fn is not None else None
        return _minimize_lm_one(
            (lambda x: residual_fn(x, *args_i))
            if residual_fn is not None else None,
            x0_i, tol, max_iter, normal_eqs_fn=ne)

    with _metrics.span("optimize.lm"):
        # the recorder's host reads block on the device work; keeping
        # them inside the span attributes that wall-time to the solver
        res = _solve_with_policy(solve_one, x0, args, restarts,
                                 restart_scale, restart_key)
        return _metrics.observe_minimize("lm", res)


class _NewtonState(NamedTuple):
    x: jnp.ndarray
    f: jnp.ndarray
    g: jnp.ndarray
    h: jnp.ndarray
    lam: jnp.ndarray
    it: jnp.ndarray
    done: jnp.ndarray


def _minimize_newton_one(fn, x0, tol, max_iter, lam0=1e-3,
                         lam_up=10.0, lam_down=0.1):
    """Single-lane damped (Levenberg-style) Newton on a scalar objective;
    designed to be vmapped.  The Hessian comes from autodiff
    (forward-over-reverse) and the step solves the damped system with the
    unrolled small-SPD Cholesky — the same trust-region-flavored state
    machine as :func:`_minimize_lm_one`, with the true Hessian in place of
    the Gauss-Newton approximation.  Quadratic local convergence makes this
    the fast path for small-parameter MLE fits (GARCH/EGARCH) whose
    objectives are not sums of squares."""
    p = x0.shape[-1]
    eye = jnp.eye(p, dtype=x0.dtype)
    value_and_grad = jax.value_and_grad(fn)
    hess = jax.hessian(fn)

    def fgh(x):
        # value_and_grad shares the primal pass; the Hessian trace is the
        # only extra recurrence evaluation per iteration
        f, g = value_and_grad(x)
        return f, g, hess(x)

    def body(s: _NewtonState):
        # damp toward gradient descent when the Hessian is indefinite or the
        # step fails; |diag| keeps the damping positive either way
        damp = s.lam * (jnp.abs(jnp.diagonal(s.h)) + 1e-8)
        delta = spd_solve(s.h + damp * eye, s.g)
        x_new = s.x - delta
        f_new, g_new, h_new = fgh(x_new)
        ok = jnp.isfinite(f_new) & jnp.all(jnp.isfinite(g_new)) \
            & jnp.all(jnp.isfinite(h_new)) & jnp.all(jnp.isfinite(delta))
        improved = (f_new < s.f) & ok
        x = jnp.where(improved, x_new, s.x)
        f = jnp.where(improved, f_new, s.f)
        g = jnp.where(improved, g_new, s.g)
        h = jnp.where(improved, h_new, s.h)
        lam = jnp.where(improved, s.lam * lam_down, s.lam * lam_up)
        rel_drop = (s.f - f_new) <= tol * (jnp.abs(s.f) + tol)
        step_small = jnp.max(jnp.abs(delta)) <= tol * (
            jnp.max(jnp.abs(s.x)) + tol)
        done = improved & (rel_drop | step_small)
        done = done | (~improved & (s.lam > 1e10))
        return _NewtonState(x, f, g, h, lam, s.it + 1, done)

    def cond(s: _NewtonState):
        return jnp.logical_and(~s.done, s.it < max_iter)

    f0, g0, h0 = fgh(x0)
    state = lax.while_loop(
        cond, body,
        _NewtonState(x0, f0, g0, h0, jnp.asarray(lam0, x0.dtype),
                     jnp.asarray(0), jnp.asarray(False)))
    return MinimizeResult(state.x, state.f, state.done, state.it)


def minimize_newton(fn: Callable, x0: jnp.ndarray, *args,
                    tol: float | None = None,
                    max_iter: int = 100,
                    restarts: int = 0, restart_scale: float = 0.25,
                    restart_key=None) -> MinimizeResult:
    """Batched damped Newton for smooth scalar objectives with *small*
    parameter counts (p ≤ ~16, where the unrolled Cholesky solve applies).

    ``fn(params, *args) -> scalar``; ``x0 (..., p)`` with leading batch dims
    vmapped (matching ``args`` dims).  ``tol`` defaults dtype-aware like
    :func:`minimize_least_squares`.  ``restarts`` enables the multi-start
    retry path (see :func:`_with_restarts`).
    """
    if tol is None:
        tol = 1e-10 if x0.dtype == jnp.float64 else 1e-6

    def solve_one(x0_i, *args_i):
        return _minimize_newton_one(lambda x: fn(x, *args_i), x0_i,
                                    tol, max_iter)

    with _metrics.span("optimize.newton"):
        # the recorder's host reads block on the device work; keeping
        # them inside the span attributes that wall-time to the solver
        res = _solve_with_policy(solve_one, x0, args, restarts,
                                 restart_scale, restart_key)
        return _metrics.observe_minimize("newton", res)


def _project(x, lower, upper):
    return jnp.clip(x, lower, upper)


class _BoxState(NamedTuple):
    x: jnp.ndarray
    f: jnp.ndarray
    g: jnp.ndarray
    it: jnp.ndarray
    done: jnp.ndarray


def _minimize_box_one(fn, x0, lower, upper, tol=1e-10, max_iter=500,
                      max_backtracks=40, value_and_grad_fn=None):
    """Single-lane projected gradient with Armijo backtracking.

    Designed to be vmapped: under ``vmap`` the ``while_loop`` keeps stepping
    until every lane's mask is set, and finished lanes hold position — the
    convergence-mask batching strategy from SURVEY.md §7.

    ``value_and_grad_fn(x) -> (f, g)`` overrides reverse-mode autodiff for
    objectives with a cheap fused forward pass (e.g. the Holt-Winters
    tangent recurrence, which otherwise stores every scan step's carry for
    the backward sweep).
    """
    value_and_grad = value_and_grad_fn if value_and_grad_fn is not None \
        else jax.value_and_grad(fn)
    # project BEFORE the initial evaluation: an out-of-box x0 would
    # otherwise pair the projected starting point with the unprojected
    # point's value and gradient
    x0 = _project(x0, lower, upper)
    f0, g0 = value_and_grad(x0)

    def cond(s: _BoxState):
        return jnp.logical_and(~s.done, s.it < max_iter)

    def body(s: _BoxState):
        # Backtracking line search on the projected-gradient arc:
        # x(t) = P(x - t g); accept when Armijo decrease holds.  Each trial
        # evaluates value-AND-grad so the accepted point's gradient rides
        # along into the next iteration — the common first-trial-accepts
        # case then costs one fused pass instead of a value pass plus a
        # separate full gradient pass over the recurrence.
        def bt_cond(carry):
            t, k, accepted = carry[0], carry[1], carry[2]
            return jnp.logical_and(~accepted, k < max_backtracks)

        def bt_body(carry):
            t, k = carry[0], carry[1]
            x_new = _project(s.x - t * s.g, lower, upper)
            f_new, g_new = value_and_grad(x_new)
            decrease = jnp.dot(s.g, s.x - x_new)
            ok = f_new <= s.f - 1e-4 * decrease
            ok = jnp.logical_and(ok, jnp.isfinite(f_new))
            return (t * 0.5, k + 1, ok, x_new, f_new, g_new)

        init = (jnp.asarray(1.0, s.x.dtype), 0, False, s.x, s.f, s.g)
        _, _, accepted, x_new, f_new, g_new = \
            lax.while_loop(bt_cond, bt_body, init)

        # converged if the projected-gradient step is tiny, the objective
        # stalls, or no Armijo step was found (local minimum to tolerance)
        step_norm = jnp.max(jnp.abs(x_new - s.x))
        f_stall = jnp.abs(f_new - s.f) <= tol * (jnp.abs(s.f) + tol)
        done = jnp.logical_or(step_norm <= tol,
                              jnp.logical_or(f_stall, ~accepted))
        x_next = jnp.where(accepted, x_new, s.x)
        f_next = jnp.where(accepted, f_new, s.f)
        g_next = jnp.where(accepted, g_new, s.g)
        return _BoxState(x_next, f_next, g_next, s.it + 1, done)

    final = lax.while_loop(
        cond, body, _BoxState(x0, f0, g0, jnp.asarray(0), jnp.asarray(False)))
    return MinimizeResult(final.x, final.f, final.done, final.it)


def minimize_box(fn: Callable, x0: jnp.ndarray, lower, upper, *args,
                 tol: float = 1e-10, max_iter: int = 500,
                 value_and_grad_fn: Callable | None = None,
                 restarts: int = 0, restart_scale: float = 0.25,
                 restart_key=None) -> MinimizeResult:
    """Batched box-constrained minimization (the BOBYQA replacement).

    ``fn(params, *args) -> scalar``; ``x0 (..., p)``; ``lower``/``upper``
    broadcastable to ``(p,)``.  Leading dims of ``x0`` (and of each ``args``
    entry) are vmapped.  ``value_and_grad_fn(params, *args) -> (f, g)``
    optionally replaces reverse-mode autodiff (see ``_minimize_box_one``).
    ``restarts`` enables the multi-start retry path (see
    :func:`_with_restarts`; jittered inits are re-projected into the box
    by the solver's own initial projection).
    """
    lower = jnp.broadcast_to(jnp.asarray(lower, x0.dtype), x0.shape[-1:])
    upper = jnp.broadcast_to(jnp.asarray(upper, x0.dtype), x0.shape[-1:])

    def solve_one(x0_i, *args_i):
        vag = (lambda p: value_and_grad_fn(p, *args_i)) \
            if value_and_grad_fn is not None else None
        return _minimize_box_one(lambda p: fn(p, *args_i), x0_i, lower, upper,
                                 tol=tol, max_iter=max_iter,
                                 value_and_grad_fn=vag)

    with _metrics.span("optimize.box"):
        # the recorder's host reads block on the device work; keeping
        # them inside the span attributes that wall-time to the solver
        res = _solve_with_policy(solve_one, x0, args, restarts,
                                 restart_scale, restart_key)
        return _metrics.observe_minimize("box", res)
