"""Residual-based anomaly detection over fitted panels, batched.

Beyond the reference's inventory (no anomaly surface exists anywhere in
``/root/reference``): the capability follows ARIMA_PLUS's model-based
recipe (PAPERS.md, "Large-scale ... In-Database Time Series Forecasting
and Anomaly Detection") — fit any model family, score each observation
by its one-step prediction residual against a per-series noise scale,
and flag points outside the confidence band.

Composes with every model in the package: anything exposing fitted
one-step values works (``arima_model.forecast(ts, 1)[..., :n]``,
``holt_winters_model.add_time_dependent_effects``, the EWMA smooth, a
``decompose`` trend+season reconstruction, ...).  All math is
elementwise/batched — no scans, shards over the series axis like any
panel op.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..models.base import normal_quantile


class AnomalyResult(NamedTuple):
    """``is_anomaly``/``score`` have the input's shape; ``sigma``/
    ``center`` drop the time axis.  ``score`` is the absolute centered
    residual in sigma units, zeroed inside the burn-in window — so
    ``score > threshold_z`` ⇔ flagged holds everywhere (warm-up
    artifacts can't re-enter through a consumer re-thresholding the
    scores)."""
    is_anomaly: jnp.ndarray
    score: jnp.ndarray
    sigma: jnp.ndarray
    center: jnp.ndarray
    threshold_z: jnp.ndarray


def detect_anomalies(values: jnp.ndarray, fitted: jnp.ndarray,
                     conf: float = 0.99, robust: bool = True,
                     burn_in: int = 0) -> AnomalyResult:
    """Flag observations whose residual ``values - fitted`` falls outside
    the two-sided ``conf`` band of the per-series noise distribution.

    ``robust=True`` (default) estimates the noise scale by the median
    absolute deviation (scaled by 1.4826 to be sigma-consistent under
    Gaussian noise) so the anomalies being hunted do not inflate the
    threshold that hunts them; ``robust=False`` uses the plain standard
    deviation (ARIMA_PLUS-style prediction-interval semantics, matching
    the ``forecast_interval`` sigmas elsewhere in the package).

    ``burn_in`` masks the first observations from BOTH the scale estimate
    and the flags — model warm-up positions (a seasonal model's first
    ``period``, an ARIMA's first ``d + max(p, q)``) are fit artifacts,
    not anomalies.

    ``values``/``fitted`` are ``(..., n)``; returns :class:`AnomalyResult`.
    """
    # promote integer panels (counts are a common anomaly-detection
    # input): erfinv of an int-cast conf would give threshold 0 and a
    # float fitted view would truncate toward zero
    dtype = jnp.result_type(jnp.asarray(values).dtype, jnp.float32)
    values = jnp.asarray(values, dtype)
    fitted = jnp.asarray(fitted, dtype)
    if fitted.shape != values.shape:
        raise ValueError(
            f"fitted must match values' shape {values.shape}; got "
            f"{fitted.shape} — pass the one-step fitted view, not a "
            f"future forecast")
    n = values.shape[-1]
    if not 0 <= burn_in < n:
        raise ValueError(f"burn_in must be in [0, {n}); got {burn_in}")

    resid = values - fitted
    t_ok = jnp.arange(n) >= burn_in
    masked = jnp.where(t_ok, resid, jnp.nan)

    center = jnp.nanmedian(masked, axis=-1) if robust \
        else jnp.nanmean(masked, axis=-1)
    dev = masked - center[..., None]
    if robust:
        mad = 1.4826 * jnp.nanmedian(jnp.abs(dev), axis=-1)
        # the MAD collapses to 0 whenever >= 50% of residuals tie at the
        # median (sparse/quantized panels — e.g. mostly-zero counts),
        # which would silently suppress every flag including gross
        # spikes; fall back to the std estimate for exactly those lanes
        # (a truly constant-residual lane still gets sigma 0 from it)
        std = jnp.sqrt(jnp.nanmean(dev * dev, axis=-1))
        sigma = jnp.where(mad > 0, mad, std)
    else:
        sigma = jnp.sqrt(jnp.nanmean(dev * dev, axis=-1))

    z = normal_quantile(conf, dtype)
    # a constant-residual series has sigma 0: nothing is anomalous by its
    # own (degenerate) noise model, rather than everything
    safe = jnp.where(sigma > 0, sigma, jnp.inf)
    score = jnp.where(t_ok,
                      jnp.abs(resid - center[..., None]) / safe[..., None],
                      jnp.zeros((), dtype))
    return AnomalyResult(score > z, score, sigma, center,
                         jnp.broadcast_to(z, sigma.shape))
