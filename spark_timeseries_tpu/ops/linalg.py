"""Batched dense linear algebra for model fitting.

Replaces the reference's Breeze / Commons-Math ``OLSMultipleLinearRegression``
scalar path (ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/Autoregression.scala:47-50``
and the OLS uses across stats/models) with QR-based least squares batched over
a leading series axis — the MXU does the heavy lifting for the whole panel at
once.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular


class OLSResult(NamedTuple):
    """Batched OLS fit artifacts (shapes with leading batch dims ``...``)."""
    beta: jnp.ndarray        # (..., p)   coefficients (intercept first if added)
    residuals: jnp.ndarray   # (..., n)
    fitted: jnp.ndarray      # (..., n)
    sigma2: jnp.ndarray      # (...,)     residual variance (n - p denominator)
    xtx_inv: jnp.ndarray     # (..., p, p) (X'X)^-1 for standard errors / tests


def _maybe_add_intercept(X: jnp.ndarray, add_intercept: bool) -> jnp.ndarray:
    """Prepend a ones column (reference convention: intercept first)."""
    if not add_intercept:
        return X
    ones = jnp.ones((*X.shape[:-1], 1), dtype=X.dtype)
    return jnp.concatenate([ones, X], axis=-1)


def ols(X: jnp.ndarray, y: jnp.ndarray, add_intercept: bool = False) -> OLSResult:
    """Least squares via batched QR: ``X (..., n, p)``, ``y (..., n)``."""
    X = _maybe_add_intercept(X, add_intercept)
    n, p = X.shape[-2], X.shape[-1]
    q, r = jnp.linalg.qr(X)
    qty = jnp.einsum("...np,...n->...p", q, y)
    beta = solve_triangular(r, qty, lower=False)
    fitted = jnp.einsum("...np,...p->...n", X, beta)
    resid = y - fitted
    dof = max(n - p, 1)
    sigma2 = jnp.sum(resid * resid, axis=-1) / dof
    r_inv = solve_triangular(r, jnp.broadcast_to(jnp.eye(p, dtype=X.dtype),
                                                 r.shape), lower=False)
    xtx_inv = jnp.einsum("...ij,...kj->...ik", r_inv, r_inv)
    return OLSResult(beta, resid, fitted, sigma2, xtx_inv)


def ols_gram(Xs: jnp.ndarray, y: jnp.ndarray,
             add_intercept: bool = False) -> OLSResult:
    """Least squares from a *stacked* design ``Xs (..., p, n)`` (features on
    the second-minor axis — see :func:`~spark_timeseries_tpu.ops.lag.lag_stack`)
    via the normal equations ``(Xs Xsᵀ) β = Xs y``.

    The TPU-scale path for lag designs: the gram products contract over the
    long ``n`` axis (well-tiled MXU matmuls) and never materialize an
    ``(..., n, p)`` matrix whose minor-axis padding would inflate HBM ~25×
    at small ``p``.  QR on the row-major design (:func:`ols`) remains the
    general path; gram solves lose ~half the mantissa on conditioning, which
    the well-conditioned lag designs (p ≤ ~12) tolerate in both f32 and f64.
    """
    if add_intercept:
        ones = jnp.ones((*Xs.shape[:-2], 1, Xs.shape[-1]), Xs.dtype)
        Xs = jnp.concatenate([ones, Xs], axis=-2)
    n, p = Xs.shape[-1], Xs.shape[-2]
    N = jnp.einsum("...pn,...qn->...pq", Xs, Xs)
    b = jnp.einsum("...pn,...n->...p", Xs, y)
    xtx_inv = jnp.linalg.inv(N)
    beta = jnp.einsum("...pq,...q->...p", xtx_inv, b)
    fitted = jnp.einsum("...pn,...p->...n", Xs, beta)
    resid = y - fitted
    dof = max(n - p, 1)
    sigma2 = jnp.sum(resid * resid, axis=-1) / dof
    return OLSResult(beta, resid, fitted, sigma2, xtx_inv)


def ols_beta(X: jnp.ndarray, y: jnp.ndarray, add_intercept: bool = False) -> jnp.ndarray:
    """Coefficients only: QR + one triangular solve, skipping residual stats."""
    X = _maybe_add_intercept(X, add_intercept)
    q, r = jnp.linalg.qr(X)
    qty = jnp.einsum("...np,...n->...p", q, y)
    return solve_triangular(r, qty, lower=False)


def t_statistics(res: OLSResult) -> jnp.ndarray:
    """Per-coefficient t statistics ``beta / se(beta)``."""
    se = jnp.sqrt(res.sigma2[..., None]
                  * jnp.diagonal(res.xtx_inv, axis1=-2, axis2=-1))
    return res.beta / se


def r_squared(res: OLSResult, y: jnp.ndarray) -> jnp.ndarray:
    """Coefficient of determination of the fit."""
    ss_res = jnp.sum(res.residuals ** 2, axis=-1)
    ss_tot = jnp.sum((y - jnp.mean(y, axis=-1, keepdims=True)) ** 2, axis=-1)
    return 1.0 - ss_res / ss_tot
