"""Batched dense linear algebra for model fitting.

Replaces the reference's Breeze / Commons-Math ``OLSMultipleLinearRegression``
scalar path (ref ``/root/reference/src/main/scala/com/cloudera/sparkts/models/Autoregression.scala:47-50``
and the OLS uses across stats/models) with QR-based least squares batched over
a leading series axis — the MXU does the heavy lifting for the whole panel at
once.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax.scipy.linalg import cho_solve, solve_triangular

# Batched tiny SPD systems ((..., p, p) with p ≤ ~16) dominate every fit's
# inner loop: LM normal equations, gram-matrix OLS, the auto-fit candidate
# grid.  XLA lowers ``jnp.linalg.solve``/``inv`` on TPU to a pivoted LU with
# dynamic control flow — measured 48ms per solve at (32768, 5, 5) f32 on
# v5e, ~15x slower than a fully unrolled Cholesky (3.3ms) whose ops are just
# fused elementwise arithmetic over the batch.  Everything here routes small
# SPD systems through the unrolled path.
_SPD_UNROLL_MAX = 16


def _chol_unrolled(A: jnp.ndarray, p: int):
    """Lower Cholesky factor of SPD ``A (..., p, p)`` as a list-of-lists of
    ``(...)`` lanes — fully unrolled, no control flow."""
    L = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            s = A[..., i, j]
            for k in range(j):
                s = s - L[i][k] * L[j][k]
            L[i][j] = jnp.sqrt(s) if i == j else s / L[j][j]
    return L


def _fwd_sub(L, b_cols, p: int):
    """Solve ``L y = b`` for each entry of ``b_cols`` (list of ``(...)``)."""
    y = [None] * p
    for i in range(p):
        s = b_cols[i]
        for k in range(i):
            s = s - L[i][k] * y[k]
        y[i] = s / L[i][i]
    return y


def _back_sub(L, y, p: int):
    """Solve ``Lᵀ x = y`` (list form)."""
    x = [None] * p
    for i in reversed(range(p)):
        s = y[i]
        for k in range(i + 1, p):
            s = s - L[k][i] * x[k]
        x[i] = s / L[i][i]
    return x


def spd_solve(A: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Solve SPD ``A (..., p, p) @ x = b (..., p)`` by Cholesky.

    Unrolled elementwise arithmetic for ``p ≤ 16`` (the TPU fast path);
    batched ``cho_solve`` beyond.  A non-SPD lane yields NaNs (sqrt of a
    negative pivot) rather than an LU's garbage solution — callers already
    quarantine non-finite lanes.
    """
    p = A.shape[-1]
    if p == 0:
        return jnp.zeros_like(b)
    if p > _SPD_UNROLL_MAX:
        return cho_solve((jnp.linalg.cholesky(A), True),
                         b[..., None])[..., 0]
    L = _chol_unrolled(A, p)
    x = _back_sub(L, _fwd_sub(L, [b[..., i] for i in range(p)], p), p)
    return jnp.stack(x, axis=-1)


def spd_inverse(A: jnp.ndarray) -> jnp.ndarray:
    """Inverse of SPD ``A (..., p, p)`` via the unrolled Cholesky:
    ``A⁻¹ = L⁻ᵀ L⁻¹`` with the triangular inverse unrolled for ``p ≤ 16``."""
    p = A.shape[-1]
    if p == 0 or p > _SPD_UNROLL_MAX:
        eye = jnp.broadcast_to(jnp.eye(p, dtype=A.dtype), A.shape)
        return cho_solve((jnp.linalg.cholesky(A), True), eye)
    L = _chol_unrolled(A, p)
    # Y = L^-1 (lower triangular), column by column
    Y = [[None] * p for _ in range(p)]
    for j in range(p):
        Y[j][j] = 1.0 / L[j][j]
        for i in range(j + 1, p):
            s = L[i][j] * Y[j][j]
            for k in range(j + 1, i):
                s = s + L[i][k] * Y[k][j]
            Y[i][j] = -s / L[i][i]
    rows = []
    for i in range(p):
        row = []
        for j in range(p):
            s = 0.0
            for k in range(max(i, j), p):
                s = s + Y[k][i] * Y[k][j]
            row.append(s)
        rows.append(jnp.stack(row, axis=-1))
    return jnp.stack(rows, axis=-2)


class OLSResult(NamedTuple):
    """Batched OLS fit artifacts (shapes with leading batch dims ``...``)."""
    beta: jnp.ndarray        # (..., p)   coefficients (intercept first if added)
    residuals: jnp.ndarray   # (..., n)
    fitted: jnp.ndarray      # (..., n)
    sigma2: jnp.ndarray      # (...,)     residual variance (n - p denominator)
    xtx_inv: jnp.ndarray     # (..., p, p) (X'X)^-1 for standard errors / tests


def _maybe_add_intercept(X: jnp.ndarray, add_intercept: bool) -> jnp.ndarray:
    """Prepend a ones column (reference convention: intercept first)."""
    if not add_intercept:
        return X
    ones = jnp.ones((*X.shape[:-1], 1), dtype=X.dtype)
    return jnp.concatenate([ones, X], axis=-1)


def ols(X: jnp.ndarray, y: jnp.ndarray, add_intercept: bool = False) -> OLSResult:
    """Least squares via batched QR: ``X (..., n, p)``, ``y (..., n)``."""
    X = _maybe_add_intercept(X, add_intercept)
    n, p = X.shape[-2], X.shape[-1]
    q, r = jnp.linalg.qr(X)
    qty = jnp.einsum("...np,...n->...p", q, y)
    beta = solve_triangular(r, qty, lower=False)
    fitted = jnp.einsum("...np,...p->...n", X, beta)
    resid = y - fitted
    dof = max(n - p, 1)
    sigma2 = jnp.sum(resid * resid, axis=-1) / dof
    r_inv = solve_triangular(r, jnp.broadcast_to(jnp.eye(p, dtype=X.dtype),
                                                 r.shape), lower=False)
    xtx_inv = jnp.einsum("...ij,...kj->...ik", r_inv, r_inv)
    return OLSResult(beta, resid, fitted, sigma2, xtx_inv)


def ols_gram(Xs: jnp.ndarray, y: jnp.ndarray,
             add_intercept: bool = False,
             row_weights: jnp.ndarray | None = None) -> OLSResult:
    """Least squares from a *stacked* design ``Xs (..., p, n)`` (features on
    the second-minor axis — see :func:`~spark_timeseries_tpu.ops.lag.lag_stack`)
    via the normal equations ``(Xs Xsᵀ) β = Xs y``.

    The TPU-scale path for lag designs: the gram products contract over the
    long ``n`` axis (well-tiled MXU matmuls) and never materialize an
    ``(..., n, p)`` matrix whose minor-axis padding would inflate HBM ~25×
    at small ``p``.  QR on the row-major design (:func:`ols`) remains the
    general path; gram solves lose ~half the mantissa on conditioning, which
    the well-conditioned lag designs (p ≤ ~12) tolerate in both f32 and f64.

    ``row_weights (..., n)`` of 0/1 restricts the solve to the weighted
    rows — exactly OLS on the subset (ragged-panel fits: rows whose lag
    window leaves a lane's valid window get weight 0).  Residual/fitted
    outputs keep full length; ``sigma2``'s denominator counts live rows.
    """
    if add_intercept:
        ones = jnp.ones((*Xs.shape[:-2], 1, Xs.shape[-1]), Xs.dtype)
        Xs = jnp.concatenate([ones, Xs], axis=-2)
    n, p = Xs.shape[-1], Xs.shape[-2]
    if row_weights is None:
        Xw, yw = Xs, y
        dof = jnp.asarray(max(n - p, 1), Xs.dtype)
    else:
        w = jnp.asarray(row_weights, Xs.dtype)
        Xw = Xs * w[..., None, :]
        yw = y * w
        dof = jnp.maximum(jnp.sum(w, axis=-1) - p, 1.0)
    N = jnp.einsum("...pn,...qn->...pq", Xw, Xs)
    b = jnp.einsum("...pn,...n->...p", Xw, y)
    xtx_inv = spd_inverse(N)    # gram matrices are SPD: unrolled Cholesky
    beta = jnp.einsum("...pq,...q->...p", xtx_inv, b)
    fitted = jnp.einsum("...pn,...p->...n", Xs, beta)
    if row_weights is None:
        resid = y - fitted
    else:
        resid = (y - fitted) * w       # dead rows carry garbage y: zero them
    sigma2 = jnp.sum(resid * resid, axis=-1) / dof
    return OLSResult(beta, resid, fitted, sigma2, xtx_inv)


def ols_beta(X: jnp.ndarray, y: jnp.ndarray, add_intercept: bool = False) -> jnp.ndarray:
    """Coefficients only: QR + one triangular solve, skipping residual stats."""
    X = _maybe_add_intercept(X, add_intercept)
    q, r = jnp.linalg.qr(X)
    qty = jnp.einsum("...np,...n->...p", q, y)
    return solve_triangular(r, qty, lower=False)


def t_statistics(res: OLSResult) -> jnp.ndarray:
    """Per-coefficient t statistics ``beta / se(beta)``."""
    se = jnp.sqrt(res.sigma2[..., None]
                  * jnp.diagonal(res.xtx_inv, axis1=-2, axis2=-1))
    return res.beta / se


def r_squared(res: OLSResult, y: jnp.ndarray) -> jnp.ndarray:
    """Coefficient of determination of the fit."""
    ss_res = jnp.sum(res.residuals ** 2, axis=-1)
    ss_tot = jnp.sum((y - jnp.mean(y, axis=-1, keepdims=True)) ** 2, axis=-1)
    return 1.0 - ss_res / ss_tot
