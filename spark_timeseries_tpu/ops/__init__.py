"""Vectorized series ops (L1/L2): lag matrices, univariate kernels, resample,
OLS, batched optimizers, and sequence-parallel recurrences."""

from . import optimize, scan_parallel
from .anomaly import AnomalyResult, detect_anomalies
from .decompose import Decomposition, decompose
from .lag import lag_matrix, lag_matrix_multi
from .linalg import OLSResult, ols, ols_beta, r_squared, t_statistics
from .resample import bucket_assignments, resample
from .scan_parallel import (ar1_filter, ewma_smooth, garch_variance,
                            linear_recurrence)
from .univariate import (
    autocorr,
    differences_at_lag,
    differences_of_order_d,
    downsample,
    fill_linear,
    fill_nearest,
    fill_next,
    fill_previous,
    fill_spline,
    fill_value,
    fill_with_default,
    fill_zero,
    fillts,
    first_not_nan,
    inverse_differences_at_lag,
    inverse_differences_of_order_d,
    last_not_nan,
    price2ret,
    quotients,
    roll_mean,
    roll_sum,
    trim_leading,
    trim_trailing,
    upsample,
)

__all__ = [
    "optimize", "scan_parallel",
    "Decomposition", "decompose",
    "linear_recurrence", "ewma_smooth", "ar1_filter", "garch_variance",
    "lag_matrix", "lag_matrix_multi",
    "OLSResult", "ols", "ols_beta", "r_squared", "t_statistics",
    "bucket_assignments", "resample",
    "autocorr", "differences_at_lag", "differences_of_order_d", "downsample",
    "fill_linear", "fill_nearest", "fill_next", "fill_previous", "fill_spline",
    "fill_value", "fill_with_default", "fill_zero", "fillts", "first_not_nan",
    "inverse_differences_at_lag", "inverse_differences_of_order_d",
    "last_not_nan", "price2ret", "quotients", "roll_mean", "roll_sum",
    "trim_leading", "trim_trailing", "upsample",
]
