"""Native (C++) runtime helpers, compiled on demand and always optional.

The TPU compute path is JAX/XLA/Pallas; the host runtime around it — here,
the persistence tier's text codec — goes native where the reference's does
(the JVM's Double.toString/parseDouble under ``TimeSeriesRDD.scala:498-509``
are C-speed codecs; CPython's equivalents are not).  Build model:

- source ships in the package (``fastcsv.cpp``); the shared object is
  compiled ONCE per source hash into ``~/.cache/spark_timeseries_tpu/``
  (or ``STS_NATIVE_CACHE``) with plain ``g++ -O3 -shared -fPIC`` — no
  pybind11, no build-system dependency; the ABI is C + ctypes;
- every caller keeps a pure-Python fallback: no compiler, a failed build,
  or ``STS_NO_NATIVE=1`` simply means the slow path (tests pin both paths
  to identical bytes);
- thread-safe and race-safe across processes (atomic rename into place).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "fastcsv.cpp")
_lock = threading.Lock()
_cached: dict = {}


def _cache_dir() -> str:
    base = os.environ.get("STS_NATIVE_CACHE")
    if base:
        return base
    xdg = os.environ.get("XDG_CACHE_HOME",
                         os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(xdg, "spark_timeseries_tpu")


def _build(src: str, tag: str) -> Optional[str]:
    """Compile ``src`` into the cache (atomic rename); None on failure."""
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out_dir = _cache_dir()
    so_path = os.path.join(out_dir, f"{tag}-{digest}.so")
    if os.path.exists(so_path):
        return so_path
    try:
        os.makedirs(out_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=out_dir)
        os.close(fd)
        res = subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp],
            capture_output=True, timeout=120)
        if res.returncode != 0:
            os.unlink(tmp)
            return None
        os.replace(tmp, so_path)          # atomic: racing builders agree
        return so_path
    except Exception:                     # noqa: BLE001 — fall back to Python
        return None


def fastcsv() -> Optional[ctypes.CDLL]:
    """The fastcsv shared library, building it on first use; None when
    native is unavailable or disabled (``STS_NO_NATIVE=1``)."""
    if os.environ.get("STS_NO_NATIVE") == "1":
        return None
    with _lock:
        if "fastcsv" in _cached:
            return _cached["fastcsv"]
    # build OUTSIDE the lock (STS103): _build runs g++ for up to 120s,
    # and holding _lock across it would stall every thread that merely
    # wants the (possibly None) handle.  A duplicate concurrent build is
    # harmless — racing builders agree via the atomic rename — and the
    # publish below prefers a non-None result, the same
    # compile-outside-the-lock idiom as the fit engine's executable cache
    lib = None
    so = _build(_SRC, "fastcsv")
    if so is not None:
        try:
            lib = ctypes.CDLL(so)
            LL = ctypes.c_longlong
            lib.sts_format_csv.restype = LL
            lib.sts_format_csv.argtypes = [
                ctypes.c_char_p, LL, ctypes.c_void_p, LL, LL,
                ctypes.c_void_p]
            lib.sts_parse_csv.restype = LL
            lib.sts_parse_csv.argtypes = [
                ctypes.c_char_p, LL, LL, LL, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.POINTER(LL)]
        except Exception:             # noqa: BLE001
            lib = None
    return _publish(lib)


def _publish(lib: Optional[ctypes.CDLL]) -> Optional[ctypes.CDLL]:
    """First NON-None result wins: a racing builder whose g++ timed out
    (lib=None) must not pin the failure over a concurrent success.  A
    lone failure still caches None, so a toolchain-less host pays one
    build attempt per process, not one per call."""
    with _lock:
        if _cached.get("fastcsv") is None:
            _cached["fastcsv"] = lib
        return _cached["fastcsv"]
