// Native CSV codec for the panel persistence tier (io.save_csv/load_csv).
//
// The reference's CSV tier rides the JVM's native text machinery
// (TimeSeriesRDD.scala:498-509 saveAsCsv / :750-764 timeSeriesRDDFromCsv:
// Scala Double.toString and java.lang.Double.parseDouble are C-speed
// shortest-repr codecs under the hood).  The Python-side equivalents
// (np.savetxt's per-row %-formatting loop, pandas' round_trip parser)
// measured ~9-12 s EACH for a 100k x 64 panel — so this file does the two
// O(rows x cols) jobs natively:
//
//   sts_format_csv: double -> shortest round-trip decimal via
//     std::to_chars (C++17 charconv; correctly rounded, locale-free),
//     assembling the whole data.csv buffer (key,v0,...,vN lines) in one
//     pass.
//   sts_parse_csv: the inverse via std::from_chars, plus the same
//     RFC-4180-aware key scan io._split_key implements (quoted keys with
//     doubled quotes; malformed quoting falls back to the bare first-comma
//     split, matching the reference loader's behavior on raw keys that
//     merely start with a quote).
//
// Loud-failure contract (identical to the Python loader): a row whose
// field count differs from the first row's, or any field that is not a
// well-formed double (empty fields included), aborts the parse with a
// negative code — silent NaN-filling of corrupt files is how data loss
// hides.  Real NaNs travel as the literal token "nan" (from_chars parses
// nan/inf/-inf case-insensitively).
//
// Compiled on demand by spark_timeseries_tpu.native (g++ -O3 -shared);
// every caller falls back to the pure-Python path when the toolchain is
// absent, so the .so is an accelerator, never a requirement.

#include <charconv>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// longest shortest-repr double: -2.2250738585072014e-308 (24 chars)
constexpr int kMaxNum = 32;

inline const char* find_newline(const char* p, const char* end) {
    const void* nl = memchr(p, '\n', static_cast<size_t>(end - p));
    return nl ? static_cast<const char*>(nl) : end;
}

// RFC-4180-aware key scan, mirroring io._split_key: returns the end of
// the raw key token (quotes included for quoted keys) and sets *rest to
// the first character of the numeric payload.  Malformed quoting falls
// back to the bare first-comma split.
inline const char* scan_key(const char* ls, const char* le,
                            const char** rest) {
    if (ls < le && *ls == '"') {
        const char* i = ls + 1;
        while (i < le) {
            if (*i == '"') {
                if (i + 1 < le && i[1] == '"') { i += 2; continue; }
                if (i + 1 == le || i[1] == ',') {      // well-formed
                    *rest = (i + 1 == le) ? le : i + 2;
                    return i + 1;
                }
                break;                                  // malformed
            }
            ++i;
        }
    }
    const void* c = memchr(ls, ',', static_cast<size_t>(le - ls));
    if (!c) { *rest = le; return le; }
    const char* comma = static_cast<const char*>(c);
    *rest = comma + 1;
    return comma;
}

}  // namespace

extern "C" {

// Build the whole data.csv: keys are pre-escaped, '\n'-joined (rows of
// them); values row-major (rows x cols).  out must hold at least
// keys_len + rows * (cols * (kMaxNum + 1) + 2) bytes.  Returns bytes
// written, or -1 on a keys/rows count mismatch (fewer keys than rows) /
// formatting failure — the C ABI fails loudly even if a future caller
// drops save_csv's Python-side shape check.
long long sts_format_csv(const char* keys, long long keys_len,
                         const double* values, long long rows,
                         long long cols, char* out) {
    const char* kp = keys;
    const char* kend = keys + keys_len;
    bool keys_exhausted = false;
    char* o = out;
    for (long long r = 0; r < rows; ++r) {
        // the previous row consumed the blob's last key (no newline
        // followed it), so this row would silently get an empty key
        if (keys_exhausted) return -1;
        const char* knl = find_newline(kp, kend);
        memcpy(o, kp, static_cast<size_t>(knl - kp));
        o += knl - kp;
        if (knl == kend) keys_exhausted = true;
        kp = knl < kend ? knl + 1 : kend;
        const double* row = values + r * cols;
        for (long long c = 0; c < cols; ++c) {
            *o++ = ',';
            auto res = std::to_chars(o, o + kMaxNum, row[c]);
            if (res.ec != std::errc()) return -1;
            o = res.ptr;
        }
        *o++ = '\n';
    }
    return o - out;
}

// Parse data.csv text into values (capacity rows_cap x cols) and
// key_spans (rows_cap x 2, [start, end) byte offsets of each raw key
// token).  Empty lines are skipped; a trailing '\r' per line is
// tolerated.  Returns the number of rows parsed, or a negative code:
//   -1  field is not a well-formed double (empty fields included);
//       well-formed tokens beyond double range do NOT error: overflow
//       parses as +/-inf and underflow as (+/-)0, matching the pandas
//       round_trip fallback codec (ADVICE r5)
//   -2  a row's field count differs from `cols`
//   -4  more than rows_cap data rows
// On error, err_row receives the offending 0-based data-row index.
long long sts_parse_csv(const char* text, long long len, long long rows_cap,
                        long long cols, double* values,
                        long long* key_spans, long long* err_row) {
    const char* p = text;
    const char* end = text + len;
    long long r = 0;
    while (p < end) {
        const char* nl = find_newline(p, end);
        const char* le = nl;
        if (le > p && le[-1] == '\r') --le;
        if (le == p) { p = nl + 1; continue; }          // blank line
        if (r >= rows_cap) { *err_row = r; return -4; }
        const char* rest;
        const char* ke = scan_key(p, le, &rest);
        key_spans[2 * r] = p - text;
        key_spans[2 * r + 1] = ke - text;
        double* row = values + r * cols;
        long long c = 0;
        const char* f = rest;
        while (true) {
            const void* cm = memchr(f, ',', static_cast<size_t>(le - f));
            const char* fe = cm ? static_cast<const char*>(cm) : le;
            if (c >= cols) { *err_row = r; return -2; }
            auto res = std::from_chars(f, fe, row[c]);
            if (res.ec == std::errc::result_out_of_range &&
                res.ptr == fe) {
                // ADVICE r5: a well-formed token whose magnitude escapes
                // double range ("1e400", "-4e-400") must match the pandas
                // round_trip fallback — overflow parses as +/-inf,
                // underflow as (+/-)0 — not abort the row.  from_chars
                // leaves the value unset on out_of_range, so re-parse
                // with strtod, whose C-standard mapping is exactly that
                // (+/-HUGE_VAL on overflow, magnitude <= DBL_MIN on
                // underflow).  Bounded stack copy keeps this path
                // allocation-free; a pathological >511-char token (or a
                // non-C decimal locale) falls through to the loud -1.
                char buf[512];
                size_t tok_len = static_cast<size_t>(fe - f);
                if (tok_len < sizeof(buf)) {
                    memcpy(buf, f, tok_len);
                    buf[tok_len] = '\0';
                    char* endp = nullptr;
                    double v = strtod(buf, &endp);
                    if (endp == buf + tok_len) {
                        row[c] = v;
                        res.ec = std::errc();
                    }
                }
            }
            if (res.ec != std::errc() || res.ptr != fe) {
                *err_row = r;
                return -1;
            }
            ++c;
            if (!cm) break;
            f = fe + 1;
            if (f == le) {                   // trailing comma: empty field
                *err_row = r;
                return -1;
            }
        }
        if (c != cols) { *err_row = r; return -2; }
        ++r;
        p = nl + 1;
    }
    return r;
}

}  // extern "C"
