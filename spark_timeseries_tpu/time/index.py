"""Date-time indices: uniform, irregular, hybrid.

Capability parity with the reference's ``DateTimeIndex.scala``
(``/root/reference/src/main/scala/com/cloudera/sparkts/DateTimeIndex.scala:40-914``):
a bi-directional map between instants and integer locations, with slicing by time
(inclusive) and by position (exclusive end), ``loc_at_*`` lookups, iteration, and
a string round-trip (``to_string``/``from_string``) used as the sidecar format by
save/load.

TPU-first design: indices are host-side objects backed by int64 epoch-nanos numpy
arrays.  Only resolved integer locations ever enter jitted code; calendar logic
(zones, business days) never touches the device.  All lookups have vectorized
array variants (``locs_at``, ``insertion_locs``) used by the ingestion and
rebase paths, replacing the reference's per-observation scalar lookups
(ref ``TimeSeriesRDD.scala:727``).
"""

from __future__ import annotations

import datetime as _dt
import re
from abc import ABC, abstractmethod
from typing import Iterable, List, Sequence, Union

import numpy as np

from .frequency import (
    NANOS_PER_MICRO,
    NANOS_PER_SECOND,
    DurationFrequency,
    Frequency,
    datetime_to_nanos,
    frequency_from_string,
    nanos_to_datetime,
    rebase_day_of_week,
    zone_of,
)

DateTimeLike = Union[int, np.int64, _dt.datetime, str]


def to_nanos(dt: DateTimeLike) -> int:
    """Coerce an instant-like value (epoch-nanos int, datetime, ISO string) to nanos."""
    if isinstance(dt, (int, np.integer)):
        return int(dt)
    if isinstance(dt, _dt.datetime):
        return datetime_to_nanos(dt)
    if isinstance(dt, str):
        nanos, _ = parse_zoned_datetime(dt)
        return nanos
    raise TypeError(f"cannot interpret {type(dt)} as an instant")


# ---------------------------------------------------------------------------
# Java-compatible ZonedDateTime formatting (sidecar string contract)
# ---------------------------------------------------------------------------

_ZDT_RE = re.compile(
    r"^(\d{4,})-(\d{2})-(\d{2})T(\d{2}):(\d{2})"
    r"(?::(\d{2})(?:\.(\d{1,9}))?)?"
    r"(Z|[+-]\d{2}:\d{2}(?::\d{2})?)"
    r"(?:\[([^\]]+)\])?$"
)


def parse_zoned_datetime(s: str) -> tuple[int, str]:
    """Parse java.time ``ZonedDateTime.toString`` output.

    Returns (epoch_nanos, zone_id).  Zone falls back to the offset when no
    ``[Zone]`` suffix is present.  Keeps full nanosecond precision.
    """
    m = _ZDT_RE.match(s.strip())
    if not m:
        raise ValueError(f"cannot parse zoned date-time: {s!r}")
    year, month, day, hour, minute = (int(m.group(i)) for i in range(1, 6))
    second = int(m.group(6) or 0)
    frac = (m.group(7) or "").ljust(9, "0")
    nanos_frac = int(frac) if frac else 0
    offset_s = m.group(8)
    zone = m.group(9)
    offset = _parse_offset(offset_s)
    local = _dt.datetime(year, month, day, hour, minute, second,
                         tzinfo=_dt.timezone(offset))
    nanos = datetime_to_nanos(local) + nanos_frac
    if zone is None:
        total = int(offset.total_seconds())
        if total == 0:
            zone = "Z"
        else:
            sign_c = "+" if total >= 0 else "-"
            total = abs(total)
            zone = f"{sign_c}{total // 3600:02d}:{(total % 3600) // 60:02d}"
    return nanos, zone


def format_zoned_datetime(nanos: int, zone) -> str:
    """Format epoch-nanos as java.time ``ZonedDateTime.toString`` would.

    Trailing zero components are omitted (``T00:00`` not ``T00:00:00``);
    fractions print in 3/6/9 digit groups; offset 0 prints ``Z``; a named zone
    is appended as ``[Zone]``.
    """
    zone_str = str(zone)
    zi = zone_of(zone) if not _is_offset_zone(zone_str) else None
    if zi is not None:
        aware = nanos_to_datetime(nanos - (nanos % NANOS_PER_MICRO), zi)
        offset = aware.utcoffset()
    else:
        offset = _parse_offset(zone_str)
    off_total = int(offset.total_seconds())
    wall_nanos = nanos + off_total * NANOS_PER_SECOND
    days, day_nanos = divmod(wall_nanos, 86_400 * NANOS_PER_SECOND)
    date = _dt.date(1970, 1, 1) + _dt.timedelta(days=int(days))
    hour, rem = divmod(int(day_nanos), 3_600 * NANOS_PER_SECOND)
    minute, rem = divmod(rem, 60 * NANOS_PER_SECOND)
    second, nanos_frac = divmod(rem, NANOS_PER_SECOND)

    out = f"{date.year:04d}-{date.month:02d}-{date.day:02d}T{hour:02d}:{minute:02d}"
    if second or nanos_frac:
        out += f":{second:02d}"
        if nanos_frac:
            frac = f"{nanos_frac:09d}"
            for width in (3, 6, 9):
                if int(frac[width:] or 0) == 0:
                    out += "." + frac[:width]
                    break
    if off_total == 0:
        out += "Z"
    else:
        sign = "+" if off_total >= 0 else "-"
        a = abs(off_total)
        out += f"{sign}{a // 3600:02d}:{(a % 3600) // 60:02d}"
        if a % 60:
            out += f":{a % 60:02d}"
    if zi is not None and zone_str not in ("Z",):
        out += f"[{zone_str}]"
    return out


def _is_offset_zone(zone_str: str) -> bool:
    return zone_str == "Z" or bool(re.match(r"^[+-]\d{2}:\d{2}", zone_str))


def _parse_offset(zone_str: str) -> _dt.timedelta:
    if zone_str == "Z":
        return _dt.timedelta(0)
    sign = 1 if zone_str[0] == "+" else -1
    parts = zone_str[1:].split(":")
    return sign * _dt.timedelta(hours=int(parts[0]), minutes=int(parts[1]),
                                seconds=int(parts[2]) if len(parts) > 2 else 0)


# ---------------------------------------------------------------------------
# DateTimeIndex
# ---------------------------------------------------------------------------

class DateTimeIndex(ABC):
    """Bi-directional time <-> location map (ref ``DateTimeIndex.scala:40-156``)."""

    zone: str

    # -- size / bounds ------------------------------------------------------
    @property
    @abstractmethod
    def size(self) -> int: ...

    def __len__(self) -> int:
        return self.size

    @property
    @abstractmethod
    def first_nanos(self) -> int: ...

    @property
    @abstractmethod
    def last_nanos(self) -> int: ...

    @property
    def first(self) -> _dt.datetime:
        return nanos_to_datetime(self.first_nanos, self.zone)

    @property
    def last(self) -> _dt.datetime:
        return nanos_to_datetime(self.last_nanos, self.zone)

    # -- slicing ------------------------------------------------------------
    @abstractmethod
    def islice(self, start: int, end: int) -> "DateTimeIndex":
        """Position slice; exclusive end (ref ``DateTimeIndex.scala:61-69``)."""

    @abstractmethod
    def slice(self, start: DateTimeLike, end: DateTimeLike) -> "DateTimeIndex":
        """Time slice; inclusive both ends (ref ``DateTimeIndex.scala:45-55``)."""

    # -- lookups ------------------------------------------------------------
    @abstractmethod
    def datetime_at_loc(self, loc: int) -> _dt.datetime: ...

    @abstractmethod
    def nanos_at_loc(self, loc: int) -> int: ...

    @abstractmethod
    def loc_at_datetime(self, dt: DateTimeLike) -> int:
        """Location of the instant; -1 if absent (ref ``DateTimeIndex.scala:98-110``)."""

    @abstractmethod
    def loc_at_or_before(self, dt: DateTimeLike) -> int: ...

    @abstractmethod
    def loc_at_or_after(self, dt: DateTimeLike) -> int: ...

    @abstractmethod
    def insertion_loc(self, dt: DateTimeLike) -> int:
        """Location of the first instant strictly greater than ``dt``
        (ref ``DateTimeIndex.scala:124-139``)."""

    # -- vectorized lookups (TPU ingestion path) ----------------------------
    def locs_at(self, nanos: np.ndarray) -> np.ndarray:
        """Vectorized ``loc_at_datetime`` over an int64 nanos array; -1 where absent."""
        arr = self.to_nanos_array()
        pos = np.searchsorted(arr, nanos, side="left")
        pos_c = np.clip(pos, 0, arr.size - 1)
        return np.where((pos < arr.size) & (arr[pos_c] == nanos), pos, -1).astype(np.int64)

    def locs_at_or_before(self, nanos: np.ndarray) -> np.ndarray:
        """Vectorized location of the last instant ``<=`` each value; -1
        where every instant is later (unlike the scalar
        ``loc_at_or_before``'s clamped edge returns, callers see the
        out-of-range case explicitly)."""
        arr = self.to_nanos_array()
        return (np.searchsorted(arr, np.asarray(nanos, dtype=np.int64),
                                side="right") - 1).astype(np.int64)

    # -- materialization ----------------------------------------------------
    @abstractmethod
    def to_nanos_array(self) -> np.ndarray:
        """All instants as an int64 epoch-nanos array."""

    def to_datetime_array(self) -> List[_dt.datetime]:
        return [nanos_to_datetime(int(n), self.zone) for n in self.to_nanos_array()]

    def nanos_iterator(self) -> Iterable[int]:
        return iter(int(x) for x in self.to_nanos_array())

    # -- zone ---------------------------------------------------------------
    @abstractmethod
    def at_zone(self, zone) -> "DateTimeIndex": ...

    # -- serialization ------------------------------------------------------
    @abstractmethod
    def to_string(self) -> str:
        """Sidecar serialization (ref ``DateTimeIndex.scala:886-913`` contract)."""

    def __str__(self) -> str:
        return self.to_string()


class UniformDateTimeIndex(DateTimeIndex):
    """Start + periods + frequency; O(1) lookups via frequency arithmetic
    (ref ``DateTimeIndex.scala:162-306``)."""

    def __init__(self, start: DateTimeLike, periods: int, frequency: Frequency,
                 zone: Union[str, None] = None):
        self.start_nanos = to_nanos(start)
        self.periods = int(periods)
        if self.periods < 0:
            # otherwise the first touch is an obscure "__len__() should
            # return >= 0" far from the construction site
            raise ValueError(f"periods must be >= 0, got {self.periods}")
        self.frequency = frequency
        if zone is None and isinstance(start, _dt.datetime) and start.tzinfo is not None \
                and hasattr(start.tzinfo, "key"):
            zone = start.tzinfo.key  # type: ignore[attr-defined]
        self.zone = str(zone) if zone is not None else "Z"
        self._nanos_cache: np.ndarray | None = None

    # -- size / bounds ------------------------------------------------------
    @property
    def size(self) -> int:
        return self.periods

    @property
    def first_nanos(self) -> int:
        return self.start_nanos

    @property
    def last_nanos(self) -> int:
        return self.frequency.advance(self.start_nanos, self.periods - 1, self.zone)

    # -- slicing ------------------------------------------------------------
    def islice(self, start: int, end: int) -> "UniformDateTimeIndex":
        return UniformDateTimeIndex(
            self.frequency.advance(self.start_nanos, start, self.zone),
            end - start, self.frequency, self.zone)

    def slice(self, start: DateTimeLike, end: DateTimeLike) -> "UniformDateTimeIndex":
        s, e = to_nanos(start), to_nanos(end)
        periods = self.frequency.difference(s, e, self.zone) + 1
        return UniformDateTimeIndex(s, periods, self.frequency, self.zone)

    # -- lookups ------------------------------------------------------------
    def nanos_at_loc(self, loc: int) -> int:
        return self.frequency.advance(self.start_nanos, loc, self.zone)

    def datetime_at_loc(self, loc: int) -> _dt.datetime:
        return nanos_to_datetime(self.nanos_at_loc(loc), self.zone)

    def loc_at_datetime(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        loc = self.frequency.difference(self.start_nanos, nanos, self.zone)
        if 0 <= loc < self.size and self.nanos_at_loc(loc) == nanos:
            return loc
        return -1

    def loc_at_or_before(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        loc = self.frequency.difference(self.start_nanos, nanos, self.zone)
        if 0 <= loc < self.size:
            return loc - 1 if self.nanos_at_loc(loc) > nanos else loc
        return 0 if loc < 0 else self.size

    def loc_at_or_after(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        loc = self.frequency.difference(self.start_nanos, nanos, self.zone)
        if 0 <= loc < self.size:
            return loc + 1 if self.nanos_at_loc(loc) < nanos else loc
        return 0 if loc < 0 else self.size

    def insertion_loc(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        loc = self.frequency.difference(self.start_nanos, nanos, self.zone)
        if 0 <= loc < self.size:
            return loc + 1 if self.nanos_at_loc(loc) <= nanos else loc
        return 0 if loc < 0 else self.size

    def locs_at(self, nanos: np.ndarray) -> np.ndarray:
        nanos = np.asarray(nanos, dtype=np.int64)
        if isinstance(self.frequency, DurationFrequency):
            step = self.frequency.duration_nanos
            rel = nanos - np.int64(self.start_nanos)
            loc = rel // step
            ok = (rel % step == 0) & (loc >= 0) & (loc < self.size)
            return np.where(ok, loc, -1).astype(np.int64)
        return super().locs_at(nanos)

    # -- materialization ----------------------------------------------------
    def to_nanos_array(self) -> np.ndarray:
        if self._nanos_cache is None:
            if isinstance(self.frequency, DurationFrequency):
                self._nanos_cache = (
                    np.int64(self.start_nanos)
                    + np.arange(self.periods, dtype=np.int64)
                    * np.int64(self.frequency.duration_nanos))
            else:
                self._nanos_cache = self.frequency.advance_array(
                    self.start_nanos, np.arange(self.periods), self.zone)
        return self._nanos_cache

    # -- zone / serialization ----------------------------------------------
    def at_zone(self, zone) -> "UniformDateTimeIndex":
        return UniformDateTimeIndex(self.start_nanos, self.periods, self.frequency, str(zone))

    def to_string(self) -> str:
        return ",".join([
            "uniform", self.zone,
            format_zoned_datetime(self.start_nanos, self.zone),
            str(self.periods), str(self.frequency)])

    def __eq__(self, other):
        return isinstance(other, UniformDateTimeIndex) \
            and other.start_nanos == self.start_nanos \
            and other.periods == self.periods and other.frequency == self.frequency

    def __hash__(self):
        return hash((self.start_nanos, self.periods, self.frequency))

    def __repr__(self):
        return f"UniformDateTimeIndex({self.to_string()})"


class IrregularDateTimeIndex(DateTimeIndex):
    """Arbitrary sorted instants; O(log n) lookups by binary search
    (ref ``DateTimeIndex.scala:312-432``)."""

    def __init__(self, instants, zone: Union[str, None] = None):
        if isinstance(instants, np.ndarray) and instants.dtype == np.int64:
            self.instants = instants
        else:
            vals = [to_nanos(x) for x in instants]
            self.instants = np.asarray(vals, dtype=np.int64)
        if self.instants.size > 1 and np.any(np.diff(self.instants) < 0):
            # every lookup is a binary search (ref DateTimeIndex.scala:352-360)
            # — unsorted instants would return silently wrong locations
            raise ValueError(
                "irregular index instants must be in non-decreasing order")
        self.zone = str(zone) if zone is not None else "Z"

    @property
    def size(self) -> int:
        return int(self.instants.size)

    @property
    def first_nanos(self) -> int:
        return int(self.instants[0])

    @property
    def last_nanos(self) -> int:
        return int(self.instants[-1])

    def islice(self, start: int, end: int) -> "IrregularDateTimeIndex":
        return IrregularDateTimeIndex(self.instants[start:end], self.zone)

    def slice(self, start: DateTimeLike, end: DateTimeLike) -> "IrregularDateTimeIndex":
        s, e = to_nanos(start), to_nanos(end)
        lo = int(np.searchsorted(self.instants, s, side="left"))
        hi = int(np.searchsorted(self.instants, e, side="right"))
        return IrregularDateTimeIndex(self.instants[lo:hi], self.zone)

    def nanos_at_loc(self, loc: int) -> int:
        return int(self.instants[loc])

    def datetime_at_loc(self, loc: int) -> _dt.datetime:
        return nanos_to_datetime(self.nanos_at_loc(loc), self.zone)

    def loc_at_datetime(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        loc = int(np.searchsorted(self.instants, nanos, side="left"))
        if loc < self.size and self.instants[loc] == nanos:
            return loc
        return -1

    def loc_at_or_before(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        return int(np.searchsorted(self.instants, nanos, side="right")) - 1

    def loc_at_or_after(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        return int(np.searchsorted(self.instants, nanos, side="left"))

    def insertion_loc(self, dt: DateTimeLike) -> int:
        return int(np.searchsorted(self.instants, to_nanos(dt), side="right"))

    def to_nanos_array(self) -> np.ndarray:
        return self.instants

    def at_zone(self, zone) -> "IrregularDateTimeIndex":
        return IrregularDateTimeIndex(self.instants, str(zone))

    def to_string(self) -> str:
        stamps = ",".join(format_zoned_datetime(int(n), self.zone) for n in self.instants)
        return f"irregular,{self.zone},{stamps}"

    def __eq__(self, other):
        return isinstance(other, IrregularDateTimeIndex) \
            and np.array_equal(other.instants, self.instants)

    def __hash__(self):
        return hash(self.instants.tobytes())

    def __repr__(self):
        return f"IrregularDateTimeIndex(n={self.size}, zone={self.zone})"


class HybridDateTimeIndex(DateTimeIndex):
    """Sorted disjoint sub-indices with prefix-sum offsets
    (ref ``DateTimeIndex.scala:442-677``)."""

    def __init__(self, indices: Sequence[DateTimeIndex], zone: Union[str, None] = None):
        if not indices:
            raise ValueError("hybrid index needs at least one sub-index")
        self.indices = list(indices)
        self.size_on_left = np.concatenate(
            [[0], np.cumsum([ix.size for ix in self.indices])[:-1]]).astype(np.int64)
        self.zone = str(zone) if zone is not None else self.indices[0].zone
        self._firsts = np.asarray([ix.first_nanos for ix in self.indices], dtype=np.int64)
        self._lasts = np.asarray([ix.last_nanos for ix in self.indices], dtype=np.int64)

    @property
    def size(self) -> int:
        return int(self.size_on_left[-1] + self.indices[-1].size)

    @property
    def first_nanos(self) -> int:
        return self.indices[0].first_nanos

    @property
    def last_nanos(self) -> int:
        return self.indices[-1].last_nanos

    # -- sub-index location -------------------------------------------------
    def _sub_for_loc(self, loc: int) -> tuple[int, int]:
        i = int(np.searchsorted(self.size_on_left, loc, side="right")) - 1
        return i, loc - int(self.size_on_left[i])

    def _sub_for_time(self, nanos: int) -> int:
        """Index of the sub-index whose [first, last] may contain ``nanos``.

        Returns the last sub-index with first <= nanos (clipped to 0).
        """
        i = int(np.searchsorted(self._firsts, nanos, side="right")) - 1
        return max(i, 0)

    def islice(self, start: int, end: int) -> DateTimeIndex:
        si, soff = self._sub_for_loc(start)
        ei, eoff = self._sub_for_loc(end - 1)
        if si == ei:
            return self.indices[si].islice(soff, eoff + 1)
        parts: List[DateTimeIndex] = [self.indices[si].islice(soff, self.indices[si].size)]
        parts.extend(self.indices[si + 1:ei])
        parts.append(self.indices[ei].islice(0, eoff + 1))
        return HybridDateTimeIndex(parts, self.zone)

    def slice(self, start: DateTimeLike, end: DateTimeLike) -> DateTimeIndex:
        lo = self.loc_at_or_after(start)
        hi = self.loc_at_or_before(end)
        return self.islice(lo, hi + 1)

    def nanos_at_loc(self, loc: int) -> int:
        i, off = self._sub_for_loc(loc)
        return self.indices[i].nanos_at_loc(off)

    def datetime_at_loc(self, loc: int) -> _dt.datetime:
        return nanos_to_datetime(self.nanos_at_loc(loc), self.zone)

    def loc_at_datetime(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        i = self._sub_for_time(nanos)
        loc = self.indices[i].loc_at_datetime(nanos)
        return int(self.size_on_left[i]) + loc if loc >= 0 else -1

    def loc_at_or_before(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        i = self._sub_for_time(nanos)
        if nanos < self.indices[i].first_nanos:
            return -1
        if nanos > self.indices[i].last_nanos:
            return int(self.size_on_left[i]) + self.indices[i].size - 1
        return int(self.size_on_left[i]) + self.indices[i].loc_at_or_before(nanos)

    def loc_at_or_after(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        i = self._sub_for_time(nanos)
        if nanos > self.indices[i].last_nanos:
            if i + 1 < len(self.indices):
                return int(self.size_on_left[i + 1])
            return self.size
        if nanos < self.indices[i].first_nanos:
            return int(self.size_on_left[i])
        return int(self.size_on_left[i]) + self.indices[i].loc_at_or_after(nanos)

    def insertion_loc(self, dt: DateTimeLike) -> int:
        nanos = to_nanos(dt)
        i = self._sub_for_time(nanos)
        if nanos > self.indices[i].last_nanos:
            return int(self.size_on_left[i]) + self.indices[i].size
        if nanos < self.indices[i].first_nanos:
            return int(self.size_on_left[i])
        return int(self.size_on_left[i]) + self.indices[i].insertion_loc(nanos)

    def to_nanos_array(self) -> np.ndarray:
        return np.concatenate([ix.to_nanos_array() for ix in self.indices])

    def at_zone(self, zone) -> "HybridDateTimeIndex":
        return HybridDateTimeIndex([ix.at_zone(zone) for ix in self.indices], str(zone))

    def to_string(self) -> str:
        return f"hybrid,{self.zone}," + ";".join(ix.to_string() for ix in self.indices)

    def __eq__(self, other):
        return isinstance(other, HybridDateTimeIndex) and other.indices == self.indices

    def __hash__(self):
        return hash(tuple(self.indices))

    def __repr__(self):
        return f"HybridDateTimeIndex(n_sub={len(self.indices)}, size={self.size})"


# ---------------------------------------------------------------------------
# Factories (ref ``DateTimeIndex.scala:679-913``)
# ---------------------------------------------------------------------------

def uniform(start: DateTimeLike, periods: int, frequency: Frequency,
            zone: Union[str, None] = None) -> UniformDateTimeIndex:
    return UniformDateTimeIndex(start, periods, frequency, zone)


def uniform_from_interval(start: DateTimeLike, end: DateTimeLike, frequency: Frequency,
                          zone: Union[str, None] = None) -> UniformDateTimeIndex:
    z = zone if zone is not None else "Z"
    periods = frequency.difference(to_nanos(start), to_nanos(end), z) + 1
    return UniformDateTimeIndex(start, periods, frequency, zone)


def irregular(instants, zone: Union[str, None] = None) -> IrregularDateTimeIndex:
    return IrregularDateTimeIndex(instants, zone)


def hybrid(indices: Sequence[DateTimeIndex],
           zone: Union[str, None] = None) -> HybridDateTimeIndex:
    z = zone if zone is not None else indices[0].zone
    if any(ix.zone != z for ix in indices):
        raise ValueError("All indices should have the same zone")
    return HybridDateTimeIndex(indices, z)


def next_business_day(nanos: int, zone=None, first_day_of_week: int = 1) -> int:
    """First business day at or after the instant (ref ``DateTimeIndex.scala:858-869``)."""
    local = nanos_to_datetime(nanos, zone_of(zone))
    aligned = rebase_day_of_week(local.isoweekday(), first_day_of_week)
    if aligned == 6:
        shift = 2
    elif aligned == 7:
        shift = 1
    else:
        shift = 0
    wall = (local + _dt.timedelta(days=shift)).replace(tzinfo=None)
    return datetime_to_nanos(wall.replace(tzinfo=zone_of(zone)))


def from_string(s: str) -> DateTimeIndex:
    """Parse ``to_string`` output (sidecar contract, ref ``DateTimeIndex.scala:886-913``)."""
    kind, rest = s.split(",", 1)
    if kind == "uniform":
        zone, start_s, periods_s, freq_s = rest.split(",")
        start_nanos, _ = parse_zoned_datetime(start_s)
        return UniformDateTimeIndex(start_nanos, int(periods_s),
                                    frequency_from_string(freq_s), zone)
    if kind == "irregular":
        parts = rest.split(",")
        zone, stamps = parts[0], parts[1:]
        instants = [parse_zoned_datetime(t)[0] for t in stamps]
        return IrregularDateTimeIndex(instants, zone)
    if kind == "hybrid":
        zone, subs = rest.split(",", 1)
        indices = [from_string(sub) for sub in subs.split(";")]
        return HybridDateTimeIndex(indices, zone)
    raise ValueError(f"DateTimeIndex type {kind!r} not recognized")
