"""Index union & simplify.

Capability parity with the reference's ``DateTimeIndexUtils.scala``
(``/root/reference/src/main/scala/com/cloudera/sparkts/DateTimeIndexUtils.scala:22-154``):
unions a collection of date-time indices into one hybrid index via a priority
queue with overlap trimming/splitting, then simplifies adjacent
irregular/size-1 sub-indices into single irregular blocks.

Host-side only; never enters jitted code.
"""

from __future__ import annotations

import heapq
from typing import List, Sequence

import numpy as np

from .index import (
    DateTimeIndex,
    HybridDateTimeIndex,
    IrregularDateTimeIndex,
)


def _sort_key(ix: DateTimeIndex) -> tuple[int, int]:
    # order by first instant, ties by size (ref DateTimeIndexUtils.scala:23-28)
    return (ix.first_nanos, ix.size)


def simplify(indices: Sequence[DateTimeIndex]) -> List[DateTimeIndex]:
    """Merge runs of adjacent irregular or size-1 indices into one irregular index
    (ref ``DateTimeIndexUtils.scala:40-78``)."""
    simplified: List[DateTimeIndex] = []
    buffer: List[DateTimeIndex] = []
    last_i = len(indices) - 1

    for i, current in enumerate(indices):
        mergeable = current.size == 1 or isinstance(current, IrregularDateTimeIndex)
        if mergeable:
            buffer.append(current)
        if not mergeable or i == last_i:
            if len(buffer) > 1:
                simplified.append(IrregularDateTimeIndex(
                    np.concatenate([b.to_nanos_array() for b in buffer]),
                    buffer[0].zone))
                buffer.clear()
            elif len(buffer) == 1:
                simplified.append(buffer[0])
                buffer.clear()
            if not mergeable:
                simplified.append(current)
    return simplified


def union(indices: Sequence[DateTimeIndex], zone=None) -> DateTimeIndex:
    """Union indices into a single hybrid index (ref ``DateTimeIndexUtils.scala:114-153``).

    Duplicated instants are represented once; overlapping indices are trimmed or
    split so the resulting sub-indices are sorted and disjoint.
    """
    if zone is None:
        zone = indices[0].zone
    heap: List[tuple[tuple[int, int], int, DateTimeIndex]] = []
    counter = 0
    for ix in indices:
        heapq.heappush(heap, (_sort_key(ix), counter, ix))
        counter += 1

    union_list: List[DateTimeIndex] = [heapq.heappop(heap)[2]]

    while heap:
        a = union_list.pop()
        b = heapq.heappop(heap)[2]

        b_trimmed = False
        while b.size > 0 and a.loc_at_datetime(b.first_nanos) > -1:
            b = b.islice(1, b.size)
            b_trimmed = True

        if b_trimmed and b.size > 0:
            union_list.append(a)
            heapq.heappush(heap, (_sort_key(b), counter, b))
            counter += 1
        elif b.size == 0:
            union_list.append(a)
        else:
            split_loc = a.insertion_loc(b.first_nanos)
            if split_loc < a.size:
                a_lower = a.islice(0, split_loc)
                a_upper = a.islice(split_loc, a.size)
                union_list.append(a_lower)
                union_list.append(b)
                heapq.heappush(heap, (_sort_key(a_upper), counter, a_upper))
                counter += 1
            else:
                union_list.append(a)
                union_list.append(b)

    simplified = simplify(union_list)
    return HybridDateTimeIndex(simplified).at_zone(zone)
