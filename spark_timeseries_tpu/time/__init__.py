"""Time & index core (L0): frequencies, date-time indices, union, rebase.

Host-side calendar logic; only resolved integer locations enter jitted code.
"""

from .frequency import (
    BusinessDayFrequency,
    DayFrequency,
    DurationFrequency,
    Frequency,
    HourFrequency,
    MicrosecondFrequency,
    MillisecondFrequency,
    MinuteFrequency,
    MonthFrequency,
    NanosecondFrequency,
    PeriodFrequency,
    SecondFrequency,
    YearFrequency,
    datetime_to_nanos,
    frequency_from_string,
    nanos_to_datetime,
    rebase_day_of_week,
)
from .index import (
    DateTimeIndex,
    HybridDateTimeIndex,
    IrregularDateTimeIndex,
    UniformDateTimeIndex,
    format_zoned_datetime,
    from_string,
    hybrid,
    irregular,
    next_business_day,
    parse_zoned_datetime,
    to_nanos,
    uniform,
    uniform_from_interval,
)
from .rebase import Rebaser, rebase, rebaser
from .union import simplify, union

__all__ = [
    "BusinessDayFrequency", "DayFrequency", "DurationFrequency", "Frequency",
    "HourFrequency", "MicrosecondFrequency", "MillisecondFrequency",
    "MinuteFrequency", "MonthFrequency", "NanosecondFrequency",
    "PeriodFrequency", "SecondFrequency", "YearFrequency",
    "datetime_to_nanos", "frequency_from_string", "nanos_to_datetime",
    "rebase_day_of_week",
    "DateTimeIndex", "HybridDateTimeIndex", "IrregularDateTimeIndex",
    "UniformDateTimeIndex", "format_zoned_datetime", "from_string", "hybrid",
    "irregular", "next_business_day", "parse_zoned_datetime", "to_nanos",
    "uniform", "uniform_from_interval",
    "Rebaser", "rebase", "rebaser", "simplify", "union",
]
