"""Rebasing: move series from a source index onto a target index.

Capability parity with the reference's ``TimeSeriesUtils.scala``
(``/root/reference/src/main/scala/com/cloudera/sparkts/TimeSeriesUtils.scala:107-221``).
The reference builds per-target-location scalar lookups (with fast paths for
uniform->uniform and irregular->uniform); here every case reduces to one
vectorized **index mapping**: an int64 array ``m`` with ``m[i] = j`` meaning
"target location i takes source location j", and ``m[i] = -1`` meaning "no
source observation; fill with the default".

Applying a rebase is then a gather — `vals[..., m]` masked by `m < 0` — which
is jit/vmap friendly and applies to a whole (n_series, n_obs) panel at once
instead of per-series.
"""

from __future__ import annotations

import numpy as np

from .index import DateTimeIndex, UniformDateTimeIndex


class Rebaser:
    """A reusable source-index -> target-index alignment (gather spec)."""

    def __init__(self, index_mapping: np.ndarray, default_value: float = np.nan):
        self.index_mapping = np.asarray(index_mapping, dtype=np.int64)
        self.default_value = default_value
        self._safe = np.clip(self.index_mapping, 0, None)
        self._missing = self.index_mapping < 0
        self.is_identity = bool(np.array_equal(
            self.index_mapping, np.arange(self.index_mapping.size, dtype=np.int64)))

    def __call__(self, values: np.ndarray) -> np.ndarray:
        """Apply along the last (time) axis; works on 1-D series and 2-D panels."""
        values = np.asarray(values)
        if self.is_identity and values.shape[-1] == self.index_mapping.size:
            return values
        safe = np.minimum(self._safe, values.shape[-1] - 1)
        gathered = values[..., safe]
        missing = self._missing | (self.index_mapping >= values.shape[-1])
        return np.where(missing, self.default_value, gathered)


def rebaser(source_index: DateTimeIndex, target_index: DateTimeIndex,
            default_value: float = np.nan) -> Rebaser:
    """Build the alignment from ``source_index`` to ``target_index``.

    Equivalent of ref ``TimeSeriesUtils.rebaser`` (``TimeSeriesUtils.scala:78-102``);
    all source/target type combinations collapse to the vectorized mapping.
    """
    if isinstance(source_index, UniformDateTimeIndex) \
            and isinstance(target_index, UniformDateTimeIndex) \
            and source_index.frequency == target_index.frequency:
        freq = source_index.frequency
        start = freq.difference(source_index.first_nanos, target_index.first_nanos,
                                source_index.zone)
        # O(1) arithmetic fast path (ref TimeSeriesUtils.scala:107-128), valid
        # only when the target grid is in phase with the source grid
        if freq.advance(source_index.first_nanos, start, source_index.zone) \
                == target_index.first_nanos:
            mapping = start + np.arange(target_index.size, dtype=np.int64)
            mapping[(mapping < 0) | (mapping >= source_index.size)] = -1
            return Rebaser(mapping, default_value)
    target_nanos = target_index.to_nanos_array()
    mapping = source_index.locs_at(target_nanos)
    return Rebaser(mapping, default_value)


def rebase(source_index: DateTimeIndex, target_index: DateTimeIndex,
           values: np.ndarray, default_value: float = np.nan) -> np.ndarray:
    """One-shot rebase (ref ``TimeSeriesUtils.scala:62-68``)."""
    return rebaser(source_index, target_index, default_value)(values)
