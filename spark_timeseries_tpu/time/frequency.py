"""Frequency hierarchy for uniform date-time indices.

Capability parity with the reference's ``Frequency.scala`` (see
``/root/reference/src/main/scala/com/cloudera/sparkts/Frequency.scala:29-189``):
a frequency knows how to ``advance`` an instant n steps and how to count the
number of whole steps between two instants (``difference``).

Design notes (TPU-first): all calendar logic is host-side and never enters a
jitted computation.  Instants are int64 epoch-nanoseconds (UTC).  Duration
frequencies (ms/us/s/min/h) are pure nanosecond arithmetic and vectorize over
numpy arrays; calendar frequencies (day/month/year/business-day) operate on
zone-local wall-clock fields via ``zoneinfo``, matching java.time semantics
(DST-aware calendar-day addition, day-of-month clamping for months/years,
weekday-skipping for business days).
"""

from __future__ import annotations

import datetime as _dt
from abc import ABC, abstractmethod
from typing import Union
from zoneinfo import ZoneInfo

import numpy as np

NANOS_PER_SECOND = 1_000_000_000
NANOS_PER_MICRO = 1_000
NANOS_PER_MILLI = 1_000_000
NANOS_PER_MINUTE = 60 * NANOS_PER_SECOND
NANOS_PER_HOUR = 60 * NANOS_PER_MINUTE
NANOS_PER_DAY = 24 * NANOS_PER_HOUR

Nanos = Union[int, np.int64]


def zone_of(zone: Union[str, ZoneInfo, None]) -> ZoneInfo:
    if zone is None or zone == "Z":
        return ZoneInfo("UTC")
    if isinstance(zone, ZoneInfo):
        return zone
    return ZoneInfo(zone)


def nanos_to_datetime(nanos: Nanos, zone: Union[str, ZoneInfo, None] = None) -> _dt.datetime:
    """Epoch-nanos (UTC) -> zone-aware datetime (microsecond precision floor)."""
    zi = zone_of(zone)
    secs, rem = divmod(int(nanos), NANOS_PER_SECOND)
    base = _dt.datetime.fromtimestamp(secs, tz=_dt.timezone.utc).astimezone(zi)
    return base + _dt.timedelta(microseconds=rem // NANOS_PER_MICRO)


def datetime_to_nanos(dt: _dt.datetime) -> int:
    """Zone-aware datetime -> epoch nanos. Naive datetimes are treated as UTC."""
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    whole = dt.replace(microsecond=0)
    return int(whole.timestamp()) * NANOS_PER_SECOND + dt.microsecond * NANOS_PER_MICRO


def _local_wall(dt_nanos: int, zi: ZoneInfo) -> _dt.datetime:
    return nanos_to_datetime(dt_nanos, zi)


def _wall_to_nanos(local: _dt.datetime) -> int:
    """Interpret a zone-aware wall-clock datetime as an instant (fold=0 on gaps)."""
    return datetime_to_nanos(local)


def _is_utc(zi: ZoneInfo) -> bool:
    return getattr(zi, "key", None) in ("UTC", "Etc/UTC", "Z")


def _offsets_at_instants(ns: np.ndarray, zi: ZoneInfo) -> np.ndarray:
    """UTC offset (nanos) of zone ``zi`` at each *instant*, vectorized by
    resolving one offset per unique UTC hour (offsets are piecewise-constant
    with transitions on hour boundaries in practice; zones with sub-hour
    transition instants mis-resolve only inside that single hour)."""
    ns = np.asarray(ns, dtype=np.int64)
    if _is_utc(zi):
        return np.zeros(ns.shape, np.int64)
    hours, inverse = np.unique(ns // NANOS_PER_HOUR, return_inverse=True)
    offs = np.empty(hours.shape, np.int64)
    for i, h in enumerate(hours):
        dt = _dt.datetime.fromtimestamp(int(h) * 3600,
                                        tz=_dt.timezone.utc).astimezone(zi)
        offs[i] = int(dt.utcoffset().total_seconds()) * NANOS_PER_SECOND
    return offs[inverse].reshape(ns.shape)


def _offsets_at_walls(wall_ns: np.ndarray, zi: ZoneInfo) -> np.ndarray:
    """UTC offset (nanos) of zone ``zi`` at each *wall-clock* time (fold=0 on
    ambiguity/gaps, matching the scalar path), one lookup per unique hour."""
    wall_ns = np.asarray(wall_ns, dtype=np.int64)
    if _is_utc(zi):
        return np.zeros(wall_ns.shape, np.int64)
    hours, inverse = np.unique(wall_ns // NANOS_PER_HOUR, return_inverse=True)
    offs = np.empty(hours.shape, np.int64)
    for i, h in enumerate(hours):
        naive = _dt.datetime.fromtimestamp(int(h) * 3600,
                                           tz=_dt.timezone.utc)
        local = naive.replace(tzinfo=zi)
        offs[i] = int(local.utcoffset().total_seconds()) * NANOS_PER_SECOND
    return offs[inverse].reshape(wall_ns.shape)


class Frequency(ABC):
    """Abstract step used by uniform indices (ref ``Frequency.scala:29-39``)."""

    @abstractmethod
    def advance(self, nanos: Nanos, n: int, zone=None) -> int:
        """Advance instant ``nanos`` by this frequency ``n`` times."""

    @abstractmethod
    def difference(self, nanos1: Nanos, nanos2: Nanos, zone=None) -> int:
        """Whole number of steps from ``nanos1`` to ``nanos2``, rounded toward zero."""

    def advance_each(self, nanos: np.ndarray, steps, zone=None) -> np.ndarray:
        """Element-wise advance: instant ``nanos[i]`` moved ``steps[i]``
        (broadcastable) whole frequencies.  Subclasses override with numpy
        field-decomposition implementations; this fallback loops on host."""
        nanos = np.asarray(nanos, dtype=np.int64)
        steps_b = np.broadcast_to(np.asarray(steps, dtype=np.int64),
                                  nanos.shape)
        return np.asarray(
            [self.advance(int(t), int(k), zone)
             for t, k in zip(nanos.ravel(), steps_b.ravel())],
            dtype=np.int64).reshape(nanos.shape)

    def advance_array(self, nanos: Nanos, steps: np.ndarray, zone=None) -> np.ndarray:
        """Vectorized advance of one base instant over an int array of step
        counts (host-side)."""
        steps = np.asarray(steps, dtype=np.int64)
        return self.advance_each(
            np.broadcast_to(np.int64(nanos), steps.shape), steps, zone)

    # subclasses override __str__ to produce the save/load token (e.g. "days 1")


class DurationFrequency(Frequency):
    """Fixed-duration step: pure nanosecond arithmetic (ref ``Frequency.scala:41-62``)."""

    def __init__(self, duration_nanos: int):
        if duration_nanos <= 0:
            raise ValueError("duration must be positive")
        self.duration_nanos = int(duration_nanos)

    def advance(self, nanos, n, zone=None) -> int:
        return int(nanos) + self.duration_nanos * int(n)

    def difference(self, nanos1, nanos2, zone=None) -> int:
        return int((int(nanos2) - int(nanos1)) // self.duration_nanos) \
            if int(nanos2) >= int(nanos1) \
            else -int((int(nanos1) - int(nanos2)) // self.duration_nanos)

    def advance_array(self, nanos, steps, zone=None) -> np.ndarray:
        return np.int64(nanos) + np.asarray(steps, dtype=np.int64) * np.int64(self.duration_nanos)

    def advance_each(self, nanos, steps, zone=None) -> np.ndarray:
        return np.asarray(nanos, dtype=np.int64) \
            + np.asarray(steps, dtype=np.int64) * np.int64(self.duration_nanos)

    def __eq__(self, other):
        return isinstance(other, DurationFrequency) \
            and other.duration_nanos == self.duration_nanos

    def __hash__(self):
        return hash(self.duration_nanos)


class NanosecondFrequency(DurationFrequency):
    def __init__(self, ns: int):
        super().__init__(ns)
        self.ns = ns

    def __str__(self):
        return f"nanoseconds {self.ns}"


class MicrosecondFrequency(DurationFrequency):
    def __init__(self, us: int):
        super().__init__(us * NANOS_PER_MICRO)
        self.us = us

    def __str__(self):
        return f"microseconds {self.us}"


class MillisecondFrequency(DurationFrequency):
    def __init__(self, ms: int):
        super().__init__(ms * NANOS_PER_MILLI)
        self.ms = ms

    def __str__(self):
        return f"milliseconds {self.ms}"


class SecondFrequency(DurationFrequency):
    def __init__(self, seconds: int):
        super().__init__(seconds * NANOS_PER_SECOND)
        self.seconds = seconds

    def __str__(self):
        return f"seconds {self.seconds}"


class MinuteFrequency(DurationFrequency):
    def __init__(self, minutes: int):
        super().__init__(minutes * NANOS_PER_MINUTE)
        self.minutes = minutes

    def __str__(self):
        return f"minutes {self.minutes}"


class HourFrequency(DurationFrequency):
    def __init__(self, hours: int):
        super().__init__(hours * NANOS_PER_HOUR)
        self.hours = hours

    def __str__(self):
        return f"hours {self.hours}"


class PeriodFrequency(Frequency):
    """Calendar-period step, zone-local wall-clock arithmetic
    (ref ``Frequency.scala:64-123``)."""

    def __eq__(self, other):
        return type(other) is type(self) and other.__dict__ == self.__dict__

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items()))))


class DayFrequency(PeriodFrequency):
    """Calendar days: adding a day preserves local wall-clock time across DST."""

    def __init__(self, days: int):
        if days <= 0:
            raise ValueError("days must be positive")
        self.days = int(days)

    def advance(self, nanos, n, zone=None) -> int:
        zi = zone_of(zone)
        local = _local_wall(int(nanos), zi)
        shifted = local + _dt.timedelta(days=self.days * int(n))
        # re-resolve the zone offset at the new local date (calendar addition)
        wall = shifted.replace(tzinfo=None)
        return _wall_to_nanos(wall.replace(tzinfo=zi))

    def difference(self, nanos1, nanos2, zone=None) -> int:
        if int(nanos2) < int(nanos1):
            return -self.difference(nanos2, nanos1, zone)
        zi = zone_of(zone)
        d1, d2 = _local_wall(int(nanos1), zi), _local_wall(int(nanos2), zi)
        days = (d2.date() - d1.date()).days
        if d2.time() < d1.time():
            days -= 1
        return days // self.days

    def advance_each(self, nanos, steps, zone=None) -> np.ndarray:
        """Vectorized: calendar-day addition is uniform in *wall-clock*
        space, so shift into the zone's wall frame, add whole days, and
        re-resolve the offset at each landing wall time (preserves full
        nanosecond precision, like java.time)."""
        zi = zone_of(zone)
        nanos = np.asarray(nanos, dtype=np.int64)
        steps = np.asarray(steps, dtype=np.int64)
        wall = nanos + _offsets_at_instants(nanos, zi) \
            + steps * np.int64(self.days * NANOS_PER_DAY)
        return wall - _offsets_at_walls(wall, zi)

    def __str__(self):
        return f"days {self.days}"


class MonthFrequency(PeriodFrequency):
    """Calendar months with day-of-month clamping (java.time ``plusMonths``)."""

    def __init__(self, months: int):
        if months <= 0:
            raise ValueError("months must be positive")
        self.months = int(months)

    @staticmethod
    def _add_months(local: _dt.datetime, months: int) -> _dt.datetime:
        y = local.year + (local.month - 1 + months) // 12
        m = (local.month - 1 + months) % 12 + 1
        # clamp day to the last valid day of the target month
        if m == 12:
            last = 31
        else:
            last = (_dt.date(y, m + 1, 1) - _dt.timedelta(days=1)).day
        d = min(local.day, last)
        return local.replace(year=y, month=m, day=d)

    def advance(self, nanos, n, zone=None) -> int:
        zi = zone_of(zone)
        local = _local_wall(int(nanos), zi)
        shifted = self._add_months(local.replace(tzinfo=None), self.months * int(n))
        return _wall_to_nanos(shifted.replace(tzinfo=zi))

    def difference(self, nanos1, nanos2, zone=None) -> int:
        zi = zone_of(zone)
        d1, d2 = _local_wall(int(nanos1), zi), _local_wall(int(nanos2), zi)
        months = (d2.year - d1.year) * 12 + (d2.month - d1.month)
        # ChronoUnit.MONTHS on LocalDate: partial months don't count
        if months > 0 and d2.day < d1.day:
            months -= 1
        elif months < 0 and d2.day > d1.day:
            months += 1
        return int(months // self.months) if months >= 0 else -int((-months) // self.months)

    def advance_each(self, nanos, steps, zone=None) -> np.ndarray:
        """Vectorized month addition via numpy datetime64 field
        decomposition: split each wall time into (month index, day-of-month,
        time-of-day), add months, clamp the day to the target month's length
        (java.time ``plusMonths`` semantics), reassemble, re-resolve zone
        offsets."""
        zi = zone_of(zone)
        nanos = np.asarray(nanos, dtype=np.int64)
        steps = np.asarray(steps, dtype=np.int64)
        wall = nanos + _offsets_at_instants(nanos, zi)

        w64 = wall.astype("datetime64[ns]")
        m0 = w64.astype("datetime64[M]")
        day0 = (w64.astype("datetime64[D]") - m0.astype("datetime64[D]")
                ).astype(np.int64)                       # day-of-month - 1
        tod = wall - w64.astype("datetime64[D]").astype(
            "datetime64[ns]").astype(np.int64)
        m2 = m0 + (steps * np.int64(self.months)).astype("timedelta64[M]")
        mstart = m2.astype("datetime64[D]")
        dim = ((m2 + np.timedelta64(1, "M")).astype("datetime64[D]")
               - mstart).astype(np.int64)                # days in month
        day2 = np.minimum(day0, dim - 1)
        wall2 = mstart.astype("datetime64[ns]").astype(np.int64) \
            + day2 * np.int64(NANOS_PER_DAY) + tod
        return wall2 - _offsets_at_walls(wall2, zi)

    def __str__(self):
        return f"months {self.months}"


class YearFrequency(PeriodFrequency):
    def __init__(self, years: int):
        if years <= 0:
            raise ValueError("years must be positive")
        self.years = int(years)

    def advance(self, nanos, n, zone=None) -> int:
        return MonthFrequency(12).advance(nanos, self.years * int(n), zone)

    def difference(self, nanos1, nanos2, zone=None) -> int:
        months = MonthFrequency(1).difference(nanos1, nanos2, zone)
        years = months // 12 if months >= 0 else -((-months) // 12)
        return years // self.years if years >= 0 else -((-years) // self.years)

    def advance_each(self, nanos, steps, zone=None) -> np.ndarray:
        return MonthFrequency(12).advance_each(
            nanos, np.asarray(steps, dtype=np.int64) * self.years, zone)

    def __str__(self):
        return f"years {self.years}"


def rebase_day_of_week(iso_day_of_week: int, first_day_of_week: int = 1) -> int:
    """Re-index an ISO day-of-week (Mon=1..Sun=7) so ``first_day_of_week`` is 1.

    Semantics of ref ``DateTimeIndex.scala:848-853``.
    """
    return (iso_day_of_week - first_day_of_week + 7) % 7 + 1


class BusinessDayFrequency(Frequency):
    """Weekday-skipping day arithmetic (ref ``Frequency.scala:143-189``).

    ``first_day_of_week`` is an ISO weekday (Mon=1); the 6th and 7th days of the
    rebased week are the weekend.
    """

    def __init__(self, days: int, first_day_of_week: int = 1):
        if days <= 0:
            raise ValueError("days must be positive")
        self.days = int(days)
        self.first_day_of_week = int(first_day_of_week)

    def _aligned_dow(self, local: _dt.datetime) -> int:
        return rebase_day_of_week(local.isoweekday(), self.first_day_of_week)

    def advance(self, nanos, n, zone=None) -> int:
        zi = zone_of(zone)
        local = _local_wall(int(nanos), zi)
        aligned = self._aligned_dow(local)
        if aligned > 5:
            raise ValueError(f"{local} is not a business day")
        total_days = int(n) * self.days
        if total_days >= 0:
            weekend_days = (total_days // 5) * 2
            remaining = total_days % 5
            extra = 2 if aligned + remaining > 5 else 0
            shift = total_days + weekend_days + extra
        else:
            back = -total_days
            weekend_days = (back // 5) * 2
            remaining = back % 5
            extra = 2 if aligned - remaining < 1 else 0
            shift = -(back + weekend_days + extra)
        wall = (local + _dt.timedelta(days=shift)).replace(tzinfo=None)
        return _wall_to_nanos(wall.replace(tzinfo=zi))

    def difference(self, nanos1, nanos2, zone=None) -> int:
        if int(nanos2) < int(nanos1):
            return -self.difference(nanos2, nanos1, zone)
        zi = zone_of(zone)
        d1, d2 = _local_wall(int(nanos1), zi), _local_wall(int(nanos2), zi)
        days_between = (d2.date() - d1.date()).days
        if d2.time() < d1.time():
            days_between -= 1
        aligned1 = self._aligned_dow(d1)
        if aligned1 > 5:
            raise ValueError(f"{d1} is not a business day")
        weekend_days = (days_between // 7) * 2
        remaining = days_between % 7
        extra = 2 if aligned1 + remaining > 5 else 0
        return (days_between - weekend_days - extra) // self.days

    def advance_each(self, nanos, steps, zone=None) -> np.ndarray:
        """Vectorized weekday-skipping arithmetic: day-of-week comes from the
        wall day number (epoch day 0 = Thursday), the weekend-skip count is
        the same closed form as the scalar path, and zone offsets are
        re-resolved at the landing wall times."""
        zi = zone_of(zone)
        nanos = np.asarray(nanos, dtype=np.int64)
        steps = np.asarray(steps, dtype=np.int64)
        wall = nanos + _offsets_at_instants(nanos, zi)
        day = np.floor_divide(wall, NANOS_PER_DAY)
        iso = (day + 3) % 7 + 1                          # 1970-01-01 = Thu(4)
        aligned = (iso - self.first_day_of_week + 7) % 7 + 1
        if np.any(aligned > 5):
            bad = nanos[np.argmax(aligned > 5)]
            raise ValueError(
                f"{nanos_to_datetime(int(bad), zi)} is not a business day")
        total = steps * np.int64(self.days)
        mag = np.abs(total)
        weekend = (mag // 5) * 2
        remaining = mag % 5
        extra_f = np.where(aligned + remaining > 5, 2, 0)
        extra_b = np.where(aligned - remaining < 1, 2, 0)
        shift = np.where(total >= 0, total + weekend + extra_f,
                         -(mag + weekend + extra_b))
        wall2 = wall + shift * np.int64(NANOS_PER_DAY)
        return wall2 - _offsets_at_walls(wall2, zi)

    def __eq__(self, other):
        return isinstance(other, BusinessDayFrequency) and other.days == self.days \
            and other.first_day_of_week == self.first_day_of_week

    def __hash__(self):
        return hash((self.days, self.first_day_of_week))

    def __str__(self):
        return f"businessDays {self.days} firstDayOfWeek {self.first_day_of_week}"


_FREQ_PARSERS = {
    "nanoseconds": lambda t: NanosecondFrequency(int(t[1])),
    "microseconds": lambda t: MicrosecondFrequency(int(t[1])),
    "milliseconds": lambda t: MillisecondFrequency(int(t[1])),
    "seconds": lambda t: SecondFrequency(int(t[1])),
    "minutes": lambda t: MinuteFrequency(int(t[1])),
    "hours": lambda t: HourFrequency(int(t[1])),
    "days": lambda t: DayFrequency(int(t[1])),
    "months": lambda t: MonthFrequency(int(t[1])),
    "years": lambda t: YearFrequency(int(t[1])),
    "businessDays": lambda t: BusinessDayFrequency(
        int(t[1]), int(t[3]) if len(t) >= 4 else 1),
}


def frequency_from_string(s: str) -> Frequency:
    """Parse the token emitted by ``str(freq)`` (save/load sidecar contract,
    ref ``DateTimeIndex.scala:886-913``)."""
    tokens = s.strip().split(" ")
    try:
        return _FREQ_PARSERS[tokens[0]](tokens)
    except KeyError:
        raise ValueError(f"Frequency {tokens[0]!r} not recognized") from None
