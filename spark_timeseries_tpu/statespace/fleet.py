"""Fleet scheduler: a multi-tenant serving front-end over shared
executables.

PRs 7–10 built one :class:`~spark_timeseries_tpu.statespace.serving.
ServingSession` per logical stream — health-monitored, self-healing,
SLO-windowed, telemetered.  One session per tenant does not survive
millions of users: every tenant would pay its own device call per tick,
and nothing protects the process when demand exceeds device throughput.
This module is the missing fleet layer (ROADMAP item 3): a
:class:`FleetScheduler` multiplexes many logical tenants onto the small
set of compiled programs the sessions already share (the update jit is
keyed on ``(bucket, dtype, SSMeta, HealthPolicy)`` precisely so it CAN be
shared — ``ServingSession.update_key``), and stays correct and
responsive under overload and failure.  Four robustness mechanisms, each
deterministically fault-injectable (``utils.resilience``):

- **admission control + backpressure** — every tenant owns a bounded
  ingress queue; a deterministic :class:`AdmissionPolicy` decides what
  saturation means (``"reject"`` raises the named
  :class:`FleetSaturated`; ``"drop_oldest"`` evicts the stalest queued
  tick — the newest observation is the valuable one; ``"degrade"``
  sheds the tenant onto the cached-forecast lane).  Counters:
  ``fleet.admitted`` / ``fleet.rejected`` / ``fleet.queued``.  The
  ``tenant_flood`` fault amplifies ingress to drive all three paths.
- **tick coalescing** — tenants whose sessions share an update key are
  one *coalescing group*: their pending ticks gather into one wider
  device call of the very same traced update function (the group's
  pytrees are concatenated lane-wise, ``monitored_step`` is per-lane
  math with no cross-lane reductions, and each tenant's slice scatters
  back through the session's own ``_prepare_tick``/``_absorb_tick``
  pair), so N tenants cost one dispatch instead of N — and the results
  are **bitwise** the per-session ticks (pinned by test).  A group
  flushes when every live tenant has a tick queued, or when the oldest
  queued tick outlives the **coalescing-window deadline**
  (``AdmissionPolicy.coalesce_window_s``) — a slow tenant
  (``coalesce_straggler`` fault) can delay only itself, never the
  batch.  Group width is padded to a power-of-two slot count so tenant
  churn compiles at most O(log fleet) programs.
- **SLO-aware shedding** — the scheduler folds every coalesced
  dispatch's wall latency into a rolling window; when the p95 burns the
  ``STS_SERVING_SLO_MS`` budget, tenants shed one per pump in health
  order (:func:`~spark_timeseries_tpu.statespace.health.shed_priority`:
  diverged-laden first, then suspect — the lattice from PR 9).  A shed
  tenant stops dispatching: its ticks buffer in a bounded catch-up ring
  and its reads serve the **periodicity-aware forecast cache** — the
  last live forecast path, indexed by elapsed ticks, within a staleness
  bound — falling back to a predict-only forecast off the frozen state.
  When the burn clears for ``shed_cooldown`` consecutive pumps, tenants
  restore in reverse order, replaying their buffered ticks through the
  warmed per-session executable (zero new compiles).  Overload degrades
  output quality; it never raises and never crashes.
- **checkpoint-based lane migration** — :meth:`FleetScheduler.drain`
  writes one atomic tenant bundle (the session's
  ``checkpoint_blob`` plus any still-queued ticks, via
  ``utils.checkpoint.save_pytree_atomic``), and
  :meth:`FleetScheduler.adopt` restores it into another scheduler — or
  another process: a ``kill -9`` after the drain commit loses nothing
  (subprocess-pinned), and the adopted tenant's ticks are bitwise the
  undrained ones.  A bundle that disagrees with the adopting process
  raises :class:`FleetRestoreMismatch` naming the differing fields (the
  ``JournalSpecMismatch`` discipline).

Every admitted tick also carries a **lineage record**
(``utils.lineage``): a monotonic trace id plus contiguous stage
timestamps — admit → queue → gather → dispatch → scatter → deliver,
with detour markers for shed rolls, cache serves, catch-up replay,
drain/adopt migration, and pump-restart redelivery — so the end-to-end
latency a *caller* experiences decomposes per stage
(``fleet.e2e.<tenant>.p50_ms``/``.p95_ms`` gauges, the
``/snapshot.json`` ``lineage`` section, lineage spans interleaved in
``/trace.json``).  The record rides the queue entry itself, so it
survives pump crashes and migrates with the tenant; every record is
finalised exactly once (``delivered``/``rejected``/``dropped``/
``migrated``).  Strictly host-side; ``STS_LINEAGE=0`` disarms.

Like a single session, a scheduler is one logical serving plane: not
thread-safe per instance — shard across schedulers (the compiled
programs are shared through the jit cache anyway).

Metrics: ``fleet.admitted/rejected/queued/dropped_ticks`` (admission),
``fleet.coalesced_dispatches/coalesced_ticks`` + the
``fleet.coalesced_step`` span (coalescing), ``fleet.slo_burns``,
``fleet.shed_lanes``, ``fleet.shed_tenants`` gauge,
``fleet.restored_tenants``, ``fleet.cache_serves``, ``fleet.cache_stale``
(shedding), ``fleet.drained/adopted`` (migration).  ``bench.py`` embeds
a ``fleet_demo`` block and ``tools/bench_gate.py`` gates
``fleet_ticks_per_s`` and zero-baselines ``fleet_shed_lanes``.
"""

from __future__ import annotations

import itertools
import os
import signal
import time
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..utils import checkpoint as _checkpoint
from ..utils import lineage as _lineage
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from ..utils import telemetry as _telemetry
from .health import shed_priority
from .serving import ServingSession, TickResult, _jitted, check_label

__all__ = ["AdmissionPolicy", "FleetScheduler", "FleetSaturated",
           "FleetRestoreMismatch", "TENANT_LIVE", "TENANT_SHED",
           "DEFAULT_QUEUE_DEPTH"]

# tenant bundle format written by drain() / read by adopt(); bumped when
# the bundle's fields change incompatibly
_BUNDLE_FORMAT = 1

DEFAULT_QUEUE_DEPTH = 8

# tenant serving modes
TENANT_LIVE = "live"    # ticks coalesce onto the device
TENANT_SHED = "shed"    # ticks buffer; reads serve the forecast cache

_fleet_seq = itertools.count(1)


class FleetSaturated(RuntimeError):
    """A tenant's bounded ingress queue is full under the ``"reject"``
    admission policy.  Deterministic backpressure: the caller sees WHICH
    tenant saturated at WHAT depth and can slow down, reroute, or switch
    the policy — instead of the queue growing without bound until the
    process dies."""


class FleetRestoreMismatch(ValueError):
    """A tenant bundle disagrees with the adopting scheduler/process
    (format, label, tick geometry — or, chained underneath, the session
    half's own :class:`~spark_timeseries_tpu.statespace.serving.
    ServingRestoreMismatch`).  Raised eagerly by
    :meth:`FleetScheduler.adopt` with the differing fields spelled out
    (the ``JournalSpecMismatch`` discipline) — adopting would serve
    garbage."""


class AdmissionPolicy(NamedTuple):
    """Static knobs of one scheduler's overload behavior — deterministic
    by construction (no randomness, no wall-clock feeding traced code).

    ``queue_depth`` bounds every tenant's ingress queue; ``on_full`` is
    what saturation does (``"reject"`` → :class:`FleetSaturated`,
    ``"drop_oldest"`` → evict the stalest queued tick and admit the new
    one, ``"degrade"`` → shed the tenant onto the cached-forecast
    lane); ``coalesce_window_s`` is the coalescing deadline — the
    longest a queued tick may wait for its group to fill before a
    partial batch flushes anyway (0 = never wait); ``slo_window`` the
    rolling dispatch-latency sample count behind the fleet p95;
    ``shed_cooldown`` how many consecutive clear pumps the p95 burn must
    stay quiet before shed tenants restore; ``cache_staleness`` the max
    elapsed ticks a cached forecast path may be phase-shifted by before
    it is declared stale; ``catchup_ring`` how many ticks a shed tenant
    buffers for replay-on-restore (older ones drop — degradation is
    bounded memory, too)."""
    queue_depth: int = DEFAULT_QUEUE_DEPTH
    on_full: str = "reject"
    coalesce_window_s: float = 0.05
    slo_window: int = 64
    shed_cooldown: int = 4
    cache_staleness: int = 32
    catchup_ring: int = 64

    def validate(self) -> "AdmissionPolicy":
        if self.queue_depth < 1:
            raise ValueError(
                f"queue_depth must be >= 1, got {self.queue_depth}")
        if self.on_full not in ("reject", "drop_oldest", "degrade"):
            raise ValueError(
                f"on_full must be 'reject', 'drop_oldest', or "
                f"'degrade', got {self.on_full!r}")
        if self.coalesce_window_s < 0:
            raise ValueError(
                f"coalesce_window_s must be >= 0, "
                f"got {self.coalesce_window_s}")
        if self.slo_window < 4:
            raise ValueError(
                f"slo_window must be >= 4, got {self.slo_window}")
        if self.shed_cooldown < 1:
            raise ValueError(
                f"shed_cooldown must be >= 1, got {self.shed_cooldown}")
        if self.cache_staleness < 1:
            raise ValueError(
                f"cache_staleness must be >= 1, "
                f"got {self.cache_staleness}")
        if self.catchup_ring < 1:
            raise ValueError(
                f"catchup_ring must be >= 1, got {self.catchup_ring}")
        return self


def _slots_for(n: int) -> int:
    """Group slot count: next power of two >= n (floor 1), so tenant
    churn within a power-of-two band reuses one coalesced executable."""
    s = 1
    while s < n:
        s *= 2
    return s


class _Tenant:
    """One logical tenant: its session plus the scheduler-side state
    (ingress queue, serving mode, catch-up ring, forecast cache,
    per-tenant counters).  Internal — the public surface speaks labels."""

    def __init__(self, session: ServingSession, policy: AdmissionPolicy):
        self.session = session
        self.label = session.label
        self.queue: deque = deque()   # (tick, offset, t_arrival, lineage)
        self.mode = TENANT_LIVE
        self.shed_reason: Optional[str] = None
        # (tick, offset, lineage) — bounded shed-lane replay buffer
        self.catchup: deque = deque(maxlen=policy.catchup_ring)
        self.cache_fc: Optional[np.ndarray] = None   # (n_series, H)
        self.cache_stamp = 0                 # `arrived` at cache time
        self.admitted = 0
        self.rejected = 0
        self.dropped = 0
        self.cache_serves = 0
        self.ticks_dispatched = 0
        # monotonic count of ticks that ever ARRIVED for this tenant
        # (admitted into the queue or the catch-up ring).  The forecast
        # cache's phase is measured against this, NOT against ring/queue
        # sizes: a bounded ring saturates (len stops growing while the
        # stream keeps ticking), which would freeze the phase shift and
        # let a long-shed tenant serve the same stale path forever.
        self.arrived = 0
        self.arrived_prev_pump = 0           # ingress-quiescence probe

    @property
    def n_series(self) -> int:
        return self.session.n_series

    def elapsed_since_cache(self) -> int:
        """Stream ticks that arrived since the cached forecast path was
        taken — the phase shift a cache read must apply (arrival-based:
        every tick advances the stream's clock whether it was
        dispatched, buffered, or evicted from the bounded ring)."""
        return self.arrived - self.cache_stamp

    def summary(self) -> Dict[str, Any]:
        return {
            "tenant": self.label,
            "mode": self.mode,
            "shed_reason": self.shed_reason,
            "n_series": self.n_series,
            "queued": len(self.queue),
            "catchup": len(self.catchup),
            "admitted": self.admitted,
            "rejected": self.rejected,
            "dropped": self.dropped,
            "cache_serves": self.cache_serves,
            "ticks_dispatched": self.ticks_dispatched,
            "health": self.session.health_counts(),
        }


class FleetScheduler:
    """Multiplex many labeled :class:`ServingSession` tenants onto shared
    coalesced device calls, with admission control, SLO-aware shedding,
    and checkpoint-based migration (module docstring for the contract).

    Build one, :meth:`attach` (or :meth:`open_tenant`) tenants,
    :meth:`warmup`, then :meth:`submit` ticks — dispatch is automatic
    (``auto_pump``) or explicit via :meth:`pump`.  Reads go through
    :meth:`forecast`, which transparently serves shed tenants from the
    cache."""

    def __init__(self, policy: Optional[AdmissionPolicy] = None, *,
                 registry=None, label: Optional[str] = None,
                 auto_pump: bool = True):
        self.policy = (policy if policy is not None
                       else AdmissionPolicy()).validate()
        self._reg = registry if registry is not None \
            else _metrics.get_registry()
        self.label = check_label(label) if label is not None \
            else f"fleet{next(_fleet_seq)}"
        self.auto_pump = bool(auto_pump)
        self._tenants: Dict[str, _Tenant] = {}
        self._groups: Dict[Any, List[str]] = {}   # update_key -> labels
        self._lat: deque = deque(maxlen=self.policy.slo_window)
        self._slo_ms = _telemetry.env_positive("STS_SERVING_SLO_MS",
                                               float, None)
        self._slo_burns = 0
        self._burning = False
        self._clear_pumps = 0
        self._shed_order: List[str] = []     # labels in shed order
        # gathered-SSM reuse (the SSM is static between heal/splice;
        # re-concatenating O(tenants·bucket·m²) transition floats every
        # dispatch would tax exactly the throughput the fleet gate
        # measures): (group key, participant labels, slots) -> (per-
        # member ssm object refs, gathered pytree).  Holding the refs
        # makes the identity check safe — a healed session swaps in a
        # NEW ssm object, which misses and re-gathers.
        self._gather_cache: Dict[Any, Tuple[list, Any]] = {}
        # set by statespace.runtime.FleetRuntime when it adopts this
        # scheduler as a shard: a zero-arg callable returning the pump
        # supervision summary, folded into telemetry_summary() so the
        # scrape plane and sts_top see liveness next to the tenants
        self._runtime_info = None
        _telemetry.register_fleet(self)
        _telemetry.ensure_started_from_env()
        self._reg.inc("fleet.schedulers")

    # -- tenant lifecycle ---------------------------------------------------

    def attach(self, session: ServingSession) -> str:
        """Register a session as a tenant (its label is the tenant id —
        unique per scheduler).  Sessions with equal ``update_key``
        coalesce into one group."""
        label = check_label(session.label)
        if label in self._tenants:
            raise ValueError(
                f"tenant label {label!r} is already attached to "
                f"{self.label!r}; labels identify tenants — give the "
                f"session a distinct label=")
        t = _Tenant(session, self.policy)
        self._tenants[label] = t
        self._groups.setdefault(session.update_key, []).append(label)
        self._reg.inc("fleet.tenants_attached")
        self._reg.set_gauge("fleet.tenants", len(self._tenants))
        return label

    def open_tenant(self, model, history, *, label: Optional[str] = None,
                    **kwargs) -> str:
        """Convenience: :meth:`ServingSession.start` + :meth:`attach`."""
        sess = ServingSession.start(model, history, label=label,
                                    registry=self._reg, **kwargs)
        return self.attach(sess)

    def detach(self, label: str) -> ServingSession:
        """Remove a tenant (undispatched ticks are dropped and counted);
        returns its session, still live and servable standalone."""
        t = self._pop_tenant(label)
        if t.queue or t.catchup:
            self._reg.inc("fleet.dropped_ticks",
                          len(t.queue) + len(t.catchup))
            for entry in t.queue:
                _lineage.complete(entry[3], self._reg, outcome="dropped")
            for entry in t.catchup:
                _lineage.complete(entry[2], self._reg, outcome="dropped")
        return t.session

    def _pop_tenant(self, label: str) -> _Tenant:
        t = self._tenants.pop(label, None)
        if t is None:
            raise KeyError(
                f"no tenant {label!r} in scheduler {self.label!r} "
                f"(tenants: {sorted(self._tenants) or 'none'})")
        key = t.session.update_key
        self._groups[key].remove(label)
        if not self._groups[key]:
            del self._groups[key]
        if label in self._shed_order:
            self._shed_order.remove(label)
        self._reg.set_gauge("fleet.tenants", len(self._tenants))
        return t

    @property
    def tenants(self) -> List[str]:
        return sorted(self._tenants)

    @property
    def n_groups(self) -> int:
        return len(self._groups)

    def session(self, label: str) -> ServingSession:
        return self._require(label).session

    def _require(self, label: str) -> _Tenant:
        t = self._tenants.get(label)
        if t is None:
            raise KeyError(
                f"no tenant {label!r} in scheduler {self.label!r} "
                f"(tenants: {sorted(self._tenants) or 'none'})")
        return t

    # -- admission ----------------------------------------------------------

    def submit(self, label: str, tick, offset=None) -> None:
        """Admit one tick for one tenant through the bounded ingress
        queue; dispatch happens on the next :meth:`pump` (automatic by
        default).  Saturation behavior is the :class:`AdmissionPolicy`'s
        — the only path that raises is the explicit ``"reject"`` policy,
        and it raises the named :class:`FleetSaturated`."""
        t = self._require(label)
        flood = _resilience.fleet_fault("tenant_flood")
        copies = max(1, int(flood.n_attempts)) if flood is not None else 1
        for _ in range(copies):
            self._admit_one(t, tick, offset)
        if self.auto_pump:
            self.pump()

    def _admit_one(self, t: _Tenant, tick, offset, lin=None) -> None:
        # width is validated HERE, at the admission boundary: a
        # malformed tick discovered only inside a coalesced dispatch
        # would already have dequeued the peers' ticks (losing them) and
        # would raise out of an unrelated tenant's submit — the bad
        # producer must be the one that sees the error
        if lin is None:
            # minted once per admitted tick — the "degrade" branch
            # re-enters with the SAME record (one tick, one lineage)
            lin = _lineage.begin(t.label)
        tick = np.asarray(tick).reshape(-1)
        if tick.shape[0] != t.n_series:
            raise ValueError(
                f"tenant {t.label!r} expects one tick per series "
                f"({t.n_series}), got {tick.shape[0]}")
        if offset is not None:
            offset = np.asarray(offset).reshape(-1)
            if offset.shape[0] != t.n_series:
                raise ValueError(
                    f"tenant {t.label!r} expects one exogenous offset "
                    f"per series ({t.n_series}), got {offset.shape[0]}")
        if t.mode == TENANT_SHED:
            # shed lane: ticks buffer for replay-on-restore; the bounded
            # ring makes overload cost memory-bounded (maxlen evicts)
            if len(t.catchup) == t.catchup.maxlen:
                t.dropped += 1
                self._reg.inc("fleet.dropped_ticks")
                _lineage.complete(t.catchup[0][2], self._reg,
                                  outcome="dropped")
            if lin is not None:
                lin.detour("shed")
                lin.stage_end("admit")
            t.catchup.append((np.array(tick, copy=True),
                              None if offset is None
                              else np.array(offset, copy=True), lin))
            t.admitted += 1
            t.arrived += 1
            self._reg.inc("fleet.admitted")
            return
        if len(t.queue) >= self.policy.queue_depth:
            mode = self.policy.on_full
            if mode == "reject":
                t.rejected += 1
                self._reg.inc("fleet.rejected")
                _lineage.complete(lin, self._reg, outcome="rejected")
                raise FleetSaturated(
                    f"tenant {t.label!r} ingress queue is full "
                    f"({self.policy.queue_depth} ticks) and the "
                    f"admission policy is 'reject'; pump() the "
                    f"scheduler, slow the producer, or use "
                    f"on_full='drop_oldest'/'degrade'")
            if mode == "drop_oldest":
                evicted = t.queue.popleft()
                t.dropped += 1
                self._reg.inc("fleet.dropped_ticks")
                _lineage.complete(evicted[3], self._reg,
                                  outcome="dropped")
            else:                     # degrade: shed onto the cache lane
                self._shed(t, reason="admission")
                self._admit_one(t, tick, offset, lin)
                return
        if lin is not None:
            lin.stage_end("admit")
        t.queue.append((np.asarray(tick), offset, time.monotonic(), lin))
        t.admitted += 1
        t.arrived += 1
        self._reg.inc("fleet.admitted")
        self._reg.inc("fleet.queued")

    # -- coalesced dispatch -------------------------------------------------

    def pump(self, force: bool = False) -> List[Dict[str, Any]]:
        """Dispatch every ready coalescing group (``force=True``
        dispatches any group with pending ticks regardless of readiness)
        and run the shed/restore ladder.  Returns one report dict per
        dispatched group."""
        reports = []
        strag = _resilience.fleet_fault("coalesce_straggler")
        for key in list(self._groups):
            labels = self._groups.get(key)
            if not labels:
                continue
            members = [self._tenants[la] for la in labels]
            live = [m for m in members if m.mode == TENANT_LIVE]
            stragglers = set()
            if strag is not None:
                stragglers = {m.label for i, m in enumerate(live)
                              if i % max(1, strag.lane_stride) == 0}
            ready_pool = [m for m in live if m.label not in stragglers]
            with_ticks = [m for m in ready_pool if m.queue]
            if not with_ticks:
                continue
            all_present = len(with_ticks) == len(ready_pool)
            oldest = min(m.queue[0][2] for m in with_ticks)
            expired = self.policy.coalesce_window_s == 0.0 or \
                (time.monotonic() - oldest) >= self.policy.coalesce_window_s
            if not (force or all_present or expired):
                continue
            # a window-deadline flush with members still missing is the
            # straggler-pays-alone path — the dispatched ticks' lineage
            # records mark it, so a latency regression can be attributed
            # to partial batching rather than the device call
            reports.append(self._dispatch_group(
                key, with_ticks,
                deadline_flush=expired and not all_present))
        self._shed_restore_step()
        return reports

    def _dispatch_group(self, key, members: List[_Tenant],
                        deadline_flush: bool = False) -> Dict[str, Any]:
        """One coalesced device call: pop one queued tick per member,
        gather the group's pytrees lane-wise, run the SAME jitted update
        the sessions run solo, scatter each member's slice back through
        its session's absorb path.  Bitwise the per-session ticks — the
        math is per-lane, the function object is shared, and the host
        accounting is the session's own.  Each popped tick's lineage
        record closes its ``queue`` segment here and then tracks
        gather/dispatch/scatter/deliver through this call."""
        import jax
        import jax.numpy as jnp

        bucket, _dtype, meta, policy, quality = key
        G = len(members)
        slots = _slots_for(G)
        prepped = []
        lins = []
        for m in members:
            tick, offset, _, lin = m.queue.popleft()
            if lin is not None:
                lin.stage_end("queue")
                if deadline_flush:
                    lin.detour("window_deadline")
            lins.append(lin)
            host, y, off = m.session._prepare_tick(tick, offset)
            prepped.append((m, host, y, off))

        def gather(*leaves):
            # pad vacant slots by replicating member 0's leaf: finite,
            # harmless — their ticks are NaN and their results are
            # never scattered back
            parts = list(leaves) + [leaves[0]] * (slots - G)
            return jnp.concatenate([jnp.asarray(p) for p in parts])

        ckey = (key, tuple(p[0].label for p in prepped), slots)
        member_ssms = [p[0].session._ssm for p in prepped]
        cached = self._gather_cache.get(ckey)
        if cached is not None and len(cached[0]) == G and all(
                a is b for a, b in zip(cached[0], member_ssms)):
            ssm = cached[1]
        else:
            ssm = jax.tree_util.tree_map(gather, *member_ssms)
            if len(self._gather_cache) > 64:   # participation churn
                self._gather_cache.clear()
            self._gather_cache[ckey] = (member_ssms, ssm)
        state = jax.tree_util.tree_map(
            gather, *(p[0].session._state for p in prepped))
        health = jax.tree_util.tree_map(
            gather, *(p[0].session._health for p in prepped))
        qstate = None
        if quality is not None:
            # the quality carry gathers/scatters lane-wise exactly like
            # state/health (the QualityState leaves are all batched on
            # the series axis by construction)
            qstate = jax.tree_util.tree_map(
                gather, *(p[0].session._qstate for p in prepped))
        y_all = np.full((slots * bucket,), np.nan,
                        prepped[0][0].session._dtype)
        off_all = np.zeros_like(y_all)
        for i, (_, _, y, off) in enumerate(prepped):
            y_all[i * bucket:(i + 1) * bucket] = y
            off_all[i * bucket:(i + 1) * bucket] = off

        fn = _jitted("update")
        for lin in lins:
            if lin is not None:
                lin.stage_end("gather")
        t0 = time.perf_counter()
        with _metrics.span("fleet.coalesced_step"):
            state2, health2, qstate2, v, f, ll_inc, anom = fn(
                meta, policy, quality, ssm, state, health, qstate,
                y_all, off_all)
            # materialize inside the span: the latency each session
            # records must cover real per-tick cost, as in update().
            # One whole-array transfer per output, host-side slicing per
            # tenant — slicing the device outputs per tenant here
            # launches 6 tiny slice programs + transfers per tenant per
            # dispatch (STS203, the pad-slice pattern)
            vh, fh, llh, sth, anh, ewh = (
                np.asarray(v), np.asarray(f), np.asarray(ll_inc),
                np.asarray(health2.status), np.asarray(anom),
                np.asarray(health2.ew))
            outs = []
            for i, (m, host, _, _) in enumerate(prepped):
                lo = i * bucket
                n = m.n_series
                outs.append(TickResult(
                    vh[lo:lo + n], fh[lo:lo + n], llh[lo:lo + n],
                    sth[lo:lo + n], anh[lo:lo + n], ewh[lo:lo + n]))
        dt = time.perf_counter() - t0
        for lin in lins:
            if lin is not None:
                lin.stage_end("dispatch")

        def take(i):
            lo = i * bucket
            return lambda leaf: leaf[lo:lo + bucket]

        for i, (m, host, _, _) in enumerate(prepped):
            sub_state = jax.tree_util.tree_map(take(i), state2)
            sub_health = jax.tree_util.tree_map(take(i), health2)
            sub_q = jax.tree_util.tree_map(take(i), qstate2) \
                if quality is not None else None
            m.session._absorb_tick(host, sub_state, sub_health, outs[i],
                                   dt, sub_q, lineage=lins[i])
            m.ticks_dispatched += 1
        self._reg.inc("fleet.coalesced_dispatches")
        self._reg.inc("fleet.coalesced_ticks", G)
        self._note_latency(dt)
        # delivery: the results are committed and visible to readers —
        # close each journey and publish its e2e sample
        for lin in lins:
            if lin is not None:
                lin.stage_end("deliver")
                _lineage.complete(lin, self._reg)
        return {"key": (bucket, meta.family, meta.m), "tenants": G,
                "slots": slots, "wall_ms": round(dt * 1e3, 3),
                "dtype": _dtype}

    def warmup(self) -> None:
        """Precompile every path a pump can take at the current
        membership: each group's coalesced executable at EVERY
        power-of-two slot width up to the full group (partial flushes —
        window-deadline expiries, stragglers, shed-thinned groups —
        dispatch at intermediate widths, and an unwarmed width would
        compile inside the hot pump), the scatter-back slicing at each
        width, and each group's per-session executable (shared across
        same-key tenants) for the replays lane migration and
        shed-restore run.  After this, submit/pump/restore trigger zero
        XLA compiles at any group size — the scheduler-armed equivalent
        of ``ServingSession.warmup`` (pinned by test, partial flush
        included).

        Zero host round-trips (the old warmup was the rank-1 STS205
        fusion chain, 4.58 s span self-time in FUSION_AUDIT r08): every
        per-width dispatch and every scatter-back slice program runs
        **async** — jit dispatch blocks on *compile* but not on
        *execution*, and it is the compiles this pass exists to front-
        load.  (AOT ``.lower().compile()`` would skip the executions
        entirely, but on this jax it does not populate the jit call
        cache — the first real call would compile again — so one real
        async call per width stays.)  D2H transfers compile nothing, so
        the dispatch path's whole-array materializations need no
        warming.  One terminal ``jax.block_until_ready`` keeps warmup
        synchronous — the wall-time pin measures finished work, and no
        warmup execution can overhang into the first pump."""
        import jax
        import jax.numpy as jnp

        fn = _jitted("update")
        pending = []
        with _metrics.span("fleet.warmup"):
            for key, labels in self._groups.items():
                bucket, _dtype, meta, policy, quality = key
                members = [self._tenants[la] for la in labels]
                members[0].session.warmup()     # the replay-lane program
                sizes = {len(members)}
                w = 1
                while w < len(members):
                    sizes.add(w)
                    w *= 2
                for G in sorted(sizes):
                    slots = _slots_for(G)

                    def gather(*leaves):
                        parts = (list(leaves)
                                 + [leaves[0]] * (slots - len(leaves)))
                        return jnp.concatenate(
                            [jnp.asarray(p) for p in parts])

                    srcs = members[:G]
                    ssm = jax.tree_util.tree_map(
                        gather, *(m.session._ssm for m in srcs))
                    state = jax.tree_util.tree_map(
                        gather, *(m.session._state for m in srcs))
                    health = jax.tree_util.tree_map(
                        gather, *(m.session._health for m in srcs))
                    qstate = None
                    if quality is not None:
                        qstate = jax.tree_util.tree_map(
                            gather, *(m.session._qstate for m in srcs))
                    y = np.full((slots * bucket,), np.nan,
                                srcs[0].session._dtype)
                    off = np.zeros_like(y)
                    state2, health2, q2, v, f, ll, anom = fn(
                        meta, policy, quality, ssm, state, health,
                        qstate, y, off)
                    for i in range(G):
                        lo = i * bucket
                        # the scatter-back slice programs (static start
                        # offsets — one program per member position)
                        pending.append(jax.tree_util.tree_map(
                            lambda leaf, lo=lo: leaf[lo:lo + bucket],
                            (state2, health2)))
                        if quality is not None:
                            pending.append(jax.tree_util.tree_map(
                                lambda leaf, lo=lo: leaf[lo:lo + bucket],
                                q2))
                    pending.append((v, f, ll, anom))
            jax.block_until_ready(pending)

    # -- SLO shedding -------------------------------------------------------

    def _note_latency(self, dt_s: float) -> None:
        self._lat.append(float(dt_s))
        ms = dt_s * 1e3
        if self._slo_ms is not None and ms > self._slo_ms:
            self._slo_burns += 1
            self._reg.inc("fleet.slo_burns")

    def _p95_ms(self) -> Optional[float]:
        if len(self._lat) < 4:
            return None
        arr = np.fromiter(self._lat, dtype=np.float64) * 1e3
        return float(np.percentile(arr, 95))

    def _burn_active(self) -> bool:
        if self._slo_ms is None:
            return False
        p95 = self._p95_ms()
        return p95 is not None and p95 > self._slo_ms

    def _shed_restore_step(self) -> None:
        """The shed ladder, one rung per pump: while the p95 window
        burns the SLO budget, shed the worst-health live tenant; once
        the burn stays clear for ``shed_cooldown`` pumps, restore shed
        tenants (newest shed first) with catch-up replay.  One tenant
        per pump in each direction keeps the feedback loop damped —
        shedding everything on one bad sample would oscillate."""
        burning = self._burn_active()
        if burning:
            self._burning = True
            self._clear_pumps = 0
            live = [t for t in self._tenants.values()
                    if t.mode == TENANT_LIVE]
            if live:
                worst = max(
                    live, key=lambda t: (
                        shed_priority(t.session.lane_status), t.label))
                self._shed(worst, reason="slo")
            return
        if not self._burning and not self._shed_order:
            return
        self._clear_pumps += 1
        if self._clear_pumps < self.policy.shed_cooldown:
            return
        # restore newest-shed first: it was shed under the worst burn,
        # and the oldest shed (worst health) re-earns its slot last
        restored = None
        for label in reversed(self._shed_order):
            t = self._tenants.get(label)
            if t is not None and t.shed_reason != "admission":
                restored = t
                break
        if restored is None:
            # only admission-shed tenants remain; they restore only
            # once their own ingress pressure is gone — no new arrivals
            # since the previous pump (restoring into a live flood
            # would just re-saturate the queue and oscillate
            # shed/replay/shed every cooldown)
            for label in reversed(self._shed_order):
                t = self._tenants.get(label)
                if t is not None and t.arrived == t.arrived_prev_pump:
                    restored = t
                    break
        for t in self._tenants.values():
            t.arrived_prev_pump = t.arrived
        if restored is not None:
            self._restore(restored)
        if not self._shed_order:
            self._burning = False

    def _shed(self, t: _Tenant, reason: str) -> None:
        if t.mode == TENANT_SHED:
            return
        t.mode = TENANT_SHED
        t.shed_reason = reason
        self._shed_order.append(t.label)
        self._burning = True        # a shed episode is active until the
        #                             ladder restores the last tenant
        # fresh measurement epoch: the p95 that justified this shed is
        # pre-shed load — keeping it would shed the whole fleet off one
        # bad window and then never restore (a stale burn with no new
        # dispatches to clear it)
        self._lat.clear()
        self._clear_pumps = 0
        # undispatched queued ticks roll into the catch-up ring so a
        # later restore replays them in order
        while t.queue:
            tick, offset, _, lin = t.queue.popleft()
            if len(t.catchup) == t.catchup.maxlen:
                t.dropped += 1
                self._reg.inc("fleet.dropped_ticks")
                _lineage.complete(t.catchup[0][2], self._reg,
                                  outcome="dropped")
            if lin is not None:
                lin.detour("shed")
            t.catchup.append((np.array(tick, copy=True),
                              None if offset is None
                              else np.array(offset, copy=True), lin))
        self._reg.inc("fleet.shed_lanes", t.n_series)
        self._reg.inc("fleet.shed_events")
        self._reg.set_gauge("fleet.shed_tenants", len(self._shed_order))
        _metrics.trace_instant(
            "fleet.tenant_shed",
            {"tenant": t.label, "reason": reason,
             "lanes": t.n_series,
             "p95_ms": self._p95_ms()})

    def _restore(self, t: _Tenant) -> None:
        """Bring a shed tenant back to the live lane: replay its
        buffered ticks through the warmed per-session executable (zero
        new compiles — the (bucket,) program is warm), then clear the
        shed mark.  Ticks the bounded ring evicted stay lost, counted —
        the deterministic price of the overload window."""
        replayed = 0
        while t.catchup:
            tick, offset, lin = t.catchup.popleft()
            if lin is not None:
                lin.stage_end("queue")
                lin.via = "replay"
                lin.detour("catchup_replay")
            t.session.update(tick, offset)
            if lin is not None:
                lin.stage_end("replay")
                _lineage.complete(lin, self._reg)
            replayed += 1
        t.mode = TENANT_LIVE
        t.shed_reason = None
        if t.label in self._shed_order:
            self._shed_order.remove(t.label)
        self._reg.inc("fleet.restored_tenants")
        self._reg.set_gauge("fleet.shed_tenants", len(self._shed_order))
        _metrics.trace_instant(
            "fleet.tenant_restored",
            {"tenant": t.label, "replayed": replayed})

    # -- reads --------------------------------------------------------------

    def forecast(self, label: str, horizon: int,
                 offsets=None) -> np.ndarray:
        """h-step forecasts for one tenant.  Live tenants forecast off
        their filtered state (and refresh the tenant's cache — the
        periodicity-aware precompute: one device call buys a whole
        forward path).  Shed tenants never touch the device on the hot
        path: the cached path is served phase-shifted by the ticks that
        arrived since it was taken, within the staleness bound; a stale
        or absent cache degrades to a predict-only forecast off the
        frozen state (one forecast call, no tick work) and re-caches.

        ``offsets (n_series, horizon)`` carries known future exogenous
        contributions (ARX).  An offset forecast is request-specific:
        it passes straight through to the session (live or frozen
        state) and never enters the shared cache — a phase-shifted
        replay of someone else's offsets would be silently wrong."""
        t = self._require(label)
        horizon = int(horizon)
        if horizon < 1:
            raise ValueError("forecast needs horizon >= 1")
        if offsets is not None:
            return t.session.forecast(horizon, offsets=offsets)
        if t.mode == TENANT_LIVE:
            fc = t.session.forecast(horizon)
            t.cache_fc = np.array(fc, copy=True)
            # stamp on the arrival clock, at the state's own position:
            # queued-but-undispatched ticks are arrivals the filtered
            # state has not absorbed yet
            t.cache_stamp = t.arrived - len(t.queue)
            return fc
        # a cache serve is a real request with a real latency — without
        # its own lineage, a shed tenant's e2e panel would silently go
        # blank exactly while it is degraded
        lin = _lineage.begin(t.label, via="cache")
        shift = t.elapsed_since_cache()
        if t.cache_fc is not None and shift <= self.policy.cache_staleness \
                and shift + horizon <= t.cache_fc.shape[1]:
            t.cache_serves += 1
            self._reg.inc("fleet.cache_serves")
            out = t.cache_fc[:, shift:shift + horizon]
            if lin is not None:
                lin.stage_end("cache")
                _lineage.complete(lin, self._reg)
            return out
        # stale (or too-short) cache: predict-only refresh off the
        # frozen state — still no tick dispatched, still bounded work;
        # cache far enough ahead to keep serving through the bound
        if lin is not None:
            lin.detour("cache_stale")
        self._reg.inc("fleet.cache_stale")
        depth = horizon + self.policy.cache_staleness
        fc = t.session.forecast(depth)
        t.cache_fc = np.array(fc, copy=True)
        t.cache_stamp = t.arrived
        if lin is not None:
            lin.stage_end("cache")
            _lineage.complete(lin, self._reg)
        return fc[:, :horizon]

    def last_status(self, label: str) -> np.ndarray:
        return self._require(label).session.lane_status

    # -- migration ----------------------------------------------------------

    def _pack_bundle(self, t: _Tenant) -> Dict[str, Any]:
        """The migration/checkpoint bundle for one tenant: the session's
        full ``checkpoint_blob`` PLUS every still-queued/buffered tick
        with its exogenous offsets.  :meth:`drain` and
        :meth:`checkpoint_tenant` write the SAME format — one adopt path
        restores both."""

        def pack(ticks, offsets):
            """(k, n_series) tick rows + offset rows (or None when no
            tick in the slice carried one) — drain and adopt must agree
            on BOTH, or an ARX tenant's replay would silently apply
            zero exogenous offsets and break the bitwise contract."""
            rows = [np.asarray(x, t.session._dtype) for x in ticks]
            stacked = np.stack(rows) if rows else \
                np.zeros((0, t.session.n_series), t.session._dtype)
            if not any(o is not None for o in offsets):
                return stacked, None
            return stacked, np.stack([
                np.asarray(o, t.session._dtype) if o is not None
                else np.zeros(t.session.n_series, t.session._dtype)
                for o in offsets])

        pending, pending_offs = pack([q[0] for q in t.queue],
                                     [q[1] for q in t.queue])
        catchup, catchup_offs = pack([c[0] for c in t.catchup],
                                     [c[1] for c in t.catchup])
        return {
            "format": _BUNDLE_FORMAT,
            "label": t.label,
            "mode": t.mode,
            "n_series": t.session.n_series,
            "pending": pending,
            "pending_offsets": pending_offs,
            "catchup": catchup,
            "catchup_offsets": catchup_offs,
            "session": t.session.checkpoint_blob(),
        }

    def checkpoint_tenant(self, label: str, path: str) -> Dict[str, Any]:
        """Crash-only snapshot of one tenant: the exact :meth:`drain`
        bundle (session blob + undispatched ticks), written via the
        atomic pytree writer — but the tenant stays attached and keeps
        serving.  ``adopt()`` of the bundle in a fresh process lands the
        tenant bitwise where it was at the snapshot; everything admitted
        after the snapshot is the caller's (auto-checkpointer's) loss
        window to bound."""
        t = self._require(label)
        bundle = self._pack_bundle(t)
        _checkpoint.save_pytree_atomic(path, bundle)
        self._reg.inc("fleet.tenant_checkpoints")
        return {"tenant": label, "path": path,
                "pending": int(bundle["pending"].shape[0]),
                "catchup": int(bundle["catchup"].shape[0])}

    def drain(self, label: str, path: str) -> Dict[str, Any]:
        """Move a tenant out of this scheduler: flush nothing, lose
        nothing — the bundle carries the session's full
        ``checkpoint_blob`` PLUS every still-queued/buffered tick, and
        lands via the atomic pytree writer, so a ``kill -9`` one
        instruction after :meth:`drain` returns leaves a bundle another
        process adopts bitwise.  The tenant is detached on success.
        The ``drop_tenant_process`` fault SIGKILLs right after the
        commit (forensics bundle first), pinning exactly that."""
        t = self._require(label)
        bundle = self._pack_bundle(t)
        pending, catchup = bundle["pending"], bundle["catchup"]
        _checkpoint.save_pytree_atomic(path, bundle)
        self._reg.inc("fleet.drained")
        # the bundle is committed: the queued ticks' journeys end HERE
        # in this process (the adopting scheduler mints fresh records) —
        # finalised before the injectable SIGKILL below, like the
        # forensics bundle, so a drain-kill leaves no orphans behind
        for entry in t.queue:
            if entry[3] is not None:
                entry[3].detour("drain")
                _lineage.complete(entry[3], self._reg,
                                  outcome="migrated")
        for entry in t.catchup:
            if entry[2] is not None:
                entry[2].detour("drain")
                _lineage.complete(entry[2], self._reg,
                                  outcome="migrated")
        _metrics.trace_instant(
            "fleet.tenant_drained",
            {"tenant": t.label, "pending": int(pending.shape[0]),
             "catchup": int(catchup.shape[0])})
        if _resilience.fleet_fault("drop_tenant_process") is not None:
            # a real SIGKILL runs no handlers: forensics first, like
            # the engine's kill_after_chunk
            from ..utils import flightrec as _flightrec
            _flightrec.record_incident(
                "drop_tenant_process",
                extra={"tenant": t.label, "bundle": path,
                       "note": "injected SIGKILL after drain commit"},
                registry=self._reg)
            os.kill(os.getpid(), signal.SIGKILL)
        self._pop_tenant(label)
        return {"tenant": label, "path": path,
                "pending": int(pending.shape[0]),
                "catchup": int(catchup.shape[0])}

    def adopt(self, path: str, *, replay: bool = True) -> str:
        """Restore a drained tenant bundle into this scheduler.

        Validation mirrors the journal's: the bundle's own fields are
        checked first (:class:`FleetRestoreMismatch` lists every
        disagreement), then the session half goes through
        ``ServingSession.from_blob``'s geometry/engine-policy
        validation — its ``ServingRestoreMismatch`` is chained under a
        :class:`FleetRestoreMismatch` so one exception type means "this
        bundle cannot serve here".  ``replay=True`` (default)
        immediately replays the bundle's undispatched ticks through the
        session so the adopted tenant is bitwise where the drained one
        would have been."""
        try:
            bundle = _checkpoint.load_pytree(path)
        except Exception as e:
            raise FleetRestoreMismatch(
                f"tenant bundle at {path!r} cannot be read: "
                f"{type(e).__name__}: {e}") from e
        diffs = []
        fmt = bundle.get("format")
        if fmt != _BUNDLE_FORMAT:
            diffs.append(f"  format: bundle={fmt!r} vs "
                         f"adopting-process={_BUNDLE_FORMAT}")
        label = bundle.get("label")
        try:
            check_label(label if isinstance(label, str) else "")
        except ValueError:
            diffs.append(f"  label: bundle={label!r} vs "
                         f"adopting-process=[A-Za-z0-9_-]+")
        n_series = bundle.get("n_series")
        pending = np.asarray(bundle.get("pending"))
        for name, arr in (("pending", pending),
                          ("catchup", np.asarray(bundle.get("catchup")))):
            if arr.ndim != 2 or (n_series is not None
                                 and arr.shape[1] != n_series):
                diffs.append(
                    f"  {name}: bundle shape={tuple(arr.shape)} vs "
                    f"adopting-process=(k, {n_series})")
        if diffs:
            raise FleetRestoreMismatch(
                f"tenant bundle at {path!r} disagrees with the adopting "
                f"scheduler; differing fields:\n" + "\n".join(diffs))
        if isinstance(label, str) and label in self._tenants:
            raise FleetRestoreMismatch(
                f"tenant bundle at {path!r} names label {label!r}, "
                f"which is already attached to {self.label!r} — a "
                f"tenant must live in exactly one scheduler")
        try:
            sess = ServingSession.from_blob(
                bundle["session"], source=path, registry=self._reg,
                label=label)
        except ValueError as e:
            raise FleetRestoreMismatch(
                f"tenant bundle at {path!r}: the session half refuses "
                f"this process ({e})") from e
        self.attach(sess)
        t = self._tenants[label]
        self._reg.inc("fleet.adopted")
        # chronological order is catchup (buffered while shed) FIRST,
        # then the still-queued pending ticks — both with their saved
        # exogenous offsets
        catchup = np.asarray(bundle.get("catchup"))
        c_offs = bundle.get("catchup_offsets")
        p_offs = bundle.get("pending_offsets")
        if replay:
            if len(catchup):
                sess.update_batch(catchup.T, offsets=None
                                  if c_offs is None else c_offs.T)
            if len(pending):
                sess.update_batch(pending.T, offsets=None
                                  if p_offs is None else p_offs.T)
        else:
            # deferred ingest: everything lands at the FRONT of the
            # live queue in stream order (the catch-up ring only drains
            # on a shed-restore, which a live tenant never takes —
            # parking ticks there would reorder them behind new
            # submits, or lose them)
            now = time.monotonic()

            def _migrated_lin():
                # fresh records for the adopted ticks — trace ids never
                # cross a process boundary; the origin finalised its
                # records as "migrated" at drain commit
                lin = _lineage.begin(label)
                if lin is not None:
                    lin.detour("adopt_migration")
                    lin.stage_end("admit")
                return lin

            deferred = [(np.array(row, copy=True),
                         None if c_offs is None else c_offs[i], now,
                         _migrated_lin())
                        for i, row in enumerate(catchup)]
            deferred += [(np.array(row, copy=True),
                          None if p_offs is None else p_offs[i], now,
                          _migrated_lin())
                         for i, row in enumerate(pending)]
            t.queue.extendleft(reversed(deferred))
            # the deferred ticks are stream arrivals for this tenant:
            # without advancing the clock, a later cache stamp
            # (arrived - len(queue)) would go negative and phase-shift
            # shed reads into the future.  Migration deliberately
            # bypasses queue_depth — dropping migrated ticks to honor a
            # backpressure bound would silently lose committed data.
            t.arrived += len(deferred)
        _metrics.trace_instant(
            "fleet.tenant_adopted",
            {"tenant": label, "replayed": int(replay)
             and (len(pending) + len(catchup))})
        return label

    # -- introspection ------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        qd = sum(len(t.queue) for t in self._tenants.values())
        return {
            "label": self.label,
            "tenants": len(self._tenants),
            "groups": len(self._groups),
            "queued": qd,
            "queue_depth": self.policy.queue_depth,
            "shed_tenants": len(self._shed_order),
            "slo_ms": self._slo_ms,
            "slo_burns": self._slo_burns,
            "p95_ms": self._p95_ms(),
            "window": len(self._lat),
        }

    def telemetry_summary(self) -> Dict[str, Any]:
        """Scrape-ready fleet panel for ``/snapshot.json``
        (``utils.telemetry.fleet_summaries``): the aggregate plus one
        row per tenant, plus — when a :class:`~.runtime.FleetRuntime`
        supervises this scheduler — its pump liveness block."""
        out = {**self.stats(),
               "tenant_rows": [t.summary() for t in
                               sorted(self._tenants.values(),
                                      key=lambda t: t.label)]}
        info = self._runtime_info
        if info is not None:
            try:
                out["pump"] = info()
            except Exception as e:  # noqa: BLE001 — scrape isolation
                out["pump"] = {"error": f"{type(e).__name__}: {e}"}
        return out
