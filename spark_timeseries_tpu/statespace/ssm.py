"""Batched linear-Gaussian state-space representation.

The serving tier's common currency (ROADMAP open item 3): every classical
family the framework fits — ARIMA, AR/ARX, EWMA, Holt-Winters — can be
expressed as a linear-Gaussian state-space model

    y_t = d + Z·α_t (+ offset_t) + ε_t,   ε_t ~ N(0, H)
    α_t = c + T·α_{t-1} + η_t,            η_t ~ N(0, Q)

over a small hidden state α (dimension ``m``: ``max(p, q+1)`` for ARMA,
``2 + period`` for Holt-Winters).  Once a series lives in this form, a
new observation is one O(m²) Kalman-filter step — constant work per tick,
independent of history length — instead of a full re-optimization through
``engine.stream_fit``, and the *exact* Gaussian likelihood (an accuracy
upgrade over the CSS objective, which drops the first ``max(p, q)``
residuals and ignores the stationary initial distribution) falls out of
the same recursion.

Two filter modes share one step (``statespace.kalman``):

- ``"exact"``: the textbook covariance-propagating filter.  Used by the
  ARMA-family converters (observation noise H = 0; all noise enters the
  state through the Harvey companion form) and by
  ``arima.fit(objective="exact")``.  State cov ``P`` starts at the
  stationary solution of the Lyapunov equation ``P = T P Tᵀ + Q``.
- ``"innovations"``: the single-source-of-error (ETS) form with the gain
  pinned to the model's own smoothing vector.  The Holt-Winters and EWMA
  recursions ARE this filter — the per-tick update reproduces the
  fitted model's recurrence bit-for-bit, with ``P`` degenerate (the
  innovation variance is the constant ``H = σ²``).

Everything here is a pytree of arrays with a leading ``(n_series,)``
batch dim, so sessions vmap/jit over whole panels; the static facts a
trace must specialize on (mode, state dim, differencing order) live in
:class:`SSMeta`, a hashable NamedTuple passed as a static jit argument —
never inside the traced pytrees.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

__all__ = ["StateSpace", "SSMeta", "FilterState", "initial_state",
           "stationary_covariance", "stationary_mean", "state_nbytes"]


class StateSpace(NamedTuple):
    """One family's batched state-space parameters (arrays only — static
    metadata lives in :class:`SSMeta` so the pytree jits cleanly).

    ``gain`` is the pinned predictive-form Kalman gain for
    ``mode="innovations"`` (the ETS smoothing vector); zeros — and unused
    — in ``mode="exact"``, where the gain comes from ``P`` each step.
    """
    T: jnp.ndarray       # (S, m, m) state transition
    Z: jnp.ndarray       # (S, m)    observation row vector
    c: jnp.ndarray       # (S, m)    state intercept
    d: jnp.ndarray       # (S,)      observation intercept
    H: jnp.ndarray       # (S,)      observation noise variance (σ² in
    #                                innovations mode; 0 for ARMA forms)
    Q: jnp.ndarray       # (S, m, m) state noise covariance
    gain: jnp.ndarray    # (S, m)    pinned gain (innovations mode)

    @property
    def n_series(self) -> int:
        return self.T.shape[0]

    @property
    def state_dim(self) -> int:
        return self.T.shape[-1]


class SSMeta(NamedTuple):
    """Static (hashable) facts about a :class:`StateSpace` — the jit keys.

    ``d_order`` is the integration order the converter folded out of the
    family (ARIMA's ``d``): the filter runs on the d-times-differenced
    series and carries a length-``d_order`` ring of the last raw
    differences so ticks arrive — and forecasts leave — on the raw scale.
    """
    family: str          # "arima" | "ar" | "arx" | "ewma" | "holt_winters"
    mode: str            # "exact" | "innovations"
    d_order: int         # integration order handled outside the filter
    m: int               # state dimension


class FilterState(NamedTuple):
    """Per-series filter carry — the whole of a serving session's mutable
    state (one small pytree of device buffers, O(m²) floats per series).

    ``a``/``P`` are the one-step *predicted* state mean/cov (the
    prediction-form filter: ``a = E[α_t | y_{1..t-1}]``), so the next
    tick's innovation and the h-step forecast both read straight off the
    carry.  ``ring[j] = Δʲ y_last`` (j < d_order) reconstructs raw-scale
    differences and integrations.  ``ssq`` (Σ v²/F), ``sumlogf``
    (Σ log F) and ``n_obs`` accumulate the pieces of the concentrated
    Gaussian likelihood in-graph; ``loglik`` is the running exact
    log-likelihood at the model's own noise scale.
    """
    a: jnp.ndarray        # (S, m)
    P: jnp.ndarray        # (S, m, m)
    ring: jnp.ndarray     # (S, d_order)
    loglik: jnp.ndarray   # (S,)
    ssq: jnp.ndarray      # (S,)
    sumlogf: jnp.ndarray  # (S,)
    n_obs: jnp.ndarray    # (S,) int32


def stationary_covariance(T: jnp.ndarray, Q: jnp.ndarray,
                          fallback_scale: float = 1e6) -> jnp.ndarray:
    """Batched stationary state covariance: solve ``P = T P Tᵀ + Q`` via
    the vec trick ``(I - T⊗T) vec(P) = vec(Q)`` (m² × m² solve — m is
    tiny, so this is a batched matmul-sized problem).

    Non-stationary lanes (unit/explosive roots make ``I - T⊗T``
    singular) fall back to a large diagonal ``fallback_scale · I`` — the
    standard quasi-diffuse initialization — instead of poisoning the
    batch with NaN.
    """
    T = jnp.asarray(T)
    Q = jnp.asarray(Q)
    m = T.shape[-1]
    batch = T.shape[:-2]
    # T ⊗ T, batched: (..., m, m, m, m) -> (..., m², m²)
    kron = jnp.einsum("...ij,...kl->...ikjl", T, T)
    kron = kron.reshape(*batch, m * m, m * m)
    eye = jnp.eye(m * m, dtype=T.dtype)
    vec_p = jnp.linalg.solve(eye - kron, Q.reshape(*batch, m * m, 1))
    P = vec_p.reshape(*batch, m, m)
    P = 0.5 * (P + jnp.swapaxes(P, -1, -2))     # symmetrize f-noise away
    ok = jnp.all(jnp.isfinite(P), axis=(-1, -2), keepdims=True)
    diffuse = fallback_scale * jnp.eye(m, dtype=T.dtype) \
        * (1.0 + jnp.abs(jnp.einsum("...ii->...", Q))[..., None, None])
    return jnp.where(ok, jnp.where(ok, P, 0.0), diffuse)


def stationary_mean(T: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """Batched stationary state mean ``(I - T)⁻¹ c``; non-stationary
    lanes fall back to ``c`` itself (the zero-history prior)."""
    T = jnp.asarray(T)
    c = jnp.asarray(c)
    m = T.shape[-1]
    eye = jnp.eye(m, dtype=T.dtype)
    mu = jnp.linalg.solve(eye - T, c[..., None])[..., 0]
    ok = jnp.all(jnp.isfinite(mu), axis=-1, keepdims=True)
    return jnp.where(ok, jnp.where(ok, mu, 0.0), c)


def initial_state(ssm: StateSpace, meta: SSMeta) -> FilterState:
    """Pre-data filter state: stationary mean/cov for ``mode="exact"``
    (the exact-likelihood prior), zero mean and degenerate cov for
    ``mode="innovations"`` (the converters overwrite ``a`` with the
    model's own initial components)."""
    S = ssm.n_series
    m = ssm.state_dim
    dtype = ssm.T.dtype
    zeros = jnp.zeros((S,), dtype)
    if meta.mode == "exact":
        a0 = stationary_mean(ssm.T, ssm.c)
        p0 = stationary_covariance(ssm.T, ssm.Q)
    else:
        a0 = jnp.zeros((S, m), dtype)
        p0 = jnp.zeros((S, m, m), dtype)
    return FilterState(a=a0, P=p0,
                       ring=jnp.zeros((S, meta.d_order), dtype),
                       loglik=zeros, ssq=zeros, sumlogf=zeros,
                       n_obs=jnp.zeros((S,), jnp.int32))


def state_nbytes(tree) -> int:
    """Total bytes of the array leaves of a pytree — the
    ``serving.state_bytes`` gauge's source."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None and hasattr(leaf, "size"):
            nbytes = leaf.size * leaf.dtype.itemsize
        total += int(nbytes or 0)
    return total
