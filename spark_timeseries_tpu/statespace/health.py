"""Per-lane in-graph serving health: divergence detection + quarantine.

A numerically diverged serving lane — NaN-poisoned state, exploding
covariance, a model that stopped describing its stream — silently emits
garbage forecasts for the rest of the session's life unless something
*watches* the filter.  This module is that watcher, and it lives INSIDE
the single jitted per-tick update (``serving._update_impl``), so
monitoring adds zero XLA compiles after warmup and zero host round-trips
per tick: everything here is array math over the same ``(bucket,)``
lanes the filter already touches.  It is the serving half of the
failure state machine ``utils.resilience`` built for batch
(docs/design.md §3b): classify → isolate → recover, but per tick
instead of per fit.

Three signals feed one per-lane status in the ``ok(0) < suspect(1) <
diverged(2)`` lattice (the quality plane — ``statespace.quality`` —
extends it with a fourth code, ``drifted(3)``: numerically out of band
on *accuracy* but still finite, so the lane keeps serving while flagged
for refit; see that module for the escalation semantics):

- **standardized-innovation tracking**: for a well-specified lane the
  standardized innovation ``ν²/F`` is χ²₁ (mean 1, variance 2).  An
  exponentially-weighted mean of it (``ew' = (1−α)·ew + α·ν²/F``, missing
  ticks hold) has standard deviation ``σ_ew ≈ sqrt(α/(2−α) · 2)`` at
  stationarity, so fixed thresholds are calibrated z-scores against the
  χ² band: the defaults (α = 0.02 → σ_ew ≈ 0.142) put ``suspect`` at
  ≈ 1 + 8.5σ and ``diverged`` at ≈ 1 + 21σ — far enough out that a
  5000-tick well-specified stream quarantines nothing (pinned by test),
  close enough in that a poisoned state (whose first innovation is
  astronomically out of band) trips in one tick.
- **non-finite detection**: any NaN/Inf in the lane's predicted state,
  covariance, or difference ring, a non-finite innovation on an observed
  tick, or a non-positive/non-finite innovation variance → ``diverged``
  immediately.
- **covariance conditioning**: the exact-mode subtractive covariance
  update can go indefinite under f32 round-off; ``HealthPolicy.joseph``
  routes the step through the Joseph stabilized form
  (``kalman.filter_step_one``), which is symmetric-PSD by construction —
  prevention for the failure the other two signals detect.

``diverged`` is **sticky** and quarantines the lane: its later ticks are
masked to missing inside the same jitted step (predict-only — the lane
contributes no likelihood and its poison cannot spread into the
accumulators), until ``ServingSession.heal()`` refits it from the
bounded per-lane history ring through the batch resilient path and
splices a fresh state in.  ``suspect`` is advisory and self-clearing:
the lane keeps serving, the EW score decides whether it escalates or
recovers.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from .kalman import filter_step_panel
from .ssm import FilterState, SSMeta, StateSpace

__all__ = ["LANE_OK", "LANE_SUSPECT", "LANE_DIVERGED", "LANE_DRIFTED",
           "LANE_NAMES", "HealthPolicy", "LaneHealth", "initial_health",
           "monitored_step", "monitor_panel", "shed_priority"]

LANE_OK = 0        # EW standardized-innovation score inside the χ² band
LANE_SUSPECT = 1   # score out of band but finite — advisory, self-clears
LANE_DIVERGED = 2  # non-finite state/covariance or score far out of
#                    band — sticky; quarantined (predict-only) until heal
LANE_DRIFTED = 3   # the quality plane's drift detector alarmed: the lane
#                    serves on (never quarantined) but its online error
#                    has sustainedly left the fit-time baseline — sticky
#                    until heal(drifted=True) refits it.  Severity sits
#                    between suspect and diverged; the code is 3 (not
#                    renumbering diverged) so pre-quality checkpoints
#                    stay restorable.

LANE_NAMES = {LANE_OK: "ok", LANE_SUSPECT: "suspect",
              LANE_DIVERGED: "diverged", LANE_DRIFTED: "drifted"}


class HealthPolicy(NamedTuple):
    """Static (hashable) health knobs — part of the serving update's jit
    key, like :class:`~spark_timeseries_tpu.statespace.ssm.SSMeta`.

    ``ew_alpha`` is the EW weight of the standardized-innovation mean;
    ``suspect_hi`` / ``diverged_hi`` are the band edges on that mean
    (χ²₁ has mean 1 — see the module docstring for the z-score
    calibration of the defaults); ``joseph`` selects the stabilized
    covariance update for exact-mode lanes; ``forecast_policy`` is what
    quarantined lanes report from ``forecast`` — ``"nan"`` (explicitly
    absent) or ``"last_good"`` (mean propagation from the lane's last
    pre-divergence state)."""
    ew_alpha: float = 0.02
    suspect_hi: float = 2.2
    diverged_hi: float = 4.0
    joseph: bool = True
    forecast_policy: str = "nan"

    def validate(self) -> "HealthPolicy":
        if not 0.0 < self.ew_alpha <= 1.0:
            raise ValueError(f"ew_alpha must be in (0, 1], "
                             f"got {self.ew_alpha}")
        if not 1.0 < self.suspect_hi < self.diverged_hi:
            raise ValueError(
                f"need 1 < suspect_hi < diverged_hi, got "
                f"{self.suspect_hi} / {self.diverged_hi}")
        if self.forecast_policy not in ("nan", "last_good"):
            raise ValueError(
                f"forecast_policy must be 'nan' or 'last_good', "
                f"got {self.forecast_policy!r}")
        return self


class LaneHealth(NamedTuple):
    """Per-lane monitor carry, riding next to ``FilterState`` in the
    serving session's device buffers (O(m) extra floats per lane).

    ``ew`` is the EW mean of ``ν²/F`` (starts at 1.0, the χ²₁ mean —
    the monitor needs no warmup period); ``status`` the ``LANE_*`` code;
    ``good_a`` / ``good_ring`` snapshot the last non-diverged predicted
    state mean and raw-difference ring, the ``"last_good"`` forecast
    source (they stop following a lane the tick it diverges, so they
    are never poisoned)."""
    ew: jnp.ndarray         # (S,)
    status: jnp.ndarray     # (S,) int32
    good_a: jnp.ndarray     # (S, m)
    good_ring: jnp.ndarray  # (S, d_order)


def initial_health(state: FilterState) -> LaneHealth:
    """All-OK monitor state seeded from a (bootstrapped) filter state."""
    S = state.a.shape[0]
    dtype = state.a.dtype
    return LaneHealth(ew=jnp.ones((S,), dtype),
                      status=jnp.zeros((S,), jnp.int32),
                      good_a=state.a,
                      good_ring=state.ring)


def monitored_step(ssm: StateSpace, state: FilterState,
                   health: LaneHealth, y: jnp.ndarray,
                   offset: jnp.ndarray, meta: SSMeta,
                   policy: HealthPolicy
                   ) -> Tuple[FilterState, LaneHealth,
                              Tuple[jnp.ndarray, jnp.ndarray]]:
    """One health-monitored tick across the panel — the serving tier's
    traced kernel (``meta``/``policy`` static).  Fully fused with the
    filter step: quarantined lanes see a masked (missing) tick and
    predict forward; everyone else filters normally, then the three
    detection signals update the lane status.  Returns
    ``(state', health', (v, F))``.
    """
    dtype = y.dtype
    quarantined = health.status == LANE_DIVERGED
    nan = jnp.asarray(jnp.nan, dtype)
    y_eff = jnp.where(quarantined, nan, y)
    state2, (v, F) = filter_step_panel(ssm, state, y_eff, offset, meta,
                                       joseph=policy.joseph)

    observed = jnp.isfinite(y_eff)
    score = v * v / F
    score_ok = jnp.isfinite(score)
    alpha = jnp.asarray(policy.ew_alpha, dtype)
    ew = jnp.where(observed & score_ok,
                   (1.0 - alpha) * health.ew + alpha * score,
                   health.ew)

    finite = (jnp.all(jnp.isfinite(state2.a), axis=-1)
              & jnp.all(jnp.isfinite(state2.P), axis=(-2, -1))
              & jnp.all(jnp.isfinite(state2.ring), axis=-1)
              & jnp.isfinite(ew))
    f_bad = observed & ~(jnp.isfinite(F) & (F > 0))
    v_bad = observed & ~score_ok
    bad_now = ~finite | f_bad | v_bad

    status = jnp.where(ew > policy.suspect_hi, LANE_SUSPECT, LANE_OK)
    status = jnp.where((ew > policy.diverged_hi) | bad_now | quarantined,
                       LANE_DIVERGED, status).astype(jnp.int32)

    good = status != LANE_DIVERGED
    good_a = jnp.where(good[:, None], state2.a, health.good_a)
    good_ring = jnp.where(good[:, None], state2.ring, health.good_ring) \
        if meta.d_order else health.good_ring
    return state2, LaneHealth(ew, status, good_a, good_ring), (v, F)


def shed_priority(status) -> Tuple[int, int, int]:
    """The fleet shed ladder's per-tenant rank over a lane-status vector:
    ``(n_diverged, n_drifted, n_suspect)``, compared lexicographically
    descending — tenants whose lanes are already diverged (quarantined,
    serving NaN or last-good anyway) shed first under SLO pressure, then
    drift-flagged tenants (persistently inaccurate — a cached forecast
    serves them no worse than their drifted model does), then
    suspect-laden tenants, and fully healthy tenants only last — the
    ``ok < suspect < drifted < diverged`` severity order, applied.  Pure
    host math; the scheduler sorts on this (label as the deterministic
    tie-break)."""
    import numpy as np

    s = np.asarray(status)
    return (int(np.sum(s == LANE_DIVERGED)),
            int(np.sum(s == LANE_DRIFTED)),
            int(np.sum(s == LANE_SUSPECT)))


def monitor_panel(ssm: StateSpace, state: FilterState,
                  health: LaneHealth, ys: jnp.ndarray, meta: SSMeta,
                  policy: HealthPolicy,
                  offsets: Optional[jnp.ndarray] = None
                  ) -> Tuple[FilterState, LaneHealth]:
    """Stream a whole ``(S, n)`` panel of ticks through
    :func:`monitored_step` as one ``lax.scan`` — the batch driver for
    calibration/false-positive testing and for bulk catch-up ingest
    (replaying a backlog through the exact per-tick semantics, health
    transitions included, without n host round-trips)."""
    ys = jnp.asarray(ys)
    rows = int(state.a.shape[0])
    if ys.ndim != 2 or int(ys.shape[0]) != rows:
        # without this, a panel whose width disagrees with the filter
        # state (a transposed stream, an unbucketed tenant panel)
        # surfaces as an opaque broadcast error from inside the scan
        raise ValueError(
            f"monitor_panel expects a (S, n) tick panel with S == the "
            f"filter state's {rows} bucketed lanes, got shape "
            f"{tuple(ys.shape)}; pad the panel to the session bucket "
            f"(or transpose a time-major stream) first")
    offs = jnp.zeros_like(ys) if offsets is None \
        else jnp.asarray(offsets, ys.dtype)

    def step(carry, inp):
        st, h = carry
        y, off = inp
        st2, h2, _ = monitored_step(ssm, st, h, y, off, meta, policy)
        return (st2, h2), None

    (final_state, final_health), _ = lax.scan(
        step, (state, health), (ys.T, offs.T))
    return final_state, final_health
