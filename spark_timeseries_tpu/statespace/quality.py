"""Live forecast-quality plane: per-tick anomaly scores, rolling online
accuracy, and drift alarms — fused into the serving tick.

The serving tier (§7) made ingest O(1) and the health tier (§7/§9 —
``statespace.health``) made *numerical* failure observable, but accuracy
stayed an **offline** fact: the backtest tier (§9) scores a model before
it serves, and nothing watches whether a serving model's forecasts are
still any good once traffic flows.  ARIMA_PLUS (PAPERS.md, arXiv
2510.24452) treats "forecast + explain + flag anomalies, automatically"
as the product surface; this module is that surface for the serving
tier, with **zero new per-tick device dispatches** — everything below is
array math fused into the same single jitted update the session already
runs (``serving._update_impl``), so the warmed-tick 0-recompile pin
holds with quality armed (pinned by test).

Three signals per lane, per tick:

- **anomaly score** — the standardized innovation ``ν/√F`` (signed) and
  its EW aggregate (``LaneHealth.ew``, the EW mean of ``ν²/F`` the χ²
  health band already tracks).  Both are promoted onto
  :class:`~spark_timeseries_tpu.statespace.serving.TickResult`
  (``anomaly`` / ``anomaly_ew``) instead of staying an internal lattice
  input: for a well-specified lane ``ν/√F ~ N(0, 1)``, so the score IS
  a per-tick z-score users can threshold/alert on directly.  NaN on
  missing and quarantined (predict-only) ticks.
- **rolling online accuracy** — the session keeps a bounded
  device-resident ring of its own ``horizon``-step-ahead forecasts
  (:class:`QualityState.fc_ring`, O(horizon) floats per lane).  Each
  tick the forecast made ``horizon`` ticks ago is scored against the
  arriving actual with the backtest tier's NaN-masked pointwise
  definitions (``backtest.evaluate.masked_pointwise`` — sMAPE with
  0/0 → 0, MASE against the fit-time naive-MAE scale, interval coverage
  against the model's own ψ-weight half-widths), folded into
  exponentially-weighted means (``ew_alpha``).  A tick only scores when
  both the forecast and the actual are finite and the ring is warm.
- **drift alarm** — a Page-Hinkley detector (one-sided CUSUM) on the
  standardized-innovation score against its fit-time baseline: for a
  well-specified lane ``E[ν²/F] = 1``, so ``cusum' = max(0, cusum +
  ν²/F − 1 − ph_delta)`` drifts down under the null and climbs linearly
  under a sustained mean/level shift; ``cusum > ph_lambda`` trips a
  **sticky** ``drifted`` status (``health.LANE_DRIFTED``).  Calibration:
  χ²₁ steps have variance 2, so the default ``ph_delta = 0.5`` /
  ``ph_lambda = 50`` put the per-lane false-alarm odds around
  ``exp(−2·δ·λ/σ²) = e^{−25}`` (Wald's approximation) — a stationary
  5000-tick 64-lane stream alarms nothing (pinned by test) — while a
  regime shift of ``k`` innovation standard deviations (score mean
  ``1 + k²``) alarms after ≈ ``λ/(k² − δ)`` ticks (~30 ticks at
  k = 1.3).  Drift deliberately catches what the χ² EW band cannot: a
  shift big enough to matter but too small to ever cross
  ``diverged_hi`` accumulates here instead of self-clearing as
  ``suspect``.

The lattice becomes ``ok < suspect < drifted < diverged``: ``drifted``
lanes keep serving (never quarantined — their forecasts are degraded,
not garbage) until ``ServingSession.heal(drifted=True)`` refits them
from the history ring — whose bounded window is by then dominated by
the post-shift regime — through the batch resilient path with the
auto-order mini candidate search, splices the recovered lanes back, and
resets their quality state (fresh MASE scale and coverage half-widths
from the refit bootstrap).  Post-heal accuracy recovers to a fresh
fit's (the regime-shift acceptance pin).

ARX caveat: online scoring adds the tick's own exogenous offset to the
stored forecast, which is exact at ``horizon=1`` (the offset enters the
observation additively); at ``horizon>1`` intermediate future offsets
are unknown at forecast time and assumed zero.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from .health import LANE_DIVERGED, LANE_DRIFTED, LaneHealth
from .kalman import forecast_mean
from .ssm import FilterState, SSMeta, StateSpace

__all__ = ["QualityPolicy", "QualityState", "initial_quality",
           "quality_step", "quality_panel", "forecast_half_widths",
           "naive_scale"]


class QualityPolicy(NamedTuple):
    """Static (hashable) quality knobs — part of the serving update's jit
    key alongside ``SSMeta``/``HealthPolicy`` (arming quality changes the
    traced program, so two sessions coalesce only when their quality
    policies agree).

    ``horizon`` is the online-accuracy lead time (the forecast ring's
    depth: each tick scores the ``horizon``-step-ahead forecast made
    ``horizon`` ticks ago); ``ew_alpha`` the EW weight of the online
    sMAPE/MASE/coverage means; ``ph_delta``/``ph_lambda`` the
    Page-Hinkley drift allowance and alarm threshold on the
    standardized-innovation score (see the module docstring for the
    false-alarm calibration); ``coverage`` the nominal level of the
    online interval-coverage metric."""
    horizon: int = 1
    ew_alpha: float = 0.05
    ph_delta: float = 0.5
    ph_lambda: float = 50.0
    coverage: float = 0.9

    def validate(self) -> "QualityPolicy":
        if int(self.horizon) < 1:
            raise ValueError(f"horizon must be >= 1, got {self.horizon}")
        if not isinstance(self.horizon, int):
            # the horizon is a static trace parameter (ring width, scan
            # length) — normalize to a plain int so equal policies hash
            # equal and the jit key never splits on 2 vs 2.0
            return self._replace(horizon=int(self.horizon)).validate()
        if not 0.0 < self.ew_alpha <= 1.0:
            raise ValueError(f"ew_alpha must be in (0, 1], "
                             f"got {self.ew_alpha}")
        if self.ph_delta <= 0 or self.ph_lambda <= 0:
            raise ValueError(
                f"ph_delta/ph_lambda must be > 0, got "
                f"{self.ph_delta}/{self.ph_lambda}")
        if not 0.0 < self.coverage < 1.0:
            raise ValueError(f"coverage must be in (0, 1), "
                             f"got {self.coverage}")
        return self


class QualityState(NamedTuple):
    """Per-lane quality carry, riding next to ``FilterState`` /
    ``LaneHealth`` in the session's device buffers — O(horizon) floats
    per lane, every leaf batched on the series axis so the fleet tier's
    lane-wise gather/scatter coalescing works unchanged.

    ``fc_ring[:, pos]`` holds the raw-scale ``horizon``-step forecast
    made for the *current* tick (written ``horizon`` ticks ago); ``pos``
    cycles 0..horizon−1 and ``warm`` saturates at ``horizon`` (a slot is
    scoreable only once the ring has wrapped).  ``scale`` is the
    fit-time lag-1 naive MAE (the MASE denominator — the same definition
    the backtest tier uses) and ``half`` the model's own ψ-weight
    ``horizon``-step interval half-width; both are per-lane constants
    refreshed on heal.  ``ew_*``/``n_scored`` are the EW online metrics;
    ``ph`` the Page-Hinkley CUSUM and ``drifted`` the sticky alarm
    flag."""
    fc_ring: jnp.ndarray    # (S, horizon)
    pos: jnp.ndarray        # (S,) int32
    warm: jnp.ndarray       # (S,) int32, saturates at horizon
    scale: jnp.ndarray      # (S,)
    half: jnp.ndarray       # (S,)
    ew_smape: jnp.ndarray   # (S,)
    ew_mase: jnp.ndarray    # (S,)
    ew_cover: jnp.ndarray   # (S,)
    n_scored: jnp.ndarray   # (S,) int32
    ph: jnp.ndarray         # (S,)
    drifted: jnp.ndarray    # (S,) bool


def initial_quality(n_series: int, policy: QualityPolicy, dtype,
                    scale, half) -> QualityState:
    """A cold quality state: empty forecast ring, zeroed EW metrics and
    drift statistic.  ``scale``/``half`` are the per-lane fit-time
    baselines (:func:`naive_scale` / :func:`forecast_half_widths`)."""
    S = int(n_series)
    zeros = jnp.zeros((S,), dtype)
    zi = jnp.zeros((S,), jnp.int32)
    return QualityState(
        fc_ring=jnp.full((S, int(policy.horizon)), jnp.nan, dtype),
        pos=zi, warm=zi,
        scale=jnp.asarray(scale, dtype), half=jnp.asarray(half, dtype),
        ew_smape=zeros, ew_mase=zeros, ew_cover=zeros,
        n_scored=zi, ph=zeros, drifted=jnp.zeros((S,), jnp.bool_))


def naive_scale(history) -> "jnp.ndarray":
    """Per-lane lag-1 naive MAE of a raw history window (NaN pairs
    masked) — the fit-time MASE denominator, matching the backtest
    tier's default (non-seasonal m=1) scaling.  Host-side NumPy (called
    once per session start / heal, never per tick); lanes with no
    finite pair come back NaN and their online MASE never scores."""
    import numpy as np

    h = np.asarray(history, np.float64)
    if h.ndim == 1:
        h = h[None]
    if h.shape[1] < 2:
        return np.full((h.shape[0],), np.nan)
    d1 = h[:, 1:] - h[:, :-1]
    m = np.isfinite(d1)
    cnt = m.sum(axis=1)
    s = np.where(m, np.abs(d1), 0.0).sum(axis=1)
    return np.where(cnt > 0, s / np.maximum(cnt, 1), np.nan)


def forecast_half_widths(ssm: StateSpace, meta: SSMeta, horizon: int,
                         conf: float) -> jnp.ndarray:
    """Symmetric ``conf``-level forecast-band half-widths at lead time
    ``horizon``, per lane, off a **serving-calibrated** state-space form
    (``convert.bootstrap`` already folded σ² into Q/H — unlike the
    backtest tier's unit-scale ``_half_widths``, no external σ² rides
    in).  Same ψ-weight construction as ``backtest.evaluate``: exact
    mode reads the noise loading off ``Q``'s first column (Harvey form:
    ``Q = σ²RRᵀ`` with ``R₀ = 1``, so ``σ² = Q[0,0]`` and
    ``σR = Q[:, 0]/σ``); innovations mode is the single-source-of-error
    expansion ``ψ₀ = σ, ψ_k = σ·Z T^{k-1} gain``; ``d_order``
    integrations are cumulative sums of the ψ sequence.  Eager host-side
    math (once per session start / heal)."""
    from ..models.base import normal_quantile

    dtype = ssm.T.dtype
    tiny = jnp.asarray(1e-30, dtype)
    psis = []
    if meta.mode == "exact":
        s2 = jnp.maximum(ssm.Q[:, 0, 0], tiny)
        x = ssm.Q[:, :, 0] / jnp.sqrt(s2)[:, None]
        for _ in range(int(horizon)):
            psis.append(jnp.einsum("sm,sm->s", ssm.Z, x))
            x = jnp.einsum("smk,sk->sm", ssm.T, x)
    else:
        s = jnp.sqrt(jnp.maximum(ssm.H, tiny))
        x = ssm.gain * s[:, None]
        psis.append(s)
        for _ in range(int(horizon) - 1):
            psis.append(jnp.einsum("sm,sm->s", ssm.Z, x))
            x = jnp.einsum("smk,sk->sm", ssm.T, x)
    psi = jnp.stack(psis, axis=-1)                           # (S, H)
    for _ in range(meta.d_order):
        psi = jnp.cumsum(psi, axis=-1)
    var = jnp.cumsum(psi * psi, axis=-1)[:, int(horizon) - 1]
    return normal_quantile(float(conf), dtype) * jnp.sqrt(var)


def quality_step(policy: QualityPolicy, meta: SSMeta, ssm: StateSpace,
                 state2: FilterState, health2: LaneHealth,
                 qstate: QualityState, y: jnp.ndarray,
                 offset: jnp.ndarray, v: jnp.ndarray, f: jnp.ndarray
                 ) -> Tuple[LaneHealth, QualityState]:
    """One quality tick across the panel, fused into the serving update
    (``policy``/``meta`` static; called from ``serving._update_impl``
    right after ``health.monitored_step``).

    ``state2``/``health2`` are the post-filter carries, ``v``/``f`` the
    tick's innovations and variances.  Scores the ring's due forecast
    against ``y``, folds the EW online metrics, advances the
    Page-Hinkley statistic, overlays the sticky ``drifted`` status onto
    the lane lattice (never demoting ``diverged``), and writes the next
    ``horizon``-step forecast into the freed ring slot.  Returns
    ``(health', qstate')``.
    """
    from ..backtest.evaluate import masked_pointwise

    dtype = y.dtype
    H = policy.horizon          # static (validated int ≥ 1)
    S = y.shape[0]
    rows = jnp.arange(S)

    # v is NaN exactly on missing and quarantined (predict-only) ticks
    observed = jnp.isfinite(v) & jnp.isfinite(f) & (f > 0)
    score = jnp.where(observed, v * v / f, jnp.zeros((), dtype))

    # -- score the forecast made `horizon` ticks ago against this tick.
    # The stored forecast omitted exogenous offsets (unknown at forecast
    # time); the arriving tick's own offset enters the observation
    # additively, so adding it back is exact at horizon 1.
    fc_due = qstate.fc_ring[rows, qstate.pos] + offset
    ring_warm = qstate.warm >= H
    mask, abserr, smape_pt = masked_pointwise(
        jnp.where(ring_warm, fc_due, jnp.asarray(jnp.nan, dtype)),
        jnp.where(observed, y, jnp.asarray(jnp.nan, dtype)))
    ok_scale = jnp.isfinite(qstate.scale) & (qstate.scale > 0)
    mase_pt = abserr / jnp.where(ok_scale, qstate.scale,
                                 jnp.ones((), dtype))
    cover_pt = (abserr <= qstate.half).astype(dtype)

    beta = jnp.asarray(policy.ew_alpha, dtype)

    def ew_fold(ew, pt, m):
        # seed on each metric's OWN first valid point (a NaN-scale lane
        # must never seed its MASE with an unscaled error)
        first = m & (qstate.n_scored == 0)
        upd = (1.0 - beta) * ew + beta * pt
        return jnp.where(first, pt, jnp.where(m, upd, ew))

    ew_smape = ew_fold(qstate.ew_smape, smape_pt, mask)
    ew_mase = ew_fold(qstate.ew_mase, mase_pt, mask & ok_scale)
    ew_cover = ew_fold(qstate.ew_cover, cover_pt, mask)
    n_scored = qstate.n_scored + mask.astype(jnp.int32)

    # -- Page-Hinkley drift statistic on the standardized-innovation
    # score vs its fit-time baseline E[ν²/F] = 1 (holds on unscored
    # ticks; sticky alarm — only heal resets it)
    delta = jnp.asarray(policy.ph_delta, dtype)
    ph = jnp.where(observed,
                   jnp.maximum(jnp.zeros((), dtype),
                               qstate.ph + score - 1.0 - delta),
                   qstate.ph)
    drifted = qstate.drifted | (ph > jnp.asarray(policy.ph_lambda, dtype))

    status = health2.status
    status = jnp.where((status != LANE_DIVERGED) & drifted,
                       LANE_DRIFTED, status).astype(jnp.int32)

    # -- write the next horizon-step forecast into the slot just scored
    # (raw scale, integrated through the post-update difference ring)
    fc_new = forecast_mean(meta, H, ssm, state2.a, state2.ring,
                           jnp.zeros((S, H), dtype))[:, H - 1]
    qstate2 = QualityState(
        fc_ring=qstate.fc_ring.at[rows, qstate.pos].set(fc_new),
        pos=(qstate.pos + 1) % H,
        warm=jnp.minimum(qstate.warm + 1, H),
        scale=qstate.scale, half=qstate.half,
        ew_smape=ew_smape, ew_mase=ew_mase, ew_cover=ew_cover,
        n_scored=n_scored, ph=ph, drifted=drifted)
    return health2._replace(status=status), qstate2


def quality_panel(ssm: StateSpace, state: FilterState,
                  health: LaneHealth, qstate: QualityState,
                  ys: jnp.ndarray, meta: SSMeta, policy, quality,
                  offsets: Optional[jnp.ndarray] = None
                  ) -> Tuple[FilterState, LaneHealth, QualityState]:
    """Stream a whole ``(S, n)`` tick panel through the fused
    monitored + quality step as one ``lax.scan`` — the bulk driver for
    drift-calibration / false-alarm testing (5000 stationary ticks in
    one dispatch instead of 5000 host round-trips), with semantics
    identical to per-tick ``ServingSession.update`` calls."""
    from .health import monitored_step

    ys = jnp.asarray(ys)
    rows = int(state.a.shape[0])
    if ys.ndim != 2 or int(ys.shape[0]) != rows:
        raise ValueError(
            f"quality_panel expects a (S, n) tick panel with S == the "
            f"filter state's {rows} bucketed lanes, got shape "
            f"{tuple(ys.shape)}; pad the panel to the session bucket "
            f"(or transpose a time-major stream) first")
    offs = jnp.zeros_like(ys) if offsets is None \
        else jnp.asarray(offsets, ys.dtype)

    def step(carry, inp):
        st, h, q = carry
        y, off = inp
        st2, h2, (v, f) = monitored_step(ssm, st, h, y, off, meta,
                                         policy)
        h3, q2 = quality_step(quality, meta, ssm, st2, h2, q, y, off,
                              v, f)
        return (st2, h3, q2), None

    (fs, fh, fq), _ = lax.scan(step, (state, health, qstate),
                               (ys.T, offs.T))
    return fs, fh, fq
