"""Batched Kalman filtering: O(1) per-tick updates, exact likelihood.

One step function serves every consumer (the prediction-form filter —
state = one-step-ahead predicted mean/cov):

    v_t = y_t - d - Z·a_t                     innovation
    F_t = Z P Zᵀ + H        (exact)   |  H    (innovations)
    K_t = T P Zᵀ / F        (exact)   |  gain (innovations)
    a_{t+1} = T a_t + c + K_t v_t
    P_{t+1} = T P Tᵀ + Q - F K Kᵀ     (exact; predict-only when missing)
    ll     += -½ (log 2πF + v²/F)

A missing tick (NaN, or a zero step weight on ragged lanes) skips the
update — the state predicts forward and contributes no likelihood — so
NaN-padded panels filter without host branching.  The per-step work is
O(m²) in the (tiny) state dimension and independent of series length:
that is the serving tier's O(1)-per-tick contract.

Three drivers:

- :func:`filter_step_panel` — one tick for a whole panel (the
  ``ServingSession.update`` kernel; vmapped, jit-cached by the caller).
- :func:`filter_panel` — a whole series per lane as one ``lax.scan``,
  accumulating the exact log-likelihood (and its concentrated-σ² pieces)
  in-graph; optionally returns the predicted-state path for diagnostics.
- :func:`filter_panel_parallel` — the parallel-prefix variant for pinned
  gains: the filtered-state recursion ``x_t = (T - gZ) x_{t-1} + c +
  g(y_t - d)`` is an affine map, so
  :func:`~spark_timeseries_tpu.ops.scan_parallel.affine_recurrence`
  evaluates the whole series in O(log n) depth (time-shardable, same
  results as the sequential scan).

:func:`concentrated_loglik` turns the accumulated ``(ssq, sumlogf,
n_obs)`` into the σ²-profiled Gaussian log-likelihood — the objective
``arima.fit(objective="exact")`` maximizes.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .ssm import FilterState, SSMeta, StateSpace

__all__ = ["filter_step_one", "filter_step_panel", "filter_panel",
           "filter_panel_parallel", "concentrated_loglik", "FilterResult",
           "forecast_mean", "steady_gain", "filter_forecast_origin",
           "pinned_state_path"]


class FilterResult(NamedTuple):
    """Outcome of a whole-series filter pass.

    ``state`` is the carry after the last tick (ready for serving);
    ``loglik`` the exact Gaussian log-likelihood at the model's noise
    scale; ``path`` (only when requested) the per-step
    ``(a_pred, P_pred, v, F)`` tuple, time-major."""
    state: FilterState
    loglik: jnp.ndarray
    path: Optional[Tuple[jnp.ndarray, ...]] = None


def _diff_step(ring: jnp.ndarray, y: jnp.ndarray, d_order: int):
    """Advance the raw-difference ring by one tick.

    ``ring[j] = Δʲ y_prev``; returns ``(ring', Δ^d y)``.  The first
    ``d_order`` ticks after a zero ring produce garbage differences —
    callers weight those steps out (the burn-in mirrors the CSS path's
    ``differences_of_order_d(ts, d)[d:]`` trim).  A NaN tick holds the
    ring (one bad tick must not poison every later difference)."""
    if d_order == 0:
        return ring, y
    levels = []
    cur = y
    for j in range(d_order):
        levels.append(cur)
        cur = cur - ring[j]
    ok = jnp.isfinite(y)
    new_ring = jnp.where(ok, jnp.stack(levels), ring)
    diffed = jnp.where(ok, cur, jnp.nan)
    return new_ring, diffed


def filter_step_one(ssm: StateSpace, meta: SSMeta, a: jnp.ndarray,
                    P: jnp.ndarray, y: jnp.ndarray,
                    w: jnp.ndarray, joseph: bool = False):
    """One prediction-form filter step for a single lane (vmapped by the
    panel drivers).  ``w`` (0/1) is the ragged/burn-in step weight; a NaN
    ``y`` or ``w == 0`` predicts without updating.  Returns
    ``(a', P', v, F, ll_inc, observed)``.

    ``joseph=True`` (exact mode only; trace-time static) replaces the
    standard covariance update with the Joseph stabilized form
    ``P_f = (I − K_f Z) P (I − K_f Z)ᵀ + K_f H K_fᵀ`` (filtered gain
    ``K_f = P Z / F``) followed by the prediction
    ``P' = T P_f Tᵀ + Q`` and an explicit symmetrization.  Algebraically
    identical to the standard form, but symmetric-PSD by construction in
    float arithmetic — the subtractive ``P − F·KKᵀ`` can go indefinite
    under f32 round-off on ill-conditioned lanes, which is exactly the
    covariance-degeneracy failure the serving health monitor guards
    (docs/design.md §3b serving half).  ``joseph=False`` is the
    pre-existing update bit-for-bit.
    """
    dtype = a.dtype
    two_pi = jnp.asarray(2.0 * math.pi, dtype)
    v = y - ssm.d - ssm.Z @ a
    if meta.mode == "exact":
        pz = P @ ssm.Z
        F = ssm.Z @ pz + ssm.H
        K = (ssm.T @ pz) / F
    else:
        F = ssm.H
        K = ssm.gain
    obs = jnp.isfinite(y) & (w > 0)
    v_eff = jnp.where(obs, v, jnp.zeros((), dtype))
    a_next = ssm.T @ a + ssm.c + K * v_eff
    if meta.mode == "exact":
        if joseph:
            m = a.shape[-1]
            kf = pz / F
            imkz = jnp.eye(m, dtype=dtype) - jnp.outer(kf, ssm.Z)
            p_filt = imkz @ P @ imkz.T + ssm.H * jnp.outer(kf, kf)
            p_filt = jnp.where(obs, p_filt, P)
            P_next = ssm.T @ p_filt @ ssm.T.T + ssm.Q
            P_next = 0.5 * (P_next + P_next.T)
        else:
            p_pred = ssm.T @ P @ ssm.T.T + ssm.Q
            P_next = p_pred - jnp.where(obs, F, jnp.zeros((), dtype)) \
                * jnp.outer(K, K)
    else:
        P_next = P
    ll_inc = jnp.where(
        obs, -0.5 * (jnp.log(two_pi * F) + v_eff * v_eff / F),
        jnp.zeros((), dtype))
    return a_next, P_next, v, F, ll_inc, obs


def _tick_one(ssm: StateSpace, meta: SSMeta, state: FilterState,
              y: jnp.ndarray, offset: jnp.ndarray, w: jnp.ndarray,
              joseph: bool = False):
    """One raw-scale tick for a single lane: difference through the ring,
    load the exogenous observation ``offset`` (ARX) into the state, run
    the filter step, accumulate the likelihood pieces.

    The offset loads through ``Z`` (the companion form's ``e₁``, the
    "current y" slot) *before* the step, so the innovation sees
    ``y - offset - Z a`` and — crucially — the transition propagates the
    exogenous contribution into future AR lags (``T(a + offset·Z)``),
    keeping the autoregression on the raw series rather than on an
    exog-adjusted one."""
    ring, z = _diff_step(state.ring, y, meta.d_order)
    a_in = state.a + offset * ssm.Z
    a, P, v, F, ll_inc, obs = filter_step_one(
        ssm, meta, a_in, state.P, z, w, joseph)
    zero = jnp.zeros((), state.loglik.dtype)
    return FilterState(
        a=a, P=P, ring=ring,
        loglik=state.loglik + ll_inc,
        ssq=state.ssq + jnp.where(obs, v * v / F, zero),
        sumlogf=state.sumlogf + jnp.where(obs, jnp.log(F), zero),
        n_obs=state.n_obs + obs.astype(state.n_obs.dtype)), (v, F)


def filter_step_panel(ssm: StateSpace, state: FilterState,
                      y: jnp.ndarray, offset: jnp.ndarray,
                      meta: SSMeta, *, joseph: bool = False):
    """One tick across the whole panel: ``y (S,)`` raw observations,
    ``offset (S,)`` exogenous observation offsets (zeros when none).
    Returns ``(state', (v, F))``.  Pure function of arrays + the static
    ``meta`` (and the static ``joseph`` covariance-form flag — see
    :func:`filter_step_one`) — the serving session jits it once per
    (bucket, m, meta, policy)."""
    w = jnp.ones((), y.dtype)
    return jax.vmap(
        lambda sl, stl, yl, ol: _tick_one(sl, meta, stl, yl, ol, w,
                                          joseph)
    )(ssm, state, y, offset)


def _filter_series_one(ssm: StateSpace, meta: SSMeta, state: FilterState,
                       ys: jnp.ndarray, ws: jnp.ndarray,
                       offsets: jnp.ndarray, return_path: bool):
    """Whole-series scan for one lane (vmapped by :func:`filter_panel`)."""
    def step(st, inp):
        y, w, off = inp
        st2, (v, f) = _tick_one(ssm, meta, st, y, off, w)
        out = (st.a, st.P, v, f) if return_path else None
        return st2, out

    final, path = lax.scan(step, state, (ys, ws, offsets))
    return final, path


def filter_panel(ssm: StateSpace, state: FilterState, ys: jnp.ndarray,
                 meta: SSMeta, *, weights: Optional[jnp.ndarray] = None,
                 offsets: Optional[jnp.ndarray] = None,
                 return_path: bool = False) -> FilterResult:
    """Filter a whole panel ``ys (S, n)`` from ``state``, one
    ``lax.scan`` per lane (vmapped), accumulating the exact
    log-likelihood in-graph.

    ``weights (S, n)`` (0/1) marks live steps — ragged valid windows and
    the ``d_order`` differencing burn-in; when None, all steps past the
    burn-in are live.  ``offsets (S, n)`` are per-tick exogenous
    observation offsets (ARX).  ``return_path`` additionally returns the
    per-step predicted ``(a, P, v, F)`` (lane-major), the oracle-test
    surface.
    """
    ys = jnp.asarray(ys)
    S, n = ys.shape
    dtype = ys.dtype
    burn = (jnp.arange(n) >= meta.d_order).astype(dtype)
    ws = jnp.broadcast_to(burn, (S, n)) if weights is None \
        else jnp.asarray(weights, dtype) * burn
    offs = jnp.zeros((S, n), dtype) if offsets is None \
        else jnp.broadcast_to(jnp.asarray(offsets, dtype), (S, n))

    final, path = jax.vmap(
        lambda sl, stl, yl, wl, ol: _filter_series_one(
            sl, meta, stl, yl, wl, ol, return_path)
    )(ssm, state, ys, ws, offs)
    return FilterResult(final, final.loglik, path)


def concentrated_loglik(state: FilterState) -> jnp.ndarray:
    """σ²-profiled Gaussian log-likelihood from the accumulated filter
    pieces: with ``σ̂² = ssq / n``,

        ll = -n/2 · (log 2πσ̂² + 1) - ½ Σ log F

    (the filter must have run at unit noise scale — every converter's
    pre-calibration pass does).  The per-lane maximizer of this IS the
    exact-likelihood estimate with σ² solved in closed form."""
    n = state.n_obs.astype(state.ssq.dtype)
    safe_n = jnp.maximum(n, 1.0)
    sigma2 = state.ssq / safe_n
    two_pi = jnp.asarray(2.0 * math.pi, state.ssq.dtype)
    ll = -0.5 * n * (jnp.log(two_pi * sigma2) + 1.0) - 0.5 * state.sumlogf
    return jnp.where(state.n_obs > 0, ll, jnp.nan)


def forecast_mean(meta: SSMeta, horizon: int, ssm: StateSpace,
                  a: jnp.ndarray, ring: jnp.ndarray,
                  offsets: jnp.ndarray) -> jnp.ndarray:
    """h-step point forecasts from a predicted state: mean propagation
    ``x ← T(x + offset·Z) + c`` with zero future innovations, each step's
    observation integrated back to the raw scale through the
    ``d_order``-length raw-difference ring.

    ``a (S, m)`` the one-step-predicted state mean, ``ring (S, d_order)``
    the last raw differences, ``offsets (S, horizon)`` known future
    exogenous observation offsets (zeros when none).  Returns
    ``(S, horizon)``.  The single forecast program shared by
    ``ServingSession.forecast`` and the longseries tier's exact
    forecast-from-combined-model path — one math, every consumer.
    """
    d_order = meta.d_order

    def one_lane(ssm_l, a_l, ring_l, offs):
        def step(carry, off):
            x, lasts = carry
            z = ssm_l.d + ssm_l.Z @ x + off
            if d_order:
                vals = []
                cur = z
                for j in range(d_order - 1, -1, -1):
                    cur = cur + lasts[j]
                    vals.append(cur)
                y_out = cur
                lasts = jnp.stack(vals[::-1])
            else:
                y_out = z
            x = ssm_l.T @ (x + off * ssm_l.Z) + ssm_l.c
            return (x, lasts), y_out

        _, ys = lax.scan(step, (a_l, ring_l), offs, length=horizon)
        return ys

    return jax.vmap(one_lane)(ssm, a, ring, offsets)


def steady_gain(ssm: StateSpace, P: jnp.ndarray
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The prediction-form gain (and innovation variance) a converged
    predicted covariance implies: ``F = Z P Zᵀ + H``, ``K = T P Zᵀ / F``.
    ``P (S, m, m)``; returns ``(K (S, m), F (S,))``.  The exact filter's
    covariance recursion is data-independent and converges to its
    Riccati fixed point geometrically, so the ``P`` after a few hundred
    steps pins the gain every later step uses — the fact
    :func:`filter_forecast_origin` exploits."""
    pz = jnp.einsum("sij,sj->si", P, ssm.Z)
    F = jnp.einsum("si,si->s", ssm.Z, pz) + ssm.H
    K = jnp.einsum("sij,sj->si", ssm.T, pz) / F[:, None]
    return K, F


# module-level traced chunk kernel (STS006: one function object, so
# repeated chunks share the jit cache — at most two compiles per run,
# the full chunk shape and the tail)
def _origin_chunk_full(A, K, F, Z, c, d, ys, x0):
    """One time chunk of the pinned-gain state recursion in O(log k)
    depth: ``x_t = A x_{t-1} + c + K (y_t - d)`` with constant per-lane
    ``A = T - K Z``, evaluated by associative scan; innovations and the
    likelihood pieces follow elementwise off the prefix states.  Returns
    ``(x_last, ll_sum, ssq_sum, sumlogf_sum)`` per lane."""
    from ..ops.scan_parallel import affine_recurrence

    k = ys.shape[1]
    dtype = ys.dtype
    b = c[None] + K[None] * (ys.T - d[None])[..., None]      # (k, S, m)
    A_t = jnp.broadcast_to(A[None], (k,) + A.shape)
    xs = affine_recurrence(A_t, b, x0=x0)                    # (k, S, m)
    preds = jnp.concatenate([x0[None], xs[:-1]], axis=0)
    v = ys.T - d[None] - jnp.einsum("sm,tsm->ts", Z, preds)  # (k, S)
    two_pi = jnp.asarray(2.0 * math.pi, dtype)
    ll = jnp.sum(-0.5 * (jnp.log(two_pi * F)[None] + v * v / F[None]),
                 axis=0)
    ssq = jnp.sum(v * v / F[None], axis=0)
    sumlogf = jnp.asarray(k, dtype) * jnp.log(F)
    return xs[-1], ll, ssq, sumlogf


_origin_chunk = jax.jit(_origin_chunk_full)


def filter_forecast_origin(ssm: StateSpace, state: FilterState, ys,
                           meta: SSMeta, *, warm: int = 512,
                           chunk: int = 65536) -> FilterState:
    """Exact-mode forecast-origin state over an ultra-long series
    without an O(n) sequential scan.

    The exact filter's gain sequence is data-independent and converges
    geometrically to its Riccati fixed point, so: (1) filter the first
    ``warm`` observations with the full covariance-propagating scan
    (:func:`filter_panel` — tiny, sequential), (2) pin the converged
    gain (:func:`steady_gain`) and evaluate the remaining state-mean
    recursion — now the affine map ``x_t = (T - KZ) x_{t-1} + c +
    K(y_t - d)`` — chunk by chunk through
    :func:`~spark_timeseries_tpu.ops.scan_parallel.affine_recurrence`
    in O(log chunk) depth, with only chunk boundaries crossing the host.
    Matches the sequential filter to float rounding once ``warm`` covers
    the covariance burn-in (a few hundred steps for stationary models);
    this is the longseries tier's forecast-origin recovery
    (docs/design.md §8).

    ``ys (S, n)`` must be fully observed (no NaN) — missing ticks
    perturb the gain sequence, which only the sequential
    :func:`filter_panel` tracks.  Likelihood accumulators on the
    returned state use the pinned innovation variance past ``warm``
    (equal to the sequential filter's to the same rounding).  ``P`` on
    the returned state is the converged predicted covariance.
    """
    if meta.mode != "exact":
        raise ValueError(
            "filter_forecast_origin is the exact-mode fast path; pinned-"
            "gain models already have filter_panel_parallel")
    if meta.d_order != 0:
        raise ValueError(
            "filter_forecast_origin runs on the filter scale; difference "
            "the series first (d_order must be 0)")
    n = ys.shape[1]
    w = min(int(warm), n)
    head = jnp.asarray(ys[:, :w])
    res = filter_panel(ssm, state, head, meta)
    origin = res.state
    if w == n:
        return origin
    K, F = steady_gain(ssm, origin.P)
    gz = jnp.einsum("si,sj->sij", K, ssm.Z)
    A = ssm.T - gz
    x = origin.a
    ll, ssq, slf = origin.loglik, origin.ssq, origin.sumlogf
    n_obs = origin.n_obs
    step = max(1, int(chunk))
    for s in range(w, n, step):
        part = jnp.asarray(ys[:, s:s + step])
        x, ll_c, ssq_c, slf_c = _origin_chunk(A, K, F, ssm.Z, ssm.c,
                                              ssm.d, part, x)
        ll = ll + ll_c
        ssq = ssq + ssq_c
        slf = slf + slf_c
        n_obs = n_obs + jnp.asarray(part.shape[1], n_obs.dtype)
    return FilterState(a=x, P=origin.P, ring=origin.ring, loglik=ll,
                       ssq=ssq, sumlogf=slf, n_obs=n_obs)


def pinned_state_path(ssm: StateSpace, x0: jnp.ndarray, ys: jnp.ndarray,
                      K: jnp.ndarray) -> jnp.ndarray:
    """Every predicted state along a series under a pinned per-lane gain,
    in O(log n) depth — the backtest tier's origin-replay primitive.

    With the gain pinned the state recursion is the affine map
    ``x_t = (T - K Z) x_{t-1} + c + K (y_t - d)`` (a missing — NaN —
    tick drops the gain term: ``x_t = T x_{t-1} + c``), so
    :func:`~spark_timeseries_tpu.ops.scan_parallel.affine_recurrence`
    evaluates the whole path at once.  Unlike
    :func:`filter_panel_parallel` (which folds the path into likelihood
    sums) the *path itself* is returned: ``ys (S, n)``, ``x0 (S, m)``
    the state predicted for the first tick, ``K (S, m)`` a pinned
    prediction-form gain (:func:`steady_gain` output for converged
    exact-mode lanes, ``ssm.gain`` for innovations-mode lanes); returns
    ``(n + 1, S, m)`` with ``path[k]`` the state predicted after
    consuming the first ``k`` observations (``path[0] = x0``) — exactly
    the forecast origin conditioned on those ticks, so rolling-origin
    evaluation gathers one row per origin instead of refiltering.
    """
    from ..ops.scan_parallel import affine_recurrence

    ys = jnp.asarray(ys)
    dtype = ys.dtype
    obs = jnp.isfinite(ys)                                   # (S, n)
    y_eff = jnp.where(obs, ys, jnp.zeros((), dtype))
    gz = jnp.einsum("si,sj->sij", K, ssm.Z)                  # (S, m, m)
    a_obs = ssm.T - gz
    A = jnp.where(obs.T[:, :, None, None], a_obs[None], ssm.T[None])
    b = ssm.c[None] + jnp.where(
        obs.T[:, :, None],
        K[None] * (y_eff.T - ssm.d[None])[..., None], 0.0)
    xs = affine_recurrence(A, b, x0=x0)                      # (n, S, m)
    return jnp.concatenate([x0[None], xs], axis=0)


def filter_panel_parallel(ssm: StateSpace, state: FilterState,
                          ys: jnp.ndarray, meta: SSMeta) -> FilterResult:
    """Pinned-gain whole-series filter in O(log n) depth.

    With a pinned gain the state recursion is the affine map
    ``x_t = (T - g Z) x_{t-1} + c + g (y_t - d)`` (a missing tick drops
    the gain term: ``x_t = T x_{t-1} + c``), which
    :func:`ops.scan_parallel.affine_recurrence` evaluates by associative
    scan; innovations and the likelihood then follow elementwise.
    Matches :func:`filter_panel` to float rounding — the parallel-prefix
    variant for ultra-long histories and time-sharded meshes.  Exact
    mode has data-dependent gains and stays on the sequential scan.
    """
    if meta.mode != "innovations":
        raise ValueError(
            "filter_panel_parallel needs a pinned-gain (innovations-mode) "
            "model; exact-mode gains depend on the running covariance — "
            "use filter_panel")
    if meta.d_order != 0:
        raise ValueError(
            "filter_panel_parallel runs on the filter scale; difference "
            "the series first (d_order must be 0)")
    from ..ops.scan_parallel import affine_recurrence

    ys = jnp.asarray(ys)
    S, n = ys.shape
    dtype = ys.dtype
    obs = jnp.isfinite(ys)                                   # (S, n)
    y_eff = jnp.where(obs, ys, jnp.zeros((), dtype))
    # time-major per-step maps: A_t = T - g Z (observed) | T (missing)
    gz = jnp.einsum("si,sj->sij", ssm.gain, ssm.Z)           # (S, m, m)
    a_obs = ssm.T - gz
    A = jnp.where(obs.T[:, :, None, None], a_obs[None], ssm.T[None])
    b = ssm.c[None] + jnp.where(
        obs.T[:, :, None],
        ssm.gain[None] * (y_eff.T - ssm.d[None])[..., None], 0.0)
    xs = affine_recurrence(A, b, x0=state.a)                 # (n, S, m)
    # predictor for step t is x_{t-1} (x_0 = the incoming state)
    preds = jnp.concatenate([state.a[None], xs[:-1]], axis=0)
    v = ys.T - ssm.d[None] - jnp.einsum("sm,tsm->ts", ssm.Z, preds)
    F = ssm.H[None]                                          # (1, S)
    two_pi = jnp.asarray(2.0 * math.pi, dtype)
    v_eff = jnp.where(obs.T, v, jnp.zeros((), dtype))
    ll_steps = jnp.where(obs.T,
                         -0.5 * (jnp.log(two_pi * F) + v_eff * v_eff / F),
                         jnp.zeros((), dtype))
    final = FilterState(
        a=xs[-1], P=state.P, ring=state.ring,
        loglik=state.loglik + jnp.sum(ll_steps, axis=0),
        ssq=state.ssq + jnp.sum(jnp.where(obs.T, v_eff * v_eff / F, 0.0),
                                axis=0),
        sumlogf=state.sumlogf + jnp.sum(
            jnp.where(obs.T, jnp.log(jnp.broadcast_to(F, v.shape)), 0.0),
            axis=0),
        n_obs=state.n_obs + jnp.sum(obs, axis=1).astype(state.n_obs.dtype))
    return FilterResult(final, final.loglik)
