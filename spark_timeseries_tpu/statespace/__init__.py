"""State-space/Kalman subsystem: the online serving tier (ROADMAP item 3).

Express the classical families — ARIMA, AR/ARX, EWMA, additive
Holt-Winters — as batched linear-Gaussian state-space models so that

- a new observation on an already-fitted series is a single O(m²)
  Kalman-filter step (:class:`serving.ServingSession.update`, one cached
  executable per bucket — constant work per tick, no re-optimization),
- h-step forecasts read straight off the filtered state
  (:meth:`serving.ServingSession.forecast`), and
- the **exact** Gaussian likelihood falls out of the same recursion,
  which ``models.arima.fit(objective="exact")`` maximizes through the
  existing ``ops.optimize`` minimizers — an accuracy upgrade over the
  CSS objective.

Layout: :mod:`ssm` (representation + filter-state pytrees), :mod:`kalman`
(the step/scan/parallel-prefix filters and likelihood accumulation),
:mod:`convert` (fitted model → state-space form + bootstrap calibration),
:mod:`health` (per-lane in-graph divergence detection + quarantine),
:mod:`quality` (the live forecast-quality plane: per-tick anomaly
scores, rolling online accuracy off a device-resident forecast ring,
Page-Hinkley drift alarms — fused into the same jitted tick),
:mod:`serving` (warm sessions, tick ingest, lane healing,
checkpoint/restore), :mod:`fleet` (the multi-tenant front-end:
admission control, tick coalescing onto the shared executables,
SLO-aware shedding, checkpoint-based lane migration), :mod:`runtime`
(the autonomous layer over the fleet: supervised background pump with
watchdog restarts, blocking admission backpressure, crash-only
auto-checkpoint generations, self-driving drain/adopt rebalancing).
"""

from . import (convert, fleet, health, kalman, quality,  # noqa: F401
               runtime, serving, ssm)
from .fleet import (AdmissionPolicy, FleetRestoreMismatch,  # noqa: F401
                    FleetSaturated, FleetScheduler)
from .runtime import (FleetBackpressureTimeout, FleetRuntime,  # noqa: F401
                      RuntimePolicy)
from .convert import Bootstrapped, bootstrap, to_statespace  # noqa: F401
from .health import (LANE_DIVERGED, LANE_DRIFTED, LANE_OK,  # noqa: F401
                     LANE_SUSPECT, HealthPolicy, LaneHealth,
                     initial_health, monitor_panel, monitored_step,
                     shed_priority)
from .quality import (QualityPolicy, QualityState,  # noqa: F401
                      initial_quality, quality_panel, quality_step)
from .kalman import (FilterResult, concentrated_loglik,  # noqa: F401
                     filter_forecast_origin, filter_panel,
                     filter_panel_parallel, filter_step_panel,
                     forecast_mean, pinned_state_path, steady_gain)
from .serving import (ServingRestoreMismatch, ServingSession,  # noqa: F401
                      TickResult, start_session)
from .ssm import (FilterState, SSMeta, StateSpace,  # noqa: F401
                  initial_state, state_nbytes)

__all__ = [
    "ssm", "kalman", "convert", "health", "quality", "serving", "fleet",
    "runtime",
    "StateSpace", "SSMeta", "FilterState", "initial_state", "state_nbytes",
    "filter_step_panel", "filter_panel", "filter_panel_parallel",
    "filter_forecast_origin", "forecast_mean",
    "pinned_state_path", "steady_gain",
    "concentrated_loglik", "FilterResult",
    "to_statespace", "bootstrap", "Bootstrapped",
    "HealthPolicy", "LaneHealth", "initial_health",
    "monitored_step", "monitor_panel",
    "LANE_OK", "LANE_SUSPECT", "LANE_DIVERGED", "LANE_DRIFTED",
    "QualityPolicy", "QualityState", "initial_quality",
    "quality_step", "quality_panel",
    "ServingSession", "TickResult", "start_session",
    "ServingRestoreMismatch", "shed_priority",
    "FleetScheduler", "AdmissionPolicy", "FleetSaturated",
    "FleetRestoreMismatch",
    "FleetRuntime", "RuntimePolicy", "FleetBackpressureTimeout",
]
