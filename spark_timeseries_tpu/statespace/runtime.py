"""Autonomous fleet runtime: the hands-off layer around FleetScheduler.

``FleetScheduler`` (``fleet.py``) is deliberately synchronous and
single-threaded: callers ``submit()`` and ``pump()``, and one instance
is never safe to share across threads.  That keeps the coalescing /
shedding / migration core testable — but a production fleet needs a
pump that nobody babysits.  :class:`FleetRuntime` is that layer:

- **Supervised pump.**  One daemon thread sweeps every shard
  scheduler's :meth:`~.fleet.FleetScheduler.pump` under the runtime
  lock, stamping a heartbeat into a ``fleet-pump`` ``JobProgress``
  (the same record the telemetry plane already scrapes).  A watchdog
  thread detects a dead pump (the thread exited on an exception —
  forensics bundle recorded first) or a wedged one (heartbeat older
  than ``RuntimePolicy.stall_after_s``) and restarts it with bounded
  exponential backoff (``durability.BackoffPolicy``); every recovery
  increments ``fleet.pump_restarts``.  A wedged thread is *abandoned*
  via a generation token — when it wakes it notices its generation is
  stale and exits without touching the shards.  (Python threads cannot
  be preempted: a pump truly wedged inside a device call keeps the
  runtime lock, the replacement blocks behind it, and recovery
  escalates to the process supervisor via the stale ``/healthz`` —
  which is exactly what the 503 contract is for.)

- **Backpressure.**  :meth:`FleetRuntime.submit` with ``block=True``
  (the default) waits on a condition variable for queue space instead
  of racing :class:`~.fleet.FleetSaturated`; the pump notifies after
  every sweep.  A deadline turns into the named
  :class:`FleetBackpressureTimeout` so producers degrade gracefully.
  Submit also opens the tick's lineage clock (``utils.lineage``)
  *before* any park, so the eventual record's ``admit`` stage carries
  the backpressure wait (detour ``backpressure``); ticks still queued
  when the watchdog replaces a crashed pump are marked
  ``pump_restart_redelivery`` by the next generation's first sweep —
  the record itself rides the queue entry, so redelivery is the same
  record, never a duplicate (exactly-once, pinned under ``pump_crash``
  by the race harness).

- **Crash-only auto-checkpoint.**  Interval- and dirty-tick-driven
  snapshots of every tenant through the *drain bundle* format
  (``FleetScheduler.checkpoint_tenant`` — same bytes ``adopt()``
  restores), one generation directory per pass.  Each tenant bundle
  lands via the atomic tmp+fsync+rename writer; the generation's
  commit point is the fsynced rename of ``MANIFEST.json``, written
  strictly after every bundle.  A ``kill -9`` at any instant leaves
  either a committed generation (manifest present) or ignorable
  debris — :meth:`FleetRuntime.restore_latest` adopts the newest
  committed generation and replays its buffered ticks bitwise.

- **Self-driving rebalance.**  With more than one shard scheduler, a
  placement pass scores tenants by update-key group and queue load:
  fragments of one coalescing group split across shards are
  consolidated toward the largest fragment (a split group dispatches
  one under-filled device batch per shard), then residual load
  imbalance beyond ``RuntimePolicy.rebalance_imbalance`` moves the
  busiest shard's lightest tenant.  Every move executes through the
  checkpoint path — ``drain()`` then ``adopt(replay=True)`` — so it
  inherits the PR-11 bitwise/zero-loss migration pins.

Fault modes ``pump_crash`` / ``pump_hang`` / ``checkpoint_torn``
(``utils.resilience``) target exactly these paths; the PR-13 race
harness drives pump vs submit vs scrape vs checkpoint vs rebalance
through the runtime lock (``utils.races``).  See docs/design.md §7e.
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import signal
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from ..utils import lineage as _lineage
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from ..utils import telemetry as _telemetry
from ..utils.durability import BackoffPolicy
from .fleet import FleetScheduler, TENANT_LIVE
from .serving import check_label

__all__ = ["RuntimePolicy", "FleetRuntime", "FleetBackpressureTimeout"]

_runtime_seq = itertools.count(1)

# generation directory / manifest names under RuntimePolicy.checkpoint_dir
_GEN_PREFIX = "gen-"
_MANIFEST = "MANIFEST.json"
_MANIFEST_FORMAT = 1


class FleetBackpressureTimeout(RuntimeError):
    """A blocking :meth:`FleetRuntime.submit` waited out its deadline
    for queue space.  Deterministic producer-side degradation: the
    caller sees WHICH tenant stayed saturated for HOW long and can shed
    load upstream — instead of an anonymous stall or an unbounded
    queue."""


class RuntimePolicy(NamedTuple):
    """Knobs for one :class:`FleetRuntime`.

    - ``pump_interval_s``: idle sleep between pump sweeps (a submit
      wakes the pump immediately, so this only bounds idle latency);
    - ``watchdog_interval_s``: supervision poll cadence;
    - ``stall_after_s``: heartbeat age past which the watchdog declares
      the pump wedged and abandons/restarts it (distinct from the
      scrape plane's ``STS_TELEMETRY_STALE_FACTOR`` staleness, which
      only *reports*);
    - ``backoff``: restart backoff (None → ``BackoffPolicy()``); the
      delay is bounded by its ``max_delay_s``, restarts themselves are
      unbounded — a supervisor never gives up, it escalates via
      ``/healthz``;
    - ``checkpoint_dir``: root for auto-checkpoint generations (None
      disables auto-checkpointing and :meth:`FleetRuntime.checkpoint`);
    - ``checkpoint_interval_s`` / ``checkpoint_dirty_ticks``: a
      checkpoint pass runs when EITHER this much wall time has passed
      OR this many ticks were admitted since the last committed
      generation (0 disables that trigger);
    - ``keep_generations``: committed generations retained on disk
      (older ones are pruned after each commit);
    - ``rebalance_interval_s``: placement-pass cadence (0 disables the
      timer; :meth:`FleetRuntime.rebalance` always works);
    - ``rebalance_imbalance``: busiest/lightest shard load ratio that
      triggers a load-spreading move (consolidation moves ignore it);
    - ``max_moves_per_cycle``: migration budget per placement pass —
      each move replays a tenant's buffered ticks, so the budget bounds
      pump-sweep latency."""

    pump_interval_s: float = 0.005
    watchdog_interval_s: float = 0.05
    stall_after_s: float = 5.0
    backoff: Optional[BackoffPolicy] = None
    checkpoint_dir: Optional[str] = None
    checkpoint_interval_s: float = 0.0
    checkpoint_dirty_ticks: int = 0
    keep_generations: int = 2
    rebalance_interval_s: float = 0.0
    rebalance_imbalance: float = 2.0
    max_moves_per_cycle: int = 1

    def validate(self) -> "RuntimePolicy":
        if self.pump_interval_s <= 0 or self.watchdog_interval_s <= 0:
            raise ValueError(
                "pump_interval_s and watchdog_interval_s must be > 0")
        if self.stall_after_s <= 0:
            raise ValueError("stall_after_s must be > 0")
        if self.checkpoint_interval_s < 0 or self.checkpoint_dirty_ticks < 0:
            raise ValueError("checkpoint_interval_s and "
                             "checkpoint_dirty_ticks must be >= 0")
        if self.keep_generations < 1:
            raise ValueError("keep_generations must be >= 1")
        if self.rebalance_interval_s < 0:
            raise ValueError("rebalance_interval_s must be >= 0")
        if self.rebalance_imbalance < 1.0:
            raise ValueError("rebalance_imbalance must be >= 1.0")
        if self.max_moves_per_cycle < 1:
            raise ValueError("max_moves_per_cycle must be >= 1")
        if (self.checkpoint_interval_s > 0 or self.checkpoint_dirty_ticks
                > 0) and not self.checkpoint_dir:
            raise ValueError(
                "auto-checkpoint triggers need checkpoint_dir set")
        return self


def _fsync_write_json(path: str, doc: Dict[str, Any]) -> None:
    """tmp + fsync + rename + dir-fsync: the manifest is the generation
    commit point, so its rename must be as durable as the bundles'."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


class FleetRuntime:
    """Supervise one or more shard :class:`~.fleet.FleetScheduler`\\ s:
    background pump + watchdog, blocking admission, auto-checkpoint,
    and drain/adopt rebalancing (module docstring for the contract).

    Build with the shard(s), :meth:`start` (or use as a context
    manager), then :meth:`submit` from any number of producer threads.
    All scheduler access — pump sweeps, submits, checkpoints,
    migrations, :meth:`forecast` — serializes on one runtime lock,
    honoring ``FleetScheduler``'s single-thread contract."""

    def __init__(self, schedulers, *, policy: Optional[RuntimePolicy] = None,
                 registry=None, label: Optional[str] = None):
        if isinstance(schedulers, FleetScheduler):
            schedulers = [schedulers]
        self.shards: List[FleetScheduler] = list(schedulers)
        if not self.shards:
            raise ValueError("FleetRuntime needs at least one scheduler")
        seen: Dict[str, str] = {}
        for sh in self.shards:
            for la in sh.tenants:
                if la in seen:
                    raise ValueError(
                        f"tenant label {la!r} appears in shards "
                        f"{seen[la]!r} and {sh.label!r}; the runtime "
                        f"routes by label — labels must be unique "
                        f"across its shards")
                seen[la] = sh.label
        self.policy = (policy if policy is not None
                       else RuntimePolicy()).validate()
        self._backoff = self.policy.backoff if self.policy.backoff \
            is not None else BackoffPolicy()
        self._reg = registry if registry is not None \
            else _metrics.get_registry()
        self.label = check_label(label) if label is not None \
            else f"runtime{next(_runtime_seq)}"
        # THE runtime lock: every touch of a shard scheduler happens
        # under it (they are not thread-safe individually).  The
        # condition variable shares it — the pump notifies waiters
        # (blocked submits, quiesce) after every sweep.
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # management state (generation token, restart bookkeeping) gets
        # its own small lock.  Global order: runtime lock BEFORE mgmt
        # lock, never the reverse — the watchdog takes only the mgmt
        # lock, so it can declare a wedged pump dead even while that
        # pump holds the runtime lock
        self._mgmt_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._gen = 0                    # pump-thread generation token
        # set by the watchdog on every pump restart; the replacement
        # generation's first sweep consumes it and marks still-queued
        # ticks' lineage records as redelivered
        self._redeliver = False
        self._pump_thread: Optional[threading.Thread] = None
        self._watchdog_thread: Optional[threading.Thread] = None
        self._started = False
        self._pump_count = 0
        self._restarts = 0
        self._consec_failures = 0
        self._waiters = 0
        self._dirty = 0                  # ticks since last committed gen
        self._last_error: Optional[str] = None
        self._hang_tokens: set = set()   # pump_hang: once per fault scope
        self._ckpt_failures = 0
        self._ckpt_gen = 0
        self._last_ckpt_t = time.monotonic()
        self._last_ckpt_unix: Optional[float] = None
        self._last_rebalance_t = time.monotonic()
        self._migrations = 0
        ckdir = self.policy.checkpoint_dir
        if ckdir:
            os.makedirs(ckdir, exist_ok=True)
            # continue numbering past ANY existing generation dir —
            # committed or torn — so a crashed generation's number is
            # never reused (its debris would masquerade as ours)
            self._ckpt_gen = max(
                [g for g, _ in self._scan_generations(ckdir,
                                                      committed_only=False)]
                or [0])
        # the pump's heartbeat record: the same JobProgress the
        # telemetry plane already renders and ages
        self._job = _telemetry.JobProgress(
            _telemetry.new_job_id("fleet-pump"), family="fleet-pump",
            n_series=sum(len(sh.tenants) for sh in self.shards),
            n_chunks=0, chunk_size=0)
        for sh in self.shards:
            sh.auto_pump = False         # the runtime owns pumping
            sh._runtime_info = self.pump_summary
        _telemetry.register_fleet_runtime(self)
        self._reg.inc("fleet.runtimes")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetRuntime":
        """Spawn the pump and watchdog daemons and register the pump's
        heartbeat job.  One start per runtime — a stopped runtime is
        done (build a new one over the same schedulers to resume)."""
        with self._mgmt_lock:
            if self._started:
                raise RuntimeError(f"runtime {self.label!r} is already "
                                   f"started")
            if self._job.status != "running":
                raise RuntimeError(
                    f"runtime {self.label!r} was stopped; a runtime "
                    f"runs once — build a new FleetRuntime over the "
                    f"same schedulers")
            self._started = True
            self._stop.clear()
            _telemetry.register_job(self._job, self._reg)
            self._job.heartbeat("pump_start")
            self._spawn_pump_mgmt_locked()
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_main, daemon=True,
                name=f"sts-{self.label}-watchdog")
            self._watchdog_thread.start()
        return self

    def stop(self, *, checkpoint: bool = True) -> None:
        """Stop supervision (idempotent).  ``checkpoint=True`` commits
        one final generation first (when a ``checkpoint_dir`` is
        configured) so a clean shutdown loses nothing."""
        with self._mgmt_lock:
            if not self._started:
                return
            self._stop.set()
            self._gen += 1               # abandon the pump loop
            pump, dog = self._pump_thread, self._watchdog_thread
        self._wake.set()
        with self._cv:
            self._cv.notify_all()
        for th in (pump, dog):
            if th is not None and th.is_alive():
                th.join(timeout=10.0)
        if checkpoint and self.policy.checkpoint_dir:
            with self._lock:
                # bundle writes under the runtime lock are the point:
                # the generation must be consistent with the scheduler
                # state it snapshots
                self._checkpoint_locked()   # sts: noqa[STS103]
        with self._mgmt_lock:
            self._started = False
        _telemetry.finish_job(self._job, "done")

    def __enter__(self) -> "FleetRuntime":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def running(self) -> bool:
        with self._mgmt_lock:
            return self._started

    # -- tenant routing ------------------------------------------------------

    def _find(self, label: str) -> Tuple[FleetScheduler, Any]:
        for sh in self.shards:
            t = sh._tenants.get(label)
            if t is not None:
                return sh, t
        raise KeyError(
            f"no tenant {label!r} in runtime {self.label!r} "
            f"(shards: {[sh.label for sh in self.shards]})")

    def attach(self, session, *, shard: Optional[str] = None) -> str:
        """Attach a session to a shard (named, or the least-loaded by
        tenant count) under the runtime lock."""
        with self._lock:
            if shard is not None:
                targets = [sh for sh in self.shards if sh.label == shard]
                if not targets:
                    raise KeyError(
                        f"no shard {shard!r} in runtime {self.label!r}")
                target = targets[0]
            else:
                target = min(self.shards, key=lambda sh: len(sh._tenants))
            for sh in self.shards:
                if session.label in sh._tenants:
                    raise ValueError(
                        f"tenant label {session.label!r} is already "
                        f"attached to shard {sh.label!r}")
            return target.attach(session)

    def warmup(self) -> None:
        """Pre-trace every shard's coalesced programs (the warmed-tick
        0-recompile pin extends through the runtime)."""
        with self._lock:
            for sh in self.shards:
                sh.warmup()

    def forecast(self, label: str, horizon: int, offsets=None):
        with self._lock:
            sh, _ = self._find(label)
            return sh.forecast(label, horizon, offsets=offsets)

    # -- admission with backpressure ----------------------------------------

    def submit(self, label: str, tick, offset=None, *, block: bool = True,
               timeout: Optional[float] = None) -> None:
        """Admit one tick.  ``block=True`` (default) waits for queue
        space while the pump drains instead of raising
        :class:`~.fleet.FleetSaturated`; past ``timeout`` seconds it
        raises :class:`FleetBackpressureTimeout`.  ``block=False`` is
        the raw admission-policy behavior.  Blocking needs the pump
        running — on a stopped runtime the wait would never end, so the
        call degrades to the non-blocking path."""
        from .fleet import FleetSaturated
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        # open the lineage clock BEFORE any backpressure park: the
        # record minted at admission inherits this thread's entry time,
        # so its "admit" stage carries the wait a caller actually felt
        _lineage.submit_entry()
        with self._cv:
            waited = False
            while True:
                sh, t = self._find(label)   # re-routed after each wait:
                #                             the tenant may have been
                #                             rebalanced to another shard
                blocking = block and self.running \
                    and t.mode == TENANT_LIVE
                if not (blocking and len(t.queue)
                        >= sh.policy.queue_depth):
                    try:
                        sh.submit(label, tick, offset)
                        self._dirty += 1
                        break
                    except FleetSaturated:
                        # raced an admission transition under 'reject';
                        # a blocking producer waits, it never sees the
                        # saturation exception while the pump runs
                        if not blocking:
                            raise
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    self._reg.inc("fleet.backpressure_timeouts")
                    # nothing was admitted: drop the pending lineage
                    # context so it cannot leak into a later submit
                    _lineage.submit_abandon()
                    raise FleetBackpressureTimeout(
                        f"tenant {label!r} ingress queue stayed full "
                        f"({sh.policy.queue_depth} ticks) for "
                        f"{float(timeout):g}s; the pump is not keeping "
                        f"up — shed load upstream or raise the "
                        f"timeout/queue depth")
                self._waiters += 1
                self._wake.set()         # kick the pump to drain
                _lineage.submit_parked()
                try:
                    self._cv.wait(remaining)
                finally:
                    self._waiters -= 1
                waited = True
            if waited:
                self._reg.inc("fleet.backpressure_waits")
        self._wake.set()

    def quiesce(self, timeout: Optional[float] = None) -> bool:
        """Wait until every live tenant's ingress queue is empty (the
        pump has dispatched everything admitted so far).  Returns False
        on timeout.  Needs the pump running."""
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        with self._cv:
            while any(len(t.queue) for sh in self.shards
                      for t in sh._tenants.values()):
                if not self.running:
                    return False
                remaining = 0.25 if deadline is None \
                    else min(0.25, deadline - time.monotonic())
                if remaining <= 0:
                    return False
                self._wake.set()
                self._cv.wait(remaining)
            return True

    # -- the supervised pump -------------------------------------------------

    def _current_gen(self) -> int:
        with self._mgmt_lock:
            return self._gen

    def _spawn_pump_mgmt_locked(self) -> None:
        gen = self._gen
        th = threading.Thread(target=self._pump_main, args=(gen,),
                              daemon=True,
                              name=f"sts-{self.label}-pump-g{gen}")
        self._pump_thread = th
        th.start()

    def _pump_main(self, gen: int) -> None:
        try:
            while not self._stop.is_set() and self._current_gen() == gen:
                self._pump_sweep(gen)
                if self._wake.wait(self.policy.pump_interval_s):
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001 — the supervisor's job
            if self._stop.is_set():
                return
            self._note_pump_death(e)

    def _maybe_hang(self) -> None:
        # pump_hang: one sweep per fault scope sleeps, OUTSIDE the
        # runtime lock — modeling the waitable kind of wedge (the
        # unwaitable kind, hung inside a device call holding the lock,
        # is the process supervisor's problem via /healthz)
        spec = _resilience.fleet_fault("pump_hang")
        if spec is None:
            return
        tok = _resilience.fault_scope_token()
        if tok in self._hang_tokens:
            return
        self._hang_tokens.add(tok)
        time.sleep(spec.hang_s)

    def _pump_sweep(self, gen: int) -> int:
        """One supervised sweep: heartbeat, fault hooks, every shard's
        pump, due auto-checkpoint/rebalance, waiter notify."""
        self._maybe_hang()
        with self._lock:
            if self._stop.is_set() or self._current_gen() != gen:
                return 0
            self._pump_count += 1
            self._job.heartbeat("pump")
            crash = _resilience.fleet_fault("pump_crash")
            if crash is not None and \
                    self._pump_count % max(1, int(crash.n_attempts)) == 0:
                raise _resilience.InjectedPumpCrash(
                    f"injected pump crash at sweep {self._pump_count} "
                    f"(every {max(1, int(crash.n_attempts))} sweeps)")
            self._mark_redelivery_locked()
            n = 0
            for sh in self.shards:
                n += len(sh.pump())
            now = time.monotonic()
            # due checkpoints/rebalances run inside the sweep lock by
            # design: the generation snapshots a quiescent scheduler,
            # and submits waiting meanwhile is exactly backpressure
            self._maybe_checkpoint_locked(now)   # sts: noqa[STS103]
            self._maybe_rebalance_locked(now)
            self._cv.notify_all()
            self._job.heartbeat("idle")
            with self._mgmt_lock:
                self._consec_failures = 0
            return n

    def pump_once(self) -> int:
        """One manual sweep (dispatch + due checkpoint/rebalance) under
        the runtime lock — for un-started runtimes and deterministic
        tests; the background pump runs exactly this."""
        with self._lock:
            self._pump_count += 1
            self._job.heartbeat("pump")
            self._mark_redelivery_locked()
            n = 0
            for sh in self.shards:
                n += len(sh.pump())
            now = time.monotonic()
            self._maybe_checkpoint_locked(now)   # sts: noqa[STS103]
            self._maybe_rebalance_locked(now)
            self._cv.notify_all()
            return n

    def _mark_redelivery_locked(self) -> None:
        """Consume the watchdog's restart flag: every tick still queued
        across the pump generation change keeps its ORIGINAL lineage
        record (the queue survived the crash intact — that is the
        crash-only design), and gets a ``pump_restart_redelivery``
        detour so the trace shows the journey crossed a supervision
        event.  Runtime lock held; the mgmt lock nests under it per the
        §6d order."""
        with self._mgmt_lock:
            redeliver = self._redeliver
            self._redeliver = False
        if not redeliver:
            return
        for sh in self.shards:
            for t in sh._tenants.values():
                for entry in t.queue:
                    if entry[3] is not None:
                        entry[3].detour("pump_restart_redelivery")

    def _note_pump_death(self, exc: BaseException) -> None:
        from ..utils import flightrec as _flightrec
        with self._mgmt_lock:
            self._last_error = f"{type(exc).__name__}: {exc}"
            pump_count, restarts = self._pump_count, self._restarts
        self._reg.inc("fleet.pump_deaths")
        self._job.heartbeat("pump_dead")
        _flightrec.record_incident(
            "fleet_pump_death", exc=exc,
            extra={"runtime": self.label, "pump_count": pump_count,
                   "restarts_so_far": restarts},
            registry=self._reg)

    # -- the watchdog --------------------------------------------------------

    def _watchdog_main(self) -> None:
        while not self._stop.wait(self.policy.watchdog_interval_s):
            with self._mgmt_lock:
                if self._stop.is_set():
                    return
                th = self._pump_thread
                dead = th is None or not th.is_alive()
                wedged = (not dead) and (self._job.heartbeat_age_s()
                                         > self.policy.stall_after_s)
                if not (dead or wedged):
                    continue
                self._consec_failures += 1
                self._restarts += 1
                self._gen += 1           # abandon the old pump thread
                self._redeliver = True   # next sweep marks survivors
                attempt = min(self._consec_failures, 16)
            self._reg.inc("fleet.pump_restarts")
            if wedged:
                from ..utils import flightrec as _flightrec
                with self._mgmt_lock:
                    self._last_error = (
                        f"pump wedged: heartbeat "
                        f"{self._job.heartbeat_age_s():.3f}s old "
                        f"(> stall_after_s="
                        f"{self.policy.stall_after_s:g})")
                _flightrec.record_incident(
                    "fleet_pump_stall",
                    extra={"runtime": self.label,
                           "heartbeat_age_s": self._job.heartbeat_age_s(),
                           "stall_after_s": self.policy.stall_after_s},
                    registry=self._reg)
            # bounded exponential backoff before the restart; the delay
            # resets as soon as a sweep completes (_consec_failures)
            if self._stop.wait(self._backoff.delay(attempt)):
                return
            with self._mgmt_lock:
                if self._stop.is_set():
                    return
                self._job.heartbeat("pump_restart")
                self._spawn_pump_mgmt_locked()

    # -- auto-checkpoint -----------------------------------------------------

    @staticmethod
    def _scan_generations(ckdir: str, *, committed_only: bool = True
                          ) -> List[Tuple[int, str]]:
        """(generation, dir) pairs under ``ckdir``, ascending;
        ``committed_only`` keeps only those whose manifest landed."""
        out = []
        try:
            names = os.listdir(ckdir)
        except OSError:
            return out
        for name in names:
            if not name.startswith(_GEN_PREFIX):
                continue
            try:
                g = int(name[len(_GEN_PREFIX):])
            except ValueError:
                continue
            gdir = os.path.join(ckdir, name)
            if committed_only and not os.path.exists(
                    os.path.join(gdir, _MANIFEST)):
                continue
            out.append((g, gdir))
        out.sort()
        return out

    @classmethod
    def latest_generation(cls, ckdir: str
                          ) -> Optional[Tuple[int, str, Dict[str, Any]]]:
        """The newest *committed* generation under ``ckdir`` as
        ``(generation, dir, manifest)``, or None.  Torn generations
        (bundles without a manifest — a kill -9 mid-pass) are invisible
        here by construction."""
        for g, gdir in reversed(cls._scan_generations(ckdir)):
            try:
                with open(os.path.join(gdir, _MANIFEST)) as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                continue
            if manifest.get("format") == _MANIFEST_FORMAT:
                return g, gdir, manifest
        return None

    def checkpoint(self) -> Optional[Dict[str, Any]]:
        """Commit one generation now (all tenants, all shards).  Returns
        the commit report, or None when the pass failed (counted in
        ``fleet.checkpoint_failures``; the torn generation is invisible
        to restore)."""
        if not self.policy.checkpoint_dir:
            raise RuntimeError(
                f"runtime {self.label!r} has no checkpoint_dir "
                f"configured (RuntimePolicy.checkpoint_dir)")
        with self._lock:
            # consistency requires the I/O under the lock (see §7e)
            return self._checkpoint_locked()   # sts: noqa[STS103]

    def _maybe_checkpoint_locked(self, now: float) -> None:
        p = self.policy
        if not p.checkpoint_dir:
            return
        due = (p.checkpoint_interval_s > 0
               and now - self._last_ckpt_t >= p.checkpoint_interval_s) or \
              (p.checkpoint_dirty_ticks > 0
               and self._dirty >= p.checkpoint_dirty_ticks)
        if due:
            self._checkpoint_locked()

    def _checkpoint_locked(self) -> Optional[Dict[str, Any]]:
        ckdir = self.policy.checkpoint_dir
        gen = self._ckpt_gen + 1
        gdir = os.path.join(ckdir, f"{_GEN_PREFIX}{gen:08d}")
        torn = _resilience.fleet_fault("checkpoint_torn")
        written: List[Dict[str, Any]] = []
        try:
            os.makedirs(gdir, exist_ok=True)
            for idx, sh in enumerate(self.shards):
                for label in sh.tenants:
                    if torn is not None and \
                            len(written) >= max(0, int(torn.n_attempts)):
                        # the kill-9-mid-checkpoint scenario: forensics
                        # first (a real SIGKILL runs no handlers), then
                        # die BEFORE the manifest — this generation must
                        # never commit
                        from ..utils import flightrec as _flightrec
                        _flightrec.record_incident(
                            "checkpoint_torn",
                            extra={"runtime": self.label,
                                   "generation": gen, "dir": gdir,
                                   "bundles_written": len(written)},
                            registry=self._reg)
                        os.kill(os.getpid(), signal.SIGKILL)
                    rep = sh.checkpoint_tenant(
                        label, os.path.join(gdir, label))
                    written.append({"tenant": label, "shard": idx,
                                    "pending": rep["pending"],
                                    "catchup": rep["catchup"]})
        except Exception as e:  # noqa: BLE001 — crash-only: a failed
            # pass must not take the pump down; the generation simply
            # never commits and the previous one keeps ruling
            with self._mgmt_lock:
                self._ckpt_failures += 1
            self._reg.inc("fleet.checkpoint_failures")
            from ..utils import flightrec as _flightrec
            _flightrec.record_incident(
                "fleet_checkpoint_failure", exc=e,
                extra={"runtime": self.label, "generation": gen,
                       "dir": gdir, "bundles_written": len(written)},
                registry=self._reg)
            return None
        manifest = {"format": _MANIFEST_FORMAT, "generation": gen,
                    "runtime": self.label, "time_unix": time.time(),
                    "n_shards": len(self.shards), "tenants": written}
        _fsync_write_json(os.path.join(gdir, _MANIFEST), manifest)
        self._ckpt_gen = gen
        self._last_ckpt_t = time.monotonic()
        self._last_ckpt_unix = time.time()
        # every caller holds the runtime lock (the _locked contract);
        # the linter cannot see across the call boundary
        self._dirty = 0   # sts: noqa[STS101]
        self._reg.inc("fleet.checkpoints")
        _metrics.trace_instant(
            "fleet.checkpoint_committed",
            {"runtime": self.label, "generation": gen,
             "tenants": len(written)})
        self._prune_locked(ckdir)
        return {"generation": gen, "dir": gdir, "tenants": len(written)}

    def _prune_locked(self, ckdir: str) -> None:
        committed = self._scan_generations(ckdir)
        for _g, gdir in committed[:-self.policy.keep_generations]:
            shutil.rmtree(gdir, ignore_errors=True)

    def restore_latest(self, *, replay: bool = True) -> List[str]:
        """Adopt every tenant of the newest committed generation into
        this runtime's shards (by the manifest's shard index, modulo the
        current shard count) and replay their buffered ticks — the
        kill -9 resume path.  Returns the adopted labels (empty when no
        committed generation exists)."""
        if not self.policy.checkpoint_dir:
            raise RuntimeError(
                f"runtime {self.label!r} has no checkpoint_dir "
                f"configured (RuntimePolicy.checkpoint_dir)")
        with self._lock:
            # the manifest read stays under the lock so a concurrent
            # checkpoint pass cannot prune the generation mid-adopt
            found = self.latest_generation(   # sts: noqa[STS103]
                self.policy.checkpoint_dir)
            if found is None:
                return []
            gen, gdir, manifest = found
            adopted = []
            for row in manifest["tenants"]:
                sh = self.shards[int(row.get("shard", 0))
                                 % len(self.shards)]
                adopted.append(sh.adopt(
                    os.path.join(gdir, row["tenant"]), replay=replay))
            self._reg.inc("fleet.restored_tenants", len(adopted))
            _metrics.trace_instant(
                "fleet.generation_restored",
                {"runtime": self.label, "generation": gen,
                 "tenants": len(adopted)})
            return adopted

    # -- self-driving rebalance ----------------------------------------------

    def rebalance(self) -> List[Dict[str, Any]]:
        """Run one placement pass now; returns the executed moves."""
        with self._lock:
            return self._rebalance_locked()

    def _maybe_rebalance_locked(self, now: float) -> None:
        p = self.policy
        if p.rebalance_interval_s <= 0 or len(self.shards) < 2:
            return
        if now - self._last_rebalance_t >= p.rebalance_interval_s:
            self._last_rebalance_t = now
            self._rebalance_locked()

    def _shard_load(self, sh: FleetScheduler) -> int:
        # dispatch-cost proxy: each tenant costs one gather slot per
        # sweep plus its queued backlog
        return sum(1 + len(t.queue) for t in sh._tenants.values())

    def _plan_moves(self) -> List[Tuple[str, int, int]]:
        """(label, src_shard_idx, dst_shard_idx) picks, deterministic.

        1. *Consolidation*: an update-key group fragmented across shards
           dispatches one under-filled device batch per fragment — move
           tenants from the smallest fragment toward the largest.
        2. *Load spreading*: past that, if busiest/lightest load exceeds
           ``rebalance_imbalance``, move the busiest shard's lightest
           tenant to the lightest shard."""
        moves: List[Tuple[str, int, int]] = []
        frags: Dict[Any, List[Tuple[int, List[str]]]] = {}
        for i, sh in enumerate(self.shards):
            for key, labels in sh._groups.items():
                if labels:
                    frags.setdefault(key, []).append((i, sorted(labels)))
        for key in frags:
            parts = frags[key]
            if len(parts) < 2:
                continue
            # stable largest-fragment winner: size desc, shard idx asc
            parts = sorted(parts, key=lambda p: (-len(p[1]), p[0]))
            dst = parts[0][0]
            for src, labels in parts[1:]:
                for label in labels:
                    moves.append((label, src, dst))
        if not moves and len(self.shards) >= 2:
            loads = [self._shard_load(sh) for sh in self.shards]
            busiest = max(range(len(loads)), key=lambda i: loads[i])
            lightest = min(range(len(loads)), key=lambda i: loads[i])
            if busiest != lightest and loads[busiest] > max(
                    1, loads[lightest]) * self.policy.rebalance_imbalance:
                src_sh = self.shards[busiest]
                # spreading must never undo consolidation: only tenants
                # whose update-key group would stay whole (they are its
                # sole member on this shard) may move — otherwise the
                # two rules would trade the same tenant back and forth
                # every pass
                movable = [
                    la for la in src_sh.tenants
                    if len(src_sh._groups.get(
                        src_sh._tenants[la].session.update_key, ())) == 1]
                if movable:
                    label = min(
                        movable,
                        key=lambda la: len(src_sh._tenants[la].queue))
                    moves.append((label, busiest, lightest))
        return moves

    def _migrate_dir(self) -> str:
        base = self.policy.checkpoint_dir
        if base is None:
            import tempfile
            base = os.path.join(tempfile.gettempdir(),
                                f"sts-{self.label}-migrations")
        d = os.path.join(base, "migrations")
        os.makedirs(d, exist_ok=True)
        return d

    def _rebalance_locked(self) -> List[Dict[str, Any]]:
        if len(self.shards) < 2:
            return []
        done: List[Dict[str, Any]] = []
        for label, src_i, dst_i in \
                self._plan_moves()[:self.policy.max_moves_per_cycle]:
            src, dst = self.shards[src_i], self.shards[dst_i]
            path = os.path.join(self._migrate_dir(),
                                f"migrate-{self._migrations}-{label}")
            self._migrations += 1
            # the checkpoint path IS the migration path: drain commits
            # the bundle atomically, adopt replays the buffered ticks —
            # zero tick loss, bitwise (the PR-11 pins)
            src.drain(label, path)
            dst.adopt(path, replay=True)
            self._reg.inc("fleet.rebalanced_tenants")
            _metrics.trace_instant(
                "fleet.tenant_rebalanced",
                {"runtime": self.label, "tenant": label,
                 "from": src.label, "to": dst.label})
            done.append({"tenant": label, "from": src.label,
                         "to": dst.label, "path": path})
        return done

    # -- introspection -------------------------------------------------------

    def heartbeat_age_s(self) -> float:
        return self._job.heartbeat_age_s()

    def stale_after_s(self, factor: Optional[float] = None) -> float:
        """Scrape-plane staleness threshold for the pump heartbeat: the
        jobs' exact ``STS_TELEMETRY_STALE_FACTOR`` contract with the
        pump interval as the cadence (floored at 1 s, like
        ``JobProgress.stale_after_s``)."""
        f = _telemetry._stale_factor() if factor is None else float(factor)
        return f * max(self.policy.pump_interval_s, 1.0)

    def is_stale(self, factor: Optional[float] = None) -> bool:
        return self.running and \
            self.heartbeat_age_s() > self.stale_after_s(factor)

    def pump_summary(self) -> Dict[str, Any]:
        """Lock-free liveness block (folded into each shard's
        ``telemetry_summary()`` and rendered by sts_top): racy reads of
        counters are fine for a scrape, and taking the runtime lock
        here would make the scrape wait on a dispatch."""
        return {
            "runtime": self.label,
            "running": self._started,
            "pumps": self._pump_count,
            "restarts": self._restarts,
            "heartbeat_age_s": round(self._job.heartbeat_age_s(), 3),
            "stale_after_s": round(self.stale_after_s(), 3),
            "stalled": self.is_stale(),
            "backpressure_waiters": self._waiters,
            "checkpoint_generation": self._ckpt_gen,
            "checkpoint_failures": self._ckpt_failures,
            "last_checkpoint_unix": self._last_ckpt_unix,
            "last_error": self._last_error,
        }

    def pump_health(self) -> Dict[str, Any]:
        """The ``/healthz`` row: stale iff running with a heartbeat
        older than the jobs' staleness contract allows — an external
        supervisor restarts the process on a sustained 503."""
        return {
            "runtime": self.label,
            "shards": [sh.label for sh in self.shards],
            "running": self._started,
            "restarts": self._restarts,
            "heartbeat_age_s": round(self._job.heartbeat_age_s(), 3),
            "stale_after_s": round(self.stale_after_s(), 3),
            "stale": self.is_stale(),
        }
