"""Fitted model → state-space form, plus the exact-likelihood objective.

``to_statespace`` turns any supported fitted model pytree into a
``(StateSpace, SSMeta)`` pair; ``bootstrap`` additionally filters the
model's training history through it — calibrating the innovation
variance σ² and leaving a ready-to-serve
:class:`~spark_timeseries_tpu.statespace.ssm.FilterState` — which is how
:class:`~spark_timeseries_tpu.statespace.serving.ServingSession` starts.

Converter algebra (docs/design.md §7):

- **ARIMA(p, d, q)** — Harvey/Hamilton companion form on the d-times
  differenced series, state dim ``m = max(p, q+1)``: ``T`` carries φ in
  its first column and an identity superdiagonal, the noise loads
  through ``R = (1, θ₁..θ_q, 0..)`` with ``Q = σ²RRᵀ``, ``Z = e₁``,
  ``H = 0``.  The intercept rides the state (``c_vec = c·e₁``) so the
  same form serves ARX's exogenous offsets; the filter's stationary
  initialization is what makes the likelihood *exact* where CSS drops
  the first ``max(p, q)`` residuals.  ``d`` is folded into the meta —
  sessions difference ticks (and integrate forecasts) through a
  length-``d`` ring of last raw differences.
- **AR(p) / ARX** — the ARMA form with q = 0; ARX's exogenous
  contribution enters as a per-tick observation offset
  (``update(..., offset=xβ)``), keeping the state machinery identical.
- **EWMA** — the SES innovations form: state = the smoothed level,
  ``T = Z = (1,)``, pinned ``gain = (α,)``.  The filter step IS the
  smoothing recursion (``S_t = S_{t-1} + α(y_t - S_{t-1})``), so the
  session's level — and its flat forecast — match the fitted model
  bit-for-bit.
- **Holt-Winters (additive)** — the ETS(A,A,A) innovations form under
  the R↔ETS parameter map the fit already documents
  (``level += αe, trend += αβe, season += γ(1-α)e``): state
  ``(ℓ, b, s₁..s_period)`` with the season ring head-first, pinned
  ``gain = (α, αβ, 0.., γ(1-α))``, rotation rows in ``T``.  The
  multiplicative model's observation is nonlinear in the state and
  stays out (raise).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

from .kalman import concentrated_loglik, filter_panel
from .ssm import FilterState, SSMeta, StateSpace, initial_state

__all__ = ["to_statespace", "bootstrap", "companion_arma",
           "arma_concentrated_neg_ll", "Bootstrapped"]


def _batched_2d(x, width: int) -> jnp.ndarray:
    """Normalize model coefficients to a ``(S, width)`` batch."""
    x = jnp.asarray(x)
    if x.ndim == 1:
        x = x[None]
    return x.reshape(x.shape[0], width)


def companion_arma(phi: jnp.ndarray, theta: jnp.ndarray,
                   c: Optional[jnp.ndarray] = None) -> StateSpace:
    """Harvey companion-form ``StateSpace`` for a batched ARMA(p, q) at
    unit noise scale (σ² = 1; ``bootstrap`` rescales after calibration).

    ``phi (S, p)``, ``theta (S, q)``, ``c (S,)`` the regression-form
    intercept (enters the state as ``c·e₁``).
    """
    phi = jnp.asarray(phi)
    theta = jnp.asarray(theta)
    S, p = phi.shape
    q = theta.shape[-1]
    m = max(p, q + 1)
    dtype = phi.dtype

    T = jnp.zeros((S, m, m), dtype)
    if p:
        T = T.at[:, :p, 0].set(phi)
    if m > 1:
        idx = jnp.arange(m - 1)
        T = T.at[:, idx, idx + 1].set(1.0)
    R = jnp.zeros((S, m), dtype).at[:, 0].set(1.0)
    if q:
        R = R.at[:, 1:q + 1].set(theta)
    Q = jnp.einsum("si,sj->sij", R, R)
    Z = jnp.zeros((S, m), dtype).at[:, 0].set(1.0)
    c_vec = jnp.zeros((S, m), dtype)
    if c is not None:
        c_vec = c_vec.at[:, 0].set(jnp.asarray(c, dtype).reshape(S))
    return StateSpace(T=T, Z=Z, c=c_vec, d=jnp.zeros((S,), dtype),
                      H=jnp.zeros((S,), dtype), Q=Q,
                      gain=jnp.zeros((S, m), dtype))


def _arima_like(model, family: str) -> Tuple[StateSpace, SSMeta]:
    p, d, q = model.p, model.d, model.q
    coefs = jnp.asarray(model.coefficients)
    if coefs.ndim == 1:
        coefs = coefs[None]
    icpt = 1 if model.has_intercept else 0
    c = coefs[:, 0] if icpt else jnp.zeros((coefs.shape[0],), coefs.dtype)
    phi = coefs[:, icpt:icpt + p]
    theta = coefs[:, icpt + p:icpt + p + q]
    ssm = companion_arma(phi, theta, c)
    return ssm, SSMeta(family, "exact", int(d), ssm.state_dim)


def _ar_like(model, family: str) -> Tuple[StateSpace, SSMeta]:
    coefs = jnp.asarray(model.coefficients)
    if coefs.ndim == 1:
        coefs = coefs[None]
    S, p = coefs.shape
    if family == "arx":
        p = int(model.y_max_lag)
        phi = coefs[:, :p]
    else:
        phi = coefs
    c = jnp.asarray(model.c).reshape(-1)
    c = jnp.broadcast_to(c, (coefs.shape[0],))
    ssm = companion_arma(phi, jnp.zeros((coefs.shape[0], 0), coefs.dtype),
                         c)
    return ssm, SSMeta(family, "exact", 0, ssm.state_dim)


def _ewma(model) -> Tuple[StateSpace, SSMeta]:
    alpha = jnp.atleast_1d(jnp.asarray(model.smoothing))
    S = alpha.shape[0]
    dtype = alpha.dtype
    one = jnp.ones((S, 1, 1), dtype)
    ssm = StateSpace(T=one, Z=jnp.ones((S, 1), dtype),
                     c=jnp.zeros((S, 1), dtype),
                     d=jnp.zeros((S,), dtype),
                     H=jnp.ones((S,), dtype),
                     Q=(alpha * alpha)[:, None, None],
                     gain=alpha[:, None])
    return ssm, SSMeta("ewma", "innovations", 0, 1)


def _holt_winters(model) -> Tuple[StateSpace, SSMeta]:
    if not model.additive:
        raise NotImplementedError(
            "multiplicative Holt-Winters has a state-nonlinear observation "
            "(level·season); only the additive model has a linear "
            "state-space form — refit with model_type='additive' or serve "
            "multiplicative panels through batch refits")
    period = int(model.period)
    a = jnp.atleast_1d(jnp.asarray(model.alpha))
    b = jnp.atleast_1d(jnp.asarray(model.beta))
    g = jnp.atleast_1d(jnp.asarray(model.gamma))
    S = a.shape[0]
    dtype = a.dtype
    m = 2 + period
    T = jnp.zeros((S, m, m), dtype)
    T = T.at[:, 0, 0].set(1.0).at[:, 0, 1].set(1.0)       # ℓ' = ℓ + b
    T = T.at[:, 1, 1].set(1.0)                            # b' = b
    idx = jnp.arange(period - 1)
    T = T.at[:, 2 + idx, 3 + idx].set(1.0)                # ring rotation
    T = T.at[:, 2 + period - 1, 2].set(1.0)               # tail <- old head
    Z = jnp.zeros((S, m), dtype)
    Z = Z.at[:, 0].set(1.0).at[:, 1].set(1.0).at[:, 2].set(1.0)
    gain = jnp.zeros((S, m), dtype)
    gain = gain.at[:, 0].set(a).at[:, 1].set(a * b) \
        .at[:, 2 + period - 1].set(g * (1.0 - a))
    ssm = StateSpace(T=T, Z=Z, c=jnp.zeros((S, m), dtype),
                     d=jnp.zeros((S,), dtype),
                     H=jnp.ones((S,), dtype),
                     Q=jnp.einsum("si,sj->sij", gain, gain),
                     gain=gain)
    return ssm, SSMeta("holt_winters", "innovations", 0, m)


def to_statespace(model) -> Tuple[StateSpace, SSMeta]:
    """Express a fitted model pytree in state-space form.

    Dispatches on the model class (``ARIMAModel``, ``ARModel``,
    ``ARXModel``, ``EWMAModel``, ``HoltWintersModel``); scalar (single
    series) models are normalized to a batch of one.  Returns the model
    at **unit noise scale** — :func:`bootstrap` calibrates σ² from the
    training history.
    """
    name = type(model).__name__
    if name == "ARIMAModel":
        return _arima_like(model, "arima")
    if name == "ARModel":
        return _ar_like(model, "ar")
    if name == "ARXModel":
        return _ar_like(model, "arx")
    if name == "EWMAModel":
        return _ewma(model)
    if name == "HoltWintersModel":
        return _holt_winters(model)
    raise TypeError(
        f"no state-space form for {name}; supported: ARIMAModel, ARModel, "
        f"ARXModel, EWMAModel, HoltWintersModel (additive)")


class Bootstrapped(NamedTuple):
    """``to_statespace`` + a calibrated history filter pass: everything a
    serving session needs.  ``sigma2`` is the per-lane concentrated
    innovation-variance estimate the ssm/state were rescaled with."""
    ssm: StateSpace
    meta: SSMeta
    state: FilterState
    sigma2: jnp.ndarray


def _rescale(ssm: StateSpace, state: FilterState, meta: SSMeta,
             sigma2: jnp.ndarray) -> Tuple[StateSpace, FilterState]:
    """Move the unit-scale filter to the calibrated σ²: Q (and H in
    innovations mode) scale linearly, as does the predicted covariance;
    gains and means are scale-invariant, so nothing else moves."""
    s2q = sigma2[:, None, None]
    ssm = ssm._replace(Q=ssm.Q * s2q,
                       H=ssm.H * (sigma2 if meta.mode == "innovations"
                                  else 1.0))
    state = state._replace(P=state.P * s2q)
    return ssm, state


def bootstrap(model, history, *, offsets=None) -> Bootstrapped:
    """Build the serving form of a fitted model: convert, filter the
    training ``history (S, n)`` (NaNs are missing ticks), calibrate σ²
    from the innovations, and return the rescaled
    ``(ssm, meta, state, sigma2)``.

    The returned state's ``loglik`` is the exact log-likelihood of the
    history at the calibrated scale, so a session's running likelihood
    continues seamlessly from its bootstrap.  ``offsets (S, n)`` carries
    per-tick exogenous observation offsets for ARX models.
    """
    ssm, meta = to_statespace(model)
    history = jnp.asarray(history)
    if history.ndim == 1:
        history = history[None]
    if history.shape[0] != ssm.n_series:
        if ssm.n_series == 1:
            # scalar model over a panel: broadcast the parameters
            import jax
            ssm = jax.tree_util.tree_map(
                lambda leaf: jnp.broadcast_to(
                    leaf, (history.shape[0],) + leaf.shape[1:]), ssm)
        else:
            raise ValueError(
                f"history has {history.shape[0]} series but the model is "
                f"batched over {ssm.n_series}")
    dtype = history.dtype
    ssm = type(ssm)(*(jnp.asarray(leaf, dtype) for leaf in ssm))
    state = initial_state(ssm, meta)

    if offsets is not None:
        offsets = jnp.asarray(offsets)

    if meta.family == "ewma":
        # S_0 = x_0 exactly (the model's own seed); filter from t = 1
        first = history[:, 0]
        state = state._replace(a=jnp.where(jnp.isfinite(first),
                                           first, 0.0)[:, None])
        res = filter_panel(ssm, state, history[:, 1:], meta,
                           offsets=None if offsets is None
                           else offsets[:, 1:])
    elif meta.family == "holt_winters":
        period = meta.m - 2
        if history.shape[1] < 2 * period:
            raise ValueError(
                f"Holt-Winters bootstrap needs >= 2 periods of history "
                f"({2 * period} obs), got {history.shape[1]}")
        level0, trend0, season0 = model._init_components(history)
        a0 = jnp.concatenate([level0[..., None], trend0[..., None],
                              season0], axis=-1)
        state = state._replace(a=jnp.asarray(a0, dtype))
        res = filter_panel(ssm, state, history[:, period:], meta,
                           offsets=None if offsets is None
                           else offsets[:, period:])
    else:
        res = filter_panel(ssm, state, history, meta, offsets=offsets)

    final = res.state
    n = jnp.maximum(final.n_obs.astype(dtype), 1.0)
    sigma2 = final.ssq / n
    sigma2 = jnp.where(jnp.isfinite(sigma2) & (sigma2 > 0), sigma2, 1.0)
    ssm, final = _rescale(ssm, final, meta, sigma2)
    # the running loglik restated at the calibrated scale (the unit-scale
    # pass measured Σlog F and Σv²/F; both shift by known σ² factors)
    final = final._replace(
        loglik=concentrated_loglik(final),
        ssq=final.ssq / sigma2,
        sumlogf=final.sumlogf
        + final.n_obs.astype(dtype) * jnp.log(sigma2))
    return Bootstrapped(ssm, meta, final, sigma2)


def arma_concentrated_neg_ll(params: jnp.ndarray, diffed: jnp.ndarray,
                             p: int, q: int, icpt: int,
                             n_valid=None) -> jnp.ndarray:
    """Negative σ²-concentrated *exact* ARMA log-likelihood of one lane —
    the ``arima.fit(objective="exact")`` objective.

    ``params (icpt+p+q,)`` in the fit's ``[c?, φ.., θ..]`` layout;
    ``diffed (n,)`` the already-differenced series; ``n_valid`` (scalar)
    restricts a left-aligned ragged lane to its valid window (steps past
    it are skipped, matching the trimmed series).  Builds the companion
    form at unit scale, runs the stationary-initialized filter, and
    profiles σ² out — fully traced, autodiff-friendly, so the existing
    ``ops.optimize`` minimizers drive it.
    """
    dtype = diffed.dtype
    params = jnp.asarray(params, dtype)
    c = params[0] if icpt else jnp.zeros((), dtype)
    phi = params[icpt:icpt + p][None]
    theta = params[icpt + p:icpt + p + q][None]
    ssm = companion_arma(phi, theta, c[None])
    meta = SSMeta("arima", "exact", 0, ssm.state_dim)
    state = initial_state(ssm, meta)
    weights = None
    if n_valid is not None:
        from ..ops.ragged import step_weights
        weights = step_weights(diffed.shape[-1], jnp.asarray(n_valid),
                               offset=0, dtype=dtype)[None]
    res = filter_panel(ssm, state, diffed[None], meta, weights=weights)
    return -concentrated_loglik(res.state)[0]
