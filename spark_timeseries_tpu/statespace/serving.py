"""Online serving sessions: O(1) per-tick ingest + forecast on warm state.

The gap this closes (ROADMAP open item 3): every pre-existing path is
batch — a new observation on an already-fitted series costs a full
re-optimization through ``engine.stream_fit``.  A
:class:`ServingSession` instead holds each series' *state-space filter
state* (``statespace.ssm``: O(m²) floats per series, engine-bucketed
device buffers) and makes ingest a single cached-executable Kalman step:

- :meth:`update` — one tick for the whole panel.  The executable is a
  module-level ``jax.jit`` keyed by ``(bucket, state dim, SSMeta,
  HealthPolicy)``, so every session of the same family/shape shares one
  compiled program; :meth:`warmup` (or ``engine.warmup``-style
  pre-warming with ``STS_COMPILE_CACHE`` armed) compiles it ahead of
  traffic, after which updates trigger **zero** XLA compiles — pinned by
  ``tests/test_statespace.py`` exactly as ``tests/test_engine.py`` pins
  the fit engine.  There is no fit/optimizer call anywhere in the tick
  path: per-tick work is O(m²) per series, independent of history
  length.
- **lane health** (``statespace.health``, fused into the same jitted
  step): standardized-innovation tracking against a χ² band, non-finite
  state/covariance detection, and Joseph-form covariance conditioning
  feed a per-lane ``ok / suspect / diverged`` status.  Diverged lanes
  are quarantined in-graph — their later ticks are predict-only and
  their forecasts read NaN (or last-good, per policy) — so one poisoned
  lane can never leak garbage into the panel's accumulators or its own
  downstream consumers.
- **forecast quality** (``statespace.quality``, fused into the same
  jitted step when ``quality=QualityPolicy()`` arms it): the per-tick
  anomaly score ``ν/√F`` and its EW aggregate ride on every
  :class:`TickResult`; a bounded device-resident ring of the session's
  own h-step forecasts scores arriving actuals with the backtest tier's
  NaN-masked sMAPE/MASE/coverage definitions into EW online-accuracy
  means; and a Page-Hinkley drift detector on the
  standardized-innovation score extends the lane lattice with a sticky
  ``drifted`` status — accuracy decay that never trips the χ² band
  still pages, and ``heal(drifted=True)`` closes the loop.
- :meth:`heal` — refit quarantined (and, with ``drifted=True``,
  drift-flagged) lanes from the session's bounded per-lane history ring
  through the batch resilient path (``engine.fit_resilient``,
  auto-order fallback included) and splice the recovered state-space
  lanes back in; the session keeps serving throughout.  Counters:
  ``serving.diverged`` / ``serving.quarantined`` / ``serving.healed``
  / ``serving.drift_alarms``.
- :meth:`forecast` — h-step point forecasts straight off the filtered
  state (mean propagation + d-order integration through the raw
  difference ring), one cached executable per horizon.
- :meth:`checkpoint` / :meth:`restore` — the whole session (SSM, filter
  state, lane health, history ring, meta, tick counters) through
  ``utils.checkpoint``'s atomic pytree writer, so a serving process
  restarts where it stopped.  Restore validates the checkpoint's bucket
  geometry and ``SSMeta`` against the restoring process' engine policy
  and raises :class:`ServingRestoreMismatch` naming the differing
  fields (the ``JournalSpecMismatch`` discipline).

Metrics: ``serving.sessions`` / ``serving.ticks`` / ``serving.updates``
/ ``serving.forecasts`` / ``serving.diverged`` / ``serving.quarantined``
/ ``serving.healed`` counters, ``serving.update`` and ``serving.heal``
spans (p50/p95 land in bench's ``serving_demo`` block and gate the
per-tick SLO and heal latency in ``tools/bench_gate.py``), and
``serving.state_bytes`` / ``serving.quarantined_lanes`` gauges.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, NamedTuple, Optional

import numpy as np

from ..utils import checkpoint as _checkpoint
from ..utils import metrics as _metrics
from ..utils import resilience as _resilience
from ..utils import telemetry as _telemetry
from .convert import Bootstrapped, bootstrap
from .health import (LANE_DIVERGED, LANE_DRIFTED, LANE_NAMES, LANE_OK,
                     HealthPolicy, LaneHealth, initial_health,
                     monitored_step)
from .quality import (QualityPolicy, QualityState, forecast_half_widths,
                      initial_quality, naive_scale, quality_step)
from .ssm import FilterState, SSMeta, StateSpace, state_nbytes

__all__ = ["ServingSession", "TickResult", "start_session",
           "warmup_update", "WARMUP_FAMILIES", "ServingRestoreMismatch",
           "DEFAULT_HISTORY_RING", "TICK_LATENCY_WINDOW", "check_label"]

# format 2 = health-era checkpoints (lane health + history ring + heal
# route); format-1 checkpoints predate the health machinery and cannot
# be resumed into a monitored session
_CHECKPOINT_FORMAT = 2

# per-lane raw-tick history kept for heal() refits (a bounded ring — the
# session's memory stays O(ring), never O(stream))
DEFAULT_HISTORY_RING = 512

# the huge-but-finite state corruption the state_poison fault writes:
# representable in f32, instantly astronomically out of the χ² band
_POISON_VALUE = 1e30

# families warmup_update can synthesize an executable-shaped SSM for
# without a fitted model (the serving-capable subset of ENGINE_FAMILIES)
WARMUP_FAMILIES = ("arima", "ar", "arx", "ewma", "holt_winters")

# rolling per-session tick-latency window: the bounded ring behind the
# serving.session.<label>.tick_p50_ms / tick_p95_ms gauges and the SLO
# burn counter — O(window) host memory per session, recomputed per tick
# (≤ window floats; noise next to the Kalman step's materialization)
TICK_LATENCY_WINDOW = 256

_session_seq = itertools.count(1)


def _serving_slo_ms() -> Optional[float]:
    """The per-tick latency SLO (``STS_SERVING_SLO_MS``, milliseconds),
    parsed once per session; unset = no SLO accounting, junk raises a
    named error (the shared ``telemetry.env_positive`` contract)."""
    return _telemetry.env_positive("STS_SERVING_SLO_MS", float, None)


def check_label(label: str) -> str:
    """The one label contract for every serving-plane name (session
    labels, fleet tenant labels): non-empty ``[A-Za-z0-9_-]`` — labels
    name metrics and checkpoint files, so junk must fail eagerly."""
    if not label or not all(ch.isalnum() or ch in "_-" for ch in label):
        raise ValueError(
            f"session label must be non-empty [A-Za-z0-9_-] (it names "
            f"the serving.session.<label>.* metrics), got {label!r}")
    return label


_check_label = check_label      # pre-fleet private name


class ServingRestoreMismatch(ValueError):
    """A serving checkpoint disagrees with the restoring process' engine
    policy or its own internal geometry (bucket size vs
    ``engine.series_bucket``, ``SSMeta`` vs the stored arrays' shapes).
    Raised eagerly by :meth:`ServingSession.restore` with the differing
    fields spelled out — resuming would serve garbage or recompile per
    tick (mirrors ``utils.durability.JournalSpecMismatch``)."""


class TickResult(NamedTuple):
    """One :meth:`ServingSession.update`'s per-series outcome (real lanes
    only): the innovations ``v`` (NaN where the tick was missing or the
    lane is quarantined), their predictive variances ``F``, the
    per-series log-likelihood increment of the tick, the per-lane
    health ``status`` (``health.LANE_OK/SUSPECT/DIVERGED/DRIFTED``),
    and the user-facing anomaly surface: ``anomaly`` is the signed
    standardized innovation ``ν/√F`` (≈ N(0, 1) on a well-specified
    lane — a per-tick z-score; NaN on missing/quarantined ticks) and
    ``anomaly_ew`` its EW aggregate (the χ² health band's own EW mean
    of ``ν²/F``, χ²₁-mean-1 at stationarity), both computed in-graph
    inside the same fused update."""
    innovations: np.ndarray
    variances: np.ndarray
    loglik_inc: np.ndarray
    status: np.ndarray
    anomaly: np.ndarray
    anomaly_ew: np.ndarray


# ---------------------------------------------------------------------------
# module-level jitted kernels (one function object per program shape, so
# every session shares jax's jit cache — the STS006 discipline)
# ---------------------------------------------------------------------------

def _update_impl(meta: SSMeta, policy: HealthPolicy,
                 quality: Optional[QualityPolicy], ssm: StateSpace,
                 state: FilterState, health: LaneHealth,
                 qstate: Optional[QualityState], y, offset):
    """The whole per-tick program: one health-monitored Kalman step
    (``health.monitored_step`` — filter + χ²-band tracking + non-finite
    detection + in-graph quarantine of diverged lanes), the per-tick
    anomaly score, and — when ``quality`` arms it — the fused
    forecast-quality step (``quality.quality_step``: online-accuracy
    scoring off the forecast ring, Page-Hinkley drift, the ``drifted``
    status overlay), single-jitted with ``meta``/``policy``/``quality``
    static.  ``qstate`` is None exactly when ``quality`` is (the static
    policy selects the traced structure)."""
    import jax.numpy as jnp

    state2, health2, (v, f) = monitored_step(ssm, state, health, y,
                                             offset, meta, policy)
    ll_inc = state2.loglik - state.loglik
    anom = v / jnp.sqrt(f)
    if quality is not None:
        health2, qstate = quality_step(quality, meta, ssm, state2,
                                       health2, qstate, y, offset, v, f)
    return state2, health2, qstate, v, f, ll_inc, anom


def _forecast_impl(meta: SSMeta, horizon: int, policy: HealthPolicy,
                   ssm: StateSpace, state: FilterState,
                   health: LaneHealth, offsets):
    """h-step point forecasts from the predicted state — the shared
    mean-propagation program (``kalman.forecast_mean``), health-aware:
    quarantined lanes report NaN (``policy.forecast_policy="nan"``) or
    propagate from their last pre-divergence state (``"last_good"``)
    instead of serving forecasts off a poisoned state."""
    import jax.numpy as jnp

    from .kalman import forecast_mean

    quarantined = health.status == LANE_DIVERGED
    if policy.forecast_policy == "last_good":
        a = jnp.where(quarantined[:, None], health.good_a, state.a)
        ring = jnp.where(quarantined[:, None], health.good_ring,
                         state.ring) if meta.d_order else state.ring
        return forecast_mean(meta, horizon, ssm, a, ring, offsets)
    fc = forecast_mean(meta, horizon, ssm, state.a, state.ring, offsets)
    return jnp.where(quarantined[:, None],
                     jnp.asarray(jnp.nan, fc.dtype), fc)


_jit_lock = threading.Lock()
_jit_cache: dict = {}


def _jitted(kind: str):
    """Lazily-built module-level jits (imports jax on first use so merely
    importing the package never initializes a backend).  Arms the
    engine's persistent compile cache first, so a serving process that
    never builds a ``FitEngine`` still honors ``STS_COMPILE_CACHE`` —
    its first update deserializes instead of compiling."""
    with _jit_lock:
        fn = _jit_cache.get(kind)
        if fn is None:
            import jax

            from ..engine import configure_compile_cache
            configure_compile_cache()
            if kind == "update":
                fn = jax.jit(_update_impl, static_argnums=(0, 1, 2))
            else:
                fn = jax.jit(_forecast_impl, static_argnums=(0, 1, 2))
            _jit_cache[kind] = fn
        return fn


def _pad_lanes(tree, bucket: int, n_real: int):
    """Pad every batched leaf to the series bucket by replicating lane 0
    (finite, harmless — padded lanes only ever see NaN ticks, which the
    filter skips)."""
    import jax
    import jax.numpy as jnp

    pad = bucket - n_real
    if pad == 0:
        return tree

    def grow(leaf):
        return jnp.concatenate(
            [leaf, jnp.broadcast_to(leaf[:1], (pad,) + leaf.shape[1:])])

    return jax.tree_util.tree_map(grow, tree)


def _heal_spec_for(model) -> Optional[Dict[str, Any]]:
    """The batch-refit route ``heal()`` takes for this model family —
    the family name plus the static fit arguments, JSON-plain so it
    checkpoints.  None when no ring-history refit exists (ARX: the
    exogenous offsets are not ring-buffered)."""
    name = type(model).__name__
    if name == "ARIMAModel":
        return {"family": "arima", "p": int(model.p), "d": int(model.d),
                "q": int(model.q),
                "include_intercept": bool(model.has_intercept)}
    if name == "ARModel":
        coefs = np.asarray(model.coefficients)
        return {"family": "ar", "max_lag": int(coefs.shape[-1])}
    if name == "EWMAModel":
        return {"family": "ewma"}
    if name == "HoltWintersModel":
        return {"family": "holt_winters", "period": int(model.period)}
    return None


class ServingSession:
    """Warm per-series filter state + cached tick/forecast executables,
    with per-lane health monitoring, divergence quarantine, and
    :meth:`heal`-able lanes.

    Build one with :meth:`start` (fitted model + its training history) or
    :meth:`restore` (a checkpoint).  Not thread-safe per instance — one
    session is one logical stream; shard across sessions for parallel
    ingest (the compiled programs are shared through the jit cache).
    """

    def __init__(self, ssm: StateSpace, meta: SSMeta, state: FilterState,
                 n_series: int, *, ticks_seen: int = 0,
                 registry=None, policy: Optional[HealthPolicy] = None,
                 health: Optional[LaneHealth] = None,
                 heal_spec: Optional[Dict[str, Any]] = None,
                 history_ring: int = DEFAULT_HISTORY_RING,
                 history_tail=None, _hist_state=None,
                 quality: Optional[QualityPolicy] = None,
                 _qstate: Optional[QualityState] = None,
                 label: Optional[str] = None):
        from ..engine import series_bucket

        self._reg = registry if registry is not None \
            else _metrics.get_registry()
        self.meta = meta
        self.policy = (policy if policy is not None
                       else HealthPolicy()).validate()
        self.n_series = int(n_series)
        self._bucket = series_bucket(self.n_series)
        self.ticks_seen = int(ticks_seen)
        if ssm.n_series == self._bucket:       # already bucketed (restore)
            self._ssm, self._state = ssm, state
        else:
            self._ssm = _pad_lanes(ssm, self._bucket, ssm.n_series)
            self._state = _pad_lanes(state, self._bucket, state.a.shape[0])
        self._dtype = np.dtype(self._ssm.T.dtype)
        self._health = initial_health(self._state) if health is None \
            else health
        self._heal_spec = heal_spec
        self._status_host = np.asarray(
            self._health.status[:self.n_series]).copy()
        self._poisoned_specs: set = set()

        # bounded per-lane raw-tick ring (real lanes only): heal()'s
        # refit history.  O(ring) memory however long the stream runs.
        if _hist_state is not None:
            self._hist, self._hist_pos, self._hist_fill = _hist_state
            self._hist_len = self._hist.shape[1]
        else:
            self._hist_len = max(8, int(history_ring))
            self._hist = np.full((self.n_series, self._hist_len),
                                 np.nan, self._dtype)
            self._hist_pos = 0
            self._hist_fill = 0
            if history_tail is not None:
                tail = np.asarray(history_tail, self._dtype)
                tail = tail[:, -self._hist_len:]
                k = tail.shape[1]
                self._hist[:, :k] = tail
                self._hist_pos = k % self._hist_len
                self._hist_fill = k
        # telemetry plane (docs/design.md §6f): a stable label names
        # this session's serving.session.<label>.* latency/SLO metrics;
        # the session is weakly registered for /snapshot.json summaries
        # (the exporter never pins it), and the STS_TELEMETRY_PORT
        # opt-in is honored here — all strictly host-side, nothing on
        # the jitted tick path changes
        self.label = _check_label(label) if label is not None \
            else f"s{next(_session_seq)}"
        self._tick_lat: deque = deque(maxlen=TICK_LATENCY_WINDOW)
        self._slo_ms = _serving_slo_ms()
        self._slo_burns = 0
        # forecast-quality plane (docs/design.md §7d): arming it fuses
        # the online-accuracy + drift step into the SAME jitted update
        # (the quality policy joins the executable's static key); the
        # MASE scale comes from the seeded history ring's tail and the
        # coverage half-width from the calibrated ssm's own ψ weights
        self._quality = quality.validate() if quality is not None \
            else None
        self._drift_alarms = 0
        self._q_host: Optional[Dict[str, np.ndarray]] = None
        if _qstate is not None:
            self._qstate: Optional[QualityState] = _qstate
        elif self._quality is not None:
            self._qstate = self._initial_qstate()
        else:
            self._qstate = None
        _telemetry.register_session(self)
        _telemetry.ensure_started_from_env()
        self._reg.inc("serving.sessions")
        self._reg.set_gauge(
            "serving.state_bytes",
            state_nbytes((self._state, self._health, self._qstate)))

    def _initial_qstate(self) -> QualityState:
        """A cold bucket-width quality state: MASE scale from the seeded
        history ring (NaN — never scoring — when the session started
        without history), coverage half-widths from the calibrated
        ssm's ψ weights.  Pad lanes replicate lane 0 (harmless: their
        ticks are always NaN, so they never score or drift)."""
        q = self._quality
        hist = self._ring_history()
        if hist.shape[1] >= 2:
            scale = naive_scale(hist)
        else:
            scale = np.full((self.n_series,), np.nan)
        half = np.asarray(forecast_half_widths(
            self._ssm, self.meta, q.horizon, q.coverage))  # bucket-wide
        scale_b = np.full((self._bucket,), np.nan, np.float64)
        scale_b[:self.n_series] = scale
        scale_b[self.n_series:] = scale[0] if scale.size else np.nan
        return initial_quality(self._bucket, q, self._dtype, scale_b,
                               half)

    # -- construction -------------------------------------------------------

    @classmethod
    def start(cls, model, history, *, offsets=None, registry=None,
              policy: Optional[HealthPolicy] = None,
              history_ring: int = DEFAULT_HISTORY_RING,
              quality: Optional[QualityPolicy] = None,
              label: Optional[str] = None) -> "ServingSession":
        """Open a session from a fitted model pytree and the history it
        was fitted on: converts to state-space form
        (``statespace.convert.to_statespace``), filters the history to a
        warm state, calibrates σ², and buckets the per-series buffers.
        ``history (n_series, n_obs)`` (NaNs are missing ticks);
        ``offsets`` carries ARX per-tick exogenous observation offsets.
        ``policy`` tunes the health monitor (χ² band, Joseph form,
        quarantined-forecast policy); ``history_ring`` bounds the
        per-lane raw-tick ring :meth:`heal` refits from (seeded with the
        history's tail); ``quality=QualityPolicy()`` arms the fused
        forecast-quality plane (online accuracy, anomaly gauges, drift
        alarms — docs/design.md §7d).
        """
        import jax.numpy as jnp

        history = jnp.asarray(history)
        if history.ndim == 1:
            history = history[None]
        boot: Bootstrapped = bootstrap(model, history, offsets=offsets)
        return cls(boot.ssm, boot.meta, boot.state, history.shape[0],
                   ticks_seen=int(history.shape[1]), registry=registry,
                   policy=policy, heal_spec=_heal_spec_for(model),
                   history_ring=history_ring, quality=quality,
                   history_tail=np.asarray(history), label=label)

    # -- serving ------------------------------------------------------------

    @property
    def update_key(self):
        """The hashable key of this session's per-tick update executable:
        ``(bucket, dtype, SSMeta, HealthPolicy, QualityPolicy-or-None)``
        (the state dim rides inside ``meta.m``; the dtype rides the
        buffers, and mixing it would silently promote a coalesced batch;
        arming quality changes the traced program, so quality-on and
        quality-off sessions never share an executable).  Sessions with
        equal keys share ONE compiled program through the module-level
        jit cache — the fact the fleet tier's tick coalescing exploits
        (``statespace.fleet``): same-key ticks can gather into one wider
        device call of the very same traced function."""
        return (self._bucket, str(self._dtype), self.meta, self.policy,
                self._quality)

    def _prepare_tick(self, ticks, offset=None):
        """Validate + pad one tick into the bucket-shaped host buffers
        the update executable consumes, applying the serving-tier fault
        hooks.  Returns ``(host (n_series,), y (bucket,), off (bucket,))``
        — shared by :meth:`update` and the fleet scheduler's coalesced
        dispatch, so both paths see identical tick semantics."""
        host = np.asarray(ticks, self._dtype).reshape(-1)
        if host.shape[0] != self.n_series:
            raise ValueError(
                f"update expects one tick per series ({self.n_series}), "
                f"got {host.shape[0]}")
        host = self._apply_faults(host)
        y = np.full((self._bucket,), np.nan, self._dtype)
        y[:self.n_series] = host
        off = np.zeros((self._bucket,), self._dtype)
        if offset is not None:
            off_host = np.asarray(offset, self._dtype).reshape(-1)
            if off_host.shape[0] != self.n_series:
                raise ValueError(
                    f"update expects one exogenous offset per series "
                    f"({self.n_series}), got {off_host.shape[0]}")
            off[:self.n_series] = off_host
        return host, y, off

    def _absorb_tick(self, host, state2, health2, out: TickResult,
                     dt_s: float, qstate2=None, lineage=None) -> TickResult:
        """Commit one tick's outputs into the session: state/health/
        quality swap, transition + latency accounting, history-ring
        push.  ``state2``/``health2``/``qstate2`` are the bucket-width
        device pytrees (or, from the fleet's coalesced call, that call's
        per-session slices); ``out`` carries the already-materialized
        real-lane results.  The other half of :meth:`_prepare_tick`; the
        fleet scheduler calls the pair around its shared device call so
        coalesced ticks are bitwise the per-session ticks.  ``lineage``
        (the fleet's per-tick trace record) closes its ``scatter``
        segment once the commit is visible — host-side accounting only,
        never traced state."""
        self._state = state2
        self._health = health2
        if self._quality is not None and qstate2 is not None:
            self._qstate = qstate2
        self._note_transitions(out.status)
        self._note_tick_latency(dt_s)
        if self._quality is not None:
            self._note_quality(out)
        # the ring normalizes non-finite arrivals to NaN (the filter
        # already degrades inf to a missed tick; a verbatim inf would
        # needlessly poison heal()'s refit window for ring-length ticks)
        self._hist[:, self._hist_pos] = np.where(np.isfinite(host),
                                                 host, np.nan)
        self._hist_pos = (self._hist_pos + 1) % self._hist_len
        self._hist_fill = min(self._hist_fill + 1, self._hist_len)
        self.ticks_seen += 1
        self._reg.inc("serving.updates")
        self._reg.inc("serving.ticks", self.n_series)
        if lineage is not None:
            lineage.stage_end("scatter")
        return out

    def update(self, ticks, offset=None) -> TickResult:
        """Ingest one tick per series — a single cached-executable
        health-monitored Kalman step, O(1) work per tick per series.

        ``ticks (n_series,)`` raw observations (NaN = missing: the lane's
        state predicts forward and contributes no likelihood; an Inf tick
        degrades to missing the same way — bad wire data must not poison
        the state); ``offset (n_series,)`` the ARX exogenous observation
        offsets for this tick.  Quarantined (diverged) lanes are
        predict-only regardless of the tick.  Returns the per-series
        :class:`TickResult`, whose ``status`` reports each lane's health
        after the tick; lanes newly entering ``diverged`` are counted
        (``serving.diverged`` / ``serving.quarantined``) and marked on
        the trace timeline.
        """
        host, y, off = self._prepare_tick(ticks, offset)
        fn = _jitted("update")
        t0 = time.perf_counter()
        with _metrics.span("serving.update"):
            state2, health2, qstate2, v, f, ll_inc, anom = fn(
                self.meta, self.policy, self._quality, self._ssm,
                self._state, self._health, self._qstate, y, off)
            # materialize inside the span: the p50/p95 the bench gate
            # SLOs must cover the real per-tick latency, not the async
            # dispatch alone
            n = self.n_series
            out = TickResult(
                np.asarray(v[:n]),
                np.asarray(f[:n]),
                np.asarray(ll_inc[:n]),
                np.asarray(health2.status[:n]),
                np.asarray(anom[:n]),
                np.asarray(health2.ew[:n]))
        return self._absorb_tick(host, state2, health2, out,
                                 time.perf_counter() - t0, qstate2)

    def update_batch(self, ticks, offsets=None) -> TickResult:
        """Bulk catch-up ingest: ``ticks (n_series, k)`` chronological
        columns, each replayed through the warmed per-tick executable —
        bitwise the ``k`` individual :meth:`update` calls, zero new
        compiles on a warmed session (the replay primitive the fleet's
        ``adopt`` migration uses; shed-restore replays per-tick to
        honor heterogeneous per-tick offsets).  Returns the LAST tick's
        :class:`TickResult`.

        A batch whose width disagrees with the session raises a named
        error up front — without this check a transposed or
        wrong-tenant panel surfaced as an opaque reshape/broadcast
        failure from inside the jitted step."""
        batch = np.asarray(ticks, self._dtype)
        if batch.ndim != 2 or batch.shape[0] != self.n_series:
            raise ValueError(
                f"update_batch expects a (n_series, k) = "
                f"({self.n_series}, k) chronological tick panel for "
                f"this session (bucket {self._bucket}), got shape "
                f"{batch.shape}; transpose a (k, n_series) stream, or "
                f"route a different-width panel to its own session")
        if batch.shape[1] == 0:
            raise ValueError("update_batch needs at least one tick "
                             "column")
        offs = None
        if offsets is not None:
            offs = np.asarray(offsets, self._dtype)
            if offs.shape != batch.shape:
                raise ValueError(
                    f"update_batch offsets must match the tick panel "
                    f"shape {batch.shape}, got {offs.shape}")
        out = None
        for t in range(batch.shape[1]):
            out = self.update(batch[:, t],
                              offs[:, t] if offs is not None else None)
        return out

    def _apply_faults(self, host: np.ndarray) -> np.ndarray:
        """Serving-tier fault injection (``utils.resilience``), all
        host-side: corrupt incoming ticks or poison filter state for
        deterministic lanes — the testable stand-ins for bad wire data
        and numerical divergence."""
        spec = _resilience.serving_fault("tick_corrupt_nan")
        if spec is None:
            spec = _resilience.serving_fault("tick_corrupt_inf")
        if spec is not None:
            host = host.copy()
            host[::spec.lane_stride] = np.nan \
                if spec.mode == "tick_corrupt_nan" else np.inf
        spec = _resilience.serving_fault("state_poison")
        token = _resilience.fault_scope_token()
        if spec is not None and token not in self._poisoned_specs:
            # once per fault scope per session (keyed by the scope's
            # never-reused token — id(spec) can be recycled across
            # scopes): a poisoned state stays poisoned on its own —
            # re-writing it every tick would defeat the
            # heal-then-keep-serving scenario under test
            import jax.numpy as jnp

            self._poisoned_specs.add(token)
            rows = np.arange(self.n_series)[::spec.lane_stride]
            a = np.asarray(self._state.a).copy()
            a[rows] = _POISON_VALUE
            self._state = self._state._replace(a=jnp.asarray(a))
            _metrics.trace_instant("serving.fault.state_poison",
                                   {"lanes": int(rows.size)})
        return host

    def _note_transitions(self, status: np.ndarray) -> None:
        newly = (status == LANE_DIVERGED) \
            & (self._status_host != LANE_DIVERGED)
        n_new = int(newly.sum())
        if n_new:
            # divergence IS quarantine: the same tick that flags the
            # lane also masks it predict-only in-graph
            self._reg.inc("serving.diverged", n_new)
            self._reg.inc("serving.quarantined", n_new)
            _metrics.trace_instant(
                "serving.lane_diverged",
                {"lanes": n_new, "tick": int(self.ticks_seen)})
        if n_new or (self._status_host == LANE_DIVERGED).any():
            self._reg.set_gauge(
                "serving.quarantined_lanes",
                int(np.sum(status == LANE_DIVERGED)))
        newly_dr = (status == LANE_DRIFTED) \
            & (self._status_host != LANE_DRIFTED)
        n_dr = int(newly_dr.sum())
        if n_dr:
            # drift alarms: the lane keeps serving, but its accuracy
            # left the fit-time baseline — pageable, heal-able
            self._drift_alarms += n_dr
            self._reg.inc("serving.drift_alarms", n_dr)
            _metrics.trace_instant(
                "serving.lane_drifted",
                {"lanes": n_dr, "tick": int(self.ticks_seen)})
        self._status_host = status.copy()

    def _note_tick_latency(self, dt_s: float) -> None:
        """Fold one tick's wall latency into the session's rolling
        window and publish the ``serving.session.<label>.*`` SLO
        surface: tick p50/p95 gauges off the bounded ring, an SLO burn
        counter against ``STS_SERVING_SLO_MS``, and the per-session
        quarantined-lanes gauge alongside (the global
        ``serving.quarantined_lanes`` gauge is last-write-wins across
        sessions; the labeled one is this session's own).  Host-side
        accounting only — the warmed tick executable is untouched."""
        self._tick_lat.append(float(dt_s))
        pre = f"serving.session.{self.label}"
        ms = dt_s * 1e3
        if self._slo_ms is not None and ms > self._slo_ms:
            self._slo_burns += 1
            self._reg.inc(f"{pre}.slo_burns")
            self._reg.inc("serving.slo_burns")
            _metrics.trace_instant(
                "serving.slo_burn",
                {"session": self.label, "tick_ms": round(ms, 3),
                 "slo_ms": self._slo_ms})
        arr = np.fromiter(self._tick_lat, dtype=np.float64)
        self._reg.set_gauge(f"{pre}.tick_p50_ms",
                            float(np.percentile(arr, 50)) * 1e3)
        self._reg.set_gauge(f"{pre}.tick_p95_ms",
                            float(np.percentile(arr, 95)) * 1e3)
        self._reg.set_gauge(
            f"{pre}.quarantined_lanes",
            int(np.sum(self._status_host == LANE_DIVERGED)))

    def _note_quality(self, out: TickResult) -> None:
        """Publish the per-tick quality surface: the
        ``serving.session.<label>.live_smape`` / ``.anomaly_p95`` /
        ``.drift_alarms`` gauges and the host-side snapshot
        :meth:`quality_summary` and ``/snapshot.json`` read.  Host-side
        accounting only — a few tiny device→host slices per tick, all
        warmed by :meth:`warmup` so the 0-recompile pin holds."""
        q = self._qstate
        n = self.n_series
        self._q_host = {
            "ew_smape": np.asarray(q.ew_smape[:n]),
            "ew_mase": np.asarray(q.ew_mase[:n]),
            "ew_cover": np.asarray(q.ew_cover[:n]),
            "n_scored": np.asarray(q.n_scored[:n]),
            "anomaly_ew": out.anomaly_ew,
        }
        pre = f"serving.session.{self.label}"
        # quarantined lanes are excluded from the aggregate: their EW
        # metrics froze at the (often astronomical) pre-divergence
        # error, which would let one dead lane mask the live panel's
        # real accuracy
        scored = (self._q_host["n_scored"] > 0) \
            & (out.status != LANE_DIVERGED)
        if scored.any():
            self._reg.set_gauge(
                f"{pre}.live_smape",
                float(self._q_host["ew_smape"][scored].mean()))
        fin = np.isfinite(out.anomaly_ew) \
            & (out.status != LANE_DIVERGED)
        if fin.any():
            # the p95 across live lanes of the EW anomaly aggregate
            # (χ²₁ mean 1 on a healthy panel — a stable paging signal,
            # unlike the raw per-tick score).  Quarantined lanes are
            # excluded here exactly as in quality_summary — their EW
            # froze at the pre-divergence blowup, and the gauge and the
            # snapshot panel must never disagree about the same metric.
            self._reg.set_gauge(
                f"{pre}.anomaly_p95",
                float(np.percentile(out.anomaly_ew[fin], 95)))
        self._reg.set_gauge(f"{pre}.drift_alarms", self._drift_alarms)
        self._reg.set_gauge(f"{pre}.drifted_lanes",
                            int(np.sum(out.status == LANE_DRIFTED)))

    def quality_summary(self) -> Optional[Dict[str, Any]]:
        """The forecast-quality panel for this session (None when
        quality tracking is off): EW online accuracy over the scored
        lanes, the lane-anomaly p95, and the drift state — exactly what
        the ``QUALITY`` section of ``/snapshot.json`` / ``sts_top``
        renders."""
        if self._quality is None:
            return None
        qh = self._q_host
        if qh is None:          # no tick yet: materialize on demand
            q = self._qstate
            n = self.n_series
            qh = {"ew_smape": np.asarray(q.ew_smape[:n]),
                  "ew_mase": np.asarray(q.ew_mase[:n]),
                  "ew_cover": np.asarray(q.ew_cover[:n]),
                  "n_scored": np.asarray(q.n_scored[:n]),
                  "anomaly_ew": np.asarray(self._health.ew[:n])}
        # live lanes only: a quarantined lane's EW metrics froze at its
        # pre-divergence error (see _note_quality)
        scored = (qh["n_scored"] > 0) \
            & (self._status_host != LANE_DIVERGED)
        ew = qh["anomaly_ew"]
        fin = np.isfinite(ew) & (self._status_host != LANE_DIVERGED)
        # lanes with no valid MASE scale (constant or NaN history) score
        # sMAPE/coverage but their ew_mase never folds — averaging their
        # 0.0 initialization in would dilute live_mase toward perfect
        scale = np.asarray(self._qstate.scale[:self.n_series])
        mase_ok = scored & np.isfinite(scale) & (scale > 0)

        def _mean(key, m=None):
            m = scored if m is None else m
            return round(float(qh[key][m].mean()), 4) \
                if m.any() else None

        return {
            "horizon": int(self._quality.horizon),
            "scored_lanes": int(scored.sum()),
            "scored_ticks": int(qh["n_scored"].sum()),
            "live_smape": _mean("ew_smape"),
            "live_mase": _mean("ew_mase", mase_ok),
            "live_coverage": _mean("ew_cover"),
            "anomaly_p95": round(float(np.percentile(ew[fin], 95)), 4)
            if fin.any() else None,
            "drifted_lanes":
                int(np.sum(self._status_host == LANE_DRIFTED)),
            "drift_alarms": int(self._drift_alarms),
        }

    def tick_latency_stats(self) -> Dict[str, Any]:
        """The rolling window's latency summary (ms) — what the labeled
        gauges and ``/snapshot.json`` report."""
        if not self._tick_lat:
            return {"window": 0}
        arr = np.fromiter(self._tick_lat, dtype=np.float64) * 1e3
        return {
            "window": int(arr.size),
            "tick_p50_ms": round(float(np.percentile(arr, 50)), 4),
            "tick_p95_ms": round(float(np.percentile(arr, 95)), 4),
            "tick_max_ms": round(float(arr.max()), 4),
            "slo_ms": self._slo_ms,
            "slo_burns": self._slo_burns,
        }

    def telemetry_summary(self) -> Dict[str, Any]:
        """One scrape-ready dict for the telemetry plane's
        ``/snapshot.json`` (``utils.telemetry.session_summaries``).
        The ``quality`` sub-dict appears only when quality tracking is
        armed — consumers (``sts_top``) must render its absence, not
        KeyError on it."""
        doc = {
            "label": self.label,
            **self.describe(),
            "health": self.health_counts(),
            "quarantined_lanes":
                int(np.sum(self._status_host == LANE_DIVERGED)),
            **self.tick_latency_stats(),
        }
        if self._quality is not None:
            doc["quality"] = self.quality_summary()
        return doc

    def forecast(self, horizon: int, offsets=None) -> np.ndarray:
        """``(n_series, horizon)`` point forecasts from the current
        filtered state — mean propagation with zero future innovations,
        integrated back through the raw-difference ring for d > 0
        families.  Quarantined lanes report NaN (or last-good, per
        ``policy.forecast_policy``) instead of garbage.  ``offsets
        (n_series, horizon)`` adds known future exogenous contributions
        (ARX)."""
        horizon = int(horizon)
        if horizon < 1:
            raise ValueError("forecast needs horizon >= 1")
        offs = np.zeros((self._bucket, horizon), self._dtype)
        if offsets is not None:
            offs[:self.n_series] = np.asarray(offsets, self._dtype)
        fn = _jitted("forecast")
        with _metrics.span("serving.forecast"):
            out = np.asarray(fn(self.meta, horizon, self.policy,
                                self._ssm, self._state, self._health,
                                offs))
        self._reg.inc("serving.forecasts")
        return out[:self.n_series]

    def warmup(self) -> None:
        """Compile the update executable ahead of traffic (the forecast
        executable is per-horizon — the first :meth:`forecast` at a new
        horizon compiles).  Functionally a no-op: the filter is pure, so
        the warmup result is simply discarded and the state is untouched.
        With ``STS_COMPILE_CACHE`` armed the compile also persists, and
        the next process deserializes instead of compiling."""
        y = np.full((self._bucket,), np.nan, self._dtype)
        off = np.zeros((self._bucket,), self._dtype)
        fn = _jitted("update")
        with _metrics.span("serving.warmup"):
            _, health2, q2, v, f, ll, anom = fn(
                self.meta, self.policy, self._quality, self._ssm,
                self._state, self._health, self._qstate, y, off)
            # also warm the real-lane result slices update materializes
            # (tiny per-(bucket, n_series) device programs of their own —
            # without this the first tick would compile them)
            n = self.n_series
            np.asarray(v[:n])
            np.asarray(f[:n])
            np.asarray(ll[:n])
            np.asarray(health2.status[:n])
            np.asarray(anom[:n])
            np.asarray(health2.ew[:n])
            if self._quality is not None:
                # the per-tick quality-gauge slices too
                np.asarray(q2.ew_smape[:n])
                np.asarray(q2.ew_mase[:n])
                np.asarray(q2.ew_cover[:n])
                np.asarray(q2.n_scored[:n])

    # -- health + healing ---------------------------------------------------

    @property
    def lane_status(self) -> np.ndarray:
        """Per-series health codes (``health.LANE_OK/SUSPECT/DIVERGED``)
        after the last tick."""
        return np.asarray(self._health.status[:self.n_series])

    def health_counts(self) -> Dict[str, int]:
        """``{status_name: lane count}`` (only nonzero entries)."""
        s = self.lane_status
        return {name: int(np.sum(s == code))
                for code, name in LANE_NAMES.items()
                if int(np.sum(s == code))}

    def _ring_history(self) -> np.ndarray:
        """The ring's ticks in chronological order, ``(n_series, k)``
        with ``k = min(ticks stored, ring capacity)``."""
        if self._hist_fill < self._hist_len:
            return self._hist[:, :self._hist_fill]
        return np.roll(self._hist, -self._hist_pos, axis=1)

    @staticmethod
    def _gapfree_suffix(hist: np.ndarray) -> np.ndarray:
        """Per lane, NaN out everything up to and including the last
        non-finite tick, leaving the longest gap-free suffix as a
        leading-NaN-padded (ragged) window — the shape the batch
        resilient path fits directly.  Without this, ONE missing tick
        anywhere in a lane's ring window would classify the lane
        ``interior_gap``-unfittable and make it permanently unhealable;
        with it, the lane heals from its clean recent history (or is
        honestly reported dead when that suffix is too short)."""
        bad = ~np.isfinite(hist)
        out = np.where(bad, np.nan, hist)
        any_bad = bad.any(axis=1)
        if any_bad.any():
            n = hist.shape[1]
            last_bad = n - 1 - np.argmax(bad[:, ::-1], axis=1)
            cols = np.arange(n)
            out[any_bad[:, None]
                & (cols[None, :] <= last_bad[:, None])] = np.nan
        return out

    def heal(self, *, auto_order: bool = True, engine=None,
             drifted: bool = False) -> Dict[str, Any]:
        """Refit every quarantined lane from the bounded history ring
        through the batch resilient path and splice the recovered lanes
        back into the live session.  ``drifted=True`` additionally
        refits the quality plane's drift-flagged lanes — by alarm time
        the bounded ring is dominated by the post-shift regime, so the
        refit (auto-order mini candidate search included) re-centers the
        lane on the stream it actually serves, and its quality state
        (MASE scale, coverage half-width, EW metrics, drift statistic)
        resets to the new baseline.

        The refit is the full §3b machinery — health masking, multi-start
        retry, fallback chains, and (``auto_order=True``, arima) the
        searched-order fallback stage — so a lane that diverged because
        its order stopped fitting its stream comes back at a *better*
        order, not just a re-bootstrapped copy of the old one.  Healed
        lanes get a fresh bootstrap (σ² recalibrated on the ring
        history), their monitor state resets to OK, and the session keeps
        serving through the same warmed executable (same bucket/meta/
        policy — zero new tick-path compiles).  Lanes whose refit still
        fails stay quarantined.

        Returns ``{"quarantined", "healed", "dead", ...}``; counts land
        in ``serving.healed`` / ``serving.heal_failed`` and the
        ``serving.heal`` span times the whole operation (the bench
        gate's ``heal_p50``).
        """
        import jax
        import jax.numpy as jnp

        status = self.lane_status
        mask = status == LANE_DIVERGED
        n_quarantined = int(mask.sum())
        report: Dict[str, Any] = {"quarantined": n_quarantined,
                                  "healed": 0, "dead": 0}
        if drifted:
            mask = mask | (status == LANE_DRIFTED)
            report["drifted"] = int(np.sum(status == LANE_DRIFTED))
        rows = np.flatnonzero(mask)
        report["dead"] = int(rows.size)
        if rows.size == 0:
            return report
        if self._heal_spec is None:
            raise NotImplementedError(
                f"heal() has no batch refit route for family "
                f"{self.meta.family!r} (its exogenous offsets are not "
                f"ring-buffered); restart the session from a fresh fit")
        hist = self._ring_history()
        with _metrics.span("serving.heal"):
            # refit (and re-bootstrap) from each lane's longest gap-free
            # recent window, as leading-NaN ragged lanes
            sub = self._gapfree_suffix(hist[rows])
            try:
                model, outcome = self._heal_refit(sub, auto_order,
                                                  engine)
            except Exception as e:  # noqa: BLE001 — a heal that cannot
                # refit must leave the session serving (quarantine
                # already contains the damage), not kill it
                self._reg.inc("serving.heal_errors")
                _metrics.trace_instant(
                    "serving.heal_error", {"error": type(e).__name__})
                # a failed heal is a crash-forensics moment: the lanes
                # stay quarantined and an operator needs the refit's
                # traceback + the session's state to decide what next
                from ..utils import flightrec as _flightrec
                _flightrec.record_incident(
                    "heal_failure", exc=e,
                    extra={"session": self.telemetry_summary(),
                           "quarantined_rows": rows.tolist()[:256]},
                    registry=self._reg)
                report["error"] = f"{type(e).__name__}: {e}"
                return report
            ok = np.isin(outcome.status,
                         (_resilience.STATUS_OK,
                          _resilience.STATUS_RETRIED,
                          _resilience.STATUS_FALLBACK))
            healed_rows = rows[ok]
            if healed_rows.size:
                ok_idx = np.flatnonzero(ok)

                def take(leaf):
                    if hasattr(leaf, "ndim") \
                            and getattr(leaf, "ndim", 0) >= 1 \
                            and leaf.shape[0] == rows.size:
                        return leaf[jnp.asarray(ok_idx)]
                    return leaf

                sub_model = jax.tree_util.tree_map(take, model)
                boot = bootstrap(sub_model, jnp.asarray(sub[ok]))
                if boot.meta != self.meta:
                    raise ServingRestoreMismatch(
                        f"heal refit produced meta {boot.meta}, session "
                        f"serves {self.meta} — the heal route drifted "
                        f"from the session's family/order")
                self._splice(healed_rows, boot)
                if self._quality is not None:
                    self._reset_quality_lanes(healed_rows, boot,
                                              sub[ok])
            n_healed = int(healed_rows.size)
            n_dead = int(rows.size - n_healed)
            self._reg.inc("serving.healed", n_healed)
            if n_dead:
                self._reg.inc("serving.heal_failed", n_dead)
            self._reg.set_gauge("serving.quarantined_lanes",
                                int(np.sum(self.lane_status
                                           == LANE_DIVERGED)))
            _metrics.trace_instant(
                "serving.heal", {"quarantined": int(rows.size),
                                 "healed": n_healed, "dead": n_dead})
        report.update(healed=n_healed, dead=n_dead)
        if outcome.orders is not None:
            report["orders"] = np.asarray(outcome.orders)[ok].tolist()
        return report

    def _heal_refit(self, values: np.ndarray, auto_order: bool, engine):
        """Batch-resilient refit of the gathered quarantined lanes,
        routed per family (the same table ``engine.resilient_dispatch``
        serves)."""
        import jax.numpy as jnp

        from ..engine import default_engine

        eng = engine if engine is not None else default_engine()
        spec = dict(self._heal_spec)
        family = spec.pop("family")
        v = jnp.asarray(values)
        if family == "arima":
            icpt = spec["include_intercept"]
            auto = bool(auto_order) and icpt \
                and (spec["p"] > 0 or spec["q"] > 0)
            return eng.fit_resilient(v, "arima", spec["p"], spec["d"],
                                     spec["q"], include_intercept=icpt,
                                     auto_order=auto)
        if family == "ar":
            return eng.fit_resilient(v, "ar", spec["max_lag"])
        if family == "ewma":
            return eng.fit_resilient(v, "ewma")
        if family == "holt_winters":
            return eng.fit_resilient(v, "holt_winters", spec["period"])
        raise NotImplementedError(
            f"no heal refit route for family {family!r}")

    def _splice(self, rows: np.ndarray, boot: Bootstrapped) -> None:
        """Scatter the re-bootstrapped lanes into the live device
        buffers and reset their monitor state.  Off the tick path —
        the warmed update executable is untouched."""
        import jax
        import jax.numpy as jnp

        idx = jnp.asarray(rows)

        def scatter(full, sub):
            arr = jnp.asarray(full)
            return arr.at[idx].set(jnp.asarray(sub, arr.dtype))

        self._ssm = jax.tree_util.tree_map(scatter, self._ssm, boot.ssm)
        self._state = jax.tree_util.tree_map(scatter, self._state,
                                             boot.state)
        h = self._health
        ones = jnp.ones((rows.size,), h.ew.dtype)
        self._health = LaneHealth(
            ew=h.ew.at[idx].set(ones),
            status=h.status.at[idx].set(LANE_OK),
            good_a=scatter(h.good_a, boot.state.a),
            good_ring=scatter(h.good_ring, boot.state.ring)
            if self.meta.d_order else h.good_ring)
        self._status_host[rows] = LANE_OK
        self._reg.set_gauge(
            "serving.state_bytes",
            state_nbytes((self._state, self._health, self._qstate)))

    def _reset_quality_lanes(self, rows: np.ndarray, boot: Bootstrapped,
                             hist_rows: np.ndarray) -> None:
        """Re-baseline the quality state of freshly healed lanes: the
        forecast ring empties (forecasts from the old model must not
        score the new one), the EW metrics and the drift statistic
        restart, and the MASE scale / coverage half-width recompute
        from the refit's own ring history and calibrated ssm — a healed
        lane is judged against the regime it now serves, not the one it
        drifted away from.  Off the tick path, like :meth:`_splice`."""
        import jax.numpy as jnp

        q = self._qstate
        pol = self._quality
        idx = jnp.asarray(rows)
        k = rows.size
        scale_new = jnp.asarray(naive_scale(hist_rows), q.scale.dtype)
        half_new = jnp.asarray(
            forecast_half_widths(boot.ssm, self.meta, pol.horizon,
                                 pol.coverage), q.half.dtype)
        fzero = jnp.zeros((k,), q.ew_smape.dtype)
        izero = jnp.zeros((k,), jnp.int32)
        self._qstate = QualityState(
            fc_ring=q.fc_ring.at[idx].set(
                jnp.asarray(jnp.nan, q.fc_ring.dtype)),
            pos=q.pos.at[idx].set(izero),
            warm=q.warm.at[idx].set(izero),
            scale=q.scale.at[idx].set(scale_new),
            half=q.half.at[idx].set(half_new),
            ew_smape=q.ew_smape.at[idx].set(fzero),
            ew_mase=q.ew_mase.at[idx].set(fzero),
            ew_cover=q.ew_cover.at[idx].set(fzero),
            n_scored=q.n_scored.at[idx].set(izero),
            ph=q.ph.at[idx].set(fzero),
            drifted=q.drifted.at[idx].set(
                jnp.zeros((k,), jnp.bool_)))
        self._q_host = None

    # -- introspection ------------------------------------------------------

    @property
    def loglik(self) -> np.ndarray:
        """Running exact log-likelihood per series (history + ticks)."""
        return np.asarray(self._state.loglik[:self.n_series])

    @property
    def state_bytes(self) -> int:
        return state_nbytes((self._state, self._health, self._qstate))

    def describe(self) -> dict:
        return {"family": self.meta.family, "mode": self.meta.mode,
                "n_series": self.n_series, "bucket": self._bucket,
                "state_dim": self.meta.m, "d_order": self.meta.d_order,
                "ticks_seen": self.ticks_seen,
                "state_bytes": self.state_bytes,
                "history_ring": self._hist_len,
                "quality_horizon": int(self._quality.horizon)
                if self._quality is not None else None,
                "dtype": str(self._dtype)}

    # -- persistence --------------------------------------------------------

    def checkpoint_blob(self) -> Dict[str, Any]:
        """The session's full persistent state as one checkpointable
        pytree dict (SSM, filter state, lane health, history ring, heal
        route, meta, tick counters).  :meth:`checkpoint` writes exactly
        this; the fleet tier's ``drain``/``adopt`` lane migration embeds
        it inside its tenant bundles — one serialization format, every
        consumer (the checkpoint-passthrough contract)."""
        return {
            "format": _CHECKPOINT_FORMAT,
            "meta": self.meta,
            "policy": self.policy,
            "n_series": self.n_series,
            "ticks_seen": self.ticks_seen,
            "bucket": self._bucket,
            "ssm": self._ssm,
            "state": self._state,
            "health": self._health,
            "heal_spec": self._heal_spec,
            "hist": self._hist,
            "hist_pos": self._hist_pos,
            "hist_fill": self._hist_fill,
            # quality plane (None when off).  Optional keys, not a
            # format bump: pre-quality format-2 checkpoints restore as
            # quality-off sessions — no old checkpoint is orphaned.
            "quality_policy": self._quality,
            "qstate": self._qstate,
        }

    def checkpoint(self, path: str) -> None:
        """Atomically persist the whole session (``utils.checkpoint``
        tmp+fsync+rename pytree writer): SSM, filter state, lane health,
        history ring, heal route, meta, and tick counters —
        :meth:`restore` resumes serving (and healing) exactly here."""
        _checkpoint.save_pytree_atomic(path, self.checkpoint_blob())
        self._reg.inc("serving.checkpoints")

    @classmethod
    def restore(cls, path: str, *, registry=None,
                label: Optional[str] = None) -> "ServingSession":
        """Rebuild a session from :meth:`checkpoint` output.

        Validated twice: ``utils.checkpoint`` rejects torn/garbled files
        (``CheckpointMismatchError``), then the checkpoint's geometry is
        checked against the restoring process — the saved bucket must
        equal what ``engine.series_bucket`` now produces for
        ``n_series`` (an engine bucket-policy change would silently
        recompile per tick or misalign pad lanes), and the saved
        ``SSMeta`` must describe the stored arrays.  Any disagreement
        raises :class:`ServingRestoreMismatch` listing the differing
        fields, instead of serving garbage."""
        blob = _checkpoint.load_pytree(path)
        return cls.from_blob(blob, source=path, registry=registry,
                             label=label)

    @classmethod
    def from_blob(cls, blob: Dict[str, Any], *, source: str = "<blob>",
                  registry=None,
                  label: Optional[str] = None) -> "ServingSession":
        """:meth:`restore`'s validation + construction over an
        already-loaded :meth:`checkpoint_blob` dict (``source`` names it
        in errors) — the passthrough the fleet tier's ``adopt`` uses on
        the session half of a tenant bundle."""
        fmt = blob.get("format")
        if fmt != _CHECKPOINT_FORMAT:
            raise ValueError(
                f"serving checkpoint format {fmt!r} is not supported "
                f"(expected {_CHECKPOINT_FORMAT}; format-1 checkpoints "
                f"predate lane-health monitoring — restart those "
                f"sessions from a fresh fit)")
        import jax.numpy as jnp

        from ..engine import series_bucket

        ssm = StateSpace(*(jnp.asarray(leaf) for leaf in blob["ssm"]))
        state = FilterState(*(jnp.asarray(leaf)
                              for leaf in blob["state"]))
        health = LaneHealth(*(jnp.asarray(leaf)
                              for leaf in blob["health"]))
        meta = blob["meta"]
        n_series = int(blob["n_series"])
        saved_bucket = int(blob["bucket"])
        hist = np.asarray(blob["hist"])

        diffs = []

        def check(field, saved, expected):
            if saved != expected:
                diffs.append(f"  {field}: checkpoint={saved!r} vs "
                             f"restoring-process={expected!r}")

        check("bucket(series_bucket policy)", saved_bucket,
              series_bucket(n_series))
        check("meta.m(state dim)", int(meta.m), int(ssm.state_dim))
        check("meta.d_order(ring width)", int(meta.d_order),
              int(state.ring.shape[1]))
        check("ssm.n_series", int(ssm.n_series), saved_bucket)
        check("state.rows", int(state.a.shape[0]), saved_bucket)
        check("health.rows", int(health.status.shape[0]), saved_bucket)
        check("hist.rows", int(hist.shape[0]), n_series)
        if meta.family not in WARMUP_FAMILIES:
            diffs.append(f"  meta.family: checkpoint={meta.family!r} vs "
                         f"restoring-process={WARMUP_FAMILIES}")
        if meta.mode not in ("exact", "innovations"):
            diffs.append(f"  meta.mode: checkpoint={meta.mode!r} vs "
                         f"restoring-process=('exact', 'innovations')")
        quality = blob.get("quality_policy")
        qstate = blob.get("qstate")
        if quality is not None and qstate is not None:
            qstate = QualityState(*(jnp.asarray(leaf) for leaf in qstate))
            if int(qstate.fc_ring.shape[0]) != saved_bucket:
                diffs.append(
                    f"  qstate.rows: checkpoint="
                    f"{int(qstate.fc_ring.shape[0])} vs "
                    f"restoring-process={saved_bucket}")
            if int(qstate.fc_ring.shape[1]) != int(quality.horizon):
                diffs.append(
                    f"  qstate.ring(horizon): checkpoint="
                    f"{int(qstate.fc_ring.shape[1])} vs "
                    f"restoring-process={int(quality.horizon)}")
        else:
            quality, qstate = None, None
        if diffs:
            raise ServingRestoreMismatch(
                f"serving checkpoint at {source!r} disagrees with the "
                f"restoring session's engine policy / its own geometry; "
                f"differing fields:\n" + "\n".join(diffs))
        return cls(ssm, meta, state, n_series,
                   ticks_seen=int(blob["ticks_seen"]), registry=registry,
                   policy=blob["policy"], health=health,
                   heal_spec=blob.get("heal_spec"),
                   quality=quality, _qstate=qstate,
                   _hist_state=(hist, int(blob["hist_pos"]),
                                int(blob["hist_fill"])), label=label)


def start_session(model, history, **kwargs) -> ServingSession:
    """Module-level convenience for :meth:`ServingSession.start`."""
    return ServingSession.start(model, history, **kwargs)


def _warmup_meta(family: str, p: int, d: int, q: int,
                 period: int) -> SSMeta:
    """The :class:`SSMeta` a session of the given family/order would
    carry — the static half of the update executable's cache key."""
    if family == "arima":
        return SSMeta("arima", "exact", int(d), max(p, q + 1))
    if family in ("ar", "arx"):
        return SSMeta(family, "exact", 0, max(int(p), 1))
    if family == "ewma":
        return SSMeta("ewma", "innovations", 0, 1)
    if family == "holt_winters":
        return SSMeta("holt_winters", "innovations", 0, 2 + int(period))
    raise ValueError(f"no serving form for family {family!r}; expected "
                     f"one of {WARMUP_FAMILIES}")


def warmup_update(family: str = "arima", n_series: int = 1024, *,
                  dtype=None, p: int = 2, d: int = 1, q: int = 2,
                  period: int = 12,
                  policy: Optional[HealthPolicy] = None,
                  quality: Optional[QualityPolicy] = None) -> dict:
    """Compile the per-tick update executable for a family/shape ahead of
    any session existing — no fitted model, no data.

    The executable is keyed by ``(series bucket, state dim, SSMeta,
    HealthPolicy)`` only, so a zeros-valued SSM of the right shape
    compiles the exact program every later :meth:`ServingSession.update`
    of that family/order/bucket/policy runs (``engine.warmup`` for the
    serving tier; ``python -m spark_timeseries_tpu.engine --serving``
    and bench's serving demo both route here).  With
    ``STS_COMPILE_CACHE`` armed the compile persists, and the next
    serving process deserializes instead of compiling.  Returns a
    summary dict.
    """
    import jax.numpy as jnp

    from ..engine import series_bucket

    if dtype is None:
        dtype = jnp.float32
    meta = _warmup_meta(family, p, d, q, period)
    pol = (policy if policy is not None else HealthPolicy()).validate()
    bucket = series_bucket(int(n_series))
    m = meta.m
    zeros = jnp.zeros((bucket,), dtype)
    ssm = StateSpace(T=jnp.zeros((bucket, m, m), dtype),
                     Z=jnp.zeros((bucket, m), dtype),
                     c=jnp.zeros((bucket, m), dtype),
                     d=zeros, H=jnp.ones((bucket,), dtype),
                     Q=jnp.zeros((bucket, m, m), dtype),
                     gain=jnp.zeros((bucket, m), dtype))
    state = FilterState(a=jnp.zeros((bucket, m), dtype),
                        P=jnp.zeros((bucket, m, m), dtype),
                        ring=jnp.zeros((bucket, meta.d_order), dtype),
                        loglik=zeros, ssq=zeros, sumlogf=zeros,
                        n_obs=jnp.zeros((bucket,), jnp.int32))
    health = initial_health(state)
    qual = quality.validate() if quality is not None else None
    qstate = None
    if qual is not None:
        qstate = initial_quality(bucket, qual, dtype,
                                 jnp.ones((bucket,), dtype),
                                 jnp.ones((bucket,), dtype))
    y = jnp.full((bucket,), jnp.nan, dtype)
    fn = _jitted("update")
    with _metrics.span("serving.warmup"):
        fn(meta, pol, qual, ssm, state, health, qstate, y, zeros)
    return {"family": family, "bucket": bucket, "state_dim": m,
            "mode": meta.mode, "d_order": meta.d_order,
            "quality": qual is not None,
            "dtype": str(np.dtype(dtype))}
