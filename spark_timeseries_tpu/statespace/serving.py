"""Online serving sessions: O(1) per-tick ingest + forecast on warm state.

The gap this closes (ROADMAP open item 3): every pre-existing path is
batch — a new observation on an already-fitted series costs a full
re-optimization through ``engine.stream_fit``.  A
:class:`ServingSession` instead holds each series' *state-space filter
state* (``statespace.ssm``: O(m²) floats per series, engine-bucketed
device buffers) and makes ingest a single cached-executable Kalman step:

- :meth:`update` — one tick for the whole panel.  The executable is a
  module-level ``jax.jit`` keyed by ``(bucket, state dim, SSMeta)``, so
  every session of the same family/shape shares one compiled program;
  :meth:`warmup` (or ``engine.warmup``-style pre-warming with
  ``STS_COMPILE_CACHE`` armed) compiles it ahead of traffic, after which
  updates trigger **zero** XLA compiles — pinned by
  ``tests/test_statespace.py`` exactly as ``tests/test_engine.py`` pins
  the fit engine.  There is no fit/optimizer call anywhere in the tick
  path: per-tick work is O(m²) per series, independent of history
  length.
- :meth:`forecast` — h-step point forecasts straight off the filtered
  state (mean propagation + d-order integration through the raw
  difference ring), one cached executable per horizon.
- :meth:`checkpoint` / :meth:`restore` — the whole session (SSM, filter
  state, meta, tick counters) through ``utils.checkpoint``'s atomic
  pytree writer, so a serving process restarts where it stopped.

Metrics: ``serving.sessions`` / ``serving.ticks`` / ``serving.updates``
/ ``serving.forecasts`` counters, a ``serving.update`` span (p50/p95
land in bench's ``serving_demo`` block and gate the per-tick SLO in
``tools/bench_gate.py``), and a ``serving.state_bytes`` gauge.
"""

from __future__ import annotations

import threading
from typing import NamedTuple, Optional

import numpy as np

from ..utils import checkpoint as _checkpoint
from ..utils import metrics as _metrics
from .convert import Bootstrapped, bootstrap
from .kalman import filter_step_panel
from .ssm import FilterState, SSMeta, StateSpace, state_nbytes

__all__ = ["ServingSession", "TickResult", "start_session",
           "warmup_update", "WARMUP_FAMILIES"]

_CHECKPOINT_FORMAT = 1

# families warmup_update can synthesize an executable-shaped SSM for
# without a fitted model (the serving-capable subset of ENGINE_FAMILIES)
WARMUP_FAMILIES = ("arima", "ar", "arx", "ewma", "holt_winters")


class TickResult(NamedTuple):
    """One :meth:`ServingSession.update`'s per-series outcome (real lanes
    only): the innovations ``v`` (NaN where the tick was missing), their
    predictive variances ``F``, and the per-series log-likelihood
    increment of the tick."""
    innovations: np.ndarray
    variances: np.ndarray
    loglik_inc: np.ndarray


# ---------------------------------------------------------------------------
# module-level jitted kernels (one function object per program shape, so
# every session shares jax's jit cache — the STS006 discipline)
# ---------------------------------------------------------------------------

def _update_impl(meta: SSMeta, ssm: StateSpace, state: FilterState,
                 y, offset):
    state2, (v, f) = filter_step_panel(ssm, state, y, offset, meta)
    ll_inc = state2.loglik - state.loglik
    return state2, v, f, ll_inc


def _forecast_impl(meta: SSMeta, horizon: int, ssm: StateSpace,
                   state: FilterState, offsets):
    """h-step point forecasts from the predicted state — the shared
    mean-propagation program (``kalman.forecast_mean``: ``x ←
    T(x + offset·Z) + c`` with zero future innovations, observations
    integrated back to the raw scale through the difference ring), so a
    serving session and the longseries exact-forecast path compile the
    identical executable."""
    from .kalman import forecast_mean

    return forecast_mean(meta, horizon, ssm, state.a, state.ring, offsets)


_jit_lock = threading.Lock()
_jit_cache: dict = {}


def _jitted(kind: str):
    """Lazily-built module-level jits (imports jax on first use so merely
    importing the package never initializes a backend).  Arms the
    engine's persistent compile cache first, so a serving process that
    never builds a ``FitEngine`` still honors ``STS_COMPILE_CACHE`` —
    its first update deserializes instead of compiling."""
    with _jit_lock:
        fn = _jit_cache.get(kind)
        if fn is None:
            import jax

            from ..engine import configure_compile_cache
            configure_compile_cache()
            if kind == "update":
                fn = jax.jit(_update_impl, static_argnums=(0,))
            else:
                fn = jax.jit(_forecast_impl, static_argnums=(0, 1))
            _jit_cache[kind] = fn
        return fn


def _pad_lanes(tree, bucket: int, n_real: int):
    """Pad every batched leaf to the series bucket by replicating lane 0
    (finite, harmless — padded lanes only ever see NaN ticks, which the
    filter skips)."""
    import jax
    import jax.numpy as jnp

    pad = bucket - n_real
    if pad == 0:
        return tree

    def grow(leaf):
        return jnp.concatenate(
            [leaf, jnp.broadcast_to(leaf[:1], (pad,) + leaf.shape[1:])])

    return jax.tree_util.tree_map(grow, tree)


class ServingSession:
    """Warm per-series filter state + cached tick/forecast executables.

    Build one with :meth:`start` (fitted model + its training history) or
    :meth:`restore` (a checkpoint).  Not thread-safe per instance — one
    session is one logical stream; shard across sessions for parallel
    ingest (the compiled programs are shared through the jit cache).
    """

    def __init__(self, ssm: StateSpace, meta: SSMeta, state: FilterState,
                 n_series: int, *, ticks_seen: int = 0,
                 registry=None):
        from ..engine import series_bucket

        self._reg = registry if registry is not None \
            else _metrics.get_registry()
        self.meta = meta
        self.n_series = int(n_series)
        self._bucket = series_bucket(self.n_series)
        self.ticks_seen = int(ticks_seen)
        if ssm.n_series == self._bucket:       # already bucketed (restore)
            self._ssm, self._state = ssm, state
        else:
            self._ssm = _pad_lanes(ssm, self._bucket, ssm.n_series)
            self._state = _pad_lanes(state, self._bucket, state.a.shape[0])
        self._dtype = np.dtype(self._ssm.T.dtype)
        self._reg.inc("serving.sessions")
        self._reg.set_gauge("serving.state_bytes",
                            state_nbytes(self._state))

    # -- construction -------------------------------------------------------

    @classmethod
    def start(cls, model, history, *, offsets=None,
              registry=None) -> "ServingSession":
        """Open a session from a fitted model pytree and the history it
        was fitted on: converts to state-space form
        (``statespace.convert.to_statespace``), filters the history to a
        warm state, calibrates σ², and buckets the per-series buffers.
        ``history (n_series, n_obs)`` (NaNs are missing ticks);
        ``offsets`` carries ARX per-tick exogenous observation offsets.
        """
        import jax.numpy as jnp

        history = jnp.asarray(history)
        if history.ndim == 1:
            history = history[None]
        boot: Bootstrapped = bootstrap(model, history, offsets=offsets)
        return cls(boot.ssm, boot.meta, boot.state, history.shape[0],
                   ticks_seen=int(history.shape[1]), registry=registry)

    # -- serving ------------------------------------------------------------

    def update(self, ticks, offset=None) -> TickResult:
        """Ingest one tick per series — a single cached-executable Kalman
        step, O(1) work per tick per series.

        ``ticks (n_series,)`` raw observations (NaN = missing: the lane's
        state predicts forward and contributes no likelihood);
        ``offset (n_series,)`` the ARX exogenous observation offsets for
        this tick.  Returns the per-series :class:`TickResult`.
        """
        host = np.asarray(ticks, self._dtype).reshape(-1)
        if host.shape[0] != self.n_series:
            raise ValueError(
                f"update expects one tick per series ({self.n_series}), "
                f"got {host.shape[0]}")
        y = np.full((self._bucket,), np.nan, self._dtype)
        y[:self.n_series] = host
        off = np.zeros((self._bucket,), self._dtype)
        if offset is not None:
            off[:self.n_series] = np.asarray(offset, self._dtype) \
                .reshape(-1)
        fn = _jitted("update")
        with _metrics.span("serving.update"):
            state2, v, f, ll_inc = fn(self.meta, self._ssm, self._state,
                                      y, off)
            # materialize inside the span: the p50/p95 the bench gate
            # SLOs must cover the real per-tick latency, not the async
            # dispatch alone
            out = TickResult(
                np.asarray(v[:self.n_series]),
                np.asarray(f[:self.n_series]),
                np.asarray(ll_inc[:self.n_series]))
        self._state = state2
        self.ticks_seen += 1
        self._reg.inc("serving.updates")
        self._reg.inc("serving.ticks", self.n_series)
        return out

    def forecast(self, horizon: int, offsets=None) -> np.ndarray:
        """``(n_series, horizon)`` point forecasts from the current
        filtered state — mean propagation with zero future innovations,
        integrated back through the raw-difference ring for d > 0
        families.  ``offsets (n_series, horizon)`` adds known future
        exogenous contributions (ARX)."""
        horizon = int(horizon)
        if horizon < 1:
            raise ValueError("forecast needs horizon >= 1")
        offs = np.zeros((self._bucket, horizon), self._dtype)
        if offsets is not None:
            offs[:self.n_series] = np.asarray(offsets, self._dtype)
        fn = _jitted("forecast")
        with _metrics.span("serving.forecast"):
            out = np.asarray(fn(self.meta, horizon, self._ssm,
                                self._state, offs))
        self._reg.inc("serving.forecasts")
        return out[:self.n_series]

    def warmup(self) -> None:
        """Compile the update executable ahead of traffic (the forecast
        executable is per-horizon — the first :meth:`forecast` at a new
        horizon compiles).  Functionally a no-op: the filter is pure, so
        the warmup result is simply discarded and the state is untouched.
        With ``STS_COMPILE_CACHE`` armed the compile also persists, and
        the next process deserializes instead of compiling."""
        y = np.full((self._bucket,), np.nan, self._dtype)
        off = np.zeros((self._bucket,), self._dtype)
        fn = _jitted("update")
        with _metrics.span("serving.warmup"):
            _, v, f, ll = fn(self.meta, self._ssm, self._state, y, off)
            # also warm the real-lane result slices update materializes
            # (tiny per-(bucket, n_series) device programs of their own —
            # without this the first tick would compile them)
            np.asarray(v[:self.n_series])
            np.asarray(f[:self.n_series])
            np.asarray(ll[:self.n_series])

    # -- introspection ------------------------------------------------------

    @property
    def loglik(self) -> np.ndarray:
        """Running exact log-likelihood per series (history + ticks)."""
        return np.asarray(self._state.loglik[:self.n_series])

    @property
    def state_bytes(self) -> int:
        return state_nbytes(self._state)

    def describe(self) -> dict:
        return {"family": self.meta.family, "mode": self.meta.mode,
                "n_series": self.n_series, "bucket": self._bucket,
                "state_dim": self.meta.m, "d_order": self.meta.d_order,
                "ticks_seen": self.ticks_seen,
                "state_bytes": self.state_bytes,
                "dtype": str(self._dtype)}

    # -- persistence --------------------------------------------------------

    def checkpoint(self, path: str) -> None:
        """Atomically persist the whole session (``utils.checkpoint``
        tmp+fsync+rename pytree writer): SSM, filter state, meta, and
        tick counters — :meth:`restore` resumes serving exactly here."""
        _checkpoint.save_pytree_atomic(path, {
            "format": _CHECKPOINT_FORMAT,
            "meta": self.meta,
            "n_series": self.n_series,
            "ticks_seen": self.ticks_seen,
            "ssm": self._ssm,
            "state": self._state,
        })
        self._reg.inc("serving.checkpoints")

    @classmethod
    def restore(cls, path: str, *, registry=None) -> "ServingSession":
        """Rebuild a session from :meth:`checkpoint` output (validated
        restore — a torn or mismatched checkpoint raises
        ``CheckpointMismatchError`` instead of serving garbage)."""
        blob = _checkpoint.load_pytree(path)
        fmt = blob.get("format")
        if fmt != _CHECKPOINT_FORMAT:
            raise ValueError(
                f"serving checkpoint format {fmt!r} is not supported "
                f"(expected {_CHECKPOINT_FORMAT})")
        import jax.numpy as jnp

        ssm = StateSpace(*(jnp.asarray(leaf) for leaf in blob["ssm"]))
        state = FilterState(*(jnp.asarray(leaf)
                              for leaf in blob["state"]))
        return cls(ssm, blob["meta"], state, blob["n_series"],
                   ticks_seen=blob["ticks_seen"], registry=registry)


def start_session(model, history, **kwargs) -> ServingSession:
    """Module-level convenience for :meth:`ServingSession.start`."""
    return ServingSession.start(model, history, **kwargs)


def _warmup_meta(family: str, p: int, d: int, q: int,
                 period: int) -> SSMeta:
    """The :class:`SSMeta` a session of the given family/order would
    carry — the static half of the update executable's cache key."""
    if family == "arima":
        return SSMeta("arima", "exact", int(d), max(p, q + 1))
    if family in ("ar", "arx"):
        return SSMeta(family, "exact", 0, max(int(p), 1))
    if family == "ewma":
        return SSMeta("ewma", "innovations", 0, 1)
    if family == "holt_winters":
        return SSMeta("holt_winters", "innovations", 0, 2 + int(period))
    raise ValueError(f"no serving form for family {family!r}; expected "
                     f"one of {WARMUP_FAMILIES}")


def warmup_update(family: str = "arima", n_series: int = 1024, *,
                  dtype=None, p: int = 2, d: int = 1, q: int = 2,
                  period: int = 12) -> dict:
    """Compile the per-tick update executable for a family/shape ahead of
    any session existing — no fitted model, no data.

    The executable is keyed by ``(series bucket, state dim, SSMeta)``
    only, so a zeros-valued SSM of the right shape compiles the exact
    program every later :meth:`ServingSession.update` of that
    family/order/bucket runs (``engine.warmup`` for the serving tier;
    ``python -m spark_timeseries_tpu.engine --serving`` and bench's
    serving demo both route here).  With ``STS_COMPILE_CACHE`` armed the
    compile persists, and the next serving process deserializes instead
    of compiling.  Returns a summary dict.
    """
    import jax.numpy as jnp

    from ..engine import series_bucket

    if dtype is None:
        dtype = jnp.float32
    meta = _warmup_meta(family, p, d, q, period)
    bucket = series_bucket(int(n_series))
    m = meta.m
    zeros = jnp.zeros((bucket,), dtype)
    ssm = StateSpace(T=jnp.zeros((bucket, m, m), dtype),
                     Z=jnp.zeros((bucket, m), dtype),
                     c=jnp.zeros((bucket, m), dtype),
                     d=zeros, H=jnp.ones((bucket,), dtype),
                     Q=jnp.zeros((bucket, m, m), dtype),
                     gain=jnp.zeros((bucket, m), dtype))
    state = FilterState(a=jnp.zeros((bucket, m), dtype),
                        P=jnp.zeros((bucket, m, m), dtype),
                        ring=jnp.zeros((bucket, meta.d_order), dtype),
                        loglik=zeros, ssq=zeros, sumlogf=zeros,
                        n_obs=jnp.zeros((bucket,), jnp.int32))
    y = jnp.full((bucket,), jnp.nan, dtype)
    fn = _jitted("update")
    with _metrics.span("serving.warmup"):
        fn(meta, ssm, state, y, zeros)
    return {"family": family, "bucket": bucket, "state_dim": m,
            "mode": meta.mode, "d_order": meta.d_order,
            "dtype": str(np.dtype(dtype))}
